"""Sharded CRDT pull rounds: the packed exchange fabric with a
commutative-merge payload.

Twin of models/crdt.make_crdt_round over the node mesh — structurally
parallel/sharded_packed.make_sharded_packed_round with the CRDT join
(elementwise max for counter shards, OR for packed set planes) in
place of the word OR, and the injection program applied locally per
shard.  The only collective is the all_gather of the masked state
table — ``N x S`` int32 shards or ``N x 2W`` uint32 set words per
round (the set payload rides the SAME 32-elements-per-word packed
layout as the rumor planes: ops/bitpack) — plus the msgs/lost psums.
Bitwise parity with the single-device round is pinned in
tests/test_crdt.py: every random draw is keyed by (base_key, round,
*global* node id), so mesh shape never changes the trajectory.

Nemesis schedules AND injection programs are runtime operands on the
step's ``tables`` tail (ops/nemesis + ops/crdt), so the compiled loops
carry shapes only and one executable serves a whole scenario family;
value convergence is judged on the eventual-alive set with an
integer-exact converged-node count divided ONCE on the host.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossip_tpu.compat import shard_map
from gossip_tpu import config as C
from gossip_tpu.config import (CrdtConfig, FaultConfig, ProtocolConfig,
                               RunConfig)
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.crdt import (CrdtState, _conv_target_count,
                                    check_byz_defendable,
                                    check_crdt_mode,
                                    check_injections_reachable,
                                    init_crdt_state, truth_scalar)
from gossip_tpu.models.state import bind_tables
from gossip_tpu.ops import crdt as CR
from gossip_tpu.ops.sampling import apply_drop, sample_peers
from gossip_tpu.parallel.sharded import (_churn_observables, _pad_rows,
                                         pad_to_mesh, sharded_alive)
from gossip_tpu.topology.generators import Topology


def make_sharded_crdt_round(
        cfg: CrdtConfig, proto: ProtocolConfig, topo: Topology,
        mesh: Mesh, fault: Optional[FaultConfig] = None, origin: int = 0,
        axis_name: str = "nodes", tabled: bool = False,
        defend: bool = False):
    """``tabled=True`` returns ``(step, tables)`` with padded topology
    + injection (+ schedule) (+ byzantine program) arrays as step
    ARGUMENTS (no O(N) jit closure constants — models/swim.py doc).
    ``defend=True`` switches the exchange to the defended admission
    (ops/crdt byzantine section; models/crdt.py twin)."""
    check_crdt_mode(proto)
    n, k = topo.n, proto.fanout
    if cfg.kind == C.VCLOCK:
        raise ValueError("vclock has no exchange driver (ops/crdt merge "
                         "kernel + tick only)")
    n_pad = pad_to_mesh(n, mesh, axis_name)
    nl = n_pad // mesh.shape[axis_name]
    drop_prob = 0.0 if fault is None else fault.drop_prob
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    # capability row: full schedule feature set on the CRDT fabric,
    # plus the byzantine liar program with array-form defenses
    NE.check_supported(fault, engine="crdt-pull", byz=True)
    check_byz_defendable(cfg, fault, k, defend)

    have_table = not topo.implicit
    if have_table:
        nbrs_pad = _pad_rows(topo.nbrs, n_pad, n)
        deg_pad = _pad_rows(topo.deg, n_pad, 0)
    counters = cfg.kind in C.CRDT_COUNTER_KINDS
    zero = jnp.zeros((), jnp.int32 if counters else jnp.uint32)

    def local_round(val_l, round_, base_key, msgs, *table):
        table, byzt = NE.split_byz(bz, table)
        table, sched = NE.split_tables(ch, table)
        table, inj = CR.split_inject(cfg, table)
        shard = jax.lax.axis_index(axis_name)
        gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        rkey = jax.random.fold_in(base_key, round_)
        alive_fn = CR.alive_at_fn(fault, n, origin)
        eventual = CR.eventual_alive_crdt(fault, n, origin)
        if ch is not None:
            base_pad = _pad_rows(
                NE.base_alive_or_ones(fault, n, origin), n_pad, False)
            alive_l = NE.alive_rows(sched, base_pad, round_)[gids]
            dp = NE.drop_at(sched, round_)
            cut = NE.cut_at(sched, round_)
        else:
            alive_l = sharded_alive(fault, n, n_pad, origin)[gids]
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        # local injections first (models/crdt.py twin); padding rows
        # (gids >= n) own no column/element, so inject_rows is zero
        # there by construction
        inj_rows = CR.inject_rows(cfg, inj, gids, round_, n, origin,
                                  alive_fn, eventual)
        val_l = val_l + inj_rows if counters else val_l | inj_rows
        visible = jnp.where(alive_l[:, None], val_l, zero)
        rows_all = jax.lax.all_gather(visible, axis_name, tiled=True)
        nbrs_l, deg_l = table if have_table else (None, None)

        qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
        partners0 = sample_peers(qkey, gids, topo, k, proto.exclude_self,
                                 local_nbrs=nbrs_l, local_deg=deg_l)
        partners = apply_drop(rkey, si_mod.PULL_DROP_TAG, gids,
                              partners0, dp, n, force=ch is not None)
        if ch is not None:
            partners = NE.partition_targets(cut, gids, partners, n)
        if bz is not None:
            pulled = CR.pull_merge_crdt_byz(
                cfg, rows_all, partners, n, byz=byzt, round_=round_,
                gids=gids, n=n, origin=origin, alive_fn=alive_fn,
                defend=defend)
        else:
            pulled = CR.pull_merge_crdt(cfg.kind, rows_all, partners, n)
        partners = jnp.where(alive_l[:, None], partners, n)
        n_req = jnp.sum(partners < n).astype(jnp.float32)
        if ch is not None:
            lost = lost + NE.lost_count(partners0, partners, alive_l, n)
        pulled = jnp.where(alive_l[:, None], pulled, zero)
        out_val = CR.merge(cfg.kind, val_l, pulled)
        msgs_new = msgs + jax.lax.psum(2.0 * n_req, axis_name)
        if ch is not None:
            return out_val, msgs_new, jax.lax.psum(lost, axis_name)
        return out_val, msgs_new

    sh2 = P(axis_name, None)
    rep = P()
    in_specs = [sh2, rep, rep, rep]
    tables = ()
    if have_table:
        in_specs += [sh2, P(axis_name)]
        tables = (nbrs_pad, deg_pad)
    # injection operands replicated (tiny padded lists; the per-shard
    # ownership slice happens via gids inside local_round)
    inj_ops = CR.inject_args(cfg, n)
    in_specs += [rep] * len(inj_ops)
    tables = tables + inj_ops
    if ch is not None:
        in_specs += [rep] * NE.N_SCHED_OPERANDS
        tables = tables + NE.sched_args(NE.build(fault, n, n_pad))
    if bz is not None:
        in_specs += [rep] * NE.N_BYZ_OPERANDS
        tables = tables + NE.byz_args(NE.build_byz(fault, n, n_pad))

    out_specs = (sh2, rep, rep) if ch is not None else (sh2, rep)
    mapped = shard_map(local_round, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=out_specs)

    def step_tabled(state: CrdtState, *tbl):
        out = mapped(state.val, state.round, state.base_key,
                     state.msgs, *tbl)
        new = CrdtState(val=out[0], round=state.round + 1,
                        base_key=state.base_key, msgs=out[1])
        return (new, out[2]) if ch is not None else new

    return bind_tables(step_tabled, tables, tabled)


def init_sharded_crdt_state(run: RunConfig, cfg: CrdtConfig,
                            topo: Topology, mesh: Mesh,
                            axis_name: str = "nodes") -> CrdtState:
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    st = init_crdt_state(run, cfg, topo.n)
    val = _pad_rows(st.val, n_pad, 0)
    val = jax.device_put(val, NamedSharding(mesh, P(axis_name, None)))
    return st._replace(val=val)


def _crdt_recorder(cfg: CrdtConfig, proto: ProtocolConfig, n: int,
                   n_pad: int, n_shards: int, truth, eventual_pad,
                   byz_extra=None):
    """In-loop metrics row for the CRDT pull kernels (ops/round_metrics
    — the parallel/sharded_packed._packed_recorder twin).  ``newly`` is
    the per-round delta of the merged payload mass (counter mass / set
    bits — monotone under merge, so the delta is exact); ``value_conv``
    is the converged fraction on the eventual-alive set; per-device
    egress is the state all_gather: ``nl * S * 4`` bytes plus the msgs
    psum.  Under a liar program ``byz_extra = (component_mask,
    honest_eventual_pad)`` adds the ``byz_conv`` column — honest-node
    convergence on honest-owned components (ops/crdt byzantine
    section)."""
    from gossip_tpu.ops import round_metrics as RM
    s = CR.state_width(cfg, n)
    nl = n_pad // n_shards
    base = 4.0 + 4.0 * nl * s
    # pull accounting: request + full-state response per exchange; the
    # response carries the payload (the 0.5 pull payload factor)
    offered_per_msg = s * RM.payload_factor(C.PULL)

    def rec(m, prev_count, round0, msgs0, s1, alive_pad, nem=None):
        count = CR.payload_count(cfg, s1.val, alive_pad)
        newly = count - prev_count
        msgs = s1.msgs - msgs0
        kw = ({} if nem is None
              else dict(alive=nem[0], cut_pairs=nem[1], dropped=nem[2]))
        covered = jnp.any(s1.val != 0, axis=1) & alive_pad
        per = jnp.sum(covered.reshape(n_shards, -1), axis=1,
                      dtype=jnp.float32)
        tot = jnp.sum(alive_pad.reshape(n_shards, -1), axis=1,
                      dtype=jnp.float32)
        if byz_extra is not None:
            comp_mask, honest_pad = byz_extra
            kw["byz_conv"] = CR.byz_conv_frac(cfg, s1.val, truth,
                                              honest_pad, comp_mask)
        return RM.record(
            m, newly=newly, msgs=msgs,
            dup=RM.dup_estimate(offered_per_msg * msgs, newly),
            bytes=jnp.float32(base),
            front=per / jnp.maximum(tot, 1.0),
            value_conv=CR.value_conv_frac(s1.val, truth, eventual_pad),
            **kw), count

    return rec


def _sharded_truth_and_alive(cfg: CrdtConfig, tbl, ch, fault, n: int,
                             n_pad: int, origin: int, bz=None):
    """(truth row, eventual-alive over padded rows) — truth from the
    TRACED injection operands on the step's table tail (the compiled
    loop carries injection shapes, never content — models/crdt.py
    discipline), shared by both sharded drivers so the metric and the
    readout agree.  The byz tail (outermost) is peeled first."""
    from gossip_tpu.ops import nemesis as NE
    head, _ = NE.split_byz(bz, tbl)
    head, _ = NE.split_tables(ch, head)
    _, inj = CR.split_inject(cfg, head)
    truth = CR.ground_truth(cfg, inj, fault, n, origin)
    eventual = _pad_rows(CR.eventual_alive_crdt(fault, n, origin),
                         n_pad, False)
    return truth, eventual


def _byz_recorder_extra(cfg, fault, bz, n: int, n_pad: int,
                        origin: int, eventual_pad):
    """``(component_mask, honest_eventual_pad)`` for the recorders'
    ``byz_conv`` column, or None without a liar program — the honest
    masks are numpy-built from the static fault config (constants in
    the trace, like the liveness predicates)."""
    if bz is None:
        return None
    from gossip_tpu.ops import nemesis as NE
    honest = NE.honest_mask(fault, n)
    comp_mask = CR.honest_component_mask(cfg, n, origin, honest)
    honest_pad = eventual_pad & _pad_rows(honest, n_pad, False)
    return comp_mask, honest_pad


def simulate_curve_crdt_sharded(cfg: CrdtConfig, proto: ProtocolConfig,
                                topo: Topology, run: RunConfig,
                                mesh: Mesh,
                                fault: Optional[FaultConfig] = None,
                                axis_name: str = "nodes", timing=None,
                                defend: bool = False):
    """Sharded scan driver: returns ``(value_conv f64[T], msgs f32[T],
    final_state, truth_value)`` — value_conv from the integer converged
    count divided once on the host (models/crdt.py contract).  With an
    active run ledger the scan carries a RoundMetrics stack with the
    ``value_conv`` column (plus ``byz_conv`` under a liar program),
    flushed once by the chokepoint."""
    import numpy as np

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    check_injections_reachable(cfg, run)
    step, tables = make_sharded_crdt_round(cfg, proto, topo, mesh, fault,
                                           run.origin, axis_name,
                                           tabled=True, defend=defend)
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    n = topo.n
    n_pad = pad_to_mesh(n, mesh, axis_name)
    n_shards = mesh.shape[axis_name]
    init = init_sharded_crdt_state(run, cfg, topo, mesh, axis_name)
    obs = _churn_observables(fault, n, n_pad, run.origin)

    @jax.jit
    def scan(state, *tbl):
        truth, eventual = _sharded_truth_and_alive(cfg, tbl, ch, fault,
                                                   n, n_pad, run.origin,
                                                   bz)
        byz_extra = _byz_recorder_extra(cfg, fault, bz, n, n_pad,
                                        run.origin, eventual)
        rec = (_crdt_recorder(cfg, proto, n, n_pad, n_shards, truth,
                              eventual, byz_extra)
               if RM.wanted() else None)
        m0 = (RM.init(run.max_rounds, n_shards,
                      "simulate_curve_crdt_sharded",
                      nemesis=ch is not None, crdt=True,
                      byz=bz is not None)
              if rec else None)
        c0 = CR.payload_count(cfg, state.val, eventual) if rec else None

        def body(carry, _):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lo = step(s0, *tbl)
            else:
                s, lo = step(s0, *tbl), None
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, eventual,
                             nem=(obs(round0, lo, NE.sched_of_tables(
                                      NE.split_byz(bz, tbl)[0]))
                                  if obs else None))
            return (s, m, cnt), (
                CR.converged_count(s.val, truth, eventual), s.msgs)

        (final, m, _), ys = jax.lax.scan(body, (state, m0, c0), None,
                                         length=run.max_rounds)
        return (final, m), ys, truth

    # truth comes back from the jitted scan (the until-driver shape) —
    # recomputing it here would re-lower the injection operands and
    # run the scatter program un-jitted on the host, per call
    (final, _), (convs, msgs), truth = maybe_aot_timed(scan, timing,
                                                       init, *tables,
                                                       label="crdt")
    eventual_np = np.asarray(CR.eventual_alive_crdt(fault, n,
                                                    run.origin))
    denom = max(1, int(eventual_np.sum()))
    return (np.asarray(convs, np.int64) / denom, np.asarray(msgs),
            final, truth_scalar(cfg, truth, n))


def simulate_until_crdt_sharded(cfg: CrdtConfig, proto: ProtocolConfig,
                                topo: Topology, run: RunConfig,
                                mesh: Mesh,
                                fault: Optional[FaultConfig] = None,
                                axis_name: str = "nodes", timing=None,
                                defend: bool = False):
    """Sharded while_loop driver: ``(rounds, value_conv, msgs,
    final_state, truth_value)`` — the loop cond is the exact integer
    converged-count compare (models/crdt._conv_target_count)."""
    import numpy as np

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    check_injections_reachable(cfg, run)
    step, tables = make_sharded_crdt_round(cfg, proto, topo, mesh, fault,
                                           run.origin, axis_name,
                                           tabled=True, defend=defend)
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    n = topo.n
    n_pad = pad_to_mesh(n, mesh, axis_name)
    n_shards = mesh.shape[axis_name]
    init = init_sharded_crdt_state(run, cfg, topo, mesh, axis_name)
    obs = _churn_observables(fault, n, n_pad, run.origin)
    eventual_np = np.asarray(CR.eventual_alive_crdt(fault, n,
                                                    run.origin))
    denom = max(1, int(eventual_np.sum()))
    target = _conv_target_count(run, denom)

    @jax.jit
    def loop(state, *tbl):
        truth, eventual = _sharded_truth_and_alive(cfg, tbl, ch, fault,
                                                   n, n_pad, run.origin,
                                                   bz)
        byz_extra = _byz_recorder_extra(cfg, fault, bz, n, n_pad,
                                        run.origin, eventual)
        rec = (_crdt_recorder(cfg, proto, n, n_pad, n_shards, truth,
                              eventual, byz_extra)
               if RM.wanted() else None)
        m0 = (RM.init(run.max_rounds, n_shards,
                      "simulate_until_crdt_sharded",
                      nemesis=ch is not None, crdt=True,
                      byz=bz is not None)
              if rec else None)
        c0 = CR.payload_count(cfg, state.val, eventual) if rec else None

        def cond(carry):
            s, _, _ = carry
            return ((CR.converged_count(s.val, truth, eventual)
                     < target) & (s.round < run.max_rounds))

        def body(carry):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lo = step(s0, *tbl)
            else:
                s, lo = step(s0, *tbl), None
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, eventual,
                             nem=(obs(round0, lo, NE.sched_of_tables(
                                      NE.split_byz(bz, tbl)[0]))
                                  if obs else None))
            return s, m, cnt

        final, m, _ = jax.lax.while_loop(cond, body, (state, m0, c0))
        return (final, m), truth

    (final, _), truth = maybe_aot_timed(loop, timing, init, *tables,
                                        label="crdt")
    eventual = _pad_rows(CR.eventual_alive_crdt(fault, n, run.origin),
                         n_pad, False)
    conv = int(CR.converged_count(final.val, truth, eventual)) / denom
    return (int(final.round), conv, float(final.msgs), final,
            truth_scalar(cfg, truth, n))
