"""Node-dimension sharding over a device mesh (pjit / shard_map layer)."""

from gossip_tpu.parallel.sharded import (  # noqa: F401
    init_sharded_state,
    make_mesh,
    make_sharded_si_round,
    pad_to_mesh,
    sharded_alive,
    simulate_until_sharded,
)
