"""Halo exchange: ``ppermute`` rounds for band-limited topologies.

This is the framework's sequence-parallelism analog (SURVEY.md §5: the
scaled long dimension is *nodes*, not tokens).  The general sharded kernels
(parallel/sharded.py) ``all_gather`` the whole digest table every round —
O(N) ICI traffic, unavoidable for topologies whose edges go anywhere
(complete, ER, power-law).  But **band-limited** graphs (rings, 2-D grids
in row-major order, unrewired Watts–Strogatz lattices — exactly the shapes
Maelstrom hands the reference) have every edge within circular distance B
of its source, so a contiguously-sharded node axis only ever reads rows
within B of its block boundary.  One ``lax.ppermute`` to each mesh neighbor
moves those 2B halo rows — O(B) traffic instead of O(N), the same
neighbor-exchange pattern ring attention uses for sequence blocks.

At the BASELINE scale: a k=6 ring at 10M nodes on 8 shards all-gathers
10 MB/round in the general kernel; the halo kernel moves 2x3 rows = bytes.

Constraints (checked, not assumed): an explicit neighbor table, band(topo)
<= rows-per-shard (halo must come from the *immediate* mesh neighbors),
and n divisible by the mesh size (contiguous blocks, no padding zone in
the circular index math).  Results are bitwise identical to the
single-device kernels — tests/test_halo.py.

CPU-mesh caveat (virtual devices only, not TPU): XLA's in-process CPU
collectives rendezvous across host threads; dispatching hundreds of
ppermute rounds without a host sync can starve one virtual device and
abort the rendezvous.  Python-loop drivers on the CPU mesh should
``block_until_ready`` periodically (a ``lax.while_loop``/``scan`` driver,
the normal production shape, has no such issue).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from gossip_tpu.compat import axis_size, shard_map
from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.state import SimState, alive_mask, bind_tables
from gossip_tpu.ops.sampling import apply_drop, drop_mask, sample_peers
from gossip_tpu.topology.generators import Topology


def band_of(topo: Topology) -> int:
    """Max circular edge distance (host-side, one-time).  B such that every
    edge (i, j) has min(|i-j|, n-|i-j|) <= B."""
    if topo.implicit:
        raise ValueError("band is undefined for the implicit complete graph")
    nbrs = np.asarray(topo.nbrs)
    deg = np.asarray(topo.deg)
    n = topo.n
    rows = np.repeat(np.arange(n), nbrs.shape[1])
    flat = nbrs.reshape(-1)
    valid = flat < n
    mask_cols = (np.arange(nbrs.shape[1])[None, :] < deg[:, None]).reshape(-1)
    use = valid & mask_cols
    d = np.abs(flat[use] - rows[use])
    return int(np.minimum(d, n - d).max()) if d.size else 0


def _ring_perms(axis_name: str):
    """(to_right, to_left) ppermute pairs on the mesh ring — the single
    source of the neighbor convention for both the forward halo read and
    the reverse push write-back."""
    p = axis_size(axis_name)
    to_right = [(i, (i + 1) % p) for i in range(p)]
    to_left = [(i, (i - 1) % p) for i in range(p)]
    return to_right, to_left


def _exchange_halos(visible_l: jax.Array, band: int,
                    axis_name: str) -> jax.Array:
    """[nl, R] -> [nl + 2B, R]: prepend the left neighbor's last B rows,
    append the right neighbor's first B rows (both rings of the mesh)."""
    to_right, to_left = _ring_perms(axis_name)
    from_left = jax.lax.ppermute(visible_l[-band:], axis_name, to_right)
    from_right = jax.lax.ppermute(visible_l[:band], axis_name, to_left)
    return jnp.concatenate([from_left, visible_l, from_right], axis=0)


def make_halo_round(proto: ProtocolConfig, topo: Topology, mesh: Mesh,
                    fault: Optional[FaultConfig] = None, origin: int = 0,
                    axis_name: str = "nodes", tabled: bool = False):
    """FLOOD, PULL, PUSH, or PUSH_PULL round with O(band) cross-shard
    traffic.

    Semantically identical to the general sharded kernels and to the
    single-device kernels; only the communication pattern differs.  Push
    scatters into the extended halo buffer and the boundary contributions
    flow BACK to the owning shard with a reverse ``ppermute`` — the push
    twin of the forward halo read.

    ``tabled=True`` returns ``(step, tables)`` with the neighbor arrays as
    step ARGUMENTS (no O(N) jit closure constants — models/swim.py doc);
    the liveness mask is built in-trace."""
    n, k = topo.n, proto.fanout
    mode = proto.mode
    if mode not in (C.FLOOD, C.PULL, C.PUSH, C.PUSH_PULL):
        raise ValueError(
            f"halo rounds support flood/pull/push/pushpull, got {mode!r}")
    if topo.implicit:
        raise ValueError("halo exchange needs an explicit neighbor table")
    p = mesh.shape[axis_name]
    if n % p != 0:
        raise ValueError(f"halo rounds need n % mesh size == 0 "
                         f"(n={n}, mesh={p}); pad the topology instead")
    nl = n // p
    band = band_of(topo)
    if band > nl:
        raise ValueError(
            f"band {band} exceeds rows/shard {nl}: edges span non-adjacent "
            "shards — use the all_gather kernels (parallel/sharded.py)")
    band = max(band, 1)            # ppermute of 0 rows is degenerate
    drop_prob = 0.0 if fault is None else fault.drop_prob
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)

    def local_round(seen_l, round_, base_key, msgs, nbrs_l, deg_l,
                    *sched_tail):
        _, sched = NE.split_tables(ch, sched_tail)
        shard = jax.lax.axis_index(axis_name)
        gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        rkey = jax.random.fold_in(base_key, round_)
        # liveness in-trace (replicated compute, no O(N) inline constant)
        if ch is not None:
            # schedule operands from the argument tail (ops/nemesis doc)
            alive_full = NE.alive_rows(
                sched, NE.base_alive_or_ones(fault, n, origin), round_)
            dp = NE.drop_at(sched, round_)
            cut = NE.cut_at(sched, round_)
        else:
            alive = alive_mask(fault, n, origin)
            alive_full = (jnp.ones((n,), jnp.bool_) if alive is None
                          else alive)
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        alive_l = alive_full[gids]
        visible = seen_l & alive_l[:, None]
        ext = _exchange_halos(visible, band, axis_name)   # [nl+2B, R]
        base = shard * nl - band
        msgs_local = jnp.float32(0.0)

        def to_ext(idx):
            # global id -> extended-local row; circular, exact because every
            # needed id is within B of this block (mod n)
            return jnp.mod(idx - base, n)

        delta = jnp.zeros_like(seen_l)
        if mode == C.FLOOD:
            nbrs_use = nbrs_l
            if ch is not None:
                # churn path: always draw (traced p), then cut the
                # cross-partition edges (models/si.py flood twin)
                dropped = drop_mask(rkey, si_mod.FLOOD_DROP_TAG, gids,
                                    nbrs_use.shape[1], dp)
                nbrs_use = jnp.where(dropped, jnp.int32(n), nbrs_use)
                nbrs_use = NE.partition_targets(cut, gids, nbrs_use, n)
                valid0 = nbrs_l < n
                act_ext = jnp.any(ext, axis=1)
                sender_up = act_ext[jnp.where(valid0, to_ext(nbrs_l), 0)]
                lost = lost + jnp.sum(valid0 & sender_up
                                      & (nbrs_use >= n),
                                      dtype=jnp.float32)
            elif drop_prob > 0.0:
                dropped = drop_mask(rkey, si_mod.FLOOD_DROP_TAG, gids,
                                    nbrs_use.shape[1], drop_prob)
                nbrs_use = jnp.where(dropped, jnp.int32(n), nbrs_use)
            valid = nbrs_use < n
            got = ext[jnp.where(valid, to_ext(nbrs_use), 0)]
            delta = jnp.any(got & valid[:, :, None], axis=1)
            sender_active = jnp.any(visible, axis=1)
            msgs_local = jnp.sum(
                jnp.where(sender_active, deg_l, 0)).astype(jnp.float32)

        if mode in (C.PUSH, C.PUSH_PULL):
            # banded push: scatter into the [nl + 2B] extended buffer, then
            # hand the boundary contributions back to their owners with a
            # reverse ppermute (O(band) bytes, the push twin of the halo
            # read)
            pkey = jax.random.fold_in(rkey, si_mod.PUSH_TAG)
            targets0 = sample_peers(pkey, gids, topo, k, proto.exclude_self,
                                    local_nbrs=nbrs_l, local_deg=deg_l)
            targets = apply_drop(rkey, si_mod.PUSH_DROP_TAG, gids,
                                 targets0, dp, n, force=ch is not None)
            if ch is not None:
                targets = NE.partition_targets(cut, gids, targets, n)
            sender_active = jnp.any(visible, axis=1)
            if ch is not None:
                lost = lost + NE.lost_count(targets0, targets,
                                            sender_active, n)
            valid = (targets < n) & sender_active[:, None]
            ext_rows = nl + 2 * band
            tloc = jnp.where(valid, to_ext(targets), ext_rows)  # drop
            flat_t = tloc.reshape(-1)
            flat_p = jnp.broadcast_to(
                visible[:, None, :],
                (nl, k, visible.shape[1])).reshape(-1, visible.shape[1])
            contrib = jnp.zeros((ext_rows, visible.shape[1]), jnp.bool_
                                ).at[flat_t].max(flat_p, mode="drop")
            to_right, to_left = _ring_perms(axis_name)
            # contrib[:B] targets the LEFT neighbor's last B rows;
            # contrib[-B:] targets the RIGHT neighbor's first B rows
            recv_hi = jax.lax.ppermute(contrib[:band], axis_name, to_left)
            recv_lo = jax.lax.ppermute(contrib[band + nl:], axis_name,
                                       to_right)
            pushed = (contrib[band:band + nl]
                      | jnp.pad(recv_lo, ((0, nl - band), (0, 0)))
                      | jnp.pad(recv_hi, ((nl - band, 0), (0, 0))))
            delta = delta | pushed
            msgs_local = msgs_local + jnp.sum(valid).astype(jnp.float32)

        if mode in (C.PULL, C.PUSH_PULL):
            qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
            partners0 = sample_peers(qkey, gids, topo, k, proto.exclude_self,
                                     local_nbrs=nbrs_l, local_deg=deg_l)
            partners = apply_drop(rkey, si_mod.PULL_DROP_TAG, gids,
                                  partners0, dp, n, force=ch is not None)
            if ch is not None:
                partners = NE.partition_targets(cut, gids, partners, n)
                lost = lost + NE.lost_count(partners0, partners,
                                            alive_l, n)
            valid = partners < n
            got = ext[jnp.where(valid, to_ext(partners), 0)]
            delta = delta | jnp.any(got & valid[:, :, None], axis=1)
            req = jnp.where(alive_l[:, None], partners, n)
            msgs_local = msgs_local + 2.0 * jnp.sum(
                req < n).astype(jnp.float32)

        delta = delta & alive_l[:, None]
        msgs_new = msgs + jax.lax.psum(msgs_local, axis_name)
        if ch is not None:
            return (seen_l | delta, msgs_new,
                    jax.lax.psum(lost, axis_name))
        return seen_l | delta, msgs_new

    sh2 = P(axis_name, None)
    rep = P()
    out_specs = (sh2, rep, rep) if ch is not None else (sh2, rep)
    in_specs = (sh2, rep, rep, rep, sh2, P(axis_name))
    tables = (topo.nbrs, topo.deg)
    if ch is not None:
        in_specs += (rep,) * NE.N_SCHED_OPERANDS
        tables = tables + NE.sched_args(NE.build(fault, n))
    mapped = shard_map(
        local_round, mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs)

    def step_tabled(state: SimState, *tbl):
        out = mapped(state.seen, state.round, state.base_key,
                     state.msgs, *tbl)
        new = SimState(seen=out[0], round=state.round + 1,
                       base_key=state.base_key, msgs=out[1])
        # churn path returns (state, lost) — the models/si.py contract
        return (new, out[2]) if ch is not None else new

    return bind_tables(step_tabled, tables, tabled)


def simulate_until_halo(proto: ProtocolConfig, topo: Topology,
                        run: RunConfig, mesh: Mesh,
                        fault: Optional[FaultConfig] = None,
                        axis_name: str = "nodes", timing=None):
    """lax.while_loop to target coverage on the O(band) halo path.
    Returns (rounds, coverage, msgs, final_state, band).
    ``timing``: optional compile/steady AOT-split dict."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.trace import maybe_aot_timed
    from gossip_tpu.models.si import coverage
    from gossip_tpu.parallel.sharded import init_sharded_state
    step, tables = make_halo_round(proto, topo, mesh, fault, run.origin,
                                   axis_name, tabled=True)
    step = NE.drop_lost(step, NE.get(fault))
    init = init_sharded_state(run, proto, topo, mesh, axis_name)
    target = jnp.float32(run.target_coverage)
    n = topo.n

    @jax.jit
    def loop(state, *tbl):
        alive = NE.metric_alive(fault, n, run.origin)
        def cond(s):
            return ((coverage(s.seen, alive) < target)
                    & (s.round < run.max_rounds))
        def body(s):
            return step(s, *tbl)
        return jax.lax.while_loop(cond, body, state)

    final = maybe_aot_timed(loop, timing, init, *tables, label="halo")
    alive = NE.metric_alive(fault, n, run.origin)
    return (int(final.round), float(coverage(final.seen, alive)),
            float(final.msgs), final, band_of(topo))


def simulate_curve_halo(proto: ProtocolConfig, topo: Topology,
                        run: RunConfig, mesh: Mesh,
                        fault: Optional[FaultConfig] = None,
                        axis_name: str = "nodes", timing=None):
    """lax.scan over rounds recording (coverage, msgs) on the halo path.
    Returns (coverage[T], msgs[T], final_state, band).
    ``timing``: optional compile/steady AOT-split dict."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.trace import maybe_aot_timed
    from gossip_tpu.models.si import coverage
    from gossip_tpu.parallel.sharded import init_sharded_state
    step, tables = make_halo_round(proto, topo, mesh, fault, run.origin,
                                   axis_name, tabled=True)
    step = NE.drop_lost(step, NE.get(fault))
    init = init_sharded_state(run, proto, topo, mesh, axis_name)
    n = topo.n

    @jax.jit
    def scan(state, *tbl):
        alive = NE.metric_alive(fault, n, run.origin)
        def body(s, _):
            s = step(s, *tbl)
            return s, (coverage(s.seen, alive), s.msgs)
        return jax.lax.scan(body, state, None, length=run.max_rounds)

    final, (covs, msgs) = maybe_aot_timed(scan, timing, init, *tables,
                                          label="halo")
    return np.asarray(covs), np.asarray(msgs), final, band_of(topo)
