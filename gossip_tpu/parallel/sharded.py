"""Node-dimension sharding: the round step over a `jax.sharding.Mesh`.

This is the TPU-pod scale path (SURVEY.md §7 layer 4, §2.3): the reference
distributes by running one OS process per cluster node under Maelstrom
(reference main.go — node identity via ``node.ID()``, topology keyed by node
id); here the node dimension is an array axis sharded across devices with
``jax.shard_map``, and the reference's stdin/stdout JSON "network" (SURVEY.md
§2.4) becomes XLA collectives over ICI:

  * **push**   — each shard scatter-adds its outgoing rumors into an
    ``int32[N, R]`` count table, reduced to the owning shard with
    ``psum_scatter`` (addition *is* an XLA collective reduction; boolean OR is
    not — ``counts > 0`` recovers the OR, see ops/propagate.push_counts).
  * **pull / flood** — the visible digest table is ``all_gather``-ed
    (``bool[N, R]``: 1 byte/node/rumor, 10 MB at 10M nodes — cheap on ICI)
    and each shard gathers its sampled rows locally.
  * **coverage / message counters** — ``psum``.

Bitwise parity with the single-device kernel (tests/test_sharding.py) holds
because every random draw is keyed by (base_key, round, *global* node id) —
see ops/sampling — so mesh shape never changes the trajectory.

Nodes are padded to a multiple of the mesh size; padding rows are permanently
dead (never sample, never receive, excluded from coverage).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossip_tpu.compat import shard_map
from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.si import coverage
from gossip_tpu.models.state import (SimState, alive_mask, bind_tables,
                                     init_state)
from gossip_tpu.ops.propagate import flood_gather, pull_merge, push_counts
from gossip_tpu.ops.sampling import apply_drop, drop_mask, sample_peers
from gossip_tpu.topology.generators import Topology


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = "nodes") -> Mesh:
    """1-D device mesh over the node axis (the SP/CP analog — SURVEY.md §5:
    the scaled long dimension is nodes, not tokens)."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available")
        devs = devs[:n_devices]
    return Mesh(devs, (axis_name,))


def pad_to_mesh(n: int, mesh: Mesh, axis_name: str) -> int:
    p = mesh.shape[axis_name]
    return math.ceil(n / p) * p


def _pad_rows(x: jax.Array, n_pad: int, fill) -> jax.Array:
    n = x.shape[0]
    if n == n_pad:
        return x
    pad_shape = (n_pad - n,) + x.shape[1:]
    return jnp.concatenate([x, jnp.full(pad_shape, fill, x.dtype)], axis=0)


def sharded_alive(fault: Optional[FaultConfig], n: int, n_pad: int,
                  origin: int) -> jax.Array:
    """Combined liveness mask over padded rows: real & not-dead.

    Unlike the single-device kernel (which skips masking entirely when there
    are no faults), the sharded kernel always carries this mask because the
    padding rows must stay dark."""
    alive = alive_mask(fault, n, origin)
    if alive is None:
        alive = jnp.ones((n,), jnp.bool_)
    return _pad_rows(alive, n_pad, False)


def make_sharded_si_round(
        proto: ProtocolConfig, topo: Topology, mesh: Mesh,
        fault: Optional[FaultConfig] = None, origin: int = 0,
        axis_name: str = "nodes", tabled: bool = False):
    """Build the sharded round step.  Semantically identical to
    models/si.make_si_round; the returned function expects ``state.seen`` of
    shape ``[n_pad, R]`` (see :func:`init_sharded_state`) and may be called
    under an outer ``jax.jit`` / ``lax.while_loop``.

    Returns ``step: SimState -> SimState``; ``tabled=True`` returns
    ``(step, tables)`` with the padded topology arrays as step ARGUMENTS —
    a closed-over 1M+-row table is serialized inline into the XLA compile
    request (models/swim.py doc).  The liveness mask is built in-trace."""
    n, k = topo.n, proto.fanout
    mode = proto.mode
    if mode == C.SWIM:
        raise ValueError("SWIM rounds are built by models/swim.py")
    if mode == C.RUMOR:
        raise ValueError("rumor-mongering rounds are built by "
                         "parallel/sharded_rumor.py (SIR state, not SI)")
    if mode == C.FLOOD and topo.implicit:
        raise ValueError("flood mode needs an explicit neighbor table")
    n_pad = pad_to_mesh(n, mesh, axis_name)
    nl = n_pad // mesh.shape[axis_name]
    drop_prob = 0.0 if fault is None else fault.drop_prob
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)

    have_table = not topo.implicit
    if have_table:
        nbrs_pad = _pad_rows(topo.nbrs, n_pad, n)   # sentinel = n
        deg_pad = _pad_rows(topo.deg, n_pad, 0)

    def local_round(seen_l, round_, base_key, msgs, *table):
        """One round on this shard's rows.  Axis-collective ops: psum_scatter
        (push counts), all_gather (pull/flood digests), psum (counters)."""
        table, sched = NE.split_tables(ch, table)
        shard = jax.lax.axis_index(axis_name)
        gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        rkey = jax.random.fold_in(base_key, round_)
        # liveness in-trace (replicated compute, no O(N) inline constant)
        if ch is not None:
            # churn path: per-round liveness / drop prob / cut from the
            # schedule OPERANDS, indexed by the loop counter (ops/nemesis
            # module doc — the compiled loop carries no schedule content)
            base_pad = _pad_rows(
                NE.base_alive_or_ones(fault, n, origin), n_pad, False)
            alive_l = NE.alive_rows(sched, base_pad, round_)[gids]
            dp = NE.drop_at(sched, round_)
            cut = NE.cut_at(sched, round_)
        else:
            alive_l = sharded_alive(fault, n, n_pad, origin)[gids]
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        visible = seen_l & alive_l[:, None]
        delta = jnp.zeros_like(seen_l)
        msgs_local = jnp.float32(0.0)
        if have_table:
            nbrs_l, deg_l = table
        else:
            nbrs_l = deg_l = None

        if mode in (C.PUSH, C.PUSH_PULL):
            pkey = jax.random.fold_in(rkey, si_mod.PUSH_TAG)
            targets0 = sample_peers(pkey, gids, topo, k, proto.exclude_self,
                                    local_nbrs=nbrs_l, local_deg=deg_l)
            targets = apply_drop(rkey, si_mod.PUSH_DROP_TAG, gids,
                                 targets0, dp, n, force=ch is not None)
            if ch is not None:
                targets = NE.partition_targets(cut, gids, targets, n)
            sender_active = jnp.any(visible, axis=1)
            valid = (targets < n) & sender_active[:, None]
            # invalid -> n_pad so scatter mode='drop' really drops them
            # (sentinel n would land on a padding row when n < n_pad)
            counts = push_counts(n_pad, jnp.where(valid, targets, n_pad),
                                 visible)
            counts_l = jax.lax.psum_scatter(counts, axis_name,
                                            scatter_dimension=0, tiled=True)
            delta = delta | (counts_l > 0)
            msgs_local = msgs_local + jnp.sum(valid).astype(jnp.float32)
            if ch is not None:
                lost = lost + NE.lost_count(targets0, targets,
                                            sender_active, n)

        if mode in (C.PULL, C.PUSH_PULL, C.ANTI_ENTROPY):
            seen_all = jax.lax.all_gather(visible, axis_name, tiled=True)
            qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
            partners0 = sample_peers(qkey, gids, topo, k, proto.exclude_self,
                                     local_nbrs=nbrs_l, local_deg=deg_l)
            partners = apply_drop(rkey, si_mod.PULL_DROP_TAG, gids,
                                  partners0, dp, n, force=ch is not None)
            if ch is not None:
                partners = NE.partition_targets(cut, gids, partners, n)
            pulled = pull_merge(seen_all, partners, n)
            partners = jnp.where(alive_l[:, None], partners, n)
            n_req = jnp.sum(partners < n).astype(jnp.float32)
            if ch is not None:
                lost_pull = NE.lost_count(partners0, partners, alive_l, n)
                if mode == C.ANTI_ENTROPY and proto.period > 1:
                    # quiescent rounds send nothing, so nothing is lost
                    lost_pull = jnp.where((round_ % proto.period) == 0,
                                          lost_pull, 0.0)
                lost = lost + lost_pull
            if mode == C.ANTI_ENTROPY:
                # bidirectional reconciliation (twin of models/si.py): the
                # initiator's state scatters back into the partner's row
                bt = jnp.where(partners < n, partners, n_pad)

                def reverse_delta(_):
                    bcounts = push_counts(n_pad, bt, visible)
                    return jax.lax.psum_scatter(bcounts, axis_name,
                                                scatter_dimension=0,
                                                tiled=True) > 0

                if proto.period > 1:
                    # lax.cond, not a mask: the psum_scatter must not move
                    # bytes on quiescent rounds (the predicate is replicated,
                    # so every shard takes the same branch)
                    on = (round_ % proto.period) == 0
                    back = jax.lax.cond(
                        on, reverse_delta,
                        lambda _: jnp.zeros_like(pulled), None)
                    pulled = jnp.where(on, pulled, False)
                    n_req = jnp.where(on, n_req, 0.0)
                else:
                    back = reverse_delta(None)
                delta = delta | pulled | back
                msgs_local = msgs_local + 3.0 * n_req
            else:
                delta = delta | pulled
                msgs_local = msgs_local + 2.0 * n_req

        if mode == C.FLOOD:
            seen_all = jax.lax.all_gather(visible, axis_name, tiled=True)
            nbrs_use = nbrs_l
            if ch is not None:
                # churn path: always draw (traced p), then cut the
                # cross-partition edges (models/si.py flood twin)
                dropped = drop_mask(rkey, si_mod.FLOOD_DROP_TAG, gids,
                                    nbrs_use.shape[1], dp)
                nbrs_use = jnp.where(dropped, jnp.int32(n), nbrs_use)
                nbrs_use = NE.partition_targets(cut, gids, nbrs_use, n)
                act_full = jnp.any(seen_all, axis=1)
                edge_live = ((nbrs_l < n)
                             & act_full[jnp.clip(nbrs_l, 0, n - 1)])
                lost = lost + jnp.sum(edge_live & (nbrs_use >= n),
                                      dtype=jnp.float32)
            elif drop_prob > 0.0:
                dropped = drop_mask(rkey, si_mod.FLOOD_DROP_TAG, gids,
                                    nbrs_use.shape[1], drop_prob)
                nbrs_use = jnp.where(dropped, jnp.int32(n), nbrs_use)
            delta = flood_gather(seen_all, nbrs_use, n)
            sender_active = jnp.any(visible, axis=1)
            msgs_local = msgs_local + jnp.sum(
                jnp.where(sender_active, deg_l, 0)).astype(jnp.float32)

        delta = delta & alive_l[:, None]
        msgs_new = msgs + jax.lax.psum(msgs_local, axis_name)
        if ch is not None:
            return (seen_l | delta, msgs_new,
                    jax.lax.psum(lost, axis_name))
        return seen_l | delta, msgs_new

    sh = P(axis_name)          # rows sharded
    sh2 = P(axis_name, None)   # rows sharded, rumor dim replicated
    rep = P()
    in_specs = [sh2, rep, rep, rep]
    tables = ()
    if have_table:
        in_specs += [sh2, sh]
        tables = (nbrs_pad, deg_pad)
    if ch is not None:
        # schedule operands replicated over the mesh (tiny tables; the
        # per-shard slice happens via gids inside local_round)
        in_specs += [rep] * NE.N_SCHED_OPERANDS
        tables = tables + NE.sched_args(NE.build(fault, n, n_pad))

    out_specs = (sh2, rep, rep) if ch is not None else (sh2, rep)
    mapped = shard_map(local_round, mesh=mesh,
                           in_specs=tuple(in_specs),
                           out_specs=out_specs)

    def step_tabled(state: SimState, *tbl):
        out = mapped(state.seen, state.round, state.base_key,
                     state.msgs, *tbl)
        seen, msgs = out[0], out[1]
        new = SimState(seen=seen, round=state.round + 1,
                       base_key=state.base_key, msgs=msgs)
        # churn path returns (state, lost) — the models/si.py contract
        return (new, out[2]) if ch is not None else new

    return bind_tables(step_tabled, tables, tabled)


def init_sharded_state(run: RunConfig, proto: ProtocolConfig, topo: Topology,
                       mesh: Mesh, axis_name: str = "nodes") -> SimState:
    """Initial state with ``seen`` padded to the mesh and placed sharded."""
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    st = init_state(run, proto, topo.n)
    seen = _pad_rows(st.seen, n_pad, False)
    seen = jax.device_put(seen, NamedSharding(mesh, P(axis_name, None)))
    return SimState(seen=seen, round=st.round, base_key=st.base_key,
                    msgs=st.msgs)


def _dense_round_bytes(proto: ProtocolConfig, n_pad: int, nl: int):
    """``round_ -> f32`` analytic per-device ICI egress of one dense
    round (ops/round_metrics ``bytes`` semantics — the SparseMeta
    per-device convention): the psum_scatter contribution table is
    ``4*n_pad*R`` int32 bytes, the all_gather egress ``nl*R`` bool
    bytes, the msgs psum 4; anti-entropy's reverse psum_scatter moves
    only on exchange rounds, which the returned closure gates in-trace
    on ``round_`` exactly as the kernel's lax.cond does."""
    r = proto.rumors
    mode = proto.mode
    base = 4.0
    if mode in (C.PUSH, C.PUSH_PULL):
        base += 4.0 * n_pad * r
    if mode in (C.PULL, C.PUSH_PULL, C.ANTI_ENTROPY, C.FLOOD):
        base += 1.0 * nl * r

    def per_round(round_):
        from gossip_tpu.ops import round_metrics as RM
        b = jnp.float32(base)
        if mode == C.ANTI_ENTROPY:
            b = b + RM.gate_on_exchange_rounds(4.0 * n_pad * r,
                                               proto.period, round_)
        return b

    return per_round


def _dense_recorder(proto: ProtocolConfig, n_pad: int, n_shards: int):
    """``(m, prev_count, round0, msgs0, s_after, alive) -> (m, count)``
    — the in-loop metrics row for the dense bool-digest drivers
    (ops/round_metrics counter semantics; a pure readout, so
    trajectories are bitwise what they were without it).  The previous
    round's entry count rides the carry as ONE scalar instead of
    re-reading the pre-step table after the step — keeping the old
    digest alive across the round body would force XLA to double-buffer
    (or copy) the state every round, the exact liveness pathology the
    fused engine's donation contract documents."""
    from gossip_tpu.ops import round_metrics as RM
    bytes_of = _dense_round_bytes(proto, n_pad, n_pad // n_shards)
    offered_per_msg = proto.rumors * RM.payload_factor(proto.mode)

    def rec(m, prev_count, round0, msgs0, s1, alive_pad, nem=None):
        count = RM.count_bool(s1.seen, alive_pad)
        newly = count - prev_count
        msgs = s1.msgs - msgs0
        kw = ({} if nem is None
              else dict(alive=nem[0], cut_pairs=nem[1], dropped=nem[2]))
        return RM.record(
            m, newly=newly, msgs=msgs,
            dup=RM.dup_estimate(offered_per_msg * msgs, newly),
            bytes=bytes_of(round0),
            front=RM.front_bool(s1.seen, alive_pad, n_shards), **kw), count

    return rec


def _churn_observables(fault, n: int, n_pad: int, origin: int):
    """``(round0, lost, sched) -> (alive, cut_pairs, dropped)`` for the
    recorders, or None without a churn schedule — the nemesis
    observable row (ops/nemesis.observables + the kernel's exact lost
    count), shared by every sharded driver family.  ``sched`` is the
    TRACED schedule operand the driver peeled off its table tail
    (``NE.split_tables`` / ``NE.sched_of_tables``) — rebuilding it here
    would bake the content back into the loop."""
    from gossip_tpu.ops import nemesis as NE
    if NE.get(fault) is None:
        return None

    def obs(round0, lost, sched):
        base_pad = _pad_rows(NE.base_alive_or_ones(fault, n, origin),
                             n_pad, False)
        alive_now = NE.alive_rows(sched, base_pad, round0)
        a, pairs = NE.observables(sched, alive_now, round0)
        return a, pairs, lost

    return obs


@functools.lru_cache(maxsize=32)
def _cached_dense_loop(kind: str, proto: ProtocolConfig, n: int,
                       have_table: bool, mesh: Mesh,
                       fault_static: FaultConfig, origin: int,
                       axis_name: str, max_rounds: int, target: float,
                       metrics_on: bool):
    """The dense sharded drivers' compiled CHURN loop (``kind``:
    ``curve`` = lax.scan, ``until`` = lax.while_loop), memoized by
    EXACTLY the statics its trace bakes — which, since the schedule
    tables are runtime operands, excludes the schedule CONTENT: K
    nemesis scenarios over one config re-enter ONE compiled loop
    (compile-count-pinned in tests/test_nemesis.py; the sweep memo
    discipline of sweep._cached_pod_sweep_scan).

    Everything scenario-shaped flows through the returned callable as
    ARGUMENTS: ``(state, alive_pad, *tables)`` where ``alive_pad`` is
    the scenario's EVENTUAL alive denominator (ops/nemesis
    .eventual_alive_pad — a function of which churn deaths are
    permanent, i.e. content) and ``tables`` is the factory tail
    (topology pads + schedule operands).  The step itself is built
    against a shape-placeholder topology and a representative one-event
    schedule: the trace reads only ``n``/implicit-vs-table from the
    topology and only SHAPES from the schedule, both part of this key
    (jit's own cache handles canonical-bucket/table-width retraces
    within one entry).  ``fault_static`` must carry ``churn=None`` —
    its static death draw IS baked, which is why it is in the key."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    rep_fault, topo_ph = NE.placeholder_trace_inputs(fault_static, n,
                                                     have_table)
    step, _ = make_sharded_si_round(proto, topo_ph, mesh, rep_fault,
                                    origin, axis_name, tabled=True)
    n_pad = pad_to_mesh(n, mesh, axis_name)
    n_shards = mesh.shape[axis_name]
    rec = (_dense_recorder(proto, n_pad, n_shards) if metrics_on
           else None)
    obs = (_churn_observables(rep_fault, n, n_pad, origin)
           if metrics_on else None)
    label = ("simulate_curve_sharded" if kind == "curve"
             else "simulate_until_sharded")

    def advance(carry, alive_pad, tbl):
        s0, m, cnt = carry
        round0, msgs0 = s0.round, s0.msgs
        s, lost = step(s0, *tbl)
        if m is not None:
            m, cnt = rec(m, cnt, round0, msgs0, s, alive_pad,
                         nem=obs(round0, lost, NE.sched_of_tables(tbl)))
        return s, m, cnt

    if kind == "curve":
        def scan(state, alive_pad, *tbl):
            m0 = (RM.init(max_rounds, n_shards, label, nemesis=True)
                  if rec else None)
            c0 = RM.count_bool(state.seen, alive_pad) if rec else None

            def body(carry, _):
                s, m, cnt = advance(carry, alive_pad, tbl)
                return (s, m, cnt), (coverage(s.seen, alive_pad),
                                     s.msgs)
            return jax.lax.scan(body, (state, m0, c0), None,
                                length=max_rounds)
        return jax.jit(scan)

    def loop(state, alive_pad, *tbl):
        m0 = (RM.init(max_rounds, n_shards, label, nemesis=True)
              if rec else None)
        c0 = RM.count_bool(state.seen, alive_pad) if rec else None

        def cond(carry):
            s, _, _ = carry
            return ((coverage(s.seen, alive_pad) < jnp.float32(target))
                    & (s.round < max_rounds))

        def body(carry):
            return advance(carry, alive_pad, tbl)
        return jax.lax.while_loop(cond, body, (state, m0, c0))
    return jax.jit(loop)


def _dense_step_tables(topo: Topology, fault, n_pad: int):
    """The dense step's table-argument tail WITHOUT building the step:
    topology pads + schedule operands, in exactly
    make_sharded_si_round's layout (pinned bitwise by the golden
    churn fingerprints) — so the K warm re-entries the memoized loop
    exists for pay only the per-scenario schedule build, not a full
    factory (shard_map plumbing + table re-pad) per call."""
    from gossip_tpu.ops import nemesis as NE
    n = topo.n
    tables = (() if topo.implicit
              else (_pad_rows(topo.nbrs, n_pad, n),
                    _pad_rows(topo.deg, n_pad, 0)))
    return tables + NE.sched_args(NE.build(fault, n, n_pad))


def _dense_churn_call(kind, proto, topo, run, mesh, fault, axis_name):
    """(loop, operands) for the memoized churn path: the shape-keyed
    compiled loop plus this scenario's runtime operands — initial
    state, eventual-alive denominator, topology pads + schedule
    tables (:func:`_dense_step_tables`)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    tables = _dense_step_tables(topo, fault, n_pad)
    # the memo key strips drop_prob too: on the churn path the per-
    # round probability always comes from the drop_tbl OPERAND (the
    # base rate is content), so scenarios differing only in drop_prob
    # must share the one compiled loop
    fn = _cached_dense_loop(
        kind, proto, topo.n, not topo.implicit, mesh,
        dataclasses.replace(fault, churn=None, drop_prob=0.0),
        run.origin, axis_name,
        run.max_rounds, run.target_coverage, RM.wanted())
    init = init_sharded_state(run, proto, topo, mesh, axis_name)
    alive_op = NE.eventual_alive_pad(fault, topo.n, n_pad, run.origin)
    return fn, (init, alive_op) + tuple(tables)


def simulate_curve_sharded(proto: ProtocolConfig, topo: Topology,
                           run: RunConfig, mesh: Mesh,
                           fault: Optional[FaultConfig] = None,
                           axis_name: str = "nodes", timing=None):
    """``lax.scan`` over rounds recording (coverage, msgs) per round, state
    resident sharded.  Sharded twin of runtime/simulator.simulate_curve.
    Returns (coverage[T], msgs[T], final_state) as host arrays/state.
    ``timing``: optional dict filled with the compile/steady AOT split
    (utils/trace.maybe_aot_timed — VERDICT r4 task 5: sharded rows must
    decompose like single-device ones).  With an active run ledger the
    scan carries a round-metrics buffer stack, flushed once by the
    chokepoint (ops/round_metrics)."""
    import numpy as np

    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    from gossip_tpu.ops import nemesis as NE
    if NE.get(fault) is not None:
        # churn path: the shape-keyed memoized loop — schedule content
        # and the eventual-alive denominator ride as operands, so K
        # scenarios compile once (_cached_dense_loop)
        fn, operands = _dense_churn_call("curve", proto, topo, run,
                                         mesh, fault, axis_name)
        (final, _, _), (covs, msgs) = maybe_aot_timed(fn, timing,
                                                      *operands, label="dense")
        return np.asarray(covs), np.asarray(msgs), final
    step, tables = make_sharded_si_round(proto, topo, mesh, fault,
                                         run.origin, axis_name, tabled=True)
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    init = init_sharded_state(run, proto, topo, mesh, axis_name)
    n_shards = mesh.shape[axis_name]
    rec = _dense_recorder(proto, n_pad, n_shards) if RM.wanted() else None

    @jax.jit
    def scan(state, *tbl):
        alive_pad = sharded_alive(fault, topo.n, n_pad, run.origin)
        m0 = (RM.init(run.max_rounds, n_shards, "simulate_curve_sharded")
              if rec else None)
        c0 = RM.count_bool(state.seen, alive_pad) if rec else None
        def body(carry, _):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            s = step(s0, *tbl)
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, alive_pad)
            return (s, m, cnt), (coverage(s.seen, alive_pad), s.msgs)
        return jax.lax.scan(body, (state, m0, c0), None,
                            length=run.max_rounds)

    (final, _, _), (covs, msgs) = maybe_aot_timed(scan, timing, init,
                                                  *tables, label="dense")
    return np.asarray(covs), np.asarray(msgs), final


def simulate_until_sharded(proto: ProtocolConfig, topo: Topology,
                           run: RunConfig, mesh: Mesh,
                           fault: Optional[FaultConfig] = None,
                           axis_name: str = "nodes", timing=None):
    """``lax.while_loop`` to target coverage, whole loop one XLA program, state
    resident sharded across the mesh.  Returns (rounds, coverage, msgs, state).
    ``timing``: optional compile/steady AOT-split dict (see
    simulate_curve_sharded).  With an active run ledger the loop carries
    a round-metrics buffer stack, flushed once by the chokepoint
    (ops/round_metrics)."""
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    from gossip_tpu.ops import nemesis as NE
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    if NE.get(fault) is not None:
        # churn path: the shape-keyed memoized loop (curve-driver twin)
        fn, operands = _dense_churn_call("until", proto, topo, run,
                                         mesh, fault, axis_name)
        final, _, _ = maybe_aot_timed(fn, timing, *operands, label="dense")
        alive_pad = NE.eventual_alive_pad(fault, topo.n, n_pad,
                                          run.origin)
        return (int(final.round),
                float(coverage(final.seen, alive_pad)),
                float(final.msgs), final)
    step, tables = make_sharded_si_round(proto, topo, mesh, fault,
                                         run.origin, axis_name, tabled=True)
    alive_pad = sharded_alive(fault, topo.n, n_pad, run.origin)
    init = init_sharded_state(run, proto, topo, mesh, axis_name)
    target = jnp.float32(run.target_coverage)
    n_shards = mesh.shape[axis_name]
    rec = _dense_recorder(proto, n_pad, n_shards) if RM.wanted() else None

    @jax.jit
    def loop(state, *tbl):
        alive_t = sharded_alive(fault, topo.n, n_pad, run.origin)
        m0 = (RM.init(run.max_rounds, n_shards, "simulate_until_sharded")
              if rec else None)
        c0 = RM.count_bool(state.seen, alive_t) if rec else None
        def cond(carry):
            s, _, _ = carry
            return ((coverage(s.seen, alive_t) < target)
                    & (s.round < run.max_rounds))
        def body(carry):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            s = step(s0, *tbl)
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, alive_t)
            return s, m, cnt
        return jax.lax.while_loop(cond, body, (state, m0, c0))

    final, _, _ = maybe_aot_timed(loop, timing, init, *tables, label="dense")
    return (int(final.round), float(coverage(final.seen, alive_pad)),
            float(final.msgs), final)
