"""Node-dim sharded SIR rumor mongering — the shard_map twin of
models/rumor.py, bitwise-identical to the single-device kernel on any
mesh (same per-node threefry streams keyed by GLOBAL ids, same counter
semantics; tested in tests/test_rumor.py).

Communication per round (dense-exchange family, parallel/sharded.py):
``psum_scatter`` of the push counts (deliveries) and — for the feedback
variant — one ``all_gather`` of the round-start ``seen`` table so each
shard can check whether its push recipients already knew the rumor.
Blind needs NO gather: its counters depend only on local state, so a
blind rumor round moves strictly less ICI than an SI push round at the
same fanout, and the hot set's extinction makes the total traffic
O(N * rumor_k) messages instead of SI's O(N * rounds).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossip_tpu.compat import shard_map
from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.rumor import (RUMOR_DROP_TAG, RUMOR_PUSH_TAG,
                                     RumorState, init_rumor_state,
                                     rumor_coverage)
from gossip_tpu.models.state import bind_tables
from gossip_tpu.ops.propagate import push_counts
from gossip_tpu.ops.sampling import apply_drop, sample_peers
from gossip_tpu.parallel.sharded import (_pad_rows, pad_to_mesh,
                                         sharded_alive)
from gossip_tpu.topology.generators import Topology


def make_sharded_rumor_round(proto: ProtocolConfig, topo: Topology,
                             mesh: Mesh,
                             fault: Optional[FaultConfig] = None,
                             origin: int = 0, axis_name: str = "nodes",
                             tabled: bool = False):
    """Sharded round step; semantics identical to make_rumor_round."""
    if proto.mode != C.RUMOR:
        raise ValueError(f"make_sharded_rumor_round builds mode='rumor' "
                         f"only (got {proto.mode!r})")
    n, k = topo.n, proto.fanout
    kk = proto.rumor_k
    feedback = proto.rumor_variant == "feedback"
    drop_prob = 0.0 if fault is None else fault.drop_prob
    n_pad = pad_to_mesh(n, mesh, axis_name)
    nl = n_pad // mesh.shape[axis_name]
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)

    have_table = not topo.implicit
    if have_table:
        nbrs_pad = _pad_rows(topo.nbrs, n_pad, n)
        deg_pad = _pad_rows(topo.deg, n_pad, 0)

    def local_round(seen_l, hot_l, cnt_l, round_, base_key, msgs, *table):
        table, sched = NE.split_tables(ch, table)
        shard = jax.lax.axis_index(axis_name)
        gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        rkey = jax.random.fold_in(base_key, round_)
        if ch is not None:
            # schedule operands from the table tail (ops/nemesis doc)
            base_pad = _pad_rows(
                NE.base_alive_or_ones(fault, n, origin), n_pad, False)
            alive_l = NE.alive_rows(sched, base_pad, round_)[gids]
            dp = NE.drop_at(sched, round_)
            cut = NE.cut_at(sched, round_)
        else:
            alive_l = sharded_alive(fault, n, n_pad, origin)[gids]
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        nbrs_l, deg_l = table if have_table else (None, None)

        payload = hot_l & alive_l[:, None]                     # [nl, R]
        pkey = jax.random.fold_in(rkey, RUMOR_PUSH_TAG)
        targets0 = sample_peers(pkey, gids, topo, k, proto.exclude_self,
                                local_nbrs=nbrs_l, local_deg=deg_l)
        targets = apply_drop(rkey, RUMOR_DROP_TAG, gids, targets0,
                             dp, n, force=ch is not None)      # [nl, k]
        if ch is not None:
            targets = NE.partition_targets(cut, gids, targets, n)
        sender_active = jnp.any(payload, axis=1)
        valid = (targets < n) & sender_active[:, None]

        # Deliveries: scatter counts of the hot payload, reduce-scatter.
        counts = push_counts(n_pad, jnp.where(valid, targets, n_pad),
                             payload)
        counts_l = jax.lax.psum_scatter(counts, axis_name,
                                        scatter_dimension=0, tiled=True)
        delta = (counts_l > 0) & alive_l[:, None]

        # Counters against the ROUND-START global seen (feedback needs the
        # recipients' prior knowledge — one all_gather; blind is local).
        if feedback:
            seen_all = jax.lax.all_gather(seen_l, axis_name, tiled=True)
            safe_t = jnp.where(valid, targets, 0)
            knew = seen_all[safe_t] & valid[:, :, None]        # [nl,k,R]
            hits = jnp.sum(knew, axis=1, dtype=jnp.int32)
        else:
            hits = jnp.sum(valid, axis=1, dtype=jnp.int32)[:, None]
        cnt_l = cnt_l + jnp.where(payload, hits, 0)

        new = delta & ~seen_l
        # dead nodes hold no hot bits (extinction-loop liveness; matches
        # the single-device kernel — a dead origin's rumor never spreads)
        hot_l = ((hot_l & (cnt_l < kk)) | new) & alive_l[:, None]
        msgs_new = msgs + jax.lax.psum(
            jnp.sum(valid).astype(jnp.float32), axis_name)
        if ch is not None:
            lost = lost + NE.lost_count(targets0, targets,
                                        sender_active, n)
            return (seen_l | delta, hot_l, cnt_l, msgs_new,
                    jax.lax.psum(lost, axis_name))
        return seen_l | delta, hot_l, cnt_l, msgs_new

    sh2 = P(axis_name, None)
    rep = P()
    in_specs = [sh2, sh2, sh2, rep, rep, rep]
    tables = ()
    if have_table:
        in_specs += [sh2, P(axis_name)]
        tables = (nbrs_pad, deg_pad)
    if ch is not None:
        in_specs += [rep] * NE.N_SCHED_OPERANDS
        tables = tables + NE.sched_args(NE.build(fault, n, n_pad))

    out_specs = ((sh2, sh2, sh2, rep, rep) if ch is not None
                 else (sh2, sh2, sh2, rep))
    mapped = shard_map(local_round, mesh=mesh,
                           in_specs=tuple(in_specs),
                           out_specs=out_specs)

    def step_tabled(state: RumorState, *tbl):
        out = mapped(state.seen, state.hot, state.cnt,
                     state.round, state.base_key, state.msgs, *tbl)
        new = RumorState(seen=out[0], hot=out[1], cnt=out[2],
                         round=state.round + 1,
                         base_key=state.base_key, msgs=out[3])
        # churn path returns (state, lost) — the models/si.py contract
        return (new, out[4]) if ch is not None else new

    return bind_tables(step_tabled, tables, tabled)


def init_sharded_rumor_state(run: RunConfig, proto: ProtocolConfig,
                             topo: Topology, mesh: Mesh,
                             axis_name: str = "nodes") -> RumorState:
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    st = init_rumor_state(run, proto, topo.n)
    put = lambda x, fill: jax.device_put(               # noqa: E731
        _pad_rows(x, n_pad, fill),
        NamedSharding(mesh, P(axis_name, None)))
    return RumorState(seen=put(st.seen, False), hot=put(st.hot, False),
                      cnt=put(st.cnt, 0), round=st.round,
                      base_key=st.base_key, msgs=st.msgs)


def _rumor_recorder(proto: ProtocolConfig, n_pad: int,
                    n_shards: int):
    """In-loop metrics row for the SIR rumor drivers
    (ops/round_metrics).  The kernel's own hit counters make ``dup``
    EXACT for the feedback variant — ``cnt`` grows by precisely the
    contacts whose recipient already knew — while blind's counter
    counts all contacts, so there the estimator subtracts the round's
    new infections (module-doc upper bound).  The previous round's
    seen/cnt totals ride the carry as two scalars
    (parallel/sharded._dense_recorder liveness rationale)."""
    from gossip_tpu.ops import round_metrics as RM
    feedback = proto.rumor_variant == "feedback"
    r = proto.rumors
    nl = n_pad // n_shards
    # psum_scatter of the int32 counts table every round; feedback adds
    # the round-start seen all_gather (bool egress); plus the msgs psum
    base_bytes = 4.0 * n_pad * r + (1.0 * nl * r if feedback else 0.0) \
        + 4.0

    def rec(m, prev, msgs0, s1, alive, nem=None):
        count = RM.count_bool(s1.seen, alive)
        cntsum = jnp.sum(jnp.where(alive[:, None], s1.cnt, 0),
                         dtype=jnp.float32)
        newly = count - prev[0]
        contacts = cntsum - prev[1]
        kw = ({} if nem is None
              else dict(alive=nem[0], cut_pairs=nem[1], dropped=nem[2]))
        return RM.record(
            m, newly=newly, msgs=s1.msgs - msgs0,
            dup=(contacts if feedback
                 else RM.dup_estimate(contacts, newly)),
            bytes=base_bytes,
            front=RM.front_bool(s1.seen, alive, n_shards), **kw), \
            (count, cntsum)

    def init_prev(state, alive):
        return (RM.count_bool(state.seen, alive),
                jnp.sum(jnp.where(alive[:, None], state.cnt, 0),
                        dtype=jnp.float32))

    return rec, init_prev


def simulate_curve_rumor_sharded(proto: ProtocolConfig, topo: Topology,
                                 run: RunConfig, mesh: Mesh,
                                 fault: Optional[FaultConfig] = None,
                                 axis_name: str = "nodes", timing=None):
    """Fixed-length scan with per-round (coverage, hot_fraction, msgs)
    curves, state resident sharded — the multi-device twin of
    models/rumor.simulate_curve_rumor (same returns; curves weighted by
    the padded alive mask so padding rows deflate nothing).  Closes the
    round-3 carve-out where rumor curve capture was single-device
    only.  ``timing``: optional compile/steady AOT-split dict
    (utils/trace.maybe_aot_timed contract); with an active run ledger
    the scan carries a round-metrics buffer stack (ops/round_metrics)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.parallel.sharded import _churn_observables
    from gossip_tpu.utils.trace import maybe_aot_timed
    step, tables = make_sharded_rumor_round(proto, topo, mesh, fault,
                                            run.origin, axis_name,
                                            tabled=True)
    init = init_sharded_rumor_state(run, proto, topo, mesh, axis_name)
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    n_shards = mesh.shape[axis_name]
    rec, init_prev = (_rumor_recorder(proto, n_pad, n_shards)
                      if RM.wanted() else (None, None))
    ch = NE.get(fault)
    obs = _churn_observables(fault, topo.n, n_pad, run.origin)

    @jax.jit
    def scan(state, *tbl):
        alive = (NE.eventual_alive_pad(fault, topo.n, n_pad, run.origin)
                 if ch is not None
                 else sharded_alive(fault, topo.n, n_pad, run.origin))
        w = alive.astype(jnp.float32)
        m0 = (RM.init(run.max_rounds, n_shards,
                      "simulate_curve_rumor_sharded",
                      nemesis=ch is not None) if rec else None)
        p0 = init_prev(state, alive) if rec else None

        def body(carry, _):
            s0, m, prev = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lost = step(s0, *tbl)
            else:
                s, lost = step(s0, *tbl), None
            if m is not None:
                m, prev = rec(m, prev, msgs0, s, alive,
                              nem=(obs(round0, lost,
                                       NE.sched_of_tables(tbl))
                                   if obs else None))
            hot_any = jnp.any(s.hot, axis=1).astype(jnp.float32)
            hot_frac = jnp.sum(hot_any * w) / jnp.sum(w)
            return ((s, m, prev),
                    (rumor_coverage(s.seen, alive), hot_frac, s.msgs))
        return jax.lax.scan(body, (state, m0, p0), None,
                            length=run.max_rounds)

    (final, _, _), (covs, hots, msgs) = maybe_aot_timed(scan, timing,
                                                        init, *tables,
                                                        label="rumor")
    return covs, hots, msgs, final


def restore_sharded_rumor_state(state: RumorState, mesh: Mesh,
                                axis_name: str = "nodes") -> RumorState:
    """Re-place a host-loaded checkpoint (utils/checkpoint.load_state
    gathers to host) back onto the mesh; rows are already padded (the
    config fingerprint pins the mesh shape)."""
    sharding = NamedSharding(mesh, P(axis_name, None))
    put = lambda x: jax.device_put(jnp.asarray(x), sharding)  # noqa: E731
    return RumorState(seen=put(state.seen), hot=put(state.hot),
                      cnt=put(state.cnt), round=state.round,
                      base_key=state.base_key, msgs=state.msgs)


def simulate_until_rumor_sharded(proto: ProtocolConfig, topo: Topology,
                                 run: RunConfig, mesh: Mesh,
                                 fault: Optional[FaultConfig] = None,
                                 axis_name: str = "nodes", timing=None):
    """Run to extinction or max_rounds, one compiled while_loop, state
    resident sharded.  Same returns as models/rumor.simulate_until_rumor.
    ``timing``: optional compile/steady AOT-split dict; with an active
    run ledger the loop carries a round-metrics buffer stack
    (ops/round_metrics)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.parallel.sharded import _churn_observables
    from gossip_tpu.utils.trace import maybe_aot_timed
    step, tables = make_sharded_rumor_round(proto, topo, mesh, fault,
                                            run.origin, axis_name,
                                            tabled=True)
    init = init_sharded_rumor_state(run, proto, topo, mesh, axis_name)
    n_pad_m = pad_to_mesh(topo.n, mesh, axis_name)
    n_shards = mesh.shape[axis_name]
    rec, init_prev = (_rumor_recorder(proto, n_pad_m, n_shards)
                      if RM.wanted() else (None, None))
    ch = NE.get(fault)
    obs = _churn_observables(fault, topo.n, n_pad_m, run.origin)

    def alive_of(n_rows):
        if ch is not None:
            return NE.eventual_alive_pad(fault, topo.n, n_rows,
                                         run.origin)
        return sharded_alive(fault, topo.n, n_rows, run.origin)

    @jax.jit
    def loop(state, *tbl):
        alive = alive_of(n_pad_m)
        m0 = (RM.init(run.max_rounds, n_shards,
                      "simulate_until_rumor_sharded",
                      nemesis=ch is not None) if rec else None)
        p0 = init_prev(state, alive) if rec else None

        def cond(carry):
            s, _, _ = carry
            return jnp.any(s.hot) & (s.round < run.max_rounds)

        def body(carry):
            s0, m, prev = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lost = step(s0, *tbl)
            else:
                s, lost = step(s0, *tbl), None
            if m is not None:
                m, prev = rec(m, prev, msgs0, s, alive,
                              nem=(obs(round0, lost,
                                       NE.sched_of_tables(tbl))
                                   if obs else None))
            return s, m, prev

        return jax.lax.while_loop(cond, body, (state, m0, p0))

    final, _, _ = maybe_aot_timed(loop, timing, init, *tables, label="rumor")
    # always weight by the padded alive mask: padding rows must not
    # deflate coverage (sharded_alive marks them dead even fault-free)
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    alive = alive_of(n_pad)
    cov = float(rumor_coverage(final.seen, alive))
    return (int(final.round), cov, 1.0 - cov, float(final.msgs), final)
