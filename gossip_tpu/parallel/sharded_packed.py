"""Sharded bit-packed pull round: 8x less ICI traffic than bool digests.

Twin of models/si_packed.make_packed_round over the node mesh.  The only
collective is the all_gather of the packed visible table — ``N x W`` uint32
words per round (1.25 MB at N=10M, R=1; 10 MB at R=256) instead of the bool
table's ``N x R`` bytes.  Bitwise-parity-tested against the single-device
packed round (and hence against the unpacked pull round) in
tests/test_packed.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossip_tpu.compat import shard_map
from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.si_packed import init_packed_state, pull_merge_packed
from gossip_tpu.models.state import SimState, bind_tables
from gossip_tpu.ops.bitpack import coverage_packed, pack, unpack
from gossip_tpu.ops.propagate import push_counts
from gossip_tpu.ops.sampling import apply_drop, sample_peers
from gossip_tpu.parallel.sharded import (_pad_rows, pad_to_mesh,
                                         sharded_alive)
from gossip_tpu.topology.generators import Topology


def make_sharded_packed_round(
        proto: ProtocolConfig, topo: Topology, mesh: Mesh,
        fault: Optional[FaultConfig] = None, origin: int = 0,
        axis_name: str = "nodes", tabled: bool = False):
    """``tabled=True`` returns ``(step, tables)`` with the padded topology
    arrays as step ARGUMENTS (no O(N) jit closure constants —
    models/swim.py doc); the liveness mask is built in-trace."""
    n, k = topo.n, proto.fanout
    mode = proto.mode
    if mode not in (C.PULL, C.ANTI_ENTROPY):
        raise ValueError("packed rounds support pull/antientropy only")
    n_pad = pad_to_mesh(n, mesh, axis_name)
    nl = n_pad // mesh.shape[axis_name]
    drop_prob = 0.0 if fault is None else fault.drop_prob
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)

    have_table = not topo.implicit
    if have_table:
        nbrs_pad = _pad_rows(topo.nbrs, n_pad, n)
        deg_pad = _pad_rows(topo.deg, n_pad, 0)

    def local_round(packed_l, round_, base_key, msgs, *table):
        table, sched = NE.split_tables(ch, table)
        shard = jax.lax.axis_index(axis_name)
        gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        rkey = jax.random.fold_in(base_key, round_)
        # liveness in-trace (replicated compute, no O(N) inline constant)
        if ch is not None:
            # schedule operands from the table tail (ops/nemesis doc)
            base_pad = _pad_rows(
                NE.base_alive_or_ones(fault, n, origin), n_pad, False)
            alive_l = NE.alive_rows(sched, base_pad, round_)[gids]
            dp = NE.drop_at(sched, round_)
            cut = NE.cut_at(sched, round_)
        else:
            alive_l = sharded_alive(fault, n, n_pad, origin)[gids]
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        visible = jnp.where(alive_l[:, None], packed_l, jnp.uint32(0))
        packed_all = jax.lax.all_gather(visible, axis_name, tiled=True)
        nbrs_l, deg_l = table if have_table else (None, None)

        qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
        partners0 = sample_peers(qkey, gids, topo, k, proto.exclude_self,
                                 local_nbrs=nbrs_l, local_deg=deg_l)
        partners = apply_drop(rkey, si_mod.PULL_DROP_TAG, gids,
                              partners0, dp, n, force=ch is not None)
        if ch is not None:
            partners = NE.partition_targets(cut, gids, partners, n)
        pulled = pull_merge_packed(packed_all, partners, n)
        partners = jnp.where(alive_l[:, None], partners, n)
        n_req = jnp.sum(partners < n).astype(jnp.float32)
        if ch is not None:
            lost_pull = NE.lost_count(partners0, partners, alive_l, n)
            if mode == C.ANTI_ENTROPY and proto.period > 1:
                # quiescent rounds send nothing, so nothing is lost
                lost_pull = jnp.where((round_ % proto.period) == 0,
                                      lost_pull, 0.0)
            lost = lost + lost_pull
        if mode == C.ANTI_ENTROPY:
            # Bidirectional reconciliation (twin of models/si_packed.py):
            # the reverse delta scatters bool contributions and reduces
            # them with psum_scatter (int counts, OR = count > 0), then
            # repacks — the pull direction keeps the packed-word
            # all_gather.  On off-period rounds a lax.cond skips the
            # collective entirely (replicated predicate, uniform branch).
            bt = jnp.where(partners < n, partners, n_pad)

            def reverse_delta(_):
                bcounts = push_counts(n_pad, bt,
                                      unpack(visible, proto.rumors))
                return pack(jax.lax.psum_scatter(bcounts, axis_name,
                                                 scatter_dimension=0,
                                                 tiled=True) > 0)

            mfac = 3.0
            if proto.period > 1:
                on = (round_ % proto.period) == 0
                back = jax.lax.cond(on, reverse_delta,
                                    lambda _: jnp.zeros_like(pulled), None)
                pulled = jnp.where(on, pulled, jnp.uint32(0))
                n_req = jnp.where(on, n_req, 0.0)
            else:
                back = reverse_delta(None)
            pulled = pulled | back
        else:
            mfac = 2.0
        pulled = jnp.where(alive_l[:, None], pulled, jnp.uint32(0))
        msgs_new = msgs + jax.lax.psum(mfac * n_req, axis_name)
        if ch is not None:
            return (packed_l | pulled, msgs_new,
                    jax.lax.psum(lost, axis_name))
        return packed_l | pulled, msgs_new

    sh2 = P(axis_name, None)
    rep = P()
    in_specs = [sh2, rep, rep, rep]
    tables = ()
    if have_table:
        in_specs += [sh2, P(axis_name)]
        tables = (nbrs_pad, deg_pad)
    if ch is not None:
        in_specs += [rep] * NE.N_SCHED_OPERANDS
        tables = tables + NE.sched_args(NE.build(fault, n, n_pad))

    out_specs = (sh2, rep, rep) if ch is not None else (sh2, rep)
    mapped = shard_map(local_round, mesh=mesh, in_specs=tuple(in_specs),
                           out_specs=out_specs)

    def step_tabled(state: SimState, *tbl):
        out = mapped(state.seen, state.round, state.base_key,
                     state.msgs, *tbl)
        new = SimState(seen=out[0], round=state.round + 1,
                       base_key=state.base_key, msgs=out[1])
        # churn path returns (state, lost) — the models/si.py contract
        return (new, out[2]) if ch is not None else new

    return bind_tables(step_tabled, tables, tabled)


def init_sharded_packed_state(run: RunConfig, proto: ProtocolConfig,
                              topo: Topology, mesh: Mesh,
                              axis_name: str = "nodes") -> SimState:
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    st = init_packed_state(run, proto, topo.n)
    seen = _pad_rows(st.seen, n_pad, 0)
    seen = jax.device_put(seen, NamedSharding(mesh, P(axis_name, None)))
    return st._replace(seen=seen)


def sharded_checkpoint_ineligible_reason(proto: ProtocolConfig,
                                         exchange: str):
    """Why a multi-device run cannot use the checkpointed sharded driver,
    or None — the ONE list of preconditions, shared by the CLI and any
    future surface (the fused engine's `_fused_ineligible_reason`
    pattern: two callers can never drift apart)."""
    if exchange != "dense":
        return ("--checkpoint shards via the dense packed engine; "
                f"exchange={exchange!r} has no checkpointed driver")
    if proto.mode not in (C.PULL, C.ANTI_ENTROPY):
        return ("the sharded checkpointed driver runs the packed "
                f"pull/antientropy kernels (got mode {proto.mode!r})")
    return None


def restore_sharded_packed_state(state: SimState, mesh: Mesh,
                                 axis_name: str = "nodes") -> SimState:
    """Re-place a host-loaded checkpoint (utils/checkpoint.load_state)
    onto the mesh: the padded ``seen`` rows go back under the node-axis
    sharding, scalars stay replicated.  The loaded rows are already
    mesh-padded (save gathered the padded global array), so a resume on
    the SAME mesh shape is bitwise exact; a different device count would
    change the padding contract, which the CLI fingerprint refuses."""
    seen = jax.device_put(jnp.asarray(state.seen),
                          NamedSharding(mesh, P(axis_name, None)))
    return state._replace(seen=seen)


def checkpointed_packed_sharded(proto: ProtocolConfig, topo: Topology,
                                run: RunConfig, mesh: Mesh, path: str,
                                every: int = 50,
                                fault: Optional[FaultConfig] = None,
                                resume_state: Optional[SimState] = None,
                                want_curve: bool = False,
                                axis_name: str = "nodes",
                                curve_prefix=(), extra_meta=None,
                                lost_prefix: float = 0.0):
    """Fixed-budget sharded run in compiled segments with atomic npz
    checkpoints — the multi-device twin of the single-device
    ``--checkpoint`` driver (utils/checkpoint.run_with_checkpoints):
    long flagship runs survive preemption (the reference loses
    everything on process death, main.go:22-26) and, with
    ``want_curve``, record their convergence curve at the same time.

    Returns ``(final_state, coverage, curve-or-None)``; bitwise equal to
    an uninterrupted segmented run (tests/test_checkpoint_sharded.py).

    Churn schedules run in the segments exactly as in the straight
    sharded drivers (the step indexes its ABSOLUTE ``state.round``;
    resume == straight run bitwise — utils/checkpoint crash contract);
    the destroyed-message total persists across kills via
    ``track_lost``/``lost_prefix`` and the coverage denominator is the
    EVENTUAL alive set (ops/nemesis.eventual_alive_pad)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.checkpoint import run_with_checkpoints
    ch = NE.get(fault)
    step, tables = make_sharded_packed_round(proto, topo, mesh, fault,
                                             run.origin, axis_name,
                                             tabled=True)
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)

    def alive_now():
        # built IN-TRACE when called from curve_fn (no O(N) host
        # constant in the compile request — models/swim.py doc); under
        # churn the eventual set: the heal-convergence denominator
        if ch is not None:
            return NE.eventual_alive_pad(fault, topo.n, n_pad,
                                         run.origin)
        return sharded_alive(fault, topo.n, n_pad, run.origin)

    if resume_state is None:
        state = init_sharded_packed_state(run, proto, topo, mesh, axis_name)
    else:
        state = restore_sharded_packed_state(resume_state, mesh, axis_name)
    r = proto.rumors

    curve_fn = None
    if want_curve:
        def curve_fn(s):
            return coverage_packed(s.seen, r, alive_now())

    remaining = max(0, run.max_rounds - int(state.round))
    out = run_with_checkpoints(step, state, remaining, path, every=every,
                               step_args=tables, curve_fn=curve_fn,
                               curve_prefix=curve_prefix,
                               extra_meta=extra_meta,
                               track_lost=ch is not None,
                               lost_prefix=lost_prefix)
    final, curve = out if want_curve else (out, None)
    cov = float(coverage_packed(final.seen, r, alive_now()))
    return final, cov, curve


def _packed_recorder(proto: ProtocolConfig, n_pad: int, n_shards: int):
    """In-loop metrics row for the packed pull/anti-entropy kernels
    (ops/round_metrics; the dense-driver twin lives in
    parallel/sharded._dense_recorder).  Per-device egress: the packed
    all_gather moves ``nl*W*4`` uint32 bytes every round; anti-entropy's
    reverse psum_scatter contributes ``4*n_pad*R`` int32 bytes (the
    counts table is unpacked) on exchange rounds only."""
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.ops.bitpack import n_words
    r = proto.rumors
    nl = n_pad // n_shards
    base = 4.0 + 4.0 * nl * n_words(r)
    offered_per_msg = r * RM.payload_factor(proto.mode)

    def rec(m, prev_count, round0, msgs0, s1, alive_pad, nem=None):
        count = RM.count_packed(s1.seen, alive_pad)
        newly = count - prev_count
        msgs = s1.msgs - msgs0
        b = jnp.float32(base)
        if proto.mode == C.ANTI_ENTROPY:
            b = b + RM.gate_on_exchange_rounds(4.0 * n_pad * r,
                                               proto.period, round0)
        kw = ({} if nem is None
              else dict(alive=nem[0], cut_pairs=nem[1], dropped=nem[2]))
        return RM.record(
            m, newly=newly, msgs=msgs,
            dup=RM.dup_estimate(offered_per_msg * msgs, newly),
            bytes=b,
            front=RM.front_packed(s1.seen, alive_pad, n_shards),
            **kw), count

    return rec


def simulate_until_packed_sharded(proto: ProtocolConfig, topo: Topology,
                                  run: RunConfig, mesh: Mesh,
                                  fault: Optional[FaultConfig] = None,
                                  axis_name: str = "nodes", timing=None):
    """``timing``: optional compile/steady AOT-split dict
    (parallel/sharded.simulate_until_sharded contract).  With an active
    run ledger the loop carries a round-metrics buffer stack, flushed
    once by the chokepoint (ops/round_metrics)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.parallel.sharded import _churn_observables
    from gossip_tpu.utils.trace import maybe_aot_timed
    step, tables = make_sharded_packed_round(proto, topo, mesh, fault,
                                             run.origin, axis_name,
                                             tabled=True)
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    ch = NE.get(fault)
    alive_pad = (NE.eventual_alive_pad(fault, topo.n, n_pad, run.origin)
                 if ch is not None
                 else sharded_alive(fault, topo.n, n_pad, run.origin))
    init = init_sharded_packed_state(run, proto, topo, mesh, axis_name)
    target = jnp.float32(run.target_coverage)
    r = proto.rumors
    n_shards = mesh.shape[axis_name]
    rec = (_packed_recorder(proto, n_pad, n_shards)
           if RM.wanted() else None)
    obs = _churn_observables(fault, topo.n, n_pad, run.origin)

    @jax.jit
    def loop(state, *tbl):
        alive_t = (NE.eventual_alive_pad(fault, topo.n, n_pad,
                                         run.origin) if ch is not None
                   else sharded_alive(fault, topo.n, n_pad, run.origin))
        m0 = (RM.init(run.max_rounds, n_shards,
                      "simulate_until_packed_sharded",
                      nemesis=ch is not None) if rec else None)
        c0 = RM.count_packed(state.seen, alive_t) if rec else None
        def cond(carry):
            s, _, _ = carry
            return ((coverage_packed(s.seen, r, alive_t) < target)
                    & (s.round < run.max_rounds))
        def body(carry):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lost = step(s0, *tbl)
            else:
                s, lost = step(s0, *tbl), None
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, alive_t,
                             nem=(obs(round0, lost,
                                      NE.sched_of_tables(tbl))
                                  if obs else None))
            return s, m, cnt
        return jax.lax.while_loop(cond, body, (state, m0, c0))

    final, _, _ = maybe_aot_timed(loop, timing, init, *tables, label="packed")
    return (int(final.round),
            float(coverage_packed(final.seen, r, alive_pad)),
            float(final.msgs), final)
