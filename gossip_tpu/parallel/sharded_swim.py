"""SWIM failure detection sharded over the node mesh.

Twin of :func:`gossip_tpu.models.swim.make_swim_round` (kept semantically
identical — tests/test_swim.py asserts bitwise parity on an 8-device CPU
mesh).  The only structural difference is dissemination: the scatter-max of
wire rows becomes a per-shard scatter-max into an ``int32[n_pad, S]``
contribution table reduced with ``lax.pmax`` over the mesh axis — boolean OR
is not an XLA collective reduction but ``max`` is, and the monotone wire
encoding (models/swim.py module doc) makes max exactly the SWIM merge.

At the BASELINE.json SWIM scale (1M nodes, S=8 subjects) the pmax moves
``1M x 8 x 4 B = 32 MB`` per round over ICI — comfortably under the <1 s
budget; the probe arrays are O(N x K) locals.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossip_tpu.compat import shard_map
from gossip_tpu.config import FaultConfig, ProtocolConfig
from gossip_tpu.models import swim as SW
from gossip_tpu.models.state import bind_tables
from gossip_tpu.models.swim import DEAD_WIRE, SwimState, base_alive
from gossip_tpu.ops.sampling import sample_peers
from gossip_tpu.parallel.sharded import _pad_rows, pad_to_mesh
from gossip_tpu.topology.generators import Topology


def make_sharded_swim_round(
        proto: ProtocolConfig, n: int, mesh: Mesh,
        dead_nodes: Tuple[int, ...] = (), fail_round: int = 0,
        fault: Optional[FaultConfig] = None,
        topo: Optional[Topology] = None,
        axis_name: str = "nodes",
        tabled: bool = False,
        max_rounds=None):
    """Returns ``step: SwimState -> SwimState``; ``tabled=True`` returns
    ``(step, tables)`` with the padded topology arrays as step ARGUMENTS
    rather than closure constants — see models/swim.make_swim_round: at
    1M+ nodes a closed-over table inflates the XLA compile request with
    inline constants.  Liveness masks are built in-trace for the same
    reason."""
    s_count = proto.swim_subjects
    if s_count > n:
        raise ValueError(
            f"swim_subjects={s_count} exceeds cluster size n={n}; the "
            "subject window cannot be wider than the membership")
    proxies = proto.swim_proxies
    t_confirm = proto.swim_suspect_rounds
    fanout = proto.fanout
    rotate = proto.swim_rotate
    epoch_rounds = SW.resolve_epoch_rounds(proto, n)
    drop_prob = 0.0 if fault is None else fault.drop_prob
    from gossip_tpu.ops import nemesis as NE
    # events + drop-rate ramps supported (the schedule rides as traced
    # operands — models/swim.py twin); partitions stay rejected
    NE.check_supported(fault, engine="swim", partitions=False)
    ch = NE.get(fault)
    ramped = ch is not None and ch.ramp is not None
    n_pad = pad_to_mesh(n, mesh, axis_name)
    nl = n_pad // mesh.shape[axis_name]
    if topo is None:
        topo = Topology(nbrs=None, deg=None, n=n, family="complete")
    have_table = not topo.implicit
    if have_table:
        nbrs_pad = _pad_rows(topo.nbrs, n_pad, n)
        deg_pad = _pad_rows(topo.deg, n_pad, 0)

    def local_round(wire_l, timer_l, round_, base_key, msgs, *table):
        table, sched = NE.split_tables(ch, table)
        shard = jax.lax.axis_index(axis_name)
        gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        rkey = jax.random.fold_in(base_key, round_)
        # O(N) liveness buffers built in-trace (replicated compute, no big
        # inline constants in the compile request — models/swim doc)
        valid = jnp.arange(n_pad) < n             # padding rows: never alive
        alive_base_full = _pad_rows(base_alive(n, dead_nodes, fault),
                                    n_pad, False)
        alive_full = jnp.where(round_ >= fail_round, alive_base_full,
                               True) & valid
        dp = drop_prob
        if ch is not None:
            # scripted crash/recover churn from the schedule OPERANDS
            # (models/swim.py twin; ops/nemesis module doc)
            alive_full = alive_full & ~((sched.die <= round_)
                                        & (round_ < sched.rec))
            if ramped:
                dp = NE.drop_at(sched, round_)
        alive_l = alive_full[gids]
        subj_gids = SW.subject_window(round_, s_count, n, rotate,
                                      epoch_rounds)
        subj_alive = alive_full[subj_gids]
        if rotate:   # epoch boundary: fresh view state for the new window
            boundary = (round_ > 0) & (round_ % epoch_rounds == 0)
            wire_l = jnp.where(boundary, 0, wire_l)
            timer_l = jnp.where(boundary, 0, timer_l)
        wire0 = wire_l
        nbrs_l, deg_l = table if have_table else (None, None)

        # 1-2: probe + suspect (draws keyed by global id — bitwise == twin)
        if proto.swim_rng == "packed":
            (subj, d_drop, proxy_ids, to_p, p_to_s,
             diss_targets) = SW.packed_round_draws(
                rkey, gids, s_count, n, proxies, fanout, dp,
                nbrs=nbrs_l, deg=deg_l, sentinel=n, force=ramped)
        else:
            subj, d_drop, proxy_ids, to_p, p_to_s = SW.probe_draws(
                rkey, gids, s_count, n, proxies, dp, force=ramped)
            diss_targets = None
        direct_ok = subj_alive[subj] & ~d_drop
        proxy_ok = (alive_full[proxy_ids] & ~to_p & ~p_to_s
                    & subj_alive[subj][:, None])
        indirect_ok = jnp.any(proxy_ok, axis=1)
        fail = alive_l & ~direct_ok & ~indirect_ok
        onehot = jax.nn.one_hot(subj, s_count, dtype=jnp.bool_)
        suspectable = (wire0 < DEAD_WIRE) & onehot & fail[:, None]
        wire1 = jnp.where(suspectable, wire0 | 1, wire0)
        msgs_local = (jnp.sum(alive_l & direct_ok) * 2.0
                      + jnp.sum(alive_l & ~direct_ok)
                      * (1.0 + 4.0 * proxies))

        # 3: dissemination — local scatter-max, pmax over the mesh ---------
        if diss_targets is None:
            dkey = jax.random.fold_in(rkey, SW._DISS_TAG)
            targets = sample_peers(dkey, gids, topo, fanout,
                                   exclude_self=True,
                                   local_nbrs=nbrs_l, local_deg=deg_l)
        else:
            targets = diss_targets
        msgs_local = msgs_local + jnp.sum(
            (targets < n) & alive_l[:, None]).astype(jnp.float32)
        # silent senders (dead/padding) -> n_pad so the scatter drops them
        # (sentinel n would land on a padding row when n < n_pad)
        targets = jnp.where(alive_l[:, None], targets, n_pad)
        contrib = SW.disseminate_max(targets, wire1, n_pad, proto.swim_diss,
                                     max_rounds)
        recv_full = jax.lax.pmax(contrib, axis_name)
        recv_l = jax.lax.dynamic_slice_in_dim(recv_full, shard * nl, nl, 0)
        wire2 = jnp.maximum(wire1, recv_l)

        # 4: refutation (only rows whose gid is an alive subject) ----------
        sel = (gids[:, None] == subj_gids[None, :]) & alive_l[:, None]
        odd = (wire2 % 2 == 1) & (wire2 < DEAD_WIRE)
        wire3 = jnp.where(sel & odd, (wire2 // 2 + 1) * 2, wire2)

        # 5: timers + confirm ---------------------------------------------
        is_susp = (wire3 % 2 == 1) & (wire3 < DEAD_WIRE)
        held = is_susp & (wire3 == wire_l)
        timer = jnp.where(held, timer_l + 1, jnp.where(is_susp, 1, 0))
        confirm = timer >= t_confirm
        wire4 = jnp.where(confirm, DEAD_WIRE, wire3)
        timer = jnp.where(confirm, 0, timer)

        wire_f = jnp.where(alive_l[:, None], wire4, wire0)
        timer_f = jnp.where(alive_l[:, None], timer, timer_l)
        msgs_new = msgs + jax.lax.psum(msgs_local, axis_name)
        return wire_f, timer_f, msgs_new

    sh2 = P(axis_name, None)
    rep = P()
    in_specs = [sh2, sh2, rep, rep, rep]
    tables = (nbrs_pad, deg_pad) if have_table else ()
    if have_table:
        in_specs += [sh2, P(axis_name)]
    if ch is not None:
        in_specs += [rep] * NE.N_SCHED_OPERANDS
        tables = tables + NE.sched_args(NE.build(fault, n, n_pad))

    mapped = shard_map(local_round, mesh=mesh, in_specs=tuple(in_specs),
                           out_specs=(sh2, sh2, rep))

    def step_tabled(state: SwimState, *tbl) -> SwimState:
        wire, timer, msgs = mapped(state.wire, state.timer, state.round,
                                   state.base_key, state.msgs, *tbl)
        return SwimState(wire=wire, timer=timer, round=state.round + 1,
                         base_key=state.base_key, msgs=msgs)

    return bind_tables(step_tabled, tables, tabled)


def init_sharded_swim_state(n: int, proto: ProtocolConfig, mesh: Mesh,
                            seed: int = 0,
                            axis_name: str = "nodes") -> SwimState:
    n_pad = pad_to_mesh(n, mesh, axis_name)
    st = SW.init_swim_state(n_pad, proto.swim_subjects, seed)
    sharding = NamedSharding(mesh, P(axis_name, None))
    return SwimState(wire=jax.device_put(st.wire, sharding),
                     timer=jax.device_put(st.timer, sharding),
                     round=st.round, base_key=st.base_key, msgs=st.msgs)


def restore_sharded_swim_state(state: SwimState, mesh: Mesh,
                               axis_name: str = "nodes") -> SwimState:
    """Re-place a host-loaded checkpoint (utils/checkpoint.load_state
    gathers to host) back onto the mesh.  The checkpoint already carries
    the padded rows — the config fingerprint pins the mesh shape, so the
    row count matches by construction."""
    sharding = NamedSharding(mesh, P(axis_name, None))
    return SwimState(wire=jax.device_put(jnp.asarray(state.wire), sharding),
                     timer=jax.device_put(jnp.asarray(state.timer),
                                          sharding),
                     round=state.round, base_key=state.base_key,
                     msgs=state.msgs)
