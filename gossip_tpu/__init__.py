"""gossip_tpu — a TPU-native gossip / epidemic-broadcast simulation framework.

Re-imagines the capabilities of the reference Go program
(``0xSherlokMo/gossip-protocol``, a Maelstrom "Gossip Glomers" broadcast node,
``/root/reference/main.go``) as a batched, round-synchronous simulator built
on JAX / XLA / shard_map for TPU device meshes.

The reference is *event-driven*: one OS process per cluster node, a goroutine
per message, blocking RPC fan-out with retries (main.go:65-89).  The TPU-native
design inverts this: the whole cluster is a handful of ``[N]``-shaped arrays,
one gossip round is one jitted function (sample targets -> scatter/gather ->
threshold -> update), and a simulation is ``lax.scan`` / ``lax.while_loop``
over rounds.  The node dimension is sharded over the device mesh with
``shard_map``; coverage counters ride ``psum`` over ICI.

Layout:
  - :mod:`gossip_tpu.topology`  — graph families as static padded neighbor tables
  - :mod:`gossip_tpu.ops`      — sampling + propagation kernels (the hot path)
  - :mod:`gossip_tpu.models`   — protocol semantics (SI push/pull, anti-entropy,
    SWIM failure detection, multi-rumor)
  - :mod:`gossip_tpu.parallel` — mesh + shard_map node-dim sharding
  - :mod:`gossip_tpu.runtime`  — simulators (round-batched JAX backend and the
    Go-semantics event-driven parity backend), Maelstrom protocol runtime
  - :mod:`gossip_tpu.utils`    — metrics, checkpointing, tracing
"""

__version__ = "0.5.0"

from gossip_tpu.config import (  # noqa: F401
    FaultConfig,
    MeshConfig,
    ProtocolConfig,
    RunConfig,
    TopologyConfig,
)
