"""Version shims for the two jax API seams this codebase straddles.

The sharded engines target the modern spellings (``jax.shard_map`` with
``check_vma``; ``pltpu.CompilerParams`` / ``pltpu.InterpretParams``),
but the pinned toolchain on some build hosts carries jax 0.4.x, where
shard_map still lives in ``jax.experimental.shard_map`` (``check_rep``)
and the Pallas params classes have their old names.  Every call site
goes through this module so the version split lives in exactly one
place and each engine file stays written against one API.

The 0.4.x Mosaic interpreter also has NO CPU lowering for the TPU
hardware-PRNG primitives (``prng_seed`` raises NotImplementedError;
newer versions stub the draw with zeros).  That asymmetry is why the
fused kernels' default ``interpret=True`` path is the pure-JAX
reference lowering in ops/pallas_round.py — the Mosaic interpreter is
reachable via ``interpret="mosaic"`` only for injected-bit tests,
which never touch the PRNG primitives.
"""

from __future__ import annotations

import jax


def legacy_jax() -> bool:
    """True on the 0.4.x fallback toolchain (the ``jax.shard_map``
    probe is the same seam every shim below keys off).  Version-gated
    *behaviors* — not just spellings — route through this: jax.random's
    partner-draw streams differ between the two lines, so statistical
    tests tuned on one stream may need a wider margin on the other
    (tests/test_sharded_sparse.py)."""
    return not hasattr(jax, "shard_map")


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on modern jax; the ``jax.experimental`` spelling
    (``check_rep`` kwarg) on 0.4.x.  Semantics are identical for the
    programs here — the kwarg was renamed, not redefined."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def interpret_impl(interpret):
    """Normalize the ``interpret`` argument of the Pallas entry points.

    ``False`` -> None (compiled TPU lowering).  ``True``/'reference' ->
    ``'reference'``: the pure-JAX lowering of the kernel math, with the
    hardware PRNG reproduced as the Mosaic interpreter defines it
    off-TPU (all-zero draws) — compiled by XLA, so interpret-mode driver
    runs execute as ordinary jitted programs instead of paying a Python
    interpreter callback per pallas_call.  ``'mosaic'`` -> the real
    Mosaic interpreter (kernel-body tests); on jax 0.4.x it cannot
    reach the TPU PRNG primitives on CPU (module doc)."""
    if not interpret:
        return None
    if interpret is True or interpret == "reference":
        return "reference"
    if interpret == "mosaic":
        return "mosaic"
    raise ValueError(f"interpret must be a bool, 'reference' or 'mosaic'; "
                     f"got {interpret!r}")


def axis_size(axis_name):
    """``jax.lax.axis_size`` on modern jax; the classic ``psum(1, axis)``
    idiom (statically folded inside shard_map) on 0.4.x."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pvary(x, axes):
    """Mark a value as varying over manual mesh ``axes`` — the
    ``jax.lax.pcast(..., to="varying")`` VMA cast of modern shard_map.
    0.4.x has no VMA type system, so there the cast is an identity (cond
    branch outputs already unify)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x


def pallas_interpret_mode(on) -> object:
    """The ``interpret=`` argument for a ``pallas_call``: the structured
    ``InterpretParams`` where it exists, the legacy bool otherwise."""
    if not on:
        return False
    from jax.experimental.pallas import tpu as pltpu
    if hasattr(pltpu, "InterpretParams"):
        return pltpu.InterpretParams()
    return True


def pallas_compiler_params(*, vmem_limit_bytes: int):
    """Mosaic compiler params under whichever class name this jax has."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(vmem_limit_bytes=vmem_limit_bytes)
