"""Version shims for the two jax API seams this codebase straddles.

The sharded engines target the modern spellings (``jax.shard_map`` with
``check_vma``; ``pltpu.CompilerParams`` / ``pltpu.InterpretParams``),
but the pinned toolchain on some build hosts carries jax 0.4.x, where
shard_map still lives in ``jax.experimental.shard_map`` (``check_rep``)
and the Pallas params classes have their old names.  Every call site
goes through this module so the version split lives in exactly one
place and each engine file stays written against one API.

The 0.4.x Mosaic interpreter also has NO CPU lowering for the TPU
hardware-PRNG primitives (``prng_seed`` raises NotImplementedError;
newer versions stub the draw with zeros).  That asymmetry is why the
fused kernels' default ``interpret=True`` path is the pure-JAX
reference lowering in ops/pallas_round.py — the Mosaic interpreter is
reachable via ``interpret="mosaic"`` only for injected-bit tests,
which never touch the PRNG primitives.
"""

from __future__ import annotations

import jax


def legacy_jax() -> bool:
    """True on the 0.4.x fallback toolchain (the ``jax.shard_map``
    probe is the same seam every shim below keys off).  Version-gated
    *behaviors* — not just spellings — route through this: jax.random's
    partner-draw streams differ between the two lines, so statistical
    tests tuned on one stream may need a wider margin on the other
    (tests/test_sharded_sparse.py)."""
    return not hasattr(jax, "shard_map")


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on modern jax; the ``jax.experimental`` spelling
    (``check_rep`` kwarg) on 0.4.x.  Semantics are identical for the
    programs here — the kwarg was renamed, not redefined."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def interpret_impl(interpret):
    """Normalize the ``interpret`` argument of the Pallas entry points.

    ``False`` -> None (compiled TPU lowering).  ``True``/'reference' ->
    ``'reference'``: the pure-JAX lowering of the kernel math, with the
    hardware PRNG reproduced as the Mosaic interpreter defines it
    off-TPU (all-zero draws) — compiled by XLA, so interpret-mode driver
    runs execute as ordinary jitted programs instead of paying a Python
    interpreter callback per pallas_call.  ``'mosaic'`` -> the real
    Mosaic interpreter (kernel-body tests); on jax 0.4.x it cannot
    reach the TPU PRNG primitives on CPU (module doc)."""
    if not interpret:
        return None
    if interpret is True or interpret == "reference":
        return "reference"
    if interpret == "mosaic":
        return "mosaic"
    raise ValueError(f"interpret must be a bool, 'reference' or 'mosaic'; "
                     f"got {interpret!r}")


def axis_size(axis_name):
    """``jax.lax.axis_size`` on modern jax; the classic ``psum(1, axis)``
    idiom (statically folded inside shard_map) on 0.4.x."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pvary(x, axes):
    """Mark a value as varying over manual mesh ``axes`` — the
    ``jax.lax.pcast(..., to="varying")`` VMA cast of modern shard_map.
    0.4.x has no VMA type system, so there the cast is an identity (cond
    branch outputs already unify)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x


def pallas_interpret_mode(on) -> object:
    """The ``interpret=`` argument for a ``pallas_call``: the structured
    ``InterpretParams`` where it exists, the legacy bool otherwise."""
    if not on:
        return False
    from jax.experimental.pallas import tpu as pltpu
    if hasattr(pltpu, "InterpretParams"):
        return pltpu.InterpretParams()
    return True


def pallas_compiler_params(*, vmem_limit_bytes: int):
    """Mosaic compiler params under whichever class name this jax has."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(vmem_limit_bytes=vmem_limit_bytes)


# -- profiler probes (utils/trace, GOSSIP_PROFILE) --------------------
#
# The profiler API is stable on both lines this repo straddles, but the
# GOSSIP_PROFILE hooks must DEGRADE, never crash, on a jax that lacks a
# piece (a trimmed build, a future rename): a profiling run that can't
# profile should still produce its numbers.


def profiler_trace_fns():
    """(start_trace, stop_trace) for a jax.profiler capture, or None
    when this jax has no trace API — the GOSSIP_PROFILE wrapper then
    runs the block unprofiled (probed like the cache knobs below,
    never assumed)."""
    prof = getattr(jax, "profiler", None)
    start = getattr(prof, "start_trace", None)
    stop = getattr(prof, "stop_trace", None)
    return (start, stop) if callable(start) and callable(stop) else None


def trace_annotation(name: str):
    """A named ``jax.profiler.TraceAnnotation`` region (host + device
    timeline), or a no-op context manager when this jax lacks the
    class — callers annotate unconditionally and degrade cleanly."""
    prof = getattr(jax, "profiler", None)
    cls = getattr(prof, "TraceAnnotation", None)
    if cls is None:
        import contextlib
        return contextlib.nullcontext()
    return cls(name)


# -- persistent-compilation-cache probes (utils/compile_cache) --------
#
# The cache knobs moved and grew across jax lines (the enable-xla-caches
# flag does not exist everywhere; CPU-backend caching itself was once
# gated).  utils/compile_cache PROBES through these helpers instead of
# assuming, so the compile-once layer degrades to "no cache" cleanly on
# a toolchain that lacks a knob rather than crashing at import or — the
# worse failure — silently recording warm walls as cold ones.

PERSISTENT_CACHE_KNOBS = (
    "jax_compilation_cache_dir",
    "jax_enable_compilation_cache",
    "jax_persistent_cache_min_compile_time_secs",
    "jax_persistent_cache_min_entry_size_bytes",
    "jax_persistent_cache_enable_xla_caches",
)


def persistent_cache_knobs() -> dict:
    """{knob_name: present_on_this_jax} for every cache knob the
    compile-once layer may touch.  On 0.4.37 (this container) all five
    exist; the consumer must tolerate any subset."""
    return {k: hasattr(jax.config, k) for k in PERSISTENT_CACHE_KNOBS}


def set_cache_knob(name: str, value) -> bool:
    """``jax.config.update`` that reports instead of raising when the
    knob does not exist on this jax line (False = not set)."""
    if not hasattr(jax.config, name):
        return False
    jax.config.update(name, value)
    return True


def serialize_executable_fns():
    """(serialize, deserialize_and_load) for the AOT executable store,
    or None when this jax cannot round-trip compiled executables — the
    store then reports every lookup as ``disabled`` and drivers compile
    normally."""
    try:
        from jax.experimental.serialize_executable import (
            deserialize_and_load, serialize)
    except ImportError:
        return None
    return serialize, deserialize_and_load
