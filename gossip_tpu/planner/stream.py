"""Execute a ScalePlan: stream word-plane tiles through the packed
pull engine.

Why tiling along the WORD-PLANE axis is exact: a packed PULL round's
partner draws, drop coins, liveness rows and partition cuts are all
functions of ``(base_key, round, node id)`` — never of plane CONTENT
(models/si_packed.make_packed_round; the same fact behind the fused
engine's zero-ICI plane sharding).  Gather-then-OR commutes with
column slicing, so a tile of Wt < W word planes runs the IDENTICAL
trajectory on its own columns, and the concatenation of T streamed
tiles is BITWISE the untiled in-memory run — the gate
:func:`untiled_reference` + ``check_bitwise`` asserts, and
tests/test_planner.py pins under a mixed fault program.

Execution contract (the PR 6/9 operand discipline):

* ONE compiled loop per tile-shape bucket: every tile pads its words
  to the plan's pow2 ``bucket_words`` (padded planes are zero words —
  inert under the OR-merge), all tiles share one step closure
  (``_tile_step``, memoized with the schedule content STRIPPED from
  the key), and the segment runner is utils/checkpoint's — so K tiles
  compile once and a salted re-entry compiles zero
  (``assert_compiles``-pinned).
* Tile content is operands: the tile words ride ``device_put``, the
  nemesis schedule rides the step's table tail.
* THREE-STAGE PIPELINE: the segment loop dispatches tile *k*'s compute
  and only THEN drains tile *k−1*'s result — so while *k−1* computes,
  *k*'s ``device_put`` transfer is in flight (stage 1), and while *k*
  computes, *k−1*'s D2H fetch (``copy_to_host_async`` + the host
  write-back) proceeds (stage 3).  Steady-state segment wall ≈
  max(compute, transfer), not their sum.  EVERY blocking fetch
  (``block_until_ready`` / ``np.asarray`` / scalar conversion) lives
  in the ``_drain`` helper — the one sanctioned site the staticcheck
  ``blocking-fetch-in-segment-loop`` rule exempts; a synchronous fetch
  anywhere else in the segment loop defeats the pipeline and flags.
  Per-tile transfer-in / compute / fetch-out walls ride ``tile_stream``
  ledger events (sync=False — no fsync in the timed window) and roll
  into the run-level ``overlap_efficiency``: the fraction of segment
  wall the host did NOT spend stalled on the device.  ``overlap=False``
  (CLI ``--no-overlap``) drains each tile immediately — the serial A/B
  leg the committed record compares against, bitwise-identical by
  construction (drain order per tile is unchanged, only its overlap
  with the next dispatch is).
* MULTI-SLICE FAN-OUT: a ``dcn_slices`` > 1 plan executes the SAME
  tile stream across the :func:`parallel.multislice.make_hybrid_mesh`
  hybrid mesh — each mesh row (one DCN slice, node axis on ICI) gets
  every ``tiles``-th tile round-robin, with one in-flight drain slot
  per slice.  Tiles are independent trajectories, so ZERO bytes cross
  DCN; the per-segment tile-0 accounting assertion is enforced per
  slice (the message names the slice); and all slices drain into the
  ONE crash-safe host cursor before each checkpoint publish, so the
  resume contract is byte-identical to the single-slice run.
* Crash safety reuses the checkpoint cursor discipline: the full
  packed state lives on the HOST between segments, every published
  checkpoint carries the absolute round cursor + exact ``dropped``
  carry + the plan AND fault-program fingerprints, and ``--resume``
  refuses a mismatch loudly (utils/checkpoint crash contract; resume
  == straight streamed run bitwise, test-pinned).

Scope refusals (loud, never silent): engine != packed, mode != pull,
more DCN slices than the platform reports (multislice.
_hybrid_device_grid refuses), explicit topologies (a 100M-row
neighbor table is its own budget item the streamed drivers do not yet
carry).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig
from gossip_tpu.planner.budget import (ScalePlan, crosscheck_peak,
                                       plan_fingerprint, WORD_BITS,
                                       WORD_BYTES)


@dataclasses.dataclass
class ScaleRunResult:
    """What a streamed run reports (CLI/tools print it as JSON)."""

    n: int
    rounds: int
    coverage: float
    msgs: float
    dropped: float
    tiles: int
    bucket_words: int
    segments_run: int
    resumed: bool
    halted: bool                       # stopped by halt_after_segments
    bitwise_equal: Optional[bool]      # vs untiled_reference, if checked
    measured_loop_bytes: Optional[int]
    predicted_peak_device_bytes: int
    dcn_slices: int                    # tile fan-out width (1 = serial)
    overlap: bool                      # three-stage pipeline engaged?
    # 1 - (host stall wall / segment wall), clamped to [0, 1]; None
    # when no segment ran (module doc "THREE-STAGE PIPELINE")
    overlap_efficiency: Optional[float]
    final_state: Optional[np.ndarray]  # uint32[n, W] when keep_state

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("final_state")
        return d


def host_init_packed(n: int, rumors: int, origin: int) -> np.ndarray:
    """uint32[n, W] initial packed state in NUMPY — bitwise the jax
    ``pack(init_state(...).seen)`` (rumor r starts at node
    ``(origin + r) % n``, models/state.init_state; pinned equal in
    tests/test_planner.py) without ever allocating the bool[N, R]
    table the jax path goes through: at 100M nodes the device-side
    init IS the budget item streaming exists to avoid."""
    w = (rumors + WORD_BITS - 1) // WORD_BITS
    out = np.zeros((n, w), np.uint32)
    r = np.arange(rumors)
    rows = (origin + r) % n
    bits = np.left_shift(np.uint32(1),
                         (r % WORD_BITS).astype(np.uint32),
                         dtype=np.uint32)
    np.bitwise_or.at(out, (rows, r // WORD_BITS), bits)
    return out


# step closures memoized with schedule CONTENT stripped from the key
# (the parallel/sharded._cached_dense_loop discipline): two fault
# programs sharing (static fault, canonical horizon bucket) get ONE
# step object, so the jitted segment runner's cache serves both and a
# salted scenario re-entry compiles zero.  BOUNDED FIFO: the keys are
# tuples, so the utils/checkpoint weak-key trick cannot apply — an
# unbounded strong dict would pin every step closure (and, through
# checkpoint._segment_runners' weak keys, its jitted executables)
# forever in a long-lived process.  Evicting the oldest entry lets
# the weak runner cache drop with it; a scale run uses ONE entry, so
# 16 covers any realistic session with zero re-trace churn.
_STEP_CACHE: "dict" = {}
_STEP_CACHE_MAX = 16


def _tile_step(proto: ProtocolConfig, n: int,
               fault: Optional[FaultConfig], origin: int, mesh):
    """(step, schedule tables) for streaming tiles of any word width.
    The packed PULL step never bakes the word count (its trace is
    width-polymorphic — jit specializes per tile-shape bucket), and
    bakes no schedule content (tables are operands)."""
    import jax.numpy as jnp  # noqa: F401  (jax import deferred)
    from gossip_tpu.models.si_packed import make_packed_round
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.topology import generators as G

    ch = NE.get(fault)
    fault_static = (None if fault is None
                    else dataclasses.replace(fault, churn=None))
    t_pad = None if ch is None else NE.canonical_horizon(ch)
    key = (proto, n, fault_static, t_pad, origin, mesh)
    step = _STEP_CACHE.get(key)
    topo = G.complete(n)
    if step is None:
        if mesh is None:
            step, _ = make_packed_round(proto, topo, fault, origin,
                                        tabled=True)
        else:
            from gossip_tpu.parallel.sharded_packed import (
                make_sharded_packed_round)
            step, _ = make_sharded_packed_round(proto, topo, mesh,
                                                fault, origin,
                                                tabled=True)
        while len(_STEP_CACHE) >= _STEP_CACHE_MAX:
            _STEP_CACHE.pop(next(iter(_STEP_CACHE)))
        _STEP_CACHE[key] = step
    tables = ()
    if ch is not None:
        n_pad = n
        if mesh is not None:
            from gossip_tpu.parallel.sharded import pad_to_mesh
            n_pad = pad_to_mesh(n, mesh, "nodes")
        tables = NE.sched_args(NE.build(fault, n, n_pad, t_pad=t_pad))
    return step, tables


def _refuse(plan: ScalePlan) -> None:
    if plan.engine != "packed":
        raise ValueError(
            f"run_at_scale streams the packed engine only; plan says "
            f"engine={plan.engine!r} (the budget model covers it, the "
            "streamed executor does not — docs/SCALING.md scope)")
    if plan.mode != C.PULL:
        raise ValueError(
            f"run_at_scale streams PULL rounds only, got mode="
            f"{plan.mode!r} (anti-entropy's reverse delta writes "
            "cross-tile state — planner/budget.plan_scale already "
            "refuses this at plan time)")


def _mesh_for(plan: ScalePlan):
    if plan.per_slice == 1:
        return None
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(plan.per_slice, axis_name="nodes")


@dataclasses.dataclass
class _SliceCtx:
    """One DCN slice's execution context: its node mesh (or pinned
    single device), its step closure and segment runner.  Tiles fan
    out round-robin ``t % dcn_slices`` — each slice streams an
    independent sub-sequence of tiles, zero DCN bytes by construction
    (module doc "MULTI-SLICE FAN-OUT")."""

    index: int
    mesh: object       # 1-D node Mesh, or None when per_slice == 1
    device: object     # pinned jax.Device when mesh is None (else None)
    step: object
    tables: tuple
    runner: object


def _slice_contexts(plan: ScalePlan, proto: ProtocolConfig,
                    track: bool, mesh) -> list:
    """Build the per-slice execution contexts.

    Single slice: the historical path — one context on the default
    device (or the caller's node mesh).  Multi slice: rows of the
    hybrid device grid (parallel/multislice) become per-slice node
    meshes (ICI inner axis) or pinned single devices, so jit
    specializes one executable per bucket PER SLICE and dispatches
    overlap across slices.  A caller-supplied ``mesh`` on a multislice
    plan must be the (dcn_slices, per_slice) hybrid mesh itself."""
    from gossip_tpu.utils.checkpoint import _segment_runner

    def ctx(i, m, dev):
        step, tables = _tile_step(proto, plan.n, plan.fault,
                                  plan.origin, m)
        return _SliceCtx(index=i, mesh=m, device=dev, step=step,
                         tables=tables, runner=_segment_runner(step,
                                                               track))

    if plan.dcn_slices <= 1:
        m = _mesh_for(plan) if mesh is None else mesh
        return [ctx(0, m, None)]

    from jax.sharding import Mesh
    if mesh is None:
        from gossip_tpu.parallel.multislice import make_hybrid_mesh
        mesh = make_hybrid_mesh(plan.dcn_slices, plan.per_slice)
    grid = np.asarray(mesh.devices)
    if grid.shape != (plan.dcn_slices, plan.per_slice):
        raise ValueError(
            f"plan wants a {plan.dcn_slices}x{plan.per_slice} hybrid "
            f"mesh; the supplied mesh has device grid {grid.shape} — "
            "build it with multislice.make_hybrid_mesh")
    out = []
    for s in range(plan.dcn_slices):
        if plan.per_slice == 1:
            out.append(ctx(s, None, grid[s, 0]))
        else:
            out.append(ctx(s, Mesh(grid[s], ("nodes",)), None))
    return out


def _measure_loop_bytes(runner, *args) -> Optional[int]:
    """Peak bytes of the compiled tile loop (argument + output + temp)
    — the 'measured allocation' the committed record holds the
    prediction against.  Acquired through the ONE attributed
    chokepoint (utils/compile_cache.load_or_compile), so the measuring
    compile emits its own ``xla_compile`` event like every other
    executable in the tree — this used to be the lone raw
    ``.lower().compile()`` in driver scope, the live true positive the
    ``unattributed-compile`` rule now guards against.  None when the
    backend cannot report memory analysis."""
    from gossip_tpu.utils import compile_cache as CC
    try:
        compiled, _ = CC.load_or_compile(runner, *args,
                                         label="scale_stream")
        return CC.xla_attribution(compiled)["peak_bytes"]
    except Exception:
        return None


def host_coverage(state: np.ndarray, rumors: int,
                  alive: Optional[np.ndarray] = None,
                  chunk: int = 1 << 20) -> float:
    """Min-over-rumors coverage of a host packed state — the numpy
    twin of ops/bitpack.coverage_packed (integer counts, ONE division
    at the end: the device-division-lottery discipline), chunked so a
    100M-row table never materializes its bool expansion."""
    n, w = state.shape
    counts = np.zeros(w * WORD_BITS, np.int64)
    denom = 0
    # the 32x bit expansion below transiently allocates rows*w*32
    # uint32s — bound it by WORDS processed, not rows, or a wide
    # state's "chunk" is the whole table (a ~GiB spike at the
    # committed-record shape)
    chunk = max(1, chunk // max(w, 1))
    for lo in range(0, n, chunk):
        rows = state[lo:lo + chunk]
        if alive is not None:
            m = alive[lo:lo + chunk]
            rows = rows[m]
            denom += int(m.sum())
        else:
            denom += rows.shape[0]
        bits = (rows[:, :, None] >> np.arange(WORD_BITS,
                                              dtype=np.uint32)) & 1
        counts += bits.reshape(rows.shape[0], -1).sum(0, dtype=np.int64)
    if denom == 0:
        return 0.0
    return float(counts[:rumors].min() / denom)


def untiled_reference(plan: ScalePlan, mesh=None, device=None):
    """The in-memory run at full word width W — ONE runner call over
    the plan's whole round budget through the SAME step factory and
    segment runner the tiles use.  Returns (uint32[n, W], msgs,
    dropped).  This is what the streamed trajectory must equal
    BITWISE.  A multislice run passes slice 0's (mesh, device) — word
    -plane trajectories are device-placement invariant, so any one
    slice's context is the reference."""
    import jax
    import jax.numpy as jnp
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.checkpoint import _segment_runner

    _refuse(plan)
    proto = ProtocolConfig(mode=plan.mode, fanout=plan.fanout,
                           rumors=plan.rumors)
    if mesh is None and device is None:
        mesh = _mesh_for(plan)
    step, tables = _tile_step(proto, plan.n, plan.fault, plan.origin,
                              mesh)
    track = NE.get(plan.fault) is not None
    runner = _segment_runner(step, track)
    seen = host_init_packed(plan.n, plan.rumors, plan.origin)
    st = _place_tile(seen, plan.n, mesh, 0, plan.seed, 0.0,
                     device=device)
    if track:
        (out, acc) = runner(st, plan.max_rounds, jnp.float32(0.0),
                            *tables)
        dropped = float(acc)
    else:
        out = runner(st, plan.max_rounds, *tables)
        dropped = 0.0
    final = np.asarray(out.seen)[:plan.n]
    return final, float(out.msgs), dropped


def _place_tile(words: np.ndarray, n: int, mesh, round_: int,
                seed: int, msgs: float, device=None):
    """Pad a host word tile to the mesh row count, ship it, and wrap
    the SimState the packed step expects.  The device_put is the
    double-buffer leg: issued eagerly, it overlaps the previous tile's
    compute under async dispatch.  ``device`` pins a meshless tile to
    one slice's device (multislice fan-out)."""
    import jax
    import jax.numpy as jnp
    from gossip_tpu.models.state import SimState

    if mesh is None:
        dev = (jax.device_put(words) if device is None
               else jax.device_put(words, device))
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from gossip_tpu.parallel.sharded import pad_to_mesh
        n_pad = pad_to_mesh(n, mesh, "nodes")
        if n_pad != words.shape[0]:
            words = np.concatenate(
                [words, np.zeros((n_pad - n, words.shape[1]),
                                 words.dtype)], axis=0)
        dev = jax.device_put(words,
                             NamedSharding(mesh, P("nodes", None)))
    return SimState(seen=dev, round=jnp.int32(round_),
                    base_key=jax.random.key(seed),
                    msgs=jnp.float32(msgs))


def run_at_scale(plan: ScalePlan, *, checkpoint_path: Optional[str] = None,
                 resume: bool = False, check_bitwise: bool = False,
                 measure_memory: bool = False, keep_state: bool = False,
                 halt_after_segments: Optional[int] = None,
                 overlap: bool = True, mesh=None) -> ScaleRunResult:
    """Drive a ScalePlan: T word-plane tiles stream host<->device
    through each checkpoint segment as a three-stage pipeline, fanned
    across DCN slices when the plan is multislice (module doc has both
    contracts).

    ``halt_after_segments`` stops after that many segments WITH the
    checkpoint published — the deterministic stand-in for a SIGKILL
    between segments (tests and the capture tool resume from it and
    must land bitwise on the uninterrupted run).  ``check_bitwise``
    additionally runs :func:`untiled_reference` and compares the final
    states byte-for-byte.  ``measure_memory`` AOT-compiles the tile
    loop once more for its memory analysis — leave it off in compile-
    count-pinned paths.  ``overlap=False`` drains every tile
    immediately after dispatch (the serial A/B leg, CLI
    ``--no-overlap``) — trajectories are identical either way, only
    the fetch's overlap with the next dispatch changes."""
    import jax.numpy as jnp
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils import telemetry
    from gossip_tpu.utils.checkpoint import (load_meta, load_state,
                                             save_state)

    _refuse(plan)
    if resume and not checkpoint_path:
        raise ValueError("resume=True needs checkpoint_path")
    n, w_total = plan.n, plan.total_words
    bucket = plan.bucket_words
    tiles = plan.tiles
    n_slices = max(1, plan.dcn_slices)
    plan_doc = plan.to_dict()
    plan_fp = plan_fingerprint(plan_doc)
    fault_fp = NE.schedule_fingerprint(plan.fault, n, plan.origin)
    proto = ProtocolConfig(mode=plan.mode, fanout=plan.fanout,
                           rumors=plan.rumors)
    track = NE.get(plan.fault) is not None
    ctxs = _slice_contexts(plan, proto, track, mesh)

    base_round, dropped, msgs = 0, 0.0, 0.0
    resumed = False
    if resume:
        meta = load_meta(checkpoint_path)
        extra = meta.get("extra") or {}
        if extra.get("scale_plan") != plan_fp:
            raise ValueError(
                f"checkpoint {checkpoint_path} was written under a "
                f"different scale plan (fingerprint "
                f"{extra.get('scale_plan')!r} != {plan_fp!r}) — "
                "resuming a re-tiled run would make its budget claims "
                "unattributable; regenerate or drop --resume")
        if extra.get("fault_program") != fault_fp:
            raise ValueError(
                f"checkpoint {checkpoint_path} carries fault program "
                f"{extra.get('fault_program')!r}; this plan builds "
                f"{fault_fp!r} — a resumed fault program must be the "
                "one the checkpoint ran (utils/checkpoint crash "
                "contract)")
        st = load_state(checkpoint_path)
        # copy: np.asarray over a jax buffer is a read-only view, and
        # the tile write-back mutates host in place
        host = np.array(st.seen, np.uint32)
        base_round = int(extra["round"])
        dropped = float(extra.get("dropped", 0.0))
        msgs = float(st.msgs)
        resumed = True
    else:
        host = host_init_packed(n, plan.rumors, plan.origin)

    def tile_cols(t):
        lo = t * bucket
        return lo, min(lo + bucket, w_total)

    def prep(t, round_, ctx):
        lo, hi = tile_cols(t)
        cols = host[:, lo:hi]
        if hi - lo < bucket:   # pad trailing planes: zero words are
            cols = np.concatenate(   # inert under the OR-merge
                [cols, np.zeros((n, bucket - (hi - lo)), np.uint32)],
                axis=1)
        return _place_tile(np.ascontiguousarray(cols), n, ctx.mesh,
                           round_, plan.seed, msgs, device=ctx.device)

    led = telemetry.current()
    if led.active:
        led.event("scale_plan", n=n, tiles=tiles, bucket_words=bucket,
                  total_words=w_total, segments=plan.segment_count,
                  dcn_slices=n_slices, overlap=overlap,
                  predicted_peak_device_bytes=
                  plan.predicted_peak_device_bytes,
                  plan_fingerprint=plan_fp, resumed=resumed)

    measured = None
    segments_run = 0
    halted = False
    wait_total_ms = 0.0        # host stall wall across all segments
    wall_total_ms = 0.0        # segment walls across all segments
    done = base_round
    while done < plan.max_rounds:
        todo = min(plan.segment_every, plan.max_rounds - done)
        seg_msgs = seg_dropped = None
        seg_round = done
        seg_t0 = time.perf_counter()
        # one in-flight (dispatched, undrained) tile per slice — the
        # third pipeline buffer budget.engine_components accounts as
        # fetch_buffer
        pending = [None] * n_slices

        def _dispatch(t, todo):
            """Stage 1+2: stage the tile's words onto its slice
            (transfer-in overlaps the slice's previous compute — the
            pending tile is NOT yet drained) and enqueue the segment
            loop; then enqueue the D2H copy behind the compute so the
            fetch starts the moment the result exists.  Returns the
            in-flight record ``_drain`` settles."""
            nonlocal measured
            ctx = ctxs[t % n_slices]
            t0 = time.perf_counter()
            cur = prep(t, seg_round, ctx)
            t1 = time.perf_counter()
            if track:
                args = (cur, todo, jnp.float32(dropped)) + ctx.tables
            else:
                args = (cur, todo) + ctx.tables
            if measured is None and measure_memory:
                measured = _measure_loop_bytes(ctx.runner, *args)
                crosscheck_peak(
                    plan.predicted_peak_device_bytes, measured,
                    engine=plan.engine, n=plan.n, tiles=plan.tiles,
                    plan_fingerprint=plan_fp)
            if track:
                out, acc = ctx.runner(*args)
            else:
                out, acc = ctx.runner(*args), None
            out.seen.copy_to_host_async()
            t2 = time.perf_counter()
            return {"tile": t, "slice": ctx.index, "out": out,
                    "acc": acc, "put_ms": (t1 - t0) * 1e3,
                    "dispatch_ms": (t2 - t1) * 1e3}

        def _drain(rec):
            """Stage 3 — the ONE place the segment loop blocks on the
            device (staticcheck blocking-fetch-in-segment-loop exempts
            ``_drain*`` by name): wait for the tile's result, write its
            columns into the host cursor, settle the message
            accounting, and emit the tile's walls."""
            nonlocal seg_msgs, seg_dropped, wait_total_ms
            t, out = rec["tile"], rec["out"]
            t0 = time.perf_counter()
            out.seen.block_until_ready()
            t1 = time.perf_counter()
            tile_msgs = float(out.msgs)
            tile_dropped = (float(rec["acc"])
                            if rec["acc"] is not None else 0.0)
            lo, hi = tile_cols(t)
            host[:, lo:hi] = np.asarray(out.seen)[:n, :hi - lo]
            t2 = time.perf_counter()
            if seg_msgs is None:
                seg_msgs, seg_dropped = tile_msgs, tile_dropped
            elif (tile_msgs, tile_dropped) != (seg_msgs, seg_dropped):
                # every tile replays the SAME content-free message
                # accounting; disagreement means the plane-independence
                # contract broke — refuse before publishing state
                raise AssertionError(
                    f"tile {t} (slice {rec['slice']}) message "
                    f"accounting ({tile_msgs}, {tile_dropped}) "
                    f"disagrees with tile 0 ({seg_msgs}, "
                    f"{seg_dropped}) — word planes are no longer "
                    "trajectory-independent")
            wait_ms = (t1 - t0) * 1e3
            wait_total_ms += wait_ms
            if led.active:
                led.event("tile_stream", sync=False, round=seg_round,
                          tile=t, slice=rec["slice"],
                          put_ms=rec["put_ms"],
                          dispatch_ms=rec["dispatch_ms"],
                          wait_ms=wait_ms,
                          copy_ms=(t2 - t1) * 1e3)

        for t in range(tiles):
            s = t % n_slices
            rec = _dispatch(t, todo)
            prev, pending[s] = pending[s], rec
            if not overlap:
                pending[s] = None
                _drain(rec)
            elif prev is not None:
                # tile t is now in flight on slice s; draining t -
                # n_slices overlaps its transfer AND compute
                _drain(prev)
        for rec in sorted((p for p in pending if p is not None),
                          key=lambda r: r["tile"]):
            _drain(rec)
        seg_wall_ms = (time.perf_counter() - seg_t0) * 1e3
        wall_total_ms += seg_wall_ms
        done += todo
        msgs, dropped = seg_msgs, seg_dropped
        segments_run += 1
        if checkpoint_path:
            from gossip_tpu.models.state import SimState
            import jax
            save_state(checkpoint_path,
                       SimState(seen=host, round=jnp.int32(done),
                                base_key=jax.random.key(plan.seed),
                                msgs=jnp.float32(msgs)),
                       extra_meta={"round": done, "dropped": dropped,
                                   "scale_plan": plan_fp,
                                   "fault_program": fault_fp})
            if led.active:
                led.event("scale_segment", round=done, tiles=tiles,
                          dropped=dropped, wall_ms=seg_wall_ms)
        if halt_after_segments is not None \
                and segments_run >= halt_after_segments \
                and done < plan.max_rounds:
            halted = True
            break

    alive = None
    if plan.fault is not None:
        m = NE.metric_alive(plan.fault, n, plan.origin)
        alive = None if m is None else np.asarray(m).astype(bool)
    cov = host_coverage(host, plan.rumors, alive)

    efficiency = None
    if wall_total_ms > 0.0:
        efficiency = max(0.0, min(1.0,
                                  1.0 - wait_total_ms / wall_total_ms))
    bitwise = None
    if check_bitwise and not halted:
        ref, ref_msgs, ref_dropped = untiled_reference(
            plan, mesh=ctxs[0].mesh, device=ctxs[0].device)
        bitwise = (np.array_equal(ref, host)
                   and ref_msgs == msgs and ref_dropped == dropped)
    if led.active:
        led.event("scale_run", rounds=done, coverage=cov, msgs=msgs,
                  dropped=dropped, tiles=tiles, halted=halted,
                  bitwise_equal=bitwise, dcn_slices=n_slices,
                  overlap=overlap, overlap_efficiency=efficiency,
                  wall_ms=wall_total_ms, wait_ms=wait_total_ms,
                  measured_loop_bytes=measured)
    return ScaleRunResult(
        n=n, rounds=done, coverage=cov, msgs=msgs, dropped=dropped,
        tiles=tiles, bucket_words=bucket, segments_run=segments_run,
        resumed=resumed, halted=halted, bitwise_equal=bitwise,
        measured_loop_bytes=measured,
        predicted_peak_device_bytes=plan.predicted_peak_device_bytes,
        dcn_slices=n_slices, overlap=overlap,
        overlap_efficiency=efficiency,
        final_state=host if keep_state else None)
