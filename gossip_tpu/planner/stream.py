"""Execute a ScalePlan: stream word-plane tiles through the packed
pull engine.

Why tiling along the WORD-PLANE axis is exact: a packed PULL round's
partner draws, drop coins, liveness rows and partition cuts are all
functions of ``(base_key, round, node id)`` — never of plane CONTENT
(models/si_packed.make_packed_round; the same fact behind the fused
engine's zero-ICI plane sharding).  Gather-then-OR commutes with
column slicing, so a tile of Wt < W word planes runs the IDENTICAL
trajectory on its own columns, and the concatenation of T streamed
tiles is BITWISE the untiled in-memory run — the gate
:func:`untiled_reference` + ``check_bitwise`` asserts, and
tests/test_planner.py pins under a mixed fault program.

Execution contract (the PR 6/9 operand discipline):

* ONE compiled loop per tile-shape bucket: every tile pads its words
  to the plan's pow2 ``bucket_words`` (padded planes are zero words —
  inert under the OR-merge), all tiles share one step closure
  (``_tile_step``, memoized with the schedule content STRIPPED from
  the key), and the segment runner is utils/checkpoint's — so K tiles
  compile once and a salted re-entry compiles zero
  (``assert_compiles``-pinned).
* Tile content is operands: the tile words ride ``device_put`` (double
  -buffered — the next tile's transfer is issued before the current
  tile's result is fetched, so jax's async dispatch overlaps copy with
  compute), the nemesis schedule rides the step's table tail.
* Crash safety reuses the checkpoint cursor discipline: the full
  packed state lives on the HOST between segments, every published
  checkpoint carries the absolute round cursor + exact ``dropped``
  carry + the plan AND fault-program fingerprints, and ``--resume``
  refuses a mismatch loudly (utils/checkpoint crash contract; resume
  == straight streamed run bitwise, test-pinned).

Scope refusals (loud, never silent): engine != packed, mode != pull,
``dcn_slices`` > 1 (the multi-slice tile fan-out is the hardware-
capture remainder — tools/hw_refresh runs this executor per slice at
the window), explicit topologies (a 100M-row neighbor table is its own
budget item the streamed drivers do not yet carry).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig
from gossip_tpu.planner.budget import (ScalePlan, plan_fingerprint,
                                       WORD_BITS, WORD_BYTES)


@dataclasses.dataclass
class ScaleRunResult:
    """What a streamed run reports (CLI/tools print it as JSON)."""

    n: int
    rounds: int
    coverage: float
    msgs: float
    dropped: float
    tiles: int
    bucket_words: int
    segments_run: int
    resumed: bool
    halted: bool                       # stopped by halt_after_segments
    bitwise_equal: Optional[bool]      # vs untiled_reference, if checked
    measured_loop_bytes: Optional[int]
    predicted_peak_device_bytes: int
    final_state: Optional[np.ndarray]  # uint32[n, W] when keep_state

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("final_state")
        return d


def host_init_packed(n: int, rumors: int, origin: int) -> np.ndarray:
    """uint32[n, W] initial packed state in NUMPY — bitwise the jax
    ``pack(init_state(...).seen)`` (rumor r starts at node
    ``(origin + r) % n``, models/state.init_state; pinned equal in
    tests/test_planner.py) without ever allocating the bool[N, R]
    table the jax path goes through: at 100M nodes the device-side
    init IS the budget item streaming exists to avoid."""
    w = (rumors + WORD_BITS - 1) // WORD_BITS
    out = np.zeros((n, w), np.uint32)
    r = np.arange(rumors)
    rows = (origin + r) % n
    bits = np.left_shift(np.uint32(1),
                         (r % WORD_BITS).astype(np.uint32),
                         dtype=np.uint32)
    np.bitwise_or.at(out, (rows, r // WORD_BITS), bits)
    return out


# step closures memoized with schedule CONTENT stripped from the key
# (the parallel/sharded._cached_dense_loop discipline): two fault
# programs sharing (static fault, canonical horizon bucket) get ONE
# step object, so the jitted segment runner's cache serves both and a
# salted scenario re-entry compiles zero.  BOUNDED FIFO: the keys are
# tuples, so the utils/checkpoint weak-key trick cannot apply — an
# unbounded strong dict would pin every step closure (and, through
# checkpoint._segment_runners' weak keys, its jitted executables)
# forever in a long-lived process.  Evicting the oldest entry lets
# the weak runner cache drop with it; a scale run uses ONE entry, so
# 16 covers any realistic session with zero re-trace churn.
_STEP_CACHE: "dict" = {}
_STEP_CACHE_MAX = 16


def _tile_step(proto: ProtocolConfig, n: int,
               fault: Optional[FaultConfig], origin: int, mesh):
    """(step, schedule tables) for streaming tiles of any word width.
    The packed PULL step never bakes the word count (its trace is
    width-polymorphic — jit specializes per tile-shape bucket), and
    bakes no schedule content (tables are operands)."""
    import jax.numpy as jnp  # noqa: F401  (jax import deferred)
    from gossip_tpu.models.si_packed import make_packed_round
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.topology import generators as G

    ch = NE.get(fault)
    fault_static = (None if fault is None
                    else dataclasses.replace(fault, churn=None))
    t_pad = None if ch is None else NE.canonical_horizon(ch)
    key = (proto, n, fault_static, t_pad, origin, mesh)
    step = _STEP_CACHE.get(key)
    topo = G.complete(n)
    if step is None:
        if mesh is None:
            step, _ = make_packed_round(proto, topo, fault, origin,
                                        tabled=True)
        else:
            from gossip_tpu.parallel.sharded_packed import (
                make_sharded_packed_round)
            step, _ = make_sharded_packed_round(proto, topo, mesh,
                                                fault, origin,
                                                tabled=True)
        while len(_STEP_CACHE) >= _STEP_CACHE_MAX:
            _STEP_CACHE.pop(next(iter(_STEP_CACHE)))
        _STEP_CACHE[key] = step
    tables = ()
    if ch is not None:
        n_pad = n
        if mesh is not None:
            from gossip_tpu.parallel.sharded import pad_to_mesh
            n_pad = pad_to_mesh(n, mesh, "nodes")
        tables = NE.sched_args(NE.build(fault, n, n_pad, t_pad=t_pad))
    return step, tables


def _refuse(plan: ScalePlan) -> None:
    if plan.engine != "packed":
        raise ValueError(
            f"run_at_scale streams the packed engine only; plan says "
            f"engine={plan.engine!r} (the budget model covers it, the "
            "streamed executor does not — docs/SCALING.md scope)")
    if plan.mode != C.PULL:
        raise ValueError(
            f"run_at_scale streams PULL rounds only, got mode="
            f"{plan.mode!r} (anti-entropy's reverse delta writes "
            "cross-tile state — planner/budget.plan_scale already "
            "refuses this at plan time)")
    if plan.dcn_slices > 1:
        raise ValueError(
            f"plan wants {plan.dcn_slices} DCN slices; this executor "
            "streams the tile axis serially on one slice — the multi-"
            "slice tile fan-out rides tools/hw_refresh at the capture "
            "window (ROADMAP item 3 remainder)")


def _mesh_for(plan: ScalePlan):
    if plan.per_slice == 1:
        return None
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(plan.per_slice, axis_name="nodes")


def _measure_loop_bytes(runner, *args) -> Optional[int]:
    """Peak bytes of the compiled tile loop via AOT memory analysis
    (argument + output + temp) — the 'measured allocation' the
    committed record holds the prediction against.  None when the
    backend cannot report it."""
    try:
        stats = runner.lower(*args).compile().memory_analysis()
        return int(stats.argument_size_in_bytes
                   + stats.output_size_in_bytes
                   + stats.temp_size_in_bytes)
    except Exception:
        return None


def host_coverage(state: np.ndarray, rumors: int,
                  alive: Optional[np.ndarray] = None,
                  chunk: int = 1 << 20) -> float:
    """Min-over-rumors coverage of a host packed state — the numpy
    twin of ops/bitpack.coverage_packed (integer counts, ONE division
    at the end: the device-division-lottery discipline), chunked so a
    100M-row table never materializes its bool expansion."""
    n, w = state.shape
    counts = np.zeros(w * WORD_BITS, np.int64)
    denom = 0
    # the 32x bit expansion below transiently allocates rows*w*32
    # uint32s — bound it by WORDS processed, not rows, or a wide
    # state's "chunk" is the whole table (a ~GiB spike at the
    # committed-record shape)
    chunk = max(1, chunk // max(w, 1))
    for lo in range(0, n, chunk):
        rows = state[lo:lo + chunk]
        if alive is not None:
            m = alive[lo:lo + chunk]
            rows = rows[m]
            denom += int(m.sum())
        else:
            denom += rows.shape[0]
        bits = (rows[:, :, None] >> np.arange(WORD_BITS,
                                              dtype=np.uint32)) & 1
        counts += bits.reshape(rows.shape[0], -1).sum(0, dtype=np.int64)
    if denom == 0:
        return 0.0
    return float(counts[:rumors].min() / denom)


def untiled_reference(plan: ScalePlan, mesh=None):
    """The in-memory run at full word width W — ONE runner call over
    the plan's whole round budget through the SAME step factory and
    segment runner the tiles use.  Returns (uint32[n, W], msgs,
    dropped).  This is what the streamed trajectory must equal
    BITWISE."""
    import jax
    import jax.numpy as jnp
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.checkpoint import _segment_runner

    _refuse(plan)
    proto = ProtocolConfig(mode=plan.mode, fanout=plan.fanout,
                           rumors=plan.rumors)
    mesh = _mesh_for(plan) if mesh is None else mesh
    step, tables = _tile_step(proto, plan.n, plan.fault, plan.origin,
                              mesh)
    track = NE.get(plan.fault) is not None
    runner = _segment_runner(step, track)
    seen = host_init_packed(plan.n, plan.rumors, plan.origin)
    st = _place_tile(seen, plan.n, mesh, 0, plan.seed, 0.0)
    if track:
        (out, acc) = runner(st, plan.max_rounds, jnp.float32(0.0),
                            *tables)
        dropped = float(acc)
    else:
        out = runner(st, plan.max_rounds, *tables)
        dropped = 0.0
    final = np.asarray(out.seen)[:plan.n]
    return final, float(out.msgs), dropped


def _place_tile(words: np.ndarray, n: int, mesh, round_: int,
                seed: int, msgs: float):
    """Pad a host word tile to the mesh row count, ship it, and wrap
    the SimState the packed step expects.  The device_put is the
    double-buffer leg: issued eagerly, it overlaps the previous tile's
    compute under async dispatch."""
    import jax
    import jax.numpy as jnp
    from gossip_tpu.models.state import SimState

    if mesh is None:
        dev = jax.device_put(words)
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from gossip_tpu.parallel.sharded import pad_to_mesh
        n_pad = pad_to_mesh(n, mesh, "nodes")
        if n_pad != words.shape[0]:
            words = np.concatenate(
                [words, np.zeros((n_pad - n, words.shape[1]),
                                 words.dtype)], axis=0)
        dev = jax.device_put(words,
                             NamedSharding(mesh, P("nodes", None)))
    return SimState(seen=dev, round=jnp.int32(round_),
                    base_key=jax.random.key(seed),
                    msgs=jnp.float32(msgs))


def run_at_scale(plan: ScalePlan, *, checkpoint_path: Optional[str] = None,
                 resume: bool = False, check_bitwise: bool = False,
                 measure_memory: bool = False, keep_state: bool = False,
                 halt_after_segments: Optional[int] = None,
                 mesh=None) -> ScaleRunResult:
    """Drive a ScalePlan: T word-plane tiles stream host<->device
    through each checkpoint segment (module doc has the contract).

    ``halt_after_segments`` stops after that many segments WITH the
    checkpoint published — the deterministic stand-in for a SIGKILL
    between segments (tests and the capture tool resume from it and
    must land bitwise on the uninterrupted run).  ``check_bitwise``
    additionally runs :func:`untiled_reference` and compares the final
    states byte-for-byte.  ``measure_memory`` AOT-compiles the tile
    loop once more for its memory analysis — leave it off in compile-
    count-pinned paths."""
    import jax.numpy as jnp
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils import telemetry
    from gossip_tpu.utils.checkpoint import (_segment_runner, load_meta,
                                             load_state, save_state)

    _refuse(plan)
    if resume and not checkpoint_path:
        raise ValueError("resume=True needs checkpoint_path")
    n, w_total = plan.n, plan.total_words
    bucket = plan.bucket_words
    tiles = plan.tiles
    plan_doc = plan.to_dict()
    plan_fp = plan_fingerprint(plan_doc)
    fault_fp = NE.schedule_fingerprint(plan.fault, n, plan.origin)
    proto = ProtocolConfig(mode=plan.mode, fanout=plan.fanout,
                           rumors=plan.rumors)
    mesh = _mesh_for(plan) if mesh is None else mesh
    track = NE.get(plan.fault) is not None

    base_round, dropped, msgs = 0, 0.0, 0.0
    resumed = False
    if resume:
        meta = load_meta(checkpoint_path)
        extra = meta.get("extra") or {}
        if extra.get("scale_plan") != plan_fp:
            raise ValueError(
                f"checkpoint {checkpoint_path} was written under a "
                f"different scale plan (fingerprint "
                f"{extra.get('scale_plan')!r} != {plan_fp!r}) — "
                "resuming a re-tiled run would make its budget claims "
                "unattributable; regenerate or drop --resume")
        if extra.get("fault_program") != fault_fp:
            raise ValueError(
                f"checkpoint {checkpoint_path} carries fault program "
                f"{extra.get('fault_program')!r}; this plan builds "
                f"{fault_fp!r} — a resumed fault program must be the "
                "one the checkpoint ran (utils/checkpoint crash "
                "contract)")
        st = load_state(checkpoint_path)
        # copy: np.asarray over a jax buffer is a read-only view, and
        # the tile write-back mutates host in place
        host = np.array(st.seen, np.uint32)
        base_round = int(extra["round"])
        dropped = float(extra.get("dropped", 0.0))
        msgs = float(st.msgs)
        resumed = True
    else:
        host = host_init_packed(n, plan.rumors, plan.origin)

    step, tables = _tile_step(proto, n, plan.fault, plan.origin, mesh)
    runner = _segment_runner(step, track)

    def tile_cols(t):
        lo = t * bucket
        return lo, min(lo + bucket, w_total)

    def prep(t, round_):
        lo, hi = tile_cols(t)
        cols = host[:, lo:hi]
        if hi - lo < bucket:   # pad trailing planes: zero words are
            cols = np.concatenate(   # inert under the OR-merge
                [cols, np.zeros((n, bucket - (hi - lo)), np.uint32)],
                axis=1)
        return _place_tile(np.ascontiguousarray(cols), n, mesh, round_,
                           plan.seed, msgs)

    led = telemetry.current()
    if led.active:
        led.event("scale_plan", n=n, tiles=tiles, bucket_words=bucket,
                  total_words=w_total, segments=plan.segment_count,
                  predicted_peak_device_bytes=
                  plan.predicted_peak_device_bytes,
                  plan_fingerprint=plan_fp, resumed=resumed)

    measured = None
    segments_run = 0
    halted = False
    done = base_round
    while done < plan.max_rounds:
        todo = min(plan.segment_every, plan.max_rounds - done)
        seg_msgs = seg_dropped = None
        nxt = prep(0, done)
        for t in range(tiles):
            cur = nxt
            if t + 1 < tiles:
                nxt = prep(t + 1, done)
            if track:
                args = (cur, todo, jnp.float32(dropped)) + tables
                if measured is None and measure_memory:
                    measured = _measure_loop_bytes(runner, *args)
                out, acc = runner(*args)
                tile_dropped = float(acc)
            else:
                args = (cur, todo) + tables
                if measured is None and measure_memory:
                    measured = _measure_loop_bytes(runner, *args)
                out = runner(*args)
                tile_dropped = 0.0
            tile_msgs = float(out.msgs)
            if seg_msgs is None:
                seg_msgs, seg_dropped = tile_msgs, tile_dropped
            elif (tile_msgs, tile_dropped) != (seg_msgs, seg_dropped):
                # every tile replays the SAME content-free message
                # accounting; disagreement means the plane-independence
                # contract broke — refuse before publishing state
                raise AssertionError(
                    f"tile {t} message accounting ({tile_msgs}, "
                    f"{tile_dropped}) disagrees with tile 0 "
                    f"({seg_msgs}, {seg_dropped}) — word planes are "
                    "no longer trajectory-independent")
            lo, hi = tile_cols(t)
            host[:, lo:hi] = np.asarray(out.seen)[:n, :hi - lo]
        done += todo
        msgs, dropped = seg_msgs, seg_dropped
        segments_run += 1
        if checkpoint_path:
            from gossip_tpu.models.state import SimState
            import jax
            save_state(checkpoint_path,
                       SimState(seen=host, round=jnp.int32(done),
                                base_key=jax.random.key(plan.seed),
                                msgs=jnp.float32(msgs)),
                       extra_meta={"round": done, "dropped": dropped,
                                   "scale_plan": plan_fp,
                                   "fault_program": fault_fp})
            if led.active:
                led.event("scale_segment", round=done, tiles=tiles,
                          dropped=dropped)
        if halt_after_segments is not None \
                and segments_run >= halt_after_segments \
                and done < plan.max_rounds:
            halted = True
            break

    alive = None
    if plan.fault is not None:
        m = NE.metric_alive(plan.fault, n, plan.origin)
        alive = None if m is None else np.asarray(m).astype(bool)
    cov = host_coverage(host, plan.rumors, alive)

    bitwise = None
    if check_bitwise and not halted:
        ref, ref_msgs, ref_dropped = untiled_reference(plan, mesh=mesh)
        bitwise = (np.array_equal(ref, host)
                   and ref_msgs == msgs and ref_dropped == dropped)
    if led.active:
        led.event("scale_run", rounds=done, coverage=cov, msgs=msgs,
                  dropped=dropped, tiles=tiles, halted=halted,
                  bitwise_equal=bitwise,
                  measured_loop_bytes=measured)
    return ScaleRunResult(
        n=n, rounds=done, coverage=cov, msgs=msgs, dropped=dropped,
        tiles=tiles, bucket_words=bucket, segments_run=segments_run,
        resumed=resumed, halted=halted, bitwise_equal=bitwise,
        measured_loop_bytes=measured,
        predicted_peak_device_bytes=plan.predicted_peak_device_bytes,
        final_state=host if keep_state else None)
