"""Scale planner: HBM budget model + streamed bit-plane tiling.

ROADMAP item 3's executable half: the repo has every ingredient for
100M+-node runs (packed word planes, sharded exchanges, multi-slice
hybrid meshes, chunked crash-safe checkpoint segments) but, until this
subsystem, nothing that could answer "what tiling fits N on this
topology?" — or execute the answer.

* :mod:`gossip_tpu.planner.budget` — the pure host-side HBM/host-RAM
  budget model.  NEVER imports jax (the analysis/ rationale: capacity
  questions must be answerable on a wedged-tunnel box, before any
  device exists).  ``plan_scale`` emits a validated :class:`ScalePlan`
  or refuses loudly with the binding constraint named.
* :mod:`gossip_tpu.planner.stream` — ``run_at_scale``: executes a
  ScalePlan through the existing packed drivers by streaming word-
  plane tiles host<->device per checkpoint segment, bitwise identical
  to the untiled in-memory run.

docs/SCALING.md has the contract; CLI: ``gossip_tpu plan`` /
``gossip_tpu scale-run``.
"""

from gossip_tpu.planner.budget import (  # noqa: F401
    DeviceSpec, InfeasiblePlanError, ScalePlan, plan_fingerprint,
    plan_scale, validate_plan)
