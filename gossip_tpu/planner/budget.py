"""The HBM / host-RAM budget model: "what tiling fits N on this box?"

Pure host arithmetic over the engines' documented memory layouts —
this module NEVER imports jax (the gossip_tpu/analysis rationale: a
capacity question must be answerable on a wedged-tunnel box, and the
closed forms below are config-sized, never N-sized).  Everything here
is bytes-per-node bookkeeping for the arrays the round kernels
actually allocate:

* **packed** (models/si_packed, parallel/sharded_packed) — the
  streamed engine.  State is ``uint32[N, W]`` word planes
  (W = ceil(R/32), ops/bitpack layout); the pull round's big
  intermediates are the all-gathered visible table (``n_pad * Wt * 4``
  per device) and the partner gather (``nl * k * Wt * 4``,
  ``pull_merge_packed``'s ``[Nl, k, W]`` pickup).  Because a PULL
  round's partner draws, drop coins, liveness and partition cuts are
  all functions of (key, round, node) — never of plane CONTENT — the
  word-plane axis is embarrassingly parallel: a tile of Wt < W planes
  runs the identical trajectory on its own columns, which is what the
  streamed executor (planner/stream.py) exploits and what these forms
  budget.
* **dense** (models/si, parallel/sharded) — bool rows, 8x the packed
  bytes per rumor plus the push scatter's count table; modeled for the
  refusal message (at 100M nodes dense is the binding constraint
  almost immediately), not for streaming.
* **fused** (ops/pallas_round, parallel/sharded_fused) — the
  ``[W, rows, 128]`` plane stack, one int32 lane word per node per
  plane; planes already shard rumor-wise (zero-ICI), so its natural
  scale axis is more devices, not host streaming.

The plan a feasible target lowers to is a :class:`ScalePlan`:
pow2-bucketed word-tile width (ONE compiled loop serves every tile —
tile content is operands, never memo keys, the PR 6/9 discipline),
checkpoint segment schedule (utils/checkpoint cursor discipline, so a
streamed run is crash-safe), and the mesh shape — single-slice, or
the ``parallel/multislice.make_hybrid_mesh`` hybrid where the node
axis stays on ICI inside a slice and the (communication-free) tile
stream divides across DCN slices.  An infeasible target raises
:class:`InfeasiblePlanError` NAMING the binding constraint — a
refusal that cannot say which wall it hit is not a capacity model.

docs/SCALING.md documents every term; tests/test_planner.py pins the
algebra (monotonicity in N, bucket stability, refusal messages,
round-trip).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Optional

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig

PLAN_VERSION = 1

WORD_BITS = 32
WORD_BYTES = 4

# Engines the model has closed forms for.  Only "packed" is executable
# by the streamed driver (planner/stream.py) — the others exist so the
# planner can NAME why they do not reach the target N.
ENGINES = ("packed", "dense", "fused")

# Fraction of HBM the plan never touches: XLA's own scratch, the
# runtime's framebuffers, fragmentation headroom.  Deliberately
# conservative; overridable per plan_scale call.
DEFAULT_RESERVE_FRAC = 0.08

# Pessimism multiplier on the checkpoint's host footprint: the live
# numpy state plus the npz tmp-write buffer (save_state writes
# ``path + ".tmp"`` then os.replace — both exist at the publish
# instant).
HOST_CKPT_COPIES = 2

# Default checkpoint segment length (rounds) — the utils/checkpoint
# ``every`` default.
DEFAULT_SEGMENT_EVERY = 50

# Minimum canonical schedule-table length — MUST equal
# ops/nemesis.SCHED_T_MIN (pinned by tests/test_planner.py; duplicated
# here so this module stays jax-free).
SCHED_T_MIN = 32


class InfeasiblePlanError(ValueError):
    """Target N does not fit the device topology.  ``binding`` names
    the constraint that refused it; ``bytes_needed``/``bytes_budget``
    quantify the wall."""

    def __init__(self, msg: str, *, binding: str, bytes_needed: int,
                 bytes_budget: int):
        super().__init__(msg)
        self.binding = binding
        self.bytes_needed = bytes_needed
        self.bytes_budget = bytes_budget


def _pow2_at_least(x: int) -> int:
    return 1 << max(0, (int(x) - 1)).bit_length()


def n_words(rumors: int) -> int:
    """ceil(R/32) — ops/bitpack.n_words, duplicated jax-free (pinned
    equal in tests/test_planner.py)."""
    return (rumors + WORD_BITS - 1) // WORD_BITS


def sched_t_pad(fault: Optional[FaultConfig]) -> int:
    """Canonical schedule-table length for a fault program — the
    jax-free twin of ops/nemesis.canonical_horizon (pinned equal in
    tests/test_planner.py so the two cannot drift)."""
    if fault is None or fault.churn is None:
        return SCHED_T_MIN
    return max(SCHED_T_MIN, _pow2_at_least(fault.churn.horizon()))


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """The device topology a plan targets.  ``slices`` > 1 selects the
    hybrid DCN mesh (parallel/multislice): ``chips`` is the TOTAL chip
    count, ``chips // slices`` the ICI-connected inner axis."""

    chips: int = 1
    hbm_bytes_per_chip: int = 16 * 1024**3
    slices: int = 1
    host_ram_bytes: int = 64 * 1024**3

    def __post_init__(self):
        if self.chips < 1:
            raise ValueError(f"chips must be >= 1, got {self.chips}")
        if self.slices < 1:
            raise ValueError(f"slices must be >= 1, got {self.slices}")
        if self.chips % self.slices:
            raise ValueError(
                f"chips={self.chips} does not divide into "
                f"slices={self.slices} (the hybrid mesh needs equal "
                "ICI rows — parallel/multislice.make_hybrid_mesh)")
        if self.hbm_bytes_per_chip <= 0 or self.host_ram_bytes <= 0:
            raise ValueError("byte capacities must be positive")

    @property
    def per_slice(self) -> int:
        return self.chips // self.slices


def engine_components(engine: str, *, n: int, rumors: int, fanout: int,
                      tile_words: int, devices: int,
                      fault: Optional[FaultConfig],
                      max_rounds: int) -> dict:
    """Per-DEVICE byte components of one compiled round program at word
    -tile width ``tile_words`` — the closed forms the plan sums into
    its predicted peak.  Keys are stable (docs/SCALING.md glossary);
    values are bytes.  ``devices`` is the node-axis shard count (the
    ICI inner axis — DCN slices divide the tile STREAM, i.e. wall
    clock, never per-device bytes)."""
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r} (one of {ENGINES})")
    w_total = n_words(rumors)
    wt = min(tile_words, w_total)
    n_pad = math.ceil(n / devices) * devices
    nl = n_pad // devices
    k = fanout
    t_pad = sched_t_pad(fault)
    churn = fault is not None and fault.churn is not None

    # schedule operands (ops/nemesis.sched_args): die/rec int32[n_pad]
    # + cut/drop [t_pad] — replicated on every device
    sched = (2 * n_pad + 2 * t_pad) * 4 if churn else 0
    # the metrics stack (ops/round_metrics) rides the loop carry only
    # under an active ledger: max_rounds rows of ~12 f32 channels
    metrics = max_rounds * 12 * 4

    if engine == "packed":
        state = nl * wt * WORD_BYTES
        comps = {
            # the resident tile + the masked `visible` copy
            "state_tile": state,
            "visible_copy": state,
            # all_gather of the visible table — the ONE collective
            # (parallel/sharded_packed module doc); on one device this
            # is the full table itself
            "exchange_gather": n_pad * wt * WORD_BYTES,
            # pull_merge_packed's [Nl, k, Wt] partner pickup
            "partner_gather": nl * k * wt * WORD_BYTES,
            # partners0/partners/valid int32 lanes + ids
            "partner_lanes": (3 * nl * k + nl) * 4,
            # the OR-accumulated `pulled` tile + the output state
            "merge_out": 2 * state,
            # the NEXT tile's device_put landing while this one
            # computes (planner/stream double buffering)
            "double_buffer": state,
            # the PREVIOUS tile's result, still resident while its D2H
            # fetch drains behind this tile's compute — the third
            # pipeline stage (planner/stream _drain); same tile shape,
            # output dtype == state dtype
            "fetch_buffer": state,
            "sched_operands": sched,
            "metrics_stack": metrics,
        }
    elif engine == "dense":
        state = nl * rumors  # bool rows
        comps = {
            "state_rows": state,
            "visible_copy": state,
            "exchange_gather": n_pad * rumors,
            "partner_gather": nl * k * rumors,
            "partner_lanes": (3 * nl * k + nl) * 4,
            # push half: the psum_scatter'd int32 count table
            # (ops/propagate.push_counts)
            "push_counts": n_pad * rumors * 4,
            "merge_out": 2 * state,
            "sched_operands": sched,
            "metrics_stack": metrics,
        }
    else:  # fused plane stack: [planes, rows, 128] int32 lane words
        rows = math.ceil(n / 128)
        planes = math.ceil(rumors / devices) if devices > 1 else rumors
        comps = {
            "plane_stack": planes * rows * 128 * 4,
            "alive_words": rows * 128 // 8 * 4,
            "cut_words": rows * 128 // 8 * 4,
            "sched_operands": (2 * t_pad) * 4 if churn else 0,
            "metrics_stack": metrics,
        }
    # XLA rounds every buffer to its alignment quantum and keeps small
    # runtime scalars (round/key/msgs/loop counters) beside the big
    # arrays — ~1.6% headroom plus a 4 KB floor covers both (the
    # committed record gates measured <= predicted against the AOT
    # memory analysis, so this term cannot silently rot)
    comps["alignment_pad"] = max(4096, sum(comps.values()) // 64)
    return comps


def host_components(*, n: int, rumors: int) -> dict:
    """Host-RAM byte components of a streamed run: the FULL packed
    state lives in numpy on the host (that is the whole point of
    streaming), and every checkpoint publish momentarily holds the npz
    tmp buffer beside it (utils/checkpoint.save_state's atomic-write
    choreography)."""
    w = n_words(rumors)
    full = n * w * WORD_BYTES
    return {
        "host_state": full,
        "checkpoint_buffers": (HOST_CKPT_COPIES - 1) * full,
    }


@dataclasses.dataclass(frozen=True)
class ScalePlan:
    """A validated, executable capacity plan.  ``to_dict`` round-trips
    through JSON (``from_dict`` re-validates); planner/stream.py
    executes it; the CLI prints it."""

    n: int
    rumors: int
    engine: str
    mode: str
    fanout: int
    max_rounds: int
    seed: int
    origin: int
    fault: Optional[FaultConfig]
    device: DeviceSpec
    # mesh: the node axis (O(N) collectives) stays on ICI inside one
    # slice; DCN slices divide the tile stream (zero cross-slice bytes
    # — tiles are independent trajectories)
    mesh_kind: str                 # "single" | "hybrid"
    dcn_slices: int
    per_slice: int
    # tiling
    total_words: int
    tiles: int
    bucket_words: int              # pow2 — the ONE compiled tile shape
    # checkpoint segments
    segment_every: int
    segment_count: int
    # budget verdict
    reserve_frac: float
    hbm_budget_bytes: int
    predicted_peak_device_bytes: int
    predicted_host_peak_bytes: int
    components: tuple              # ((name, bytes), ...) sorted desc
    binding: str                   # largest component (the headroom edge)

    def to_dict(self) -> dict:
        d = {
            "version": PLAN_VERSION,
            "target": {
                "n": self.n, "rumors": self.rumors, "mode": self.mode,
                "fanout": self.fanout, "max_rounds": self.max_rounds,
                "seed": self.seed, "origin": self.origin,
                "topology": "complete",
            },
            "engine": self.engine,
            "fault": (None if self.fault is None
                      else dataclasses.asdict(self.fault)),
            "device": dataclasses.asdict(self.device),
            "mesh": {"kind": self.mesh_kind,
                     "dcn_slices": self.dcn_slices,
                     "per_slice": self.per_slice,
                     "axes": ["sweep", "nodes"]},
            "tiling": {"total_words": self.total_words,
                       "tiles": self.tiles,
                       "bucket_words": self.bucket_words},
            "segments": {"every": self.segment_every,
                         "count": self.segment_count},
            "budget": {"reserve_frac": self.reserve_frac,
                       "hbm_budget_bytes": self.hbm_budget_bytes,
                       "predicted_peak_device_bytes":
                           self.predicted_peak_device_bytes,
                       "predicted_host_peak_bytes":
                           self.predicted_host_peak_bytes,
                       "components": {k: v for k, v in self.components},
                       "binding": self.binding},
        }
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)


def _fault_from_dict(d: Optional[dict]) -> Optional[FaultConfig]:
    if d is None:
        return None
    d = dict(d)
    churn = d.get("churn")
    if isinstance(churn, dict):
        # JSON lists -> the tuple-of-tuples ChurnConfig expects
        churn = {k: (tuple(tuple(x) if isinstance(x, list) else x
                           for x in v)
                     if isinstance(v, list) else v)
                 for k, v in churn.items()}
        d["churn"] = churn
    return FaultConfig(**d)


def plan_from_dict(doc: dict) -> ScalePlan:
    """Rebuild (and re-validate) a ScalePlan from its JSON dict —
    the ONE loader the CLI and the streamed executor share.  Every
    malformation is a ``ValueError`` naming the field (the CLI's
    one-line-refusal contract): a KeyError/TypeError from a truncated
    or foreign dict must never escape as a traceback."""
    validate_plan(doc)
    t = doc["target"]
    try:
        fault = _fault_from_dict(doc.get("fault"))
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"plan fault section is malformed: "
                         f"{type(e).__name__}: {e}") from e
    try:
        device = DeviceSpec(**doc["device"])
    except (TypeError, ValueError) as e:
        raise ValueError(f"plan device section is malformed: "
                         f"{type(e).__name__}: {e}") from e
    # re-derive rather than trust: a hand-edited plan must still be
    # internally consistent with the model
    plan = plan_scale(
        t["n"], rumors=t["rumors"], device=device,
        engine=doc["engine"], mode=t["mode"], fanout=t["fanout"],
        max_rounds=t["max_rounds"], seed=t["seed"],
        origin=t["origin"], fault=fault,
        segment_every=doc["segments"]["every"],
        reserve_frac=doc["budget"]["reserve_frac"])
    got, want = plan.to_dict(), doc
    for key in ("tiling", "mesh", "segments"):
        if got[key] != want[key]:
            raise ValueError(
                f"plan file's {key} section {want[key]} disagrees with "
                f"the model's derivation {got[key]} — stale or "
                "hand-edited plan; regenerate with `gossip_tpu plan`")
    return plan


_REQUIRED_SECTIONS = ("target", "engine", "device", "mesh", "tiling",
                      "segments", "budget")
_REQUIRED_TARGET = ("n", "rumors", "mode", "fanout", "max_rounds",
                    "seed", "origin")


def validate_plan(doc: dict) -> None:
    """Structural validation of a plan dict; ``ValueError`` NAMES the
    offending field (the CLI prints it one-line — a wrong-TYPED
    section must refuse the same way, never escape as a
    TypeError/AttributeError traceback)."""
    if not isinstance(doc, dict):
        raise ValueError(f"plan must be a JSON object, got "
                         f"{type(doc).__name__}")
    if doc.get("version") != PLAN_VERSION:
        raise ValueError(f"plan version {doc.get('version')!r} != "
                         f"{PLAN_VERSION} (regenerate with "
                         "`gossip_tpu plan`)")
    for sec in _REQUIRED_SECTIONS:
        if sec not in doc:
            raise ValueError(f"plan is missing the {sec!r} section")
        if sec != "engine" and not isinstance(doc[sec], dict):
            raise ValueError(
                f"plan {sec!r} section must be an object, got "
                f"{type(doc[sec]).__name__}")
    for key in _REQUIRED_TARGET:
        if key not in doc["target"]:
            raise ValueError(f"plan target is missing {key!r}")
    tiling = doc["tiling"]
    for key in ("total_words", "tiles", "bucket_words"):
        if not isinstance(tiling.get(key), int) or tiling[key] < 1:
            raise ValueError(f"plan tiling.{key} must be a positive "
                             f"int, got {tiling.get(key)!r}")
    if tiling["tiles"] * tiling["bucket_words"] < tiling["total_words"]:
        raise ValueError(
            f"plan tiling covers {tiling['tiles']}*"
            f"{tiling['bucket_words']} words < total_words="
            f"{tiling['total_words']}")
    bw = tiling["bucket_words"]
    if bw & (bw - 1):
        raise ValueError(f"plan tiling.bucket_words={bw} is not a "
                         "power of two (the one-executable-per-bucket "
                         "contract)")
    seg = doc["segments"]
    if not isinstance(seg.get("every"), int) or seg["every"] < 1:
        raise ValueError("plan segments.every must be a positive int")
    if "reserve_frac" not in doc["budget"]:
        raise ValueError("plan budget section is missing "
                         "'reserve_frac'")


def plan_fingerprint(doc: dict) -> str:
    """sha256 of the canonical plan JSON — stamped into streamed-run
    checkpoints (extra['scale_plan']) so --resume refuses a checkpoint
    written under a DIFFERENT plan (the utils/checkpoint fingerprint
    discipline: a silently re-tiled resume would still be bitwise, but
    its budget claims would be unattributable)."""
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


def crosscheck_peak(predicted_bytes, measured_bytes, *,
                    engine: str = "packed", n=None, tiles=None,
                    plan_fingerprint=None,
                    source: str = "xla_memory_analysis") -> dict:
    """The measured≤predicted drift gate, as ONE reusable cross-check:
    XLA's own memory analysis (the independent source ROADMAP item 1
    asks for) against this module's hand-maintained closed forms.
    Returns the verdict dict and emits it as one ``budget_xcheck``
    event — sync=False, because callers run this inside timed windows
    (the streamed executor's first dispatch; tools/cost_capture.py's
    engine sweep).

    Record-never-gate at the event layer: ``measured_bytes=None`` (a
    backend without memory analysis) records explicit nulls with
    ``ok=None`` — the EVENT never fabricates a verdict; gating callers
    (scale_capture's memory gate, cost_capture's packed cross-check)
    decide what a null means for THEIR artifact.  A real pair with
    measured > predicted is ``ok=False``: the closed form drifted
    below reality and every capacity plan built on it is a lie —
    exactly what the PR 15 committed record (92.3 MB ≤ 106.5 MB)
    existed to prevent, now re-checked wherever a compiled executable
    self-reports its footprint."""
    from gossip_tpu.utils import telemetry
    ok = None
    headroom = None
    predicted = int(predicted_bytes) if predicted_bytes is not None \
        else None
    measured = int(measured_bytes) if measured_bytes is not None \
        else None
    if measured is not None and predicted:
        ok = bool(measured <= predicted)
        headroom = round(1.0 - measured / predicted, 4)
    verdict = {"engine": engine, "n": n, "tiles": tiles,
               "predicted_bytes": predicted,
               "measured_bytes": measured, "ok": ok,
               "headroom_frac": headroom, "source": source,
               "plan_fingerprint": plan_fingerprint}
    telemetry.current().event("budget_xcheck", sync=False, **verdict)
    return verdict


def forced_device_for_tiles(n: int, *, rumors: int, fanout: int,
                            max_rounds: int,
                            fault: Optional[FaultConfig],
                            tiles_at_least: int, devices: int = 1,
                            host_ram_bytes: int = 64 * 1024**3
                            ) -> DeviceSpec:
    """A DeviceSpec whose artificial HBM budget FORCES >=
    ``tiles_at_least`` streamed tiles for this target — the ONE
    forced-budget construction (the dry-run ``scale_plan`` family,
    tools/scale_capture.py, and the test suite all build theirs here,
    so the load-bearing reserve-frac inversion + headroom margin
    cannot drift between the gates).  The budget is sized just above
    the peak of a candidate tile width and then VERIFIED by planning
    against it — at degenerate shapes (tiny n, wide fixed terms) a
    wider bucket can still fit a budget sized for a narrower one, so
    the candidate shrinks until the plan really streams >=
    ``tiles_at_least`` tiles; impossible requests (more tiles than
    word planes) are refused loudly."""
    w = n_words(rumors)
    if tiles_at_least > w:
        raise ValueError(
            f"cannot force {tiles_at_least} tiles over {w} word "
            f"plane(s) (rumors={rumors}); tiles are word-granular")
    for wt in range(max(1, w // tiles_at_least), 0, -1):
        peak = sum(engine_components(
            "packed", n=n, rumors=rumors, fanout=fanout,
            tile_words=wt, devices=devices, fault=fault,
            max_rounds=max_rounds).values())
        dev = DeviceSpec(
            chips=devices,
            hbm_bytes_per_chip=int(peak / (1 - DEFAULT_RESERVE_FRAC))
            + 4096,
            host_ram_bytes=host_ram_bytes)
        plan = plan_scale(n, rumors=rumors, device=dev,
                          fanout=fanout, max_rounds=max_rounds,
                          fault=fault)
        if plan.tiles >= tiles_at_least:
            return dev
    raise ValueError(
        f"cannot force {tiles_at_least} tiles for n={n}, "
        f"rumors={rumors} on {devices} device(s): fixed-size "
        "components dominate even the 1-word tile budget")


def plan_scale(n: int, *, rumors: int = 1,
               device: DeviceSpec = DeviceSpec(),
               engine: str = "packed", mode: str = C.PULL,
               fanout: int = 1, max_rounds: int = 64, seed: int = 0,
               origin: int = 0, fault: Optional[FaultConfig] = None,
               segment_every: Optional[int] = None,
               reserve_frac: float = DEFAULT_RESERVE_FRAC) -> ScalePlan:
    """Pick the word-tile width / segment schedule / mesh shape that
    fits ``n`` on ``device``, or refuse with the binding constraint
    named (:class:`InfeasiblePlanError`).

    The search is over pow2 tile-width buckets, widest first: the
    fewest tiles whose per-device peak fits the reserved HBM budget
    wins (fewer tiles = fewer host<->device round trips per segment).
    All tiles share ONE bucket (padded trailing planes are zero words,
    inert under the OR-merge), so the streamed executor compiles
    exactly one loop per plan."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if n > 2**31 - 1:
        raise InfeasiblePlanError(
            f"n={n} exceeds the int32 node-id space (2^31-1) every "
            "round kernel indexes with — the binding constraint is "
            "node_id_dtype, not memory",
            binding="node_id_dtype", bytes_needed=n,
            bytes_budget=2**31 - 1)
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r} (one of {ENGINES})")
    if engine == "packed" and mode != C.PULL:
        raise ValueError(
            f"scale plans stream the packed PULL engine; mode {mode!r} "
            "is not tileable along word planes (anti-entropy's reverse "
            "delta and the push scatter write CROSS-tile state — "
            "models/si_packed module doc)")
    if fanout < 1:
        raise ValueError(f"fanout must be >= 1, got {fanout}")
    if rumors < 1:
        raise ValueError(f"rumors must be >= 1, got {rumors}")
    if max_rounds < 1:
        raise ValueError(f"max_rounds must be >= 1, got {max_rounds}")
    if not 0.0 <= reserve_frac < 1.0:
        raise ValueError(f"reserve_frac={reserve_frac} outside [0, 1)")

    w_total = n_words(rumors)
    budget = int(device.hbm_bytes_per_chip * (1.0 - reserve_frac))
    devices = device.per_slice   # node axis shards ICI-only

    def peak(wt: int):
        comps = engine_components(
            engine, n=n, rumors=rumors, fanout=fanout, tile_words=wt,
            devices=devices, fault=fault, max_rounds=max_rounds)
        return sum(comps.values()), comps

    # host side first: streaming cannot help a host that cannot hold
    # the full packed state + the checkpoint publish buffer
    hcomps = host_components(n=n, rumors=rumors)
    host_peak = sum(hcomps.values())
    if host_peak > device.host_ram_bytes:
        biggest = max(hcomps, key=hcomps.get)
        raise InfeasiblePlanError(
            f"infeasible: host RAM is the binding constraint "
            f"({biggest}: the streamed run needs {host_peak:,} host "
            f"bytes — full packed state {hcomps['host_state']:,} plus "
            f"checkpoint publish buffers — against "
            f"{device.host_ram_bytes:,} available); a bigger host or "
            "fewer rumor planes, not more HBM, moves this wall",
            binding=biggest, bytes_needed=host_peak,
            bytes_budget=device.host_ram_bytes)

    # widest pow2 bucket that fits -> fewest tiles
    bucket = _pow2_at_least(w_total)
    chosen = None
    while bucket >= 1:
        p, comps = peak(bucket)
        if p <= budget:
            chosen = (bucket, p, comps)
            break
        bucket //= 2
    if chosen is None:
        _, comps = peak(1)
        biggest = max(comps, key=comps.get)
        need = sum(comps.values())
        raise InfeasiblePlanError(
            f"infeasible: n={n:,} does not fit "
            f"{devices} chip(s) x {device.hbm_bytes_per_chip:,} HBM "
            f"bytes even at the minimum 1-word tile — the binding "
            f"constraint is {biggest} ({comps[biggest]:,} bytes of the "
            f"{need:,}-byte peak against the {budget:,}-byte reserved "
            f"budget); it scales with N/devices, so more ICI chips "
            "per slice (or a smaller N) move it, narrower tiles "
            "cannot",
            binding=biggest, bytes_needed=need, bytes_budget=budget)

    bucket_words, predicted, comps = chosen
    tiles = math.ceil(w_total / bucket_words)
    every = (DEFAULT_SEGMENT_EVERY if segment_every is None
             else int(segment_every))
    if every < 1:
        raise ValueError(f"segment_every must be >= 1, got {every}")
    every = min(every, max_rounds)
    ordered = tuple(sorted(comps.items(), key=lambda kv: -kv[1]))
    return ScalePlan(
        n=n, rumors=rumors, engine=engine, mode=mode, fanout=fanout,
        max_rounds=max_rounds, seed=seed, origin=origin, fault=fault,
        device=device,
        mesh_kind="hybrid" if device.slices > 1 else "single",
        dcn_slices=device.slices, per_slice=device.per_slice,
        total_words=w_total, tiles=tiles, bucket_words=bucket_words,
        segment_every=every,
        segment_count=math.ceil(max_rounds / every),
        reserve_frac=reserve_frac, hbm_budget_bytes=budget,
        predicted_peak_device_bytes=predicted,
        predicted_host_peak_bytes=host_peak,
        components=ordered, binding=ordered[0][0])
