"""gRPC sidecar: the host-side shim between CLIs/harnesses and the JAX
simulator (SURVEY.md §2.4 / BASELINE.json north star)."""
