"""gRPC sidecar: drive the simulator as a service, coarse-grained.

The north star (BASELINE.json) wants the Go-style CLI to select the JAX
simulator "at runtime via a gRPC shim to a Python/JAX sidecar".  This is
that shim.  Two design rules from SURVEY.md §7 ("The gRPC boundary"):

  * **Coarse calls only** — one RPC = one whole simulation run (or sweep),
    never per-round; the <1 s 10M-node budget cannot absorb per-round RPCs.
  * **No codegen** — the environment ships the grpc runtime but not
    grpc_tools, so the service uses gRPC *generic method handlers* with
    JSON payloads over raw bytes: real gRPC/HTTP-2 framing, zero .proto
    compilation, and any language's grpc client can call it with a
    bytes-in/bytes-out stub on ``/gossip.Simulator/<Method>``.

Wire format: requests and responses are UTF-8 JSON.  ``Run`` takes
``{"backend": ..., "proto": {...}, "topology": {...}, "run": {...},
"fault": {...}|null, "mesh": {...}|null, "curve": bool}`` (field names =
the config dataclasses, validated strictly) and returns a RunReport dict.
``Ensemble`` takes the same minus curve/mesh plus ``seeds`` or
``ensemble`` (count) and returns seed-ensemble statistics (round 4 —
incl. SWIM detection-latency distributions).  ``Health`` returns
backend/device facts.

Serving under load: ``serve(batching=ServingConfig(...))`` turns on the
admission-batching layer (rpc/batcher — docs/SERVING.md): concurrent
compatible requests coalesce into one device-resident megabatch per
tick, replies carry ``meta["batch"]`` metadata (including the loud
``batched: false`` label + reason on solo fallthroughs), client
timeouts bound queue wait + run (DEADLINE_EXCEEDED past them), and the
queue cap rejects with RESOURCE_EXHAUSTED.  Error hygiene: a malformed
request — bad JSON, a non-object payload, unknown fields — is always
INVALID_ARGUMENT with a one-line message, never a stringified
traceback; ``SidecarClient`` raises such replies immediately (a
well-formed error is never retried).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from concurrent import futures
from typing import Optional, Tuple

import grpc

from gossip_tpu.config import ServingConfig

SERVICE = "gossip.Simulator"

# The one gRPC metadata key of the tracing plane: SidecarClient mints a
# trace id per logical request (telemetry.new_trace_id) and sends it
# here; the router reads it, stamps its dispatch spans with it, and
# FORWARDS the same key to the replica it picks, so client -> router ->
# replica batcher all ledger the one id (docs/OBSERVABILITY.md
# "Request tracing").  Lowercase per gRPC metadata rules.
TRACE_KEY = "gossip-trace-id"


def trace_id_of(context) -> Optional[str]:
    """The request's trace id from its gRPC invocation metadata, or
    None (an untraced caller — every event gate below is conditional,
    so untraced requests cost nothing and ledger nothing new)."""
    try:
        md = context.invocation_metadata()
    except Exception:
        return None
    for item in md or ():
        if item[0] == TRACE_KEY:
            return str(item[1])
    return None


def trace_metadata(trace_id: Optional[str]):
    """Outgoing-metadata tuple carrying ``trace_id`` (None passes
    through: grpc treats metadata=None as no metadata)."""
    if trace_id is None:
        return None
    return ((TRACE_KEY, trace_id),)

# Exceptions a malformed/invalid request may legitimately raise while
# being parsed/validated/run — each becomes INVALID_ARGUMENT with a
# ONE-LINE message (never a stringified traceback: the client sees the
# first line of the error, the server log keeps the rest).
_BAD_REQUEST = (ValueError, TypeError, KeyError, AttributeError,
                json.JSONDecodeError)


def _one_line(e: BaseException) -> str:
    """The first line of an error, bounded — the whole client-visible
    error contract (tested: a malformed request must never ship a
    traceback over the wire)."""
    msg = str(e) or type(e).__name__
    return msg.splitlines()[0][:400]


def _parse_obj(request: bytes) -> dict:
    """UTF-8 JSON *object* or ValueError — a JSON list/string/number
    would otherwise hit attribute errors deep in the config layer and
    surface as a traceback instead of INVALID_ARGUMENT."""
    req = json.loads(request)
    if not isinstance(req, dict):
        raise ValueError("request must be a JSON object, got "
                         f"{type(req).__name__}")
    return req


def _identity(b: bytes) -> bytes:
    return b


def _await_batched(pending, context) -> bytes:
    """Block the handler thread on the megabatch reply; map the
    serving-layer rejections to their gRPC codes (rpc/batcher):
    Expired -> DEADLINE_EXCEEDED (admitted but not run in time),
    anything else -> INTERNAL with a one-line reason."""
    from gossip_tpu.rpc import batcher as B
    try:
        return json.dumps(pending.wait()).encode()
    except B.Expired as e:
        context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, _one_line(e))
    except B.BatchError as e:
        context.abort(grpc.StatusCode.INTERNAL, _one_line(e))


def _solo_trace(trace_id: Optional[str], req_kind: str, run_ms: float,
                note: Optional[str]):
    """The solo path's terminal replica-side ``request_trace``: no
    queue, so queue_wait is structurally zero and batch_run is the
    whole solo dispatch.  sync=False — this emit sits inside the
    handler's measured window (the driver_timing discipline)."""
    if trace_id is None:
        return
    from gossip_tpu.utils import telemetry
    telemetry.current().event(
        "request_trace", sync=False, trace_id=trace_id,
        source="replica", req_kind=req_kind, batched=False,
        solo_reason=note, queue_wait_ms=0.0,
        batch_run_ms=round(run_ms, 1))


def _run(request: bytes, context, batcher=None) -> bytes:
    from gossip_tpu.backend import request_to_args, run_simulation
    trace_id = trace_id_of(context)
    try:
        args = request_to_args(_parse_obj(request))
    except _BAD_REQUEST as e:
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, _one_line(e))
    note = None
    if batcher is not None:
        from gossip_tpu.rpc import batcher as B
        try:
            pending, note = batcher.submit_run(args,
                                               B.deadline_of(context),
                                               trace_id=trace_id)
        except B.QueueFull as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          _one_line(e))
        except B.TooLarge as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          _one_line(e))
        except B.Closed as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, _one_line(e))
        if pending is not None:
            return _await_batched(pending, context)
    t0 = time.monotonic()
    try:
        report = run_simulation(**args)
    except (ValueError, TypeError) as e:
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, _one_line(e))
    _solo_trace(trace_id, "run", (time.monotonic() - t0) * 1e3, note)
    out = report.to_dict()
    if batcher is not None:
        # the solo fallthrough under a batching sidecar is loudly
        # labeled with WHY it did not coalesce (docs/SERVING.md)
        out["meta"]["batch"] = {"batched": False, "reason": note}
    return json.dumps(out).encode()


def _ensemble(request: bytes, context, batcher=None) -> bytes:
    """Seed-ensemble statistics in one call (still coarse-grained: one
    RPC = one batched XLA program).  Request = the Run fields minus
    ``curve``/``mesh``, plus ``seeds`` (list of ints) or ``ensemble``
    (count, seeded run.seed + i); response = {"ensemble": summary,
    mode-specific keys...} exactly like the CLI's --ensemble output.
    Under an admission-batching sidecar, each seed rides one megabatch
    lane next to concurrent Run requests of the same batch key."""
    from gossip_tpu.backend import request_to_args, run_ensemble
    trace_id = trace_id_of(context)
    try:
        req = _parse_obj(request)
        seeds = req.pop("seeds", None)
        count = req.pop("ensemble", None)
        if (seeds is None) == (count is None):
            raise ValueError("pass exactly one of 'seeds' (list) or "
                             "'ensemble' (count)")
        # coerce HERE, inside the INVALID_ARGUMENT net: a malformed
        # seed list must get the one-line error on the batched path
        # too, not an uncaught int() failure deep in the batcher
        if seeds is not None:
            seeds = [int(s) for s in seeds]
        if count is not None:
            count = int(count)
        args = request_to_args(req)
        if args["backend"] != "jax-tpu":
            raise ValueError("ensembles need the jax-tpu backend")
        if args.get("log_cfg") is not None:
            raise ValueError("the Ensemble RPC does not run the log "
                             "workload; use Run (one log program per "
                             "call)")
        if args.get("txn_cfg") is not None:
            raise ValueError("the Ensemble RPC does not run the txn "
                             "workload; use Run (one write program "
                             "per call)")
        if args["mesh_cfg"] is not None:
            raise ValueError("the Ensemble RPC is single-process "
                             "single-device; shard seed axes via the "
                             "library API")
        if args["want_curve"]:
            raise ValueError("the Ensemble RPC returns summary "
                             "statistics, not curves; drop 'curve' "
                             "(bands are a CLI --save-curve feature)")
    except _BAD_REQUEST as e:
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, _one_line(e))
    note = None
    if batcher is not None:
        from gossip_tpu.rpc import batcher as B
        try:
            pending, note = batcher.submit_ensemble(
                args, seeds, count, B.deadline_of(context),
                trace_id=trace_id)
        except B.QueueFull as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          _one_line(e))
        except B.TooLarge as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          _one_line(e))
        except B.Closed as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, _one_line(e))
        if pending is not None:
            return _await_batched(pending, context)
    t0 = time.monotonic()
    try:
        # the payload-workload keys are always present in the parsed
        # args (request_to_args emits them as None when absent) and
        # were rejected above when set — run_ensemble takes neither
        run_args = {k: v for k, v in args.items()
                    if k not in ("backend", "mesh_cfg", "want_curve",
                                 "log_cfg", "txn_cfg")}
        ens, extra = run_ensemble(seeds=seeds, count=count, **run_args)
        out = {"ensemble": ens.summary(), "mode": args["proto"].mode,
               "n": args["tc"].n, **extra}
    except (ValueError, TypeError) as e:
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, _one_line(e))
    _solo_trace(trace_id, "ensemble", (time.monotonic() - t0) * 1e3,
                note)
    if batcher is not None:
        out["batch"] = {"batched": False, "reason": note}
    return json.dumps(out).encode()


def _health(request: bytes, context, batcher=None) -> bytes:
    import jax
    return json.dumps({
        "ok": True,
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        # the megabatch mesh width this replica actually serves with —
        # the fleet's devices_per_replica refusal probes THIS field, so
        # a child that came up with a degraded mesh cannot hide behind
        # a healthy raw device count (rpc/router.Fleet)
        "serving_devices": (batcher.devices if batcher is not None
                            else 1),
        "service": SERVICE,
    }).encode()


def _backend_compiles() -> Optional[int]:
    """This process's cumulative backend-compile count from the shared
    JitCompileMonitor, or None when unknowable.  Guarded on jax being
    ALREADY imported: a Metrics poll must never be the thing that
    initializes a backend (the telemetry record_runtime rule) — and a
    jax-less process truthfully has zero compiles."""
    if "jax" not in sys.modules:
        return 0
    try:
        from gossip_tpu.rpc.batcher import _monitor
        mon = _monitor()
        return mon.backend_compiles if mon.durations_available else None
    except Exception:
        return None


def _metrics(request: bytes, context, batcher=None, window=None,
             state=None, lock=None) -> bytes:
    """The replica's live-metrics reply (the fleet plane's per-replica
    leaf — docs/OBSERVABILITY.md "Live fleet metrics"): the rolling
    request window (rps + p50/p95/p99 over ``window_s``), in-flight
    gauge, cumulative + since-last-poll backend compiles, and the
    ambient ledger's fsync count (the zero-new-fsyncs-in-the-timed-
    path verification hook).  Read-only and cheap: no jax init, no
    device transfer, no ledger write."""
    from gossip_tpu.utils import compile_cache, telemetry
    snap = window.snapshot() if window is not None else {}
    compiles = _backend_compiles()
    inflight = 0
    delta = None
    if state is not None and lock is not None:
        with lock:
            inflight = state["inflight"]
            if compiles is not None:
                delta = compiles - state["last_compiles"]
                state["last_compiles"] = compiles
    reply = {
        "ok": True,
        "service": SERVICE,
        "role": "replica",
        "serving_devices": (batcher.devices if batcher is not None
                            else 1),
        "inflight": inflight,
        "window": snap,
        "compiles_total": compiles,
        "compiles_delta": delta,
        "ledger_fsyncs": getattr(telemetry.current(), "fsyncs", 0),
    }
    # last-compile attribution (the cost plane's per-replica leaf):
    # absent-not-wrong — before the first chokepoint compile there is
    # NO last_compile key, never a fabricated empty one
    last = compile_cache.last_compile()
    if last is not None:
        reply["last_compile"] = {"label": last.get("label"),
                                 "cache": last.get("cache"),
                                 "compile_ms": last.get("compile_ms"),
                                 "peak_bytes": last.get("peak_bytes")}
    return json.dumps(reply).encode()


def _maybe_init_distributed(batching: Optional[ServingConfig]):
    """The cross-host path: one logical replica spanning processes via
    ``jax.distributed.initialize`` (SNIPPETS.md [1]/[2] — "run
    computations across all available devices across processes").
    Driven entirely by ServingConfig's coordinator/num_processes/
    process_id; the degenerate ``num_processes == 1`` case (the
    default) skips initialization and runs everywhere, single-process
    multi-device included.  Must run before the first jax use in this
    process — serve() calls it before constructing the Batcher (whose
    mesh enumerates devices).  Idempotence: a second initialize in one
    process is a jax error, so a re-serve in-process keeps num_processes
    at 1 (tests, the load harness)."""
    if batching is None or batching.num_processes <= 1:
        return
    import jax
    jax.distributed.initialize(
        coordinator_address=batching.coordinator,
        num_processes=batching.num_processes,
        process_id=batching.process_id)


def serve(port: int = 50051, max_workers: int = 4,
          host: str = "127.0.0.1",
          batching: Optional[ServingConfig] = None
          ) -> Tuple[grpc.Server, int]:
    """Start the sidecar; returns (server, bound_port).  port=0 picks a
    free port (tests).

    ``batching`` enables the admission-batching serving layer
    (rpc/batcher): concurrent batchable Run/Ensemble requests coalesce
    into one device-resident megabatch per collector tick, solo
    fallthroughs are labeled in ``meta["batch"]``, deadlines bound
    queue wait + run, and admissions past the queue cap get
    RESOURCE_EXHAUSTED.  ``None`` (the default) keeps today's
    per-request solo dispatch byte for byte.  With batching on,
    ``max_workers`` bounds the number of requests that can WAIT on a
    tick concurrently — size it at least to the expected concurrency.
    ``batching.devices > 1`` shards each tick's megabatch over a 1-D
    device mesh (rpc/batcher mesh dispatch); ``batching.num_processes
    > 1`` first joins the jax.distributed topology so one logical
    replica spans processes (docs/SERVING.md "Mesh-sharded replicas").
    The collector is a daemon thread; ``server.gossip_batcher.close()``
    drains it (tests, the load harness)."""
    from gossip_tpu.utils import telemetry
    batcher = None
    if batching is not None:
        _maybe_init_distributed(batching)
        from gossip_tpu.rpc.batcher import Batcher
        batcher = Batcher(batching)
    # the live-metrics plane: one rolling window + inflight gauge per
    # replica, read by the Metrics RPC (and through it the router's
    # fleet fan-out and `gossip_tpu fleet-status`)
    window = telemetry.MetricsWindow()
    mstate = {"inflight": 0, "last_compiles": 0}
    mlock = threading.Lock()

    def _observed(fn):
        """Record every Run/Ensemble into the rolling window (latency
        + inflight), success or abort — a replica that only aborts
        still shows traffic."""
        def handler(req, ctx):
            t0 = time.perf_counter()
            with mlock:
                mstate["inflight"] += 1
            try:
                return fn(req, ctx)
            finally:
                with mlock:
                    mstate["inflight"] -= 1
                window.record((time.perf_counter() - t0) * 1e3)
        return handler

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    handlers = {
        "Run": grpc.unary_unary_rpc_method_handler(
            _observed(lambda req, ctx: _run(req, ctx, batcher)),
            request_deserializer=_identity,
            response_serializer=_identity),
        "Ensemble": grpc.unary_unary_rpc_method_handler(
            _observed(lambda req, ctx: _ensemble(req, ctx, batcher)),
            request_deserializer=_identity,
            response_serializer=_identity),
        "Health": grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: _health(req, ctx, batcher),
            request_deserializer=_identity,
            response_serializer=_identity),
        "Metrics": grpc.unary_unary_rpc_method_handler(
            lambda req, ctx: _metrics(req, ctx, batcher, window,
                                      mstate, mlock),
            request_deserializer=_identity,
            response_serializer=_identity),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0 and port != 0:      # grpc's bind-failure sentinel
        raise OSError(f"could not bind {host}:{port} (port in use?)")
    server.start()
    server.gossip_batcher = batcher
    server.gossip_metrics = window
    return server, bound


# Status codes that mark a TRANSIENT transport failure — the server
# was unreachable or the connection died, so nothing was processed and
# a retry is safe.  A WELL-FORMED error reply (INVALID_ARGUMENT from
# the handlers' context.abort, INTERNAL, etc.) means the server DID
# process the call and said no: retrying it is never correct, exactly
# as maelstrom_node treats an error reply as a failed delivery rather
# than a lost one (runtime/maelstrom_node.gossip).
_TRANSIENT_CODES = frozenset({grpc.StatusCode.UNAVAILABLE})


class SidecarClient:
    """Typed client over the JSON-bytes wire (usable from any grpc client
    in any language the same way).

    Transient transport failures (UNAVAILABLE — server starting up,
    connection reset; plus DEADLINE_EXCEEDED for the cheap idempotent
    ``health`` probe only, whose timeout is not workload-dependent)
    are retried with capped jittered exponential backoff
    (``max_attempts`` overflow guard, no sleep after the last try).
    The caller's ``timeout`` is the TOTAL retry budget, not a
    per-attempt allowance: each attempt's deadline is clamped to the
    remaining budget and an exhausted budget re-raises instead of
    dispatching again (the fleet-PR contract — previously each attempt
    got a fresh deadline, so a dying server could stretch one call to
    attempts x timeout).  Under that rule a probe that consumed its
    whole budget in a DEADLINE_EXCEEDED is re-raised immediately; the
    code stays in ``health``'s retryable set for transport stacks that
    surface it early, with budget to spare.  Each retry emits an
    ``rpc_retry`` event on the ambient run ledger
    (utils/telemetry.current) so a flaky transport is flight-recorded,
    never silent.  Well-formed error replies are raised immediately."""

    def __init__(self, address: str, max_attempts: int = 4,
                 backoff_base: float = 0.1, backoff_cap: float = 2.0):
        if max_attempts < 1:
            raise ValueError(f"max_attempts={max_attempts} must be >= 1")
        self._channel = grpc.insecure_channel(address)
        self.max_attempts = max_attempts
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._run = self._channel.unary_unary(
            f"/{SERVICE}/Run", request_serializer=_identity,
            response_deserializer=_identity)
        self._ensemble = self._channel.unary_unary(
            f"/{SERVICE}/Ensemble", request_serializer=_identity,
            response_deserializer=_identity)
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health", request_serializer=_identity,
            response_deserializer=_identity)
        self._metrics = self._channel.unary_unary(
            f"/{SERVICE}/Metrics", request_serializer=_identity,
            response_deserializer=_identity)

    def _call_with_retry(self, call, payload: bytes, timeout,
                         method: str, retryable=_TRANSIENT_CODES,
                         metadata=None, trace_id=None):
        """One RPC with the retry contract above.  ``retryable`` is the
        status-code set that marks a transport (not application)
        failure.

        Retry BUDGET: ``timeout`` is the caller's TOTAL wall budget
        across all attempts, not a per-attempt allowance — every
        attempt's deadline is clamped to the remaining budget (the
        last attempt gets exactly what is left, test-pinned), backoff
        sleeps never overrun it, and a budget exhausted between
        attempts re-raises the last transport error instead of
        starting an attempt the caller already gave up on.  Without
        the clamp a dying replica could stretch one call to
        ``max_attempts x timeout`` — exactly the stall a fleet
        failover deadline cannot absorb."""
        import random
        import time as _time

        from gossip_tpu.utils import telemetry
        deadline = (None if timeout is None
                    else _time.monotonic() + float(timeout))
        for attempt in range(self.max_attempts):
            attempt_timeout = timeout
            if deadline is not None:
                attempt_timeout = deadline - _time.monotonic()
                if attempt > 0 and attempt_timeout <= 0:
                    # budget spent by earlier attempts/backoff — the
                    # caller abandoned this call; surface the last
                    # transport failure rather than dispatch again
                    raise last_error
            try:
                return call(payload, timeout=attempt_timeout,
                            metadata=metadata)
            except grpc.RpcError as e:
                last_error = e
                code = e.code() if callable(getattr(e, "code", None)) \
                    else None
                if code not in retryable \
                        or attempt + 1 >= self.max_attempts:
                    raise
                # full jitter on the capped exponential step: herds of
                # clients racing a restarting sidecar must not resync
                sleep = (min(self.backoff_base * (2 ** attempt),
                             self.backoff_cap)
                         * (0.5 + random.random()))
                if deadline is not None:
                    sleep = min(sleep,
                                max(0.0, deadline - _time.monotonic()))
                telemetry.current().event(
                    "rpc_retry", sync=False, method=method,
                    attempt=attempt + 1, code=str(code),
                    sleep_s=round(sleep, 3), trace_id=trace_id)
                _time.sleep(sleep)
        raise AssertionError("unreachable: loop returns or raises")

    def run(self, timeout: Optional[float] = 600.0,
            trace_id: Optional[str] = None, **request) -> dict:
        """One simulation.  kwargs mirror the JSON request fields:
        backend, proto, topology, run, fault, mesh, curve.

        Every call carries a trace id in gRPC metadata (minted here
        unless the caller supplies one — capture tools pass their own
        so they can join the waterfall afterwards); the reply bytes
        stay untouched (the router's transparent-bytes contract), the
        correlation lives entirely in metadata + ledgers."""
        from gossip_tpu.utils import telemetry
        tid = trace_id or telemetry.new_trace_id()
        return json.loads(self._call_with_retry(
            self._run, json.dumps(request).encode(), timeout, "run",
            metadata=trace_metadata(tid), trace_id=tid))

    def ensemble(self, timeout: Optional[float] = 600.0,
                 trace_id: Optional[str] = None, **request) -> dict:
        """Seed-ensemble statistics; kwargs mirror the Run fields plus
        seeds=[...] or ensemble=count.  Trace-id contract as in
        :meth:`run`."""
        from gossip_tpu.utils import telemetry
        tid = trace_id or telemetry.new_trace_id()
        return json.loads(self._call_with_retry(
            self._ensemble, json.dumps(request).encode(), timeout,
            "ensemble", metadata=trace_metadata(tid), trace_id=tid))

    def health(self, timeout: float = 10.0) -> dict:
        return json.loads(self._call_with_retry(
            self._health, b"{}", timeout, "health",
            retryable=_TRANSIENT_CODES
            | {grpc.StatusCode.DEADLINE_EXCEEDED}))

    def metrics(self, timeout: float = 10.0) -> dict:
        """The live-metrics snapshot: a replica answers for itself
        (rps/percentiles/inflight/compiles/fsyncs); a router answers
        for the whole fleet (its own dispatch window + one row per
        replica — rpc/router Metrics fan-out).  Untraced: a metrics
        poll is not a request."""
        return json.loads(self._call_with_retry(
            self._metrics, b"{}", timeout, "metrics",
            retryable=_TRANSIENT_CODES
            | {grpc.StatusCode.DEADLINE_EXCEEDED}))

    def close(self) -> None:
        self._channel.close()
