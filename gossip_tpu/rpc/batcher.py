"""Admission batching: coalesce concurrent sidecar RPCs into one
device-resident megabatch per tick.

The reference program is a *server* — one node answering an open-ended
stream of client RPCs under the Maelstrom harness (PAPER.md §1) — yet
until this layer the gRPC sidecar ran every ``Run``/``Ensemble``
request as a solo dispatch.  This module is the continuous-batching
layer LLM inference stacks use, applied to simulation serving: in-flight
requests enqueue with a deadline, a collector loop drains the queue
every tick, and requests with compatible static structure run as ONE
compiled megabatch (parallel/sweep.request_sweep_curves) while
incompatible requests fall through to the solo path, loudly labeled in
the reply.

Batch key (memo key) vs operand — the serving analog of the nemesis
schedule contract (ops/nemesis module doc).  Two requests share an
executable iff they agree on everything the TRACE bakes:

  ============================  =====================================
  memo key (static, batch key)  runtime operand (varies per request)
  ============================  =====================================
  pow2 n-bucket                 n itself (traced peer bound)
  topology (explicit families:  —
    the exact TopologyConfig;
    implicit complete: family
    only, n via the bucket)
  fanout (the shared draw        mode (do_push/do_pull/do_ae flags)
    width — the solo-bitwise
    contract, RequestSpec doc)
  pow2 rumor bucket             rumors itself (phantom-column mask)
  max_rounds (scan length)      target_coverage (host-side readout)
  exclude_self                  seed, origin (key + seen operands)
  mesh width (ServingConfig     drop_prob (the drop table)
    .devices: the 1-D request-
    axis mesh; 1 = solo path)
  —                             static death mask (alive operands)
  —                             the whole churn schedule
                                  (nemesis.build_request_stack)
  ============================  =====================================

Mesh-sharded dispatch (the perf PR): when ``ServingConfig.devices > 1``
the collector dispatches each tick's megabatch onto a 1-D device mesh
over the request axis (request_sweep_curves ``mesh=``) instead of the
solo single-device path.  The mesh itself never enters the scan memo
key — jit re-specializes on input shardings — and the replica uses ONE
mesh for its lifetime, so the executable cache stays one-per-(key,
lane-bucket) exactly as on the solo path.  Lane buckets are padded up
to the device count (both powers of two, so every bucket divides the
mesh evenly); requests padded to the bucket ride inert rows.  Replies
stay bitwise equal to solo dispatch: the sharded scan computes the
same integer counts per lane and the host readout is unchanged.  The
batcher REFUSES at construction when the process has fewer devices
than configured — a mesh silently degrading to 1 device is the failure
mode the fleet's devices_per_replica gate exists to catch.

Everything else about the serving queue (tick cadence, per-tick batch
cap, backpressure depth) lives in :class:`~gossip_tpu.config
.ServingConfig`.  Deadlines: the client's RPC timeout must bound queue
wait + run, so a request admitted but expired before its tick is
rejected with DEADLINE_EXCEEDED (and ledgered) instead of silently run
late.  Backpressure: an admission past ``max_queue`` lanes is rejected
with RESOURCE_EXHAUSTED immediately.

Telemetry: one ``batch`` event per executed group on the ambient run
ledger (utils/telemetry) — queue depth at drain, batch size/lanes,
wait/run walls, and the compile verdict (backend-compile delta around
the megabatch: steady-state serving must be ``warm``) — rendered by
tools/batching_report.py and gated by tools/load_harness.py.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from functools import lru_cache
from typing import Optional, Tuple

from gossip_tpu import config as C
from gossip_tpu.config import ServingConfig, TopologyConfig

BATCHABLE_MODES = (C.PUSH, C.PULL, C.PUSH_PULL, C.ANTI_ENTROPY)


class BatchError(Exception):
    """Base class for serving-layer rejections (the handler maps each
    subclass to its gRPC status code)."""


class QueueFull(BatchError):
    """Backpressure: the admission queue is at ``max_queue`` lanes."""


class TooLarge(BatchError):
    """The request needs more lanes than ``max_batch`` — it could never
    be scheduled (it would cycle through the leftover queue forever),
    so admission refuses it up front; the handler maps this to
    INVALID_ARGUMENT."""


class Closed(BatchError):
    """The batcher is shut down (``close()``): no collector will ever
    drain this queue again, so admission refuses instead of stranding
    the handler thread on an event nobody will set; the handler maps
    this to UNAVAILABLE (a transient the client may retry against a
    restarted server)."""


class Expired(BatchError):
    """The request's deadline passed before its batch tick ran."""


@dataclasses.dataclass(frozen=True)
class BatchKey:
    """The compiled-executable identity of a batchable request — see
    the module-doc memo-key vs operand table.  Requests coalesce iff
    their keys are equal."""
    n_bucket: int
    rounds: int
    fanout: int
    rumor_bucket: int
    topology: Optional[TopologyConfig]   # None = implicit complete

    def describe(self) -> dict:
        return {"n_bucket": self.n_bucket, "rounds": self.rounds,
                "fanout": self.fanout,
                "rumor_bucket": self.rumor_bucket,
                "topology": (self.topology.family
                             if self.topology is not None
                             else "complete")}


def deadline_of(context) -> Optional[float]:
    """The request's absolute monotonic deadline from its gRPC context
    (None = no client timeout).  This is what makes the client timeout
    bound queue wait + run: the collector refuses to run a request
    whose deadline already passed."""
    rem = context.time_remaining()
    if rem is None:
        return None
    return time.monotonic() + float(rem)


def classify_run(args):
    """``(key, spec, want_curve)`` for a batchable Run request, or
    ``(None, reason, None)`` naming the first incompatibility — the
    reason lands verbatim in the solo reply's ``meta["batch"]`` so a
    fallthrough is always loudly labeled."""
    from gossip_tpu.parallel.sweep import RequestSpec, _pow2_at_least
    if args["backend"] != "jax-tpu":
        return None, f"backend={args['backend']}", None
    if args.get("log_cfg") is not None:
        # the replicated-log workload carries its own payload state +
        # injection operands (ops/logs) — not a megabatch lane shape
        # the SI request sweep can host; it dispatches solo, loudly
        # labeled (the PR 9 fall-through contract)
        return None, "log workload dispatches solo", None
    if args.get("txn_cfg") is not None:
        # the LWW-register transaction workload carries its own payload
        # state + write operands (ops/registers) — same solo rule
        return None, "txn workload dispatches solo", None
    if args["mesh_cfg"] is not None:
        return None, "mesh requests dispatch solo", None
    run, proto, tc = args["run"], args["proto"], args["tc"]
    if run.engine not in ("auto", "xla"):
        return None, f"engine={run.engine}", None
    if proto.mode not in BATCHABLE_MODES:
        return None, f"mode={proto.mode}", None
    fault = args["fault"]
    if fault is not None and (fault.dead_nodes or fault.fail_round):
        # SWIM-scripted scenario fields: the SI solo path defines their
        # (no-op) meaning; keep that single source of truth
        return None, "swim-scripted fault fields", None
    if run.engine == "auto":
        # on a TPU the solo auto-route picks the fused Pallas engine
        # for eligible runs (hardware PRNG — a DIFFERENT trajectory
        # than the XLA megabatch); batching such a request would
        # silently break the bitwise solo-dispatch contract, so it
        # falls through to the solo path (labeled).  On CPU this is
        # never true and auto requests batch normally.  Since the
        # fused-operand PR this fall-through is also CHEAP for
        # fault-bearing sweeps: the solo fused drivers consume the
        # drop threshold and fault masks as runtime operands, so a
        # client sweeping drop rates / death rates over auto re-enters
        # one fused executable instead of paying a Mosaic recompile
        # per scenario — the batcher no longer needs to steer such
        # sweeps away from the fused route for compile-amortization
        # reasons (only the bitwise contract keeps them solo).
        from gossip_tpu.backend import _fused_auto_ok
        if _fused_auto_ok(proto, tc, fault):
            return None, "engine=auto routes to the fused engine", None
    try:
        spec = RequestSpec(proto, run, fault, tc.n)
        if fault is not None:
            from gossip_tpu.ops import nemesis as NE
            # per-request content validation HERE, not at execution:
            # an out-of-range churn event must fail ITS request (the
            # solo path's INVALID_ARGUMENT via the fallthrough), never
            # poison a whole megabatch with INTERNAL
            NE.validate_events(fault, tc.n)
    except ValueError as e:
        return None, str(e).splitlines()[0], None
    if tc.family == C.COMPLETE:
        topo_key = None
        n_bucket = _pow2_at_least(tc.n, 2)
    else:
        topo_key = tc
        n_bucket = tc.n
    key = BatchKey(n_bucket=n_bucket, rounds=run.max_rounds,
                   fanout=proto.fanout,
                   rumor_bucket=_pow2_at_least(proto.rumors),
                   topology=topo_key)
    return key, spec, bool(args["want_curve"])


def classify_ensemble(args, seeds, count):
    """``(key, specs)`` for a batchable Ensemble request (one spec per
    seed — ensemble members ride the same megabatch lanes as Run
    requests of the same key), or ``(None, reason)``."""
    run = args["run"]
    if seeds is None:
        seeds = [run.seed + i for i in range(int(count))]
    seeds = [int(s) for s in seeds]
    if not seeds:
        return None, "empty seed list"
    probe = dict(args)
    probe["want_curve"] = False
    key, first, _ = classify_run(probe)
    if key is None:
        return None, first
    specs = [dataclasses.replace(
        first, run=dataclasses.replace(run, seed=s)) for s in seeds]
    return key, tuple(specs)


@lru_cache(maxsize=8)
def _topo_for(tc: Optional[TopologyConfig]):
    """The shared explicit table for a batch key (None for the
    implicit complete family) — built once per config, reused across
    ticks."""
    if tc is None:
        return None
    from gossip_tpu.topology import generators as G
    return G.build(tc)


_MONITOR = None
_MONITOR_LOCK = threading.Lock()


def _monitor():
    """Process-wide JitCompileMonitor (listener registration is
    permanent — utils/compile_cache doc — so never one per Batcher)."""
    global _MONITOR
    with _MONITOR_LOCK:
        if _MONITOR is None:
            from gossip_tpu.utils.compile_cache import JitCompileMonitor
            _MONITOR = JitCompileMonitor()
        return _MONITOR


class _Pending:
    """One admitted request waiting on its batch tick."""

    __slots__ = ("kind", "key", "specs", "want_curve", "deadline",
                 "enq_t", "event", "reply", "error", "trace_id")

    def __init__(self, kind, key, specs, want_curve, deadline,
                 trace_id=None):
        self.kind = kind                  # "run" | "ensemble"
        self.key = key
        self.specs = specs                # tuple[RequestSpec]
        self.want_curve = want_curve
        self.deadline = deadline          # absolute monotonic or None
        self.trace_id = trace_id          # request correlation id
        self.enq_t = time.monotonic()
        self.event = threading.Event()
        self.reply = None
        self.error: Optional[BaseException] = None

    def wait(self) -> dict:
        self.event.wait()
        if self.error is not None:
            raise self.error
        return self.reply


class Batcher:
    """The admission queue + collector loop (module doc).  One
    instance per serving sidecar; ``close()`` drains and stops the
    collector thread (it is a daemon, so process exit never hangs on
    it)."""

    def __init__(self, cfg: Optional[ServingConfig] = None):
        self.cfg = cfg or ServingConfig()
        # the replica's megabatch mesh, built ONCE for the batcher's
        # lifetime (one mesh -> one sharding per shape -> the
        # executable cache stays one-per-(key, lane-bucket)); devices=1
        # is the solo single-device path with no mesh at all
        self.devices = self.cfg.devices
        self._mesh = self._build_mesh(self.cfg.devices)
        self._lock = threading.Lock()
        self._queue = []          # [(BatchKey, _Pending)], FIFO
        self._stop = threading.Event()
        self._tick = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="gossip-admission-batcher",
                                        daemon=True)
        self._thread.start()

    @staticmethod
    def _build_mesh(devices: int):
        """The replica's 1-D request-axis mesh, or None for the solo
        path.  Refuses LOUDLY when the process has fewer devices than
        configured: a replica pinned to CPU without the host-device-
        count env would otherwise serve a silently degraded mesh (the
        devices_per_replica satellite)."""
        if devices <= 1:
            return None
        import jax
        from jax.sharding import Mesh
        devs = jax.devices()
        if len(devs) < devices:
            raise ValueError(
                f"ServingConfig.devices={devices} but this process has "
                f"only {len(devs)} JAX device(s) — the megabatch mesh "
                "would silently degrade; launch with XLA_FLAGS="
                f"--xla_force_host_platform_device_count={devices} "
                "(CPU) or on a host with enough accelerator devices")
        return Mesh(devs[:devices], ("request",))

    # -- admission -----------------------------------------------------

    def _admit(self, pending: _Pending) -> _Pending:
        if len(pending.specs) > self.cfg.max_batch:
            # an oversized request can NEVER be scheduled (every tick
            # would defer it back to the leftovers) — refuse at
            # admission instead of hanging its handler forever
            raise TooLarge(
                f"request needs {len(pending.specs)} megabatch lanes "
                f"but max_batch is {self.cfg.max_batch}; split the "
                "ensemble or raise the server's batch cap")
        with self._lock:
            # the stop check lives INSIDE the queue lock (shutdown-race
            # pin, tests/test_serving.py): close() sets the flag and
            # THEN flushes, so any admission serialized after the flag
            # refuses here with Closed (-> UNAVAILABLE) while any
            # admission serialized before it is already in the queue
            # the final drain flushes — a draining replica rejects new
            # work BEFORE flushing queued work, and no request can
            # land in a queue nobody will ever drain again
            if self._stop.is_set():
                raise Closed("sidecar batcher is shut down")
            depth = sum(len(p.specs) for _, p in self._queue)
            if depth + len(pending.specs) > self.cfg.max_queue:
                from gossip_tpu.utils import telemetry
                telemetry.current().event(
                    "backpressure", sync=False, queue_depth=depth,
                    rejected_lanes=len(pending.specs),
                    max_queue=self.cfg.max_queue,
                    trace_id=pending.trace_id)
                raise QueueFull(
                    f"admission queue full ({depth}/"
                    f"{self.cfg.max_queue} lanes); back off and retry")
            self._queue.append((pending.key, pending))
        if pending.trace_id is not None:
            # the admission span: queue depth at entry + the lane
            # count this request will occupy; its queue-wait closes in
            # the terminal request_trace (sync=False — admission runs
            # inside the handler's measured window)
            from gossip_tpu.utils import telemetry
            telemetry.current().event(
                "trace_admit", sync=False, trace_id=pending.trace_id,
                req_kind=pending.kind, lanes=len(pending.specs),
                queue_depth=depth)
        return pending

    def submit_run(self, args, deadline,
                   trace_id=None) -> Tuple[Optional[_Pending],
                                           Optional[str]]:
        """Admit a Run request: ``(pending, None)`` when batchable
        (caller blocks on ``pending.wait()``), ``(None, reason)`` for
        the solo fallthrough.  Raises :class:`QueueFull` at the
        backpressure cap.  ``trace_id`` rides the pending through the
        tick so the batch event and the terminal request_trace carry
        it (docs/OBSERVABILITY.md)."""
        key, spec, want_curve = classify_run(args)
        if key is None:
            return None, spec
        return self._admit(_Pending("run", key, (spec,), want_curve,
                                    deadline, trace_id)), None

    def submit_ensemble(self, args, seeds, count, deadline,
                        trace_id=None):
        """Ensemble twin of :meth:`submit_run` — each seed is one
        megabatch lane."""
        key, specs = classify_ensemble(args, seeds, count)
        if key is None:
            return None, specs
        return self._admit(_Pending("ensemble", key, specs, False,
                                    deadline, trace_id)), None

    # -- collector -----------------------------------------------------

    def close(self):
        """Drain ordering (the shutdown-race pin): set the stop flag
        FIRST — from this point every admission that reaches the
        in-lock check refuses with Closed/UNAVAILABLE — and flush the
        queued work SECOND.  Rejecting before flushing is what makes a
        router-initiated drain safe: an admission can never be
        appended after the final drain swapped the queue out, so no
        request is ever stranded in a closed queue."""
        self._stop.set()
        self._thread.join(timeout=10)
        # flush admissions serialized before the stop flag (their
        # in-lock check passed, so they are in the queue) — nobody
        # else will ever answer them
        self._drain_once()

    def _loop(self):
        tick_s = self.cfg.tick_ms / 1e3
        while not self._stop.wait(tick_s):
            self._drain_once()
        # final drain: submitters racing close() are answered, never
        # stranded on an event that would no longer be set
        self._drain_once()

    def _drain_once(self):
        with self._lock:
            q, self._queue = self._queue, []
        if not q:
            return
        try:
            depth = sum(len(p.specs) for _, p in q)
            now = time.monotonic()
            groups: dict = {}
            leftovers = []
            for key, p in q:
                if p.deadline is not None and now >= p.deadline:
                    self._expire(p, now)
                    continue
                entries = groups.get(key, [])
                lanes = sum(len(e.specs) for e in entries)
                if lanes + len(p.specs) > self.cfg.max_batch:
                    leftovers.append((key, p))     # next tick
                    continue
                # only materialize the group on a real append — a
                # deferred request must not leave an EMPTY group
                # behind (it would run a zero-entry megabatch)
                groups.setdefault(key, entries).append(p)
            if leftovers:
                with self._lock:
                    # keep FIFO: deferred requests go back ahead of
                    # anything admitted while we drained
                    self._queue = leftovers + self._queue
            for key, entries in groups.items():
                self._run_group(key, entries, depth)
        except BaseException as e:              # noqa: BLE001
            # the collector must NEVER die with waiters attached: a
            # bug escaping the per-group handling fails this tick's
            # requests LOUDLY (the handler maps it to INTERNAL)
            # instead of stranding their handler threads forever
            err = BatchError(
                "collector tick failed: "
                f"{type(e).__name__}: "
                + (str(e).splitlines()[0] if str(e) else ""))
            from gossip_tpu.utils import telemetry
            telemetry.current().event("batch_error", sync=False,
                                      error=str(err)[:300])
            failed = {id(p) for _, p in q}
            with self._lock:
                # leftovers re-queued earlier in this tick are part of
                # the failure sweep below — purge them, or the next
                # tick would re-run a megabatch whose handlers already
                # aborted with INTERNAL
                self._queue = [(k2, p2) for k2, p2 in self._queue
                               if id(p2) not in failed]
            for _, p in q:
                if not p.event.is_set():
                    p.error = err
                    p.event.set()

    def _expire(self, p: _Pending, now: float):
        from gossip_tpu.utils import telemetry
        wait_ms = (now - p.enq_t) * 1e3
        # field is req_kind, not kind: `kind` is Ledger.event's own
        # positional (the event name) and would collide
        telemetry.current().event(
            "deadline_exceeded", sync=False, req_kind=p.kind,
            wait_ms=round(wait_ms, 1), lanes=len(p.specs),
            trace_id=p.trace_id)
        p.error = Expired(
            "deadline expired before the batch tick ran "
            f"(waited {wait_ms:.0f} ms; the client timeout bounds "
            "queue wait + run)")
        p.event.set()

    def _run_group(self, key: BatchKey, entries, queue_depth: int):
        from gossip_tpu.parallel.sweep import (_pow2_at_least,
                                               request_sweep_curves)
        from gossip_tpu.utils import telemetry
        specs = tuple(s for e in entries for s in e.specs)
        # lane bucket padded up to the mesh width: pow2 buckets divide
        # pow2 device counts, so mesh dispatch reuses exactly the solo
        # path's bucket set (floored at `devices`) and never fragments
        # the executable cache; None keeps the solo default
        lanes = (_pow2_at_least(len(specs), self.devices)
                 if self._mesh is not None else None)
        mon = _monitor()
        before = mon.backend_compiles
        t0 = time.monotonic()
        try:
            # full=True: one executable per (key, lane bucket)
            # whatever mode mix this tick coalesced — the half-elision
            # switches are composition statics and would fragment the
            # serving cache (request_sweep_curves doc)
            res = request_sweep_curves(specs,
                                       topo=_topo_for(key.topology),
                                       n_pad=(None if key.topology
                                              is not None
                                              else key.n_bucket),
                                       mesh=self._mesh,
                                       lanes=lanes,
                                       full=True)
        except Exception as e:          # defensive: classify should
            err = BatchError(           # have filtered invalid configs
                f"batch execution failed: {type(e).__name__}: "
                + (str(e).splitlines()[0] if str(e) else ""))
            telemetry.current().event("batch_error", sync=False,
                                      error=str(err)[:300])
            for p in entries:
                p.error = err
                p.event.set()
            return
        run_ms = (time.monotonic() - t0) * 1e3
        compiles = (mon.backend_compiles - before
                    if mon.durations_available else None)
        self._tick += 1
        waits = sorted((t0 - e.enq_t) * 1e3 for e in entries)
        cache = (None if compiles is None
                 else ("warm" if compiles == 0 else "compiled"))
        batch_meta = {
            "batched": True, "tick": self._tick,
            "size": len(specs), "requests": len(entries),
            "run_ms": round(run_ms, 1), "cache": cache,
            "devices": self.devices,
            "semantics": "fixed-scan", **key.describe()}
        telemetry.current().event(
            "batch", sync=False, tick=self._tick,
            queue_depth=queue_depth, batch_size=len(specs),
            requests=len(entries),
            wait_ms_p50=round(telemetry.percentile(waits, 0.50), 1),
            wait_ms_max=round(waits[-1], 1) if waits else 0.0,
            run_ms=round(run_ms, 1), compiles=compiles, cache=cache,
            devices=self.devices,
            # the megabatch span links its member traces — the
            # tick-membership edge of the waterfall join
            trace_ids=[p.trace_id for p in entries
                       if p.trace_id is not None],
            **key.describe())
        off = 0
        for p in entries:
            k = len(p.specs)
            try:
                p.reply = (self._run_reply(p, res, off, batch_meta)
                           if p.kind == "run"
                           else self._ensemble_reply(p, res, off, k,
                                                     batch_meta))
            except Exception as e:
                p.error = BatchError(
                    f"reply assembly failed: {type(e).__name__}: {e}")
            if p.trace_id is not None:
                # the replica half of the per-request waterfall
                # (queue wait + batch run); the router half carries
                # proxy_ms/retries — tools/trace_report.py joins them
                telemetry.current().event(
                    "request_trace", sync=False, trace_id=p.trace_id,
                    source="replica", req_kind=p.kind, batched=True,
                    tick=self._tick, lanes=k, cache=cache,
                    queue_wait_ms=round((t0 - p.enq_t) * 1e3, 1),
                    batch_run_ms=round(run_ms, 1))
            off += k
            p.event.set()

    # -- replies -------------------------------------------------------

    @staticmethod
    def _run_reply(p: _Pending, res, i: int, batch_meta: dict) -> dict:
        """A RunReport-shaped dict whose curve/rounds/coverage/msgs
        equal the request's solo dispatch through the same readout
        (fixed-length-scan semantics: the ``curve=True`` solo report's
        numbers — docs/SERVING.md admission contract)."""
        spec = p.specs[0]
        curve = [float(c) for c in res.curves[i]]
        return {
            "backend": "jax-tpu", "mode": spec.proto.mode, "n": spec.n,
            "rounds": int(res.rounds_to_target[i]),
            "coverage": curve[-1],
            "msgs": float(res.msgs[i][-1]),
            "wall_s": round(batch_meta["run_ms"] / 1e3, 4),
            "curve": curve if p.want_curve else None,
            "meta": {"clock": "rounds",
                     "devices": batch_meta.get("devices", 1),
                     "msgs_counts": "transmissions",
                     "engine": "xla-request-batch",
                     "state_digest": res.state_digests[i],
                     "dropped_total": float(res.dropped[i].sum()),
                     "batch": dict(batch_meta)},
        }

    @staticmethod
    def _ensemble_reply(p: _Pending, res, off: int, k: int,
                        batch_meta: dict) -> dict:
        """The Ensemble RPC's reply shape from this request's lane
        slice — per-seed curves are bitwise the solo runs, so the
        summary equals parallel/sweep.ensemble_curves' by
        construction."""
        from gossip_tpu.parallel.sweep import EnsembleResult
        spec = p.specs[0]
        ens = EnsembleResult(
            curves=res.curves[off:off + k],
            msgs=res.msgs[off:off + k],
            rounds_to_target=res.rounds_to_target[off:off + k],
            target=spec.run.target_coverage)
        return {"ensemble": ens.summary(), "mode": spec.proto.mode,
                "n": spec.n, "batch": dict(batch_meta)}
