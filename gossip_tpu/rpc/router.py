"""Replicated sidecar serving: a fronting router with health-gated
failover over N sidecar replicas.

PAPER.md's reference node survives Maelstrom's nemesis because every
peer retries until acked; until this layer the serving story had no
such property — the admission-batched sidecar (rpc/batcher) is one
process on one device, and a SIGKILL lost every in-flight request.
This module is ROADMAP item 2(b): a router that fronts N ``serve()``
replicas, health-probes them on the existing ``SidecarClient.health``
path, routes ``Run``/``Ensemble`` to healthy replicas, and on a
replica transport failure **re-dispatches the in-flight request to a
survivor**.  The re-dispatch is safe by construction: a request is a
deterministic pure function of its payload (seeded threefry streams,
no server state), so a replay returns the bitwise-same reply — pinned
in tests/test_router.py and gated end-to-end by
tools/fleet_crashloop.py's committed record.

Contract (docs/SERVING.md "Fleet"):

  * **Transparent bytes**: the router proxies request/reply bytes
    untouched — a reply through the router is byte-identical to the
    replica's (and therefore to solo dispatch; the fleet_crashloop
    parity gate).  Failover visibility lives in the run ledger
    (``replica_down`` / ``failover`` / ``replica_up`` events), never
    in mutated replies.
  * **Failover**: only a TRANSPORT failure (UNAVAILABLE — connection
    refused/reset, the replica process died) triggers re-dispatch; any
    well-formed replica reply (INVALID_ARGUMENT, RESOURCE_EXHAUSTED
    from its batcher, INTERNAL) means the replica processed the call
    and is propagated verbatim — the SidecarClient never-retry rule,
    one layer up.
  * **Deadlines propagate end-to-end**: each dispatch attempt gets the
    client's REMAINING budget as its timeout, so a failover retry can
    never run a request its client already abandoned —
    DEADLINE_EXCEEDED is terminal, never replayed.
  * **Shed, never queue**: the router holds no queue.  When no healthy
    replica has a free in-flight slot (``FleetConfig.max_inflight``)
    the request is shed with RESOURCE_EXHAUSTED + a ``shed`` ledger
    event — bounded by construction, never a silent drop.
  * **Hysteresis**: a dispatch failure or ``down_after`` consecutive
    probe failures mark a replica down; a previously-down replica
    re-enters rotation only after ``up_after`` CONSECUTIVE healthy
    probes, so a flapping replica cannot oscillate in and out faster
    than the re-admission threshold (scripted-probe-sequence pinned).

Control plane — the fleet eats its own dogfood (ops/logs): replica
admission/config state replicates as entries on a per-replica OWNER
key of a replicated log (``LogConfig(keys=n_replicas)``), state
transitions append monotonically, and the committed offset of a
replica's key IS its config epoch.  Each replica holds a VIEW row-set
merged by the log's join (``ops.logs.merge_max`` — elementwise max
over owner-indexed slot planes, the exact kafka-log lattice), gossiped
one rotating partner per probe tick; a replica that rejoins after a
kill starts from a ZERO view and catches up from the survivors' gossip
(``control_catchup``), never from operator state.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from gossip_tpu.config import FleetConfig, LogConfig

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# Control-plane admission states, appended as log-entry values (>= 1 by
# the LogConfig contract: 0 is the empty-slot sentinel).
STATE_UP = 1
STATE_DOWN = 2
_STATE_NAMES = {STATE_UP: "up", STATE_DOWN: "down"}


class ControlPlane:
    """The fleet's replicated admission/config log (module doc).

    One ``ops/logs`` row per replica VIEW over ``LogConfig(keys=n,
    capacity=control_capacity)``: replica ``i`` owns key ``i``; its
    state transitions append values at offsets ``0..e-1`` and the
    committed count of key ``i`` is its config epoch.  Views merge by
    the log join (``merge_max``), so gossip order/duplication can
    never corrupt an epoch, and a zeroed (rejoined) view recovers the
    whole fleet state by merging any survivor — exactly the kafka-log
    recovery semantics, applied to the serving layer's own control
    state.  All mutation happens under the Router lock."""

    def __init__(self, n: int, capacity: int):
        from gossip_tpu.ops import logs
        self._logs = logs
        self.cfg = LogConfig(keys=n, capacity=capacity)
        self.n = n
        self.width = logs.state_width(self.cfg)
        self.views = np.zeros((n, self.width), np.int32)
        self._gtick = 0

    def _merged(self) -> np.ndarray:
        out = self.views[0]
        for i in range(1, self.n):
            out = np.asarray(self._logs.merge_max(out, self.views[i]),
                             np.int32)
        return out

    def append(self, owner: int, state: int) -> int:
        """Append ``state`` as the next entry on ``owner``'s key (in
        the owner's view; gossip carries it out) and commit it —
        returns the new epoch.  The epoch is derived from the MERGED
        fleet view so a catchup-lagged owner can never reuse an
        offset."""
        cap = self.cfg.capacity
        lens = np.asarray(self._logs.log_len(self.cfg,
                                             self._merged()), np.int32)
        e = int(lens[owner])
        if e >= cap:
            raise ValueError(
                f"control-plane log for replica {owner} is full "
                f"({e}/{cap} epochs) — a ring wrap would alias epochs; "
                "raise FleetConfig.control_capacity")
        self.views[owner, owner * cap + e] = state
        com = self.cfg.keys * cap + owner
        self.views[owner, com] = max(int(self.views[owner, com]), e + 1)
        return e + 1

    def gossip_tick(self):
        """One rotating-partner pull per replica (the dense pull
        exchange shape on the fleet's own state): view ``i`` merges
        partner ``(i + k) % n`` — full convergence within n-1 ticks."""
        if self.n < 2:
            return
        self._gtick += 1
        k = 1 + (self._gtick % (self.n - 1))
        for i in range(self.n):
            j = (i + k) % self.n
            self.views[i] = np.asarray(
                self._logs.merge_max(self.views[i], self.views[j]),
                np.int32)

    def flush(self, i: int):
        """Push view ``i``'s entries out to every peer (the router's
        last gossip on a dying replica's behalf): the down-transition
        the router just appended must reach a survivor BEFORE the view
        is recycled, or the epoch record would lose an entry and a
        later append could alias its offset."""
        for j in range(self.n):
            if j != i:
                self.views[j] = np.asarray(
                    self._logs.merge_max(self.views[j], self.views[i]),
                    np.int32)

    def wipe(self, i: int):
        """Replica ``i`` died: its in-memory view is gone."""
        self.views[i] = 0

    def catchup(self, i: int) -> int:
        """Rejoin: replica ``i`` rebuilds its view by merging every
        survivor (gossip, not operator state) — returns its recovered
        epoch."""
        merged = np.zeros((self.width,), np.int32)
        for j in range(self.n):
            if j != i:
                merged = np.asarray(
                    self._logs.merge_max(merged, self.views[j]),
                    np.int32)
        self.views[i] = np.asarray(
            self._logs.merge_max(self.views[i], merged), np.int32)
        return self.epoch(i)

    def epoch(self, i: int) -> int:
        """Replica ``i``'s config epoch per ITS OWN view (committed
        offset of its key — the module-doc contract)."""
        com = np.asarray(self._logs.committed_of(self.cfg,
                                                 self.views[i]),
                         np.int32)
        return int(com[i])

    def epochs(self) -> list:
        """Fleet-merged epoch vector (one per replica key)."""
        com = np.asarray(self._logs.committed_of(self.cfg,
                                                 self._merged()),
                         np.int32)
        return [int(c) for c in com]

    def state_of(self, i: int) -> Optional[str]:
        """Replica ``i``'s current admission state from the merged
        log: the LAST committed entry on its key."""
        merged = self._merged()
        e = self.epochs()[i]
        if e == 0:
            return None
        val = int(merged[i * self.cfg.capacity + e - 1])
        return _STATE_NAMES.get(val, f"state{val}")


class _Replica:
    """One fronted replica: address, raw stubs (the router owns
    failover — no client-level retries), health counters, in-flight
    gauge."""

    def __init__(self, index: int, address: str):
        self.index = index
        self.address = address
        self.proc: Optional[subprocess.Popen] = None
        self.healthy = False
        self.ever_down = False
        self.wiped = False
        self.consec_ok = 0
        self.consec_fail = 0
        self.inflight = 0
        self._connect(address)

    def _connect(self, address: str):
        from gossip_tpu.rpc.sidecar import SidecarClient
        self.address = address
        self.client = SidecarClient(address, max_attempts=1)
        self.stubs = {"run": self.client._run,
                      "ensemble": self.client._ensemble,
                      "health": self.client._health,
                      "metrics": self.client._metrics}

    def close(self):
        try:
            self.client.close()
        except Exception:
            pass


class Router:
    """Health-gated failover dispatch over a replica set (module doc).

    ``start_probes()`` runs the prober thread (``serve_router`` does);
    tests drive :meth:`observe_probe` directly with scripted
    sequences.  All state transitions go through the one lock and the
    control-plane log."""

    def __init__(self, addresses: Sequence[str],
                 cfg: Optional[FleetConfig] = None):
        if not addresses:
            raise ValueError("router needs at least one replica "
                             "address")
        self.cfg = cfg or FleetConfig()
        self._lock = threading.Lock()
        self.replicas = [_Replica(i, a) for i, a in enumerate(addresses)]
        self.control = ControlPlane(len(self.replicas),
                                    self.cfg.control_capacity)
        self.counters = {"dispatched": 0, "failovers": 0, "sheds": 0,
                         "deadline_rejects": 0, "downs": 0, "ups": 0,
                         "catchups": 0}
        # the router's own live-metrics window: end-to-end dispatch
        # latencies (queue wait + run + failover retries, as the
        # CLIENT experiences them) plus shed/failover counters — the
        # fleet half of the Metrics reply (docs/OBSERVABILITY.md)
        from gossip_tpu.utils import telemetry
        self.metrics = telemetry.MetricsWindow()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- health state machine -----------------------------------------

    def observe_probe(self, r: _Replica, ok: bool):
        """Feed one probe outcome into the hysteresis state machine
        (the prober calls this; tests script it).  Re-admission after
        a down needs ``up_after`` CONSECUTIVE healthy probes; initial
        admission needs one (nothing was lost yet)."""
        with self._lock:
            if ok:
                r.consec_fail = 0
                r.consec_ok += 1
                need = self.cfg.up_after if r.ever_down else 1
                if not r.healthy and r.consec_ok >= need:
                    self._mark_up_locked(r)
            else:
                r.consec_ok = 0
                r.consec_fail += 1
                if r.healthy and r.consec_fail >= self.cfg.down_after:
                    self._mark_down_locked(
                        r, f"{r.consec_fail} consecutive probe "
                        "failures")

    def _control_append(self, index: int, state: int):
        """Record a transition on the control-plane log; a FULL ring
        must never take health gating down with it (the prober thread
        and the dispatch failover path both run through here), so the
        overflow is ledgered + counted loudly and the admission state
        machine keeps working with the epoch record frozen."""
        try:
            return self.control.append(index, state)
        except ValueError as e:
            self.counters["control_plane_full"] = \
                self.counters.get("control_plane_full", 0) + 1
            from gossip_tpu.utils import telemetry
            telemetry.current().event(
                "control_plane_full", sync=False, replica=index,
                state=_STATE_NAMES.get(state, state),
                error=str(e).splitlines()[0][:200])
            return None

    def _mark_down_locked(self, r: _Replica, reason: str):
        if not r.healthy:
            return
        r.healthy = False
        r.ever_down = True
        r.consec_ok = 0
        self.counters["downs"] += 1
        epoch = self._control_append(r.index, STATE_DOWN)
        from gossip_tpu.utils import telemetry
        telemetry.current().event(
            "replica_down", sync=False, replica=r.index,
            address=r.address, reason=reason, epoch=epoch)

    def _mark_up_locked(self, r: _Replica):
        if r.wiped:
            # rejoin: the view died with the process — catch up from
            # the survivors' gossip, never from operator state
            epoch = self.control.catchup(r.index)
            r.wiped = False
            self.counters["catchups"] += 1
            from gossip_tpu.utils import telemetry
            telemetry.current().event(
                "control_catchup", sync=False, replica=r.index,
                epoch=epoch, epochs=self.control.epochs())
        r.healthy = True
        r.consec_fail = 0
        self.counters["ups"] += 1
        epoch = self._control_append(r.index, STATE_UP)
        from gossip_tpu.utils import telemetry
        telemetry.current().event(
            "replica_up", sync=False, replica=r.index,
            address=r.address, epoch=epoch)

    def mark_down(self, r: _Replica, reason: str):
        with self._lock:
            self._mark_down_locked(r, reason)

    def drain_replica(self, i: int, wait_s: float = 10.0) -> bool:
        """Router-initiated graceful drain: take replica ``i`` out of
        rotation FIRST (new dispatches stop landing on it), then wait
        for its in-flight requests to finish — the ordering twin of
        the batcher's stop-before-flush contract.  Returns True once
        in-flight hit zero."""
        r = self.replicas[i]
        self.mark_down(r, "drain")
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            with self._lock:
                if r.inflight == 0:
                    return True
            time.sleep(0.01)
        return False

    def replace_replica(self, i: int, address: str,
                        proc: Optional[subprocess.Popen] = None):
        """A replica process was replaced (fleet restart after a
        kill): point the handle at the new address, zero its
        control-plane view (the old process's state is gone), and
        leave it DOWN until the probe hysteresis re-admits it."""
        r = self.replicas[i]
        with self._lock:
            self._mark_down_locked(r, "replaced")
            r.close()
            r._connect(address)
            r.proc = proc
            r.consec_ok = r.consec_fail = 0
            # replicate the dying view's entries (incl. the down
            # transition just appended) before recycling it — an
            # unflushed wipe would lose epochs and alias offsets
            self.control.flush(i)
            self.control.wipe(i)
            r.wiped = True
        return r

    # -- probing -------------------------------------------------------

    def _probe(self, r: _Replica) -> bool:
        import grpc
        try:
            r.stubs["health"](b"{}", timeout=self.cfg.probe_timeout_s)
            return True
        except (grpc.RpcError, ValueError):
            # ValueError: grpcio raises it (not RpcError) when the
            # channel was CLOSED under this call — replace_replica
            # racing a probe; either way the probe failed, and the
            # prober thread must survive it
            return False

    def probe_once(self):
        for r in list(self.replicas):
            self.observe_probe(r, self._probe(r))
        with self._lock:
            self.control.gossip_tick()

    def start_probes(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._probe_loop,
                                        name="gossip-fleet-prober",
                                        daemon=True)
        self._thread.start()

    def _probe_loop(self):
        interval = self.cfg.probe_interval_ms / 1e3
        while not self._stop.wait(interval):
            self.probe_once()

    def wait_healthy(self, count: int, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.healthy_count() >= count:
                return True
            time.sleep(0.02)
        return False

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.healthy)

    def stats(self) -> dict:
        with self._lock:
            return {**self.counters,
                    "replicas": len(self.replicas),
                    "healthy": sum(1 for r in self.replicas
                                   if r.healthy),
                    "inflight": [r.inflight for r in self.replicas],
                    "epochs": self.control.epochs(),
                    "states": [self.control.state_of(i)
                               for i in range(len(self.replicas))]}

    # -- dispatch ------------------------------------------------------

    def _pick(self, tried) -> Optional[_Replica]:
        """Least-inflight healthy replica not yet tried for this
        request (ties break to the lowest index — deterministic under
        serial load, spreading under concurrency); reserves an
        in-flight slot."""
        with self._lock:
            cands = [r for r in self.replicas
                     if r.healthy and r.index not in tried
                     and r.inflight < self.cfg.max_inflight]
            if not cands:
                return None
            r = min(cands, key=lambda x: (x.inflight, x.index))
            r.inflight += 1
            self.counters["dispatched"] += 1
            return r

    def dispatch(self, method: str, payload: bytes, context) -> bytes:
        """Route one RPC with failover (module-doc contract); aborts
        the gRPC context on shed/deadline/replica-reply errors.

        Tracing: the incoming ``gossip-trace-id`` metadata (rpc/sidecar
        TRACE_KEY) is read once, stamped on every span this dispatch
        emits (``dispatch_attempt`` per attempt, ``failover``/``shed``/
        ``deadline_exceeded`` on those paths, a terminal
        ``request_trace`` on success), and FORWARDED verbatim to the
        replica — the reply bytes stay untouched.  All emits are
        sync=False: the dispatch loop IS the timed path."""
        import grpc

        from gossip_tpu.rpc import batcher as B
        from gossip_tpu.rpc.sidecar import trace_id_of, trace_metadata
        from gossip_tpu.utils import telemetry
        deadline = B.deadline_of(context)
        trace_id = trace_id_of(context)
        metadata = trace_metadata(trace_id)
        t_start = time.monotonic()
        tried: list = []
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # the client already abandoned this request — a
                    # failover retry must never run it
                    self.counters["deadline_rejects"] += 1
                    telemetry.current().event(
                        "deadline_exceeded", sync=False,
                        source="router", method=method,
                        tried=list(tried), trace_id=trace_id)
                    context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        "deadline expired before a replica could "
                        "serve the request (tried "
                        f"{len(tried)} replicas)")
            r = self._pick(tried)
            if r is None:
                with self._lock:
                    healthy = sum(1 for x in self.replicas
                                  if x.healthy)
                    inflight = [x.inflight for x in self.replicas]
                    self.counters["sheds"] += 1
                self.metrics.bump("sheds")
                reason = ("no healthy replica"
                          if healthy == 0 else "all replicas at the "
                          "in-flight cap")
                telemetry.current().event(
                    "shed", sync=False, method=method, reason=reason,
                    healthy=healthy, inflight=inflight,
                    tried=list(tried), trace_id=trace_id)
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"fleet shed: {reason} "
                    f"({healthy}/{len(self.replicas)} healthy); back "
                    "off and retry")
            if trace_id is not None:
                # one span per dispatch attempt: which replica, its
                # probe state at pick time, and the deadline budget
                # still available — the failover half of the waterfall
                telemetry.current().event(
                    "dispatch_attempt", sync=False, trace_id=trace_id,
                    method=method, attempt=len(tried) + 1,
                    replica=r.index, consec_ok=r.consec_ok,
                    consec_fail=r.consec_fail,
                    remaining_s=(None if remaining is None
                                 else round(remaining, 3)))
            try:
                try:
                    reply = r.stubs[method](payload, timeout=remaining,
                                            metadata=metadata)
                finally:
                    with self._lock:
                        r.inflight -= 1
            except (grpc.RpcError, ValueError) as e:
                code = e.code() if callable(getattr(e, "code", None)) \
                    else None
                if code in (grpc.StatusCode.UNAVAILABLE,
                            grpc.StatusCode.CANCELLED) \
                        or isinstance(e, ValueError):
                    # transport failure: the replica is gone
                    # (UNAVAILABLE — connection refused/reset) or its
                    # channel was closed under this call (CANCELLED
                    # mid-RPC, or grpcio's ValueError "Cannot invoke
                    # RPC on closed channel!" when the close landed
                    # before the invoke — a fleet restart replacing
                    # the handle races both ways).  Mark it down and
                    # replay on a survivor — safe in every case:
                    # requests are deterministic pure functions of
                    # their payload, so even a processed-but-reply-
                    # lost call replays to the bitwise-same answer
                    self.mark_down(r, f"dispatch {method}: "
                                   f"{code or type(e).__name__}")
                    tried.append(r.index)
                    with self._lock:
                        self.counters["failovers"] += 1
                    self.metrics.bump("failovers")
                    telemetry.current().event(
                        "failover", sync=False, method=method,
                        from_replica=r.index, tried=list(tried),
                        remaining_s=(None if remaining is None
                                     else round(remaining, 3)),
                        trace_id=trace_id)
                    continue
                # a WELL-FORMED replica reply (it processed the call)
                # or the propagated client deadline: verbatim, never
                # replayed
                details = e.details() if callable(
                    getattr(e, "details", None)) else str(e)
                context.abort(code, details or str(code))
            proxy_ms = (time.monotonic() - t_start) * 1e3
            self.metrics.record(proxy_ms)
            if trace_id is not None:
                # the terminal router-side waterfall half: end-to-end
                # proxy wall, retry count, and how much of the client
                # deadline this request consumed
                budget_s = (None if deadline is None
                            else deadline - t_start)
                telemetry.current().event(
                    "request_trace", sync=False, trace_id=trace_id,
                    source="router", method=method, replica=r.index,
                    retries=len(tried),
                    proxy_ms=round(proxy_ms, 1),
                    deadline_consumed=(
                        None if not budget_s
                        else round(proxy_ms / 1e3 / budget_s, 4)))
            return reply

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for r in self.replicas:
            r.close()


def serve_router(addresses: Sequence[str], port: int = 0,
                 max_workers: int = 16,
                 cfg: Optional[FleetConfig] = None,
                 host: str = "127.0.0.1", start_probes: bool = True):
    """Start the fronting router over ``addresses``; returns
    ``(server, bound_port, router)``.  The router speaks the SAME
    ``gossip.Simulator`` service as a sidecar, so any ``SidecarClient``
    targets it transparently; its ``Health`` reply carries the fleet
    summary (healthy count, config epochs) instead of device facts.
    ``start_probes=False`` leaves the prober thread OFF — callers that
    need deterministic admission timing (the dry-run family, tests)
    drive ``router.probe_once()`` themselves."""
    import grpc
    from concurrent import futures

    from gossip_tpu.rpc.sidecar import SERVICE, _identity
    router = Router(addresses, cfg)

    def _run(request, context):
        return router.dispatch("run", request, context)

    def _ensemble(request, context):
        return router.dispatch("ensemble", request, context)

    def _health(request, context):
        s = router.stats()
        return json.dumps({
            "ok": s["healthy"] > 0, "router": True,
            "replicas": s["replicas"], "healthy": s["healthy"],
            "epochs": s["epochs"], "states": s["states"],
            "service": SERVICE}).encode()

    def _metrics(request, context):
        """The fleet metrics plane: the router's own dispatch window
        plus one row per replica (its Metrics reply fanned in, or the
        error that kept it out — a dead replica is a row, never a
        silent hole).  `gossip_tpu fleet-status` renders exactly this
        reply and exits nonzero on any degraded row."""
        s = router.stats()
        rows = []
        for r in list(router.replicas):
            row = {"replica": r.index, "address": r.address,
                   "healthy": r.healthy,
                   "state": s["states"][r.index],
                   "epoch": s["epochs"][r.index],
                   "inflight": s["inflight"][r.index]}
            try:
                raw = r.stubs["metrics"](
                    b"{}", timeout=router.cfg.probe_timeout_s)
                row["metrics"] = json.loads(raw)
            except Exception as e:          # noqa: BLE001 — a dead
                # replica's row must carry WHY, not kill the fan-out
                row["error"] = (f"{type(e).__name__}: "
                                + str(e).splitlines()[0][:200]
                                if str(e) else type(e).__name__)
            rows.append(row)
        return json.dumps({
            "ok": s["healthy"] > 0, "router": True,
            "service": SERVICE, "role": "router",
            "replicas": s["replicas"], "healthy": s["healthy"],
            "window": router.metrics.snapshot(),
            "counters": {k: s[k] for k in
                         ("dispatched", "failovers", "sheds",
                          "deadline_rejects", "downs", "ups",
                          "catchups") if k in s},
            "fleet": rows}).encode()

    server = grpc.server(futures.ThreadPoolExecutor(
        max_workers=max_workers))
    handlers = {
        "Run": grpc.unary_unary_rpc_method_handler(
            _run, request_deserializer=_identity,
            response_serializer=_identity),
        "Ensemble": grpc.unary_unary_rpc_method_handler(
            _ensemble, request_deserializer=_identity,
            response_serializer=_identity),
        "Health": grpc.unary_unary_rpc_method_handler(
            _health, request_deserializer=_identity,
            response_serializer=_identity),
        "Metrics": grpc.unary_unary_rpc_method_handler(
            _metrics, request_deserializer=_identity,
            response_serializer=_identity),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0 and port != 0:
        raise OSError(f"could not bind {host}:{port} (port in use?)")
    server.start()
    if start_probes:
        router.start_probes()
    server.gossip_router = router
    return server, bound, router


# -- spawned fleets (subprocess replicas) ------------------------------

def spawn_replica(workdir: str, name: str, extra_argv=(),
                  env: Optional[dict] = None,
                  timeout_s: float = 90.0) -> Tuple[subprocess.Popen,
                                                    int]:
    """Launch one ``gossip_tpu serve --port 0`` replica subprocess and
    read its bound port from the serve command's first stdout JSON
    line.  Child output goes to ``<workdir>/<name>.out/.err`` FILES,
    never pipes (the crashloop lesson: a chatty child filling an
    undrained pipe blocks mid-write and deadlocks its supervisor)."""
    os.makedirs(workdir, exist_ok=True)
    out_path = os.path.join(workdir, name + ".out")
    err_path = os.path.join(workdir, name + ".err")
    argv = [sys.executable, "-m", "gossip_tpu", "serve", "--port", "0",
            *extra_argv]
    with open(out_path, "wb") as fo, open(err_path, "wb") as fe:
        proc = subprocess.Popen(argv, stdout=fo, stderr=fe,
                                env=env, cwd=_REPO)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            err = open(err_path, errors="replace").read()
            raise RuntimeError(
                f"replica {name} died during startup "
                f"rc={proc.returncode}:\n{err[-2000:]}")
        try:
            with open(out_path) as f:
                line = f.readline().strip()
            if line:
                return proc, int(json.loads(line)["port"])
        except (OSError, ValueError, KeyError):
            pass
        time.sleep(0.05)
    proc.kill()
    proc.wait()
    raise RuntimeError(f"replica {name} did not report a port within "
                       f"{timeout_s}s")


def fleet_env(compile_cache_dir: Optional[str] = None,
              platform: Optional[str] = "cpu",
              devices: Optional[int] = None) -> dict:
    """Replica-child environment: repo importable, platform pinned
    (default CPU — N replica processes cannot share one TPU; pass
    ``platform=None`` to inherit the ambient pin on a multi-chip
    host), and an optional SHARED compile-cache dir so a respawned
    replica starts warm from its predecessors' executables.

    ``devices`` threads the host-device-count env
    (``XLA_FLAGS=--xla_force_host_platform_device_count=K``) for
    mesh-sharded replicas: a child pinned to CPU has exactly ONE
    device without it, so its megabatch mesh would silently degrade —
    the bug the devices_per_replica satellite closes.  An ambient
    host-device-count flag is left alone (the caller pinned it);
    otherwise the flag is appended to any other ambient XLA_FLAGS."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    if compile_cache_dir is not None:
        env["GOSSIP_COMPILE_CACHE"] = compile_cache_dir
    if devices is not None and devices > 1:
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{devices}").strip()
    return env


def _verify_replica_devices(addr: str, name: str, want: int,
                            timeout_s: float = 30.0):
    """The devices-per-replica gate: a freshly spawned child must
    REPORT the mesh width it actually serves with (the health reply's
    ``serving_devices`` — rpc/sidecar._health) or the fleet refuses
    loudly.  Without this, a replica missing the host-device-count env
    (or spawned without ``--devices``) comes up healthy, answers
    correctly, and silently serves a 1-device mesh — throughput
    degradation no probe would ever surface."""
    if want <= 1:
        return
    from gossip_tpu.rpc.sidecar import SidecarClient
    client = SidecarClient(addr)
    try:
        h = client.health(timeout=timeout_s)
    finally:
        client.close()
    got = int(h.get("serving_devices", h.get("devices", 1)))
    if got < want:
        raise RuntimeError(
            f"replica {name} at {addr} reports serving_devices={got} "
            f"but the fleet requires devices_per_replica={want} — the "
            "megabatch mesh silently degraded; spawn children with "
            "fleet_env(devices=K) (XLA_FLAGS=--xla_force_host_platform"
            "_device_count=K) AND the serve --devices flag")


class Fleet:
    """N spawned sidecar replicas behind a served router — the
    process-level fleet tools/fleet_crashloop.py SIGKILLs and the CLI
    ``route`` command runs.  ``kill(i)`` SIGKILLs a replica;
    ``restart(i)`` spawns a replacement on a fresh port and leaves the
    router's hysteresis to re-admit it (after a control-plane
    catchup).  When ``cfg.devices_per_replica > 1`` every spawn (and
    respawn) is gated by :func:`_verify_replica_devices` — a child
    serving a narrower mesh than configured fails the fleet loudly at
    startup instead of degrading throughput silently."""

    def __init__(self, n: Optional[int] = None,
                 cfg: Optional[FleetConfig] = None,
                 workdir: Optional[str] = None, replica_argv=(),
                 env: Optional[dict] = None, port: int = 0,
                 max_workers: int = 16):
        self.cfg = cfg or FleetConfig()
        n = self.cfg.replicas if n is None else n
        if workdir is None:
            import tempfile
            workdir = tempfile.mkdtemp(prefix="gossip_fleet_")
        self.workdir = workdir
        self.replica_argv = tuple(replica_argv)
        self.env = env if env is not None else fleet_env()
        self._gen = [0] * n
        procs, addrs = [], []
        try:
            for i in range(n):
                proc, rport = spawn_replica(workdir, f"r{i}_g0",
                                            self.replica_argv, self.env)
                procs.append(proc)
                addrs.append(f"127.0.0.1:{rport}")
                _verify_replica_devices(
                    addrs[-1], f"r{i}_g0", self.cfg.devices_per_replica)
            # serve_router inside the same net: a router bind failure
            # (port in use) must not strand N orphaned replica children
            self.server, self.port, self.router = serve_router(
                addrs, port=port, max_workers=max_workers, cfg=self.cfg)
        except Exception:
            for p in procs:
                p.kill()
                p.wait()
            raise
        for i, proc in enumerate(procs):
            self.router.replicas[i].proc = proc

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def kill(self, i: int) -> int:
        """SIGKILL replica ``i`` (the nemesis pointed at our own
        serving process); returns the killed pid."""
        r = self.router.replicas[i]
        if r.proc is None or r.proc.poll() is not None:
            raise ValueError(f"replica {i} has no live process")
        pid = r.proc.pid
        r.proc.send_signal(signal.SIGKILL)
        r.proc.wait()
        return pid

    def restart(self, i: int) -> str:
        """Spawn a replacement for replica ``i`` on a fresh port; the
        router wipes its control-plane view and the probe hysteresis
        re-admits it after ``up_after`` consecutive healthy probes
        (with a gossip catchup first)."""
        self._gen[i] += 1
        name = f"r{i}_g{self._gen[i]}"
        proc, rport = spawn_replica(self.workdir, name,
                                    self.replica_argv, self.env)
        addr = f"127.0.0.1:{rport}"
        try:
            _verify_replica_devices(addr, name,
                                    self.cfg.devices_per_replica)
        except Exception:
            # a degraded replacement must not join the rotation — kill
            # it and re-raise (the caller decides whether to retry)
            proc.kill()
            proc.wait()
            raise
        self.router.replace_replica(i, addr, proc)
        return addr

    def close(self):
        self.server.stop(grace=None)
        self.router.close()
        for r in self.router.replicas:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.kill()
                r.proc.wait()
