"""Topology library: graph families as static padded neighbor tables.

The reference's topology is a runtime ``map[string][]string`` of node-id ->
neighbor list (reference main.go:60-63, filled at main.go:142, read in the
gossip hot loop at main.go:72).  For XLA, ragged per-node neighbor lists
become a **fixed-width padded table** ``nbrs: int32[N, D]`` (D = max degree,
optionally capped) with out-of-range sentinel ``N`` in unused slots, plus a
``deg: int32[N]`` vector.  Static shapes mean one compiled program per
(N, D) — no recompiles as the rumor spreads.

The ``complete`` family is *implicit*: at 10M nodes a table would be absurd,
and uniform peer sampling needs no adjacency at all, so ``nbrs is None`` and
samplers draw targets directly from ``[0, N)``.

All generators are host-side numpy (cheap, one-time) and deterministic in
their seed.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from gossip_tpu import config as cfg_mod
from gossip_tpu.config import TopologyConfig


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Topology:
    """A static graph, ready to be passed into jitted round kernels.

    ``nbrs[i, j]`` is the j-th neighbor of node i for ``j < deg[i]`` and the
    sentinel value ``n`` (out of range — scatter ``mode='drop'`` ignores it,
    gathers mask it) for ``j >= deg[i]``.  ``nbrs is None`` for the implicit
    complete graph.
    """

    nbrs: Optional[jax.Array]  # int32[N, D] or None (implicit complete graph)
    deg: Optional[jax.Array]   # int32[N] or None
    n: int = dataclasses.field(metadata=dict(static=True), default=0)
    family: str = dataclasses.field(metadata=dict(static=True), default="complete")

    @property
    def implicit(self) -> bool:
        return self.nbrs is None

    @property
    def width(self) -> int:
        return 0 if self.nbrs is None else int(self.nbrs.shape[1])


@functools.partial(jax.jit, static_argnums=(3, 4), donate_argnums=())
def _scatter_table(src: jax.Array, dst: jax.Array, col: jax.Array,
                   n: int, d_max: int) -> jax.Array:
    """Build the padded table ON DEVICE from the edge list: one scatter of E
    elements into a sentinel-filled [n, d_max] table.  Host->device traffic
    is O(E) (the edges), never O(n * d_max) (the padding): at 1M-node
    power-law with cap 256 that is ~70 MB of edges instead of a 1 GB padded
    table — measured 45-100 s of pack+transfer before, ~3 s after."""
    nbrs = jnp.full((n, d_max), jnp.int32(n), dtype=jnp.int32)
    return nbrs.at[src, col].set(dst, unique_indices=True)


def _pack(n: int, src: np.ndarray, dst: np.ndarray,
          degree_cap: Optional[int], family: str,
          rng: np.random.Generator) -> Topology:
    """Pack an edge list (directed pairs; callers pass both directions for
    undirected graphs) into a padded neighbor table."""
    order = np.argsort(src, kind="stable")
    src, dst = src[order], dst[order]
    deg = np.bincount(src, minlength=n).astype(np.int32)
    d_max = int(deg.max()) if len(src) else 0
    starts = np.concatenate([[0], np.cumsum(deg)])[:-1]
    col = np.arange(len(src)) - np.repeat(starts, deg)
    if degree_cap is not None and d_max > degree_cap:
        # Per-node random subsample of neighbors down to the cap, fully
        # vectorized: within over-cap rows, rank edges by an iid uniform
        # priority (a random within-row permutation) and keep the first
        # `cap`; under-cap rows keep their original column order exactly.
        over = deg > degree_cap
        pri = np.where(over[src], rng.random(len(src)), col.astype(np.float64))
        order2 = np.lexsort((pri, src))
        src, dst = src[order2], dst[order2]
        rank = np.arange(len(src)) - np.repeat(starts, deg)
        keep = rank < degree_cap
        src, dst, col = src[keep], dst[keep], rank[keep]
        deg = np.minimum(deg, degree_cap)
        d_max = degree_cap
    d_max = max(d_max, 1)
    nbrs = _scatter_table(jnp.asarray(src, jnp.int32),
                          jnp.asarray(dst, jnp.int32),
                          jnp.asarray(col, jnp.int32), n, d_max)
    return Topology(nbrs=nbrs, deg=jnp.asarray(deg), n=n, family=family)


def complete(n: int) -> Topology:
    """Implicit complete graph: every node can sample every other node.

    This is the 10M-node scale path — no adjacency memory at all."""
    return Topology(nbrs=None, deg=None, n=n, family=cfg_mod.COMPLETE)


def complete_table(n: int) -> Topology:
    """Materialized complete graph (small n only — parity fixtures)."""
    src = np.repeat(np.arange(n), n - 1)
    dst = np.concatenate([np.delete(np.arange(n), i) for i in range(n)])
    return _pack(n, src.astype(np.int64), dst.astype(np.int64), None,
                 cfg_mod.COMPLETE, np.random.default_rng(0))


def ring(n: int, k: int = 2) -> Topology:
    """Ring lattice: each node linked to the k nearest neighbors (k/2 per
    side).  k must be even."""
    if k % 2 or k < 2:
        raise ValueError("ring k must be even and >= 2")
    offs = np.concatenate([np.arange(1, k // 2 + 1), -np.arange(1, k // 2 + 1)])
    src = np.repeat(np.arange(n), k)
    dst = (src + np.tile(offs, n)) % n
    return _pack(n, src, dst, None, cfg_mod.RING, np.random.default_rng(0))


def grid2d(rows: int, cols: int) -> Topology:
    """2-D grid, 4-connected, non-wrapping (the classic Maelstrom topology
    shape that the harness hands to the reference node)."""
    n = rows * cols
    i = np.arange(n)
    r, c = i // cols, i % cols
    pairs = []
    for dr, dc in ((0, 1), (1, 0)):
        ok = (r + dr < rows) & (c + dc < cols)
        a = i[ok]
        b = (r[ok] + dr) * cols + (c[ok] + dc)
        pairs.append((a, b))
        pairs.append((b, a))
    src = np.concatenate([p[0] for p in pairs])
    dst = np.concatenate([p[1] for p in pairs])
    return _pack(n, src, dst, None, cfg_mod.GRID, np.random.default_rng(0))


def erdos_renyi(n: int, p: float, seed: int = 0,
                degree_cap: Optional[int] = None) -> Topology:
    """G(n, p) via sparse edge sampling: draw Binomial(n*(n-1)/2, p) edge
    slots, then sample that many distinct unordered pairs.  O(E), not O(N^2)."""
    rng = np.random.default_rng(seed)
    m_total = n * (n - 1) // 2
    m = rng.binomial(m_total, p)
    if m > m_total // 8:
        # Dense regime: rejection sampling degenerates (coupon collector);
        # take a permutation prefix instead.  Only feasible when m_total
        # itself is materializable — which is the only regime where a dense
        # G(n,p) is materializable anyway.
        codes = rng.permutation(m_total)[:m]
    else:
        # Sparse regime: sample unordered-pair codes without replacement via
        # collision-resample with geometrically growing batches.
        codes = np.unique(rng.integers(0, m_total, size=int(m * 1.05) + 16))
        batch = max(m // 8, 64)
        while len(codes) < m:
            extra = rng.integers(0, m_total, size=batch)
            codes = np.unique(np.concatenate([codes, extra]))
            batch *= 2
        codes = rng.permutation(codes)[:m]
    # Decode unordered-pair index -> (a, b), a < b (triangular decoding).
    b = np.ceil((np.sqrt(8.0 * codes + 9) - 1) / 2).astype(np.int64)
    a = (codes - b * (b - 1) // 2).astype(np.int64)
    src = np.concatenate([a, b])
    dst = np.concatenate([b, a])
    return _pack(n, src, dst, degree_cap, cfg_mod.ERDOS_RENYI, rng)


def watts_strogatz(n: int, k: int = 4, beta: float = 0.1,
                   seed: int = 0) -> Topology:
    """Watts–Strogatz small world: ring lattice with each edge rewired to a
    uniform random endpoint with probability beta."""
    if k % 2 or k < 2:
        raise ValueError("watts_strogatz k must be even and >= 2")
    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(n), k // 2)
    dst = (src + np.tile(np.arange(1, k // 2 + 1), n)) % n
    rewire = rng.random(len(src)) < beta
    new_dst = rng.integers(0, n, size=len(src))
    # avoid self-loops on rewire
    new_dst = np.where(new_dst == src, (new_dst + 1) % n, new_dst)
    dst = np.where(rewire, new_dst, dst)
    s = np.concatenate([src, dst])
    d = np.concatenate([dst, src])
    # rewiring can collide with an existing or another rewired edge; collapse
    # duplicates so the padded table never repeats a neighbor
    codes = np.unique(s.astype(np.int64) * n + d)
    s, d = codes // n, codes % n
    return _pack(n, s, d, None, cfg_mod.WATTS_STROGATZ, rng)


def power_law(n: int, m: int = 2, seed: int = 0,
              degree_cap: Optional[int] = None) -> Topology:
    """Barabási–Albert preferential attachment via the repeated-nodes trick:
    each new node attaches to m targets drawn uniformly from the flat list of
    all previous edge endpoints (which is exactly degree-proportional).
    Vectorized enough to build 1M-node graphs in seconds."""
    rng = np.random.default_rng(seed)
    if m < 1 or n <= m:
        raise ValueError("power_law needs n > m >= 1")
    # endpoint pool; seed with a small clique among the first m+1 nodes
    srcs = [np.repeat(np.arange(m + 1), m)]
    dsts = [np.concatenate([np.delete(np.arange(m + 1), i)[:m]
                            for i in range(m + 1)])]
    pool = np.concatenate(srcs + dsts)
    pool_list = [pool]
    pool_size = len(pool)
    # process new nodes in growing chunks; inside a chunk, attach against the
    # frozen pool (slight approximation of strict sequential BA, standard for
    # scalable generation)
    new = np.arange(m + 1, n)
    chunk = max(1024, (n - m - 1) // 64)
    for lo in range(0, len(new), chunk):
        nodes = new[lo:lo + chunk]
        flat_pool = np.concatenate(pool_list) if len(pool_list) > 1 else pool_list[0]
        pool_list = [flat_pool]
        # picks come from the frozen pool, whose ids all predate this chunk's
        # nodes, so self-picks are impossible; duplicate directed edges are
        # collapsed by the unique() pass below.
        picks = flat_pool[rng.integers(0, pool_size, size=(len(nodes), m))]
        s = np.repeat(nodes, m)
        d = picks.reshape(-1)
        srcs.append(s)
        dsts.append(d)
        addition = np.concatenate([s, d])
        pool_list.append(addition)
        pool_size += len(addition)
    src = np.concatenate(srcs + dsts)
    dst = np.concatenate(dsts + srcs)
    # collapse duplicate directed edges so the padded table has no repeats
    codes = src.astype(np.int64) * n + dst
    codes = np.unique(codes)
    src, dst = codes // n, codes % n
    self_loop = src != dst
    return _pack(n, src[self_loop], dst[self_loop], degree_cap,
                 cfg_mod.POWER_LAW, rng)


def build(tc: TopologyConfig) -> Topology:
    """Build a topology from config (the CLI/sweep entry point)."""
    if tc.family == cfg_mod.COMPLETE:
        return complete(tc.n)
    if tc.family == cfg_mod.RING:
        return ring(tc.n, tc.k)
    if tc.family == cfg_mod.GRID:
        side = int(np.sqrt(tc.n))
        return grid2d(side, (tc.n + side - 1) // side)
    if tc.family == cfg_mod.ERDOS_RENYI:
        return erdos_renyi(tc.n, tc.p, tc.seed, tc.degree_cap)
    if tc.family == cfg_mod.WATTS_STROGATZ:
        return watts_strogatz(tc.n, tc.k, tc.p, tc.seed)
    if tc.family == cfg_mod.POWER_LAW:
        return power_law(tc.n, tc.k, tc.seed, tc.degree_cap)
    raise ValueError(tc.family)
