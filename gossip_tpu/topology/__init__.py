from gossip_tpu.topology.generators import (  # noqa: F401
    Topology,
    build,
    complete,
    complete_table,
    erdos_renyi,
    grid2d,
    power_law,
    ring,
    watts_strogatz,
)
