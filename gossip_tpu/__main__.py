import sys

from gossip_tpu.cli import main

sys.exit(main())
