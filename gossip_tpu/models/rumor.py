"""SIR rumor mongering: push gossip that STOPS — the Demers et al. 1987
"rumor mongering" family (§1.4 of the Clearinghouse paper), counter-death
variants.

The SI modes (models/si.py) never stop pushing: an infected node stays
infective forever, so push traffic is Theta(N * fanout) every round even
at full coverage.  Rumor mongering adds the classic third state — each
(node, rumor) is susceptible -> infective ("hot", actively forwarded) ->
REMOVED (known but no longer forwarded) — and nodes lose interest via an
unnecessary-contact counter:

* ``feedback``: a push whose recipient ALREADY knew the rumor increments
  the sender's counter for it; ``rumor_k`` such hits remove it.
* ``blind``: every push increments the counter — removal after exactly
  ``rumor_k`` pushes, regardless of outcome.

The run self-terminates when the hot set is empty.  The classic quality
metric is the **residue** s(infinity): the fraction of nodes never
informed when gossip dies out (Demers: counter feedback k=2 leaves
~2-6% residue on its own, which is why real systems pair rumor
mongering with periodic anti-entropy — both live in this framework, and
``--mode antientropy`` is the complement).

Reference mapping: the reference's relay (main.go:72-88) is SI flood —
it forwards forever and terminates only because the *dedup set* stops
re-broadcasts (main.go:113).  Rumor mongering is what the reference
would need at scale to stop paying O(degree) per duplicate delivery;
the counter-death semantics here are the batched, round-synchronous
form of that upgrade.

Everything is a pure array update: one round = sample targets for hot
(node, rumor) pairs -> scatter-OR the hot payload -> gather recipients'
prior knowledge for the feedback counters -> threshold against
``rumor_k``.  No data-dependent shapes: dead (node, rumor) pairs simply
push nothing.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.state import alive_mask, bind_tables
from gossip_tpu.ops.propagate import push_delta
from gossip_tpu.ops.sampling import apply_drop, sample_peers
from gossip_tpu.topology.generators import Topology

RUMOR_PUSH_TAG, RUMOR_DROP_TAG = 11, 12


class RumorState(NamedTuple):
    """SIR per-(node, rumor) state carried through the round loop."""

    seen: jax.Array      # bool[N, R] — informed (infective OR removed)
    hot: jax.Array       # bool[N, R] — infective: still forwarding
    cnt: jax.Array       # int32[N, R] — unnecessary-contact counter
    round: jax.Array     # int32 scalar
    base_key: jax.Array  # PRNG key
    msgs: jax.Array      # float32 scalar — push messages sent


def init_rumor_state(run: RunConfig, proto: ProtocolConfig,
                     n: int) -> RumorState:
    """Rumor r starts hot at node (origin + r) % n (models/state contract)."""
    r = proto.rumors
    origins = (run.origin + jnp.arange(r)) % n
    seen = jnp.zeros((n, r), jnp.bool_).at[origins, jnp.arange(r)].set(True)
    return RumorState(seen=seen, hot=seen, cnt=jnp.zeros((n, r), jnp.int32),
                      round=jnp.int32(0), base_key=jax.random.key(run.seed),
                      msgs=jnp.float32(0.0))


def make_rumor_round(proto: ProtocolConfig, topo: Topology,
                     fault: Optional[FaultConfig] = None,
                     origin: int = 0, tabled: bool = False):
    """Build the single-device rumor-mongering round step
    (``RumorState -> RumorState``; ``tabled=True`` as in make_si_round)."""
    if proto.mode != C.RUMOR:
        raise ValueError(f"make_rumor_round builds mode='rumor' only "
                         f"(got {proto.mode!r})")
    n, k = topo.n, proto.fanout
    kk = proto.rumor_k
    feedback = proto.rumor_variant == "feedback"
    drop_prob = 0.0 if fault is None else fault.drop_prob
    tables = () if topo.implicit else (topo.nbrs, topo.deg)
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)
    if ch is not None:
        # schedule as runtime operands on the table tail (models/si.py
        # twin; ops/nemesis module doc)
        tables = tables + NE.sched_args(NE.build(fault, n))

    def step_tabled(state: RumorState, *tbl):
        tbl, sched = NE.split_tables(ch, tbl)
        nbrs_t, deg_t = tbl if tbl else (None, None)
        ids = jnp.arange(n, dtype=jnp.int32)
        rkey = jax.random.fold_in(state.base_key, state.round)
        seen, hot, cnt = state.seen, state.hot, state.cnt
        if ch is not None:
            # churn path: per-round liveness / drop prob / cut from the
            # schedule operands.  A churn-down node loses its hot
            # (forwarding) state like a process crash; its seen set
            # persists (the durable dedup store, main.go:22-26).
            alive = NE.alive_rows(sched, NE.base_alive_or_ones(
                fault, n, origin), state.round)
            dp = NE.drop_at(sched, state.round)
            cut = NE.cut_at(sched, state.round)
        else:
            alive = alive_mask(fault, n, origin)
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)

        # What this node forwards this round: its hot rumors (dead nodes
        # go dark — neither send nor count).
        payload = hot if alive is None else hot & alive[:, None]   # [N, R]

        pkey = jax.random.fold_in(rkey, RUMOR_PUSH_TAG)
        targets0 = sample_peers(pkey, ids, topo, k, proto.exclude_self,
                                local_nbrs=nbrs_t, local_deg=deg_t)
        targets = apply_drop(rkey, RUMOR_DROP_TAG, ids, targets0,
                             dp, n, force=ch is not None)         # [N, k]
        if ch is not None:
            targets = NE.partition_targets(cut, ids, targets, n)
        sender_active = jnp.any(payload, axis=1)                   # [N]
        valid = (targets < n) & sender_active[:, None]             # [N, k]
        safe_t = jnp.where(valid, targets, 0)

        # Deliveries: scatter-OR of the hot payload into the targets.
        delta = push_delta(n, jnp.where(valid, targets, n), payload)
        if alive is not None:
            delta = delta & alive[:, None]     # dead nodes receive nothing

        # Counter update against ROUND-START knowledge (synchronous
        # semantics: all pushes observe the same snapshot).
        #   feedback: count pushes whose recipient already knew the rumor;
        #   blind:    count every push of a hot rumor.
        if feedback:
            knew = seen[safe_t] & valid[:, :, None]                # [N,k,R]
            hits = jnp.sum(knew, axis=1, dtype=jnp.int32)          # [N, R]
        else:
            hits = jnp.sum(valid, axis=1, dtype=jnp.int32)[:, None]
        cnt = cnt + jnp.where(payload, hits, 0)

        # Loss of interest (removal) + fresh infections become hot.  Dead
        # nodes can hold no hot bits (a dead multi-rumor origin would
        # otherwise stay "hot" forever with its payload masked, and the
        # extinction loop would never terminate); like SI, a rumor whose
        # origin is dead simply never spreads.
        new = delta & ~seen
        hot = (hot & (cnt < kk)) | new
        if alive is not None:
            hot = hot & alive[:, None]
        msgs = state.msgs + jnp.sum(valid).astype(jnp.float32)
        if ch is not None:
            lost = lost + NE.lost_count(targets0, targets,
                                        sender_active, n)
        out = RumorState(seen=seen | delta, hot=hot, cnt=cnt,
                         round=state.round + 1,
                         base_key=state.base_key, msgs=msgs)
        return (out, lost) if ch is not None else out

    return bind_tables(step_tabled, tables, tabled)


def rumor_coverage(seen: jax.Array,
                   alive: Optional[jax.Array] = None) -> jax.Array:
    """Min-over-rumors informed fraction (same contract as si.coverage)."""
    if alive is None:
        return jnp.min(jnp.mean(seen.astype(jnp.float32), axis=0))
    w = alive.astype(jnp.float32)
    per_rumor = (seen.astype(jnp.float32) * w[:, None]).sum(0) / w.sum()
    return jnp.min(per_rumor)


def simulate_until_rumor(proto: ProtocolConfig, topo: Topology,
                         run: RunConfig,
                         fault: Optional[FaultConfig] = None):
    """Run to extinction (no hot pairs left) or max_rounds, one compiled
    while_loop.  Returns (rounds, coverage, residue, msgs, final_state):
    ``residue`` is the never-informed fraction at termination — the
    rumor-mongering quality metric (worst rumor)."""
    from gossip_tpu.ops import nemesis as NE
    step, tbl = make_rumor_round(proto, topo, fault, run.origin, tabled=True)
    step = NE.drop_lost(step, NE.get(fault))
    init = init_rumor_state(run, proto, topo.n)

    @jax.jit
    def loop(state, *tables):
        def cond(s):
            return jnp.any(s.hot) & (s.round < run.max_rounds)

        def body(s):
            return step(s, *tables)

        return jax.lax.while_loop(cond, body, state)

    final = loop(init, *tbl)
    # alive_mask, NOT static_death_draw: the kernel pins the origin alive,
    # so the metric weighting must too (matches the sharded twin and
    # every SI curve path); under churn the eventual alive set
    # (ops/nemesis.metric_alive — heal-convergence denominator)
    alive = NE.metric_alive(fault, topo.n, run.origin)
    cov = float(rumor_coverage(final.seen, alive))
    return (int(final.round), cov, 1.0 - cov, float(final.msgs), final)


def checkpointed_rumor(proto: ProtocolConfig, topo: Topology,
                       run: RunConfig, path: str, every: int = 50,
                       fault: Optional[FaultConfig] = None, mesh=None,
                       resume_state=None, want_curve: bool = False,
                       curve_prefix=(), extra_meta=None,
                       lost_prefix: float = 0.0):
    """Fixed-budget rumor-mongering run in compiled segments with atomic
    npz checkpoints (utils/checkpoint.run_with_checkpoints) — the SIR
    twin of the SI/SWIM ``--checkpoint`` engines.  Unlike
    :func:`simulate_until_rumor` this does NOT early-exit at extinction
    (segments are fixed-length); the extinct state is absorbing, so the
    trailing rounds are no-ops and the trajectory stays bitwise equal to
    the segmented run it resumes.

    ``want_curve`` records TWO named channels per round — ``coverage``
    (min-over-rumors informed fraction) and ``hot`` (infective
    fraction) — because the extinction round is only recoverable from
    the hot channel (a coverage plateau is NOT extinction: feedback
    pushes keep flowing between informed pairs).  With ``mesh`` the
    node-sharded twin runs.  Returns ``(final_state, coverage,
    residue, curve-dict-or-None)``.

    Under a churn schedule the segments run the fault program exactly
    as the straight drivers do (the step reads its ABSOLUTE
    ``state.round``, which the checkpoint persists — resume == straight
    run bitwise, utils/checkpoint crash contract), the destroyed-
    message total accumulates across kills (``track_lost``; seed a
    resume with the checkpoint's ``extra['dropped']`` via
    ``lost_prefix``), and the metric denominator is the EVENTUAL alive
    set (heal-convergence contract, ops/nemesis.metric_alive).
    """
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.checkpoint import run_with_checkpoints
    ch = NE.get(fault)
    if mesh is None:
        step, tables = make_rumor_round(proto, topo, fault, run.origin,
                                        tabled=True)
        state = (resume_state if resume_state is not None
                 else init_rumor_state(run, proto, topo.n))

        def alive_now():
            # static mask without churn, eventual-alive set under it —
            # metric_alive is the one dispatch
            return NE.metric_alive(fault, topo.n, run.origin)
    else:
        from gossip_tpu.parallel.sharded import pad_to_mesh, sharded_alive
        from gossip_tpu.parallel.sharded_rumor import (
            init_sharded_rumor_state, make_sharded_rumor_round,
            restore_sharded_rumor_state)
        step, tables = make_sharded_rumor_round(proto, topo, mesh, fault,
                                                run.origin, tabled=True)
        state = (restore_sharded_rumor_state(resume_state, mesh)
                 if resume_state is not None
                 else init_sharded_rumor_state(run, proto, topo, mesh))
        n_rows = pad_to_mesh(topo.n, mesh, "nodes")

        def alive_now():
            # padded alive mask: padding rows must not deflate coverage
            if ch is not None:
                return NE.eventual_alive_pad(fault, topo.n, n_rows,
                                             run.origin)
            return sharded_alive(fault, topo.n, n_rows, run.origin)

    curve_fn = None
    if want_curve:
        def curve_fn(s):
            alive = alive_now()
            hot_any = jnp.any(s.hot, axis=1).astype(jnp.float32)
            if alive is None:
                hot_frac = jnp.mean(hot_any)
            else:
                w = alive.astype(jnp.float32)
                hot_frac = jnp.sum(hot_any * w) / jnp.sum(w)
            return {"coverage": rumor_coverage(s.seen, alive),
                    "hot": hot_frac}

    remaining = max(0, run.max_rounds - int(state.round))
    out = run_with_checkpoints(step, state, remaining, path, every=every,
                               step_args=tables, curve_fn=curve_fn,
                               curve_prefix=curve_prefix,
                               extra_meta=extra_meta,
                               track_lost=ch is not None,
                               lost_prefix=lost_prefix)
    final, curve = out if want_curve else (out, None)
    cov = float(rumor_coverage(final.seen, alive_now()))
    return final, cov, 1.0 - cov, curve


def simulate_curve_rumor(proto: ProtocolConfig, topo: Topology,
                         run: RunConfig,
                         fault: Optional[FaultConfig] = None):
    """Fixed-length scan: per-round (coverage, hot_fraction, msgs) curves
    — hot_fraction shows the infective wave rise and die out."""
    from gossip_tpu.ops import nemesis as NE
    step, tbl = make_rumor_round(proto, topo, fault, run.origin, tabled=True)
    step = NE.drop_lost(step, NE.get(fault))
    init = init_rumor_state(run, proto, topo.n)

    @jax.jit
    def scan(state, *tables):
        # alive-weighted coverage, consistent with the until-driver and
        # the SI curve paths (dead nodes are unreachable, not uninformed)
        alive = NE.metric_alive(fault, topo.n, run.origin)
        hot_w = (jnp.float32(1.0) if alive is None
                 else alive.astype(jnp.float32))

        def body(s, _):
            s = step(s, *tables)
            hot_any = jnp.any(s.hot, axis=1).astype(jnp.float32)
            hot_frac = (jnp.mean(hot_any) if alive is None
                        else jnp.sum(hot_any * hot_w) / jnp.sum(hot_w))
            return s, (rumor_coverage(s.seen, alive), hot_frac, s.msgs)
        return jax.lax.scan(body, state, None, length=run.max_rounds)

    final, (covs, hots, msgs) = scan(init, *tbl)
    return covs, hots, msgs, final
