"""LWW-register gossip rounds: the exchange fabric with the
totally-available transaction payload.

The step below is models/log.make_log_round with the register payload
(ops/registers): the gossip mechanics — peer sampling streams, drop
coins, partition cuts, churn liveness — are the EXISTING fabric,
untouched, and the payload merge is the per-key last-writer-wins join
on packed (round, owner) timestamps (a lattice join, so order,
duplication, and loss never fork a winner).  Pull only, by design:
state-based dissemination IS the pull/digest exchange, and the push
half would need a scatter-argmax collective XLA does not have (the
models/si_packed, models/crdt, and models/log precedent).

Semantics under a nemesis schedule (docs/WORKLOADS.md
"Transactions"):

  * a churn-down node neither serves pulls, requests, nor receives —
    but its registers PERSIST across downtime (the durable-store
    convention), so a recovered node re-disseminates every winner it
    ever merged;
  * a write fires iff its owner is alive at the scripted round and
    eventually alive (the acked-writes rule — ops/registers module
    doc), which makes exact convergence to
    :func:`~gossip_tpu.ops.registers.ground_truth` on the
    eventual-alive set a guaranteed invariant under any fault
    program;
  * txn convergence (``txn_conv``) is judged INTEGER-exact: the
    drivers move a converged-node COUNT off device and divide by the
    eventual-alive total once on the host (the bitwise-curve
    convention).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from gossip_tpu import config as C
from gossip_tpu.config import (FaultConfig, ProtocolConfig, RunConfig,
                               TxnConfig)
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.state import alive_mask, bind_tables
from gossip_tpu.ops import registers as RG
from gossip_tpu.ops.sampling import apply_drop, sample_peers
from gossip_tpu.topology.generators import Topology


class RegState(NamedTuple):
    """Carried through ``lax.scan`` / ``lax.while_loop`` rounds —
    ``val`` is the ``int32[N, 2K]`` value-planes + timestamp-planes
    row (ops/registers layout)."""

    val: jax.Array
    round: jax.Array
    base_key: jax.Array
    msgs: jax.Array


def init_reg_state(run: RunConfig, cfg: TxnConfig, n: int) -> RegState:
    """All-zero state: writes apply IN the round loop at their
    scripted rounds, indexed by the absolute ``state.round`` clock the
    nemesis schedule shares."""
    return RegState(
        val=jnp.zeros((n, RG.state_width(cfg)), jnp.int32),
        round=jnp.int32(0),
        base_key=jax.random.key(run.seed),
        msgs=jnp.float32(0.0),
    )


def check_writes_reachable(cfg: TxnConfig, run: RunConfig) -> None:
    """Every scripted write must fire inside the run (the models/crdt
    rule: an unreachable write makes ground truth unreachable by
    construction — a loud error, never a quiet converged:false)."""
    last = cfg.horizon() - 1
    if last >= run.max_rounds:
        raise ValueError(
            f"txn write at round {last} can never fire: the run "
            f"stops after max_rounds={run.max_rounds} rounds, so "
            "ground truth would be unreachable by construction — "
            "raise --max-rounds past the last scripted round")


def check_txn_mode(proto: ProtocolConfig) -> None:
    """Pull only (module doc) — one loud reason, shared by every
    driver and the CLI."""
    if proto.mode != C.PULL:
        raise ValueError(
            "LWW-register rounds run the pull exchange only "
            "(state-based merge IS the digest pull; got mode "
            f"{proto.mode!r} — the push half would need a "
            "scatter-argmax collective XLA does not have, the "
            "models/crdt and models/log precedent)")


def make_register_round(cfg: TxnConfig, proto: ProtocolConfig,
                        topo: Topology,
                        fault: Optional[FaultConfig] = None,
                        origin: int = 0, tabled: bool = False,
                        defend: bool = False):
    """Single-device LWW-register round step; the sharded twin lives
    in parallel/sharded_register.py and must stay bitwise identical
    (pinned in tests/test_txn.py).  Returns ``step: RegState ->
    RegState`` (or ``(state, lost)`` on the churn path);
    ``tabled=True`` returns ``(step, tables)`` with topology + write
    (+ schedule) (+ byzantine program) arrays as step ARGUMENTS.
    ``defend=True`` switches the exchange to the owner/clamp-defended
    admission (ops/registers byzantine section); ``defend=False``
    under a liar program is the undefended control arm."""
    check_txn_mode(proto)
    n, k = topo.n, proto.fanout
    drop_prob = 0.0 if fault is None else fault.drop_prob
    tables = () if topo.implicit else (topo.nbrs, topo.deg)
    from gossip_tpu.models.crdt import check_byz_defendable
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    # capability row: the register pull exchange rides the dense
    # fabric and honors the FULL schedule feature set — events,
    # partition windows, drop ramps — plus the byzantine liar program
    # with the owner/clamp defense (docs/ROBUSTNESS.md scenario
    # catalog)
    NE.check_supported(fault, engine="txn-pull", byz=True)
    check_byz_defendable(None, fault, k, defend)
    tables = tables + RG.inject_args(cfg, n)
    if ch is not None:
        tables = tables + NE.sched_args(NE.build(fault, n))
    if bz is not None:
        tables = tables + NE.byz_args(NE.build_byz(fault, n))
    zero = jnp.zeros((), jnp.int32)

    def step_tabled(state: RegState, *tbl):
        tbl, byzt = NE.split_byz(bz, tbl)
        tbl, sched = NE.split_tables(ch, tbl)
        tbl, inj = RG.split_inject(cfg, tbl)
        nbrs_t, deg_t = tbl if tbl else (None, None)
        ids = jnp.arange(n, dtype=jnp.int32)
        rkey = jax.random.fold_in(state.base_key, state.round)
        if ch is not None:
            alive = NE.alive_rows(sched, NE.base_alive_or_ones(
                fault, n, origin), state.round)
            dp = NE.drop_at(sched, state.round)
            cut = NE.cut_at(sched, state.round)
        else:
            alive = alive_mask(fault, n, origin)  # None on the hot path
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        # local writes land BEFORE the exchange (a write gossips in its
        # own round); the apply mask is the shared liveness predicate,
        # so trajectory and ground truth cannot drift.  The injection
        # merges via the SAME LWW join as the exchange — an own write
        # always wins locally (its timestamp exceeds anything merged in
        # earlier rounds) and same-round peers resolve by owner order.
        inj_rows = RG.inject_rows(cfg, inj, ids, state.round, n,
                                  origin, fault)
        val = RG.merge_lww(state.val, inj_rows)
        visible = val if alive is None else jnp.where(
            alive[:, None], val, zero)
        qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
        partners0 = sample_peers(qkey, ids, topo, k, proto.exclude_self,
                                 local_nbrs=nbrs_t, local_deg=deg_t)
        partners = apply_drop(rkey, si_mod.PULL_DROP_TAG, ids,
                              partners0, dp, n, force=ch is not None)
        if ch is not None:
            partners = NE.partition_targets(cut, ids, partners, n)
        if bz is not None:
            pulled = RG.pull_merge_reg_byz(
                visible, partners, n, byz=byzt, round_=state.round,
                gids=ids, n=n,
                alive_fn=RG.alive_at_fn(fault, n, origin),
                defend=defend)
        else:
            pulled = RG.pull_merge_reg(visible, partners, n)
        if alive is not None:
            partners = jnp.where(alive[:, None], partners, n)
        n_req = jnp.sum(partners < n).astype(jnp.float32)
        if ch is not None:
            req_active = (jnp.ones((n,), jnp.bool_) if alive is None
                          else alive)
            lost = lost + NE.lost_count(partners0, partners,
                                        req_active, n)
        if alive is not None:
            pulled = jnp.where(alive[:, None], pulled, zero)
        out = RegState(val=RG.merge_lww(val, pulled),
                       round=state.round + 1,
                       base_key=state.base_key,
                       msgs=state.msgs + 2.0 * n_req)
        return (out, lost) if ch is not None else out

    return bind_tables(step_tabled, tables, tabled)


def _conv_target_count(run: RunConfig, eventual_total: int) -> int:
    """Integer while_loop target (the models/crdt rule: no f32
    division near control flow)."""
    import math
    return min(eventual_total,
               math.ceil(run.target_coverage * eventual_total - 1e-9))


def simulate_curve_txn(cfg: TxnConfig, proto: ProtocolConfig,
                       topo: Topology, run: RunConfig,
                       fault: Optional[FaultConfig] = None,
                       timing=None, defend: bool = False):
    """``lax.scan`` over rounds recording the per-round CONVERGED-NODE
    COUNT (int32) and msgs; returns ``(txn_conv f64[T], msgs f32[T],
    final_state, truth_summary)`` with txn_conv divided once on the
    host.  ``truth_summary``: per-key winning values + unpacked
    (round, owner) timestamps (ops/registers.truth_summary)."""
    import numpy as np

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.trace import maybe_aot_timed
    check_writes_reachable(cfg, run)
    step, tables = make_register_round(cfg, proto, topo, fault,
                                       run.origin, tabled=True,
                                       defend=defend)
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    n = topo.n
    init = init_reg_state(run, cfg, n)

    @jax.jit
    def scan(state, *tbl):
        _, inj0 = RG.split_inject(cfg, NE.split_tables(
            ch, NE.split_byz(bz, tbl)[0])[0])
        truth = RG.ground_truth(cfg, inj0, fault, n, run.origin)
        eventual = RG.eventual_alive_crdt(fault, n, run.origin)

        def body(s, _):
            out = step(s, *tbl)
            s1 = out[0] if ch is not None else out
            return s1, (RG.converged_count(s1.val, truth, eventual),
                        s1.msgs)

        final, (convs, msgs) = jax.lax.scan(body, state, None,
                                            length=run.max_rounds)
        return final, convs, msgs, truth

    final, convs, msgs, truth = maybe_aot_timed(scan, timing, init,
                                                *tables, label="txn_solo")
    eventual = np.asarray(RG.eventual_alive_crdt(fault, n, run.origin))
    denom = max(1, int(eventual.sum()))
    conv = np.asarray(convs, np.int64) / denom
    return conv, np.asarray(msgs), final, RG.truth_summary(cfg, truth,
                                                           n)


def simulate_until_txn(cfg: TxnConfig, proto: ProtocolConfig,
                       topo: Topology, run: RunConfig,
                       fault: Optional[FaultConfig] = None,
                       timing=None, defend: bool = False):
    """``lax.while_loop`` until the converged-node count reaches the
    integer target; returns ``(rounds, txn_conv, msgs, final_state,
    truth_summary)``."""
    import numpy as np

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.trace import maybe_aot_timed
    check_writes_reachable(cfg, run)
    step, tables = make_register_round(cfg, proto, topo, fault,
                                       run.origin, tabled=True,
                                       defend=defend)
    step = NE.drop_lost(step, NE.get(fault))
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    n = topo.n
    init = init_reg_state(run, cfg, n)
    eventual_np = np.asarray(RG.eventual_alive_crdt(fault, n,
                                                    run.origin))
    denom = max(1, int(eventual_np.sum()))
    target = _conv_target_count(run, denom)

    @jax.jit
    def loop(state, *tbl):
        _, inj0 = RG.split_inject(cfg, NE.split_tables(
            ch, NE.split_byz(bz, tbl)[0])[0])
        truth = RG.ground_truth(cfg, inj0, fault, n, run.origin)
        eventual = RG.eventual_alive_crdt(fault, n, run.origin)

        def cond(s):
            return ((RG.converged_count(s.val, truth, eventual)
                     < target) & (s.round < run.max_rounds))

        return jax.lax.while_loop(cond, lambda s: step(s, *tbl),
                                  state), truth

    final, truth = maybe_aot_timed(loop, timing, init, *tables,
                                   label="txn_solo")
    conv = int(RG.converged_count(
        final.val, truth,
        RG.eventual_alive_crdt(fault, n, run.origin))) / denom
    return (int(final.round), conv, float(final.msgs), final,
            RG.truth_summary(cfg, truth, n))
