"""CRDT gossip rounds: the exchange fabric with a commutative-merge
payload.

The payload replaces the infected bit; the gossip mechanics — peer
sampling streams, drop coins, partition cuts, churn liveness — are the
EXISTING fabric, untouched: the step below is models/si_packed
.make_packed_round with ``uint32 | ``/`` max`` merge in place of the
bool OR and the injection program applied before the exchange.  Pull
only, by design: state-based CRDT dissemination IS the pull/digest
exchange (each round a node fetches k peers' full states and joins
them — Shapiro et al. §3.2 state-based replication), and the push half
would need a scatter-max/scatter-OR collective XLA does not have —
exactly the reason models/si_packed.py rejects push modes.

Semantics under a nemesis schedule (docs/WORKLOADS.md):

  * a churn-down node neither serves pulls, requests, nor receives —
    but its state PERSISTS across downtime (the durable-store
    convention of the rumor kernels' ``seen``), so a recovered node
    re-disseminates everything it ever merged;
  * an injection fires iff its owner is alive at the injection round
    and eventually alive (ops/crdt module doc — the acked-adds
    semantics), which makes exact convergence to
    :func:`~gossip_tpu.ops.crdt.ground_truth` on the eventual-alive
    set a guaranteed invariant under any fault program;
  * value convergence is judged INTEGER-exact: the drivers move a
    converged-node COUNT off device and divide by the eventual-alive
    total once on the host (the bitwise-curve convention).

Schedules AND injections ride the step's ``tables`` tuple as runtime
operands (ops/nemesis + ops/crdt.inject_args), so one compiled loop
serves a whole scenario family.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from gossip_tpu import config as C
from gossip_tpu.config import (CrdtConfig, FaultConfig, ProtocolConfig,
                               RunConfig)
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.state import alive_mask, bind_tables
from gossip_tpu.ops import crdt as CR
from gossip_tpu.ops.sampling import apply_drop, sample_peers
from gossip_tpu.topology.generators import Topology


class CrdtState(NamedTuple):
    """Carried through ``lax.scan`` / ``lax.while_loop`` rounds — the
    CRDT twin of models/state.SimState (NamedTuple == registered
    pytree).  ``val`` is ``int32[N, S]`` counter shards or
    ``uint32[N, 2W]`` packed set planes (ops/crdt layout)."""

    val: jax.Array
    round: jax.Array
    base_key: jax.Array
    msgs: jax.Array


def init_crdt_state(run: RunConfig, cfg: CrdtConfig, n: int) -> CrdtState:
    """All-zero state: injections are applied IN the round loop at
    their scripted rounds (a round-0 add lands in the first step,
    before its exchange), so resume-from-checkpoint and scripted-add
    programs index the same absolute clock as the nemesis schedule."""
    return CrdtState(
        val=jnp.zeros((n, CR.state_width(cfg, n)), CR.state_dtype(cfg)),
        round=jnp.int32(0),
        base_key=jax.random.key(run.seed),
        msgs=jnp.float32(0.0),
    )


def check_injections_reachable(cfg: CrdtConfig, run: RunConfig) -> None:
    """Every scripted injection must fire inside the run: an add at a
    round >= max_rounds would be counted by ground_truth (the owner IS
    alive there) but never applied by the loop, so the run could never
    converge — reported as a quiet converged:false instead of the loud
    error the no-silent-failure policy demands.  Called by every
    driver (the factories do not see RunConfig)."""
    last = cfg.horizon() - 1
    if last >= run.max_rounds:
        raise ValueError(
            f"injection at round {last} can never fire: the run stops "
            f"after max_rounds={run.max_rounds} rounds, so ground "
            "truth would be unreachable by construction — raise "
            "--max-rounds past the last scripted round")


def check_crdt_mode(proto: ProtocolConfig) -> None:
    """Pull only (module doc) — one loud reason, shared by every
    driver and the CLI."""
    if proto.mode != C.PULL:
        raise ValueError(
            "CRDT rounds run the pull exchange only (state-based merge "
            f"IS the digest pull; got mode {proto.mode!r} — the push "
            "half would need a scatter-max/scatter-OR collective XLA "
            "does not have, the models/si_packed.py precedent)")


def check_byz_defendable(cfg, fault, fanout: int, defend: bool) -> None:
    """The defend/byz coupling, one loud reason per arm (shared by the
    single-device and sharded factories and the CLI): ``defend=True``
    without a liar program is rejected (the defended admission CHANGES
    the exchange — owner-direct propagation — so a defended no-liar
    run is not the control arm of anything), and a defended packed-set
    run needs ``fanout >= quorum`` (a round that samples fewer
    partners than the echo threshold could never admit a broadcast
    bit by quorum)."""
    from gossip_tpu.ops import nemesis as NE
    bz = NE.get_byz(fault)
    if defend and bz is None:
        raise ValueError(
            "defend=True without a byzantine program: the defended "
            "admission changes the exchange (owner-direct "
            "propagation), so there is nothing it would be defending "
            "against — script liars with --byz, or drop --defend")
    if (defend and bz is not None and cfg is not None
            and getattr(cfg, "kind", None) in C.CRDT_SET_KINDS
            and fanout < fault.byz.quorum):
        raise ValueError(
            f"defended packed-set exchange with fanout={fanout} < "
            f"quorum={fault.byz.quorum}: a bit echoed by fewer "
            "partners than are even sampled per round can never meet "
            "the quorum — raise --fanout or lower ByzConfig.quorum")


def make_crdt_round(cfg: CrdtConfig, proto: ProtocolConfig,
                    topo: Topology, fault: Optional[FaultConfig] = None,
                    origin: int = 0, tabled: bool = False,
                    defend: bool = False):
    """Single-device CRDT round step; the sharded twin lives in
    parallel/sharded_crdt.py and must stay bitwise identical (pinned
    in tests/test_crdt.py).  Returns ``step: CrdtState -> CrdtState``
    (or ``(state, lost)`` on the churn path — the models/si.py
    contract); ``tabled=True`` returns ``(step, tables)`` with
    topology + injection (+ schedule) (+ byzantine program) arrays as
    step ARGUMENTS.  ``defend=True`` switches the exchange to the
    defended admission (ops/crdt byzantine section); ``defend=False``
    under a liar program is the undefended control arm."""
    check_crdt_mode(proto)
    n, k = topo.n, proto.fanout
    if cfg.kind == C.VCLOCK:
        raise ValueError("vclock has no exchange driver (merge kernel "
                         "+ tick only — ops/crdt); run gcounter/"
                         "pncounter/gset/orset")
    drop_prob = 0.0 if fault is None else fault.drop_prob
    tables = () if topo.implicit else (topo.nbrs, topo.deg)
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    # capability row: the CRDT pull exchange rides the dense/packed
    # fabric and honors the FULL schedule feature set — events,
    # partition windows, drop ramps — plus the byzantine liar program
    # with array-form defenses (docs/ROBUSTNESS.md catalog)
    NE.check_supported(fault, engine="crdt-pull", byz=True)
    check_byz_defendable(cfg, fault, k, defend)
    # injections then (on the churn path) the schedule, then the liar
    # program OUTERMOST: all runtime operands on the table tail,
    # shapes-only in the compiled loop
    tables = tables + CR.inject_args(cfg, n)
    if ch is not None:
        tables = tables + NE.sched_args(NE.build(fault, n))
    if bz is not None:
        tables = tables + NE.byz_args(NE.build_byz(fault, n))
    zero = jnp.zeros((), CR.state_dtype(cfg))

    def step_tabled(state: CrdtState, *tbl):
        tbl, byzt = NE.split_byz(bz, tbl)
        tbl, sched = NE.split_tables(ch, tbl)
        tbl, inj = CR.split_inject(cfg, tbl)
        nbrs_t, deg_t = tbl if tbl else (None, None)
        ids = jnp.arange(n, dtype=jnp.int32)
        rkey = jax.random.fold_in(state.base_key, state.round)
        alive_fn = CR.alive_at_fn(fault, n, origin)
        eventual = CR.eventual_alive_crdt(fault, n, origin)
        if ch is not None:
            alive = NE.alive_rows(sched, NE.base_alive_or_ones(
                fault, n, origin), state.round)
            dp = NE.drop_at(sched, state.round)
            cut = NE.cut_at(sched, state.round)
        else:
            alive = alive_mask(fault, n, origin)  # None on the hot path
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        # local injections land BEFORE the exchange (an add gossips in
        # its own round); the apply mask is the shared alive_at
        # predicate, so the trajectory and ground truth cannot drift.
        # Own columns add (increments accumulate), set planes OR.
        inj_rows = CR.inject_rows(cfg, inj, ids, state.round, n,
                                  origin, alive_fn, eventual)
        if cfg.kind in C.CRDT_COUNTER_KINDS:
            val = state.val + inj_rows
        else:
            val = state.val | inj_rows
        visible = val if alive is None else jnp.where(
            alive[:, None], val, zero)
        qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
        partners0 = sample_peers(qkey, ids, topo, k, proto.exclude_self,
                                 local_nbrs=nbrs_t, local_deg=deg_t)
        partners = apply_drop(rkey, si_mod.PULL_DROP_TAG, ids,
                              partners0, dp, n, force=ch is not None)
        if ch is not None:
            partners = NE.partition_targets(cut, ids, partners, n)
        if bz is not None:
            pulled = CR.pull_merge_crdt_byz(
                cfg, visible, partners, n, byz=byzt,
                round_=state.round, gids=ids, n=n, origin=origin,
                alive_fn=alive_fn, defend=defend)
        else:
            pulled = CR.pull_merge_crdt(cfg.kind, visible, partners, n)
        if alive is not None:
            partners = jnp.where(alive[:, None], partners, n)
        n_req = jnp.sum(partners < n).astype(jnp.float32)
        if ch is not None:
            req_active = (jnp.ones((n,), jnp.bool_) if alive is None
                          else alive)
            lost = lost + NE.lost_count(partners0, partners,
                                        req_active, n)
        if alive is not None:
            pulled = jnp.where(alive[:, None], pulled, zero)
        out = CrdtState(val=CR.merge(cfg.kind, val, pulled),
                        round=state.round + 1,
                        base_key=state.base_key,
                        msgs=state.msgs + 2.0 * n_req)
        return (out, lost) if ch is not None else out

    return bind_tables(step_tabled, tables, tabled)


def _conv_target_count(run: RunConfig, eventual_total: int) -> int:
    """The integer while_loop target: converged-node count that meets
    ``run.target_coverage`` of the eventual-alive total — computed ONCE
    on the host so the loop cond is an exact integer compare (no f32
    division anywhere near control flow)."""
    import math
    return min(eventual_total,
               math.ceil(run.target_coverage * eventual_total - 1e-9))


def simulate_curve_crdt(cfg: CrdtConfig, proto: ProtocolConfig,
                        topo: Topology, run: RunConfig,
                        fault: Optional[FaultConfig] = None,
                        timing=None, defend: bool = False):
    """``lax.scan`` over rounds recording the per-round CONVERGED-NODE
    COUNT (int32) and msgs; returns ``(value_conv f64[T], msgs f32[T],
    final_state, truth_value)`` with value_conv divided once on the
    host (ops/crdt module doc).  ``truth_value``: the scalar counter
    ground-truth value, or the member-element count for sets."""
    import numpy as np

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.trace import maybe_aot_timed
    check_injections_reachable(cfg, run)
    step, tables = make_crdt_round(cfg, proto, topo, fault, run.origin,
                                   tabled=True, defend=defend)
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    n = topo.n
    init = init_crdt_state(run, cfg, n)

    @jax.jit
    def scan(state, *tbl):
        _, inj0 = CR.split_inject(cfg, NE.split_tables(
            ch, NE.split_byz(bz, tbl)[0])[0])
        truth = CR.ground_truth(cfg, inj0, fault, n, run.origin)
        eventual = CR.eventual_alive_crdt(fault, n, run.origin)

        def body(s, _):
            out = step(s, *tbl)
            s1 = out[0] if ch is not None else out
            return s1, (CR.converged_count(s1.val, truth, eventual),
                        s1.msgs)

        final, (convs, msgs) = jax.lax.scan(body, state, None,
                                            length=run.max_rounds)
        return final, convs, msgs, truth

    final, convs, msgs, truth = maybe_aot_timed(scan, timing, init,
                                                *tables, label="crdt_solo")
    eventual = np.asarray(CR.eventual_alive_crdt(fault, n, run.origin))
    denom = max(1, int(eventual.sum()))
    conv = np.asarray(convs, np.int64) / denom
    return conv, np.asarray(msgs), final, truth_scalar(cfg, truth, n)


def truth_scalar(cfg: CrdtConfig, truth, n: int):
    """The human-readable ground truth: counter value (int) or member
    count (int) — integer-exact, for reports and the CLI."""
    import numpy as np
    truth = np.asarray(truth)
    if cfg.kind in C.CRDT_COUNTER_KINDS:
        if cfg.kind == C.PNCOUNTER:
            return int(truth[:n].sum() - truth[n:].sum())
        return int(truth.sum())
    w = truth.shape[0] // 2
    members = truth[:w] & ~truth[w:]
    return int(sum(bin(int(x)).count("1") for x in members))


def simulate_until_crdt(cfg: CrdtConfig, proto: ProtocolConfig,
                        topo: Topology, run: RunConfig,
                        fault: Optional[FaultConfig] = None,
                        timing=None, defend: bool = False):
    """``lax.while_loop`` until the converged-node count reaches the
    integer target (``target_coverage`` of the eventual-alive set);
    returns ``(rounds, value_conv, msgs, final_state, truth_value)``."""
    import numpy as np

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.utils.trace import maybe_aot_timed
    check_injections_reachable(cfg, run)
    step, tables = make_crdt_round(cfg, proto, topo, fault, run.origin,
                                   tabled=True, defend=defend)
    step = NE.drop_lost(step, NE.get(fault))
    ch = NE.get(fault)
    bz = NE.get_byz(fault)
    n = topo.n
    init = init_crdt_state(run, cfg, n)
    eventual_np = np.asarray(CR.eventual_alive_crdt(fault, n,
                                                    run.origin))
    denom = max(1, int(eventual_np.sum()))
    target = _conv_target_count(run, denom)

    @jax.jit
    def loop(state, *tbl):
        _, inj0 = CR.split_inject(cfg, NE.split_tables(
            ch, NE.split_byz(bz, tbl)[0])[0])
        truth = CR.ground_truth(cfg, inj0, fault, n, run.origin)
        eventual = CR.eventual_alive_crdt(fault, n, run.origin)

        def cond(s):
            return ((CR.converged_count(s.val, truth, eventual)
                     < target) & (s.round < run.max_rounds))

        return jax.lax.while_loop(cond, lambda s: step(s, *tbl),
                                  state), truth

    final, truth = maybe_aot_timed(loop, timing, init, *tables,
                                   label="crdt_solo")
    conv = int(CR.converged_count(
        final.val, truth,
        CR.eventual_alive_crdt(fault, n, run.origin))) / denom
    return (int(final.round), conv, float(final.msgs), final,
            truth_scalar(cfg, truth, n))
