"""SWIM-style failure detection (suspect / confirm) in batched array form.

The reference has no membership or failure detection at all — only a blind
unbounded retry loop per neighbor RPC (reference main.go:77-87, SURVEY.md §5
"Failure detection: retry only").  This module supplies the real thing, per
the BASELINE.json config "SWIM-style suspect/confirm failure detection, 1M
nodes": each node runs the SWIM probe cycle against a tracked set of S
subjects, with indirect probes through K proxies, suspicion timers,
confirm-after-timeout, and incarnation-based refutation, all as pure
array updates — no per-node state machines, no control flow that XLA can't
tile (SURVEY.md §7 "SWIM semantics in array form").

**Membership coverage.**  Two subject-window modes:

* fixed (default): the window is nodes ``0..S-1`` for the whole run — the
  cheap array-form reduction for a known failure scenario;
* rotating (``proto.swim_rotate``): the window advances by S every
  *epoch* of ``swim_epoch_rounds`` rounds — epoch ``e`` watches global ids
  ``(e*S + j) % n``.  Every node is eventually watched (full-membership
  semantics) while per-observer view state stays ``[N, S]``, never
  ``[N, N]``.  At each epoch boundary wire and timer reset: detection
  state is scoped to the epoch, exactly like real SWIM's bounded
  piggyback buffers scope dissemination.  The auto epoch length
  (:func:`suggested_epoch_rounds`) leaves room for probe + epidemic
  dissemination + suspicion timeout + confirm spread inside one epoch.

**The wire encoding** is what makes SWIM XLA-native.  A view of a subject is
(status, incarnation) with SWIM's override rules: Alive@i beats Suspect@j iff
i > j; Suspect@i beats Alive@j iff i >= j; Dead beats everything.  That is a
total order, so encode each view as ONE monotone int32

    wire = incarnation * 2 + (1 if SUSPECT else 0)      # ALIVE/SUSPECT
    wire = DEAD_WIRE (1 << 30)                          # DEAD (absorbing)

and every SWIM merge — gossip dissemination, local suspicion, confirmation —
becomes ``max``.  Dissemination is then a scatter-max (single device) or a
per-shard scatter-max + ``lax.pmax`` over the mesh (sharded): the exact same
shape as the SI push kernel, riding ICI.

Round structure (one jitted step):
  1. every alive node probes one uniform subject; on direct-probe failure it
     ping-reqs K random proxies (SWIM's indirect probe);
  2. total failure -> set the SUSPECT bit at the viewed incarnation;
  3. nodes push their view rows to ``fanout`` random peers; receivers merge
     by max (piggyback dissemination);
  4. an alive subject that sees itself suspected refutes: self-view becomes
     ALIVE at incarnation+1 (a larger wire, so it propagates over the stale
     suspicion);
  5. a view held at SUSPECT with the same wire for ``swim_suspect_rounds``
     consecutive rounds is confirmed DEAD (absorbing — as in SWIM, a
     confirmed-dead subject cannot refute).

Ground truth: all nodes are alive before ``fail_round``; at ``fail_round``
the nodes in ``dead_nodes`` (plus any FaultConfig static deaths) fail
permanently.  Dead nodes neither probe, nor disseminate, nor update their
views.  ``drop_prob`` models lossy links on probe paths (the source of false
suspicions that refutation must outrun).

Probes go node-to-subject directly (SWIM's membership overlay is the
complete graph); the topology argument, when given, restricts only the
*dissemination* targets — on a power-law graph that is the BASELINE.json
1M-node config.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from gossip_tpu.config import FaultConfig, ProtocolConfig
from gossip_tpu.models.state import bind_tables
from gossip_tpu.ops.sampling import drop_mask, node_keys, sample_peers
from gossip_tpu.topology.generators import Topology

ALIVE, SUSPECT, DEAD = 0, 1, 2
DEAD_WIRE = jnp.int32(1 << 30)

# fold_in tags (disjoint from models/si.py's 1..5 by convention)
_SUBJ_TAG, _PROXY_TAG, _DISS_TAG = 10, 11, 12
_DIRECT_DROP_TAG, _TO_PROXY_DROP_TAG, _PROXY_SUBJ_DROP_TAG = 13, 14, 15


class SwimState(NamedTuple):
    """Carried through rounds.  ``wire[i, s]`` is node i's view of subject s
    in the monotone encoding above; ``timer[i, s]`` counts consecutive rounds
    the exact suspect wire has been held."""

    wire: jax.Array     # int32[N, S]
    timer: jax.Array    # int32[N, S]
    round: jax.Array    # int32 scalar
    base_key: jax.Array
    msgs: jax.Array     # float32 scalar


def suggested_suspect_rounds(n: int, fanout: int = 2) -> int:
    """Suspicion timeout long enough for refutation to make the round trip.

    SWIM's accuracy guarantee is probabilistic in exactly this timeout (SWIM
    paper §4): a false suspicion must travel to the subject and the bumped
    incarnation back to the suspector before the timer expires.  Both legs
    are epidemic pushes, ~log_{1+fanout}(n) rounds each — stretched further
    by whatever link loss caused the false suspicion in the first place —
    so 2x that plus generous slack.  Shorter timeouts trade detection
    latency for a real false-positive rate.
    """
    import math
    leg = math.log(max(n, 2)) / math.log(1 + max(fanout, 1))
    return max(6, int(math.ceil(2 * leg)) + 6)


def suggested_epoch_rounds(n: int, fanout: int, suspect_rounds: int) -> int:
    """Rotating-window epoch length: probe seeding (~2 rounds; with n/S
    probers per subject the dead subject is suspected almost immediately)
    + one epidemic dissemination leg + the suspicion timeout + slack for
    the DEAD confirmation itself to spread."""
    import math
    leg = math.log(max(n, 2)) / math.log(1 + max(fanout, 1))
    return suspect_rounds + int(math.ceil(leg)) + 8


def resolve_epoch_rounds(proto: ProtocolConfig, n: int) -> int:
    """The epoch length a given config actually runs with (0 = auto)."""
    return proto.swim_epoch_rounds or suggested_epoch_rounds(
        n, proto.fanout, proto.swim_suspect_rounds)


def subject_window(round_, s_count: int, n: int, rotate: bool,
                   epoch_rounds: int) -> jax.Array:
    """Global subject ids ``int32[S]`` watched during ``round_``.  Fixed
    mode: always ``0..S-1``.  Rotating: epoch ``round_ // epoch_rounds``
    shifts the window by S (mod n) — distinct ids whenever S <= n."""
    slot = jnp.arange(s_count, dtype=jnp.int32)
    if not rotate:
        return slot
    epoch = (jnp.asarray(round_, jnp.int32) // epoch_rounds).astype(jnp.int32)
    return (epoch * s_count + slot) % n


def decode_status(wire: jax.Array) -> jax.Array:
    """wire -> {ALIVE, SUSPECT, DEAD}."""
    return jnp.where(wire >= DEAD_WIRE, DEAD,
                     jnp.where(wire % 2 == 1, SUSPECT, ALIVE))


def init_swim_state(n: int, n_subjects: int, seed: int = 0) -> SwimState:
    return SwimState(
        wire=jnp.zeros((n, n_subjects), jnp.int32),   # everyone ALIVE@0
        timer=jnp.zeros((n, n_subjects), jnp.int32),
        round=jnp.int32(0),
        base_key=jax.random.key(seed),
        msgs=jnp.float32(0.0),
    )


def base_alive(n: int, dead_nodes: Tuple[int, ...],
               fault: Optional[FaultConfig]) -> jax.Array:
    """Static post-``fail_round`` liveness (True = stays alive).  Uses the
    canonical draw from models/state so one FaultConfig kills the same node
    set in SI and SWIM kernels alike.  Scripted churn events are NOT in
    this mask — their die/recover windows are applied per round by the
    kernels (ops/nemesis; a churn death before its die_round would
    corrupt the timeline)."""
    from gossip_tpu.models.state import static_death_draw
    alive = jnp.ones((n,), jnp.bool_)
    if dead_nodes:
        alive = alive.at[jnp.asarray(dead_nodes)].set(False)
    drawn = static_death_draw(fault, n)
    if drawn is not None:
        alive = alive & drawn
    return alive


def observer_alive(n: int, dead_nodes: Tuple[int, ...],
                   fault: Optional[FaultConfig]) -> jax.Array:
    """The detection-metric OBSERVER population: :func:`base_alive`
    minus PERMANENT churn deaths (recover_round < 0) — a forever-down
    node cannot observe; a node that recovers stays in the denominator
    (it must re-learn the confirmed deaths via dissemination, which is
    part of what the heal gate tests)."""
    from gossip_tpu.ops import nemesis as NE
    alive = base_alive(n, dead_nodes, fault)
    dead = NE.permanent_dead_ids(NE.get(fault))
    if dead:
        alive = alive.at[jnp.asarray(dead, jnp.int32)].set(False)
    return alive


def detection_targets(dead_nodes: Tuple[int, ...],
                      fault: Optional[FaultConfig]) -> Tuple[int, ...]:
    """GLOBAL ids the detection metric must confirm: the scripted static
    deaths plus PERMANENT churn deaths (recover_round < 0).  A churn
    crash is exactly the event SWIM exists to detect (Das et al., DSN
    2002), so a churn-only scenario has real targets; a node that
    RECOVERS is never a target — the heal gate asserts it is refuted,
    not confirmed.  Every detection_fraction caller builds its target
    set here so the four drivers (curve/until/checkpointed/ensemble)
    cannot disagree."""
    from gossip_tpu.ops import nemesis as NE
    return tuple(sorted(set(tuple(dead_nodes))
                        | set(NE.permanent_dead_ids(NE.get(fault)))))


def pack_width(max_rounds) -> int:
    """Static transport-lane width (bits) for ``disseminate_max('pack')``.

    Live wires are bounded by ``2*rounds + 1`` (incarnation grows by at
    most 1 per round, via refutation), so a run capped at ``max_rounds``
    fits every live wire strictly below the lane cap when
    ``2*max_rounds + 3 < 2**width - 1`` (margin 2 over the proof bound).
    Returns 8, 16, or 0 (no width fits / bound unknown — caller falls
    back to the unpacked ``sort`` lowering)."""
    if max_rounds is None:
        return 0
    bound = 2 * int(max_rounds) + 3
    if bound < 0xFF:
        return 8
    if bound < 0xFFFF:
        return 16
    return 0


def effective_diss(impl: str, max_rounds) -> str:
    """The dissemination lowering :func:`disseminate_max` will actually
    run: ``pack`` silently degrades to ``sort`` when no transport-lane
    width fits (``pack_width`` 0 — unbounded ``max_rounds``).  Results
    are bitwise-identical either way, but a benchmark of ``pack`` that
    measured ``sort`` must be visible in run meta, not silent
    (ADVICE r4: the no-silent-substitution policy)."""
    if impl == "pack" and not pack_width(max_rounds):
        return "sort"
    return impl


def disseminate_max(targets: jax.Array, wire: jax.Array, num_rows: int,
                    impl: str = "scatter", max_rounds=None) -> jax.Array:
    """Max-merge pushed wire rows into an ``int32[num_rows, S]`` table.

    The piggyback-dissemination reduce (reference relay loop
    main.go:72-88, batched): each sender ``i`` pushes its whole wire
    row ``wire[i]`` to every receiver in ``targets[i]``; row ``r`` of
    the result is the elementwise max of every row pushed to it; rows
    nobody pushed to are 0 (the ALIVE@0 floor — wires are
    non-negative).  Targets outside ``[0, num_rows)`` (the
    silent-sender sentinel) are dropped.

    Three lowerings, bitwise-identical results (max is
    order-independent; ``pack``'s transport code is an order
    isomorphism on the values that can occur):

    * ``scatter`` — one duplicate-index scatter-max.  On TPU a scatter
      whose indices repeat serializes its updates, so cost grows with
      the push count ``N*fanout``, not with HBM traffic.
    * ``sort`` — sort the pushes by receiver, then a segment-max with
      ``indices_are_sorted=True``.  Pays an O(M log M) sort but hands
      XLA a monotone-index reduce.  The chip arbitrated
      (artifacts/swim_ab_r04.json, 1M-node BASELINE shape): sort is
      2.2x faster steady-state (25.7 s -> 11.6 s over 31 rounds) and
      1.5x faster to compile (183 s -> 119 s), hence the default;
      ``ProtocolConfig.swim_diss`` keeps scatter as the control.
    * ``pack`` — the sort lowering with the random row gather (its
      dominant HBM cost: ~7 ns/word x M*S words, the repo cost model)
      done on 8- or 16-bit *transport codes*, 4 or 2 lanes per uint32
      word.  ``t = min(wire, cap)`` is monotone and injective on the
      values a ``max_rounds``-bounded run can produce (live wires
      <= 2*rounds+1 << cap; DEAD_WIRE -> cap), so max commutes with
      the coding and ``cap -> DEAD_WIRE`` after the reduce restores
      the exact int32 wires: trajectories stay bitwise identical to
      ``scatter``/``sort``.  The gather also reads the [N, W] packed
      table via ``sorted_index // fanout`` instead of a materialized
      [N*fanout, S] broadcast, cutting the gathered words 4x (8-bit)
      or 2x (16-bit) plus the operand copy.  Requires ``max_rounds``
      (the static round budget every driver knows); without it the
      bound is unprovable and this falls back to ``sort``.
    """
    fanout = targets.shape[1]
    s_count = wire.shape[1]
    flat_t = targets.reshape(-1)
    width = pack_width(max_rounds) if impl == "pack" else 0
    if impl == "pack" and width:
        lanes = 32 // width
        cap = (1 << width) - 1
        code = jnp.minimum(wire, cap).astype(jnp.uint32)     # order-iso
        lane_pad = (-s_count) % lanes
        if lane_pad:
            code = jnp.pad(code, ((0, 0), (0, lane_pad)))
        grouped = code.reshape(code.shape[0], -1, lanes)
        packed = grouped[:, :, 0]
        for lane in range(1, lanes):
            packed = packed | (grouped[:, :, lane] << (width * lane))
        order = jnp.argsort(flat_t)
        g = packed[order // fanout]          # THE gather, in packed words
        cols = [((g >> (width * lane)) & cap).astype(jnp.uint16)
                for lane in range(lanes)]
        codes = jnp.stack(cols, axis=-1).reshape(g.shape[0], -1)[:, :s_count]
        # empty segments fill with the uint16 min = 0: the floor for free
        recv = jax.ops.segment_max(codes, flat_t[order],
                                   num_segments=num_rows,
                                   indices_are_sorted=True).astype(jnp.int32)
        return jnp.where(recv == cap, DEAD_WIRE, recv)
    flat_w = jnp.broadcast_to(wire[:, None, :],
                              (wire.shape[0], fanout, s_count)
                              ).reshape(-1, s_count)
    if impl in ("sort", "pack"):             # pack w/o a bound: plain sort
        order = jnp.argsort(flat_t)
        recv = jax.ops.segment_max(flat_w[order], flat_t[order],
                                   num_segments=num_rows,
                                   indices_are_sorted=True)
        # empty segments fill with int32 min; clamp to the 0 floor the
        # scatter form produces
        return jnp.maximum(recv, 0)
    return jnp.zeros((num_rows, flat_w.shape[1]), jnp.int32
                     ).at[flat_t].max(flat_w, mode="drop")


def probe_draws(rkey, gids, s_count: int, n: int, proxies: int,
                drop_prob, force: bool = False):
    """Steps 1-2 random draws: each node's probed subject, direct-probe drop,
    proxy ids, and the two per-proxy hop drops.  All keyed by *global* node
    id so the sharded kernel reproduces them bitwise (ops/sampling
    contract).  ``force=True`` skips the static zero-rate early-out so
    ``drop_prob`` may be a TRACED per-round scalar (the ops/nemesis
    drop-ramp path — bernoulli takes a traced p, and a p == the static
    value draws the identical coins).  Returns (subj[Nl], d_drop[Nl],
    proxy_ids[Nl,K], to_p[Nl,K], p_to_s[Nl,K])."""
    keys = node_keys(jax.random.fold_in(rkey, _SUBJ_TAG), gids)
    subj = jax.vmap(
        lambda k: jax.random.randint(k, (), 0, s_count, dtype=jnp.int32)
    )(keys)
    pkeys = node_keys(jax.random.fold_in(rkey, _PROXY_TAG), gids)
    proxy_ids = jax.vmap(
        lambda k: jax.random.randint(k, (proxies,), 0, n, dtype=jnp.int32)
    )(pkeys)
    m = len(gids)
    if force or drop_prob > 0.0:
        d_drop = drop_mask(rkey, _DIRECT_DROP_TAG, gids, 1, drop_prob)[:, 0]
        to_p = drop_mask(rkey, _TO_PROXY_DROP_TAG, gids, proxies, drop_prob)
        p_to_s = drop_mask(rkey, _PROXY_SUBJ_DROP_TAG, gids, proxies,
                           drop_prob)
    else:
        d_drop = jnp.zeros((m,), jnp.bool_)
        to_p = p_to_s = jnp.zeros((m, proxies), jnp.bool_)
    return subj, d_drop, proxy_ids, to_p, p_to_s


_PACKED_TAG = 16          # the packed-rng lowering's one fold_in tag


def packed_round_draws(rkey, gids, s_count: int, n: int, proxies: int,
                       fanout: int, drop_prob,
                       nbrs=None, deg=None, sentinel: Optional[int] = None,
                       force: bool = False):
    """ALL of a SWIM round's per-node randomness from ONE key chain and
    ONE multi-word draw (``ProtocolConfig.swim_rng='packed'``).

    The 'split' contract derives an independent per-node key chain per
    random quantity — subject, proxies, dissemination peers, and (with
    loss) three drop-coin streams — each a full threefry pass over
    every node's key, ~5 such passes per node per round at the BASELINE
    shape.  This lowering derives per-node keys ONCE
    (``node_keys(fold_in(rkey, _PACKED_TAG), gids)``) and draws one
    ``uint32[W]`` word vector per node, splitting fields:

      word 0                      -> probed subject       (mod s_count)
      words 1..proxies            -> proxy ids            (mod n)
      next ``fanout`` words       -> dissemination peers
                                     (complete: mod n-1 + self-shift;
                                      table: mod deg, row gather)
      [when drop_prob > 0]
      next word                   -> direct-probe drop coin
      next 2*proxies words        -> per-proxy hop drop coins
                                     (uint32 threshold compare:
                                      quantization 2^-32)

    Statistical contract (opt-in; tests/test_swim.py): each field is
    uniform on its range up to the documented modulo bias <= m/2^32
    (m = range; 2.3e-4 relative at n=1M — the same documentation
    standard as the fused kernel's rotation bias), fields of one node
    are independent bits of one threefry stream, and draws are keyed by
    GLOBAL node id, so the sharded twin reproduces them bitwise
    (SURVEY.md §7 "Cross-shard randomness").  Trajectories differ from
    'split' (different streams) — this is an engine-level contract
    like fused-SI-vs-threefry, not a relowering.

    ``force=True`` (the ops/nemesis drop-ramp path) always draws the
    coin words with ``drop_prob`` as a TRACED threshold — computed in
    float32, so the effective threshold quantizes within one f32 ulp of
    the static path's exact ``int(p * 2**32)`` (the same documented
    tolerance class as the modulo bias above; ramp configs have no
    static twin to match bitwise).

    Returns ``(subj, d_drop, proxy_ids, to_p, p_to_s, targets)`` —
    probe_draws' tuple plus the dissemination targets."""
    have_drop = force or drop_prob > 0.0
    w = 1 + proxies + fanout + (1 + 2 * proxies if have_drop else 0)
    keys = node_keys(jax.random.fold_in(rkey, _PACKED_TAG), gids)
    words = jax.vmap(
        lambda k: jax.random.bits(k, (w,), jnp.uint32))(keys)

    subj = (words[:, 0] % jnp.uint32(s_count)).astype(jnp.int32)
    proxy_ids = (words[:, 1:1 + proxies]
                 % jnp.uint32(n)).astype(jnp.int32)
    peer_w = words[:, 1 + proxies:1 + proxies + fanout]
    if nbrs is None:
        # complete graph.  Degenerate n=1 (one node, one subject —
        # passes the swim_subjects <= n validation): the max(n-1, 1)
        # guard makes the draw 0 and the self-shift maps it to gid+1,
        # an out-of-range target the scatter's sentinel handling drops
        # — the lone node gossips to nobody, like the split path's
        # degenerate guard in sample_peers_complete.
        from gossip_tpu.ops.sampling import shift_excluding_self
        r = (peer_w % jnp.uint32(max(n - 1, 1))).astype(jnp.int32)
        targets = shift_excluding_self(r, gids[:, None])
    else:
        from gossip_tpu.ops.sampling import table_lookup_or_sentinel
        idx = (peer_w % jnp.maximum(deg, 1)[:, None].astype(jnp.uint32)
               ).astype(jnp.int32)
        targets = table_lookup_or_sentinel(idx, nbrs, deg[:, None],
                                           sentinel)

    m = len(gids)
    if have_drop:
        if force:
            # traced p -> uint32 threshold in f32 (clamped below 2**32:
            # 4294967040 is the largest f32 under it, so the convert
            # can never overflow; p >= 1 saturates to all-ones)
            dp = jnp.asarray(drop_prob, jnp.float32)
            thresh = jnp.where(
                dp >= 1.0, jnp.uint32(0xFFFFFFFF),
                jnp.minimum(dp * jnp.float32(4294967296.0),
                            jnp.float32(4294967040.0)).astype(jnp.uint32))
        else:
            thresh = jnp.uint32(min(int(drop_prob * 2**32), 2**32 - 1))
        base = 1 + proxies + fanout
        d_drop = words[:, base] < thresh
        to_p = words[:, base + 1:base + 1 + proxies] < thresh
        p_to_s = words[:, base + 1 + proxies:base + 1 + 2 * proxies] < thresh
    else:
        d_drop = jnp.zeros((m,), jnp.bool_)
        to_p = p_to_s = jnp.zeros((m, proxies), jnp.bool_)
    return subj, d_drop, proxy_ids, to_p, p_to_s, targets


def make_swim_round(proto: ProtocolConfig, n: int,
                    dead_nodes: Tuple[int, ...] = (),
                    fail_round: int = 0,
                    fault: Optional[FaultConfig] = None,
                    topo: Optional[Topology] = None,
                    tabled: bool = False,
                    max_rounds=None,
                    ):
    """Single-device SWIM round step (sharded twin:
    :func:`gossip_tpu.parallel.sharded_swim.make_sharded_swim_round`, kept
    semantically identical — tests/test_swim.py asserts bitwise parity).

    ``max_rounds`` (the driver's static round budget) is only consulted
    by the ``swim_diss='pack'`` dissemination lowering, which needs it to
    prove its transport-lane bound (:func:`pack_width`); None is always
    safe (pack falls back to the unpacked sort lowering).

    Returns ``step: SwimState -> SwimState``, or with ``tabled=True`` the
    pair ``(step, tables)`` where ``step(state, *tables)`` takes the
    topology's neighbor arrays as ARGUMENTS instead of closure constants —
    required at 1M+ nodes with explicit tables, where a closed-over table
    would be serialized into the XLA compile request (hundreds of MB of
    inline HLO constants) instead of shipped once as a runtime device
    buffer.  The other O(N) buffers (node iota, liveness mask) are computed
    INSIDE the trace from scalars for the same reason."""
    s_count = proto.swim_subjects
    if s_count > n:
        raise ValueError(
            f"swim_subjects={s_count} exceeds cluster size n={n}; the "
            "subject window cannot be wider than the membership")
    proxies = proto.swim_proxies
    t_confirm = proto.swim_suspect_rounds
    fanout = proto.fanout
    rotate = proto.swim_rotate
    epoch_rounds = resolve_epoch_rounds(proto, n)
    drop_prob = 0.0 if fault is None else fault.drop_prob
    from gossip_tpu.ops import nemesis as NE
    # SWIM probes ride the complete membership overlay (no per-pair
    # messages a link cut models): churn EVENTS — exactly the scenario
    # SWIM exists to detect (Das et al., DSN 2002) — and drop-rate
    # RAMPS (the coin streams read drop_tbl[r] as a traced operand)
    # are the supported schedule; partitions stay rejected
    NE.check_supported(fault, engine="swim", partitions=False)
    ch = NE.get(fault)
    # traced per-round drop only when the schedule actually ramps: a
    # static-p churn run keeps the exact PR 5 coin streams (bitwise
    # pins in tests/data/churn_fingerprints_r06.json)
    ramped = ch is not None and ch.ramp is not None
    if topo is None:
        topo = Topology(nbrs=None, deg=None, n=n, family="complete")
    slots = jnp.arange(s_count, dtype=jnp.int32)
    tables = () if topo.implicit else (topo.nbrs, topo.deg)
    if ch is not None:
        # schedule as runtime operands on the table tail (models/si.py
        # twin; ops/nemesis module doc)
        tables = tables + NE.sched_args(NE.build(fault, n))

    def step_tabled(state: SwimState, *tbl) -> SwimState:
        tbl, sched = NE.split_tables(ch, tbl)
        nbrs, deg = tbl if tbl else (None, None)
        # O(N) buffers built in-trace (iota + small scatters), so the
        # compile request carries no big inline constants
        ids = jnp.arange(n, dtype=jnp.int32)
        alive_base = base_alive(n, dead_nodes, fault)
        rkey = jax.random.fold_in(state.base_key, state.round)
        alive_now = jnp.where(state.round >= fail_round, alive_base, True)
        dp = drop_prob
        if ch is not None:
            # scripted crash/recover churn: down for die <= r < rec
            # (ops/nemesis) — a recovered subject refutes its own
            # suspicion (step 4) unless the timer already confirmed it
            alive_now = alive_now & ~((sched.die <= state.round)
                                      & (state.round < sched.rec))
            if ramped:
                dp = NE.drop_at(sched, state.round)
        subj_gids = subject_window(state.round, s_count, n, rotate,
                                   epoch_rounds)
        subj_alive = alive_now[subj_gids]
        if rotate:   # epoch boundary: fresh view state for the new window
            boundary = (state.round > 0) & (state.round % epoch_rounds == 0)
            wire_prev = jnp.where(boundary, 0, state.wire)
            timer_prev = jnp.where(boundary, 0, state.timer)
        else:
            wire_prev, timer_prev = state.wire, state.timer
        wire0 = wire_prev

        # 1-2: probe + suspect -------------------------------------------
        if proto.swim_rng == "packed":
            (subj, d_drop, proxy_ids, to_p, p_to_s,
             diss_targets) = packed_round_draws(
                rkey, ids, s_count, n, proxies, fanout, dp,
                nbrs=nbrs, deg=deg, sentinel=n, force=ramped)
        else:
            subj, d_drop, proxy_ids, to_p, p_to_s = probe_draws(
                rkey, ids, s_count, n, proxies, dp, force=ramped)
            diss_targets = None
        direct_ok = subj_alive[subj] & ~d_drop
        proxy_ok = (alive_now[proxy_ids] & ~to_p & ~p_to_s
                    & subj_alive[subj][:, None])
        indirect_ok = jnp.any(proxy_ok, axis=1)
        fail = alive_now & ~direct_ok & ~indirect_ok          # [N]
        onehot = jax.nn.one_hot(subj, s_count, dtype=jnp.bool_)
        suspectable = (wire0 < DEAD_WIRE) & onehot & fail[:, None]
        wire1 = jnp.where(suspectable, wire0 | 1, wire0)

        # probe message accounting: direct ping (+ack on success); on direct
        # failure, 4 messages per proxy path attempted (SWIM ping-req chain)
        msgs_probe = (jnp.sum(alive_now & direct_ok) * 2.0
                      + jnp.sum(alive_now & ~direct_ok)
                      * (1.0 + 4.0 * proxies))

        # 3: dissemination (scatter-max of wire rows) --------------------
        if diss_targets is None:
            dkey = jax.random.fold_in(rkey, _DISS_TAG)
            targets = sample_peers(dkey, ids, topo, fanout,
                                   exclude_self=True,
                                   local_nbrs=nbrs, local_deg=deg)
        else:
            targets = diss_targets
        targets = jnp.where(alive_now[:, None], targets, n)   # dead: silent
        recv = disseminate_max(targets, wire1, n, proto.swim_diss,
                               max_rounds)
        wire2 = jnp.maximum(wire1, recv)
        msgs_diss = jnp.sum(targets < n).astype(jnp.float32)

        # 4: refutation (alive subjects bump incarnation over suspicion) --
        self_view = wire2[subj_gids, slots]                    # [S]
        refuted = jnp.where(
            subj_alive & (self_view % 2 == 1) & (self_view < DEAD_WIRE),
            (self_view // 2 + 1) * 2, self_view)
        wire3 = wire2.at[subj_gids, slots].set(refuted)

        # 5: suspicion timers + confirm ----------------------------------
        is_susp = (wire3 % 2 == 1) & (wire3 < DEAD_WIRE)
        held = is_susp & (wire3 == wire_prev)
        timer = jnp.where(held, timer_prev + 1,
                          jnp.where(is_susp, 1, 0))
        confirm = timer >= t_confirm
        wire4 = jnp.where(confirm, DEAD_WIRE, wire3)
        timer = jnp.where(confirm, 0, timer)

        # dead nodes are frozen observers (no probe/diss/merge above was
        # theirs; freeze their rows too — within the epoch; a rotating
        # boundary resets every row, dead observers' stale views included)
        wire_f = jnp.where(alive_now[:, None], wire4, wire_prev)
        timer_f = jnp.where(alive_now[:, None], timer, timer_prev)
        return SwimState(wire=wire_f, timer=timer_f,
                         round=state.round + 1, base_key=state.base_key,
                         msgs=state.msgs + msgs_probe + msgs_diss)

    return bind_tables(step_tabled, tables, tabled)


def detection_fraction(state: SwimState, dead_subjects, alive_now=None,
                       subj_gids=None) -> jax.Array:
    """Fraction of (alive-observer, dead-subject) pairs confirmed DEAD —
    the SWIM convergence metric (completeness).

    ``dead_subjects`` are GLOBAL node ids.  ``subj_gids`` maps window slots
    to global ids (``subject_window``); default is the fixed window
    ``0..S-1``, in which case out-of-window dead ids are an error.  With a
    rotating window, dead ids outside the current window simply contribute
    no pairs (fraction over in-window dead subjects only; 0.0 when none)."""
    status = decode_status(state.wire)                    # [N, S]
    s_count = status.shape[1]
    if subj_gids is None:
        if any(s >= s_count for s in dead_subjects):
            raise ValueError(
                f"dead_subjects {tuple(dead_subjects)} out of range: the "
                f"fixed window tracks nodes 0..{s_count - 1} only "
                "(set proto.swim_rotate for full-membership coverage)")
        subj_gids = jnp.arange(s_count, dtype=jnp.int32)
    dead_arr = jnp.asarray(tuple(dead_subjects), dtype=jnp.int32)
    dead = jnp.any(subj_gids[:, None] == dead_arr[None, :], axis=1)  # [S]
    obs = (status == DEAD) & dead[None, :]
    if alive_now is None:
        denom = status.shape[0] * jnp.maximum(dead.sum(), 1)
        return obs.sum() / denom
    w = alive_now.astype(jnp.float32)[:, None] * dead[None, :]
    return (obs * w).sum() / jnp.maximum(w.sum(), 1.0)
