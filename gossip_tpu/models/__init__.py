from gossip_tpu.models.state import SimState, init_state, alive_mask  # noqa: F401
from gossip_tpu.models.si import make_si_round, coverage  # noqa: F401
