"""SI-family gossip rounds: push / pull / push-pull / flood / anti-entropy.

One round is a pure function ``SimState -> SimState`` built once per
(protocol, topology, fault) config and jitted by the caller.  Semantics map
from the reference like so:

  reference (event-driven, main.go)       batched round (here)
  --------------------------------------  --------------------------------
  relay to all neighbors   (72-75)        ``flood`` mode (gather over row)
  dedup set receipt        (113, 66)      OR-merge into ``seen`` (idempotent)
  at-least-once retry      (80-87)        a lost push is simply re-sent in a
                                          later round because the sender stays
                                          active while infected
  ack-before-process       (109)          N/A — no blocking anywhere
  sender exclusion         (73-75)        omitted: changes message counts by
                                          O(1/degree), never the infected set

Fault injection (the analog of Maelstrom's external partitions, SURVEY.md §5):
``FaultConfig.node_death_rate`` statically kills nodes (they neither send,
respond, nor receive); ``drop_prob`` drops each (sender, target) edge use
per round, modeling lossy links healed by the next round's resend.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig
from gossip_tpu.models.state import SimState, alive_mask, bind_tables
from gossip_tpu.ops.propagate import flood_gather, pull_merge, push_delta
from gossip_tpu.ops.sampling import apply_drop, drop_mask, sample_peers
from gossip_tpu.topology.generators import Topology

# Sub-key tags so push and pull draws in the same round are independent.
# Drop keys are folded into the *round* key (not the push/pull key) because
# fold_in(pkey, small_tag) would collide with node small_tag's per-node
# sampling key (node keys are fold_in(pkey, node_id)).
PUSH_TAG, PULL_TAG, PUSH_DROP_TAG, PULL_DROP_TAG, FLOOD_DROP_TAG = (
    1, 2, 3, 4, 5)


def make_si_round(proto: ProtocolConfig, topo: Topology,
                  fault: Optional[FaultConfig] = None,
                  origin: int = 0, tabled: bool = False):
    """Build the single-device round step.  The sharded equivalent lives in
    :mod:`gossip_tpu.parallel.sharded` and must stay semantically identical
    (tested in tests/test_sharding.py).

    Returns ``step: SimState -> SimState``, or with ``tabled=True`` the pair
    ``(step, tables)`` where ``step(state, *tables)`` takes the topology's
    neighbor arrays as ARGUMENTS rather than closure constants — at 1M+
    nodes a closed-over table is serialized inline into the XLA compile
    request (models/swim.py doc).  O(N) iota/liveness buffers are built
    in-trace for the same reason."""
    n, k = topo.n, proto.fanout
    mode = proto.mode
    if mode == C.SWIM:
        raise ValueError("SWIM rounds are built by models/swim.py")
    if mode == C.RUMOR:
        raise ValueError("rumor-mongering rounds are built by "
                         "models/rumor.py (SIR state, not SI)")
    if mode == C.FLOOD and topo.implicit:
        raise ValueError("flood mode needs an explicit neighbor table")
    drop_prob = 0.0 if fault is None else fault.drop_prob
    tables = () if topo.implicit else (topo.nbrs, topo.deg)
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)
    if ch is not None:
        # schedule as runtime OPERANDS: built ONCE on the host and
        # appended to the step's table arguments, so the compiled loop
        # carries schedule shapes but no schedule content (ops/nemesis
        # module doc — one executable serves a whole scenario family)
        tables = tables + NE.sched_args(NE.build(fault, n))

    def step_tabled(state: SimState, *tbl):
        tbl, sched = NE.split_tables(ch, tbl)
        nbrs_t, deg_t = tbl if tbl else (None, None)
        ids = jnp.arange(n, dtype=jnp.int32)
        rkey = jax.random.fold_in(state.base_key, state.round)
        seen = state.seen
        if ch is not None:
            # churn path: per-round liveness / drop prob / cut from the
            # schedule operands, indexed by the loop counter
            alive = NE.alive_rows(sched, NE.base_alive_or_ones(
                fault, n, origin), state.round)
            dp = NE.drop_at(sched, state.round)
            cut = NE.cut_at(sched, state.round)
        else:
            alive = alive_mask(fault, n, origin)  # in-trace, None-free path
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        # What peers can observe of node i: dead nodes go dark.
        visible = seen if alive is None else seen & alive[:, None]
        delta = jnp.zeros_like(seen)
        msgs = state.msgs

        if mode in (C.PUSH, C.PUSH_PULL):
            pkey = jax.random.fold_in(rkey, PUSH_TAG)
            targets0 = sample_peers(pkey, ids, topo, k, proto.exclude_self,
                                    local_nbrs=nbrs_t, local_deg=deg_t)
            targets = apply_drop(rkey, PUSH_DROP_TAG, ids,
                                 targets0, dp, n, force=ch is not None)
            if ch is not None:
                targets = NE.partition_targets(cut, ids, targets, n)
            sender_active = jnp.any(visible, axis=1)          # [N]
            valid = (targets < n) & sender_active[:, None]    # [N, k]
            delta = delta | push_delta(n, jnp.where(valid, targets, n),
                                       visible)
            msgs = msgs + jnp.sum(valid).astype(jnp.float32)
            if ch is not None:
                lost = lost + NE.lost_count(targets0, targets,
                                            sender_active, n)

        if mode in (C.PULL, C.PUSH_PULL) or mode == C.ANTI_ENTROPY:
            qkey = jax.random.fold_in(rkey, PULL_TAG)
            partners0 = sample_peers(qkey, ids, topo, k, proto.exclude_self,
                                     local_nbrs=nbrs_t, local_deg=deg_t)
            partners = apply_drop(rkey, PULL_DROP_TAG, ids,
                                  partners0, dp, n, force=ch is not None)
            if ch is not None:
                partners = NE.partition_targets(cut, ids, partners, n)
            pulled = pull_merge(visible, partners, n)
            # dead nodes neither request nor receive (alive-mask contract)
            if alive is not None:
                partners = jnp.where(alive[:, None], partners, n)
            n_req = jnp.sum(partners < n).astype(jnp.float32)
            if ch is not None:
                req_active = (jnp.ones((n,), jnp.bool_) if alive is None
                              else alive)
                lost_pull = NE.lost_count(partners0, partners,
                                          req_active, n)
                if mode == C.ANTI_ENTROPY and proto.period > 1:
                    # quiescent rounds send nothing, so nothing is lost
                    lost_pull = jnp.where(
                        (state.round % proto.period) == 0, lost_pull, 0.0)
                lost = lost + lost_pull
            if mode == C.ANTI_ENTROPY:
                # Classic anti-entropy (Demers et al. §1.2 "anti-entropy"):
                # the periodic exchange reconciles BOTH directions — the
                # initiator pulls the partner's digest AND pushes its own
                # state back, so the pair converges to the union in one
                # exchange.  3 messages per exchange: request + digest
                # response + reverse delta.  Off-rounds are quiescent, and
                # lax.cond (not a mask) skips the reverse scatter's work on
                # them.
                if proto.period > 1:
                    on = (state.round % proto.period) == 0
                    back = jax.lax.cond(
                        on, lambda _: push_delta(n, partners, visible),
                        lambda _: jnp.zeros_like(pulled), None)
                    pulled = jnp.where(on, pulled, False)
                    n_req = jnp.where(on, n_req, 0.0)
                else:
                    back = push_delta(n, partners, visible)
                delta = delta | pulled | back
                msgs = msgs + 3.0 * n_req
            else:
                delta = delta | pulled
                msgs = msgs + 2.0 * n_req  # request + digest response

        if mode == C.FLOOD:
            nbrs = nbrs_t
            if ch is not None:
                # churn path: always draw (traced p), then cut the
                # cross-partition edges; a destroyed edge is retried
                # next round (at-least-once, main.go:80-87)
                dropped = drop_mask(rkey, FLOOD_DROP_TAG, ids,
                                    nbrs.shape[1], dp)
                nbrs = jnp.where(dropped, jnp.int32(n), nbrs)
                nbrs = NE.partition_targets(cut, ids, nbrs, n)
                # lost edge uses whose SENDER (the neighbor the gather
                # reads from) had something to say
                act = jnp.any(visible, axis=1)
                edge_live = (nbrs_t < n) & act[jnp.clip(nbrs_t, 0, n - 1)]
                lost = lost + jnp.sum(edge_live & (nbrs >= n),
                                      dtype=jnp.float32)
            elif drop_prob > 0.0:
                # lossy links drop individual edge uses this round; the edge
                # is retried next round (at-least-once, main.go:80-87)
                dropped = drop_mask(rkey, FLOOD_DROP_TAG, ids,
                                    nbrs.shape[1], drop_prob)
                nbrs = jnp.where(dropped, jnp.int32(n), nbrs)
            delta = flood_gather(visible, nbrs, n)
            sender_active = jnp.any(visible, axis=1)
            msgs = msgs + jnp.sum(
                jnp.where(sender_active, deg_t, 0)).astype(jnp.float32)

        if alive is not None:
            delta = delta & alive[:, None]  # dead nodes receive nothing
        out = SimState(seen=seen | delta, round=state.round + 1,
                       base_key=state.base_key, msgs=msgs)
        return (out, lost) if ch is not None else out

    return bind_tables(step_tabled, tables, tabled)


def coverage(seen: jax.Array,
             alive: Optional[jax.Array] = None) -> jax.Array:
    """Min-over-rumors fraction of (alive) nodes that have each rumor.

    The Maelstrom checker's invariant is "every broadcast eventually appears
    in every node's read" (SURVEY.md §4); with dead nodes the reachable
    population is the alive set.
    """
    if alive is None:
        return jnp.min(jnp.mean(seen.astype(jnp.float32), axis=0))
    w = alive.astype(jnp.float32)
    per_rumor = (seen.astype(jnp.float32) * w[:, None]).sum(0) / w.sum()
    return jnp.min(per_rumor)
