"""Simulation state: the whole cluster as a few arrays.

The reference keeps, per process: an append-only message log + dedup set
behind an RWMutex (main.go:22-58) and a topology map (main.go:60-63).  The
batched equivalent of the *dedup set across the whole cluster* is one array:

    seen: bool[N, R]    seen[i, r]  <=>  node i has received rumor r

The append-only ordered log exists to serve ``read`` (main.go:123-130); order
is arrival order with no guarantee (SURVEY.md §2.2.9), so the set view is the
semantically load-bearing part — the Maelstrom checker itself treats messages
as a set (SURVEY.md §2.2.5).  The Maelstrom-compat runtime
(:mod:`gossip_tpu.runtime.maelstrom_node`) keeps a real ordered log, since it
must answer real ``read`` RPCs.

There are deliberately **no locks anywhere**: one round = one XLA program, so
the reference's dedup TOCTOU race and unsynchronized topology write
(SURVEY.md §2.2.5-6) are structurally impossible here.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig


class SimState(NamedTuple):
    """Carried through ``lax.scan`` / ``lax.while_loop`` rounds."""

    seen: jax.Array      # bool[N, R]
    round: jax.Array     # int32 scalar — round counter (the synchronous clock)
    base_key: jax.Array  # PRNG key; round keys are fold_in(base_key, round)
    msgs: jax.Array      # float32 scalar — cumulative messages sent


def init_state(run: RunConfig, proto: ProtocolConfig, n: int) -> SimState:
    """Rumor r starts at node (origin + r) % n — the ``broadcast`` injection
    point (each Maelstrom client broadcast lands at one node, main.go:102)."""
    r = proto.rumors
    origins = (run.origin + jnp.arange(r)) % n
    seen = jnp.zeros((n, r), jnp.bool_).at[origins, jnp.arange(r)].set(True)
    return SimState(
        seen=seen,
        round=jnp.int32(0),
        base_key=jax.random.key(run.seed),
        msgs=jnp.float32(0.0),
    )


def static_death_draw(fault: Optional[FaultConfig],
                      n: int) -> Optional[jax.Array]:
    """The one canonical static-death draw: the same FaultConfig kills the
    same node set in every kernel family (SI here, SWIM in models/swim.py),
    so cross-protocol experiments on one cluster line up."""
    if fault is None or fault.node_death_rate <= 0.0:
        return None
    key = jax.random.key(fault.seed ^ 0x5157)
    return ~jax.random.bernoulli(key, fault.node_death_rate, (n,))


def alive_mask(fault: Optional[FaultConfig], n: int,
               origin: int = 0) -> Optional[jax.Array]:
    """Static dead-node mask (None when no faults — keeps the fault-free hot
    path free of masking work).  The rumor origin is pinned alive so the
    simulation is non-degenerate."""
    alive = static_death_draw(fault, n)
    if alive is None:
        return None
    return alive.at[origin].set(True)


def bind_tables(step_tabled, tables: tuple, tabled: bool):
    """Shared epilogue for the round-step factories.

    ``tabled=True`` exposes ``(step_tabled, tables)`` so callers pass the
    topology arrays through the jit boundary as ARGUMENTS — a closed-over
    1M+-row table is serialized inline into the XLA compile request, which
    remote-compile endpoints reject (models/swim.py doc).  ``tabled=False``
    binds them as a convenience closure for small-n callers."""
    if tabled:
        return step_tabled, tables

    def step(state):
        return step_tabled(state, *tables)

    return step
