"""Bit-packed pull / anti-entropy rounds: the gather-only TPU fast path.

The measured cost model on the target TPU (methodology: 20-iteration
``fori_loop`` microbenches at N=10M, see bench.py notes):

  * XLA scatter  ~ 10.6 ns/element  (the push half of push-pull)
  * XLA gather   ~  8.0 ns/element  (bool), ~7.0 ns/element (uint32 word)
  * everything else in a round fuses to ~5 ms at N=10M

so a *pull-only* round costs one gather and nothing else, and pull's
endgame is quadratic (the uninfected fraction squares each round: an
uninfected node stays uninfected only if its sampled partner was also
uninfected), giving ~log2(N) + O(log log N) rounds to 99%.  Measured at
N=10M: pull 27 rounds / 2.30 s vs push-pull 17 rounds / 3.54 s — pull wins
on wall-clock by 1.5x despite more rounds.  Packing (ops/bitpack.py) then
moves 32 rumors per gathered word.

Semantics are EXACTLY models/si.make_si_round's PULL / ANTI_ENTROPY modes —
same RNG tags, same per-global-node-id keying, same message accounting —
verified bitwise in tests/test_packed.py.  Push modes are deliberately
absent: scatter-OR is not an XLA primitive and the scatter is the expensive
half; use models/si.py when push semantics are required.  The one
exception is anti-entropy's reverse delta (the exchange is bidirectional),
which unpacks to bools for the scatter on exchange rounds only.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.state import (SimState, alive_mask, bind_tables,
                                     init_state)
from gossip_tpu.ops.bitpack import coverage_packed, pack, unpack
from gossip_tpu.ops.propagate import push_delta
from gossip_tpu.ops.sampling import apply_drop, sample_peers
from gossip_tpu.topology.generators import Topology


def init_packed_state(run: RunConfig, proto: ProtocolConfig,
                      n: int) -> SimState:
    """SimState whose ``seen`` is uint32[N, ceil(R/32)] (packed)."""
    st = init_state(run, proto, n)
    return st._replace(seen=pack(st.seen))


def pull_merge_packed(packed_all: jax.Array, partners: jax.Array,
                      sentinel: int) -> jax.Array:
    """OR of k sampled peers' packed digest words -> uint32[N_local, W].

    The packed twin of ops/propagate.pull_merge: one uint32 gather moves 32
    rumor bits."""
    valid = partners < sentinel
    safe = jnp.minimum(partners, sentinel - 1)
    got = packed_all[safe]                        # [Nl, k, W] uint32
    got = jnp.where(valid[:, :, None], got, jnp.uint32(0))
    out = got[:, 0, :]
    for j in range(1, got.shape[1]):
        out = out | got[:, j, :]
    return out


def make_packed_round(proto: ProtocolConfig, topo: Topology,
                      fault: Optional[FaultConfig] = None,
                      origin: int = 0,
                      sampler: str = "threefry",
                      sampler_seed: int = 0,
                      tabled: bool = False):
    """Packed PULL / ANTI_ENTROPY round step.

    ``sampler="threefry"`` (default) is RNG-identical to
    models/si.make_si_round — same tags, bitwise-equal trajectories.
    ``sampler="pallas"`` draws partners with the TPU hardware PRNG
    (ops/pallas_sampling — different stream, implicit complete graph only,
    the opt-in bench fast path).

    ``tabled=True`` returns ``(step, tables)`` with the topology arrays as
    step ARGUMENTS (no O(N) jit closure constants — models/swim.py doc);
    the liveness mask is built in-trace."""
    n, k = topo.n, proto.fanout
    mode = proto.mode
    if mode not in (C.PULL, C.ANTI_ENTROPY):
        raise ValueError(
            f"packed rounds support pull/antientropy only, got {mode!r} "
            "(push needs scatter-OR, which XLA does not have — see module "
            "doc)")
    if sampler not in ("threefry", "pallas"):
        raise ValueError(f"unknown sampler {sampler!r}")
    if sampler == "pallas" and not topo.implicit:
        raise ValueError("the pallas sampler draws on the implicit "
                         "complete graph only")
    drop_prob = 0.0 if fault is None else fault.drop_prob
    tables = () if topo.implicit else (topo.nbrs, topo.deg)
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)
    if ch is not None:
        # schedule as runtime operands on the table tail (models/si.py
        # twin; ops/nemesis module doc)
        tables = tables + NE.sched_args(NE.build(fault, n))

    def step_tabled(state: SimState, *tbl):
        tbl, sched = NE.split_tables(ch, tbl)
        nbrs_t, deg_t = tbl if tbl else (None, None)
        ids = jnp.arange(n, dtype=jnp.int32)
        rkey = jax.random.fold_in(state.base_key, state.round)
        packed = state.seen
        if ch is not None:
            # churn path: per-round liveness / drop prob / cut from the
            # schedule operands (models/si.py twin)
            alive = NE.alive_rows(sched, NE.base_alive_or_ones(
                fault, n, origin), state.round)
            dp = NE.drop_at(sched, state.round)
            cut = NE.cut_at(sched, state.round)
        else:
            alive = alive_mask(fault, n, origin)  # in-trace
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        visible = packed if alive is None else jnp.where(
            alive[:, None], packed, jnp.uint32(0))
        if sampler == "pallas":
            from gossip_tpu.ops.pallas_sampling import sample_peers_fast
            partners = sample_peers_fast(sampler_seed, state.round, n, n, k,
                                         proto.exclude_self)
        else:
            qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
            partners = sample_peers(qkey, ids, topo, k, proto.exclude_self,
                                    local_nbrs=nbrs_t, local_deg=deg_t)
        partners0 = partners
        partners = apply_drop(rkey, si_mod.PULL_DROP_TAG, ids,
                              partners0, dp, n, force=ch is not None)
        if ch is not None:
            partners = NE.partition_targets(cut, ids, partners, n)
        pulled = pull_merge_packed(visible, partners, n)
        if alive is not None:
            partners = jnp.where(alive[:, None], partners, n)
        n_req = jnp.sum(partners < n).astype(jnp.float32)
        if ch is not None:
            lost_pull = NE.lost_count(partners0, partners, alive, n)
            if mode == C.ANTI_ENTROPY and proto.period > 1:
                # quiescent rounds send nothing, so nothing is lost
                lost_pull = jnp.where(
                    (state.round % proto.period) == 0, lost_pull, 0.0)
            lost = lost + lost_pull
        if mode == C.ANTI_ENTROPY:
            # Bidirectional reconciliation (twin of models/si.py): the
            # initiator's digest also scatters back into the partner's row.
            # XLA has no scatter-OR on words, so the push-back unpacks to
            # bools for the scatter and repacks — lax.cond confines that
            # cost to exchange rounds; the pull direction stays a pure
            # word gather.
            def reverse_delta(_):
                return pack(push_delta(n, partners,
                                       unpack(visible, proto.rumors)))

            mfac = 3.0    # request + digest response + reverse delta
            if proto.period > 1:
                on = (state.round % proto.period) == 0
                back = jax.lax.cond(on, reverse_delta,
                                    lambda _: jnp.zeros_like(pulled), None)
                pulled = jnp.where(on, pulled, jnp.uint32(0))
                n_req = jnp.where(on, n_req, 0.0)
            else:
                back = reverse_delta(None)
            pulled = pulled | back
        else:
            mfac = 2.0    # request + digest response
        if alive is not None:
            pulled = jnp.where(alive[:, None], pulled, jnp.uint32(0))
        out = SimState(seen=packed | pulled, round=state.round + 1,
                       base_key=state.base_key,
                       msgs=state.msgs + mfac * n_req)
        return (out, lost) if ch is not None else out

    return bind_tables(step_tabled, tables, tabled)


def simulate_until_packed(proto: ProtocolConfig, topo: Topology,
                          run: RunConfig,
                          fault: Optional[FaultConfig] = None,
                          timing: Optional[dict] = None):
    """while_loop to target coverage on packed state — the bench fast path.
    Returns (rounds, coverage, msgs, final_state).  ``timing``: pass a
    dict for the compile/steady AOT split (utils.trace.aot_timed)."""
    from gossip_tpu.ops import nemesis as NE
    step, tables = make_packed_round(proto, topo, fault, run.origin,
                                     tabled=True)
    step = NE.drop_lost(step, NE.get(fault))
    alive = NE.metric_alive(fault, topo.n, run.origin)
    init = init_packed_state(run, proto, topo.n)
    target = jnp.float32(run.target_coverage)
    r = proto.rumors

    @jax.jit
    def loop(state, *tbl):
        alive_t = NE.metric_alive(fault, topo.n, run.origin)
        def cond(s):
            return ((coverage_packed(s.seen, r, alive_t) < target)
                    & (s.round < run.max_rounds))
        def body(s):
            return step(s, *tbl)
        return jax.lax.while_loop(cond, body, state)

    from gossip_tpu.utils.trace import maybe_aot_timed
    final = maybe_aot_timed(loop, timing, init, *tables)
    return (int(final.round),
            float(coverage_packed(final.seen, r, alive)),
            float(final.msgs), final)


def compiled_until_packed(proto: ProtocolConfig, topo: Topology,
                          run: RunConfig,
                          fault: Optional[FaultConfig] = None,
                          sampler: str = "threefry"):
    """Compiled packed while-loop + fresh init (bench: compile/run split).
    Returns (loop, init, tables); call ``loop(state, *tables)``."""
    from functools import partial

    from gossip_tpu.ops import nemesis as NE
    step, tables = make_packed_round(proto, topo, fault, run.origin,
                                     sampler, run.seed, tabled=True)
    step = NE.drop_lost(step, NE.get(fault))
    init = init_packed_state(run, proto, topo.n)
    target = jnp.float32(run.target_coverage)
    r = proto.rumors

    @partial(jax.jit, donate_argnums=0)
    def loop(state, *tbl):
        alive = NE.metric_alive(fault, topo.n, run.origin)
        def cond(s):
            return ((coverage_packed(s.seen, r, alive) < target)
                    & (s.round < run.max_rounds))
        def body(s):
            return step(s, *tbl)
        return jax.lax.while_loop(cond, body, state)

    return loop, init, tables
