"""Headline benchmark: simulated node-rounds/sec/chip (BASELINE.md metric).

Runs the flagship config — multi-rumor push-pull SI epidemic broadcast on the
implicit complete graph (the 10M-node scale path: zero adjacency memory,
SURVEY.md §7) — to 99% coverage as ONE compiled ``lax.while_loop`` (no host
sync per round), and reports throughput as

    node_rounds_per_sec_per_chip = N * rounds / wall_seconds / n_chips

``vs_baseline`` is measured against the derived north-star rate from
BASELINE.json (the reference publishes no numbers — BASELINE.md): 10M nodes
to 99% coverage in <1 s on 8 chips at ~24 rounds -> 30e6 node-rounds/s/chip.

Prints exactly one JSON line.
"""

import json
import sys
import time

import jax

from gossip_tpu import config as C
from gossip_tpu.config import ProtocolConfig, RunConfig
from gossip_tpu.runtime.simulator import compiled_until
from gossip_tpu.topology import generators as G

# North-star-derived baseline rate (BASELINE.json: 10M nodes, 99% coverage,
# <1 s wall-clock, v4-8): 10e6 nodes * 24 rounds / 1 s / 8 chips.
BASELINE_NODE_ROUNDS_PER_SEC_PER_CHIP = 30.0e6


def main():
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    # Full 10M-node config on TPU; scaled down on CPU so CI stays fast.
    n = 10_000_000 if on_tpu else 500_000
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=1, rumors=1)
    run = RunConfig(target_coverage=0.99, max_rounds=128, seed=0)
    topo = G.complete(n)

    loop, init = compiled_until(proto, topo, run)
    # Warm-up executes + compiles; `loop` donates its argument, so rebuild
    # the init state for the timed run.
    warm = loop(init)
    jax.block_until_ready(warm.seen)
    rounds = int(warm.round)

    _, init2 = compiled_until(proto, topo, run)
    t0 = time.perf_counter()
    final = loop(init2)
    jax.block_until_ready(final.seen)
    dt = time.perf_counter() - t0

    # compiled_until is the single-device kernel: the work runs on one chip
    # regardless of how many are attached, so per-chip rate divides by 1.
    # (The multi-chip path is parallel.sharded, exercised by dryrun_multichip.)
    n_chips = 1
    rate = n * rounds / dt / n_chips
    print(json.dumps({
        "metric": "node_rounds_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": f"node-rounds/s/chip (N={n}, push-pull SI to 99% in "
                f"{rounds} rounds, {dt*1e3:.1f} ms, backend={backend})",
        "vs_baseline": round(rate / BASELINE_NODE_ROUNDS_PER_SEC_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
