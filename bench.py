"""Headline benchmark: simulated node-rounds/sec/chip (BASELINE.md metric).

Runs the measured-fastest exact configuration — **bit-packed pull gossip**
on the implicit complete graph (the 10M-node scale path, zero adjacency
memory) — to 99% coverage as ONE compiled ``lax.while_loop`` (no host sync
per round), and reports

    node_rounds_per_sec_per_chip = N * rounds / wall_seconds / n_chips

Why this configuration (all measured on the target chip via 20-iteration
``fori_loop`` microbenches + full while-loop runs at N=10M; the axon tunnel
memoizes identical executions, so naive repeat-timing lies — vary inputs or
chain state):

  * XLA scatter ~10.6 ns/elt, gather ~8.0 ns/elt (bool) / ~7.0 (uint32):
    the push half of push-pull costs more than the pull half.
  * Pull-only removes the scatter entirely and has a quadratic endgame
    (uninfected fraction squares per round): 27 rounds / 2.28 s at 10M vs
    push-pull's 17 rounds / 3.54 s.
  * Bit-packing (ops/bitpack.py) gathers uint32 words: 32 rumors per
    gathered element and 8x less digest traffic.
  * The pallas hw-PRNG sampler measured SLOWER than threefry here (fusion
    barrier; see ops/pallas_sampling.py) — threefry it is.

Result on v5e-1: ~118M node-rounds/s/chip vs the 48M of the push-pull
variant this bench used before.

``vs_baseline`` is against the derived north-star rate from BASELINE.json
(the reference publishes no numbers — BASELINE.md): 10M nodes to 99%
coverage in <1 s on 8 chips at ~24 rounds -> 30e6 node-rounds/s/chip.

Prints exactly one JSON line.
"""

import json
import sys
import time

import jax

from gossip_tpu.config import ProtocolConfig, RunConfig
from gossip_tpu.models.si_packed import compiled_until_packed
from gossip_tpu.topology import generators as G

# North-star-derived baseline rate (BASELINE.json: 10M nodes, 99% coverage,
# <1 s wall-clock, v4-8): 10e6 nodes * 24 rounds / 1 s / 8 chips.
BASELINE_NODE_ROUNDS_PER_SEC_PER_CHIP = 30.0e6


def main():
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    # Full 10M-node config on TPU; scaled down on CPU so CI stays fast.
    n = 10_000_000 if on_tpu else 500_000
    proto = ProtocolConfig(mode="pull", fanout=1, rumors=1)
    run = RunConfig(target_coverage=0.99, max_rounds=128, seed=0)
    topo = G.complete(n)

    loop, init = compiled_until_packed(proto, topo, run)
    # Warm-up executes + compiles; `loop` donates its argument, so rebuild
    # the init state for the timed run.
    warm = loop(init)
    jax.block_until_ready(warm.seen)
    rounds = int(warm.round)

    _, init2 = compiled_until_packed(proto, topo, run)
    t0 = time.perf_counter()
    final = loop(init2)
    jax.block_until_ready(final.seen)
    dt = time.perf_counter() - t0

    # the single-device packed kernel runs on one chip regardless of how
    # many are attached (multi-chip twin: parallel/sharded_packed.py, dry-
    # run by __graft_entry__.dryrun_multichip and parity-tested on the
    # 8-device CPU mesh in tests/test_packed.py)
    n_chips = 1
    rate = n * rounds / dt / n_chips
    print(json.dumps({
        "metric": "node_rounds_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": f"node-rounds/s/chip (N={n}, bit-packed pull SI to 99% in "
                f"{rounds} rounds, {dt*1e3:.1f} ms, backend={backend})",
        "vs_baseline": round(rate / BASELINE_NODE_ROUNDS_PER_SEC_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
