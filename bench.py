"""Headline benchmark: simulated node-rounds/sec/chip (BASELINE.md metric).

Runs the flagship configuration — 10M-node single-rumor pull gossip on the
implicit complete graph to 99% coverage — as ONE compiled ``lax.while_loop``
and reports

    node_rounds_per_sec_per_chip = N * rounds / wall_seconds / n_chips

On TPU the round step is the **fully-fused Pallas kernel**
(ops/pallas_round.py): the whole 10M-node bitmap lives node-packed in VMEM
(1.25 MB) and one ``pallas_call`` does hardware-PRNG partner sampling,
in-row dynamic gather, and OR-merge per round — no HBM gather at all.
History of this number on the same chip (v5e-1), honestly measured:

  * round 1, XLA push-pull bool path: 17 rounds / 3.54 s
  * round 1, XLA bit-packed pull (gather-bound, ~8 ns/elt, 84 ms/round):
    27 rounds / 2.28 s  -> 118M node-rounds/s/chip (vs_baseline 3.96)
  * round 2, fused Pallas round (this file): 26 rounds / ~80 ms
    (~3.1 ms/round) -> ~3.2B node-rounds/s/chip (vs_baseline ~108)

The fused kernel's sampling scheme and its distributional contract (exactly
uniform per-node partner marginals; 128 shared per-lane row shifts per
round) are documented in ops/pallas_round.py and validated against a numpy
model + mean-field trajectory tests in tests/test_pallas_round.py.

On CPU (CI) the bench falls back to the round-1 XLA bit-packed pull path at
a smaller N, since the fused kernel needs the TPU hardware PRNG.

``vs_baseline`` is against the derived north-star rate from BASELINE.json
(the reference publishes no numbers — BASELINE.md): 10M nodes to 99%
coverage in <1 s on 8 chips at ~24 rounds -> 30e6 node-rounds/s/chip.

Prints exactly one JSON line.
"""

import json
import os
import shlex
import subprocess
import sys
import time

# North-star-derived baseline rate (BASELINE.json: 10M nodes, 99% coverage,
# <1 s wall-clock, v4-8): 10e6 nodes * 24 rounds / 1 s / 8 chips.
BASELINE_NODE_ROUNDS_PER_SEC_PER_CHIP = 30.0e6


TARGET = 0.99


def _target_f32():
    # the loops exit on a float32 compare; check against the same threshold
    import jax.numpy as jnp
    return float(jnp.float32(TARGET))


def _bench_compile_split(loop, *args):
    """(compiled, {"cold_s", "warm_s"}): the loop's compile measured
    COLD into a fresh one-shot executable store, then WARM from it
    (utils/compile_cache.timed_split: jax's in-memory caches cleared
    in between, so warm = trace+lower+deserialize — the disk store,
    not a Python memo).  The split is the reproducible CPU-side
    compile-once signal the BENCH trajectory carries on boxes where no
    TPU rate moves (this one), and on TPU it decomposes the old
    aggregate "compile+warm" wall.  The temp store keeps the
    measurement hermetic: bench's cold number can never be served by —
    or pollute — the operator's persistent cache
    (GOSSIP_COMPILE_CACHE="" policy, _hermetic_cpu_env)."""
    import shutil
    import tempfile

    from gossip_tpu.utils import compile_cache
    tmp = tempfile.mkdtemp(prefix="gossip_bench_split_")
    try:
        compiled, cold_s, warm_s, statuses = compile_cache.timed_split(
            loop, *args, cache_dir=tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    # statuses ride along so the artifact is self-describing: anything
    # but (miss, hit) means warm_s was NOT a store round-trip (store
    # failed -> a second full compile; store unavailable -> the warm
    # leg was skipped and warm_s is null) and must not be read as a
    # warm number
    return compiled, {"cold_s": round(cold_s, 4),
                      "warm_s": (round(warm_s, 4)
                                 if warm_s is not None else None),
                      "statuses": list(statuses)}


def run_tpu_fused(n):
    import jax
    from gossip_tpu.ops.pallas_round import (
        compiled_until_fused, coverage_node_packed, init_fused_state)
    from gossip_tpu.utils.trace import steady_timed
    loop, init = compiled_until_fused(n, seed=0, target_coverage=TARGET)
    compiled, split = _bench_compile_split(loop, init)
    warm = compiled(init)       # warm-up run; donated, so rebuild init
    jax.block_until_ready(warm.table)
    init2 = init_fused_state(n)
    jax.block_until_ready(init2.table)
    # steady_timed: the measured wall is ONE cached-executable run — the
    # headline rate decomposes by construction (compile reported
    # alongside, never mixed in; round-2 verdict contract)
    final, dt = steady_timed(compiled, init2)
    rounds = int(final.round)
    cov = float(coverage_node_packed(final.table, n))
    assert cov >= _target_f32(), f"coverage {cov} below target at {rounds}"
    warm_str = (f"{split['warm_s']:.1f} s" if split["warm_s"] is not None
                else "skipped")
    return rounds, dt, ("fused-pallas pull SI, steady wall (compile "
                        f"cold {split['cold_s']:.1f} s / warm "
                        f"{warm_str} excluded)"), split


def run_xla_packed(n):
    import jax

    from gossip_tpu.config import ProtocolConfig, RunConfig
    from gossip_tpu.models.si_packed import (
        compiled_until_packed, init_packed_state)
    from gossip_tpu.ops.bitpack import coverage_packed
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode="pull", fanout=1, rumors=1)
    run = RunConfig(target_coverage=TARGET, max_rounds=128, seed=0)
    topo = G.complete(n)
    loop, init, tables = compiled_until_packed(proto, topo, run)
    compiled, split = _bench_compile_split(loop, init, *tables)
    warm = compiled(init, *tables)
    jax.block_until_ready(warm.seen)
    init2 = init_packed_state(run, proto, n)
    jax.block_until_ready(init2.seen)
    t0 = time.perf_counter()
    final = compiled(init2, *tables)
    jax.block_until_ready(final.seen)
    dt = time.perf_counter() - t0
    rounds = int(final.round)
    cov = float(coverage_packed(final.seen, proto.rumors, None))
    assert cov >= _target_f32(), f"coverage {cov} below target at {rounds}"
    return rounds, dt, "bit-packed pull SI (XLA fallback)", split


def run_churn_families(on_tpu):
    """The nemesis families on the scoreboard line (the traced-operand
    PR): per-family walls so the BENCH trajectory carries the fault
    path, not just the fault-free flagship.

    * ``churn_heal`` — the flagship pull config under a FULL nemesis
      program (crash/recover churn + partition window + drop ramp) run
      to target through the XLA kernels; rate is node-rounds/s on this
      backend (schedules are runtime operands, so this is the same
      compiled shape every scenario shares).
    * ``churn_sweep`` — K=8 mixed scenarios through ONE compiled loop
      (parallel/sweep.churn_sweep_curves); ``first_ms`` pays the one
      compile, ``warm_ms`` re-runs a DIFFERENT scenario family of the
      same shapes (pure executable reuse — the amortization this PR
      exists for; committed deep record:
      artifacts/ledger_churn_sweep_r11.jsonl, 8-scenario warm path vs
      solo recompiles)."""
    from gossip_tpu.config import (ChurnConfig, FaultConfig,
                                   ProtocolConfig, RunConfig)
    from gossip_tpu.models.si_packed import simulate_until_packed
    from gossip_tpu.parallel.sweep import churn_sweep_curves
    from gossip_tpu.topology import generators as G

    n = 1_000_000 if on_tpu else 100_000
    heal_end = 6
    topo = G.complete(n)
    proto = ProtocolConfig(mode="pull", fanout=1, rumors=1)
    run = RunConfig(target_coverage=TARGET, max_rounds=128, seed=0)
    fault = FaultConfig(drop_prob=0.02, seed=0, churn=ChurnConfig(
        events=((1, 1, 4), (2, 2, -1)),
        partitions=((0, heal_end, n // 2),),
        ramp=(0, 4, 0.0, 0.1)))
    t0 = time.perf_counter()
    rounds, cov, _msgs, _ = simulate_until_packed(proto, topo, run,
                                                  fault)
    heal_s = time.perf_counter() - t0
    heal = {"n": n, "rounds": rounds, "coverage": round(cov, 6),
            "wall_ms": round(heal_s * 1e3, 1),
            "node_rounds_per_sec": round(n * rounds / heal_s, 1),
            "scenario": "2 churn events + partition [0,6) at n/2 + "
                        "ramp 0->0.1"}

    kn = 65_536 if on_tpu else 8_192
    ktopo = G.complete(kn)
    kproto = ProtocolConfig(mode="pull", fanout=1, rumors=1)
    krun = RunConfig(target_coverage=TARGET, max_rounds=32, seed=0)

    def family(salt):
        # the ONE shared scenario-family generator (the dry run's
        # churn_sweep family and tools/churn_sweep_capture.py use it
        # too — same shape coverage on every surface)
        from gossip_tpu.ops import nemesis as NE
        return NE.mixed_scenarios(8, kn, salt=salt, drop_prob=0.01,
                                  seed=0, ramp_to=0.09)

    t0 = time.perf_counter()
    res = churn_sweep_curves(kproto, ktopo, krun, family(0))
    first_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    res = churn_sweep_curves(kproto, ktopo, krun, family(9))
    warm_ms = (time.perf_counter() - t0) * 1e3
    sweep = {"k": 8, "n": kn,
             "first_ms": round(first_ms, 1),
             "warm_ms": round(warm_ms, 1),
             "amortization": round(first_ms / max(warm_ms, 1e-9), 1),
             "converged": int((res.rounds_to_target >= 0).sum())}
    return {"churn_heal": heal, "churn_sweep": sweep}


def body():
    """The measurement itself — runs in a subprocess whose platform the
    parent has already probed (or forced to CPU)."""
    import jax

    from gossip_tpu.utils import trace as tr
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    # Full 10M-node config on TPU; scaled down on CPU so CI stays fast.
    n = 10_000_000 if on_tpu else 500_000
    # GOSSIP_PROFILE=<dir>: capture the whole measurement leg as a
    # jax.profiler trace (no-op unset; compat-probed).  A profiled leg's
    # walls carry profiler overhead — use the capture as a timeline, and
    # never commit its scoreboard line as a clean measurement.
    with tr.profile(f"bench:{backend}"):
        if on_tpu:
            rounds, dt, variant, split = run_tpu_fused(n)
        else:
            rounds, dt, variant, split = run_xla_packed(n)

    # Single-device flagship runs on one chip regardless of how many are
    # attached (multi-chip twin: parallel/sharded_packed.py, dry-run by
    # __graft_entry__.dryrun_multichip, parity-tested on the 8-device CPU
    # mesh in tests/test_packed.py).
    n_chips = 1
    rate = n * rounds / dt / n_chips
    # the nemesis families ride the same line (run AFTER the flagship
    # measurement so they can never perturb it)
    families = run_churn_families(on_tpu)
    print(json.dumps(measurement_line(rate, backend, n, variant, rounds, dt,
                                      compile_split=split,
                                      families=families,
                                      plan=plan_for_headline(backend),
                                      serving=serving_for_headline(),
                                      costs=costs_for_headline())))
    return 0


def last_tpu_capture():
    """Newest committed TPU bench capture from the hardware-refresh
    artifacts, as a machine-readable pointer (VERDICT r4 task 2: the
    scoreboard must survive a wedged-tunnel fallback — rounds 2-4 all
    recorded "null" while the proof of 116x sat one directory over in
    artifacts/hw_refresh_r04.json).  Returns None when no committed TPU
    capture exists.  ``vs_baseline`` on the live line stays null either
    way: this field POINTS at proof, it never substitutes for a live
    measurement."""
    repo = os.path.dirname(os.path.abspath(__file__))
    art_dir = os.path.join(repo, "artifacts")
    best = None
    try:
        names = sorted(os.listdir(art_dir))
    except OSError:
        return None
    for name in names:
        # lexicographic r01 < r02 < ... ordering; later rounds win.
        # .smoke rehearsal artifacts are hermetic-CPU by construction
        # and excluded by the backend check anyway.
        if not (name.startswith("hw_refresh_r") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(art_dir, name)) as f:
                steps = {r.get("step"): r for r in json.load(f)}
        except (OSError, ValueError, TypeError, AttributeError):
            continue
        step = steps.get("bench") or {}
        line = step.get("result") or {}
        if step.get("ok") and line.get("backend") == "tpu":
            best = {
                "artifact": os.path.join("artifacts", name),
                "value": line.get("value"),
                "unit": line.get("unit"),
                "vs_baseline": line.get("vs_baseline"),
            }
    if best is not None:
        # provenance: the commit that captured the artifact (None when
        # uncommitted or git is unavailable — the pointer still stands)
        try:
            p = subprocess.run(
                ["git", "log", "-1", "--format=%H %cI", "--",
                 best["artifact"]],
                capture_output=True, text=True, timeout=30, cwd=repo)
            parts = p.stdout.strip().split()
            if p.returncode == 0 and len(parts) == 2:
                best["git_commit"], best["captured"] = parts
        except (OSError, subprocess.SubprocessError):
            pass
    return best


# The documented reference topology for planning the 100M headline off
# hardware (a v4-8-class host: 8 chips x 16 GiB HBM, one slice) — the
# CPU fallback line plans against THIS so the scoreboard always says
# what tiling the next TPU window should run; a real TPU line plans
# against the DETECTED topology instead.
REFERENCE_TPU_CHIPS = 8
REFERENCE_TPU_HBM_BYTES = 16 * 1024**3
HEADLINE_TARGET_N = 100_000_000
HEADLINE_RUMORS = 64


def plan_for_headline(backend):
    """Optional ``plan`` object for the scoreboard line (the scale-
    planner PR): what word-plane tiling the HBM budget model picks for
    the 100M-node headline — so when hardware returns, the headline
    can move to node-rounds/s/chip AT 100M with the tiling already
    decided.  Predicted peak bytes come from the plan; the measured
    side rides the newest committed scale record (predicted-vs-
    measured at ITS n — the model-validation evidence), since the
    bench never executes the 100M leg itself (that is the hw_refresh
    scale_plan step's job).  Returns None if the planner cannot load
    (this function must never cost the scoreboard its line — the
    last_tpu_capture wedge-resilience rule); an INFEASIBLE target
    returns the refusal, binding constraint named — the scoreboard
    must say which wall, not go quiet."""
    import jax

    try:
        from gossip_tpu.planner import budget as PB
        if backend == "tpu":
            # any of these can fail on an odd platform (memory_stats
            # None-or-raise, slice detection, a chip count the mesh
            # rule refuses) — the scoreboard line outranks the plan
            devs = jax.devices()
            stats = devs[0].memory_stats() or {}
            from gossip_tpu.parallel.multislice import detect_slices
            dev = PB.DeviceSpec(
                chips=len(devs),
                hbm_bytes_per_chip=int(
                    stats.get("bytes_limit",
                              REFERENCE_TPU_HBM_BYTES)),
                slices=detect_slices(devs))
            source = "detected"
        else:
            dev = PB.DeviceSpec(
                chips=REFERENCE_TPU_CHIPS,
                hbm_bytes_per_chip=REFERENCE_TPU_HBM_BYTES)
            source = "reference"
        out = {"target_n": HEADLINE_TARGET_N,
               "rumors": HEADLINE_RUMORS, "chips": dev.chips,
               "hbm_bytes_per_chip": dev.hbm_bytes_per_chip,
               "slices": dev.slices, "source": source}
        try:
            plan = PB.plan_scale(HEADLINE_TARGET_N,
                                 rumors=HEADLINE_RUMORS, device=dev,
                                 fanout=1, max_rounds=64)
        except PB.InfeasiblePlanError as e:
            out.update(infeasible=str(e), binding=e.binding)
            return out
        out.update(tiles=plan.tiles, bucket_words=plan.bucket_words,
                   predicted_peak_device_bytes=
                   plan.predicted_peak_device_bytes,
                   binding=plan.binding)
        out["record"] = last_scale_record()
        return out
    except Exception:
        return None


def last_scale_record():
    """Newest committed streamed-scale record's predicted-vs-measured
    pair (artifacts/ledger_scale_r*.jsonl, .smoke excluded) — the
    evidence that the budget model's predictions bound real
    allocations.  None when no committed record exists."""
    repo = os.path.dirname(os.path.abspath(__file__))
    art_dir = os.path.join(repo, "artifacts")
    best = None
    try:
        names = sorted(os.listdir(art_dir))
    except OSError:
        return None
    for name in names:
        if not (name.startswith("ledger_scale_r")
                and name.endswith(".jsonl") and ".smoke" not in name):
            continue
        try:
            from gossip_tpu.utils import telemetry
            events = telemetry.load_ledger(
                os.path.join(art_dir, name), run="last")
        except (OSError, ValueError):
            continue
        recs = [e for e in events if e.get("ev") == "scale_record"]
        if recs:
            r = recs[-1]
            best = {"artifact": os.path.join("artifacts", name),
                    "n": r.get("n"), "tiles": r.get("tiles"),
                    "predicted_peak_device_bytes":
                        r.get("predicted_peak_device_bytes"),
                    "measured_loop_bytes": r.get("measured_loop_bytes"),
                    # the pipelined-vs-serial pair (r23+): how much of
                    # the segment wall the three-stage pipeline hid,
                    # and the two A/B walls it was derived from
                    "overlap_efficiency": r.get("overlap_efficiency"),
                    "streamed_wall_ms": r.get("streamed_wall_ms"),
                    "serial_wall_ms": r.get("serial_wall_ms"),
                    "ok": r.get("ok")}
    return best


def serving_for_headline():
    """Optional ``serving`` object for the scoreboard line (the
    mesh-sharded serving PR): rps + p99 per devices-per-replica width
    from the newest committed meshserve capture
    (artifacts/ledger_meshserve_r*.jsonl, .smoke excluded) — so the
    serving trajectory joins the headline the way ``plan`` did for
    capacity.  Carries the capture's own honesty bits verbatim:
    ``scaling_resolved`` says whether the host could even express the
    device parallelism (tools/load_harness meshserve gate), and
    ``ok``/``devices_ratio`` are the gate's verdict, not re-derived.
    Returns None when no committed record exists or anything fails to
    parse — this function must never cost the scoreboard its line
    (the last_tpu_capture wedge-resilience rule)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    art_dir = os.path.join(repo, "artifacts")
    best = None
    try:
        names = sorted(os.listdir(art_dir))
        for name in names:
            if not (name.startswith("ledger_meshserve_r")
                    and name.endswith(".jsonl")
                    and ".smoke" not in name):
                continue
            try:
                from gossip_tpu.utils import telemetry
                events = telemetry.load_ledger(
                    os.path.join(art_dir, name), run="last")
            except (OSError, ValueError):
                continue
            gates = [e for e in events
                     if e.get("ev") == "meshserve_gate"]
            if not gates:
                continue
            g = gates[-1]
            legs = {}
            for label, leg in sorted((g.get("legs") or {}).items()):
                legs[label] = {"devices": leg.get("devices"),
                               "rps": leg.get("rps"),
                               "p99_ms": leg.get("p99_ms")}
            best = {"artifact": os.path.join("artifacts", name),
                    "ok": g.get("ok"),
                    "connections": g.get("connections"),
                    "devices_ratio": g.get("devices_ratio"),
                    "scaling_resolved": g.get("scaling_resolved"),
                    "legs": legs}
        return best
    except Exception:
        return None


def costs_for_headline():
    """Optional ``costs`` object for the scoreboard line (the
    observability PR): per-engine XLA cost attribution from the newest
    committed cost record (artifacts/ledger_cost_r*.jsonl, .smoke
    excluded) — the chokepoint's ``xla_compile`` events joined by
    tools/cost_report, plus the packed ``budget_xcheck`` verdict
    (measured ≤ predicted peak bytes at the forced-tile plan).  Null
    attribution fields ride verbatim (a backend without cost analysis
    recorded explicit nulls, never zeros).  Returns None when no
    committed record exists or anything fails to parse — this function
    must never cost the scoreboard its line (the last_tpu_capture
    wedge-resilience rule)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    art_dir = os.path.join(repo, "artifacts")
    best = None
    try:
        names = sorted(os.listdir(art_dir))
        for name in names:
            if not (name.startswith("ledger_cost_r")
                    and name.endswith(".jsonl")
                    and ".smoke" not in name):
                continue
            try:
                from gossip_tpu.utils import telemetry
                events = telemetry.load_ledger(
                    os.path.join(art_dir, name), run="last")
            except (OSError, ValueError):
                continue
            sys.path.insert(0, os.path.join(repo, "tools"))
            try:
                from cost_report import join_costs
            finally:
                sys.path.pop(0)
            joined = join_costs(events)
            if not joined["rows"]:
                continue
            engines = {}
            for r in joined["rows"]:
                eng = engines.setdefault(r["label"], {
                    "compile_ms": 0.0, "flops": None,
                    "bytes_accessed": None, "peak_bytes": None,
                    "bytes_per_node_round": None})
                eng["compile_ms"] = round(
                    eng["compile_ms"] + r["compile_ms"], 1)
                for k in ("flops", "bytes_accessed", "peak_bytes",
                          "bytes_per_node_round"):
                    if r.get(k) is not None:
                        eng[k] = max(eng[k] or 0, r[k])
            xc = [x for x in joined["xchecks"]
                  if x.get("engine") == "packed"] or joined["xchecks"]
            best = {"artifact": os.path.join("artifacts", name),
                    "engines": engines,
                    "budget_xcheck": xc[-1] if xc else None}
        return best
    except Exception:
        return None


def measurement_line(rate, backend, n, variant, rounds, dt,
                     compile_split=None, families=None, plan=None,
                     serving=None, costs=None):
    """The one-JSON-line scoreboard contract (tests/test_bench_contract.py).

    ``vs_baseline`` compares against a TPU-derived north-star rate, so it
    is only meaningful for a TPU measurement: off-TPU it is ``null`` and
    the machine-readable ``backend`` field says what actually ran — a CPU
    fallback can never masquerade as a TPU perf regression/improvement
    (the round-2 scoreboard read a wedged-tunnel CPU fallback as 0.21x).
    A fallback line additionally carries ``last_tpu``, a pointer to the
    newest committed TPU capture, so a wedge can hide the live number
    but never the proof.

    ``compile_split`` (compile-once PR): the probe's cold/warm compile
    walls — cold a real XLA compile, warm the same program loaded from
    a fresh one-shot executable store (_bench_compile_split).  The
    machine-readable warm-start proof on boxes where the rate itself
    cannot move; the parent re-emits the whole line into the run
    ledger, so the split lands there too.

    ``families`` (the traced-operand PR): per-family nemesis walls —
    ``churn_heal`` (the flagship config under a full fault program)
    and ``churn_sweep`` (K scenarios, one executable, with the
    first/warm amortization split) — ride the line the same optional
    way, honestly tagged by the line's own ``backend``.

    ``plan`` (the scale-planner PR): the 100M-node headline's capacity
    plan — target N, tiles/bucket, predicted peak device bytes against
    the detected (TPU) or reference (fallback) topology, plus the
    newest committed scale record's predicted-vs-measured pair — so
    the scoreboard names the tiling the next hardware window should
    run (:func:`plan_for_headline`).

    ``serving`` (the mesh-sharded serving PR): rps + p99 per
    devices-per-replica width from the newest committed meshserve
    capture, with the gate's own ``ok``/``devices_ratio``/
    ``scaling_resolved`` verdict bits carried verbatim
    (:func:`serving_for_headline`).

    ``costs`` (the observability PR): per-engine XLA cost attribution
    and the packed budget cross-check verdict from the newest
    committed cost record (:func:`costs_for_headline`) — nulls stay
    nulls, the record-never-gate convention."""
    on_tpu = backend == "tpu"
    line = {
        "metric": "node_rounds_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": f"node-rounds/s/chip (N={n}, {variant} to 99% in "
                f"{rounds} rounds, {dt*1e3:.1f} ms, backend={backend})",
        "vs_baseline": (round(rate / BASELINE_NODE_ROUNDS_PER_SEC_PER_CHIP, 4)
                        if on_tpu else None),
        "backend": backend,
    }
    if compile_split is not None:
        line["compile_split"] = compile_split
    if families is not None:
        line["families"] = families
    if plan is not None:
        line["plan"] = plan
    if serving is not None:
        line["serving"] = serving
    if costs is not None:
        line["costs"] = costs
    if not on_tpu:
        line["last_tpu"] = last_tpu_capture()
    return line


# Probe/body timeout constants, exported so tools/hw_refresh.py can
# compute its outer budget from the same numbers the loops below use.
PROBE_TIMEOUT_S = 240
PROBE_SLEEP_S = 300
BODY_TIMEOUT_S = 3000
HERMETIC_RETRY_TIMEOUT_S = 1500


def worst_case_budget_s():
    """Upper bound on a full bench.py run: every probe times out, the
    body uses its whole budget, and the hermetic retry runs too."""
    attempts = probe_attempts_from_env()
    return (attempts * PROBE_TIMEOUT_S + (attempts - 1) * PROBE_SLEEP_S
            + BODY_TIMEOUT_S + HERMETIC_RETRY_TIMEOUT_S)


def probe_attempts_from_env(default=3):
    """GOSSIP_BENCH_PROBE_ATTEMPTS, hardened: malformed values fall back
    to the default (never crash before the one-JSON-line contract can be
    met) and the count is clamped to >= 1 so the TPU probe can never be
    silently disabled."""
    raw = os.environ.get("GOSSIP_BENCH_PROBE_ATTEMPTS", str(default))
    try:
        return max(1, int(raw))
    except ValueError:
        print("bench: ignoring malformed GOSSIP_BENCH_PROBE_ATTEMPTS="
              f"{raw!r}; using {default}", file=sys.stderr)
        return default


def _hermetic_cpu_env():
    """CPU env with the axon plugin disarmed (the sitecustomize-preloaded
    TPU tunnel hangs ANY jax init while wedged, even under
    JAX_PLATFORMS=cpu — the dryrun_multichip/conftest hardening).  Only
    sitecustomize-bearing PYTHONPATH entries are dropped; everything else
    is preserved in case dependencies are provisioned via PYTHONPATH."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    for hazard in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORM_NAME",
                   "LIBTPU_INIT_ARGS"):
        env.pop(hazard, None)
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and p != repo
            and not os.path.exists(os.path.join(p, "sitecustomize.py"))]
    env["PYTHONPATH"] = os.pathsep.join([repo] + keep)
    env["JAX_PLATFORMS"] = "cpu"
    # hermetic means cache-off too: CLI children must neither write the
    # operator's persistent ~/.cache nor report warm-cache walls as if
    # they were cold measurements (cli._cache_stamp contract)
    env["GOSSIP_COMPILE_CACHE"] = ""
    return env


def main():
    """Probe the ambient JAX platform in a subprocess, then run the
    measurement there; if the platform cannot even enumerate devices
    (single-client TPU tunnel wedged by an earlier killed process — it
    stays down for an hour+), fall back to a hermetic CPU measurement
    instead of hanging the whole bench run.  One JSON line either way.

    Every probe attempt, fallback decision, and the final measurement
    are recorded in the run ledger (utils/telemetry: $GOSSIP_TELEMETRY,
    default artifacts/ledger_bench.jsonl, fsync per event, echoed to
    stderr) instead of ad-hoc stderr prints — the round-5 dark window
    left 78/78 timed-out probes with no machine-readable trace; now a
    wedge that hides the live number still leaves its own timeline."""
    from gossip_tpu.utils import telemetry
    led = telemetry.from_env(default_path=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "artifacts",
        "ledger_bench.jsonl"), echo=True)
    try:
        return _main_ledgered(led)
    finally:
        led.close()


def _main_ledgered(led):
    probe = [sys.executable, "-c", "import jax; jax.devices()"]
    body_cmd = [sys.executable, os.path.abspath(__file__), "--body"]

    def run_body(env, timeout):
        """(returncode-or-None, stdout).  The child's stdout is CAPTURED
        and only the final JSON line is re-emitted on success — so a body
        that prints its line and then wedges in teardown, or fails fast
        after printing nothing, can never break the one-line contract.
        On timeout the output captured SO FAR is returned: a completed
        measurement whose process wedged in teardown still counts."""
        def _text(x):
            return ("" if x is None
                    else x if isinstance(x, str)
                    else x.decode(errors="replace"))
        try:
            p = subprocess.run(body_cmd, env=env, timeout=timeout,
                               capture_output=True, text=True)
            sys.stderr.write(_text(p.stderr))
            return p.returncode, _text(p.stdout)
        except subprocess.TimeoutExpired as e:
            sys.stderr.write(_text(e.stderr))
            return None, _text(e.stdout)

    def final_json_line(out):
        # scan BACKWARDS for the last parsable line: teardown noise
        # printed after the measurement must not discard it
        for line in reversed(out.splitlines()):
            if not line.strip():
                continue
            try:
                json.loads(line)
            except json.JSONDecodeError:
                continue
            return line
        return None

    # Retry a timed-out probe before settling for the CPU fallback
    # (round-2 lesson: one 240 s probe flipped the official scoreboard
    # to a CPU number on a wedge that cleared later).  Caveats baked
    # into the shape of the loop: killing a timed-out probe itself
    # leaves a dead TPU-client process, which can PROLONG a wedge — so
    # attempts are few and the sleeps long (a hard wedge lasts 1h+ and
    # no in-budget retry policy beats it; the target is the transient
    # kind).  Only a probe TIMEOUT (the wedge signature) is retried — a
    # probe that fails fast (CalledProcessError: broken install, plugin
    # import error) is deterministic, so fall back immediately.  Worst
    # case at the default: 3 x 240 s probes + 2 x 300 s sleeps = 1320 s.
    # GOSSIP_BENCH_PROBE_ATTEMPTS=1 restores the single-probe behavior.
    probe_attempts = probe_attempts_from_env()
    ambient_ok = False
    for attempt in range(probe_attempts):
        t0 = time.perf_counter()
        try:
            subprocess.run(probe, timeout=PROBE_TIMEOUT_S, check=True,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
            led.event("probe", outcome="ok", attempt=attempt + 1,
                      of=probe_attempts,
                      wall_s=round(time.perf_counter() - t0, 1))
            ambient_ok = True
            break
        except subprocess.CalledProcessError:
            # broken ambient platform, not a wedge — deterministic, so
            # no retries
            led.event("probe", outcome="fast-fail", attempt=attempt + 1,
                      of=probe_attempts,
                      wall_s=round(time.perf_counter() - t0, 1))
            break
        except subprocess.TimeoutExpired:
            # the wedge signature
            led.event("probe", outcome="timeout", attempt=attempt + 1,
                      of=probe_attempts, timeout_s=PROBE_TIMEOUT_S)
            led.counter("probe_timeouts")
            if attempt + 1 < probe_attempts:
                time.sleep(PROBE_SLEEP_S)
    if ambient_ok:
        env = dict(os.environ)
    else:
        led.event("fallback", to="hermetic-cpu",
                  reason="ambient JAX platform unusable "
                         "(wedged TPU tunnel?)")
        env = _hermetic_cpu_env()
    rc, out = run_body(env, BODY_TIMEOUT_S)
    line = final_json_line(out)
    if line is None and rc != 0 and ambient_ok:
        # no measurement AND the body died on the ambient platform — the
        # tunnel wedged between probe and body (hang: rc None; fast init
        # failure: rc nonzero); one hermetic retry
        led.event("fallback", to="hermetic-cpu-retry", rc=rc,
                  reason="body failed on the ambient platform")
        rc, out = run_body(_hermetic_cpu_env(), HERMETIC_RETRY_TIMEOUT_S)
        line = final_json_line(out)
    if line is not None:
        # a parsable measurement line is THE success criterion: a body
        # that completed and then wedged/died in teardown still counts
        if rc != 0:
            led.event("body_abnormal_exit", rc=rc,
                      note="measurement emitted before death; keeping it")
        led.event("measurement", line=json.loads(line))
        print(line)
        return 0
    # keep the one-JSON-line contract even in total failure; vs_baseline
    # null + backend null: no TPU measurement happened (measurement_line
    # contract)
    led.event("measurement_failed", rc=rc)
    print(json.dumps({
        "metric": "node_rounds_per_sec_per_chip", "value": 0.0,
        "unit": f"bench body failed on every platform (rc={rc}; "
                "wedged TPU tunnel?)",
        "vs_baseline": None, "backend": None,
        "last_tpu": last_tpu_capture()}))
    return 1


if __name__ == "__main__":
    if "--print-hermetic-env" in sys.argv:
        # shell-exportable lines for launching ANY command wedge-immune
        # (e.g. pytest while the tunnel is down — tests/conftest.py can
        # only protect test-spawned children, not the pytest parent).
        # GOSSIP_COMPILE_CACHE is bench's own cold-measurement policy,
        # not a wedge hazard — exporting it would silently disable the
        # default-on persistent compile cache for the rest of the
        # operator's shell, so it is NOT printed.
        # Only the keys the hermetic env CONTROLS are printed (the env
        # dict is a full os.environ copy — dumping it would leak the
        # whole shell), and unconditionally (no skip-if-already-set):
        # the output must be deterministic so `eval` is idempotent in
        # any starting shell.
        henv = _hermetic_cpu_env()
        for k in ("JAX_PLATFORMS", "PYTHONPATH"):
            print(f"export {k}={shlex.quote(henv[k])}")
        for k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORM_NAME",
                  "LIBTPU_INIT_ARGS"):
            print(f"unset {k}")
        sys.exit(0)
    if "--body" in sys.argv:
        sys.exit(body())
    sys.exit(main())
