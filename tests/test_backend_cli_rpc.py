"""Backend seam, CLI, and gRPC sidecar tests (SURVEY.md §7 layers 5-6)."""

import json
import os
import subprocess
import sys

import pytest

from gossip_tpu.backend import (RunReport, request_to_args, run_simulation)
from gossip_tpu.config import (MeshConfig, ProtocolConfig, RunConfig,
                               TopologyConfig)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_backend_parity_race_free_ring():
    # On the k=2 ring the event sim's hop clock equals the kernel's round
    # clock exactly (gonative parity contract), so the two backends must
    # report identical rounds-to-target through the seam.
    tc = TopologyConfig(family="ring", n=256, k=2)
    run = RunConfig(target_coverage=1.0, max_rounds=200)
    jax_r = run_simulation("jax-tpu", ProtocolConfig(mode="flood"), tc, run)
    go_r = run_simulation("go-native", ProtocolConfig(mode="flood"), tc, run)
    assert jax_r.coverage == go_r.coverage == 1.0
    assert jax_r.rounds == go_r.rounds == 128
    assert go_r.meta["clock"] == "hop-depth"


def test_backend_swim_report():
    proto = ProtocolConfig(mode="swim", fanout=2, swim_subjects=4,
                           swim_proxies=2, swim_suspect_rounds=4)
    r = run_simulation("jax-tpu", proto,
                       TopologyConfig(family="complete", n=128),
                       RunConfig(max_rounds=40))
    assert r.mode == "swim"
    assert r.coverage > 0.97          # detection fraction
    assert 0 < r.rounds < 40


def test_backend_swim_scenario_from_fault():
    # VERDICT r1: the failure scenario is config, not a hardcode — which
    # nodes die, and when, comes from the FaultConfig / RPC request.
    from gossip_tpu.config import FaultConfig
    proto = ProtocolConfig(mode="swim", fanout=2, swim_subjects=6,
                           swim_proxies=2, swim_suspect_rounds=4)
    fault = FaultConfig(dead_nodes=(0, 3, 5), fail_round=4)
    r = run_simulation("jax-tpu", proto,
                       TopologyConfig(family="complete", n=128),
                       RunConfig(max_rounds=48), fault=fault)
    assert r.meta["dead_subjects"] == [0, 3, 5]
    assert r.meta["fail_round"] == 4
    assert r.meta["default_scenario"] is False
    assert r.coverage > 0.97
    # out-of-window dead id without rotation is a config error
    with pytest.raises(ValueError, match="swim-rotate"):
        run_simulation("jax-tpu", proto,
                       TopologyConfig(family="complete", n=128),
                       RunConfig(max_rounds=8),
                       fault=FaultConfig(dead_nodes=(100,)))
    # ... and with rotation it is detected (meta records the window mode)
    proto_rot = ProtocolConfig(mode="swim", fanout=2, swim_subjects=8,
                               swim_proxies=2, swim_suspect_rounds=4,
                               swim_rotate=True)
    r = run_simulation("jax-tpu", proto_rot,
                       TopologyConfig(family="complete", n=96),
                       RunConfig(max_rounds=250),
                       fault=FaultConfig(dead_nodes=(57,), fail_round=0))
    assert r.meta["subject_window"] == "rotating"
    assert r.meta["peak_detection"] > 0.97


def test_rpc_request_carries_swim_scenario():
    args = request_to_args({"proto": {"mode": "swim", "swim_rotate": True},
                            "fault": {"dead_nodes": [4, 9],
                                      "fail_round": 3}})
    assert args["fault"].dead_nodes == (4, 9)    # list -> hashable tuple
    assert args["fault"].fail_round == 3
    assert args["proto"].swim_rotate is True
    assert hash(args["fault"]) is not None


def test_backend_sharded_path():
    r = run_simulation("jax-tpu", ProtocolConfig(mode="pushpull"),
                       TopologyConfig(family="complete", n=512),
                       RunConfig(max_rounds=64),
                       mesh_cfg=MeshConfig(n_devices=8), want_curve=True)
    assert r.meta["devices"] == 8
    assert r.coverage >= 0.99
    assert len(r.curve) == 64


def test_wall_reconciliation_contract():
    """VERDICT r4 task 5: every reported wall decomposes in the report
    itself — wall == compile_s + steady_wall_s + driver_overhead_s, the
    topology build is attributed separately, and the split exists on
    SHARDED engines too (round 4 left them as one fused wall)."""
    proto = ProtocolConfig(mode="pull", fanout=1)
    tc = TopologyConfig(family="erdos_renyi", n=1024, p=0.02)
    run = RunConfig(max_rounds=64)
    for mesh_cfg in (None, MeshConfig(n_devices=8)):
        r = run_simulation("jax-tpu", proto, tc, run, mesh_cfg=mesh_cfg)
        m = r.meta
        assert m["topo_build_s"] >= 0.0
        parts = (m["compile_s"] + m["steady_wall_s"]
                 + m["driver_overhead_s"])
        # == up to the 4-decimal rounding of the three parts
        assert r.wall_s == pytest.approx(parts, abs=2e-3)


def test_backend_packed_routing_matches_bool_path():
    # pull/anti-entropy route through the bit-packed engine; trajectories
    # are bitwise-identical to the bool path, so rounds-to-target and final
    # coverage must agree exactly with the curve (bool) run.
    proto = ProtocolConfig(mode="pull", fanout=1, rumors=3)
    tc = TopologyConfig(family="erdos_renyi", n=1024, p=0.02)
    run = RunConfig(max_rounds=64)
    fast = run_simulation("jax-tpu", proto, tc, run)
    assert fast.meta["engine"] == "bit-packed"
    slow = run_simulation("jax-tpu", proto, tc, run, want_curve=True)
    assert "engine" not in slow.meta          # curve keeps the bool path
    # identical trajectory => same rounds-to-target (the while-loop run
    # stops there; the curve run continues to max_rounds, so final
    # coverage/msgs are not comparable between the two driver shapes)
    assert fast.rounds == slow.rounds
    assert fast.coverage >= run.target_coverage
    # sharded twin routes too and agrees exactly
    sh = run_simulation("jax-tpu", proto, tc, run,
                        mesh_cfg=MeshConfig(n_devices=8))
    assert sh.meta["engine"] == "bit-packed"
    assert sh.rounds == fast.rounds
    assert sh.msgs == pytest.approx(fast.msgs)


# ~8 s (flight data, the log-PR rebalance): the sparse exchange keeps
# three in-gate smokes — the dry run's two sparse families and the
# compile-cache sparse driver leg (the PR 3 rationale) — and full
# mesh-vs-reference parity already runs under -m slow; this
# backend-routing depth joins it
@pytest.mark.slow
def test_backend_sparse_exchange():
    # the O(messages) all_to_all path as a product surface (--exchange)
    r = run_simulation("jax-tpu", ProtocolConfig(mode="pull", fanout=1),
                       TopologyConfig(family="complete", n=2048),
                       RunConfig(max_rounds=64),
                       mesh_cfg=MeshConfig(n_devices=8, exchange="sparse"))
    assert r.meta["exchange"] == "sparse"
    assert r.coverage >= 0.99
    b = r.meta["ici_bytes_per_round"]
    assert b["sparse"] < b["dense_equivalent"]
    # explicit families route to the capacity-capped topology path
    # (round 3; was a ValueError before) — full coverage in
    # tests/test_sharded_sparse.py
    r2 = run_simulation("jax-tpu", ProtocolConfig(mode="pull"),
                        TopologyConfig(family="ring", n=512, k=4),
                        RunConfig(max_rounds=200),
                        mesh_cfg=MeshConfig(n_devices=8, exchange="sparse"))
    assert r2.meta["exchange"] == "sparse"
    assert "overflow_dropped_requests" in r2.meta


def test_backend_halo_exchange():
    # the O(band) ppermute path as a product surface, with curve
    r = run_simulation("jax-tpu", ProtocolConfig(mode="pushpull", fanout=2),
                       TopologyConfig(family="ring", n=512, k=6),
                       RunConfig(max_rounds=128, target_coverage=0.9),
                       mesh_cfg=MeshConfig(n_devices=8, exchange="halo"),
                       want_curve=True)
    assert r.meta["exchange"] == "halo"
    assert r.meta["band"] == 3
    assert r.coverage >= 0.9
    assert len(r.curve) == 128
    with pytest.raises(ValueError, match="unknown exchange"):
        MeshConfig(n_devices=8, exchange="carrier-pigeon")
    # a requested non-dense exchange is never silently substituted
    with pytest.raises(ValueError, match="n_devices > 1"):
        run_simulation("jax-tpu", ProtocolConfig(mode="pull"),
                       TopologyConfig(family="complete", n=256), RunConfig(),
                       mesh_cfg=MeshConfig(n_devices=1, exchange="sparse"))
    with pytest.raises(ValueError, match="swim"):
        run_simulation("jax-tpu", ProtocolConfig(mode="swim"),
                       TopologyConfig(family="ring", n=256, k=4),
                       RunConfig(),
                       mesh_cfg=MeshConfig(n_devices=8, exchange="halo"))


def test_backend_rejections():
    with pytest.raises(ValueError, match="unknown backend"):
        run_simulation("torch", ProtocolConfig(), TopologyConfig(),
                       RunConfig())
    with pytest.raises(ValueError, match="no Go equivalent"):
        run_simulation("go-native", ProtocolConfig(mode="pushpull"),
                       TopologyConfig(family="ring", n=64), RunConfig())
    with pytest.raises(ValueError, match="capped"):
        run_simulation("go-native", ProtocolConfig(mode="flood"),
                       TopologyConfig(family="ring", n=50_000), RunConfig())
    from gossip_tpu.config import FaultConfig
    with pytest.raises(ValueError, match="no FaultConfig"):
        run_simulation("go-native", ProtocolConfig(mode="flood"),
                       TopologyConfig(family="ring", n=64), RunConfig(),
                       fault=FaultConfig(drop_prob=0.1))


def test_engine_fused_routing_and_rejections():
    import jax

    with pytest.raises(ValueError, match="unknown engine"):
        RunConfig(engine="warp")
    fused = RunConfig(engine="fused", max_rounds=64)
    # config errors surface identically on any backend (platform check last)
    with pytest.raises(ValueError, match="pull rounds only"):
        run_simulation("jax-tpu", ProtocolConfig(mode="push"),
                       TopologyConfig(n=4096), fused)
    with pytest.raises(ValueError, match="complete"):
        run_simulation("jax-tpu", ProtocolConfig(mode="pull"),
                       TopologyConfig(family="ring", n=4096, k=2), fused)
    from gossip_tpu.config import FaultConfig
    # round 4: static fault masks (drop_prob / node_death_rate) are
    # in-kernel on every fused layout — only SCRIPTED deaths reject
    with pytest.raises(ValueError, match="dead_nodes"):
        run_simulation("jax-tpu", ProtocolConfig(mode="pull"),
                       TopologyConfig(n=4096), fused,
                       fault=FaultConfig(dead_nodes=(3,), fail_round=2))
    # >32 rumors needs the plane-sharded multi-device path
    with pytest.raises(ValueError, match="shard rumor planes"):
        run_simulation("jax-tpu", ProtocolConfig(mode="pull", rumors=33),
                       TopologyConfig(n=4096), fused)
    # multi-rumor past the VMEM envelope: ANY fanout routes through the
    # staged big-table path since round 5 (multi-pass accumulation) —
    # no upper bound on n
    from gossip_tpu.ops.pallas_round import check_fused_fits
    assert check_fused_fits(50_000_000, 8, 1) > 0
    assert check_fused_fits(50_000_000, 8, 2) > 0
    # the single-rumor node-packed layout has no staged twin, so a
    # table past the envelope still raises the friendly error
    with pytest.raises(ValueError, match="VMEM budget"):
        check_fused_fits(2_000_000_000, 1)
    with pytest.raises(ValueError, match="jax-tpu kernel"):
        run_simulation("go-native", ProtocolConfig(mode="flood"),
                       TopologyConfig(family="ring", n=64, k=2), fused)
    # the RPC schema reaches the engine knob through the run object
    args = request_to_args({"run": {"engine": "fused"}})
    assert args["run"].engine == "fused"

    if jax.default_backend() != "tpu":
        with pytest.raises(ValueError, match="needs a TPU"):
            run_simulation("jax-tpu", ProtocolConfig(mode="pull"),
                           TopologyConfig(n=4096), fused)
        # round 4: want_curve is fused-eligible (scan twins), so off-TPU
        # the platform probe is the error that surfaces — not a config
        # rejection (on TPU this combination simply runs)
        with pytest.raises(ValueError, match="needs a TPU"):
            run_simulation("jax-tpu", ProtocolConfig(mode="pull"),
                           TopologyConfig(n=4096), fused, want_curve=True)
        # multi-device (rumor-plane sharded) path gates on TPU the same way
        with pytest.raises(ValueError, match="needs a TPU"):
            run_simulation("jax-tpu", ProtocolConfig(mode="pull", rumors=256),
                           TopologyConfig(n=4096), fused,
                           mesh_cfg=MeshConfig(n_devices=8))
    else:
        for rumors in (1, 8):
            rep = run_simulation("jax-tpu",
                                 ProtocolConfig(mode="pull", rumors=rumors),
                                 TopologyConfig(n=1 << 16), fused)
            assert rep.meta["engine"] == "fused-pallas"
            assert rep.coverage >= 0.99 and rep.rounds > 0
            assert rep.msgs == 2.0 * (1 << 16) * rep.rounds

    # a requested sparse/halo exchange is never silently dropped
    with pytest.raises(ValueError, match="no exchange"):
        run_simulation("jax-tpu", ProtocolConfig(mode="pull", rumors=256),
                       TopologyConfig(n=4096), fused,
                       mesh_cfg=MeshConfig(n_devices=8, exchange="sparse"))


def test_request_to_args_strict():
    args = request_to_args({"backend": "jax-tpu",
                            "proto": {"mode": "push", "fanout": 2},
                            "topology": {"family": "ring", "n": 64, "k": 2}})
    assert args["proto"].fanout == 2
    assert args["tc"].family == "ring"
    with pytest.raises(ValueError, match="unknown proto fields"):
        request_to_args({"proto": {"fanoot": 2}})


def test_rpc_sidecar_round_trip():
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    server, port = serve(port=0, max_workers=2)
    try:
        client = SidecarClient(f"127.0.0.1:{port}")
        h = client.health()
        assert h["ok"] and h["devices"] >= 1
        rep = client.run(
            backend="jax-tpu",
            proto={"mode": "pushpull", "fanout": 1},
            topology={"family": "erdos_renyi", "n": 500, "p": 0.02},
            run={"max_rounds": 64}, curve=True)
        assert rep["coverage"] >= 0.99
        assert rep["backend"] == "jax-tpu"
        assert len(rep["curve"]) == 64
        # same request direct == same result (the shim adds nothing)
        direct = run_simulation(
            "jax-tpu", ProtocolConfig(mode="pushpull", fanout=1),
            TopologyConfig(family="erdos_renyi", n=500, p=0.02),
            RunConfig(max_rounds=64), want_curve=True)
        assert rep["rounds"] == direct.rounds
        assert rep["msgs"] == direct.msgs
        # bad requests become INVALID_ARGUMENT, not server crashes
        import grpc as g
        with pytest.raises(g.RpcError) as ei:
            client.run(backend="torch")
        assert ei.value.code() == g.StatusCode.INVALID_ARGUMENT
        client.close()
    finally:
        server.stop(grace=None)


# Children inherit the session-scoped compile cache dir conftest put
# in GOSSIP_COMPILE_CACHE (a fresh temp dir — never the developer's
# persistent ~/.cache, which the old "" pin guarded against): CLI
# re-execs sharing a shape start warm.  An explicit --compile-cache /
# --no-compile-cache flag in a test still overrides the env default.
CLI_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           "PYTHONPATH": _REPO}


def _cli(*argv):
    return subprocess.run([sys.executable, "-m", "gossip_tpu", *argv],
                          capture_output=True, text=True, cwd=_REPO,
                          env=CLI_ENV, timeout=240)


def test_cli_run_json():
    p = _cli("run", "--backend", "go-native", "--mode", "flood",
             "--family", "ring", "--n", "128", "--k", "2",
             "--target", "1.0", "--max-rounds", "100")
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    assert rep["rounds"] == 64 and rep["coverage"] == 1.0


def test_cli_run_jax_and_error_paths():
    p = _cli("run", "--mode", "pushpull", "--n", "300",
             "--family", "erdos_renyi", "--p", "0.03", "--curve")
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    assert rep["coverage"] >= 0.99 and rep["curve"]
    p = _cli("run", "--backend", "go-native", "--mode", "pushpull",
             "--family", "ring", "--n", "64")
    assert p.returncode == 2
    assert "no Go equivalent" in p.stderr
    p = _cli("run", "--mode", "pull", "--n", "256", "--engine", "fused",
             "--ensemble", "4")
    assert p.returncode == 2
    assert "single-run only" in p.stderr


# depth tier (tier-1 wall budget, CRDT-PR rebalance): 2 CLI children;
# the compile-cache contracts keep in-gate coverage via
# tests/test_compile_cache.py (cross-process populate-then-hit +
# per-driver warm-vs-cold), and every CLI child in the gate already
# runs through _enable_compile_cache with the session cache dir
@pytest.mark.slow
def test_cli_compile_cache_flags(tmp_path):
    """--compile-cache creates the cache dir and the run still works
    (whether entries land depends on the 2 s min-compile threshold);
    --no-compile-cache runs without touching the path."""
    cache = tmp_path / "xla-cache"
    p = _cli("run", "--mode", "pushpull", "--n", "256",
             "--family", "erdos_renyi", "--p", "0.05",
             "--compile-cache", str(cache))
    assert p.returncode == 0, p.stderr
    assert json.loads(p.stdout)["coverage"] >= 0.9
    assert cache.is_dir()
    off = tmp_path / "never-created"
    p = _cli("run", "--mode", "pushpull", "--n", "256",
             "--family", "erdos_renyi", "--p", "0.05",
             "--compile-cache", str(off), "--no-compile-cache")
    assert p.returncode == 0, p.stderr
    assert not off.exists()


def test_cli_grid_ns_one_program():
    # the n axis of the structural sweep, batched (VERDICT r3 item 6):
    # two sizes of one family in one compiled program, per-point n/family
    # reported; deeper bitwise coverage in tests/test_config_sweep.py
    p = _cli("grid", "--modes", "push", "pull", "--fanouts", "1",
             "--family", "erdos_renyi", "--ns", "300", "600",
             "--p", "0.02", "--max-rounds", "24")
    assert p.returncode == 0, p.stderr
    rows = [json.loads(line) for line in p.stdout.splitlines()]
    assert sorted({r["n"] for r in rows}) == [300, 600]
    assert all(r["family"] == "erdos_renyi" and r["converged"]
               for r in rows)


@pytest.mark.slow
def test_cli_sweep_smoke():
    p = _cli("sweep", "--scale", "0.002", "--devices", "4",
             "--only", "push-complete-64-goref", "pushpull-er-10k",
             "multirumor-10m-sharded")
    assert p.returncode == 0, p.stderr
    lines = [json.loads(line) for line in p.stdout.splitlines()]
    assert len(lines) == 3
    byname = {line["config"]: line for line in lines}
    assert byname["push-complete-64-goref"]["gonative_ref"]["coverage"] == 1.0
    assert byname["multirumor-10m-sharded"]["meta"]["devices"] == 4
    assert all(line["coverage"] >= 0.99 for line in lines)
    # row-level reconciliation (VERDICT r4 task 5): the row wall covers
    # engine wall + topo build + the go-native ref + named residual
    for line in lines:
        parts = (line["wall_s"]
                 + (line.get("meta") or {}).get("topo_build_s", 0.0)
                 + (line.get("gonative_ref") or {}).get("wall_s", 0.0)
                 + line["row_overhead_s"])
        assert line["row_wall_s"] >= line["wall_s"]
        assert abs(line["row_wall_s"] - parts) < 0.05


# slow tier (tier-1 wall budget): the diss-override CLI leg;
# sweep-CLI stays gated via test_cli_grid_ns_one_program
@pytest.mark.slow
def test_cli_sweep_swim_diss_override():
    """`sweep --swim-diss` re-measures the SWIM row under an A/B-
    arbitrated lowering without a code change (hw_refresh contract);
    trajectories must be identical across lowerings and the effective
    lowering must be visible in the row's meta."""
    rows = {}
    for impl in ("sort", "pack"):
        p = _cli("sweep", "--scale", "0.002", "--only", "swim-powerlaw-1m",
                 "--swim-diss", impl)
        assert p.returncode == 0, p.stderr
        rows[impl] = json.loads(p.stdout.splitlines()[0])
        assert rows[impl]["meta"]["swim_diss_effective"] == impl
    a, b = rows["sort"], rows["pack"]
    assert (a["rounds"], a["coverage"], a["msgs"]) == \
        (b["rounds"], b["coverage"], b["msgs"])


def test_fused_auto_routing_decision():
    """engine='auto' picks the fused engine exactly when a single-device
    run satisfies every _run_fused precondition (quietly)."""
    import jax

    from gossip_tpu.backend import _fused_auto_ok
    from gossip_tpu.config import FaultConfig

    pull = ProtocolConfig(mode="pull")
    comp = TopologyConfig(family="complete", n=100_000)

    # on CPU the fused engine is never auto-picked (hardware PRNG)
    if jax.default_backend() != "tpu":
        assert not _fused_auto_ok(pull, comp, None)

    # decision logic independent of platform, via a patched backend probe
    real = jax.default_backend
    jax.default_backend = lambda: "tpu"
    try:
        assert _fused_auto_ok(pull, comp, None)
        assert _fused_auto_ok(ProtocolConfig(mode="pull", rumors=32),
                              comp, None)
        # the flagship: 10M x 32 rumors fanout 1 -> staged big path
        assert _fused_auto_ok(
            ProtocolConfig(mode="pull", rumors=32),
            TopologyConfig(family="complete", n=10_000_000), None)
        # fanout 2 past the VMEM envelope: the staged path multi-pass
        # accumulates since round 5 -> eligible
        assert _fused_auto_ok(
            ProtocolConfig(mode="pull", rumors=32, fanout=2),
            TopologyConfig(family="complete", n=10_000_000), None)
        assert not _fused_auto_ok(ProtocolConfig(mode="pushpull"),
                                  comp, None)
        assert not _fused_auto_ok(
            pull, TopologyConfig(family="ring", n=4096, k=2), None)
        # round 4: static fault masks are fused-eligible (in-kernel) —
        # auto may pick it; scripted deaths remain ineligible
        assert _fused_auto_ok(pull, comp, FaultConfig(drop_prob=0.1))
        assert not _fused_auto_ok(
            pull, comp, FaultConfig(dead_nodes=(5,), fail_round=1))
        assert not _fused_auto_ok(ProtocolConfig(mode="pull", rumors=33),
                                  comp, None)
    finally:
        jax.default_backend = real


def test_auto_stays_on_xla_path_off_tpu():
    """On CPU, engine='auto' must keep the bit-packed XLA path (and not
    record an auto fused pick)."""
    import jax

    if jax.default_backend() == "tpu":
        pytest.skip("CPU-only routing assertion")
    rep = run_simulation("jax-tpu", ProtocolConfig(mode="pull"),
                         TopologyConfig(family="complete", n=4096),
                         RunConfig(max_rounds=64))
    assert rep.meta.get("engine") == "bit-packed"
    assert "engine_auto" not in rep.meta
    assert rep.coverage >= 0.99


def test_engine_xla_is_the_auto_fused_opt_out():
    """engine='xla' forces the XLA kernels (identical to auto's XLA
    route), never the fused engine — the opt-out that keeps the
    single-device <-> sharded bitwise cross-validation reachable on TPU."""
    proto = ProtocolConfig(mode="pull")
    tc = TopologyConfig(family="complete", n=2048)
    run_auto = RunConfig(max_rounds=64)
    run_xla = RunConfig(max_rounds=64, engine="xla")
    a = run_simulation("jax-tpu", proto, tc, run_auto)
    x = run_simulation("jax-tpu", proto, tc, run_xla)
    assert x.meta["engine"] == "bit-packed"
    assert "engine_auto" not in x.meta
    # same threefry stream when auto also lands on XLA (always on CPU)
    if "engine_auto" not in a.meta:
        assert (a.rounds, a.coverage, a.msgs) == (x.rounds, x.coverage,
                                                  x.msgs)
    args = request_to_args({"run": {"engine": "xla"}})
    assert args["run"].engine == "xla"


@pytest.mark.slow
def test_cli_checkpoint_resume_and_profile(tmp_path):
    ck = str(tmp_path / "run.npz")
    prof = str(tmp_path / "prof")
    # 12 rounds, checkpoint every 5 -> file exists, rounds == 12
    p = _cli("run", "--mode", "pushpull", "--n", "512", "--max-rounds",
             "12", "--checkpoint", ck, "--checkpoint-every", "5")
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    assert rep["rounds"] == 12 and os.path.exists(ck)
    # resume continues to 20 TOTAL rounds and must match an
    # uninterrupted 20-round checkpointed run bitwise (same seed)
    p = _cli("run", "--mode", "pushpull", "--n", "512", "--max-rounds",
             "20", "--checkpoint", ck, "--resume")
    assert p.returncode == 0, p.stderr
    resumed = json.loads(p.stdout)
    assert resumed["rounds"] == 20 and resumed["resumed"] is True
    ck2 = str(tmp_path / "solo.npz")
    p = _cli("run", "--mode", "pushpull", "--n", "512", "--max-rounds",
             "20", "--checkpoint", ck2)
    solo = json.loads(p.stdout)
    assert (resumed["coverage"], resumed["msgs"]) == (solo["coverage"],
                                                      solo["msgs"])
    # round-4: swim checkpointing is a supported engine now (the full
    # resume contract lives in test_checkpoint_sharded.py); the guard
    # that remains is the backend gate
    p = _cli("run", "--mode", "swim", "--n", "256", "--max-rounds", "6",
             "--checkpoint", str(tmp_path / "sw.npz"))
    assert p.returncode == 0, p.stderr
    assert json.loads(p.stdout)["engine"] == "swim-xla"
    p = _cli("run", "--backend", "go-native", "--n", "64",
             "--checkpoint", str(tmp_path / "gn.npz"))
    assert p.returncode == 2 and "jax-tpu engines" in p.stderr
    # resume with different flags refuses (config fingerprint mismatch)
    p = _cli("run", "--mode", "pushpull", "--n", "512", "--max-rounds",
             "30", "--seed", "9", "--checkpoint", ck, "--resume")
    assert p.returncode == 2 and "config mismatch" in p.stderr
    assert "seed" in p.stderr
    # --resume without --checkpoint errors instead of silently restarting
    p = _cli("run", "--mode", "pushpull", "--n", "512", "--resume")
    assert p.returncode == 2 and "--checkpoint" in p.stderr
    # round 4: --curve composes with the segment driver (scan segments;
    # deeper coverage in tests/test_checkpoint_sharded.py)
    p = _cli("run", "--mode", "pushpull", "--n", "512", "--max-rounds",
             "6", "--checkpoint", str(tmp_path / "curve.npz"), "--curve")
    assert p.returncode == 0, p.stderr
    assert len(json.loads(p.stdout)["curve"]) == 6
    # --profile wraps the run and writes a trace directory
    p = _cli("run", "--mode", "pull", "--n", "256", "--max-rounds", "16",
             "--profile", prof)
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    assert rep["profile_logdir"] == prof
    assert os.path.isdir(prof) and any(os.scandir(prof))


def test_rpc_sidecar_runs_rumor_mode():
    """The new SIR family is reachable through the service seam with its
    extinction metadata intact."""
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    server, port = serve(port=0, max_workers=2)
    try:
        client = SidecarClient(f"127.0.0.1:{port}")
        rep = client.run(proto={"mode": "rumor", "rumor_k": 2,
                                "rumor_variant": "blind"},
                         topology={"family": "complete", "n": 1024},
                         run={"max_rounds": 128})
        assert rep["mode"] == "rumor"
        assert rep["meta"]["terminated"] is True
        assert rep["meta"]["variant"] == "blind"
        assert 0 < rep["coverage"] <= 1.0
    finally:
        server.stop(0)


def test_bench_hermetic_env_preserves_pythonpath(monkeypatch, tmp_path):
    """The wedged-tunnel CPU fallback must drop ONLY sitecustomize-bearing
    PYTHONPATH entries (the axon trigger), not dependency paths."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(_REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    keepdir = tmp_path / "deps"
    axondir = tmp_path / "axon"
    keepdir.mkdir()
    axondir.mkdir()
    (axondir / "sitecustomize.py").write_text("")
    monkeypatch.setenv("PYTHONPATH",
                       os.pathsep.join([str(keepdir), str(axondir)]))
    env = bench._hermetic_cpu_env()
    parts = env["PYTHONPATH"].split(os.pathsep)
    assert parts[0] == _REPO
    assert str(keepdir) in parts
    assert str(axondir) not in parts
    assert env["JAX_PLATFORMS"] == "cpu"


# ---------------------------------------------------------------------
# engine='native' above the go-native cap + --parity-check (VERDICT r2
# item 8).


def test_gonative_native_engine_raises_cap():
    """engine='native' forces the C++ core and lifts the 20k ceiling;
    engine='auto' above the ceiling stays a loud error."""
    import dataclasses as _dc
    from gossip_tpu.backend import run_simulation
    from gossip_tpu.runtime.native_sim import native_available
    proto = ProtocolConfig(mode="flood")
    tc = TopologyConfig(family="erdos_renyi", n=25_000, p=0.0004, seed=1)
    run = RunConfig(max_rounds=24)
    with pytest.raises(ValueError, match="native"):
        run_simulation("go-native", proto, tc, run)
    if not native_available():
        pytest.skip("no C++ compiler")
    rep = run_simulation("go-native", proto, tc,
                         _dc.replace(run, engine="native"))
    assert rep.meta["engine"] == "NativeGoSim"
    assert rep.coverage > 0.95
    # jax-tpu must reject the go-native engine selection loudly
    with pytest.raises(ValueError, match="go-native"):
        run_simulation("jax-tpu", proto, tc,
                       _dc.replace(run, engine="native"))
    # and xla/fused are jax selections the event backend rejects
    with pytest.raises(ValueError, match="jax-tpu"):
        run_simulation("go-native", proto, tc,
                       _dc.replace(run, engine="xla"))


def test_cli_parity_check_race_free_ring():
    """The CLI parity artifact: on the race-free k=2 ring the two
    backends agree EXACTLY on the hop clock."""
    p = _cli("run", "--mode", "flood", "--family", "ring", "--n", "256",
             "--k", "2", "--max-rounds", "140", "--target", "1.0",
             "--parity-check")
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    assert rep["curve_gap"] == 0.0
    assert rep["hop_bound_violation"] == 0.0
    assert rep["fixed_point_gap"] == 0.0


def test_cli_parity_check_rejects_non_flood():
    p = _cli("run", "--mode", "push", "--family", "ring", "--n", "64",
             "--parity-check")
    assert p.returncode == 2
    assert "flood" in p.stderr


# depth tier (tier-1 wall budget, CRDT-PR rebalance): 3 CLI children
# of pure flag-validation; the parity-check surface keeps its in-gate
# smokes via test_cli_parity_check_race_free_ring (happy path) and
# test_cli_parity_check_rejects_non_flood (rejection path)
@pytest.mark.slow
def test_cli_parity_check_flag_conflicts_and_truncation():
    # insufficient --max-rounds must error, not report a bogus gap
    p = _cli("run", "--mode", "flood", "--family", "ring", "--n", "256",
             "--k", "2", "--max-rounds", "20", "--target", "1.0",
             "--parity-check")
    assert p.returncode == 2 and "max-rounds" in p.stderr
    # conflicting run shapes are rejected, never silently dropped
    p = _cli("run", "--mode", "flood", "--family", "ring", "--n", "128",
             "--k", "2", "--parity-check", "--ensemble", "4")
    assert p.returncode == 2 and "parity" in p.stderr
    p = _cli("run", "--mode", "flood", "--family", "ring", "--n", "128",
             "--k", "2", "--parity-check", "--curve")
    assert p.returncode == 2 and "self-contained" in p.stderr


def test_until_reports_split_compile_and_steady_wall():
    """Hardware-table contract (round-2 verdict): non-curve runs report
    compile_s and steady_wall_s separately so tables stop mixing one-off
    compile cost with steady-state throughput."""
    for proto in (ProtocolConfig(mode="pushpull"),        # bool until
                  ProtocolConfig(mode="pull")):           # bit-packed
        r = run_simulation("jax-tpu", proto,
                           TopologyConfig(family="complete", n=256),
                           RunConfig(max_rounds=32))
        assert r.meta["compile_s"] > 0
        assert r.meta["steady_wall_s"] > 0
        assert r.meta["compile_s"] + r.meta["steady_wall_s"] \
            <= r.wall_s + 0.05
    # swim early-exit driver too
    r = run_simulation("jax-tpu",
                       ProtocolConfig(mode="swim", fanout=2,
                                      swim_subjects=4, swim_proxies=2,
                                      swim_suspect_rounds=4),
                       TopologyConfig(family="complete", n=128),
                       RunConfig(max_rounds=40))
    assert r.meta["compile_s"] > 0 and r.meta["steady_wall_s"] > 0
    # curve runs keep the fused wall (no AOT split there)
    r = run_simulation("jax-tpu", ProtocolConfig(mode="pushpull"),
                       TopologyConfig(family="complete", n=256),
                       RunConfig(max_rounds=16), want_curve=True)
    assert "compile_s" not in r.meta


# depth tier (tier-1 wall budget, CRDT-PR rebalance): the sidecar
# surface keeps test_rpc_sidecar_round_trip in-gate, and the shared
# ensemble dispatch (backend.run_ensemble) stays gated via
# tests/test_sweep.py's ensemble pins — this RPC-transport twin of the
# same dispatch runs under -m slow
@pytest.mark.slow
def test_rpc_sidecar_ensemble():
    """Round 4: the Ensemble RPC — seed-ensemble statistics in one
    coarse call, mode-dispatched through backend.run_ensemble (shared
    with the CLI so the two surfaces cannot drift)."""
    import grpc

    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    server, port = serve(0, 2)
    c = SidecarClient(f"localhost:{port}")
    try:
        r = c.ensemble(proto={"mode": "pushpull"},
                       topology={"family": "complete", "n": 256},
                       run={"max_rounds": 24}, ensemble=4)
        assert r["ensemble"]["seeds"] == 4
        assert r["ensemble"]["converged"] == 4
        r = c.ensemble(proto={"mode": "swim", "fanout": 2,
                              "swim_subjects": 4, "swim_proxies": 2,
                              "swim_suspect_rounds": 4},
                       topology={"family": "complete", "n": 128},
                       run={"max_rounds": 40}, seeds=[5, 6, 7])
        assert r["metric"] == "detection_fraction"
        assert r["ensemble"]["converged"] == 3
        # strict schema: flood, both/neither seed forms, unknown fields
        for bad in (dict(proto={"mode": "flood"}, topology={"n": 64},
                         run={}, ensemble=2),
                    dict(proto={"mode": "push"}, topology={"n": 64},
                         run={}),
                    dict(proto={"mode": "push"}, topology={"n": 64},
                         run={}, ensemble=2, seeds=[1]),
                    dict(proto={"mode": "push"}, topology={"n": 64},
                         run={}, ensemble=2, bogus=1)):
            with pytest.raises(grpc.RpcError) as exc:
                c.ensemble(**bad)
            assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        c.close()
        server.stop(0)
