"""bench.py scoreboard-line contract (VERDICT r2 item 9).

A CPU fallback must never masquerade as a TPU perf number: off-TPU the
``vs_baseline`` field is null and the machine-readable ``backend`` field
records what ran.
"""

import importlib.util
import json
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# repo-root module, not a package member: load by path so collection
# works from any cwd (same pattern as test_backend_cli_rpc.py)
_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(_REPO, "bench.py"))
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def test_tpu_measurement_carries_vs_baseline():
    line = bench.measurement_line(
        rate=3.2e9, backend="tpu", n=10_000_000,
        variant="fused-pallas pull SI", rounds=26, dt=0.077)
    assert line["backend"] == "tpu"
    assert line["vs_baseline"] == round(
        3.2e9 / bench.BASELINE_NODE_ROUNDS_PER_SEC_PER_CHIP, 4)
    assert line["metric"] == "node_rounds_per_sec_per_chip"


def test_cpu_fallback_has_null_vs_baseline():
    line = bench.measurement_line(
        rate=6.4e6, backend="cpu", n=500_000,
        variant="bit-packed pull SI (XLA fallback)", rounds=27, dt=2.1)
    assert line["vs_baseline"] is None
    assert line["backend"] == "cpu"
    # and the null survives the JSON trip the driver performs
    assert json.loads(json.dumps(line))["vs_baseline"] is None


def test_probe_attempts_env_hardening(monkeypatch):
    monkeypatch.delenv("GOSSIP_BENCH_PROBE_ATTEMPTS", raising=False)
    assert bench.probe_attempts_from_env() == 3
    monkeypatch.setenv("GOSSIP_BENCH_PROBE_ATTEMPTS", "7")
    assert bench.probe_attempts_from_env() == 7
    # malformed -> default (never crash before the one-line contract)
    monkeypatch.setenv("GOSSIP_BENCH_PROBE_ATTEMPTS", "2x")
    assert bench.probe_attempts_from_env() == 3
    # zero/negative can't silently disable the TPU probe
    monkeypatch.setenv("GOSSIP_BENCH_PROBE_ATTEMPTS", "0")
    assert bench.probe_attempts_from_env() == 1
    monkeypatch.setenv("GOSSIP_BENCH_PROBE_ATTEMPTS", "-5")
    assert bench.probe_attempts_from_env() == 1


def test_line_is_json_serializable_and_flat():
    line = bench.measurement_line(1.0, "cpu", 10, "x", 1, 1.0)
    parsed = json.loads(json.dumps(line))
    assert set(parsed) == {"metric", "value", "unit", "vs_baseline",
                           "backend", "last_tpu"}


def test_line_carries_compile_split():
    """Compile-once PR: the probe's cold/warm compile walls ride the
    scoreboard line (the reproducible CPU-side warm-start signal on
    boxes where no TPU rate moves) and survive the JSON trip."""
    split = {"cold_s": 9.31, "warm_s": 0.42, "statuses": ["miss", "hit"]}
    line = bench.measurement_line(1.0, "cpu", 10, "x", 1, 1.0,
                                  compile_split=split)
    parsed = json.loads(json.dumps(line))
    assert parsed["compile_split"] == split
    # absent when the body did not measure one (old artifacts replay)
    assert "compile_split" not in bench.measurement_line(
        1.0, "cpu", 10, "x", 1, 1.0)


def test_bench_compile_split_measures_store_roundtrip():
    """_bench_compile_split on a small jitted program: the recorded
    statuses must be a true (miss, hit) pair — timed_split suspends
    the ambient persistent cache itself, so anything else means the
    warm wall was not a store round-trip — and both walls are real."""
    import jax
    import jax.numpy as jnp
    compiled, split = bench._bench_compile_split(
        jax.jit(lambda x: jnp.cumsum(x * 2.0)),
        jnp.arange(256, dtype=jnp.float32))
    assert split["statuses"] == ["miss", "hit"]
    assert split["cold_s"] > 0 and split["warm_s"] > 0
    assert float(compiled(jnp.arange(256, dtype=jnp.float32))[-1]) > 0


def test_line_carries_churn_families():
    """Traced-operand PR: the nemesis families (churn_heal +
    churn_sweep with its first/warm amortization split) ride the
    scoreboard line as an optional ``families`` object and survive the
    JSON trip; absent when the body did not measure them (old
    artifacts replay)."""
    fam = {"churn_heal": {"n": 100_000, "rounds": 23,
                          "wall_ms": 4200.0,
                          "node_rounds_per_sec": 5.4e5},
           "churn_sweep": {"k": 8, "n": 8192, "first_ms": 3000.0,
                           "warm_ms": 500.0, "amortization": 6.0,
                           "converged": 8}}
    line = bench.measurement_line(1.0, "cpu", 10, "x", 1, 1.0,
                                  families=fam)
    assert json.loads(json.dumps(line))["families"] == fam
    assert "families" not in bench.measurement_line(
        1.0, "cpu", 10, "x", 1, 1.0)


def test_line_carries_headline_plan():
    """Scale-planner PR: the 100M-node capacity plan rides the
    scoreboard line as an optional ``plan`` object.  plan_for_headline
    on the CPU fallback plans against the REFERENCE topology, names
    the binding constraint, and carries the committed scale record's
    predicted-vs-measured pair (the model-validation evidence shipped
    with this tree); the object survives the JSON trip and is absent
    when the body did not plan (old artifacts replay)."""
    plan = bench.plan_for_headline("cpu")
    assert plan["target_n"] == bench.HEADLINE_TARGET_N
    assert plan["source"] == "reference"
    assert plan["chips"] == bench.REFERENCE_TPU_CHIPS
    # 100M x 64 rumors fits a v4-8-class host in the packed model
    assert plan["tiles"] >= 1 and plan["binding"]
    assert plan["predicted_peak_device_bytes"] > 0
    rec = plan["record"]
    assert rec is not None, "committed ledger_scale_r23 must resolve"
    assert rec["ok"] is True
    assert rec["measured_loop_bytes"] <= \
        rec["predicted_peak_device_bytes"]
    # the newest record by name wins: r23 carries the pipeline pair
    assert rec["artifact"].endswith("ledger_scale_r23.jsonl")
    assert 0.0 <= rec["overlap_efficiency"] <= 1.0
    assert rec["streamed_wall_ms"] > 0 and rec["serial_wall_ms"] > 0
    line = bench.measurement_line(1.0, "cpu", 10, "x", 1, 1.0,
                                  plan=plan)
    assert json.loads(json.dumps(line))["plan"]["record"]["ok"] is True
    assert "plan" not in bench.measurement_line(
        1.0, "cpu", 10, "x", 1, 1.0)


def test_line_carries_headline_serving():
    """Mesh-serving PR: the committed meshserve capture rides the
    scoreboard line as an optional ``serving`` object — rps + p99 per
    devices-per-replica width, with the capture's own honesty bits
    (``ok``/``devices_ratio``/``scaling_resolved``) carried verbatim,
    never re-derived.  The repo ships
    artifacts/ledger_meshserve_r21.jsonl, so the object must resolve
    against this tree; it survives the JSON trip and is absent when
    the body did not pass one (old artifacts replay)."""
    serving = bench.serving_for_headline()
    assert serving is not None, \
        "committed ledger_meshserve record must resolve"
    assert serving["artifact"].startswith("artifacts/ledger_meshserve")
    assert serving["ok"] is True
    assert serving["connections"] >= 1024
    assert serving["devices_ratio"] > 0
    assert isinstance(serving["scaling_resolved"], bool)
    assert len(serving["legs"]) >= 2
    widths = set()
    for leg in serving["legs"].values():
        assert leg["rps"] > 0 and leg["p99_ms"] > 0
        widths.add(leg["devices"])
    assert 1 in widths and max(widths) >= 4
    line = bench.measurement_line(1.0, "cpu", 10, "x", 1, 1.0,
                                  serving=serving)
    assert json.loads(json.dumps(line))["serving"]["ok"] is True
    assert "serving" not in bench.measurement_line(
        1.0, "cpu", 10, "x", 1, 1.0)


def test_fallback_carries_last_tpu_pointer():
    """VERDICT r4 task 2: a wedged-tunnel fallback line must point at
    the newest COMMITTED TPU capture so the scoreboard survives a
    wedge.  The repo ships artifacts/hw_refresh_r04.json with a green
    TPU bench step, so the pointer must resolve against this tree."""
    line = bench.measurement_line(
        rate=6.4e6, backend="cpu", n=500_000,
        variant="bit-packed pull SI (XLA fallback)", rounds=27, dt=2.1)
    ptr = line["last_tpu"]
    assert ptr is not None
    assert ptr["artifact"].startswith("artifacts/hw_refresh_r")
    assert ".smoke" not in ptr["artifact"]
    assert ptr["value"] > 1e9            # the r04 capture reads 3.49B
    assert ptr["vs_baseline"] > 100      # ... at 116.2x north star
    assert "backend=tpu" in ptr["unit"]
    # provenance fields resolve when the artifact is committed AND git
    # is available — last_tpu_capture tolerates their absence (source
    # exports without .git), so only assert where they can exist
    import shutil
    if shutil.which("git") and os.path.isdir(os.path.join(_REPO, ".git")):
        assert len(ptr.get("git_commit", "")) == 40
        assert ptr.get("captured", "").startswith("20")
    # the pointer never masquerades as a live measurement
    assert line["vs_baseline"] is None
    # and the whole line still survives the driver's JSON trip
    assert json.loads(json.dumps(line))["last_tpu"]["value"] == ptr["value"]


def test_tpu_line_has_no_last_tpu_field():
    """A live TPU measurement IS the record; the pointer only appears
    on fallback lines (keeps the scoreboard schema unambiguous)."""
    line = bench.measurement_line(
        rate=3.2e9, backend="tpu", n=10_000_000,
        variant="fused-pallas pull SI", rounds=26, dt=0.077)
    assert "last_tpu" not in line


def test_print_hermetic_env_contract():
    """``--print-hermetic-env`` is the operator's wedge-immunity eval
    line (a wedged tunnel hangs ANY armed interpreter at jax init, so
    pytest itself must be launchable disarmed).  Contract: exports the
    CPU platform + plugin-free PYTHONPATH, unsets every hazard var that
    arms the sitecustomize plugin, and never exports
    GOSSIP_COMPILE_CACHE (bench's cold-measurement policy — exporting
    it would silently disable the default-on persistent compile cache
    for the rest of the operator's shell)."""
    import subprocess
    import sys
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "10.0.0.1"   # armed shell
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--print-hermetic-env"],
        capture_output=True, text=True, env=env, timeout=120)
    assert p.returncode == 0
    out = p.stdout
    assert "export JAX_PLATFORMS=cpu" in out
    assert "unset PALLAS_AXON_POOL_IPS" in out
    assert "GOSSIP_COMPILE_CACHE" not in out
    for line in out.splitlines():
        assert line.startswith(("export ", "unset ")), line


@pytest.mark.skipif(
    os.environ.get("GOSSIP_TPU_TEST_PLATFORM", "cpu") != "cpu",
    reason="the axon tier deliberately keeps the tunnel plugin armed")
def test_conftest_disarms_tunnel_plugin_for_children():
    """Wedge-immunity contract (round 4): for the CPU tier, conftest
    scrubs the env that test-spawned subprocesses inherit — no
    tunnel-arming vars, no sitecustomize-bearing PYTHONPATH entries —
    so a mid-suite tunnel wedge cannot freeze child interpreters at
    startup.  This test IS a child-env observer: it asserts the state
    conftest promised."""
    import subprocess
    import sys
    assert os.environ.get("PALLAS_AXON_POOL_IPS") is None
    assert os.environ.get("JAX_PLATFORM_NAME") is None
    assert os.environ.get("LIBTPU_INIT_ARGS") is None
    for entry in os.environ.get("PYTHONPATH", "").split(os.pathsep):
        if entry:
            assert not os.path.exists(
                os.path.join(entry, "sitecustomize.py")), entry
    # and a real child sees the same scrubbed env + CPU platform
    p = subprocess.run(
        [sys.executable, "-c",
         "import os; print(os.environ.get('PALLAS_AXON_POOL_IPS'), "
         "os.environ.get('JAX_PLATFORMS'))"],
        capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    assert p.stdout.split() == ["None", "cpu"], (p.stdout, p.stderr)


def test_bench_trend_renders_full_trajectory(capsys):
    """tools/bench_trend.py: one row per committed BENCH_rNN record,
    backend recovered even for the pre-backend-field lines (round 2's
    CPU fallback must NOT render as a TPU number — the masquerade the
    backend field was added to kill), and a fallback round shows the
    last committed TPU proof it carried."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(_REPO, "tools", "bench_trend.py"))
    bt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bt)
    assert bt.main([]) == 0
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.startswith("| r0")]
    records = [n for n in os.listdir(_REPO)
               if n.startswith("BENCH_r") and n.endswith(".json")]
    assert len(lines) == len(records) >= 5
    r01 = next(ln for ln in lines if ln.startswith("| r01"))
    r02 = next(ln for ln in lines if ln.startswith("| r02"))
    assert "| tpu |" in r01
    assert "| cpu |" in r02            # the wedged-tunnel fallback
    r05 = next(ln for ln in lines if ln.startswith("| r05"))
    assert "hw_refresh_r04.json" in r05   # the last_tpu proof pointer
