"""Checkpoint/resume, metrics, and tracing (SURVEY.md §5 subsystems)."""

import json
import os

import jax
import numpy as np
import pytest

from gossip_tpu.config import ProtocolConfig, RunConfig
from gossip_tpu.models.si import make_si_round
from gossip_tpu.models.state import init_state
from gossip_tpu.models.swim import init_swim_state, make_swim_round
from gossip_tpu.topology import generators as G
from gossip_tpu.utils.checkpoint import (load_meta, load_state,
                                         run_with_checkpoints, save_state)
from gossip_tpu.utils.metrics import (curve_gap, dump_curve_jsonl,
                                      load_curve_jsonl, summarize_curve)
from gossip_tpu.utils.trace import RoundTimer, annotate, trace


def test_checkpoint_resume_is_bitwise_identical(tmp_path):
    # resume == straight run, bitwise — the PRNG key survives the npz trip
    proto = ProtocolConfig(mode="pushpull", fanout=1, rumors=3)
    topo = G.erdos_renyi(128, 0.08, seed=2)
    step = jax.jit(make_si_round(proto, topo))
    st = init_state(RunConfig(seed=9), proto, topo.n)
    for _ in range(4):
        st = step(st)
    p = str(tmp_path / "ck.npz")
    save_state(p, st)
    resumed = load_state(p)
    a, b = st, resumed
    for _ in range(4):
        a = step(a)
        b = step(b)
    np.testing.assert_array_equal(np.asarray(a.seen), np.asarray(b.seen))
    assert float(a.msgs) == float(b.msgs)
    assert int(a.round) == int(b.round)


def test_checkpoint_swim_state(tmp_path):
    proto = ProtocolConfig(mode="swim", fanout=2, swim_subjects=4,
                           swim_proxies=2, swim_suspect_rounds=4)
    step = jax.jit(make_swim_round(proto, 64, dead_nodes=(1,), fail_round=2))
    st = init_swim_state(64, 4, seed=3)
    for _ in range(6):
        st = step(st)
    p = str(tmp_path / "swim.npz")
    save_state(p, st)
    r = load_state(p)
    np.testing.assert_array_equal(np.asarray(st.wire), np.asarray(r.wire))
    a, b = step(st), step(r)
    np.testing.assert_array_equal(np.asarray(a.wire), np.asarray(b.wire))


def test_run_with_checkpoints_writes_and_resumes(tmp_path):
    proto = ProtocolConfig(mode="pull", fanout=1)
    topo = G.complete(128)
    step = jax.jit(make_si_round(proto, topo))
    st0 = init_state(RunConfig(seed=1), proto, topo.n)
    p = str(tmp_path / "run.npz")
    final = run_with_checkpoints(step, st0, rounds=7, path=p, every=3)
    assert os.path.exists(p)
    assert int(load_state(p).round) == int(final.round) == 7
    # continue from disk for 3 more == straight 10
    more = run_with_checkpoints(step, load_state(p), rounds=3, path=p)
    straight = st0
    for _ in range(10):
        straight = step(straight)
    np.testing.assert_array_equal(np.asarray(more.seen),
                                  np.asarray(straight.seen))


def test_run_with_checkpoints_is_chunk_compiled(tmp_path):
    # VERDICT r1: the checkpoint driver must not pay a host dispatch per
    # round.  A counting wrapper proves the step fn is invoked only while
    # TRACING the segment runner (a handful of times), never once per
    # round, and the trajectory stays bitwise equal to a straight loop.
    proto = ProtocolConfig(mode="pull", fanout=1)
    topo = G.complete(256)
    base = make_si_round(proto, topo)
    calls = {"n": 0}

    def counted(s):
        calls["n"] += 1
        return base(s)

    st0 = init_state(RunConfig(seed=4), proto, topo.n)
    p = str(tmp_path / "c.npz")
    final = run_with_checkpoints(counted, st0, rounds=120, path=p, every=50)
    assert calls["n"] < 10                       # trace-time only
    assert int(final.round) == 120
    straight = st0
    sj = jax.jit(base)
    for _ in range(120):
        straight = sj(straight)
    np.testing.assert_array_equal(np.asarray(final.seen),
                                  np.asarray(straight.seen))

    # (throughput equivalence to a fused loop follows from the trace-count
    # property above: 3 segment dispatches, not 120 — a wall-clock assert
    # here would only add CI flake risk)
    with pytest.raises(ValueError, match="every"):
        run_with_checkpoints(counted, st0, rounds=5, path=p, every=0)


def test_summarize_curve_and_gap():
    cov = [0.1, 0.5, 0.995, 1.0]
    msgs = [10, 30, 60, 80]
    m = summarize_curve(cov, msgs, n=100, target=0.99, wall_s=2.0)
    assert m.rounds_to_target == 3
    assert m.final_coverage == 1.0
    assert m.msgs_total == 80
    assert m.msgs_per_node_per_round == pytest.approx(80 / 400)
    assert m.node_rounds_per_sec == pytest.approx(100 * 4 / 2.0)
    assert curve_gap(cov, cov) == 0.0
    assert curve_gap([0.5, 1.0], [0.4, 1.0, 1.0]) == pytest.approx(0.1)
    assert m.to_dict()["auc"] == pytest.approx(sum(cov) / 4)


def test_curve_jsonl_round_trip(tmp_path):
    p = str(tmp_path / "curve.jsonl")
    dump_curve_jsonl(p, [0.5, 1.0], [3, 7], meta={"mode": "pull"})
    rows = load_curve_jsonl(p)
    assert rows[0] == {"meta": {"mode": "pull"}}
    assert rows[1] == {"round": 1, "coverage": 0.5, "msgs": 3.0}
    assert rows[2]["coverage"] == 1.0
    # full dump -> load round trip including the meta line: the loaded
    # rows reconstruct exactly the series that were dumped
    cov = [r["coverage"] for r in rows if "round" in r]
    msgs = [r["msgs"] for r in rows if "round" in r]
    p2 = str(tmp_path / "curve2.jsonl")
    dump_curve_jsonl(p2, cov, msgs, meta=rows[0]["meta"])
    assert load_curve_jsonl(p2) == rows
    # msgs-free dump omits the field entirely
    dump_curve_jsonl(p2, cov)
    assert all("msgs" not in r for r in load_curve_jsonl(p2))


def test_curve_jsonl_rejects_length_mismatch(tmp_path):
    """A msgs series of the wrong length must raise ValueError BEFORE
    any write (the old IndexError fired mid-write and left a torn
    artifact that parsed as a shorter run)."""
    p = str(tmp_path / "bad.jsonl")
    with pytest.raises(ValueError, match="len"):
        dump_curve_jsonl(p, [0.5, 1.0], [3])
    with pytest.raises(ValueError, match="len"):
        dump_curve_jsonl(p, [0.5], [3, 7], meta={"m": 1})
    assert not os.path.exists(p), "nothing may be written on rejection"


def test_round_timer_percentiles():
    """p50/p95 alongside mean: stepwise drivers report means that hide
    stragglers — one wedged round in 100 fast ones moves p95, not the
    mean."""
    t = RoundTimer()
    assert t.mean_ms == t.p50_ms == t.p95_ms == 0.0   # no samples yet
    t.times = [0.001 * v for v in range(1, 101)]      # 1..100 ms
    assert t.p50_ms == pytest.approx(50.0)
    assert t.p95_ms == pytest.approx(95.0)
    assert t.mean_ms == pytest.approx(50.5)
    # a straggler dominates the tail, barely moves the mean
    t.times = [0.001] * 99 + [1.0]
    assert t.p50_ms == pytest.approx(1.0)
    assert t.p95_ms == pytest.approx(1.0)
    assert t.percentile_ms(1.0) == pytest.approx(1000.0)
    # single sample: every percentile is that sample
    t.times = [0.004]
    assert t.p50_ms == t.p95_ms == pytest.approx(4.0)
    with pytest.raises(ValueError, match="outside"):
        t.percentile_ms(1.5)


def test_trace_smoke(tmp_path, monkeypatch):
    """One real profiler capture smokes the whole observability
    surface: the $GOSSIP_PROFILE ambient hook (trace.profile — what
    the dry run and bench wrap), a named annotation inside it, and the
    compat probes it degrades through.  trace(logdir) shares the same
    jax.profiler machinery (its CLI path runs under `-m slow`)."""
    from gossip_tpu import compat
    from gossip_tpu.utils.trace import profile, profile_dir
    prof = str(tmp_path / "prof")
    monkeypatch.setenv("GOSSIP_PROFILE", prof)
    assert profile_dir() == prof
    assert compat.profiler_trace_fns() is not None   # this jax has it
    with profile("smoke"):
        with annotate("round"):
            jax.block_until_ready(jax.numpy.arange(8) * 2)
    # trace files land under the ambient dir
    assert any(os.scandir(prof))
    # unset/empty = strictly off (the GOSSIP_TELEMETRY convention):
    # the profiler probe must never even be consulted
    monkeypatch.setenv("GOSSIP_PROFILE", "")
    assert profile_dir() is None

    def _probed():
        raise AssertionError("profiler probed while GOSSIP_PROFILE off")
    monkeypatch.setattr(compat, "profiler_trace_fns", _probed)
    with profile("dark"):
        pass
    t = RoundTimer()
    for _ in range(2):
        with t:
            pass
    assert len(t.times) == 2 and t.mean_ms >= 0


def test_run_with_checkpoints_named_curve_channels(tmp_path):
    """Dict-valued curve_fn (rumor's coverage+hot pair): one list per
    channel, persisted in the checkpoint meta, resumable via a dict
    curve_prefix; a flat-list prefix against a dict curve_fn is a
    TypeError (never silently mixed)."""
    import pytest

    from gossip_tpu.models.si import coverage
    proto = ProtocolConfig(mode="pull", fanout=1)
    topo = G.complete(64)
    step = jax.jit(make_si_round(proto, topo))
    st0 = init_state(RunConfig(seed=2), proto, topo.n)

    def channels(s):
        return {"coverage": coverage(s.seen, None),
                "msgs": s.msgs}

    p = str(tmp_path / "chan.npz")
    st, curve = run_with_checkpoints(step, st0, rounds=5, path=p,
                                     every=2, curve_fn=channels)
    assert set(curve) == {"coverage", "msgs"}
    assert len(curve["coverage"]) == len(curve["msgs"]) == 5
    saved = load_meta(p)["extra"]["curve"]
    assert saved == curve
    st2, curve2 = run_with_checkpoints(step, load_state(p), rounds=3,
                                       path=p, curve_fn=channels,
                                       curve_prefix=saved)
    assert len(curve2["coverage"]) == 8
    assert curve2["coverage"][:5] == curve["coverage"]
    straight, full = run_with_checkpoints(step, st0, rounds=8,
                                          path=str(tmp_path / "s.npz"),
                                          curve_fn=channels)
    assert curve2 == full
    np.testing.assert_array_equal(np.asarray(st2.seen),
                                  np.asarray(straight.seen))
    with pytest.raises(TypeError):
        run_with_checkpoints(step, st0, rounds=2,
                             path=str(tmp_path / "bad.npz"),
                             curve_fn=channels, curve_prefix=[0.5])
    # zero-rounds resume of an already-complete run: a dict-valued
    # curve_fn must still return its named channels, never a bare []
    # (ADVICE r4 — downstream channel extraction would silently lose
    # the names)
    st3, curve3 = run_with_checkpoints(step, load_state(p), rounds=0,
                                       path=p, curve_fn=channels,
                                       curve_prefix=())
    assert isinstance(curve3, dict)
    assert set(curve3) == {"coverage", "msgs"}
    assert curve3 == {"coverage": [], "msgs": []}



def test_tier1_wall_warning_predicate():
    """tests/conftest.py's 90%-of-gate warning threshold, unit-tested
    without an 800 s session (the sweep_cache_eviction pattern)."""
    import conftest
    assert conftest.tier1_wall_warning(700.0) is None
    assert conftest.tier1_wall_warning(783.0 - 1e-6) is None
    msg = conftest.tier1_wall_warning(800.0)
    assert msg and "rebalance" in msg and "870" in msg
    # scales with the gate, not hardcoded to it
    assert conftest.tier1_wall_warning(80.0, gate_s=100.0,
                                       frac=0.5) is not None
