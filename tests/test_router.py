"""Replicated sidecar serving (rpc/router + tools/fleet_crashloop):
health-gated failover dispatch, flap hysteresis, the ops/logs control
plane, shed/deadline semantics, the SidecarClient retry budget, the
batcher drain ordering, and the committed fleet-crashloop record's
gates."""

import importlib.util
import json
import os
import sys
import time

import pytest

from gossip_tpu.config import FleetConfig, ServingConfig
from gossip_tpu.utils import telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLEET_RECORD = os.path.join(_REPO, "artifacts",
                            "ledger_fleet_r18.jsonl")
TRACE_RECORD = os.path.join(_REPO, "artifacts",
                            "ledger_trace_r22.jsonl")


# -- control plane (ops/logs dogfood) ---------------------------------

def test_control_plane_log_epochs_and_catchup():
    """The fleet's admission state IS a replicated log (ops/logs):
    per-replica owner keys, committed offset = config epoch, views
    merged by the log join — and a wiped (rejoined) view catches the
    whole fleet state up from any survivor's gossip, never from
    operator state."""
    from gossip_tpu.rpc.router import (STATE_DOWN, STATE_UP,
                                       ControlPlane)
    cp = ControlPlane(3, 8)
    assert cp.append(0, STATE_UP) == 1
    assert cp.append(1, STATE_UP) == 1
    assert cp.append(0, STATE_DOWN) == 2
    # transitions live only in the owners' views until gossip carries
    # them (replica 2 has not yet heard of replica 0's transitions);
    # rotating-partner pulls converge the fleet within n-1 ticks
    assert int(cp.views[2].sum()) == 0
    for _ in range(3):
        cp.gossip_tick()
    assert cp.epochs() == [2, 1, 0]
    assert (cp.views[0] == cp.views[2]).all()      # fully converged
    assert cp.state_of(0) == "down" and cp.state_of(1) == "up"
    # rejoin: replica 0's view dies with its process; catchup rebuilds
    # epoch AND state purely by merging survivors
    cp.wipe(0)
    assert cp.epoch(0) == 0
    assert cp.catchup(0) == 2
    assert cp.state_of(0) == "down"
    assert cp.append(0, STATE_UP) == 3     # epochs never alias
    # a full ring refuses loudly instead of aliasing epochs on a wrap
    cp2 = ControlPlane(1, 4)
    for state in (STATE_UP, STATE_DOWN, STATE_UP, STATE_DOWN):
        cp2.append(0, state)
    with pytest.raises(ValueError, match="ring wrap"):
        cp2.append(0, STATE_UP)
    # flush-before-wipe: an owner-only entry pushed to peers survives
    # the owner's death (the replace_replica ordering)
    cp3 = ControlPlane(2, 8)
    cp3.append(0, STATE_UP)
    cp3.flush(0)
    cp3.wipe(0)
    assert cp3.catchup(0) == 1


# -- hysteresis (satellite: probe flapping) ---------------------------

def test_probe_flapping_respects_readmission_hysteresis():
    """Satellite pin: a replica alternating healthy/unhealthy must NOT
    oscillate in and out of rotation — after a down, re-admission
    takes ``up_after`` CONSECUTIVE healthy probes, so a scripted
    flap sequence keeps it out until a genuinely stable stretch."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from gossip_tpu.rpc.router import Router
    router = Router(["127.0.0.1:1", "127.0.0.1:2"],
                    FleetConfig(down_after=2, up_after=3,
                                probe_interval_ms=10_000))
    r = router.replicas[0]
    try:
        # initial admission: one healthy probe (nothing was lost yet)
        router.observe_probe(r, True)
        assert r.healthy
        # down takes down_after consecutive failures, not one blip
        router.observe_probe(r, False)
        assert r.healthy
        router.observe_probe(r, False)
        assert not r.healthy
        # the flap: ok/fail alternation never re-admits (consec_ok
        # resets every failure, so it never reaches up_after=3)
        for _ in range(6):
            router.observe_probe(r, True)
            assert not r.healthy, "flapping replica re-entered " \
                "rotation before the hysteresis threshold"
            router.observe_probe(r, False)
        # a stable healthy stretch re-admits at exactly up_after
        router.observe_probe(r, True)
        router.observe_probe(r, True)
        assert not r.healthy
        router.observe_probe(r, True)
        assert r.healthy
        # the control-plane log recorded the admission history
        assert router.control.epoch(0) == 3          # up, down, up
        assert router.control.state_of(0) == "up"
    finally:
        router.close()


# -- dispatch unit semantics (shed / deadline) ------------------------

class _Aborted(Exception):
    pass


class _Ctx:
    """Minimal gRPC server-context stand-in for dispatch unit tests."""

    def __init__(self, remaining=None):
        self._remaining = remaining
        self.code = self.details = None

    def time_remaining(self):
        return self._remaining

    def abort(self, code, details):
        self.code, self.details = code, details
        raise _Aborted(details)


def test_router_sheds_and_honors_abandoned_deadlines(tmp_path):
    """Shed, never queue: with no healthy replica the router rejects
    RESOURCE_EXHAUSTED and ledgers a ``shed`` event.  Deadlines
    propagate end-to-end: a request whose client deadline already
    passed is rejected DEADLINE_EXCEEDED without ever dispatching — a
    failover retry can never run a request its client abandoned."""
    grpc = pytest.importorskip("grpc")
    from gossip_tpu.rpc.router import Router
    led_path = str(tmp_path / "router.jsonl")
    led = telemetry.Ledger(led_path)
    prev = telemetry.activate(led)
    router = Router(["127.0.0.1:1"],
                    FleetConfig(probe_interval_ms=10_000))
    try:
        # nothing admitted yet -> shed
        ctx = _Ctx()
        with pytest.raises(_Aborted, match="shed"):
            router.dispatch("run", b"{}", ctx)
        assert ctx.code == grpc.StatusCode.RESOURCE_EXHAUSTED
        # a healthy replica but an expired client deadline -> terminal
        # DEADLINE_EXCEEDED, zero dispatch attempts (the stub would
        # raise UNAVAILABLE and the counters would show a failover)
        router.observe_probe(router.replicas[0], True)
        ctx = _Ctx(remaining=-0.01)
        with pytest.raises(_Aborted, match="deadline"):
            router.dispatch("run", b"{}", ctx)
        assert ctx.code == grpc.StatusCode.DEADLINE_EXCEEDED
        assert router.counters["failovers"] == 0
        assert router.counters["deadline_rejects"] == 1
        # saturation: every healthy replica at the in-flight cap
        router.replicas[0].inflight = router.cfg.max_inflight
        ctx = _Ctx()
        with pytest.raises(_Aborted, match="shed"):
            router.dispatch("run", b"{}", ctx)
        assert ctx.code == grpc.StatusCode.RESOURCE_EXHAUSTED
    finally:
        router.close()
        telemetry.activate(prev)
        led.close()
    events = telemetry.load_ledger(led_path)
    sheds = [e for e in events if e.get("ev") == "shed"]
    assert len(sheds) == 2
    assert sheds[0]["reason"] == "no healthy replica"
    assert "cap" in sheds[1]["reason"]
    assert [e for e in events if e.get("ev") == "deadline_exceeded"
            and e.get("source") == "router"]


# -- live failover (in-gate: one compile, two replicas) ---------------

def test_router_failover_redispatches_inflight_bitwise(tmp_path):
    """THE fleet tentpole, live and in-process: two batching sidecar
    replicas behind the router; a request runs, replica 0 dies hard,
    the next dispatch fails over to the survivor and the reply is
    BITWISE the same as replaying the identical payload (requests are
    pure functions of their payload — the re-dispatch safety
    contract), with the down/failover flight-record and the
    control-plane epochs advancing."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from gossip_tpu.rpc import router as RT
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    led_path = str(tmp_path / "fleet.jsonl")
    led = telemetry.Ledger(led_path)
    prev = telemetry.activate(led)
    servers = [serve(port=0, max_workers=4,
                     batching=ServingConfig(tick_ms=25))
               for _ in range(2)]
    # start_probes=False: admission driven by probe_once below, so a
    # background probe can never race the hard stop and steal the
    # failover (the dispatch must find the corpse first)
    rserver, rport, router = RT.serve_router(
        [f"127.0.0.1:{p}" for _, p in servers],
        cfg=FleetConfig(probe_interval_ms=10_000, down_after=1,
                        up_after=2), start_probes=False)
    client = SidecarClient(f"127.0.0.1:{rport}", max_attempts=1)

    def req(seed):
        return dict(backend="jax-tpu",
                    proto={"mode": "pushpull", "fanout": 2},
                    topology={"family": "complete", "n": 64},
                    run={"max_rounds": 4, "engine": "xla",
                         "seed": seed}, curve=True)
    try:
        router.probe_once()
        assert router.healthy_count() == 2
        a = client.run(timeout=120, **req(0))
        assert a["meta"]["batch"]["batched"] is True
        # hard failure: the serial least-inflight policy had routed to
        # replica 0, so the next dispatch lands on the corpse first
        servers[0][0].gossip_batcher.close()
        servers[0][0].stop(grace=None)
        b = client.run(timeout=120, **req(1))
        assert b["coverage"] > 0
        s = router.stats()
        assert s["failovers"] >= 1 and s["healthy"] == 1
        assert s["states"][0] == "down" and s["states"][1] == "up"
        assert s["epochs"][0] >= 2          # up, then down
        # bitwise replay parity: the surviving replica re-serves the
        # SAME payload to the same bytes — what makes failover
        # re-dispatch safe
        a2 = client.run(timeout=120, **req(0))
        for field in ("curve", "msgs", "coverage", "rounds"):
            assert a2[field] == a[field], field
        # the router's health reply carries the fleet summary
        h = client.health()
        assert h["router"] is True and h["healthy"] == 1
    finally:
        client.close()
        rserver.stop(grace=None)
        router.close()
        servers[1][0].gossip_batcher.close()
        servers[1][0].stop(grace=None)
        telemetry.activate(prev)
        led.close()
    events = telemetry.load_ledger(led_path)
    kinds = {e.get("ev") for e in events}
    assert {"replica_down", "failover", "replica_up"} <= kinds


def test_trace_propagates_through_failover_redispatch(tmp_path):
    """Satellite pin: ONE minted trace_id survives a mid-flight
    failover re-dispatch.  The replayed attempt carries the SAME
    trace_id with a NEW ``dispatch_attempt`` span on the survivor, the
    ``failover`` span carries it too, the router's terminal
    ``request_trace`` waterfall counts the retry, and the trace_id
    join (tools/trace_report) yields one COMPLETE waterfall — the
    end-to-end tracing contract under the fleet's hardest path."""
    pytest.importorskip("grpc")
    from gossip_tpu.rpc import router as RT
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    led_path = str(tmp_path / "trace_failover.jsonl")
    led = telemetry.Ledger(led_path)
    prev = telemetry.activate(led)
    servers = [serve(port=0, max_workers=4,
                     batching=ServingConfig(tick_ms=25))
               for _ in range(2)]
    rserver, rport, router = RT.serve_router(
        [f"127.0.0.1:{p}" for _, p in servers],
        cfg=FleetConfig(probe_interval_ms=10_000, down_after=1,
                        up_after=2), start_probes=False)
    client = SidecarClient(f"127.0.0.1:{rport}", max_attempts=1)

    def req(seed):
        return dict(backend="jax-tpu",
                    proto={"mode": "push", "fanout": 2},
                    topology={"family": "complete", "n": 64},
                    run={"max_rounds": 4, "engine": "xla",
                         "seed": seed}, curve=True)
    tid = "feedfacecafe0001"
    try:
        router.probe_once()
        assert router.healthy_count() == 2
        client.run(timeout=120, **req(0))    # routes to replica 0
        # kill replica 0 hard: the serial least-inflight policy sends
        # the NEXT dispatch to the corpse first, forcing the failover
        servers[0][0].gossip_batcher.close()
        servers[0][0].stop(grace=None)
        out = client.run(timeout=120, trace_id=tid, **req(1))
        assert out["coverage"] > 0
        assert router.stats()["failovers"] >= 1
    finally:
        client.close()
        rserver.stop(grace=None)
        router.close()
        servers[1][0].gossip_batcher.close()
        servers[1][0].stop(grace=None)
        telemetry.activate(prev)
        led.close()
    # the trace_id= filter isolates the one request's span set
    tev = telemetry.load_ledger(led_path, trace_id=tid)
    attempts = [e for e in tev if e.get("ev") == "dispatch_attempt"]
    assert [a["attempt"] for a in attempts] == [1, 2]
    assert attempts[0]["replica"] == 0          # the corpse
    assert attempts[1]["replica"] == 1          # the survivor
    assert any(e.get("ev") == "failover" for e in tev)
    rt = [e for e in tev if e.get("ev") == "request_trace"]
    router_half = [e for e in rt if e.get("source") == "router"]
    replica_half = [e for e in rt if e.get("source") == "replica"]
    assert len(router_half) == 1
    assert router_half[0]["retries"] == 1       # the replay counted
    assert router_half[0]["replica"] == 1
    assert replica_half, rt                     # survivor's half joins
    # and the one join implementation agrees: a complete waterfall
    # with the failover attributed
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    wf = trace_report.waterfall(
        trace_report.join_traces(telemetry.load_ledger(led_path))[tid])
    assert wf["complete"] and wf["attempts"] == 2
    assert wf["failovers"] >= 1 and wf["retries"] == 1


# -- SidecarClient retry budget (satellite) ---------------------------

def test_client_retry_budget_clamps_attempt_deadlines():
    """Satellite pin: the caller's timeout is the TOTAL retry budget —
    each attempt's deadline is clamped to the remaining budget (the
    last attempt gets exactly what is left), and a budget exhausted
    between attempts re-raises instead of dispatching again.  Without
    this a dying replica stretches one call to attempts x timeout."""
    grpc = pytest.importorskip("grpc")
    from gossip_tpu.rpc.sidecar import SidecarClient

    class Unavailable(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return "fake transport failure"

    client = SidecarClient("127.0.0.1:1", max_attempts=4,
                           backoff_base=0.03, backoff_cap=0.05)
    calls = []

    def fake(payload, timeout=None, metadata=None):
        calls.append((timeout, time.monotonic()))
        raise Unavailable()
    t0 = time.monotonic()
    budget = 0.5
    with pytest.raises(grpc.RpcError):
        client._call_with_retry(fake, b"{}", budget, "run")
    wall = time.monotonic() - t0
    assert len(calls) == 4              # budget covered all attempts
    deadline = t0 + budget
    timeouts = [c[0] for c in calls]
    # strictly shrinking deadlines, each equal to the REMAINING budget
    assert all(a > b for a, b in zip(timeouts, timeouts[1:]))
    for tmo, at in calls:
        assert abs(tmo - (deadline - at)) < 0.05, (tmo, deadline - at)
    assert timeouts[-1] < budget        # the clamp actually engaged
    assert wall < budget + 0.2
    # budget exhausted mid-backoff: NO further attempt is dispatched
    client2 = SidecarClient("127.0.0.1:1", max_attempts=4,
                            backoff_base=0.2, backoff_cap=0.4)
    calls.clear()
    t0 = time.monotonic()
    with pytest.raises(grpc.RpcError):
        client2._call_with_retry(fake, b"{}", 0.05, "run")
    assert len(calls) < 4, "an attempt ran after the budget expired"
    assert time.monotonic() - t0 < 0.5
    client.close()
    client2.close()


# -- batcher drain ordering (satellite) -------------------------------

def test_batcher_drain_rejects_new_admissions_before_flushing():
    """Satellite pin: a draining batcher refuses new admissions with
    Closed (-> UNAVAILABLE) BEFORE flushing queued work — the stop
    flag is checked inside the queue lock, so no admission can land in
    a queue after its final drain and strand its handler forever."""
    from gossip_tpu.backend import request_to_args
    from gossip_tpu.rpc import batcher as B
    args = request_to_args({
        "backend": "jax-tpu", "proto": {"mode": "pull", "fanout": 1},
        "topology": {"family": "complete", "n": 8},
        "run": {"max_rounds": 2}})
    b = B.Batcher(ServingConfig(tick_ms=10_000, max_batch=8,
                                max_queue=8))
    # park the collector so the drain points are OURS alone (the
    # white-box way to pin an ordering that is otherwise a race)
    b._stop.set()
    b._thread.join(timeout=10)
    b._stop.clear()
    pending, note = b.submit_run(args, time.monotonic() - 0.01)
    assert pending is not None and note is None
    # the drain begins: stop flag FIRST...
    b._stop.set()
    with pytest.raises(B.Closed, match="shut down"):
        b.submit_run(args, None)
    # ...and the queued request is still pending (not yet flushed):
    # rejection precedes flush, so nothing can slip in between
    assert not pending.event.is_set()
    # ...flush SECOND: close() answers the queued request (expired
    # here, so it errors rather than runs) — never strands it
    b.close()
    with pytest.raises(B.Expired, match="deadline expired"):
        pending.wait()
    assert b._queue == []


# -- CLI ---------------------------------------------------------------

def test_cli_route_validates_flags(capsys):
    from gossip_tpu.cli import main as cli_main
    assert cli_main(["route", "--replicas", "0"]) == 2
    assert "replicas" in capsys.readouterr().err
    # mesh-sharded replicas need the admission batcher: refusing the
    # contradiction beats spawning a fleet whose mesh can never run
    assert cli_main(["route", "--devices-per-replica", "4",
                     "--no-batching"]) == 2
    assert "devices-per-replica" in capsys.readouterr().err
    # devices per replica must be a pow2 (FleetConfig validation):
    # lane buckets divide the mesh or the executable cache fragments
    assert cli_main(["route", "--devices-per-replica", "3"]) == 2
    assert "power of two" in capsys.readouterr().err


# -- devices-per-replica gate (the mesh-sharded serving PR) -----------

def test_fleet_env_threads_host_device_count(monkeypatch):
    """A replica child pinned to CPU has exactly ONE XLA device unless
    fleet_env threads the host-device-count flag — the silent-mesh-
    degradation bug this PR's satellite closes.  An ambient pin is
    respected, never duplicated."""
    from gossip_tpu.rpc.router import fleet_env
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    env = fleet_env(devices=4)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["XLA_FLAGS"] == \
        "--xla_force_host_platform_device_count=4"
    # devices=1 (or None) adds nothing: the solo replica path
    assert "XLA_FLAGS" not in fleet_env(devices=1)
    # an ambient count is the caller's pin — left alone
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
    assert fleet_env(devices=4)["XLA_FLAGS"] == \
        "--xla_force_host_platform_device_count=8"
    # other ambient flags survive the append
    monkeypatch.setenv("XLA_FLAGS", "--xla_dump_to=/tmp/x")
    assert fleet_env(devices=4)["XLA_FLAGS"] == \
        "--xla_dump_to=/tmp/x --xla_force_host_platform_device_count=4"


def test_replica_device_verification_refuses_degraded_mesh():
    """Planted degradation: a live replica serving WITHOUT a mesh
    (exactly what a child missing the host-device-count env degrades
    to) reports serving_devices=1 in its health reply, and the fleet's
    spawn-time gate refuses it loudly instead of running a healthy-
    looking 1-device fleet — a gate that cannot fail is not a gate."""
    from gossip_tpu.rpc.router import _verify_replica_devices
    from gossip_tpu.rpc.sidecar import serve
    server, port = serve(port=0, max_workers=2,
                         batching=ServingConfig(tick_ms=25.0))
    try:
        addr = f"127.0.0.1:{port}"
        _verify_replica_devices(addr, "r0_g0", 1)        # solo: fine
        with pytest.raises(RuntimeError) as ei:
            _verify_replica_devices(addr, "r0_g0", 2)
        msg = str(ei.value)
        assert "serving_devices=1" in msg
        assert "devices_per_replica=2" in msg
    finally:
        server.gossip_batcher.close()
        server.stop(grace=None)


# -- committed record + live smoke ------------------------------------

def test_committed_fleet_crashloop_record_gates_hold():
    """The committed fleet nemesis record
    (artifacts/ledger_fleet_r18.jsonl) re-asserted so it can never
    rot: provenance present, K >= 2 seeded SIGKILLs that all landed
    MID-load, zero acked-request loss, per-request bitwise reply
    parity vs solo dispatch, failover-visible flight-record
    (replica_down / failover / replica_up / control_catchup), and
    recovery to full healthy capacity."""
    events = telemetry.load_ledger(FLEET_RECORD, run="last")
    prov = events[0]
    assert prov["ev"] == "provenance"
    assert len(prov["git_commit"]) == 40
    cfgs = [e for e in events if e.get("ev") == "config"]
    assert cfgs and cfgs[0]["replicas"] >= 3
    verdict = [e for e in events if e.get("ev") == "verdict"][-1]
    assert verdict["ok"] is True
    assert verdict["kills"] >= 2
    assert verdict["zero_acked_loss"] is True
    assert verdict["errors"] == 0
    assert verdict["acked"] == verdict["requests"]
    assert verdict["bitwise_equal"] is True
    assert verdict["mismatches"] == 0
    assert verdict["failovers"] >= 1
    assert verdict["recovered_full_capacity"] is True
    assert verdict["healthy"] == cfgs[0]["replicas"]
    # every kill landed strictly mid-load
    kills = [e for e in events if e.get("ev") == "kill"]
    assert len(kills) == verdict["kills"]
    for k in kills:
        assert 0 < k["acked"] < verdict["requests"]
    # the failover flight-record is complete: downs, re-dispatches,
    # re-admissions, and the gossip catchup of every respawn
    kinds = {e.get("ev") for e in events}
    assert {"replica_down", "failover", "replica_up",
            "control_catchup", "respawn", "recovered"} <= kinds
    catchups = [e for e in events if e.get("ev") == "control_catchup"]
    assert len(catchups) >= verdict["kills"]
    for e in catchups:
        assert e["epoch"] >= 2          # up + down survived the wipe


def test_committed_trace_capture_record_gates_hold():
    """The committed request-tracing record
    (artifacts/ledger_trace_r22.jsonl, tools/trace_capture.py)
    re-asserted so it can never rot: provenance present, a 3-replica
    K=1 SIGKILL crashloop with zero acked loss, EVERY trace joined to
    a complete waterfall (failover-replayed included — re-joined live
    here via tools/trace_report.py, not just trusted from the
    verdict), fleet-status seeing the kill and the recovery, and the
    zero-steady-state-cost claim (zero compiles + zero fsyncs at the
    Metrics window edges)."""
    # the trace ledger is MULTI-writer (router + replica children):
    # no run filter — the join is exactly the cross-run contract
    events = telemetry.load_ledger(TRACE_RECORD)
    prov = events[0]
    assert prov["ev"] == "provenance"
    assert len(prov["git_commit"]) == 40
    cfgs = [e for e in events if e.get("ev") == "config"]
    assert cfgs and cfgs[0]["replicas"] >= 3
    verdict = [e for e in events if e.get("ev") == "verdict"][-1]
    assert verdict["ok"] is True
    assert verdict["problems"] == []
    assert verdict["kills"] >= 1
    assert verdict["errors"] == 0
    assert verdict["acked"] == verdict["requests"]
    assert verdict["complete"] == verdict["traces"]
    assert verdict["replayed"] >= 1
    assert verdict["replayed_complete"] >= 1
    assert verdict["fleet_status_saw_kill"] is True
    assert verdict["fleet_status_saw_recovery"] is True
    assert verdict["recovered_full_capacity"] is True
    assert verdict["healthy"] == cfgs[0]["replicas"]
    for k in [e for e in events if e.get("ev") == "kill"]:
        assert 0 < k["acked"] < verdict["requests"]
    # fleet-status's own flight-record: degraded after the kill,
    # healthy again after the probe hysteresis re-admits the respawn
    fs = [e for e in events if e.get("ev") == "fleet_status"]
    assert any(e["degraded"] and e["tag"].startswith("after_kill")
               for e in fs)
    assert any(not e["degraded"] and e["tag"] == "after_recovery"
               for e in fs)
    # the zero-cost window, from the recorded Metrics edge deltas
    cost = [e for e in events if e.get("ev") == "steady_cost"][-1]
    assert cost["ok"] is True
    assert cost["router_fsyncs_delta"] == 0
    assert cost["replicas"]
    for row in cost["replicas"].values():
        assert row["compiles_delta"] in (0, None)
        assert row["fsyncs_delta"] == 0
    # re-join the artifact live: every traced request must close
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(_REPO, "tools",
                                     "trace_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    joined = tr.join_traces(events)
    # router-dispatched traces (the capture's measured + steady mix);
    # direct-to-replica warmup calls structurally have no router half
    terminal_tids = {e["trace_id"] for e in events
                     if e.get("ev") == "request_trace"
                     and e.get("source") == "router"}
    assert len(terminal_tids) == verdict["traces"]
    complete = [t for t in terminal_tids
                if tr.waterfall(joined[t])["complete"]]
    assert len(complete) == len(terminal_tids)
    replayed = [t for t in terminal_tids
                if joined[t]["attempts"] > 1]
    assert replayed and all(
        tr.waterfall(joined[t])["complete"] for t in replayed)


# depth tier (tier-1 wall budget): the live fleet smoke spawns 2 jax
# replica subprocesses + a respawn (~2 min); the in-gate fleet surface
# keeps the live in-process failover test above + the committed-record
# pin, and the dry-run fleet_failover family runs a live fleet every
# session
@pytest.mark.slow
def test_fleet_crashloop_smoke_live(tmp_path):
    """tools/fleet_crashloop --smoke end to end: a real subprocess
    fleet, one seeded mid-load SIGKILL, every gate enforced."""
    spec = importlib.util.spec_from_file_location(
        "fleet_crashloop", os.path.join(_REPO, "tools",
                                        "fleet_crashloop.py"))
    fc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fc)
    out = str(tmp_path / "fleet_smoke.jsonl")
    assert fc.main(["--smoke", "--out", out]) == 0
    events = telemetry.load_ledger(out, run="last")
    verdict = [e for e in events if e.get("ev") == "verdict"][-1]
    assert verdict["ok"] is True and verdict["kills"] == 1
