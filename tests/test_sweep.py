"""Ensemble sweep (the DP/vmap axis, SURVEY.md §2.3)."""

import numpy as np
import pytest

from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.parallel.sweep import ensemble_curves
from gossip_tpu.runtime.simulator import simulate_curve
from gossip_tpu.topology import generators as G


# ~9 s (txn-PR rebalance): the ensemble-vs-solo mechanism stays
# pinned in-gate by the nemesis ensemble twins (rumor churn solo
# parity + SWIM observer denominator, tests/test_nemesis.py) and the
# CLI/RPC ensemble smokes; this SI reference re-proves under -m slow
@pytest.mark.slow
def test_ensemble_matches_individual_runs():
    # the vmapped batch must reproduce each seed's solo trajectory exactly
    proto = ProtocolConfig(mode="pushpull", fanout=1)
    topo = G.erdos_renyi(200, 0.05, seed=1)
    run = RunConfig(max_rounds=16)
    seeds = [3, 11, 42]
    ens = ensemble_curves(proto, topo, run, seeds)
    for i, seed in enumerate(seeds):
        solo = simulate_curve(proto, topo,
                              RunConfig(max_rounds=16, seed=seed))
        np.testing.assert_allclose(ens.curves[i], solo.coverage, atol=1e-6)
        np.testing.assert_allclose(ens.msgs[i], solo.msgs)


def test_ensemble_summary_statistics():
    proto = ProtocolConfig(mode="push", fanout=2)
    topo = G.complete(256)
    run = RunConfig(max_rounds=32, target_coverage=0.99)
    ens = ensemble_curves(proto, topo, run, list(range(8)))
    s = ens.summary()
    assert s["seeds"] == 8 and s["converged"] == 8
    assert 3 <= s["rounds_p50"] <= 20
    assert s["rounds_p95"] >= s["rounds_p50"]
    assert ens.converged.all()
    # seeds genuinely differ
    assert len({int(r) for r in ens.rounds_to_target}) >= 1
    assert (np.diff(ens.curves, axis=1) >= -1e-6).all()   # monotone


def test_ensemble_with_faults_some_may_stall():
    proto = ProtocolConfig(mode="push", fanout=1)
    topo = G.ring(64, 2)
    fault = FaultConfig(node_death_rate=0.2, seed=5)
    run = RunConfig(max_rounds=8, target_coverage=1.0)
    ens = ensemble_curves(proto, topo, run, [0, 1], fault)
    # a ring with 20% dead nodes cannot reach full alive-coverage in 8
    # rounds from one origin; -1 entries must be well-formed
    assert set(ens.rounds_to_target) <= {-1} | set(range(1, 9))


# slow tier (tier-1 wall budget): ensemble parity stays gated via
# test_ensemble_matches_individual_runs
@pytest.mark.slow
def test_ensemble_swim_matches_solo_curves_bitwise():
    """Round 4: the SWIM seed ensemble (detection-latency distribution
    for one failure scenario).  Every lane must equal the solo curve
    driver with the same seed bitwise; rounds_to_target is
    rounds-to-detection."""
    from gossip_tpu.config import ProtocolConfig, RunConfig
    from gossip_tpu.parallel.sweep import ensemble_swim_curves
    from gossip_tpu.runtime.simulator import simulate_swim_curve
    proto = ProtocolConfig(mode="swim", fanout=2, swim_proxies=2,
                           swim_subjects=4, swim_suspect_rounds=4)
    n, rounds, dead, fr = 96, 14, (1,), 2
    run = RunConfig(seed=11, max_rounds=rounds, target_coverage=0.9)
    seeds = [11, 12, 13, 14]
    ens = ensemble_swim_curves(proto, n, run, seeds, dead_nodes=dead,
                               fail_round=fr)
    assert ens.curves.shape == (4, rounds)
    for i, s in enumerate(seeds):
        fracs, final = simulate_swim_curve(proto, n, rounds,
                                           dead_nodes=dead, fail_round=fr,
                                           seed=s)
        np.testing.assert_array_equal(ens.curves[i],
                                      np.asarray(fracs, np.float32),
                                      err_msg=f"seed {s}")
        assert float(ens.msgs[i, -1]) == float(final.msgs)
    assert (ens.rounds_to_target > 0).all()     # every seed detected


# slow tier (tier-1 wall budget): seed-axis sharding invariance
# stays gated via test_sweep_axis_sharding_is_value_invariant
@pytest.mark.slow
def test_ensemble_seed_axis_mesh_is_value_invariant():
    """Round 4: the ensembles shard their SEED axis over a 1-D mesh —
    values never change (embarrassingly parallel), for SI, SWIM, and
    rumor ensembles alike; non-dividing seed counts reject loudly."""
    from gossip_tpu.config import ProtocolConfig, RunConfig
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sweep import (ensemble_curves,
                                           ensemble_rumor_curves,
                                           ensemble_swim_curves)
    mesh = make_mesh(4, axis_name="seed")
    seeds = [3, 4, 5, 6, 7, 8, 9, 10]
    run = RunConfig(seed=0, max_rounds=10)
    topo = G.complete(256)
    a = ensemble_curves(ProtocolConfig(mode="pushpull"), topo, run, seeds)
    b = ensemble_curves(ProtocolConfig(mode="pushpull"), topo, run, seeds,
                        mesh=mesh)
    np.testing.assert_array_equal(a.curves, b.curves)
    np.testing.assert_array_equal(a.msgs, b.msgs)
    sp = ProtocolConfig(mode="swim", fanout=2, swim_proxies=2,
                        swim_subjects=4, swim_suspect_rounds=4)
    sa = ensemble_swim_curves(sp, 96, run, seeds, dead_nodes=(1,),
                              fail_round=2)
    sb = ensemble_swim_curves(sp, 96, run, seeds, dead_nodes=(1,),
                              fail_round=2, mesh=mesh)
    np.testing.assert_array_equal(sa.curves, sb.curves)
    np.testing.assert_array_equal(sa.msgs, sb.msgs)
    rp = ProtocolConfig(mode="rumor", fanout=1, rumor_k=2, rumors=2)
    ra = ensemble_rumor_curves(rp, topo, run, seeds)
    rb = ensemble_rumor_curves(rp, topo, run, seeds, mesh=mesh)
    np.testing.assert_array_equal(ra.curves, rb.curves)
    np.testing.assert_array_equal(ra.hot, rb.hot)
    np.testing.assert_array_equal(ra.msgs, rb.msgs)
    with pytest.raises(ValueError, match="do not divide"):
        ensemble_curves(ProtocolConfig(mode="push"), topo, run,
                        seeds[:6], mesh=mesh)
