"""go-native backend semantics + parity with the batched flood kernel.

The north-star parity requirement (BASELINE.json): convergence curves of the
TPU backend match the Go reference at N=1024.  Parity is defined on the
hop-depth clock (SURVEY.md §7 "Event-driven vs. round-synchronous parity"):
flood-kernel coverage after round t == event-sim coverage within t hops ==
the BFS ball of radius t around the origin.
"""

import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import ProtocolConfig, RunConfig
from gossip_tpu.runtime.gonative import (
    GoNativeSim, NetConfig, topology_from_table)
from gossip_tpu.runtime.simulator import simulate_curve
from gossip_tpu.topology import generators as G


def make_sim(topo, **kw):
    return GoNativeSim(topology_from_table(topo), **kw)


def bfs_coverage(topo, origin, rounds):
    """Independent BFS ball sizes from the raw adjacency (numpy, no jax)."""
    nbrs, deg = np.asarray(topo.nbrs), np.asarray(topo.deg)
    dist = np.full(topo.n, -1)
    dist[origin] = 0
    frontier = [origin]
    d = 0
    while frontier:
        d += 1
        nxt = []
        for u in frontier:
            for v in nbrs[u, :deg[u]]:
                if dist[v] < 0:
                    dist[v] = d
                    nxt.append(int(v))
        frontier = nxt
    return np.array([(0 <= dist) & (dist <= h) for h in range(rounds + 1)
                     ]).mean(axis=1)


@pytest.mark.parametrize("topo_fn,rounds", [
    (lambda: G.ring(1024, 4), 300),
    (lambda: G.grid2d(32, 32), 70),
    (lambda: G.erdos_renyi(1024, 0.008, seed=1), 40),
], ids=["ring1024", "grid32x32", "er1024"])
def test_flood_kernel_is_exact_bfs_and_bounds_event_sim(topo_fn, rounds):
    """The parity-clock contract (gonative module doc): flood kernel == BFS
    ball per round; event-sim hop coverage is bounded above by it and both
    converge to the same fixed point (the Maelstrom invariant)."""
    topo = topo_fn()
    res = simulate_curve(ProtocolConfig(mode=C.FLOOD), topo,
                         RunConfig(max_rounds=rounds, target_coverage=1.0))
    kernel_cov = np.asarray(res.coverage)
    bfs = bfs_coverage(topo, 0, rounds)
    np.testing.assert_allclose(kernel_cov, bfs[1:], atol=1e-6)

    sim = make_sim(topo)
    sim.broadcast(origin=0, message=42)
    sim.run()
    hop_cov = np.array(sim.coverage_by_hop(42, rounds))
    assert (hop_cov[1:] <= kernel_cov + 1e-9).all()
    # Same fixed point: both backends cover exactly the origin's reachable
    # component (races inflate the event sim's hop counts, never its eventual
    # coverage) — the Maelstrom checker's set invariant, SURVEY.md §4.
    kernel_set = set(np.nonzero(np.asarray(res.state.seen)[:, 0])[0])
    sim_set = {i for i in range(topo.n) if 42 in sim.nodes[i].seen}
    assert kernel_set == sim_set
    assert len(sim_set) >= 0.99 * topo.n


def test_exact_hop_parity_on_race_free_graph():
    """On a k=2 ring every relayer has exactly one non-sender neighbor, so
    no relay race exists and hop-of-arrival == BFS distance == kernel round,
    exactly (the equality case of the parity contract)."""
    topo = G.ring(256, 2)
    rounds = 130
    res = simulate_curve(ProtocolConfig(mode=C.FLOOD), topo,
                         RunConfig(max_rounds=rounds, target_coverage=1.0))
    sim = make_sim(topo)
    sim.broadcast(origin=0, message=1)
    sim.run()
    hop_cov = sim.coverage_by_hop(1, rounds)
    kernel_cov = np.asarray(res.coverage)
    for t in range(1, rounds + 1):
        assert kernel_cov[t - 1] == pytest.approx(hop_cov[t]), f"round {t}"


def test_all_messages_reach_all_nodes():
    topo = G.erdos_renyi(256, 0.03, seed=7)
    sim = make_sim(topo)
    for i, m in enumerate([5, 9, 13]):
        sim.broadcast(origin=i * 10, message=m, t=0.01 * i)
    sim.run()
    for nid in range(topo.n):
        assert sorted(sim.read(nid)) == [5, 9, 13]


def test_dedup_and_sender_exclusion_two_nodes():
    # A -- B only.  One injection at A: A->B is the only relay; B excludes
    # its sender so it never echoes back (main.go:73-75); duplicate client
    # injection is absorbed by the dedup set (main.go:113).
    sim = GoNativeSim({0: [1], 1: [0]})
    sim.broadcast(0, 99)
    sim.run()
    first = sim.msgs_sent
    # client inject + ack (2) + A->B relay + ack (2) = 4; no echo
    assert first == 4
    assert sim.read(0) == [99] and sim.read(1) == [99]
    sim.broadcast(0, 99, t=1.0)   # duplicate: ack only, no re-relay
    sim.run()
    assert sim.msgs_sent == first + 2
    assert sim.read(0) == [99]


def test_read_preserves_arrival_order():
    sim = GoNativeSim({0: [1], 1: [0]})
    sim.broadcast(0, 7, t=0.0)
    sim.broadcast(0, 3, t=0.5)
    sim.broadcast(0, 11, t=1.0)
    sim.run()
    assert sim.read(0) == [7, 3, 11]
    assert sim.read(1) == [7, 3, 11]


def test_transient_partition_heals_via_retry():
    # line 0-1-2; cut (1,2) for 3 s.  Faithful mode: node 1's retries keep
    # resending (the send precedes the ctx check), so node 2 gets the message
    # after the heal — at-least-once delivery (main.go:80-87).
    sim = GoNativeSim({0: [1], 1: [0, 2], 2: [1]}, horizon=30.0)
    sim.partition(1, 2, 0.0, 3.0)
    sim.broadcast(0, 1)
    sim.run()
    assert sim.read(2) == [1]
    t2 = [t for (t, nid, m, _) in sim.deliveries if nid == 2][0]
    assert t2 >= 3.0   # only after the heal


def test_liveness_hole_blocks_later_neighbors():
    # Defect §2.2.7: node 1 fans out to [0, 2, 3] sequentially (0 is the
    # sender -> excluded; order is [2, 3]).  With (1,2) cut forever, the
    # faithful node spins on neighbor 2 and NEVER contacts neighbor 3.
    topo = {0: [1], 1: [0, 2, 3], 2: [1], 3: [1]}
    sim = GoNativeSim(topo, horizon=30.0)
    sim.partition(1, 2, 0.0, 1e9)
    sim.broadcast(0, 1)
    sim.run()
    assert sim.read(2) == []
    assert sim.read(3) == []   # starved by the stuck retry loop
    # The fixed node (fresh ctx per attempt) still can't reach 2, but moves
    # on?  No — the reference loop only advances on success; the *fix* is the
    # fresh context, which lets a healed link succeed.  With a permanent cut
    # neither variant reaches 3 via node 1; redundancy must come from the
    # graph.  A cycle provides it:
    ring = GoNativeSim({0: [1, 3], 1: [0, 2], 2: [1, 3], 3: [2, 0]},
                       horizon=30.0)
    ring.partition(1, 2, 0.0, 1e9)
    ring.broadcast(0, 1)
    ring.run()
    assert ring.read(2) == [1]   # arrived the other way around


def test_fixed_ctx_resumes_fanout_after_heal():
    # Fixed mode: after the (1,2) link heals, the retry succeeds with its
    # fresh context and the fan-out PROCEEDS to neighbor 3.
    topo = {0: [1], 1: [0, 2, 3], 2: [1], 3: [1]}
    sim = GoNativeSim(topo, net=NetConfig(faithful_ctx_bug=False),
                      horizon=60.0)
    sim.partition(1, 2, 0.0, 5.0)
    sim.broadcast(0, 1)
    sim.run()
    assert sim.read(2) == [1]
    assert sim.read(3) == [1]
    # faithful mode starves node 3 under the same transient cut
    sim2 = GoNativeSim(topo, horizon=60.0)
    sim2.partition(1, 2, 0.0, 5.0)
    sim2.broadcast(0, 1)
    sim2.run()
    assert sim2.read(2) == [1]   # resends still deliver after heal
    assert sim2.read(3) == []    # but the loop never exits -> 3 starved
