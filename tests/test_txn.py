"""LWW-register transaction subsystem (ops/registers, models/register,
parallel/sharded_register, runtime/txn_checker): config validation,
the LWW join algebra + owner-order tie-break, acked-writes ground
truth, the partition-stall/exact-heal acceptance, 1-vs-4-device
bitwise parity under the full mixed fault program, the txn_conv
round-metrics column, CLI + RPC fall-through + Maelstrom
txn-rw-register workload surfaces, the weak-isolation checker (which
MUST flag planted G0/G1a anomalies), the committed artifact verdict
pin, and the ``*txn*``/``*register*`` provenance rule."""

import json
import os

import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import (ChurnConfig, FaultConfig,
                               ProtocolConfig, RunConfig, TxnConfig)
from gossip_tpu.topology import generators as G

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROTO = ProtocolConfig(mode=C.PULL, fanout=2)
# the full mixed fault program every parity/heal surface runs:
# crash/recover, permanent crash, open partition window, drop ramp
_CFAULT = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
    events=((3, 2, 5), (7, 1, -1)), partitions=((0, 6, 16),),
    ramp=(1, 4, 0.0, 0.3)))


# -- config validation -------------------------------------------------

def test_txn_config_validation():
    TxnConfig(keys=2, writes=((0, 0, 0, 5), (1, 0, 2, 7),
                              (2, 1, 0, 1)))
    with pytest.raises(ValueError, match="keys must be"):
        TxnConfig(keys=0)
    with pytest.raises(ValueError, match="values must be >= 1"):
        TxnConfig(writes=((0, 0, 0, 0),))
    with pytest.raises(ValueError, match="outside"):
        TxnConfig(keys=2, writes=((0, 5, 0, 1),))
    with pytest.raises(ValueError, match="horizon cap"):
        TxnConfig(writes=((0, 0, 10 ** 9, 1),))
    # the unique-timestamp contract: two writes sharing one
    # (key, round, node) would fork the LWW winner — a loud error
    with pytest.raises(ValueError, match="duplicate"):
        TxnConfig(writes=((0, 0, 1, 5), (0, 0, 1, 6)))
    with pytest.raises(ValueError, match="zipf_alpha"):
        TxnConfig(zipf_alpha=0.0)
    with pytest.raises(ValueError, match="hot_key"):
        TxnConfig(hot_key=1.5)
    with pytest.raises(ValueError, match="unknown load"):
        TxnConfig(load="lunar")
    # horizon: last scripted round + 1; the default spans spread_rounds
    assert TxnConfig(writes=((0, 0, 7, 1),)).horizon() == 8
    assert TxnConfig(spread_rounds=6).horizon() == 6


def test_skewed_default_program_is_closed_form_and_skewed():
    """The default traffic generator is a pure function of the config
    scalars (same config -> identical program), zipf-skews key
    popularity, honors the hot-key storm window, and spreads diurnal
    load toward the window's middle."""
    from gossip_tpu.ops import registers as RG
    n = 64
    cfg = TxnConfig(keys=8, txns=200, zipf_alpha=1.5)
    ws = RG.txn_writes(cfg, n)
    assert ws == RG.txn_writes(cfg, n)           # deterministic
    counts = [0] * 8
    for _, k, _, _ in ws:
        counts[k] += 1
    assert counts[0] > counts[4]                 # zipf head > tail
    # hot-key storm: the middle third concentrates onto key 0
    hot = TxnConfig(keys=8, txns=200, zipf_alpha=1.5, hot_key=1.0)
    hws = RG.txn_writes(hot, n)
    mid = [k for i, (_, k, _, _) in enumerate(hws)
           if 200 // 3 <= i < 400 // 3]
    assert mid and all(k == 0 for k in mid)
    # diurnal load: density peaks mid-window vs the uniform spread
    di = TxnConfig(keys=8, txns=200, load="diurnal", spread_rounds=10)
    rounds = [r for _, _, r, _ in RG.txn_writes(di, n)]
    mid_mass = sum(1 for r in rounds if 3 <= r <= 6)
    edge_mass = sum(1 for r in rounds if r <= 1 or r >= 8)
    assert mid_mass > edge_mass
    # every program obeys the unique-timestamp contract at lowering
    RG.inject_args(di, n)
    # collision-free BY CONSTRUCTION even where the old writer formula
    # collided (review finding: tiny n, many writes per (key, round)
    # bucket) — and the pigeonhole impossibility errors loudly naming
    # the knobs instead of a "script distinct writers" message for a
    # program the user never scripted
    RG.inject_args(TxnConfig(keys=2, txns=32, hot_key=1.0), 4)
    with pytest.raises(ValueError, match="lower --txns"):
        RG.txn_writes(TxnConfig(keys=1, txns=32, spread_rounds=1), 4)


# -- the LWW join algebra (the acceptance pins) ------------------------

def _rand_states(rng, shape, keys):
    """Random register rows: arbitrary value/ts planes (the algebra
    must hold on ALL states, not just reachable ones)."""
    vals = rng.integers(0, 50, size=shape).astype(np.int32)
    ts = rng.integers(0, 40, size=shape).astype(np.int32)
    return np.concatenate([vals, ts], axis=-1)


def test_lww_merge_algebra_bitwise():
    """Commutativity, associativity, idempotence, upper bound — the
    lattice-join laws, BITWISE on random states (including equal-ts
    ties, which the max(value) rule keeps total)."""
    from gossip_tpu.ops.registers import merge_lww
    rng = np.random.default_rng(7)
    for _ in range(10):
        a = _rand_states(rng, (6, 4), 4)
        b = _rand_states(rng, (6, 4), 4)
        c = _rand_states(rng, (6, 4), 4)
        ab = np.asarray(merge_lww(a, b))
        ba = np.asarray(merge_lww(b, a))
        assert (ab == ba).all()                      # commutative
        abc1 = np.asarray(merge_lww(merge_lww(a, b), c))
        abc2 = np.asarray(merge_lww(a, merge_lww(b, c)))
        assert (abc1 == abc2).all()                  # associative
        aa = np.asarray(merge_lww(a, a))
        assert (aa == a).all()                       # idempotent
        assert (ab[..., 4:] >= a[..., 4:]).all()     # ts upper bound
        again = np.asarray(merge_lww(ab, a))
        assert (again == ab).all()                   # absorbs operands


def test_tie_break_at_equal_round_is_owner_order():
    """Two writes to one key at the SAME round: the higher owner id
    wins — deterministic by the packed (round, owner) timestamp, on
    the ground truth AND on a full simulated trajectory."""
    from gossip_tpu.models.register import simulate_curve_txn
    from gossip_tpu.ops import registers as RG
    n = 16
    cfg = TxnConfig(keys=2, writes=((3, 0, 1, 9), (5, 0, 1, 7),
                                    (1, 1, 0, 4)))
    inj = RG.inject_args(cfg, n)
    truth = np.asarray(RG.ground_truth(cfg, inj, None, n, 0))
    assert truth[0] == 7                     # owner 5 > owner 3
    assert RG.truth_summary(cfg, truth, n)["ts_owner"][0] == 5
    # and the trajectory converges to that winner everywhere
    run = RunConfig(seed=0, max_rounds=12, target_coverage=1.0)
    conv, _, final, ts = simulate_curve_txn(cfg, _PROTO, G.complete(n),
                                            run)
    assert conv[-1] == 1.0
    assert ts["values"][0] == 7 and ts["ts_owner"][0] == 5
    # a LATER round beats any same-round owner: round order dominates
    cfg2 = TxnConfig(keys=2, writes=((15, 0, 1, 9), (0, 0, 2, 7)))
    t2 = np.asarray(RG.ground_truth(cfg2, RG.inject_args(cfg2, n),
                                    None, n, 0))
    assert t2[0] == 7


def test_ground_truth_acked_write_semantics():
    """A write is applied iff its owner is alive at the write round
    AND eventually alive (the acked-writes rule); the LWW winner is
    picked among APPLIED writes only, and the packed-ts overflow is a
    loud error."""
    from gossip_tpu.ops import registers as RG
    n = 8
    cfg = TxnConfig(keys=2, writes=((0, 0, 0, 10),   # healthy
                                    (7, 0, 3, 20),   # dies forever at 1
                                    (1, 0, 2, 30),   # down [1, 4)
                                    (2, 1, 1, 40)))  # healthy
    f = FaultConfig(churn=ChurnConfig(events=((7, 1, -1), (1, 1, 4))))
    inj = RG.inject_args(cfg, n)
    truth = np.asarray(RG.ground_truth(cfg, inj, f, n, 0))
    # 20 (dead owner) and 30 (down at round 2) never apply: 10 wins
    assert truth[0] == 10 and truth[1] == 40
    # fault-free, the round-3 write wins key 0
    truth0 = np.asarray(RG.ground_truth(cfg, inj, None, n, 0))
    assert truth0[0] == 20
    with pytest.raises(ValueError, match="node ids"):
        RG.inject_args(TxnConfig(writes=((99, 0, 0, 1),)), n)
    with pytest.raises(ValueError, match="overflows int32"):
        RG.check_ts_packable(TxnConfig(writes=((0, 0, 90_000, 1),)),
                             50_000)


# -- partition-heal convergence (the acceptance gate) ------------------

def test_partition_stall_and_exact_heal():
    """While the window is open, txn convergence provably stalls (no
    node holds the global LWW winners) and after heal every
    eventual-alive node reaches the exact integer ground truth —
    value AND timestamp planes — under the full mixed fault
    program."""
    from gossip_tpu.models.register import simulate_curve_txn
    from gossip_tpu.ops import registers as RG
    cfg = TxnConfig(keys=8, txns=24, zipf_alpha=1.2, hot_key=0.3)
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    n = 32
    conv, _, final, truth = simulate_curve_txn(cfg, _PROTO,
                                               G.complete(n), run,
                                               _CFAULT)
    # stalled while the committed window [0, 6) is open
    assert all(c < 1.0 for c in conv[:6]), list(conv)
    assert conv[-1] == 1.0, list(conv)
    # integer-exact: every eventual-alive node holds the truth row
    inj = RG.inject_args(cfg, n)
    truth_row = np.asarray(RG.ground_truth(cfg, inj, _CFAULT, n, 0))
    eventual = np.asarray(RG.eventual_alive_crdt(_CFAULT, n, 0))
    vals = np.asarray(final.val)
    assert (vals[eventual] == truth_row[None, :]).all()
    # the permanently-dead writer's writes won nothing
    assert 7 not in truth["ts_owner"]


# -- mesh parity: schedules + write programs as operands ---------------

def _mesh(k=4):
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(k)


def test_txn_mesh_parity_bitwise_full_fault_program():
    """1-device vs 4-device register trajectories BITWISE identical
    under the full mixed fault program (event + permanent crash + open
    partition window + ramp) — the acceptance criterion, plus exact
    convergence on the eventual-alive set."""
    from gossip_tpu.models.register import simulate_curve_txn
    from gossip_tpu.parallel.sharded_register import (
        simulate_curve_txn_sharded)
    run = RunConfig(seed=0, max_rounds=16, target_coverage=1.0)
    topo = G.complete(32)
    cfg = TxnConfig(keys=8, txns=16, zipf_alpha=1.2, hot_key=0.3)
    c1, m1, f1, t1 = simulate_curve_txn(cfg, _PROTO, topo, run, _CFAULT)
    c4, m4, f4, t4 = simulate_curve_txn_sharded(cfg, _PROTO, topo, run,
                                                _mesh(), _CFAULT)
    assert (np.asarray(c1) == np.asarray(c4)).all()
    assert (np.asarray(f1.val) == np.asarray(f4.val)[:32]).all()
    assert float(f1.msgs) == float(f4.msgs)
    assert t1 == t4
    assert c4[-1] == 1.0


def test_until_driver_integer_target():
    """The while_loop driver's cond is an exact integer converged-count
    compare; single and sharded agree on rounds and the final value."""
    from gossip_tpu.models.register import simulate_until_txn
    from gossip_tpu.parallel.sharded_register import (
        simulate_until_txn_sharded)
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    topo = G.complete(32)
    cfg = TxnConfig(keys=8, txns=16)
    r1, c1, m1, f1, t1 = simulate_until_txn(cfg, _PROTO, topo, run,
                                            _CFAULT)
    r4, c4, m4, f4, t4 = simulate_until_txn_sharded(
        cfg, _PROTO, topo, run, _mesh(), _CFAULT)
    assert (r1, c1, t1) == (r4, c4, t4)
    assert c1 == 1.0 and r1 < 24


def test_txn_rejections_are_loud():
    from gossip_tpu.models.register import (make_register_round,
                                            simulate_until_txn)
    with pytest.raises(ValueError, match="pull exchange only"):
        make_register_round(TxnConfig(), ProtocolConfig(mode=C.PUSH),
                            G.complete(8))
    # a write the loop can never fire makes ground truth unreachable
    # by construction — a loud error (models/crdt rule)
    with pytest.raises(ValueError, match="can never fire"):
        simulate_until_txn(
            TxnConfig(writes=((0, 0, 100, 1),)), _PROTO, G.complete(8),
            RunConfig(seed=0, max_rounds=8))


# -- the txn_conv round-metrics column ---------------------------------

def test_txn_conv_round_metrics_emitted_and_bitwise_free(tmp_path):
    """With an active run ledger the sharded register drivers flush a
    round_metrics stack carrying the txn_conv column (+ the nemesis
    columns under churn); recording must not move the trajectory
    bitwise (the ops/round_metrics zero-impact contract)."""
    from gossip_tpu.parallel.sharded_register import (
        simulate_curve_txn_sharded)
    from gossip_tpu.utils import telemetry
    run = RunConfig(seed=0, max_rounds=12, target_coverage=1.0)
    topo = G.complete(32)
    cfg = TxnConfig(keys=8, txns=16)
    # metrics-off reference
    c0, _, f0, _ = simulate_curve_txn_sharded(cfg, _PROTO, topo, run,
                                              _mesh(), _CFAULT)
    path = str(tmp_path / "txn_metrics.jsonl")
    led = telemetry.Ledger(path)
    prev = telemetry.activate(led)
    try:
        c1, _, f1, _ = simulate_curve_txn_sharded(
            cfg, _PROTO, topo, run, _mesh(), _CFAULT)
    finally:
        telemetry.activate(prev)
        led.close()
    assert (np.asarray(c0) == np.asarray(c1)).all()
    assert (np.asarray(f0.val) == np.asarray(f1.val)).all()
    evs = telemetry.load_ledger(path)
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert rms
    e = rms[-1]
    assert e["driver"] == "simulate_curve_txn_sharded"
    assert len(e["txn_conv"]) == e["rounds"] == 12
    assert e["totals"]["txn_conv_final"] == pytest.approx(
        float(c1[-1]), abs=1e-4)
    # nemesis columns ride the same stack under the fault program
    assert e["totals"]["dropped"] > 0
    assert any(p > 0 for p in e["cut_pairs"])


# -- the weak-isolation checker (it MUST flag planted anomalies) -------

def _committed(tid, writes=(), reads=()):
    return {"id": tid, "status": "committed",
            "reads": list(reads),
            "writes": [{"key": k, "value": v, "ts": list(ts)}
                       for k, v, ts in writes]}


def test_checker_flags_planted_g0_dirty_write():
    """A synthetic ww cycle — T1's write precedes T2's on key x while
    T2's precedes T1's on key y — MUST be classified G0 (a checker
    that cannot fail is not a checker); the same trace with consistent
    per-txn timestamps is clean."""
    from gossip_tpu.runtime.txn_checker import check_txn_trace
    planted = [
        _committed(1, writes=[("x", 10, (1, 0)), ("y", 11, (4, 0))]),
        _committed(2, writes=[("x", 20, (2, 1)), ("y", 21, (3, 1))]),
    ]
    out = check_txn_trace(planted)
    assert out["g0"] and not out["ok"]
    assert set(out["g0"][0]["cycle"]) >= {1, 2}
    assert set(out["g0"][0]["keys"]) == {"x", "y"}
    # one timestamp per txn (the server's commit discipline): clean
    clean = [
        _committed(1, writes=[("x", 10, (1, 0)), ("y", 11, (1, 0))]),
        _committed(2, writes=[("x", 20, (2, 1)), ("y", 21, (2, 1))]),
    ]
    out2 = check_txn_trace(clean)
    assert not out2["g0"] and out2["ok"]


def test_checker_flags_planted_g1a_aborted_read():
    """A committed read of a value written by an ABORTED transaction
    MUST be classified G1a; an indeterminate writer's value is
    admissible (the Maelstrom info-timeout convention)."""
    from gossip_tpu.runtime.txn_checker import check_txn_trace
    planted = [
        {"id": 1, "status": "aborted", "reads": [],
         "writes": [{"key": "x", "value": 99, "ts": [1, 0]}]},
        _committed(2, reads=[["x", 99]]),
    ]
    out = check_txn_trace(planted)
    assert out["g1a"] == [{"reader": 2, "key": "x", "value": 99,
                           "writer": 1}]
    assert not out["ok"]
    # the LIVE trace shape: an aborted txn's writes carry NO server
    # timestamp (the error reply has none) — G1a attribution must
    # still fire on them (review finding: stripping ts-less aborted
    # writes made live G1a detection vacuous)
    live = [
        {"id": 1, "status": "aborted", "reads": [],
         "writes": [{"key": "x", "value": 99, "ts": None}]},
        _committed(2, reads=[["x", 99]]),
    ]
    out_live = check_txn_trace(live)
    assert out_live["g1a"] and not out_live["ok"]
    # the same read of an INDETERMINATE writer is legitimate
    indet = [
        {"id": 1, "status": "indeterminate", "reads": [],
         "writes": [{"key": "x", "value": 99, "ts": [1, 0]}]},
        _committed(2, reads=[["x", 99]]),
    ]
    assert check_txn_trace(indet)["ok"]


def test_checker_defects_and_convergence_cross_check():
    """Trace-integrity defects (duplicate write values, same-key ts
    collisions) and the final-state LWW cross-check fail the verdict
    — a broken harness can never masquerade as a clean isolation
    run."""
    from gossip_tpu.runtime.txn_checker import check_txn_trace
    dup = [_committed(1, writes=[("x", 5, (1, 0))]),
           _committed(2, writes=[("y", 5, (2, 1))])]
    assert not check_txn_trace(dup)["ok"]
    coll = [_committed(1, writes=[("x", 5, (1, 0))]),
            _committed(2, writes=[("x", 6, (1, 0))])]
    out = check_txn_trace(coll)
    assert out["defects"] and not out["ok"]
    # convergence: final reads must agree AND match the max-ts winner
    txns = [_committed(1, writes=[("x", 5, (1, 0))]),
            _committed(2, writes=[("x", 7, (2, 1))])]
    good = {"n0": {"x": 7}, "n1": {"x": 7}}
    assert check_txn_trace(txns, final_reads=good)["ok"]
    stale = {"n0": {"x": 5}, "n1": {"x": 5}}
    out2 = check_txn_trace(txns, final_reads=stale)
    assert out2["converged"] is False and not out2["ok"]
    split = {"n0": {"x": 7}, "n1": {"x": 5}}
    assert check_txn_trace(txns, final_reads=split)["converged"] \
        is False
    # a timed-out txn's write MAY have applied and won (the Maelstrom
    # info-timeout convention): an agreed final state holding it is
    # converged, not a false alarm
    with_indet = txns + [{"id": 3, "status": "indeterminate",
                          "reads": [],
                          "writes": [{"key": "x", "value": 9,
                                      "ts": None}]}]
    won = {"n0": {"x": 9}, "n1": {"x": 9}}
    assert check_txn_trace(with_indet, final_reads=won)["converged"] \
        is True
    # an ABORTED write leaking into the final state fails the verdict
    # even on a key no committed txn ever wrote (review finding: `best`
    # never covers such a key, so the leak needs its own scan)
    leak = [_committed(1, writes=[("x", 5, (1, 0))]),
            {"id": 2, "status": "aborted", "reads": [],
             "writes": [{"key": "y", "value": 99, "ts": None}]}]
    leaked = {"n0": {"x": 5, "y": 99}, "n1": {"x": 5, "y": 99}}
    out3 = check_txn_trace(leak, final_reads=leaked)
    assert out3["converged"] is False and not out3["ok"]
    clean_final = {"n0": {"x": 5, "y": None}, "n1": {"x": 5, "y": None}}
    assert check_txn_trace(leak, final_reads=clean_final)["ok"]


def test_checker_flags_planted_g1b_intermediate_read():
    """A committed FOREIGN read of a value its writer itself
    overwrote on the same key MUST be classified G1b (intermediate
    read); reading the writer's FINAL value of the key is legitimate,
    and a txn re-reading its own intermediate write is not G1b (the
    read-your-writes path, not an isolation leak)."""
    from gossip_tpu.runtime.txn_checker import check_txn_trace
    planted = [
        _committed(1, writes=[("x", 10, (1, 0)), ("x", 12, (2, 0))]),
        _committed(2, reads=[["x", 10]]),
    ]
    out = check_txn_trace(planted)
    assert out["g1b"] == [{"reader": 2, "writer": 1, "key": "x",
                           "value": 10, "final": 12}]
    assert not out["ok"]
    # negative twin: the reader saw the writer's FINAL value — clean
    final_read = [
        _committed(1, writes=[("x", 10, (1, 0)), ("x", 12, (2, 0))]),
        _committed(2, reads=[["x", 12]]),
    ]
    out2 = check_txn_trace(final_read)
    assert not out2["g1b"] and out2["ok"]
    # negative twin: SELF-read of an intermediate value — clean
    self_read = [
        _committed(1, writes=[("x", 10, (1, 0)), ("x", 12, (2, 0))],
                   reads=[["x", 10]]),
    ]
    out3 = check_txn_trace(self_read)
    assert not out3["g1b"] and out3["ok"]


def test_checker_flags_planted_g1c_circular_information_flow():
    """A ww u wr cycle closed by a wr edge MUST be classified G1c
    (circular information flow): T2's y-write precedes T1's (ww
    T2 -> T1) while T2 reads T1's x-write (wr T1 -> T2) — no ww-only
    cycle, so G0 stays empty and the wr edge is what closes the
    loop.  Shifting T2's y-write after T1's breaks the cycle."""
    from gossip_tpu.runtime.txn_checker import check_txn_trace
    planted = [
        _committed(1, writes=[("x", 10, (1, 0)), ("y", 11, (2, 0))]),
        _committed(2, writes=[("y", 21, (1, 1))], reads=[["x", 10]]),
    ]
    out = check_txn_trace(planted)
    assert not out["g0"]
    assert out["g1c"] and not out["ok"]
    cyc = out["g1c"][0]
    assert cyc["cycle"][0] == cyc["cycle"][-1]
    assert set(cyc["cycle"]) == {1, 2}
    assert cyc["wr_edge"] == [1, 2, "x"]
    # negative twin: same reads, T2's y-write AFTER T1's — both edges
    # now point T1 -> T2, no cycle, clean
    ordered = [
        _committed(1, writes=[("x", 10, (1, 0)), ("y", 11, (2, 0))]),
        _committed(2, writes=[("y", 21, (3, 1))], reads=[["x", 10]]),
    ]
    out2 = check_txn_trace(ordered)
    assert not out2["g1c"] and out2["ok"]


def test_checker_reports_lost_update_without_failing_verdict():
    """Two committed txns that both read the same (key, pre-value)
    snapshot and both wrote the key MUST be reported as a lost update
    — but the verdict stays ok: LWW read-committed registers lose
    concurrent updates BY DESIGN (a live partitioned run can
    legitimately produce one), so the checker reports the anomaly for
    the harness counts without branding the trace a violation."""
    from gossip_tpu.runtime.txn_checker import check_txn_trace
    planted = [
        _committed(1, writes=[("x", 5, (1, 0))], reads=[["x", None]]),
        _committed(2, writes=[("x", 7, (2, 1))], reads=[["x", None]]),
    ]
    out = check_txn_trace(planted)
    assert out["lost_update"] == [{"key": "x", "pre": None,
                                   "txns": [1, 2]}]
    assert out["ok"]  # reported, NOT folded into the verdict
    # non-None pre-value: two RMWs atop the same committed version
    stacked = [
        _committed(1, writes=[("x", 5, (1, 0))]),
        _committed(2, writes=[("x", 6, (2, 1))], reads=[["x", 5]]),
        _committed(3, writes=[("x", 7, (3, 2))], reads=[["x", 5]]),
    ]
    out2 = check_txn_trace(stacked)
    assert out2["lost_update"] == [{"key": "x", "pre": 5,
                                    "txns": [2, 3]}]
    assert out2["ok"]
    # negative twin: SERIALIZED read-modify-writes — each read sees
    # the prior write, no shared snapshot, nothing lost
    serial = [
        _committed(1, writes=[("x", 5, (1, 0))], reads=[["x", None]]),
        _committed(2, writes=[("x", 7, (2, 1))], reads=[["x", 5]]),
    ]
    out3 = check_txn_trace(serial)
    assert not out3["lost_update"] and out3["ok"]


# -- CLI ---------------------------------------------------------------

def test_cli_txn_run_and_error_paths(capsys, monkeypatch):
    from gossip_tpu import cli

    # in-process cli.main: --no-compile-cache writes
    # GOSSIP_COMPILE_CACHE="" into THIS process's env — monkeypatch
    # re-pins the session cache dir for the tests that follow
    monkeypatch.setenv("GOSSIP_COMPILE_CACHE",
                       os.environ.get("GOSSIP_COMPILE_CACHE", ""))
    rc = cli.main(["txn", "--n", "32", "--max-rounds", "24",
                   "--partition", "0:4:16", "--churn-event", "3:2:5",
                   "--drop-ramp", "1:3:0.0:0.2", "--zipf-alpha", "1.3",
                   "--hot-key", "0.4", "--no-compile-cache"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["mode"] == "txn"
    assert out["converged"] is True and out["txn_conv"] == 1.0
    assert out["truth"]["written_keys"] > 0
    assert out["fault_program"] is True
    assert out["zipf_alpha"] == 1.3 and out["hot_key"] == 0.4
    # scripted writes + curve: the owner tie-break is visible in truth
    rc = cli.main(["txn", "--n", "16", "--keys", "2",
                   "--write", "3:0:1:9", "--write", "5:0:1:7",
                   "--write", "1:1:0:4", "--curve",
                   "--max-rounds", "12", "--no-compile-cache"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["truth"]["values"] == [7, 4]
    assert out["truth"]["ts_owner"] == [5, 1]
    assert out["curve"][-1] == 1.0
    # validation surfaces as a clean CLI error, never a traceback
    rc = cli.main(["txn", "--write", "0:0:0:0", "--no-compile-cache"])
    assert rc == 2
    assert "values must be >= 1" in capsys.readouterr().err


# -- RPC: the admission-batcher fall-through contract ------------------

def test_txn_request_falls_through_batcher_labeled():
    """A txn-workload Run request is NOT a megabatch lane shape: it
    must fall through the admission batcher to the solo path with a
    NAMED ``meta.batch.reason`` (the PR 9 fall-through contract — a
    labeled solo reply, never INTERNAL), and the solo path must
    actually run it."""
    from gossip_tpu.backend import request_to_args, run_simulation
    from gossip_tpu.rpc.batcher import classify_run
    base = {"backend": "jax-tpu",
            "proto": {"mode": "pull", "fanout": 2},
            "topology": {"family": "complete", "n": 32},
            "run": {"max_rounds": 16, "target_coverage": 1.0},
            "txn": {"keys": 4, "txns": 8}}
    args = request_to_args(dict(base))
    key, reason, _ = classify_run(args)
    assert key is None and "txn workload" in reason
    # the solo path the fallthrough lands on runs the workload
    rep = run_simulation(**args).to_dict()
    assert rep["mode"] == "txn" and rep["coverage"] == 1.0
    assert rep["meta"]["truth"]["written_keys"] > 0
    # without the txn field the same request batches normally
    plain = {k: v for k, v in base.items() if k != "txn"}
    key2, _, _ = classify_run(request_to_args(plain))
    assert key2 is not None
    # at most one payload workload per request — a loud error
    both = dict(base)
    both["log"] = {"keys": 2, "capacity": 8}
    with pytest.raises(ValueError, match="at most one payload"):
        run_simulation(**request_to_args(both))


def test_sidecar_txn_request_solo_reply_labeled():
    """Live batching sidecar: the txn request's reply carries the loud
    ``batched: false`` label + reason (and the Ensemble RPC rejects
    txn requests with INVALID_ARGUMENT, not INTERNAL)."""
    grpc = pytest.importorskip("grpc")
    from gossip_tpu.config import ServingConfig
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    server, port = serve(port=0, max_workers=4,
                         batching=ServingConfig(tick_ms=50,
                                                max_batch=8))
    try:
        c = SidecarClient(f"127.0.0.1:{port}")
        out = c.run(backend="jax-tpu",
                    proto={"mode": "pull", "fanout": 2},
                    topology={"family": "complete", "n": 32},
                    run={"max_rounds": 16, "target_coverage": 1.0},
                    txn={"keys": 4, "txns": 8})
        assert out["coverage"] == 1.0
        assert out["meta"]["batch"]["batched"] is False
        assert "txn workload" in out["meta"]["batch"]["reason"]
        with pytest.raises(grpc.RpcError) as ei:
            c.ensemble(backend="jax-tpu",
                       proto={"mode": "pull", "fanout": 2},
                       topology={"family": "complete", "n": 32},
                       txn={"keys": 4}, ensemble=2)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        c.close()
    finally:
        server.gossip_batcher.close()
        server.stop(0)


# -- Maelstrom txn-rw-register workload --------------------------------

# ~5 s: the in-gate acceptance surface is the maelstrom-check CLI run
# below (the SAME run_txn_workload through the same partition;
# invariant_ok already ANDs the g0/g1a/convergence flags); this
# direct-API depth — per-flag granularity, abort accounting — runs
# under -m slow
@pytest.mark.slow
def test_txn_workload_through_partition_direct_api():
    """run_txn_workload: no G0/G1a anomalies, no trace defects, and
    cross-node LWW convergence — through a harness-injected
    mid-cluster partition (total availability, checked)."""
    import asyncio

    from gossip_tpu.runtime.maelstrom_harness import run_txn_workload
    stats = asyncio.run(run_txn_workload(
        4, ops=12, rate=25.0, latency=0.001, partition_mid=True,
        seed=0))
    assert stats["invariant_ok"] is True
    assert stats["partitioned"] is True
    assert stats["g0_ok"] is True and stats["g1a_ok"] is True
    assert stats["converged"] is True
    assert stats["anomalies"] == {"g0": 0, "g1a": 0, "g1b": 0,
                                  "g1c": 0, "lost_update": 0,
                                  "defects": 0}
    assert stats["committed"] > 0
    # txns + final read-alls are client ops via the shared accounting
    assert stats["ops"] > 12 and stats["broadcast_ops"] == 0


def test_cli_maelstrom_check_txn_in_gate(capsys):
    """The acceptance surface: ``maelstrom-check --workload txn``
    passes through a mid-run partition — no G0, no G1a, LWW
    convergence across nodes."""
    from gossip_tpu import cli
    rc = cli.main(["maelstrom-check", "--workload", "txn", "--n", "4",
                   "--ops", "12", "--rate", "25", "--latency", "0.001",
                   "--partition"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["workload"] == "txn"
    assert out["invariant_ok"] is True and out["partitioned"] is True
    assert out["g0_ok"] is True and out["g1a_ok"] is True
    assert out["converged"] is True
    assert out["anomalies"] == {"g0": 0, "g1a": 0, "g1b": 0, "g1c": 0,
                                "lost_update": 0, "defects": 0}
    assert out["committed"] > 0
    # the native router speaks the broadcast envelope set only
    rc = cli.main(["maelstrom-check", "--workload", "txn",
                   "--router", "native"])
    assert rc == 2
    assert "python router" in capsys.readouterr().err


def test_txn_node_malformed_txn_is_definite_abort():
    """TxnServer validates the WHOLE micro-op list before applying
    anything: a malformed txn draws an error reply AND installs no
    writes (the definite-abort contract G1a checking rests on)."""
    import asyncio

    from gossip_tpu.runtime.maelstrom_harness import MaelstromHarness

    async def run():
        import sys as _sys
        h = MaelstromHarness(2, latency=0.001, argv=[
            _sys.executable, "-u", "-m",
            "gossip_tpu.runtime.maelstrom_node", "--workload", "txn"])
        await h.start()
        try:
            await h.set_topology({"n0": ["n1"], "n1": ["n0"]})
            # malformed: a write with a null value, after a valid write
            r = await h.txn("n0", [["w", "x", 5], ["w", "y", None]])
            assert r["body"]["type"] == "error"
            # NOTHING applied — x is still unwritten
            r2 = await h.txn("n0", [["r", "x", None],
                                    ["r", "y", None]])
            assert r2["body"]["type"] == "txn_ok"
            assert r2["body"]["txn"] == [["r", "x", None],
                                         ["r", "y", None]]
            # a committed txn reads its own earlier writes
            r3 = await h.txn("n0", [["w", "x", 9], ["r", "x", None]])
            assert r3["body"]["txn"] == [["w", "x", 9], ["r", "x", 9]]
            assert r3["body"]["ts"][1] == 0        # owner index rides
            # a txn's SECOND write to one key wins in program order
            # (both share the txn timestamp — review finding: a
            # strict ts compare silently dropped it while acking it)
            r4 = await h.txn("n0", [["w", "z", 1], ["w", "z", 2],
                                    ["r", "z", None]])
            assert r4["body"]["txn"] == [["w", "z", 1], ["w", "z", 2],
                                         ["r", "z", 2]]
            r5 = await h.txn("n0", [["r", "z", None]])
            assert r5["body"]["txn"] == [["r", "z", 2]]
        finally:
            await h.stop()

    asyncio.run(run())


# -- committed artifact + provenance gate ------------------------------

def test_committed_txn_artifact_verdict():
    """The committed txn-register record
    (artifacts/ledger_txn_r16.jsonl, tools/txn_capture.py):
    provenance-carrying; txn_conv reached 1.0 on the eventual-alive
    set under the mixed fault program with the partition stall visible
    and bitwise 1-vs-4-device parity; the Maelstrom workload leg shows
    ZERO G0/G1a anomalies through its partition with cross-node LWW
    convergence; the drivers' round_metrics events carry the txn_conv
    column — re-asserted here so the verdict can never rot."""
    from gossip_tpu.utils import telemetry
    path = os.path.join(_REPO, "artifacts", "ledger_txn_r16.jsonl")
    evs = telemetry.load_ledger(path, run="last")
    assert evs[0]["ev"] == "provenance"
    assert len(evs[0]["git_commit"]) == 40
    fp = [e for e in evs if e.get("ev") == "txn_fault_program"][-1]
    assert fp["partitions"] and fp["ramp"] and len(fp["events"]) == 2
    scen = [e for e in evs if e.get("ev") == "txn_scenario"][-1]
    assert scen["txn_conv_final"] == 1.0
    assert scen["mesh_parity_bitwise"] is True
    assert scen["partition_stalled"] is True
    # convergence STALLED while the committed window was open
    stall = scen["partition_stall_rounds"]
    assert all(c < 1.0 for c in scen["txn_conv_curve"][:stall])
    assert scen["ok"] is True
    wl = [e for e in evs if e.get("ev") == "txn_workload"][-1]
    assert wl["g0"] == 0 and wl["g1a"] == 0 and wl["defects"] == 0
    assert wl["converged"] is True and wl["partitioned"] is True
    assert wl["committed"] > 0 and wl["ok"] is True
    assert [e for e in evs if e.get("ev") == "txn_verdict"][-1]["ok"] \
        is True
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert rms and all("txn_conv" in e for e in rms)
    assert all(e["totals"]["txn_conv_final"] == 1.0 for e in rms)


def test_validate_artifacts_requires_provenance_on_txn(tmp_path):
    """``*txn*``/``*register*`` artifacts can never be grandfathered
    in without provenance (the nemesis/crdt/serving/kafka rule,
    extended)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "validate_artifacts",
        os.path.join(_REPO, "tools", "validate_artifacts.py"))
    va = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(va)
    bad = tmp_path / "txn_anomalies_rXX.jsonl"
    bad.write_text(json.dumps({"ev": "txn_scenario"}) + "\n")
    problems = va.validate_file(str(bad))
    assert problems and any("attributable" in p for p in problems)
    badj = tmp_path / "register_sweep.json"
    badj.write_text(json.dumps({"txn_conv": 1.0}))
    assert va.validate_file(str(badj))
