"""Byzantine nemesis (ops/nemesis liar programs + the array-form
defenses): config validation, the defense-bypass pin (defend=True
converges EXACTLY where the undefended control arm provably diverges),
the pure-operand compile pin (K mixed byz programs through ONE
executable, salted re-entry compiles nothing), the no-byz fingerprint
guard (an empty or inactive liar table leaves existing trajectories
bitwise unchanged), capability rejections (engines without liar
transforms reject ``fault.byz`` loudly), and the committed artifact +
provenance gates (tools/byzantine_capture / validate_artifacts)."""

import json
import os

import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import (ByzConfig, ChurnConfig, CrdtConfig,
                               FaultConfig, ProtocolConfig, RunConfig)
from gossip_tpu.topology import generators as G

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the committed scenario (tools/byzantine_capture): a 16-node pull
# fabric, one fail-stop churn event riding WITH the liar program —
# node 3 inflates foreign components from round 2, node 11 corrupts
# them with a high-bit xor from round 0
_N = 16
_BPROTO = ProtocolConfig(mode=C.PULL, fanout=3)
_BRUN = RunConfig(seed=7, max_rounds=100, target_coverage=1.0)
_LIARS = ((3, 2, "inflate", 5), (11, 0, "corrupt", 1 << 20))
_BFAULT = FaultConfig(churn=ChurnConfig(events=((4, 6, 12),)),
                      byz=ByzConfig(liars=_LIARS, quorum=2))


def _mesh(k=4):
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(k)


# -- config validation -------------------------------------------------

def test_byz_config_validation():
    ByzConfig(liars=((0, 0, "inflate", 1), (5, 3, "corrupt", 7)))
    ByzConfig(liars=(), quorum=3)  # the empty program is legal
    with pytest.raises(ValueError, match="unknown byz kind"):
        ByzConfig(liars=((0, 0, "lie", 1),))
    with pytest.raises(ValueError, match="at most once"):
        ByzConfig(liars=((0, 0, "inflate", 1), (0, 2, "corrupt", 2)))
    with pytest.raises(ValueError, match="quorum=0"):
        ByzConfig(liars=((0, 0, "inflate", 1),), quorum=0)
    with pytest.raises(ValueError, match="carry-save chain"):
        ByzConfig(liars=((0, 0, "inflate", 1),), quorum=9)
    with pytest.raises(ValueError, match=">= 0"):
        ByzConfig(liars=((-1, 0, "inflate", 1),))
    # FaultConfig carries the program next to the churn schedule
    f = FaultConfig(byz=ByzConfig(liars=_LIARS))
    assert f.byz.liars == _LIARS


# -- the defense-bypass pin --------------------------------------------

def test_defended_exact_where_undefended_control_diverges():
    """THE acceptance shape: under the mixed fail-stop + liar program
    the DEFENDED honest eventual-alive set converges EXACTLY
    (byz_conv == denominator/denominator, integer count) while the
    UNDEFENDED control arm — the same executable shape, defenses
    off — provably diverges.  A defense whose absence changes nothing
    defends nothing."""
    from gossip_tpu.models.crdt import simulate_curve_crdt
    from gossip_tpu.ops import crdt as CR
    from gossip_tpu.ops import nemesis as NE
    topo = G.complete(_N)
    cfg = CrdtConfig(kind="gcounter")
    _, _, fin_u, _ = simulate_curve_crdt(cfg, _BPROTO, topo, _BRUN,
                                         _BFAULT, defend=False)
    conv_d, _, fin_d, _ = simulate_curve_crdt(cfg, _BPROTO, topo,
                                              _BRUN, _BFAULT,
                                              defend=True)
    inj = CR.inject_args(cfg, _N)
    truth = CR.ground_truth(cfg, inj, _BFAULT, _N, 0)
    honest = NE.honest_mask(_BFAULT, _N)
    alive_h = CR.eventual_alive_crdt(_BFAULT, _N, 0) & honest
    comp = CR.honest_component_mask(cfg, _N, 0, honest)
    denom = int(alive_h.sum())
    assert denom == _N - len(_LIARS)  # churned node 4 recovers
    cnt_d = int(CR.byz_converged_count(cfg, fin_d.val, truth,
                                       alive_h, comp))
    cnt_u = int(CR.byz_converged_count(cfg, fin_u.val, truth,
                                       alive_h, comp))
    assert cnt_d == denom            # defended: exact, all honest
    assert cnt_u < denom             # undefended: provably diverged
    assert conv_d[-1] == 1.0         # the curve agrees with the count
    # the liars' rows are NOT in the denominator: honest-set metric
    assert not bool(alive_h[3]) and not bool(alive_h[11])


# -- pure-operand proof: K programs, one executable --------------------

def test_byz_programs_compile_once_and_salted_reentry_is_free(
        assert_compiles):
    """The liar program is DATA, not code: K mixed byz scenarios —
    different liars, rounds, kinds, args AND quorum — run through ONE
    jitted sharded step (tabled=True puts the byz arrays on the
    argument tail), so after the first call every salted re-entry
    compiles NOTHING."""
    import jax
    from gossip_tpu.parallel.sharded_crdt import (
        init_sharded_crdt_state, make_sharded_crdt_round)
    topo = G.complete(32)
    cfg = CrdtConfig(kind="gcounter")
    run = RunConfig(seed=0, max_rounds=8, target_coverage=1.0)
    mesh = _mesh()
    base = FaultConfig(drop_prob=0.05, seed=2,
                       churn=ChurnConfig(events=((3, 2, 5),)),
                       byz=ByzConfig(liars=((3, 1, "inflate", 5),),
                                     quorum=2))
    step, tables = make_sharded_crdt_round(cfg, _BPROTO, topo, mesh,
                                           base, 0, tabled=True,
                                           defend=True)
    step = jax.jit(step)
    state = init_sharded_crdt_state(run, cfg, topo, mesh)
    with assert_compiles(4, at_most=True):  # first call + auxiliaries
        jax.block_until_ready(step(state, *tables))
    salts = [
        ByzConfig(liars=((5, 2, "equivocate", 9), (11, 1, "replay", 0),
                         (13, 0, "inflate", 3)), quorum=3),
        ByzConfig(liars=((7, 0, "corrupt", 1 << 18),), quorum=1),
        ByzConfig(liars=((1, 3, "replay", 2), (30, 0, "equivocate", 4)),
                  quorum=2),
    ]
    with assert_compiles(0):
        for bz in salts:
            salted = FaultConfig(drop_prob=0.05, seed=2,
                                 churn=ChurnConfig(events=((3, 2, 5),)),
                                 byz=bz)
            _, tk = make_sharded_crdt_round(cfg, _BPROTO, topo, mesh,
                                            salted, 0, tabled=True,
                                            defend=True)
            jax.block_until_ready(step(state, *tk))


# -- no-byz fingerprint guard ------------------------------------------

def test_inactive_liar_table_leaves_trajectory_bitwise_unchanged():
    """Threading the byz operands through the kernels must cost the
    existing fabric NOTHING semantically: a fault program with an
    EMPTY liar table, or one whose liars only start past the horizon,
    reproduces the no-byz trajectory BITWISE (curve, final state,
    message count) — on both arms of the defend gate's control side."""
    from gossip_tpu.models.crdt import simulate_curve_crdt
    topo = G.complete(_N)
    cfg = CrdtConfig(kind="gcounter")
    run = RunConfig(seed=3, max_rounds=16, target_coverage=1.0)
    churn = ChurnConfig(events=((3, 2, 5),))
    plain = FaultConfig(drop_prob=0.05, seed=1, churn=churn)
    empty = FaultConfig(drop_prob=0.05, seed=1, churn=churn,
                        byz=ByzConfig(liars=(), quorum=2))
    dormant = FaultConfig(drop_prob=0.05, seed=1, churn=churn,
                          byz=ByzConfig(liars=((3, 900, "inflate", 5),
                                               (7, 900, "corrupt", 1)),
                                        quorum=2))
    c0, _, f0, t0 = simulate_curve_crdt(cfg, _BPROTO, topo, run, plain)
    for fault in (empty, dormant):
        c1, _, f1, t1 = simulate_curve_crdt(cfg, _BPROTO, topo, run,
                                            fault)
        assert (np.asarray(c0) == np.asarray(c1)).all()
        assert (np.asarray(f0.val) == np.asarray(f1.val)).all()
        assert float(f0.msgs) == float(f1.msgs)
        assert t0 == t1


# -- capability rows: loud rejections ----------------------------------

def test_engines_without_liar_transforms_reject_byz_loudly():
    """Only the crdt-pull and register-pull exchanges render liar
    transforms and carry the defenses — every other engine must
    reject ``fault.byz`` loudly (the no-silent-substitution policy),
    even when the program carries no churn schedule at all."""
    from gossip_tpu.config import LogConfig
    from gossip_tpu.models.log import simulate_curve_log
    from gossip_tpu.ops import nemesis as NE
    byz_only = FaultConfig(byz=ByzConfig(liars=_LIARS, quorum=2))
    with pytest.raises(ValueError, match="byz"):
        NE.check_supported(byz_only, engine="swim-probe")
    with pytest.raises(ValueError, match="byz"):
        simulate_curve_log(LogConfig(), _BPROTO, G.complete(_N), _BRUN,
                           byz_only)


# -- committed artifact + provenance gate ------------------------------

def test_committed_byz_artifact_verdict():
    """The committed byzantine convergence record
    (artifacts/ledger_byz_r25.jsonl, tools/byzantine_capture.py):
    provenance-carrying; the defended honest eventual-alive set
    converged EXACTLY (count == denominator) under the mixed
    fail-stop + liar program for BOTH the gcounter and LWW-register
    payloads while the undefended control arm diverged, with bitwise
    1-vs-4-device mesh parity; the sharded runs' round_metrics events
    carry the byz_conv column at 1.0 — re-asserted here so the
    verdict can never rot."""
    from gossip_tpu.utils import telemetry
    path = os.path.join(_REPO, "artifacts", "ledger_byz_r25.jsonl")
    evs = telemetry.load_ledger(path, run="last")
    assert evs[0]["ev"] == "provenance"
    assert len(evs[0]["git_commit"]) == 40
    fp = [e for e in evs if e.get("ev") == "byz_fault_program"][-1]
    assert fp["quorum"] == 2 and len(fp["liars"]) == 2
    assert fp["churn_events"]  # MIXED: fail-stop rides with the liars
    scen = [e for e in evs if e.get("ev") == "byz_scenario"][-1]
    assert scen["payload"] == "gcounter"
    assert scen["defended_exact"] is True
    assert scen["defended_count"] == scen["denominator"] > 0
    assert scen["undefended_diverged"] is True
    assert scen["undefended_count"] < scen["denominator"]
    assert scen["mesh_parity_bitwise"] is True and scen["ok"] is True
    assert scen["defended_curve"][-1] == 1.0
    assert scen["undefended_curve"][-1] < 1.0
    tscen = [e for e in evs if e.get("ev") == "byz_txn_scenario"][-1]
    assert tscen["defended_exact"] is True
    assert tscen["undefended_diverged"] is True
    assert tscen["mesh_parity_bitwise"] is True and tscen["ok"] is True
    assert [e for e in evs if e.get("ev") == "byz_verdict"][-1]["ok"] \
        is True
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert rms and all("byz_conv" in e for e in rms)
    assert all(e["totals"]["byz_conv_final"] == 1.0 for e in rms)
    # the hw_refresh smoke twin exists and carries the same verdict
    smoke = os.path.join(_REPO, "artifacts",
                         "ledger_byz_r25.smoke.jsonl")
    sevs = telemetry.load_ledger(smoke, run="last")
    assert sevs[0]["ev"] == "provenance"
    sv = [e for e in sevs if e.get("ev") == "byz_verdict"][-1]
    assert sv["ok"] is True and sv["smoke"] is True


def test_validate_artifacts_requires_provenance_on_byz(tmp_path):
    """``*byz*``/``*byzantine*``/``*adversary*`` artifacts can never
    be grandfathered in without provenance (the nemesis/crashloop
    rule, extended): an unattributed adversary record is the exact
    claim the defense lattice exists to reject."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "validate_artifacts",
        os.path.join(_REPO, "tools", "validate_artifacts.py"))
    va = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(va)
    bad = tmp_path / "ledger_byz_rXX.jsonl"
    bad.write_text(json.dumps({"ev": "byz_scenario"}) + "\n")
    problems = va.validate_file(str(bad))
    assert problems and any("attributable" in p for p in problems)
    badj = tmp_path / "adversary_sweep.json"
    badj.write_text(json.dumps({"byz_conv": 1.0}))
    assert va.validate_file(str(badj))
    badb = tmp_path / "byzantine_record.jsonl"
    badb.write_text(json.dumps({"ev": "byz_verdict", "ok": True})
                    + "\n")
    assert va.validate_file(str(badb))
