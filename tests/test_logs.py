"""Replicated-log subsystem (ops/logs, models/log, parallel/
sharded_log): config validation, offset-assignment + acked-appends
ground truth, the partition-stall/exact-heal acceptance, 1-vs-4-device
bitwise parity under the full mixed fault program, the log_conv
round-metrics column, CLI + RPC fall-through + Maelstrom kafka
workload surfaces, the committed artifact verdict pin, and the
``*kafka*``/``*replog*`` provenance rule."""

import json
import os

import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import (ChurnConfig, FaultConfig, LogConfig,
                               ProtocolConfig, RunConfig)
from gossip_tpu.topology import generators as G

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROTO = ProtocolConfig(mode=C.PULL, fanout=2)
# the full mixed fault program every parity/heal surface runs:
# crash/recover, permanent crash, open partition window, drop ramp
_CFAULT = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
    events=((3, 2, 5), (7, 1, -1)), partitions=((0, 6, 16),),
    ramp=(1, 4, 0.0, 0.3)))


# -- config validation -------------------------------------------------

def test_log_config_validation():
    LogConfig(keys=2, capacity=4,
              sends=((0, 0, 0, 5), (1, 0, 2, 7), (2, 1, 0, 1)),
              commits=((0, 0, 3, 2),))
    with pytest.raises(ValueError, match="keys must be"):
        LogConfig(keys=0)
    with pytest.raises(ValueError, match="values must be >= 1"):
        LogConfig(sends=((0, 0, 0, 0),))
    with pytest.raises(ValueError, match="outside"):
        LogConfig(keys=2, sends=((0, 5, 0, 1),))
    with pytest.raises(ValueError, match="horizon cap"):
        LogConfig(sends=((0, 0, 10 ** 9, 1),))
    # the ring never wraps: more sends than capacity is a loud error
    with pytest.raises(ValueError, match="wrap"):
        LogConfig(keys=1, capacity=2,
                  sends=((0, 0, 0, 1), (1, 0, 1, 2), (2, 0, 2, 3)))
    # offset order IS time order: per-key script order must be
    # round-nondecreasing
    with pytest.raises(ValueError, match="nondecreasing"):
        LogConfig(sends=((0, 0, 5, 1), (1, 0, 2, 2)))
    with pytest.raises(ValueError, match="upto must be"):
        LogConfig(commits=((0, 0, 2, 0),))
    # the DEFAULT send program (4 per key) obeys the same no-wrap
    # contract a scripted one does — a tiny unscripted capacity must
    # error loudly, never alias slots silently (review finding)
    with pytest.raises(ValueError, match="default send program"):
        LogConfig(keys=4, capacity=2)
    LogConfig(keys=4, capacity=2, sends=((0, 0, 0, 1), (1, 1, 0, 1)))
    # horizon: last scripted round + 1; defaults end at round 4
    assert LogConfig(sends=((0, 0, 7, 1),)).horizon() == 8
    assert LogConfig().horizon() == 5


# -- offset assignment + acked-appends ground truth --------------------

def test_ground_truth_acked_append_semantics():
    """A send is applied iff its appender is alive at the send round
    AND eventually alive; unapplied sends are compacted over (the
    acked log is gap-free), and commits clamp to the eventually-acked
    length."""
    from gossip_tpu.ops import logs as LG
    n = 8
    cfg = LogConfig(keys=2, capacity=8,
                    sends=((0, 0, 0, 10),   # healthy: offset 0
                           (7, 0, 1, 20),   # dies forever at 1: out
                           (1, 0, 2, 30),   # down [1, 4): missed
                           (2, 0, 5, 40)),  # healthy: offset 1
                    commits=((4, 0, 6, 3),  # clamps to acked len 2
                             (5, 1, 6, 1)))  # key 1 empty: commits 0
    f = FaultConfig(churn=ChurnConfig(events=((7, 1, -1), (1, 1, 4))))
    inj = LG.inject_args(cfg, n)
    truth = np.asarray(LG.ground_truth(cfg, inj, f, n, 0))
    assert truth[:8].tolist() == [10, 40, 0, 0, 0, 0, 0, 0]
    assert truth[8:16].tolist() == [0] * 8          # key 1 empty
    assert truth[16:].tolist() == [2, 0]            # commit clamped
    # fault-free: everything applies, offsets in script order
    truth0 = np.asarray(LG.ground_truth(cfg, inj, None, n, 0))
    assert truth0[:8].tolist() == [10, 20, 30, 40, 0, 0, 0, 0]
    assert truth0[16:].tolist() == [3, 0]
    # out-of-range appender ids are a loud error, not a silent no-op
    with pytest.raises(ValueError, match="node ids"):
        LG.inject_args(LogConfig(sends=((99, 0, 0, 1),)), n)
    # the derived append cursor reads the contiguous prefix
    lens = np.asarray(LG.log_len(cfg, truth[None, :]))[0]
    assert lens.tolist() == [2, 0]


# -- partition-heal convergence (the acceptance gate) ------------------

def test_partition_stall_and_exact_heal():
    """While the window is open, log convergence provably stalls (no
    node holds the global acked log + committed offsets) and after
    heal every eventual-alive node reaches the exact integer ground
    truth — the ordered eventual-consistency invariant under the full
    mixed fault program."""
    from gossip_tpu.models.log import simulate_curve_log
    from gossip_tpu.ops import logs as LG
    cfg = LogConfig(keys=4, capacity=8)
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    n = 32
    conv, _, final, truth = simulate_curve_log(cfg, _PROTO,
                                               G.complete(n), run,
                                               _CFAULT)
    # stalled while the committed window [0, 6) is open
    assert all(c < 1.0 for c in conv[:6]), list(conv)
    assert conv[-1] == 1.0, list(conv)
    # integer-exact: every eventual-alive node holds the truth row
    inj = LG.inject_args(cfg, n)
    truth_row = np.asarray(LG.ground_truth(cfg, inj, _CFAULT, n, 0))
    eventual = np.asarray(LG.eventual_alive_crdt(_CFAULT, n, 0))
    vals = np.asarray(final.val)
    assert (vals[eventual] == truth_row[None, :]).all()
    # the permanently-dead appender's sends are compacted out of truth
    assert truth["total_entries"] < 16


# -- mesh parity: schedules + injections as operands -------------------

def _mesh(k=4):
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(k)


def test_log_mesh_parity_bitwise_full_fault_program():
    """1-device vs 4-device log trajectories BITWISE identical under
    the full mixed fault program (event + permanent crash + open
    partition window + ramp) — the acceptance criterion, plus exact
    convergence on the eventual-alive set."""
    from gossip_tpu.models.log import simulate_curve_log
    from gossip_tpu.parallel.sharded_log import (
        simulate_curve_log_sharded)
    run = RunConfig(seed=0, max_rounds=16, target_coverage=1.0)
    topo = G.complete(32)
    cfg = LogConfig(keys=4, capacity=8)
    c1, m1, f1, t1 = simulate_curve_log(cfg, _PROTO, topo, run, _CFAULT)
    c4, m4, f4, t4 = simulate_curve_log_sharded(cfg, _PROTO, topo, run,
                                                _mesh(), _CFAULT)
    assert (np.asarray(c1) == np.asarray(c4)).all()
    assert (np.asarray(f1.val) == np.asarray(f4.val)[:32]).all()
    assert float(f1.msgs) == float(f4.msgs)
    assert t1 == t4
    assert c4[-1] == 1.0


def test_until_driver_integer_target():
    """The while_loop driver's cond is an exact integer converged-count
    compare; single and sharded agree on rounds and the final value."""
    from gossip_tpu.models.log import simulate_until_log
    from gossip_tpu.parallel.sharded_log import (
        simulate_until_log_sharded)
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    topo = G.complete(32)
    cfg = LogConfig(keys=4, capacity=8)
    r1, c1, m1, f1, t1 = simulate_until_log(cfg, _PROTO, topo, run,
                                            _CFAULT)
    r4, c4, m4, f4, t4 = simulate_until_log_sharded(
        cfg, _PROTO, topo, run, _mesh(), _CFAULT)
    assert (r1, c1, t1) == (r4, c4, t4)
    assert c1 == 1.0 and r1 < 24


def test_log_rejections_are_loud():
    from gossip_tpu.models.log import make_log_round, simulate_until_log
    with pytest.raises(ValueError, match="pull exchange only"):
        make_log_round(LogConfig(), ProtocolConfig(mode=C.PUSH),
                       G.complete(8))
    # an injection the loop can never fire makes ground truth
    # unreachable by construction — a loud error (models/crdt rule)
    with pytest.raises(ValueError, match="can never fire"):
        simulate_until_log(
            LogConfig(sends=((0, 0, 100, 1),)), _PROTO, G.complete(8),
            RunConfig(seed=0, max_rounds=8))


# -- the log_conv round-metrics column ---------------------------------

def test_log_conv_round_metrics_emitted_and_bitwise_free(tmp_path):
    """With an active run ledger the sharded log drivers flush a
    round_metrics stack carrying the log_conv column (+ the nemesis
    columns under churn); recording must not move the trajectory
    bitwise (the ops/round_metrics zero-impact contract)."""
    from gossip_tpu.parallel.sharded_log import (
        simulate_curve_log_sharded)
    from gossip_tpu.utils import telemetry
    run = RunConfig(seed=0, max_rounds=12, target_coverage=1.0)
    topo = G.complete(32)
    cfg = LogConfig(keys=4, capacity=8)
    # metrics-off reference
    c0, _, f0, _ = simulate_curve_log_sharded(cfg, _PROTO, topo, run,
                                              _mesh(), _CFAULT)
    path = str(tmp_path / "log_metrics.jsonl")
    led = telemetry.Ledger(path)
    prev = telemetry.activate(led)
    try:
        c1, _, f1, _ = simulate_curve_log_sharded(
            cfg, _PROTO, topo, run, _mesh(), _CFAULT)
    finally:
        telemetry.activate(prev)
        led.close()
    assert (np.asarray(c0) == np.asarray(c1)).all()
    assert (np.asarray(f0.val) == np.asarray(f1.val)).all()
    evs = telemetry.load_ledger(path)
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert rms
    e = rms[-1]
    assert e["driver"] == "simulate_curve_log_sharded"
    assert len(e["log_conv"]) == e["rounds"] == 12
    assert e["totals"]["log_conv_final"] == pytest.approx(
        float(c1[-1]), abs=1e-4)
    # nemesis columns ride the same stack under the fault program
    assert e["totals"]["dropped"] > 0
    assert any(p > 0 for p in e["cut_pairs"])


# -- CLI ---------------------------------------------------------------

def test_cli_log_run_and_error_paths(capsys, monkeypatch):
    from gossip_tpu import cli

    # in-process cli.main: --no-compile-cache writes
    # GOSSIP_COMPILE_CACHE="" into THIS process's env — monkeypatch
    # re-pins the session cache dir for the tests that follow
    monkeypatch.setenv("GOSSIP_COMPILE_CACHE",
                       os.environ.get("GOSSIP_COMPILE_CACHE", ""))
    rc = cli.main(["log", "--n", "32", "--max-rounds", "24",
                   "--partition", "0:4:16", "--churn-event", "3:2:5",
                   "--drop-ramp", "1:3:0.0:0.2", "--no-compile-cache"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["mode"] == "log"
    assert out["converged"] is True and out["log_conv"] == 1.0
    assert out["truth"]["total_entries"] > 0
    assert out["fault_program"] is True
    # scripted sends/commits + curve
    rc = cli.main(["log", "--n", "16", "--keys", "2",
                   "--send", "0:0:0:9", "--send", "1:0:1:4",
                   "--commit", "2:0:3:1", "--curve",
                   "--max-rounds", "12", "--no-compile-cache"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["truth"] == {"lens": [2, 0], "committed": [1, 0],
                            "total_entries": 2}
    assert out["curve"][-1] == 1.0
    # validation surfaces as a clean CLI error, never a traceback
    rc = cli.main(["log", "--send", "0:0:0:0", "--no-compile-cache"])
    assert rc == 2
    assert "values must be >= 1" in capsys.readouterr().err


# -- RPC: the admission-batcher fall-through contract ------------------

def test_log_request_falls_through_batcher_labeled():
    """A log-workload Run request is NOT a megabatch lane shape: it
    must fall through the admission batcher to the solo path with a
    NAMED ``meta.batch.reason`` (the PR 9 fall-through contract — a
    labeled solo reply, never INTERNAL), and the solo path must
    actually run it."""
    from gossip_tpu.backend import request_to_args, run_simulation
    from gossip_tpu.rpc.batcher import classify_run
    base = {"backend": "jax-tpu",
            "proto": {"mode": "pull", "fanout": 2},
            "topology": {"family": "complete", "n": 32},
            "run": {"max_rounds": 16, "target_coverage": 1.0},
            "log": {"keys": 2, "capacity": 8}}
    args = request_to_args(dict(base))
    key, reason, _ = classify_run(args)
    assert key is None and "log workload" in reason
    # the solo path the fallthrough lands on runs the workload
    rep = run_simulation(**args).to_dict()
    assert rep["mode"] == "log" and rep["coverage"] == 1.0
    assert rep["meta"]["truth"]["total_entries"] > 0
    # without the log field the same request batches normally
    plain = {k: v for k, v in base.items() if k != "log"}
    key2, _, _ = classify_run(request_to_args(plain))
    assert key2 is not None


def test_sidecar_log_request_solo_reply_labeled():
    """Live batching sidecar: the log request's reply carries the loud
    ``batched: false`` label + reason (and the Ensemble RPC rejects
    log requests with INVALID_ARGUMENT, not INTERNAL)."""
    grpc = pytest.importorskip("grpc")
    from gossip_tpu.config import ServingConfig
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    server, port = serve(port=0, max_workers=4,
                         batching=ServingConfig(tick_ms=50,
                                                max_batch=8))
    try:
        c = SidecarClient(f"127.0.0.1:{port}")
        out = c.run(backend="jax-tpu",
                    proto={"mode": "pull", "fanout": 2},
                    topology={"family": "complete", "n": 32},
                    run={"max_rounds": 16, "target_coverage": 1.0},
                    log={"keys": 2, "capacity": 8})
        assert out["coverage"] == 1.0
        assert out["meta"]["batch"]["batched"] is False
        assert "log workload" in out["meta"]["batch"]["reason"]
        with pytest.raises(grpc.RpcError) as ei:
            c.ensemble(backend="jax-tpu",
                       proto={"mode": "pull", "fanout": 2},
                       topology={"family": "complete", "n": 32},
                       log={"keys": 2}, ensemble=2)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        c.close()
    finally:
        server.gossip_batcher.close()
        server.stop(0)


# -- Maelstrom kafka workload (the Gossip Glomers invariants) ----------

# ~4 s: the in-gate acceptance surface is the maelstrom-check CLI run
# below (the SAME run_kafka_workload through the same partition;
# invariant_ok already ANDs the monotone + gapless flags); this
# direct-API depth — per-flag granularity, committed-map coverage —
# runs under -m slow
@pytest.mark.slow
def test_kafka_workload_invariants_through_partition():
    """run_kafka_workload: acked sends appear exactly once per key in
    offset order, committed offsets never regress, and polls are
    gapless — through a harness-injected mid-cluster partition (the
    fault-tolerance variant of the Gossip Glomers kafka challenge).
    ops=12/seed=0 exercises commits on multiple keys (committed map
    non-empty)."""
    import asyncio

    from gossip_tpu.runtime.maelstrom_harness import run_kafka_workload
    stats = asyncio.run(run_kafka_workload(
        4, ops=12, rate=25.0, latency=0.001, partition_mid=True,
        seed=0))
    assert stats["invariant_ok"] is True
    assert stats["partitioned"] is True
    assert stats["monotone_ok"] is True and stats["gapless_ok"] is True
    assert sum(stats["acked"].values()) > 0
    assert stats["committed"]            # commits actually exercised
    # sends/polls/commits are client ops via the shared accounting
    assert stats["ops"] > 12 and stats["broadcast_ops"] == 0


def test_cli_maelstrom_check_kafka_in_gate(capsys):
    """The acceptance surface: ``maelstrom-check --workload kafka``
    passes all three kafka invariants through a mid-run partition."""
    from gossip_tpu import cli
    rc = cli.main(["maelstrom-check", "--workload", "kafka", "--n", "4",
                   "--ops", "12", "--rate", "25", "--latency", "0.001",
                   "--partition"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["workload"] == "kafka"
    assert out["invariant_ok"] is True and out["partitioned"] is True
    # invariant_ok ANDs all three kafka checks; assert the per-flag
    # verdicts + commit coverage too (ops=12/seed=0 commits >= 2 keys)
    assert out["monotone_ok"] is True and out["gapless_ok"] is True
    assert out["committed"] and sum(out["acked"].values()) > 0
    # the native router speaks the broadcast envelope set only
    rc = cli.main(["maelstrom-check", "--workload", "kafka",
                   "--router", "native"])
    assert rc == 2
    assert "python router" in capsys.readouterr().err


def test_kafka_workload_timeout_send_is_indeterminate_not_crash():
    """A client RPC timing out (a long partition outlasting the 15 s
    budget while the node's forward retries keep going) must record
    the send INDETERMINATE — it may later appear in polls via the
    owner's at-least-once forward — never crash run_kafka_workload
    (review finding: the uncaught TimeoutError path)."""
    import asyncio

    from gossip_tpu.runtime import maelstrom_harness as MH

    orig = MH.MaelstromHarness.kafka_send
    state = {"fired": False}

    async def flaky(self, node, key, msg):
        if not state["fired"]:
            state["fired"] = True
            raise asyncio.TimeoutError()
        return await orig(self, node, key, msg)

    MH.MaelstromHarness.kafka_send = flaky
    try:
        stats = asyncio.run(MH.run_kafka_workload(
            3, ops=6, rate=50.0, latency=0.001, partition_mid=False,
            seed=1))
    finally:
        MH.MaelstromHarness.kafka_send = orig
    assert state["fired"]
    # the timed-out send is indeterminate, the rest acked; the
    # invariants still hold (an indeterminate value may appear in
    # polls, at most once)
    assert stats["invariant_ok"] is True
    assert sum(stats["indeterminate"].values()) == 1
    assert sum(stats["acked"].values()) == 5


# -- committed artifact + provenance gate ------------------------------

def test_committed_kafka_artifact_verdict():
    """The committed replicated-log convergence record
    (artifacts/ledger_kafka_r15.jsonl, tools/kafka_capture.py):
    provenance-carrying; log_conv reached 1.0 on the eventual-alive
    set under the mixed fault program with the partition stall visible
    and bitwise 1-vs-4-device parity; the drivers' round_metrics
    events carry the log_conv column — re-asserted here so the
    verdict can never rot."""
    from gossip_tpu.utils import telemetry
    path = os.path.join(_REPO, "artifacts", "ledger_kafka_r15.jsonl")
    evs = telemetry.load_ledger(path, run="last")
    assert evs[0]["ev"] == "provenance"
    assert len(evs[0]["git_commit"]) == 40
    fp = [e for e in evs if e.get("ev") == "kafka_fault_program"][-1]
    assert fp["partitions"] and fp["ramp"] and len(fp["events"]) == 2
    scen = [e for e in evs if e.get("ev") == "kafka_scenario"][-1]
    assert scen["log_conv_final"] == 1.0
    assert scen["mesh_parity_bitwise"] is True
    assert scen["partition_stalled"] is True
    # convergence STALLED while the committed window was open
    stall = scen["partition_stall_rounds"]
    assert all(c < 1.0 for c in scen["log_conv_curve"][:stall])
    assert scen["ok"] is True
    assert [e for e in evs if e.get("ev") == "kafka_verdict"][-1]["ok"] \
        is True
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert rms and all("log_conv" in e for e in rms)
    assert all(e["totals"]["log_conv_final"] == 1.0 for e in rms)


def test_validate_artifacts_requires_provenance_on_kafka(tmp_path):
    """``*kafka*``/``*replog*`` artifacts can never be grandfathered
    in without provenance (the nemesis/crdt/serving rule, extended)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "validate_artifacts",
        os.path.join(_REPO, "tools", "validate_artifacts.py"))
    va = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(va)
    bad = tmp_path / "kafka_convergence_rXX.jsonl"
    bad.write_text(json.dumps({"ev": "kafka_scenario"}) + "\n")
    problems = va.validate_file(str(bad))
    assert problems and any("attributable" in p for p in problems)
    badj = tmp_path / "replog_sweep.json"
    badj.write_text(json.dumps({"log_conv": 1.0}))
    assert va.validate_file(str(badj))
