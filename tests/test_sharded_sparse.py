"""Tests for the sparse all_to_all exchange (parallel/sharded_sparse.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.ops.bitpack import coverage_packed, n_words
from gossip_tpu.parallel.sharded import make_mesh
from gossip_tpu.parallel.sharded_sparse import (
    SPARSE_ROW_TAG, _round_draws, _slot_rows, init_sparse_state,
    make_sparse_pull_round, make_sparse_topo_pull_round, resolve_topo_cap,
    simulate_curve_topo_sparse, simulate_until_sparse,
    simulate_until_topo_sparse, sparse_meta, sparse_pull_round_reference,
    sparse_topo_pull_round_reference)
from gossip_tpu.topology import generators as G

P8 = 8


def _mesh():
    return make_mesh(P8)


# all params are depth coverage on the slow tier since the
# compile-once PR (the single in-gate param cost 40 s of the 870 s
# tier-1 budget).  The sparse surface keeps two in-gate smokes: the
# dry run executes both sparse families with schema/steady asserts
# every gate run (tests/test_graft_entry.py), and the compile-cache
# driver matrix pins the sparse curve driver's outputs bitwise across
# executable sources (tests/test_compile_cache.py).  Mesh-vs-reference
# BITWISE parity — what only this test proves — runs under `-m slow`.
@pytest.mark.parametrize("mode,fanout,rumors,fault", [
    pytest.param(C.PULL, 1, 1, None, marks=pytest.mark.slow),
    pytest.param(C.PULL, 2, 40, None, marks=pytest.mark.slow),
    pytest.param(C.PULL, 1, 1,
                 FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=3),
                 marks=pytest.mark.slow),
    pytest.param(C.ANTI_ENTROPY, 1, 5, None, marks=pytest.mark.slow),
])
def test_bitwise_parity_mesh_vs_reference(mode, fanout, rumors, fault):
    """The mesh run and the single-device reference must agree BITWISE for
    several rounds (collectives only move data)."""
    n = 256
    proto = ProtocolConfig(mode=mode, fanout=fanout, rumors=rumors, period=2)
    run = RunConfig(seed=11)
    mesh = _mesh()
    step_m = make_sparse_pull_round(proto, n, mesh, fault, run.origin)
    step_r = sparse_pull_round_reference(proto, n, P8, fault, run.origin)
    st_m = init_sparse_state(run, proto, n, mesh)
    st_r = init_sparse_state(run, proto, n)  # unsharded, same padding (p=1
    # pads to n; mesh pads to n too since 256 % 8 == 0)
    for _ in range(6):
        st_m = step_m(st_m)
        st_r = step_r(st_r)
        np.testing.assert_array_equal(np.asarray(st_m.seen),
                                      np.asarray(st_r.seen))
        assert float(st_m.msgs) == float(st_r.msgs)


def test_partner_marginal_is_uniform():
    """Stratification must leave the per-slot partner marginal uniform over
    all rows: chi-square over many rounds for one fixed slot."""
    p, nl = 8, 32
    n_pad = p * nl
    key = jax.random.key(0)
    slot = jnp.asarray([5], jnp.int32)      # fixed global slot, k=1

    @jax.jit
    @jax.vmap
    def partner_gid(rnd):
        rkey = jax.random.fold_in(key, rnd)
        pi, o = _round_draws(rkey, p)
        shard = pi[(5 + o) % p]
        return shard * nl + _slot_rows(rkey, slot, nl)[0]

    gids = np.asarray(partner_gid(jnp.arange(2000, dtype=jnp.uint32)))
    counts = np.bincount(gids, minlength=n_pad)
    expected = 2000 / n_pad
    chi2 = ((counts - expected) ** 2 / expected).sum()
    # dof = 255; 3-sigma upper bound ~ 255 + 3*sqrt(510) ~ 323
    assert chi2 < 323, chi2


@pytest.mark.slow
def test_converges_and_traffic_accounting():
    n = 1024
    proto = ProtocolConfig(mode=C.PULL, fanout=2, rumors=40)
    run = RunConfig(seed=0, target_coverage=0.99, max_rounds=64)
    rounds, cov, msgs, final, meta = simulate_until_sparse(
        proto, n, run, _mesh())
    assert cov >= 0.99
    assert 5 <= rounds <= 30
    w = n_words(40)
    nl = n // P8
    assert meta.cap == (nl * 2) // P8
    assert meta.request_bytes == P8 * meta.cap * 4
    assert meta.response_bytes == P8 * meta.cap * 4 * w
    assert meta.dense_bytes == n * 4 * w
    # the whole point: sparse moves less than dense when k < shards*W/(W+1)
    assert meta.sparse_bytes < meta.dense_bytes
    # msgs: 2 per valid request, all nodes alive -> 2*k*n per active round
    assert float(msgs) == pytest.approx(2.0 * 2 * n * rounds)


@pytest.mark.slow
def test_sparse_matches_dense_pull_statistically():
    """Same protocol, different exchange: rounds-to-99% must agree within
    +/-2 rounds of the dense packed pull path."""
    from gossip_tpu.models.si_packed import simulate_until_packed
    from gossip_tpu.topology import generators as G
    n = 2048
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    run = RunConfig(seed=5, target_coverage=0.99, max_rounds=64)
    r_sparse, cov_s, _, _, _ = simulate_until_sparse(proto, n, run, _mesh())
    r_dense, cov_d, _, _ = simulate_until_packed(proto, G.complete(n), run)
    assert cov_s >= 0.99 and cov_d >= 0.99
    assert abs(r_sparse - r_dense) <= 2, (r_sparse, r_dense)


def test_rejects_push_and_unbalanced():
    mesh = _mesh()
    with pytest.raises(ValueError, match="pull"):
        make_sparse_pull_round(ProtocolConfig(mode=C.PUSH), 256, mesh)
    with pytest.raises(ValueError, match="divide"):
        # nl*k = 4 slots per shard, not divisible by 8 shards
        make_sparse_pull_round(
            ProtocolConfig(mode=C.PULL, fanout=1), 32, mesh)


# ---------------------------------------------------------------------
# Explicit-topology sparse exchange (VERDICT r2 item 5)


@pytest.mark.parametrize("family,mode,fanout,rumors,fault", [
    pytest.param("erdos_renyi", C.PULL, 1, 1, None,
                 marks=pytest.mark.slow),
    pytest.param("erdos_renyi", C.PULL, 2, 40, None,
                 marks=pytest.mark.slow),
    pytest.param("watts_strogatz", C.PULL, 1, 5,
                 FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=3),
                 marks=pytest.mark.slow),
    pytest.param("power_law", C.PULL, 1, 1, None,
                 marks=pytest.mark.slow),
    pytest.param("erdos_renyi", C.ANTI_ENTROPY, 1, 5, None,
                 marks=pytest.mark.slow),
    pytest.param("watts_strogatz", C.ANTI_ENTROPY, 2, 3,
                 FaultConfig(drop_prob=0.15, seed=5),
                 marks=pytest.mark.slow),
])
def test_topo_bitwise_parity_mesh_vs_reference(family, mode, fanout,
                                               rumors, fault):
    """Mesh run == single-device reference BITWISE, including the
    deterministic capacity drops and the anti-entropy reverse merge, on
    explicit topologies (anti-entropy uses period=2: the cond-gated
    reverse collective and the quiescent-round masking both covered)."""
    n = 256
    topo = {"erdos_renyi": lambda: G.erdos_renyi(n, 0.05, seed=7),
            "watts_strogatz": lambda: G.watts_strogatz(n, 6, 0.1, seed=7),
            "power_law": lambda: G.power_law(n, 3, seed=7)}[family]()
    proto = ProtocolConfig(mode=mode, fanout=fanout, rumors=rumors,
                           period=2 if mode == C.ANTI_ENTROPY else 1)
    run = RunConfig(seed=11)
    mesh = _mesh()
    step_m = make_sparse_topo_pull_round(proto, topo, mesh, fault,
                                         run.origin)
    step_r = sparse_topo_pull_round_reference(proto, topo, P8, fault,
                                              run.origin)
    st_m = init_sparse_state(run, proto, n, mesh)
    st_r = init_sparse_state(run, proto, n, p=P8)
    ovf_m = ovf_r = jnp.float32(0.0)
    for _ in range(6):
        st_m, ovf_m = step_m(st_m, ovf_m)
        st_r, ovf_r = step_r(st_r, ovf_r)
        np.testing.assert_array_equal(np.asarray(st_m.seen),
                                      np.asarray(st_r.seen))
        assert float(st_m.msgs) == float(st_r.msgs)
        assert float(ovf_m) == float(ovf_r)


@pytest.mark.slow
def test_topo_overflow_is_deterministic_and_counted():
    """With a tiny forced cap, overflow drops happen, are counted, and
    stay bitwise-identical between mesh and reference."""
    n = 256
    topo = G.erdos_renyi(n, 0.08, seed=2)
    proto = ProtocolConfig(mode=C.PULL, fanout=2, rumors=1)
    run = RunConfig(seed=4)
    mesh = _mesh()
    cap = 2               # way below the balanced load 256/8*2/8 = 8
    step_m = make_sparse_topo_pull_round(proto, topo, mesh, None,
                                         run.origin, cap=cap)
    step_r = sparse_topo_pull_round_reference(proto, topo, P8, None,
                                              run.origin, cap=cap)
    st_m = init_sparse_state(run, proto, n, mesh)
    st_r = init_sparse_state(run, proto, n, p=P8)
    ovf_m = ovf_r = jnp.float32(0.0)
    for _ in range(5):
        st_m, ovf_m = step_m(st_m, ovf_m)
        st_r, ovf_r = step_r(st_r, ovf_r)
    np.testing.assert_array_equal(np.asarray(st_m.seen),
                                  np.asarray(st_r.seen))
    assert float(ovf_m) == float(ovf_r) > 0
    # overflow drops cost coverage progress, not correctness: every pull
    # that WAS delivered still lands on a legal neighbor, so msgs counts
    # only the delivered ones (2 per request)
    assert float(st_m.msgs) < 2.0 * 2 * n * 5


@pytest.mark.slow
def test_topo_byte_accounting_er_100k():
    """The VERDICT item's 'done' criterion: on a 100k-node ER graph the
    sparse exchange moves O(messages), not O(N) — the per-round ICI
    bytes drop vs the dense packed all_gather by ~p*4W/(k*(4+4W)), and
    the epidemic still converges."""
    n = 100_000
    topo = G.erdos_renyi(n, 10.0 / n, seed=1)    # mean degree ~10
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    run = RunConfig(seed=0, target_coverage=0.99, max_rounds=64)
    rounds, cov, msgs, _, meta, ovf = simulate_until_topo_sparse(
        proto, topo, run, _mesh())
    assert cov >= 0.99
    assert rounds < 64
    # O(messages): request+response bytes vs the dense packed gather.
    # ER is shard-uniform, so cap ~ balanced load + 4-sigma slack and
    # the drop at p=8, W=1, k=1 is ~3.6x; it grows linearly with mesh
    # size and rumor words.
    assert meta.sparse_bytes * 3 <= meta.dense_bytes, (
        meta.sparse_bytes, meta.dense_bytes)
    # table-derived cap (auto_topo_cap) -> overflow is rare on ER
    assert ovf < 0.01 * msgs
    # traffic formula documented in sparse_topo_meta
    nl = (n + P8 - 1) // P8
    n_pad = nl * P8
    assert meta.cap == resolve_topo_cap(topo, P8, 1)
    assert meta.request_bytes == P8 * meta.cap * 4
    assert meta.dense_bytes == n_pad * 4


@pytest.mark.slow
def test_topo_sparse_matches_dense_statistically():
    """Same ER pull protocol through the sparse exchange and the dense
    sharded path: rounds-to-99% must agree within a seed-stream-aware
    margin (the two engines draw from DIFFERENT RNG streams, so the
    agreement is statistical, not bitwise).

    The margin is a property of the random stream, and jax.random's
    stream semantics differ between the modern line and the 0.4.x
    fallback toolchain (compat module doc): +/-2 was tuned on the
    modern stream, where this seed lands <=2 apart; the 0.4.x stream
    lands the same seed 3 apart (16 vs 19) — a real stream difference,
    not an engine regression, so legacy jax widens the margin to +/-3
    instead of standing red (the bitwise-parity tests above are the
    correctness gate; this one only guards against gross divergence
    like a lost round of mixing)."""
    from gossip_tpu.compat import legacy_jax
    from gossip_tpu.parallel.sharded import simulate_until_sharded
    n = 2048
    topo = G.erdos_renyi(n, 12.0 / n, seed=9)
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    run = RunConfig(seed=5, target_coverage=0.99, max_rounds=64)
    r_s, cov_s, _, _, _, _ = simulate_until_topo_sparse(
        proto, topo, run, _mesh())
    r_d, cov_d, _, _ = simulate_until_sharded(proto, topo, run, _mesh())
    assert cov_s >= 0.99 and cov_d >= 0.99
    margin = 3 if legacy_jax() else 2
    assert abs(r_s - r_d) <= margin, (r_s, r_d, margin)


@pytest.mark.slow
def test_topo_curve_driver_and_overflow_series():
    n = 1024
    topo = G.watts_strogatz(n, 8, 0.2, seed=3)
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=3)
    run = RunConfig(seed=1, max_rounds=24)
    covs, msgs, final, meta, ovfs = simulate_curve_topo_sparse(
        proto, topo, run, _mesh())
    assert covs.shape == (24,) and ovfs.shape == (24,)
    assert (np.diff(covs) >= -1e-6).all(), "coverage must be monotone"
    assert covs[-1] > 0.99
    assert (np.diff(ovfs) >= 0).all(), "overflow count is cumulative"


def test_topo_rejections():
    mesh = _mesh()
    topo = G.erdos_renyi(256, 0.05, seed=0)
    with pytest.raises(ValueError, match="pull and anti-entropy"):
        make_sparse_topo_pull_round(ProtocolConfig(mode=C.PUSH), topo, mesh)
    with pytest.raises(ValueError, match="pull and anti-entropy"):
        make_sparse_topo_pull_round(ProtocolConfig(mode=C.FLOOD), topo,
                                    mesh)
    with pytest.raises(ValueError, match="implicit"):
        make_sparse_topo_pull_round(
            ProtocolConfig(mode=C.PULL), G.complete(256), mesh)


@pytest.mark.slow
def test_topo_antientropy_converges_and_reverse_accounting():
    """Anti-entropy through the topo exchange: faster convergence than
    pure pull (bidirectional merge), reverse bytes in the meta, msgs
    factor 3 on exchange rounds only."""
    n = 2048
    topo = G.erdos_renyi(n, 12.0 / n, seed=4)
    run = RunConfig(seed=2, target_coverage=0.99, max_rounds=64)
    r_ae, cov_ae, msgs_ae, _, meta_ae, _ = simulate_until_topo_sparse(
        ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=1, rumors=1), topo,
        run, _mesh())
    r_pl, cov_pl, _, _, meta_pl, _ = simulate_until_topo_sparse(
        ProtocolConfig(mode=C.PULL, fanout=1, rumors=1), topo, run,
        _mesh())
    assert cov_ae >= 0.99 and cov_pl >= 0.99
    assert r_ae <= r_pl
    assert meta_ae.reverse_bytes == meta_ae.response_bytes > 0
    assert meta_pl.reverse_bytes == 0
    # 3 messages per delivered request (request + digest + reverse)
    assert msgs_ae == pytest.approx(3.0 * n * r_ae, rel=0.05)


@pytest.mark.slow
def test_topo_dead_nodes_stay_dark():
    n = 256
    fault = FaultConfig(node_death_rate=0.3, seed=9)
    topo = G.erdos_renyi(n, 0.08, seed=5)
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    run = RunConfig(seed=2, max_rounds=40)
    mesh = _mesh()
    step = make_sparse_topo_pull_round(proto, topo, mesh, fault, run.origin)
    st = init_sparse_state(run, proto, n, mesh)
    ovf = jnp.float32(0.0)
    from gossip_tpu.models.state import alive_mask
    alive = np.asarray(alive_mask(fault, n, run.origin))
    for _ in range(16):
        st, ovf = step(st, ovf)
    seen = np.asarray(st.seen)[:n, 0]
    assert not (seen[~alive] != 0).any(), "dead nodes must stay dark"
    assert (seen[alive] != 0).mean() > 0.8


@pytest.mark.slow
def test_backend_routes_explicit_family_to_topo_sparse():
    """run_simulation(exchange='sparse') on an explicit family must take
    the capacity-capped topology path and report its traffic meta."""
    from gossip_tpu.backend import run_simulation
    from gossip_tpu.config import MeshConfig, TopologyConfig
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    tc = TopologyConfig(family="erdos_renyi", n=1024, p=0.01, seed=3)
    run = RunConfig(seed=0, target_coverage=0.99, max_rounds=64)
    rep = run_simulation("jax-tpu", proto, tc, run, None,
                         MeshConfig(n_devices=P8, exchange="sparse"))
    assert rep.coverage >= 0.99
    assert rep.meta["exchange"] == "sparse"
    assert "overflow_dropped_requests" in rep.meta
    assert rep.meta["ici_bytes_per_round"]["sparse"] <= \
        rep.meta["ici_bytes_per_round"]["dense_equivalent"]
    # anti-entropy routes through the same path (round 3); push is
    # rejected loudly, never silently densified
    rep_ae = run_simulation("jax-tpu",
                            ProtocolConfig(mode=C.ANTI_ENTROPY, period=2),
                            tc, run, None,
                            MeshConfig(n_devices=P8, exchange="sparse"))
    assert rep_ae.meta["exchange"] == "sparse"
    assert rep_ae.coverage >= 0.99
    with pytest.raises(ValueError, match="pull and anti-entropy"):
        run_simulation("jax-tpu", ProtocolConfig(mode=C.PUSH),
                       tc, run, None,
                       MeshConfig(n_devices=P8, exchange="sparse"))


@pytest.mark.slow
def test_dead_nodes_never_infected_or_requesting():
    n = 256
    fault = FaultConfig(node_death_rate=0.3, seed=9)
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    run = RunConfig(seed=2, max_rounds=40)
    mesh = _mesh()
    step = make_sparse_pull_round(proto, n, mesh, fault, run.origin)
    st = init_sparse_state(run, proto, n, mesh)
    from gossip_tpu.models.state import alive_mask
    alive = np.asarray(alive_mask(fault, n, run.origin))
    for _ in range(12):
        st = step(st)
    seen = np.asarray(st.seen)[:n, 0]
    assert not (seen[~alive] != 0).any(), "dead nodes must stay dark"
    assert (seen[alive] != 0).mean() > 0.9
