"""Tests for the sparse all_to_all exchange (parallel/sharded_sparse.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.ops.bitpack import coverage_packed, n_words
from gossip_tpu.parallel.sharded import make_mesh
from gossip_tpu.parallel.sharded_sparse import (
    SPARSE_ROW_TAG, _round_draws, _slot_rows, init_sparse_state,
    make_sparse_pull_round, simulate_until_sparse, sparse_meta,
    sparse_pull_round_reference)

P8 = 8


def _mesh():
    return make_mesh(P8)


@pytest.mark.parametrize("mode,fanout,rumors,fault", [
    (C.PULL, 1, 1, None),
    (C.PULL, 2, 40, None),
    (C.PULL, 1, 1, FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=3)),
    (C.ANTI_ENTROPY, 1, 5, None),
])
def test_bitwise_parity_mesh_vs_reference(mode, fanout, rumors, fault):
    """The mesh run and the single-device reference must agree BITWISE for
    several rounds (collectives only move data)."""
    n = 256
    proto = ProtocolConfig(mode=mode, fanout=fanout, rumors=rumors, period=2)
    run = RunConfig(seed=11)
    mesh = _mesh()
    step_m = make_sparse_pull_round(proto, n, mesh, fault, run.origin)
    step_r = sparse_pull_round_reference(proto, n, P8, fault, run.origin)
    st_m = init_sparse_state(run, proto, n, mesh)
    st_r = init_sparse_state(run, proto, n)  # unsharded, same padding (p=1
    # pads to n; mesh pads to n too since 256 % 8 == 0)
    for _ in range(6):
        st_m = step_m(st_m)
        st_r = step_r(st_r)
        np.testing.assert_array_equal(np.asarray(st_m.seen),
                                      np.asarray(st_r.seen))
        assert float(st_m.msgs) == float(st_r.msgs)


def test_partner_marginal_is_uniform():
    """Stratification must leave the per-slot partner marginal uniform over
    all rows: chi-square over many rounds for one fixed slot."""
    p, nl = 8, 32
    n_pad = p * nl
    key = jax.random.key(0)
    slot = jnp.asarray([5], jnp.int32)      # fixed global slot, k=1

    @jax.jit
    @jax.vmap
    def partner_gid(rnd):
        rkey = jax.random.fold_in(key, rnd)
        pi, o = _round_draws(rkey, p)
        shard = pi[(5 + o) % p]
        return shard * nl + _slot_rows(rkey, slot, nl)[0]

    gids = np.asarray(partner_gid(jnp.arange(2000, dtype=jnp.uint32)))
    counts = np.bincount(gids, minlength=n_pad)
    expected = 2000 / n_pad
    chi2 = ((counts - expected) ** 2 / expected).sum()
    # dof = 255; 3-sigma upper bound ~ 255 + 3*sqrt(510) ~ 323
    assert chi2 < 323, chi2


def test_converges_and_traffic_accounting():
    n = 1024
    proto = ProtocolConfig(mode=C.PULL, fanout=2, rumors=40)
    run = RunConfig(seed=0, target_coverage=0.99, max_rounds=64)
    rounds, cov, msgs, final, meta = simulate_until_sparse(
        proto, n, run, _mesh())
    assert cov >= 0.99
    assert 5 <= rounds <= 30
    w = n_words(40)
    nl = n // P8
    assert meta.cap == (nl * 2) // P8
    assert meta.request_bytes == P8 * meta.cap * 4
    assert meta.response_bytes == P8 * meta.cap * 4 * w
    assert meta.dense_bytes == n * 4 * w
    # the whole point: sparse moves less than dense when k < shards*W/(W+1)
    assert meta.sparse_bytes < meta.dense_bytes
    # msgs: 2 per valid request, all nodes alive -> 2*k*n per active round
    assert float(msgs) == pytest.approx(2.0 * 2 * n * rounds)


def test_sparse_matches_dense_pull_statistically():
    """Same protocol, different exchange: rounds-to-99% must agree within
    +/-2 rounds of the dense packed pull path."""
    from gossip_tpu.models.si_packed import simulate_until_packed
    from gossip_tpu.topology import generators as G
    n = 2048
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    run = RunConfig(seed=5, target_coverage=0.99, max_rounds=64)
    r_sparse, cov_s, _, _, _ = simulate_until_sparse(proto, n, run, _mesh())
    r_dense, cov_d, _, _ = simulate_until_packed(proto, G.complete(n), run)
    assert cov_s >= 0.99 and cov_d >= 0.99
    assert abs(r_sparse - r_dense) <= 2, (r_sparse, r_dense)


def test_rejects_push_and_unbalanced():
    mesh = _mesh()
    with pytest.raises(ValueError, match="pull"):
        make_sparse_pull_round(ProtocolConfig(mode=C.PUSH), 256, mesh)
    with pytest.raises(ValueError, match="divide"):
        # nl*k = 4 slots per shard, not divisible by 8 shards
        make_sparse_pull_round(
            ProtocolConfig(mode=C.PULL, fanout=1), 32, mesh)


def test_dead_nodes_never_infected_or_requesting():
    n = 256
    fault = FaultConfig(node_death_rate=0.3, seed=9)
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    run = RunConfig(seed=2, max_rounds=40)
    mesh = _mesh()
    step = make_sparse_pull_round(proto, n, mesh, fault, run.origin)
    st = init_sparse_state(run, proto, n, mesh)
    from gossip_tpu.models.state import alive_mask
    alive = np.asarray(alive_mask(fault, n, run.origin))
    for _ in range(12):
        st = step(st)
    seen = np.asarray(st.seen)[:n, 0]
    assert not (seen[~alive] != 0).any(), "dead nodes must stay dark"
    assert (seen[alive] != 0).mean() > 0.9
