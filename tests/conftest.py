"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-device behavior is tested without TPU hardware the same way the
reference tested multi-node without a cluster — the reference ran N OS
processes on one machine under Maelstrom (SURVEY.md §4); we run 8 virtual XLA
host devices in one process.

Note: this environment preloads jax modules via sitecustomize, so plain env
vars are captured before conftest runs — we must go through
``jax.config.update`` for the platform choice.  XLA_FLAGS is still read at
backend init, which has not happened yet at conftest import time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force CPU even if the surrounding environment points JAX at a TPU tunnel
# (JAX_PLATFORMS=axon): unit tests must be fast and hermetic.  Override with
# GOSSIP_TPU_TEST_PLATFORM=axon to exercise the suite on real hardware (the
# tunnel registers its platform under the name "axon", not "tpu").
jax.config.update("jax_platforms",
                  os.environ.get("GOSSIP_TPU_TEST_PLATFORM", "cpu"))
