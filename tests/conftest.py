"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-device behavior is tested without TPU hardware the same way the
reference tested multi-node without a cluster — the reference ran N OS
processes on one machine under Maelstrom (SURVEY.md §4); we run 8 virtual XLA
host devices in one process.

Note: this environment preloads jax modules via sitecustomize, so plain env
vars are captured before conftest runs — we must go through
``jax.config.update`` for the platform choice.  XLA_FLAGS is still read at
backend init, which has not happened yet at conftest import time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force CPU even if the surrounding environment points JAX at a TPU tunnel
# (JAX_PLATFORMS=axon): unit tests must be fast and hermetic.  Override with
# GOSSIP_TPU_TEST_PLATFORM=axon to exercise the suite on real hardware (the
# tunnel registers its platform under the name "axon", not "tpu").
_platform = os.environ.get("GOSSIP_TPU_TEST_PLATFORM", "cpu")
jax.config.update("jax_platforms", _platform)

# Wedge-immunity for test-spawned subprocesses: the environment's
# sitecustomize registers the TPU-tunnel PJRT plugin in EVERY interpreter
# whose env arms it, and a wedged tunnel hangs that registration — so a
# mid-suite wedge would freeze every test that spawns a child process
# (the Maelstrom harness runs real node processes, and several tests
# re-exec the CLI).  For the CPU tier, disarm the plugin in the
# inherited env via bench.py's _hermetic_cpu_env — imported, not copied,
# so the hazard list (PALLAS_AXON_POOL_IPS, JAX_PLATFORM_NAME,
# LIBTPU_INIT_ARGS, sitecustomize-bearing PYTHONPATH entries) lives in
# exactly one place.  Children neither need nor may touch the tunnel.
# The TPU tier (GOSSIP_TPU_TEST_PLATFORM=axon) keeps the env as-is.
# NOTE this cannot protect the pytest parent itself — if the tunnel is
# already wedged, launch pytest under
# `eval "$(python bench.py --print-hermetic-env)"`.
if _platform == "cpu":
    import sys
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(_repo, "tools"))
    try:
        from _bench import hermetic_cpu_env as _hermetic_cpu_env
    finally:
        sys.path.pop(0)
    _henv = _hermetic_cpu_env()
    for _k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORM_NAME",
               "LIBTPU_INIT_ARGS"):
        os.environ.pop(_k, None)
    for _k in ("PYTHONPATH", "JAX_PLATFORMS"):
        os.environ[_k] = _henv[_k]

# Compile-once session cache (utils/compile_cache), EVERY tier: one
# cache dir shared by every test-spawned CHILD, so the suite's
# subprocess-heavy tests (CLI re-execs, checkpoint resumes, the
# dry-run contract's cold+warm pair) compile each program once per
# SESSION instead of once per child — what un-slowed the compile-heavy
# resume tests back into tier-1.  Setting it on the axon tier too is a
# guard, not an optimization: without it, CLI children would fall
# through to cli.py's ~/.cache default and write the OPERATOR'S
# persistent cache (the hazard the pre-compile-once "" pin protected
# on every tier).  Tests that must measure cold compiles pin "" (or
# pass explicit --compile-cache flags) in their own child envs, which
# override this.  The PERSISTENT XLA layer is deliberately NOT
# enabled in the pytest process itself (no jax.config update here):
# one in-process persistent-cache HIT permanently breaks executable
# DESERIALIZATION for the whole process on this toolchain ("Symbols
# not found" — utils/compile_cache module doc), which would poison
# the AOT-store tests that must observe real miss->hit round-trips
# in-process.  The AOT STORE, by contrast, is ambient in-process via
# this env var (trace.aot_timed reads it) and safely so: store hits
# are bitwise-identical executables by contract, and tests that
# assert store choreography pin their own dir over this one.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_pinned_cache = os.environ.get("GOSSIP_TPU_TEST_COMPILE_CACHE")
if _pinned_cache:
    # caller-owned dir for cross-session reuse during local iteration
    os.environ["GOSSIP_COMPILE_CACHE"] = _pinned_cache
else:
    import atexit
    import shutil
    import tempfile
    _session_cache = tempfile.mkdtemp(prefix="gossip_test_compile_cache_")
    os.environ["GOSSIP_COMPILE_CACHE"] = _session_cache
    # a session's cache holds the whole suite's XLA entries + AOT
    # executables (multi-MB) — reap it ourselves rather than betting
    # on /tmp aging
    atexit.register(shutil.rmtree, _session_cache, ignore_errors=True)


# ---------------------------------------------------------------------
# Per-test duration ledger + tier-1 wall headroom warning.
#
# The tier-1 gate is a hard 870 s timeout (ROADMAP.md) that the suite
# approaches silently: every PR adds a test or two, nothing tracks the
# total, and the PR that finally crosses the line fails with an opaque
# `timeout` instead of a named culprit.  So the session records its own
# flight data — one `test` event per test with its wall, a `session`
# summary with the slowest offenders — through the same run-ledger
# layer everything else uses (utils/telemetry), and WARNS at 90% of the
# gate so the rebalance happens one PR early, not one PR late.

import sys  # noqa: E402
import time as _time  # noqa: E402

import pytest  # noqa: E402  (imported after the platform pinning above)

TIER1_GATE_S = 870.0
TIER1_WARN_FRACTION = 0.9

_session_t0 = _time.perf_counter()
_test_walls: dict = {}


def tier1_wall_warning(total_s: float, gate_s: float = TIER1_GATE_S,
                       frac: float = TIER1_WARN_FRACTION):
    """The warning line when a session's wall crosses ``frac`` of the
    tier-1 gate, else None — a plain predicate so the threshold
    arithmetic is unit-testable without running an 800 s session
    (the sweep_cache_eviction pattern)."""
    if total_s <= frac * gate_s:
        return None
    return (f"WARNING: test session wall {total_s:.0f} s exceeds "
            f"{frac:.0%} of the {gate_s:.0f} s tier-1 gate — rebalance "
            "now (mark redundant depth tests `slow`, keep one smoke "
            "per surface) instead of letting the NEXT PR trip the "
            "timeout; per-test walls are in the session ledger "
            "($GOSSIP_TEST_LEDGER, default artifacts/"
            "ledger_tests.jsonl)")


def pytest_runtest_logreport(report):
    # setup + call + teardown all count toward the wall the gate sees
    _test_walls[report.nodeid] = (_test_walls.get(report.nodeid, 0.0)
                                  + report.duration)


def pytest_sessionfinish(session, exitstatus):
    total = _time.perf_counter() - _session_t0
    path = os.environ.get("GOSSIP_TEST_LEDGER")
    explicit = path is not None
    if path is None:
        path = os.path.join(_REPO, "artifacts", "ledger_tests.jsonl")
    if not path:            # explicit "" disables (the GOSSIP_TELEMETRY
        return              # convention)
    try:
        from gossip_tpu.utils import telemetry
        # the ONE provenance-stamping artifact-ledger helper
        # (telemetry.artifact_ledger), shared with the staticcheck
        # findings writer so the choreography cannot drift.  The
        # default path is per-session flight data, rewritten every
        # session (the .gitignore contract) — only an explicit
        # $GOSSIP_TEST_LEDGER appends, so a caller can aggregate
        # several sessions into one shared ledger.  fsync=False
        # (helper default): flush-only is plenty for test flight
        # data, and ~300 per-event fsyncs would tax the very wall
        # being measured.
        with telemetry.artifact_ledger(path,
                                       rewrite=not explicit) as led:
            for nodeid, wall in sorted(_test_walls.items(),
                                       key=lambda kv: -kv[1]):
                led.event("test", nodeid=nodeid,
                          wall_s=round(wall, 3))
            led.event("session", exitstatus=int(exitstatus),
                      tests=len(_test_walls),
                      wall_s=round(total, 1),
                      gate_s=TIER1_GATE_S,
                      over_warn_threshold=bool(
                          tier1_wall_warning(total)))
    except Exception as e:      # the recorder must never fail the suite
        sys.stderr.write(f"conftest: test ledger disabled ({e})\n")


def pytest_terminal_summary(terminalreporter):
    msg = tier1_wall_warning(_time.perf_counter() - _session_t0)
    if msg:
        terminalreporter.write_line(msg, yellow=True, bold=True)


# ---------------------------------------------------------------------
# The 4-device cold+warm dry-run pair, session-scoped: ONE pair serves
# every consumer — the dry-run contract tests (tests/test_graft_entry)
# and the ledger_diff regression gate (tests/test_ledger_diff) — so
# tier-1 pays the two ~30 s runs exactly once.

def _load_graft_entry():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(_REPO, "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------
# Compile-count delta probe (the traced-operand PR): PR 3's
# JitCompileMonitor, wrapped as a reusable context so tests can pin
# "K scenarios, ONE compile" without copy-pasting monitoring plumbing.

@pytest.fixture(scope="session")
def _compile_monitor():
    # one per process: jax's listener registration is permanent
    from gossip_tpu.utils.compile_cache import JitCompileMonitor
    return JitCompileMonitor()


@pytest.fixture
def assert_compiles(_compile_monitor):
    """``with assert_compiles(n):`` — assert the block triggered exactly
    ``n`` REAL XLA backend compiles (jax.monitoring's per-compile
    duration event; in-memory executable reuse triggers none).  Pass
    ``at_most=True`` for an upper bound — the right form for "the
    first call may compile auxiliaries, later calls must compile
    NOTHING" pins.  Skips when this jax cannot report backend-compile
    events (the monitor's degrade path)."""
    import contextlib

    mon = _compile_monitor
    if not mon.durations_available:
        pytest.skip("jax.monitoring has no duration listener on this "
                    "toolchain; compile-count pins unavailable")

    @contextlib.contextmanager
    def _ctx(expected: int, at_most: bool = False):
        before = mon.backend_compiles
        yield
        got = mon.backend_compiles - before
        if at_most:
            assert got <= expected, (
                f"block compiled {got} XLA programs, expected at most "
                f"{expected} — a memoized loop lost its cache hit "
                "(schedule content leaked back into a trace?)")
        else:
            assert got == expected, (
                f"block compiled {got} XLA programs, expected exactly "
                f"{expected}")
    return _ctx


@pytest.fixture(scope="session")
def dryrun_pair(tmp_path_factory):
    """(cold, warm) 4-device dry runs sharing ONE fresh compile-cache
    dir — the cross-process warm-start proof: process A populates the
    cache, process B (expect_warm=True: the body ENFORCES the
    first_warm_ms budgets) must hit it.  4 devices for tier-1 wall
    budget; the full 8-device shape with the >= 3x acceptance ratio is
    pinned on the committed records (tests/test_graft_entry).  Each run
    keeps its own ledger; both carry round-metrics events for the
    driver-level families (ops/round_metrics — the dry-run ledger is
    always on)."""
    graft_entry = _load_graft_entry()
    tmp = tmp_path_factory.mktemp("dryrun_cc")
    cache = str(tmp / "compile_cache")
    cold_ledger = str(tmp / "cold_ledger.jsonl")
    warm_ledger = str(tmp / "warm_ledger.jsonl")
    cold = graft_entry.dryrun_multichip(4, ledger_path=cold_ledger,
                                        compile_cache_dir=cache)
    warm = graft_entry.dryrun_multichip(4, ledger_path=warm_ledger,
                                        compile_cache_dir=cache,
                                        expect_warm=True)
    return {"cold": cold, "warm": warm, "cache": cache}
