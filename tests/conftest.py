"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-device behavior is tested without TPU hardware the same way the
reference tested multi-node without a cluster — the reference ran N OS
processes on one machine under Maelstrom (SURVEY.md §4); we run 8 virtual XLA
host devices in one process.

Note: this environment preloads jax modules via sitecustomize, so plain env
vars are captured before conftest runs — we must go through
``jax.config.update`` for the platform choice.  XLA_FLAGS is still read at
backend init, which has not happened yet at conftest import time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force CPU even if the surrounding environment points JAX at a TPU tunnel
# (JAX_PLATFORMS=axon): unit tests must be fast and hermetic.  Override with
# GOSSIP_TPU_TEST_PLATFORM=axon to exercise the suite on real hardware (the
# tunnel registers its platform under the name "axon", not "tpu").
_platform = os.environ.get("GOSSIP_TPU_TEST_PLATFORM", "cpu")
jax.config.update("jax_platforms", _platform)

# Wedge-immunity for test-spawned subprocesses: the environment's
# sitecustomize registers the TPU-tunnel PJRT plugin in EVERY interpreter
# whose env arms it, and a wedged tunnel hangs that registration — so a
# mid-suite wedge would freeze every test that spawns a child process
# (the Maelstrom harness runs real node processes, and several tests
# re-exec the CLI).  For the CPU tier, disarm the plugin in the
# inherited env via bench.py's _hermetic_cpu_env — imported, not copied,
# so the hazard list (PALLAS_AXON_POOL_IPS, JAX_PLATFORM_NAME,
# LIBTPU_INIT_ARGS, sitecustomize-bearing PYTHONPATH entries) lives in
# exactly one place.  Children neither need nor may touch the tunnel.
# The TPU tier (GOSSIP_TPU_TEST_PLATFORM=axon) keeps the env as-is.
# NOTE this cannot protect the pytest parent itself — if the tunnel is
# already wedged, launch pytest under
# `eval "$(python bench.py --print-hermetic-env)"`.
if _platform == "cpu":
    import sys
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(_repo, "tools"))
    try:
        from _bench import hermetic_cpu_env as _hermetic_cpu_env
    finally:
        sys.path.pop(0)
    _henv = _hermetic_cpu_env()
    for _k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORM_NAME",
               "LIBTPU_INIT_ARGS"):
        os.environ.pop(_k, None)
    for _k in ("PYTHONPATH", "JAX_PLATFORMS"):
        os.environ[_k] = _henv[_k]

# Compile-once session cache (utils/compile_cache), EVERY tier: one
# cache dir shared by every test-spawned CHILD, so the suite's
# subprocess-heavy tests (CLI re-execs, checkpoint resumes, the
# dry-run contract's cold+warm pair) compile each program once per
# SESSION instead of once per child — what un-slowed the compile-heavy
# resume tests back into tier-1.  Setting it on the axon tier too is a
# guard, not an optimization: without it, CLI children would fall
# through to cli.py's ~/.cache default and write the OPERATOR'S
# persistent cache (the hazard the pre-compile-once "" pin protected
# on every tier).  Tests that must measure cold compiles pin "" (or
# pass explicit --compile-cache flags) in their own child envs, which
# override this.  The PERSISTENT XLA layer is deliberately NOT
# enabled in the pytest process itself (no jax.config update here):
# one in-process persistent-cache HIT permanently breaks executable
# DESERIALIZATION for the whole process on this toolchain ("Symbols
# not found" — utils/compile_cache module doc), which would poison
# the AOT-store tests that must observe real miss->hit round-trips
# in-process.  The AOT STORE, by contrast, is ambient in-process via
# this env var (trace.aot_timed reads it) and safely so: store hits
# are bitwise-identical executables by contract, and tests that
# assert store choreography pin their own dir over this one.
_pinned_cache = os.environ.get("GOSSIP_TPU_TEST_COMPILE_CACHE")
if _pinned_cache:
    # caller-owned dir for cross-session reuse during local iteration
    os.environ["GOSSIP_COMPILE_CACHE"] = _pinned_cache
else:
    import atexit
    import shutil
    import tempfile
    _session_cache = tempfile.mkdtemp(prefix="gossip_test_compile_cache_")
    os.environ["GOSSIP_COMPILE_CACHE"] = _session_cache
    # a session's cache holds the whole suite's XLA entries + AOT
    # executables (multi-MB) — reap it ourselves rather than betting
    # on /tmp aging
    atexit.register(shutil.rmtree, _session_cache, ignore_errors=True)
