"""Multi-slice (DCN) hybrid mesh tests (parallel/multislice.py).

Runs on the 8-device virtual CPU mesh (conftest) — the degenerate
single-slice case of the hybrid layout, which is the point: the same
mesh/program shapes compile on a real multi-slice pod.
"""

import numpy as np
import pytest

import jax

from gossip_tpu.config import ProtocolConfig, RunConfig, TopologyConfig
from gossip_tpu.parallel.multislice import (detect_slices,
                                            device_slice_index,
                                            make_hybrid_mesh,
                                            maybe_init_distributed)


def test_detect_slices_cpu():
    assert detect_slices() == 1
    assert all(device_slice_index(d) == 0 for d in jax.devices())


def test_hybrid_mesh_shapes_and_errors():
    mesh = make_hybrid_mesh(2, 4)
    assert mesh.shape == {"sweep": 2, "nodes": 4}
    assert mesh.devices.shape == (2, 4)
    # all 8 devices present exactly once
    assert (sorted(d.id for d in mesh.devices.ravel())
            == sorted(d.id for d in jax.devices()[:8]))
    with pytest.raises(ValueError, match="devices"):
        make_hybrid_mesh(4, 4)
    with pytest.raises(ValueError, match=">= 1"):
        make_hybrid_mesh(0, 4)


# slow tier (tier-1 wall budget): hybrid-mesh 2-D equivalence also
# runs in test_config_sweep's gated 2d_pod_sweep[complete] path
@pytest.mark.slow
def test_hybrid_mesh_runs_2d_sweep_identically():
    # the 2-D pod sweep on a hybrid mesh must reproduce the unsharded
    # batch exactly (config_sweep_curves_2d's mesh-invariance contract)
    from gossip_tpu.parallel.sweep import (SweepPoint, config_sweep_curves,
                                           config_sweep_curves_2d)
    from gossip_tpu.topology import generators as G
    topo = G.ring(256, k=4)
    run = RunConfig(seed=3, max_rounds=12)
    pts = [SweepPoint(mode=m, fanout=f, drop_prob=d, period=1, seed=5)
           for m in ("push", "pull") for f in (1, 2) for d in (0.0, 0.2)]
    mesh = make_hybrid_mesh(2, 4, axis_names=("sweep", "nodes"))
    got = config_sweep_curves_2d(pts, topo, run, mesh)
    want = config_sweep_curves(pts, topo, run)
    np.testing.assert_array_equal(got.curves, want.curves)
    np.testing.assert_array_equal(got.msgs, want.msgs)


def test_maybe_init_distributed_noop_without_env(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("GOSSIP_TPU_MULTIHOST", raising=False)
    assert maybe_init_distributed() is False


class _FakeDev:
    def __init__(self, id, slice_index):
        self.id = id
        self.slice_index = slice_index

    def __repr__(self):
        return f"dev{self.id}@slice{self.slice_index}"


def test_hybrid_grid_groups_by_slice():
    """On (simulated) multi-slice hardware every mesh row must be one
    slice — including SUB-POD meshes (fewer slices / fewer chips per
    slice than the reservation)."""
    from gossip_tpu.parallel.multislice import _hybrid_device_grid
    # 2 slices x 4 chips, interleaved enumeration order on purpose
    devs = [_FakeDev(i, slice_index=i % 2) for i in range(8)]
    grid = _hybrid_device_grid(devs, 2, 4)
    assert grid.shape == (2, 4)
    for row in grid:
        assert len({d.slice_index for d in row}) == 1   # no DCN inside a row
    assert {d.slice_index for d in grid[:, 0]} == {0, 1}
    # sub-pod: one slice of the reservation, 2 chips of it
    sub = _hybrid_device_grid(devs, 1, 2)
    assert sub.shape == (1, 2)
    assert len({d.slice_index for d in sub.ravel()}) == 1
    # 2x2 sub-pod: 2 chips from each slice
    sub22 = _hybrid_device_grid(devs, 2, 2)
    assert all(len({d.slice_index for d in row}) == 1 for row in sub22)
    # more slices than the platform has
    with pytest.raises(ValueError, match="DCN slices"):
        _hybrid_device_grid(devs, 3, 2)
    # inner axis cannot cross DCN (5 > the 4 devices slice 0 has, even
    # though 1x5 = 5 <= 8 total)
    with pytest.raises(ValueError, match="must not cross"):
        _hybrid_device_grid(devs, 1, 5)
