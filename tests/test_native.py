"""C++ event-sim core == Python gonative, event-for-event.

The Python GoNativeSim is the readable semantics contract; the native core
must reproduce its deliveries (times, nodes, hops), logs, message counts,
and hop depths exactly on shared scenarios — including partitions and both
context-bug modes — or it has no business existing."""

import pytest

from gossip_tpu.runtime.gonative import (GoNativeSim, NetConfig,
                                         topology_from_table)
from gossip_tpu.runtime.native_sim import (NativeGoSim, make_event_sim,
                                           native_available)
from gossip_tpu.topology import generators as G

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="g++ unavailable")


def run_pair(topology, scenario, net=NetConfig(), horizon=120.0):
    out = []
    for cls in (GoNativeSim, NativeGoSim):
        sim = cls(topology, net=net, horizon=horizon)
        scenario(sim)
        sim.run()
        out.append(sim)
    return out


def assert_equivalent(py, cc, messages, n):
    assert py.msgs_sent == cc.msgs_sent
    for m in messages:
        assert py.hop_depths(m) == cc.hop_depths(m), f"hop depths msg {m}"
    for i in range(n):
        assert py.read(i) == cc.read(i), f"log node {i}"
    pd = sorted(py.deliveries)
    cd = sorted(cc.deliveries)
    assert len(pd) == len(cd)
    for (t1, n1, m1, h1), (t2, n2, m2, h2) in zip(pd, cd):
        assert (n1, m1, h1) == (n2, m2, h2)
        assert t1 == pytest.approx(t2, abs=1e-9)


def test_equivalence_er_graph():
    topo = topology_from_table(G.erdos_renyi(512, 0.015, seed=4))

    def scen(sim):
        sim.broadcast(0, 42)
        sim.broadcast(100, 7, t=0.003)

    py, cc = run_pair(topo, scen)
    assert_equivalent(py, cc, [42, 7], 512)


def test_equivalence_with_partitions_faithful_and_fixed():
    topo = {0: [1], 1: [0, 2, 3], 2: [1], 3: [1]}
    for faithful in (True, False):
        net = NetConfig(faithful_ctx_bug=faithful)

        def scen(sim):
            sim.partition(1, 2, 0.0, 5.0)
            sim.broadcast(0, 1)

        py, cc = run_pair(topo, scen, net=net, horizon=60.0)
        assert_equivalent(py, cc, [1], 4)


def test_equivalence_dedup_and_duplicate_injection():
    topo = {0: [1], 1: [0]}

    def scen(sim):
        sim.broadcast(0, 9)
        sim.broadcast(0, 9, t=1.0)     # duplicate client injection

    py, cc = run_pair(topo, scen)
    assert_equivalent(py, cc, [9], 2)


def test_native_is_actually_faster():
    import time
    topo = topology_from_table(G.watts_strogatz(2048, 6, 0.1, seed=2))

    def scen(sim):
        for i in range(20):
            sim.broadcast(i * 97 % 2048, i, t=0.0005 * i)

    t0 = time.perf_counter()
    py = GoNativeSim(topo)
    scen(py)
    py.run()
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    cc = NativeGoSim(topo)
    scen(cc)
    cc.run()
    t_cc = time.perf_counter() - t0
    assert py.msgs_sent == cc.msgs_sent
    assert t_cc < t_py, (t_cc, t_py)   # typically 20-100x


def test_factory_fallback():
    sim = make_event_sim({0: [1], 1: [0]}, prefer_native=False)
    assert isinstance(sim, GoNativeSim)
    sim2 = make_event_sim({0: [1], 1: [0]}, prefer_native=True)
    assert isinstance(sim2, NativeGoSim)
