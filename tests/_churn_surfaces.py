"""The churn-trajectory fingerprint surfaces — shared by the golden
capture (run against the PR 5 baked-schedule code) and the tier-1 pin
test (run against the traced-operand code).

Promoting the nemesis schedule tables from in-trace constants to
runtime operands must be a pure re-plumbing: every converted surface's
trajectory — dense single + sharded, packed, sparse (mesh + reference
twin), rumor, halo, SWIM — must stay BITWISE what the baked lowering
produced.  Each surface below runs a small fixed config and digests
its outputs (sha256 over the raw array bytes) so the whole matrix pins
in one JSON file, tests/data/churn_fingerprints_r06.json, captured
once from the pre-refactor tree.  A no-churn twin per family rides
along: the static hot path must not move either.

Configs are tiny (n=64, <= 12 rounds) and the digests depend only on
the threefry streams + kernel arithmetic, which are platform-stable on
the CPU tier the fingerprints were captured on.

These digests also serve as the no-CRDT regression guard (the CRDT
payload PR): the CRDT subsystem rides the exchange fabric — same
sampling streams, drop coins, partition cuts — without moving any
existing broadcast/rumor/SWIM trajectory.  tests/test_crdt.py
re-verifies packed_sharded IN-GATE (on top of test_nemesis's
dense_sharded pin; rumor_single + packed_single in its ``-m slow``
twin), and the full matrix runs under test_nemesis's slow-tier pin.
"""

import hashlib
import json
import os

import numpy as np

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "data", "churn_fingerprints_r06.json")

_N = 64
_ROUNDS = 10


def _digest(*arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        a = np.asarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _churn_fault():
    from gossip_tpu.config import ChurnConfig, FaultConfig
    return FaultConfig(node_death_rate=0.1, drop_prob=0.05, seed=1,
                       churn=ChurnConfig(
                           events=((3, 2, 5), (7, 1, -1)),
                           partitions=((2, 6, 32),),
                           ramp=(1, 4, 0.0, 0.3)))


def _swim_fault():
    # SWIM supported events only at capture time (PR 5): the golden
    # timeline is events + static drop, no ramp/partitions
    from gossip_tpu.config import ChurnConfig, FaultConfig
    return FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
        events=((5, 2, -1), (3, 4, 6))))


def _static_fault():
    from gossip_tpu.config import FaultConfig
    return FaultConfig(node_death_rate=0.1, drop_prob=0.05, seed=1)


def _mesh(k=4):
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(k)


def _run(max_rounds=_ROUNDS):
    from gossip_tpu.config import RunConfig
    return RunConfig(seed=0, max_rounds=max_rounds)


def _dense_single(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.runtime.simulator import simulate_curve
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    res = simulate_curve(proto, G.complete(_N), _run(), fault)
    return _digest(res.coverage, res.msgs, res.state.seen)


def _dense_flood_single(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.runtime.simulator import simulate_curve
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.FLOOD, fanout=1, rumors=2)
    res = simulate_curve(proto, G.ring(_N, k=4), _run(), fault)
    return _digest(res.coverage, res.msgs, res.state.seen)


def _dense_sharded(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.parallel.sharded import simulate_curve_sharded
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    covs, msgs, fin = simulate_curve_sharded(proto, G.complete(_N),
                                             _run(), _mesh(), fault)
    return _digest(covs, msgs, fin.seen)


def _packed_single(fault):
    import jax
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.models.si_packed import (init_packed_state,
                                             make_packed_round)
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=2, rumors=3,
                           period=2)
    step = jax.jit(NE.drop_lost(
        make_packed_round(proto, G.complete(_N), fault, 0),
        NE.get(fault)))
    s = init_packed_state(_run(), proto, _N)
    for _ in range(6):
        s = step(s)
    return _digest(s.seen, np.float32(float(s.msgs)))


def _packed_sharded(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.parallel.sharded_packed import (
        simulate_until_packed_sharded)
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=3)
    rounds, cov, msgs, fin = simulate_until_packed_sharded(
        proto, G.complete(_N), _run(), _mesh(), fault)
    return _digest(fin.seen, np.int32(rounds), np.float32(cov),
                   np.float32(msgs))


def _sparse_mesh(fault):
    import jax
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.parallel.sharded_sparse import (
        init_sparse_state, make_sparse_pull_round)
    proto = ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=2, rumors=3,
                           period=2)
    step = jax.jit(make_sparse_pull_round(proto, _N, _mesh(), fault, 0))
    s = init_sparse_state(_run(), proto, _N, _mesh())
    lost = []
    for _ in range(4):
        out = step(s)
        s, lo = out if type(out) is tuple else (out, 0.0)
        lost.append(float(lo))
    return _digest(s.seen, np.asarray(lost, np.float32),
                   np.float32(float(s.msgs)))


def _sparse_reference(fault):
    import jax
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.parallel.sharded_sparse import (
        init_sparse_state, sparse_pull_round_reference)
    proto = ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=2, rumors=3,
                           period=2)
    step = jax.jit(sparse_pull_round_reference(proto, _N, 4, fault, 0))
    s = init_sparse_state(_run(), proto, _N, p=4)
    lost = []
    for _ in range(4):
        out = step(s)
        s, lo = out if type(out) is tuple else (out, 0.0)
        lost.append(float(lo))
    return _digest(s.seen, np.asarray(lost, np.float32),
                   np.float32(float(s.msgs)))


def _rumor_single(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.models.rumor import simulate_curve_rumor
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.RUMOR, fanout=2, rumor_k=2, rumors=2)
    covs, hots, msgs, fin = simulate_curve_rumor(
        proto, G.complete(_N), _run(), fault)
    return _digest(covs, hots, msgs, fin.seen, fin.hot, fin.cnt)


def _rumor_sharded(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.parallel.sharded_rumor import (
        simulate_curve_rumor_sharded)
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.RUMOR, fanout=2, rumor_k=2, rumors=2)
    covs, hots, msgs, fin = simulate_curve_rumor_sharded(
        proto, G.complete(_N), _run(), _mesh(), fault)
    return _digest(covs, hots, msgs, fin.seen, fin.hot, fin.cnt)


def _halo_sharded(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.parallel.halo import simulate_curve_halo
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    covs, msgs, fin, band = simulate_curve_halo(
        proto, G.ring(_N, k=4), _run(), _mesh(), fault)
    return _digest(covs, msgs, fin.seen, np.int32(band))


def _swim_single(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.runtime.simulator import simulate_swim_curve
    proto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=8,
                           swim_proxies=2, swim_suspect_rounds=4)
    fr, fin = simulate_swim_curve(proto, _N, 12, dead_nodes=(),
                                  fail_round=0, fault=fault)
    return _digest(fr, fin.wire, fin.timer, np.float32(float(fin.msgs)))


def _swim_sharded(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.runtime.simulator import simulate_swim_curve
    proto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=8,
                           swim_proxies=2, swim_suspect_rounds=4)
    fr, fin = simulate_swim_curve(proto, _N, 12, dead_nodes=(),
                                  fail_round=0, fault=fault,
                                  mesh=_mesh())
    return _digest(fr, fin.wire, fin.timer, np.float32(float(fin.msgs)))


def _ckpt_path(name):
    """A throwaway checkpoint path whose directory is removed at
    process exit — the fingerprint runs must not litter the temp dir
    with one npz per surface per run."""
    import atexit
    import shutil
    import tempfile
    d = tempfile.mkdtemp(prefix="ckpt_fp_")
    atexit.register(shutil.rmtree, d, ignore_errors=True)
    return os.path.join(d, name + ".npz")


def _ckpt_si_static(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.models.si import coverage, make_si_round
    from gossip_tpu.models.state import alive_mask, init_state
    from gossip_tpu.topology import generators as G
    from gossip_tpu.utils.checkpoint import run_with_checkpoints
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    topo = G.complete(_N)
    run = _run(8)
    step, tables = make_si_round(proto, topo, fault, 0, tabled=True)

    def curve_fn(s):
        return coverage(s.seen, alive_mask(fault, _N, 0))

    fin, curve = run_with_checkpoints(
        step, init_state(run, proto, _N), 8, _ckpt_path("si"), every=3,
        step_args=tables, curve_fn=curve_fn)
    return _digest(fin.seen, np.float32(float(fin.msgs)),
                   np.int32(int(fin.round)), np.float64(curve))


def _ckpt_packed_static(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.parallel.sharded_packed import (
        checkpointed_packed_sharded)
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=3)
    fin, cov, curve = checkpointed_packed_sharded(
        proto, G.complete(_N), _run(8), _mesh(), _ckpt_path("packed"),
        every=3, fault=fault, want_curve=True)
    return _digest(fin.seen, np.float32(float(fin.msgs)),
                   np.float64(cov), np.float64(curve))


def _ckpt_rumor_static(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.models.rumor import checkpointed_rumor
    from gossip_tpu.topology import generators as G
    proto = ProtocolConfig(mode=C.RUMOR, fanout=2, rumors=2, rumor_k=3)
    fin, cov, residue, curve = checkpointed_rumor(
        proto, G.complete(_N), _run(8), _ckpt_path("rumor"), every=3,
        fault=fault, want_curve=True)
    return _digest(fin.seen, fin.hot, fin.cnt,
                   np.float32(float(fin.msgs)), np.float64(cov),
                   np.float64(curve["coverage"]),
                   np.float64(curve["hot"]))


def _ckpt_swim_static(fault):
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.runtime.simulator import checkpointed_swim
    proto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=8,
                           swim_proxies=2, swim_suspect_rounds=4)
    fin, det, curve = checkpointed_swim(
        proto, _N, _run(10), _ckpt_path("swim"), every=4,
        dead_nodes=(5,), fail_round=2, fault=fault, want_curve=True)
    return _digest(fin.wire, fin.timer, np.float32(float(fin.msgs)),
                   np.float64(det), np.float64(curve))


def _ckpt_fused_static(fault):
    from gossip_tpu.config import RunConfig
    from gossip_tpu.parallel.sharded_fused import (
        checkpointed_fused_planes, make_plane_mesh)
    fin, cov, curve = checkpointed_fused_planes(
        _N, 2, RunConfig(seed=0, max_rounds=8), make_plane_mesh(2),
        _ckpt_path("fused"), every=3, interpret=True, fault=fault,
        want_curve=True)
    return _digest(fin.table, np.float32(float(fin.msgs)),
                   np.float64(cov), np.float64(curve))


# The no-churn checkpointed drivers, digested straight through their
# public entry points (PR 7): lifting the nemesis rejection off the
# checkpointed segment drivers must leave every EXISTING checkpointed
# trajectory — state, message accounting, curve capture — bitwise
# untouched.  Captured from the pre-lift tree (git HEAD at PR 7 start),
# appended to the same data file under "ckpt-static:*" keys.
CHECKPOINTED_SURFACES = {
    "ckpt_si": _ckpt_si_static,
    "ckpt_packed": _ckpt_packed_static,
    "ckpt_rumor": _ckpt_rumor_static,
    "ckpt_swim": _ckpt_swim_static,
    "ckpt_fused": _ckpt_fused_static,
}


# name -> (runner, fault builder).  SWIM takes its events-only schedule
# (ramps were rejected at capture time); every other churn surface runs
# the full events + partition + ramp program.
SURFACES = {
    "dense_single": (_dense_single, _churn_fault),
    "dense_flood_single": (_dense_flood_single, _churn_fault),
    "dense_sharded": (_dense_sharded, _churn_fault),
    "packed_single": (_packed_single, _churn_fault),
    "packed_sharded": (_packed_sharded, _churn_fault),
    "sparse_mesh": (_sparse_mesh, _churn_fault),
    "sparse_reference": (_sparse_reference, _churn_fault),
    "rumor_single": (_rumor_single, _churn_fault),
    "rumor_sharded": (_rumor_sharded, _churn_fault),
    "halo_sharded": (_halo_sharded, _churn_fault),
    "swim_single": (_swim_single, _swim_fault),
    "swim_sharded": (_swim_sharded, _swim_fault),
}

# the static-fault (no churn) twins: the untouched hot path, re-pinned
NO_CHURN = {
    "dense_single", "dense_sharded", "packed_single", "packed_sharded",
    "sparse_mesh", "sparse_reference", "rumor_single", "rumor_sharded",
    "halo_sharded", "swim_single", "swim_sharded",
}


def compute_all() -> dict:
    out = {}
    for name, (runner, fault_of) in SURFACES.items():
        out[f"churn:{name}"] = runner(fault_of())
    for name in sorted(NO_CHURN):
        runner, _ = SURFACES[name]
        out[f"static:{name}"] = runner(_static_fault())
    return out


def main():
    os.makedirs(os.path.dirname(DATA), exist_ok=True)
    digests = compute_all()
    with open(DATA, "w") as f:
        json.dump({"note": "captured from the PR 5 baked-schedule tree; "
                           "the traced-operand lowering must reproduce "
                           "every digest bitwise",
                   "n": _N, "digests": digests}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
    print(f"wrote {len(digests)} fingerprints to {DATA}")


if __name__ == "__main__":
    main()
