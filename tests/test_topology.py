"""Topology generators: structural invariants of the padded tables."""

import numpy as np
import pytest

from gossip_tpu import topology as T


def check_table(topo, n):
    nbrs = np.asarray(topo.nbrs)
    deg = np.asarray(topo.deg)
    assert nbrs.shape[0] == n and deg.shape == (n,)
    cols = np.arange(nbrs.shape[1])
    # entries below deg are real node ids; at/above deg are the sentinel n
    valid = cols[None, :] < deg[:, None]
    assert ((nbrs < n) == valid).all()
    # no self loops
    assert (nbrs != np.arange(n)[:, None]).all()


def as_edge_set(topo):
    nbrs = np.asarray(topo.nbrs)
    deg = np.asarray(topo.deg)
    n = topo.n
    edges = set()
    for i in range(n):
        for j in nbrs[i, : deg[i]]:
            edges.add((i, int(j)))
    return edges


def test_ring():
    topo = T.ring(10, k=4)
    check_table(topo, 10)
    edges = as_edge_set(topo)
    assert (0, 1) in edges and (0, 9) in edges and (0, 2) in edges
    assert (0, 3) not in edges
    # symmetric
    assert all((b, a) in edges for a, b in edges)


def test_complete_table():
    topo = T.complete_table(6)
    check_table(topo, 6)
    assert len(as_edge_set(topo)) == 6 * 5


def test_complete_implicit():
    topo = T.complete(10_000_000)
    assert topo.implicit and topo.n == 10_000_000 and topo.nbrs is None


def test_grid():
    topo = T.grid2d(3, 4)
    check_table(topo, 12)
    edges = as_edge_set(topo)
    assert (0, 1) in edges and (0, 4) in edges
    assert (3, 4) not in edges  # no wraparound across row boundary
    deg = np.asarray(topo.deg)
    assert deg[0] == 2 and deg[5] == 4  # corner vs interior


def test_erdos_renyi_stats():
    n, p = 2000, 0.01
    topo = T.erdos_renyi(n, p, seed=1)
    check_table(topo, n)
    edges = as_edge_set(topo)
    assert all((b, a) in edges for a, b in edges)
    mean_deg = np.asarray(topo.deg).mean()
    expect = (n - 1) * p
    assert abs(mean_deg - expect) / expect < 0.15


def test_watts_strogatz():
    n = 500
    topo = T.watts_strogatz(n, k=6, beta=0.2, seed=2)
    check_table(topo, n)
    edges = as_edge_set(topo)
    assert all((b, a) in edges for a, b in edges)
    # degree conserved on average (rewiring moves, never removes, edges)
    assert abs(np.asarray(topo.deg).mean() - 6.0) < 0.5


def test_power_law():
    n = 2000
    topo = T.power_law(n, m=3, seed=3)
    check_table(topo, n)
    edges = as_edge_set(topo)
    assert all((b, a) in edges for a, b in edges)
    deg = np.asarray(topo.deg)
    # heavy tail: max degree far above the median
    assert deg.max() > 5 * np.median(deg)
    assert (deg > 0).all()


def test_degree_cap():
    topo = T.power_law(1000, m=3, seed=4, degree_cap=10)
    check_table(topo, 1000)
    assert np.asarray(topo.deg).max() <= 10
    assert topo.nbrs.shape[1] <= 10


def test_build_dispatch():
    from gossip_tpu.config import TopologyConfig
    for family, kw in [
        ("complete", {}),
        ("ring", dict(k=4)),
        ("erdos_renyi", dict(p=0.05)),
        ("watts_strogatz", dict(k=4, p=0.1)),
        ("power_law", dict(k=2)),
        ("grid", {}),
    ]:
        topo = T.build(TopologyConfig(family=family, n=100, **kw))
        assert topo.n >= 100 if family == "grid" else topo.n == 100


def test_bad_configs():
    with pytest.raises(ValueError):
        T.ring(10, k=3)
    from gossip_tpu.config import TopologyConfig
    with pytest.raises(ValueError):
        TopologyConfig(family="nope", n=10)
