"""Run-ledger telemetry (utils/telemetry): schema, crash contract, and
the flight-recorder proof — a SIGKILLed dry run leaves a parseable
ledger with provenance and every span up to the kill point."""

import os
import signal
import subprocess
import sys
import time

import pytest

from gossip_tpu.utils import telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ledger_schema_spans_counters_gauges(tmp_path):
    p = str(tmp_path / "led.jsonl")
    with telemetry.Ledger(p, argv=["prog", "--x"]) as led:
        with led.span("outer", tag="t") as ext:
            with led.span("inner"):
                pass
            ext["rows"] = 3
        led.counter("timeouts")
        led.counter("timeouts", 2)
        led.gauge("coverage", 0.5)
        led.event("probe", outcome="ok")
    events = telemetry.load_ledger(p)
    # provenance first, with the one artifact schema's keys
    prov = events[0]
    assert prov["ev"] == "provenance"
    for key in ("run_id", "git_commit", "captured", "argv", "jax_version",
                "schema"):
        assert key in prov, key
    assert prov["argv"] == ["prog", "--x"]
    # every line is run-scoped and timestamped
    assert all(e["run"] == prov["run_id"] and "ts" in e for e in events)
    # span nesting via parent ids; walls recorded on end
    starts = {e["name"]: e for e in events if e["ev"] == "span_start"}
    ends = {e["name"]: e for e in events if e["ev"] == "span_end"}
    assert starts["inner"]["parent"] == starts["outer"]["span"]
    assert ends["outer"]["wall_ms"] >= ends["inner"]["wall_ms"] >= 0
    assert ends["outer"]["ok"] and ends["outer"]["rows"] == 3
    assert starts["outer"]["tag"] == "t"
    # counters carry a running total so partial ledgers read high-water
    totals = [e["total"] for e in events if e["ev"] == "counter"]
    assert totals == [1, 3]


def test_span_records_failure_and_start_precedes_work(tmp_path):
    p = str(tmp_path / "led.jsonl")
    led = telemetry.Ledger(p)
    with pytest.raises(RuntimeError):
        with led.span("doomed"):
            raise RuntimeError("boom")
    led.close()
    events = telemetry.load_ledger(p)
    end = next(e for e in events if e["ev"] == "span_end")
    assert end["ok"] is False
    # span_start is durable BEFORE the block body runs — the kill-proof
    # property (the start line was already fsynced when the body raised)
    assert [e["ev"] for e in events] == ["provenance", "span_start",
                                        "span_end"]


def test_from_env_null_and_activate(tmp_path, monkeypatch):
    monkeypatch.delenv(telemetry.ENV_VAR, raising=False)
    led = telemetry.from_env()
    assert isinstance(led, telemetry.NullLedger)
    with led.span("x") as ext:       # the no-op twin still yields a dict
        ext["k"] = 1
    led.event("y")
    led.counter("z")
    # explicit empty disables even over a default path
    monkeypatch.setenv(telemetry.ENV_VAR, "")
    assert isinstance(
        telemetry.from_env(str(tmp_path / "d.jsonl")),
        telemetry.NullLedger)
    # env var wins; activate() installs/restores the ambient ledger
    p = str(tmp_path / "env.jsonl")
    monkeypatch.setenv(telemetry.ENV_VAR, p)
    real = telemetry.from_env()
    assert real.path == os.path.abspath(p)
    prev = telemetry.activate(real)
    try:
        assert telemetry.current() is real
    finally:
        telemetry.activate(prev)
    real.close()
    assert telemetry.load_ledger(p)[0]["ev"] == "provenance"


def test_torn_lines_dropped_and_strict_mode(tmp_path):
    p = str(tmp_path / "led.jsonl")
    with telemetry.Ledger(p) as led:
        led.event("a")
        led.event("b")
    n = len(telemetry.load_ledger(p))
    # a kill between write and fsync tears at most one line per writer
    with open(p, "a") as f:
        f.write('{"ev": "torn_mid_wri')
    assert len(telemetry.load_ledger(p)) == n
    # mid-file tears happen in SHARED files (a killed step subprocess,
    # then the parent appends) — the post-mortem read-out must survive
    # them, so the default drops; strict mode (single-writer) raises
    lines = [ln for ln in open(p).read().splitlines() if ln.strip()]
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write(lines[0] + "\nGARBAGE\n" + lines[1] + "\n")
    assert len(telemetry.load_ledger(bad)) == 2
    with pytest.raises(ValueError, match="corrupt"):
        telemetry.load_ledger(bad, strict=True)


def test_new_writer_heals_torn_tail_of_shared_file(tmp_path):
    """A writer opening a file whose last line is torn (killed previous
    writer) must newline-separate before its provenance line — the
    fragment stays its own (dropped) line instead of corrupting the
    new run's first event."""
    p = str(tmp_path / "led.jsonl")
    with telemetry.Ledger(p) as led:
        led.event("a")
    with open(p, "a") as f:
        f.write('{"ev": "killed_mid_wri')       # no newline
    with telemetry.Ledger(p) as led2:
        led2.event("b")
    events = telemetry.load_ledger(p)
    assert any(e["ev"] == "provenance" and e["run"] == led2.run_id
               for e in events)
    assert any(e["ev"] == "b" for e in events)


def test_load_ledger_run_filter(tmp_path):
    p = str(tmp_path / "led.jsonl")
    with telemetry.Ledger(p) as a:
        a.event("first_run_event")
    with telemetry.Ledger(p) as b:
        b.event("second_run_event")
    assert a.run_id != b.run_id
    last = telemetry.load_ledger(p, run="last")
    assert {e["run"] for e in last} == {b.run_id}
    assert any(e["ev"] == "second_run_event" for e in last)
    only_a = telemetry.load_ledger(p, run=a.run_id)
    assert any(e["ev"] == "first_run_event" for e in only_a)
    assert not any(e["ev"] == "second_run_event" for e in only_a)


def test_maybe_aot_timed_emits_driver_timing(tmp_path):
    """Every sharded driver's wall decomposition reaches the ambient
    ledger through the ONE timing chokepoint (utils/trace) — no
    per-driver plumbing."""
    import jax.numpy as jnp

    import jax
    from gossip_tpu.utils.trace import maybe_aot_timed
    p = str(tmp_path / "led.jsonl")
    led = telemetry.Ledger(p)
    prev = telemetry.activate(led)
    try:
        timing = {"init_build_s": 0.001}
        out = maybe_aot_timed(jax.jit(lambda x: x * 2), timing,
                              jnp.arange(4))
        assert int(out[1]) == 2
        # no ledger event without a timing dict (the plain-call path)
        maybe_aot_timed(jax.jit(lambda x: x * 2), None, jnp.arange(4))
    finally:
        telemetry.activate(prev)
        led.close()
    events = [e for e in telemetry.load_ledger(p)
              if e["ev"] == "driver_timing"]
    assert len(events) == 1
    assert events[0]["compile_s"] >= 0
    assert events[0]["steady_s"] > 0
    assert events[0]["init_build_s"] == 0.001


@pytest.mark.skipif(not hasattr(signal, "SIGKILL"),
                    reason="needs POSIX SIGKILL")
def test_flight_recorder_survives_sigkill_mid_dryrun(tmp_path):
    """THE flight-recorder proof (ISSUE 2 acceptance): SIGKILL a
    dry-run family mid-round and the ledger on disk still parses,
    containing provenance plus every span up to the kill point.

    The child runs the real ``_dryrun_multichip_body`` on a 2-device
    hermetic CPU mesh; the parent polls the ledger and pulls the
    trigger as soon as the first FAMILY span has started (i.e. mid
    compile/round of dense_pushpull) — exactly the dark-round shape:
    a wedged/killed capture with work in flight."""
    ledger = str(tmp_path / "killed.jsonl")
    env = dict(os.environ)
    for hazard in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORM_NAME",
                   "LIBTPU_INIT_ARGS", "JAX_NUM_CPU_DEVICES"):
        env.pop(hazard, None)
    env["PYTHONPATH"] = _REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["GOSSIP_TELEMETRY"] = ledger
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "from __graft_entry__ import _dryrun_multichip_body; "
         "_dryrun_multichip_body(2)"],
        env=env, cwd=_REPO, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 180
        killed_during = None
        while time.time() < deadline:
            if proc.poll() is not None:
                pytest.fail("dry run finished before the kill — poll "
                            "window missed (raise the family count?)")
            if os.path.exists(ledger):
                try:
                    events = telemetry.load_ledger(ledger)
                except ValueError:
                    events = []
                fam_spans = [e for e in events
                             if e.get("ev") == "span_start"
                             and ":" in (e.get("name") or "")]
                if fam_spans:
                    killed_during = fam_spans[0]["name"]
                    proc.send_signal(signal.SIGKILL)
                    break
            time.sleep(0.05)
        else:
            pytest.fail("no family span appeared within 180 s")
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    # the ledger parses IN FULL (fsync-per-event contract: at most a
    # torn final line, which the loader drops by contract)
    events = telemetry.load_ledger(ledger)
    assert events[0]["ev"] == "provenance"
    assert events[0]["git_commit"] is None or len(
        events[0]["git_commit"]) == 40
    # runtime context captured before any family ran
    assert any(e["ev"] == "runtime" for e in events)
    # every span up to the kill point is present; the family the run
    # died inside shows an un-ended span — the "why was it dark" answer
    names = [e["name"] for e in events if e["ev"] == "span_start"]
    assert "dryrun_multichip" in names
    assert killed_during in names
    ended = {e["span"] for e in events if e["ev"] == "span_end"}
    started = {e["span"]: e["name"] for e in events
               if e["ev"] == "span_start"}
    unclosed = [started[s] for s in started if s not in ended]
    assert killed_during in unclosed
    # and the report tool renders the partial ledger without error,
    # naming the span the run died in
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    md = telemetry_report.render_markdown(events)
    assert "unclosed" in md
    assert killed_during.split(":")[0] in md


@pytest.mark.skipif(not hasattr(signal, "SIGKILL"),
                    reason="needs POSIX SIGKILL")
def test_flight_recorder_survives_sigkill_on_trace_ledger(tmp_path):
    """Satellite pin: the flight-recorder contract extends to a
    trace-BEARING ledger.  SIGKILL a serving process mid-traffic: the
    ledger still parses (at most a torn line, dropped by contract),
    every ``request_trace`` written before the kill survives with a
    usable 16-hex trace_id, and ``load_ledger(trace_id=...)``
    round-trips on the partial file — a crash must not cost the
    waterfalls of the requests it already acked."""
    ledger = str(tmp_path / "killed_trace.jsonl")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["GOSSIP_TELEMETRY"] = ledger
    child = (
        "from gossip_tpu.utils import telemetry\n"
        "telemetry.activate(telemetry.from_env("
        "argv=['trace_kill_child']))\n"
        "from gossip_tpu.config import ServingConfig\n"
        "from gossip_tpu.rpc.sidecar import SidecarClient, serve\n"
        "server, port = serve(port=0, batching=ServingConfig("
        "tick_ms=10, max_batch=8))\n"
        "client = SidecarClient(f'127.0.0.1:{port}')\n"
        "i = 0\n"
        "while True:\n"
        "    client.run(backend='jax-tpu',\n"
        "               proto={'mode': 'push', 'fanout': 2},\n"
        "               topology={'family': 'complete', 'n': 32},\n"
        "               run={'max_rounds': 3, 'engine': 'xla',\n"
        "                    'seed': i}, curve=True)\n"
        "    i += 1\n")
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            cwd=_REPO, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 180
        while time.time() < deadline:
            if proc.poll() is not None:
                pytest.fail("serving child exited before the kill")
            if os.path.exists(ledger):
                try:
                    events = telemetry.load_ledger(ledger)
                except ValueError:
                    events = []
                if any(e.get("ev") == "request_trace"
                       for e in events):
                    proc.send_signal(signal.SIGKILL)
                    break
            time.sleep(0.05)
        else:
            pytest.fail("no request_trace appeared within 180 s")
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    events = telemetry.load_ledger(ledger)
    assert events[0]["ev"] == "provenance"
    traced = [e for e in events if e.get("ev") == "request_trace"]
    assert traced
    tid = traced[0]["trace_id"]
    assert len(tid) == 16
    assert all(c in "0123456789abcdef" for c in tid)
    sub = telemetry.load_ledger(ledger, trace_id=tid)
    assert sub and all(e.get("trace_id") == tid for e in sub)
    assert any(e.get("ev") == "request_trace" for e in sub)


def test_reserved_keys_never_collide(tmp_path):
    """A caller-supplied run/ts/ev field (the pre-ledger watchdog
    format carried its own 'ts') must not corrupt run filtering — it
    is prefixed, never overwriting."""
    p = str(tmp_path / "led.jsonl")
    with telemetry.Ledger(p) as led:
        led.event("probe", ts="2026-01-01T00:00:00", run="bogus", ev="x")
    events = telemetry.load_ledger(p, run="last")
    probe = next(e for e in events if e["ev"] == "probe")
    assert probe["run"] == events[0]["run_id"]       # filtering intact
    assert probe["x_ts"] == "2026-01-01T00:00:00"
    assert probe["x_run"] == "bogus" and probe["x_ev"] == "x"


def test_disabled_file_keeps_echo_diagnostics(tmp_path, monkeypatch,
                                              capsys):
    """GOSSIP_TELEMETRY='' disables the FILE, but an echo-requesting
    surface (bench.py) still gets stderr diagnostics — disabling the
    recorder must never recreate the silent dark window."""
    monkeypatch.setenv(telemetry.ENV_VAR, "")
    led = telemetry.from_env(str(tmp_path / "d.jsonl"), echo=True)
    assert isinstance(led, telemetry.EchoLedger)
    assert led.path is None
    led.event("probe", outcome="timeout")
    led.counter("probe_timeouts")
    err = capsys.readouterr().err
    assert '"probe"' in err and "timeout" in err
    assert not os.path.exists(tmp_path / "d.jsonl")


def test_sync_false_event_still_lands(tmp_path):
    """sync=False (the in-window driver_timing path) skips only the
    fsync; the flushed line is still on disk immediately after."""
    p = str(tmp_path / "led.jsonl")
    led = telemetry.Ledger(p)
    led.event("driver_timing", sync=False, steady_s=0.1)
    events = telemetry.load_ledger(p)     # ledger still open
    led.close()
    assert any(e["ev"] == "driver_timing" and e["steady_s"] == 0.1
               for e in events)


def test_device_memory_stats_shape():
    """CPU devices report no memory_stats: the helper returns None (and
    memory_snapshot emits nothing) rather than fabricating zeros."""
    stats = telemetry.device_memory_stats()
    assert stats is None or (isinstance(stats, list) and stats
                             and "device" in stats[0])


def test_shared_writer_midfile_tear_is_dropped_not_fatal(tmp_path):
    """The SHARED-file crash shape end to end: writer A is killed
    mid-write (its fragment has no newline), writer B then appends a
    whole run.  B's leading-newline self-heal keeps the fragment its
    own line; the default loader drops exactly that line and keeps
    EVERY event on both sides of it — a mid-file tear, unlike the
    single-writer tail tear, so strict mode refuses the file."""
    p = str(tmp_path / "shared.jsonl")
    with telemetry.Ledger(p) as a:
        a.event("step", n=1)
    with open(p, "a") as f:
        f.write('{"ev": "step", "n": 2, "half_writ')   # killed writer
    with telemetry.Ledger(p) as b:
        b.event("step", n=3)
        b.event("step", n=4)
    events = telemetry.load_ledger(p)
    assert [e["n"] for e in events if e["ev"] == "step"] == [1, 3, 4]
    # both runs' provenance survived around the tear
    assert [e["ev"] for e in events].count("provenance") == 2
    with pytest.raises(ValueError, match="corrupt"):
        telemetry.load_ledger(p, strict=True)


def test_non_finite_values_stay_strict_json(tmp_path):
    """A poisoned gauge/counter value (nan/inf — a diverged measurement
    upstream) must record the poisoning WITHOUT breaking the file for
    strict-JSON consumers: Python's json would happily write NaN
    literals that jq and every non-Python reader reject."""
    import json as _json
    import math
    p = str(tmp_path / "led.jsonl")
    with telemetry.Ledger(p) as led:
        led.gauge("bad_rate", float("nan"))
        led.gauge("worse_rate", float("inf"))
        led.event("probe", wall_s=float("-inf"),
                  nested={"deep": float("nan"), "fine": 1.5})
        led.gauge("fine", 0.25)
    # every line parses under STRICT json (NaN/Infinity literals raise)
    def no_constants(s):
        raise ValueError(f"non-strict JSON constant {s!r}")
    with open(p) as f:
        rows = [_json.loads(ln, parse_constant=no_constants)
                for ln in f if ln.strip()]
    gauges = {r["name"]: r["value"] for r in rows if r["ev"] == "gauge"}
    assert gauges == {"bad_rate": "nan", "worse_rate": "inf",
                      "fine": 0.25}
    probe = next(r for r in rows if r["ev"] == "probe")
    assert probe["wall_s"] == "-inf"
    assert probe["nested"] == {"deep": "nan", "fine": 1.5}
    # and the crash-contract loader reads them back the same way
    evs = telemetry.load_ledger(p)
    assert any(e.get("value") == "nan" for e in evs)
    assert not any(isinstance(e.get("value"), float)
                   and math.isnan(e["value"]) for e in evs)


def _load_report_tool():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(_REPO, "tools",
                                         "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_report_check_gate_health(tmp_path, capsys):
    """telemetry_report --check: exit 0 on a healthy ledger, exit 1
    naming the problem on an unclosed span or a missing provenance
    line — the CI hook for ledger health."""
    report = _load_report_tool()
    good = str(tmp_path / "good.jsonl")
    with telemetry.Ledger(good) as led:
        with led.span("fine"):
            pass
    assert report.main([good, "--check"]) == 0

    # a run killed inside a span: span_start durable, no span_end
    wedged = str(tmp_path / "wedged.jsonl")
    led = telemetry.Ledger(wedged)
    cm = led.span("doomed_family")
    cm.__enter__()                         # never exited: the kill
    led.close()
    assert report.main([wedged, "--check"]) == 1
    err = capsys.readouterr().err
    assert "unclosed span" in err and "doomed_family" in err

    # an unknown explicit --run id is an ERROR, not an empty selection
    # misdiagnosed as "no provenance" (the ledger_diff convention)
    with pytest.raises(SystemExit, match="not in"):
        report.main([good, "--check", "--run", "no_such_run"])

    # no provenance at all (hand-rolled pre-ledger file)
    bare = str(tmp_path / "bare.jsonl")
    with open(bare, "w") as f:
        f.write('{"ev": "probe", "outcome": "ok"}\n')
    assert report.main([bare, "--check"]) == 1
    assert "no provenance" in capsys.readouterr().err

    # --all-runs checks every run in a shared file
    shared = str(tmp_path / "shared.jsonl")
    with telemetry.Ledger(shared) as led:
        with led.span("ok_span"):
            pass
    led2 = telemetry.Ledger(shared)
    cm = led2.span("dead_run_span")
    cm.__enter__()
    led2.close()
    assert report.main([shared, "--all-runs", "--check"]) == 1
    assert "dead_run_span" in capsys.readouterr().err
