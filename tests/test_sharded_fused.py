"""Rumor-plane sharding of the fused kernel (parallel/sharded_fused.py).

The inject path makes the sharded round bitwise-checkable on the virtual
8-device CPU mesh: every plane must equal the single-device multi-rumor
kernel applied to that plane with the same bits — the shared partner
stream IS the semantic (one partner per node per round, whole digest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu.config import RunConfig
from gossip_tpu.ops.pallas_round import (
    BITS, LANES, fused_multirumor_pull_round, mr_rows, word_pack,
    word_unpack)
from gossip_tpu.parallel.sharded_fused import (
    assert_prng_invariant, coverage_planes, init_plane_state,
    make_plane_mesh, make_sharded_fused_round, plane_count,
    simulate_until_sharded_fused)

ON_TPU = jax.default_backend() == "tpu"
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the virtual multi-device mesh")


def _bits(rng, rows, fanout=1):
    return (rng.integers(0, 2**32, (fanout, 8, LANES), dtype=np.uint32),
            rng.integers(0, 2**32, (fanout, rows, LANES), dtype=np.uint32))


def test_plane_count_and_init_padding():
    mesh = make_plane_mesh(4)
    assert plane_count(1, 4) == 4            # padded up to the mesh
    assert plane_count(33, 4) == 4
    assert plane_count(129, 4) == 8
    n, rumors = 500, 40                      # plane 1 has 8 real rumors
    planes = init_plane_state(n, rumors, mesh)
    assert planes.shape[0] == 4
    got = np.asarray(word_unpack(planes[1], n, BITS))
    # real rumor columns: exactly one origin each; padding columns all-True
    assert got[:, :8].sum() == 8
    assert got[:, 8:].all()
    # whole padding planes are all-ones for real nodes
    assert np.asarray(word_unpack(planes[2], n, BITS)).all()
    assert float(coverage_planes(planes, n)) == pytest.approx(1.0 / n)


def test_sharded_round_matches_single_device_per_plane():
    n, rumors, n_dev = 128 * 16, 256, 4      # 8 planes over 4 devices
    mesh = make_plane_mesh(n_dev)
    rows = mr_rows(n)
    rng = np.random.default_rng(17)
    planes = init_plane_state(n, rumors, mesh)
    # seed some extra infection so the round moves real data
    seen = rng.random((n, BITS)) < 0.1
    planes = planes.at[3].set(planes[3] | word_pack(jnp.asarray(seen)))
    bits = _bits(rng, rows)
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU,
                                    inject_bits=bits)
    out = np.asarray(step(planes, 0, 0))
    for p in range(planes.shape[0]):
        # materialize the shard slice so the single-device reference call
        # is not itself partitioned over the mesh
        plane_p = jnp.asarray(np.asarray(planes[p]))
        want = fused_multirumor_pull_round(
            plane_p, 0, 0, n, 1, interpret=not ON_TPU, inject_bits=bits)
        np.testing.assert_array_equal(out[p], np.asarray(want),
                                      err_msg=f"plane {p}")


def test_whole_digest_rides_one_partner_across_planes():
    """Nodes holding ALL 256 rumors must transfer all-or-nothing: the
    partner draw is shared across every plane."""
    n, rumors, n_dev = 128 * 16, 256, 4
    mesh = make_plane_mesh(n_dev)
    rows = mr_rows(n)
    rng = np.random.default_rng(23)
    holders = rng.random(n) < 0.1
    seen = jnp.repeat(jnp.asarray(holders)[:, None], BITS, axis=1)
    one = word_pack(seen)
    planes = jax.device_put(
        jnp.stack([one] * plane_count(rumors, n_dev)),
        jax.sharding.NamedSharding(mesh,
                                   jax.sharding.PartitionSpec("planes",
                                                              None, None)))
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU,
                                    inject_bits=_bits(rng, rows))
    out = np.asarray(step(planes, 0, 0))
    got = np.stack([np.asarray(word_unpack(jnp.asarray(out[p]), n, BITS))
                    for p in range(out.shape[0])])   # [W, n, 32]
    flat = got.transpose(1, 0, 2).reshape(n, -1)     # [n, W*32]
    assert (flat.all(axis=1) | (~flat.any(axis=1))).all()


def test_simulate_until_converges_with_degenerate_prng():
    """CPU interpreter stubs the hw PRNG with zeros: every node pulls the
    same fixed partner each round.  Not an epidemic — but the driver must
    still run the full sharded while_loop and terminate at max_rounds."""
    n, rumors = 128 * 8, 64
    mesh = make_plane_mesh(4)
    rounds, cov, msgs, final = simulate_until_sharded_fused(
        n, rumors, RunConfig(max_rounds=3), mesh, interpret=True)
    assert rounds == 3                       # degenerate PRNG never hits 99%
    assert msgs == 2.0 * n * 3
    assert final.shape[0] == plane_count(rumors, 4)
    assert 0.0 < cov < 0.99


def test_prng_same_stream_invariant_digests():
    """The zero-ICI claim as an executed assertion (VERDICT r2 item 4):
    every device's identically-seeded round digests identically.  On TPU
    (GOSSIP_TPU_TEST_PLATFORM=axon tier) this checks the HARDWARE PRNG
    stream; on the CPU interpreter the stubbed PRNG makes equality
    trivial but the digest/all_gather program is the real one."""
    mesh = make_plane_mesh(4)
    d = np.asarray(assert_prng_invariant(128 * 16, mesh,
                                         interpret=not ON_TPU))
    assert d.shape == (4, 2)
    assert (d == d[0]).all()
    assert int(d[0, 0]) > 0      # non-degenerate: bits actually flowed


def test_sharded_round_fault_masks_match_single_device():
    """Round-4 fault masks on the plane-sharded engine: every plane must
    equal the single-device MR kernel run with the SAME masks and bits
    (the masks are replicated over the node dim, rebuilt in-trace on
    each device)."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import fault_masks_word
    n, rumors, n_dev = 128 * 16, 128, 4      # 4 planes over 4 devices
    mesh = make_plane_mesh(n_dev)
    rows = mr_rows(n)
    rng = np.random.default_rng(23)
    planes = init_plane_state(n, rumors, mesh)
    seen = rng.random((n, BITS)) < 0.1
    planes = planes.at[1].set(planes[1] | word_pack(jnp.asarray(seen)))
    bits = _bits(rng, rows)
    fault = FaultConfig(drop_prob=0.3, node_death_rate=0.2, seed=12)
    alive_words, thresh = fault_masks_word(fault, n, 0)
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU,
                                    inject_bits=bits, fault=fault)
    out = np.asarray(step(planes, 0, 0))
    for p in range(planes.shape[0]):
        plane_p = jnp.asarray(np.asarray(planes[p]))
        want = fused_multirumor_pull_round(
            plane_p, 0, 0, n, 1, interpret=not ON_TPU, inject_bits=bits,
            drop_threshold=thresh, alive_words=alive_words)
        np.testing.assert_array_equal(out[p], np.asarray(want),
                                      err_msg=f"plane {p}")


def test_fused_planes_cov_fn_alive_weighting():
    """The alive-weighted plane coverage: padding rumors stay 1.0 (alive
    nodes hold their all-ones bits), real rumors weight by the alive
    population only."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.models.state import alive_mask
    from gossip_tpu.parallel.sharded_fused import fused_planes_cov_fn
    n, rumors, n_dev = 600, 40, 4            # 2 real planes + 2 padding
    mesh = make_plane_mesh(n_dev)
    rng = np.random.default_rng(4)
    fault = FaultConfig(node_death_rate=0.3, seed=9)
    alive = np.asarray(alive_mask(fault, n, 0))
    seen = rng.random((n, rumors)) < 0.6
    planes = init_plane_state(n, rumors, mesh)
    for p in range(2):
        lo = p * BITS
        real = min(rumors - lo, BITS)
        chunk = np.zeros((n, BITS), bool)
        chunk[:, :real] = seen[:, lo:lo + real]
        chunk[:, real:] = True
        planes = planes.at[p].set(planes[p]
                                  | word_pack(jnp.asarray(chunk)))
    got = float(fused_planes_cov_fn(n, fault)(planes))
    # min over REAL rumors of the alive-weighted fraction (origins of
    # the real rumors are seeded, so union with the init state)
    seen_init = np.zeros_like(seen)
    seen_init[(np.arange(rumors)) % n, np.arange(rumors)] = True
    want = ((seen | seen_init)[alive].mean(axis=0)).min()
    assert got == pytest.approx(want, abs=1e-6)
    # and the unweighted chooser is untouched by a drop-only fault
    drop_only = FaultConfig(drop_prob=0.5, seed=1)
    got2 = float(fused_planes_cov_fn(n, drop_only)(planes))
    assert got2 == pytest.approx(float(coverage_planes(planes, n)),
                                 abs=1e-7)


@pytest.mark.parametrize("fanout,with_fault", [(1, False), (2, False),
                                               (1, True), (2, True)])
def test_device_resident_loop_matches_per_round_driver(fanout, with_fault):
    """The memoized device-resident drivers (curve scan + until loop,
    on-device convergence, cached jitted init, alive mask as operand)
    reproduce the per-round driver EXACTLY: same coverage curve, same
    final planes — CPU, fanout 1 and 2, with and without FaultConfig.
    This is the byte-identity contract behind the dry-run steady-state
    speedup: faster, not different."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.parallel.sharded_fused import (
        fused_planes_cov_fn, simulate_curve_sharded_fused)
    n, rumors, n_dev, rounds = 128 * 8, 96, 4, 3
    mesh = make_plane_mesh(n_dev)
    fault = (FaultConfig(node_death_rate=0.2, drop_prob=0.3, seed=7)
             if with_fault else None)
    run = RunConfig(seed=0, max_rounds=rounds)
    covs, final = simulate_curve_sharded_fused(
        n, rumors, run, mesh, fanout=fanout, interpret=not ON_TPU,
        fault=fault)
    # the per-round driver: step eagerly, coverage recorded per round
    step = make_sharded_fused_round(n, mesh, fanout=fanout,
                                    interpret=not ON_TPU, fault=fault)
    planes = init_plane_state(n, rumors, mesh, 0)
    cov_fn = fused_planes_cov_fn(n, fault)
    for t in range(rounds):
        planes = step(planes, 0, t)
        assert float(covs[t]) == float(cov_fn(planes)), t
    np.testing.assert_array_equal(np.asarray(final), np.asarray(planes))
    # the until twin walks the same trajectory (the degenerate stubbed
    # PRNG never reaches target, so it runs the full budget) and must
    # land on the same planes and report coverage through the same
    # chooser
    rounds_u, cov_u, msgs_u, final_u = simulate_until_sharded_fused(
        n, rumors, run, mesh, fanout=fanout, interpret=not ON_TPU,
        fault=fault)
    assert rounds_u == rounds
    assert msgs_u == 2.0 * fanout * n * rounds
    np.testing.assert_array_equal(np.asarray(final_u), np.asarray(planes))
    assert float(cov_u) == float(cov_fn(planes))


def test_fault_loop_shares_executable_across_death_draws():
    """The fault-curve driver must NOT recompile per fault point: two
    configs differing only in death rate/seed (same drop_prob) hit the
    SAME memoized compiled loop — the alive mask is a runtime operand
    (sharded_fused._cached_curve_scan key contract)."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.parallel.sharded_fused import (
        _cached_curve_scan, drop_threshold_for,
        simulate_curve_sharded_fused)
    n, rumors, n_dev = 128 * 8, 64, 4
    mesh = make_plane_mesh(n_dev)
    run = RunConfig(seed=0, max_rounds=2)
    f1 = FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=3)
    f2 = FaultConfig(node_death_rate=0.3, drop_prob=0.2, seed=11)
    assert drop_threshold_for(f1) == drop_threshold_for(f2)
    covs1, _ = simulate_curve_sharded_fused(n, rumors, run, mesh,
                                            interpret=not ON_TPU, fault=f1)
    info_before = _cached_curve_scan.cache_info()
    covs2, _ = simulate_curve_sharded_fused(n, rumors, run, mesh,
                                            interpret=not ON_TPU, fault=f2)
    info_after = _cached_curve_scan.cache_info()
    assert info_after.misses == info_before.misses   # shared loop builder
    assert info_after.hits == info_before.hits + 1
    # ... and the shared executable still separates the trajectories
    # (different death draws weight coverage differently)
    assert covs1.shape == covs2.shape == (2,)


def test_simulate_curve_sharded_fused_matches_stepwise():
    """The plane-sharded curve scan equals stepping the sharded round by
    hand (stubbed interpreter PRNG), coverage recorded per round."""
    from gossip_tpu.parallel.sharded_fused import (
        fused_planes_cov_fn, simulate_curve_sharded_fused)
    n, rumors, n_dev, rounds = 128 * 16, 128, 4, 3
    mesh = make_plane_mesh(n_dev)
    run = RunConfig(seed=0, max_rounds=rounds)
    covs, final = simulate_curve_sharded_fused(n, rumors, run, mesh,
                                               interpret=not ON_TPU)
    assert covs.shape == (rounds,)
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU)
    planes = init_plane_state(n, rumors, mesh, 0)
    cov_fn = fused_planes_cov_fn(n)
    for t in range(rounds):
        planes = step(planes, 0, t)
        assert float(covs[t]) == float(cov_fn(planes)), t
    np.testing.assert_array_equal(np.asarray(final), np.asarray(planes))
