"""Rumor-plane sharding of the fused kernel (parallel/sharded_fused.py).

The inject path makes the sharded round bitwise-checkable on the virtual
8-device CPU mesh: every plane must equal the single-device multi-rumor
kernel applied to that plane with the same bits — the shared partner
stream IS the semantic (one partner per node per round, whole digest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu.config import RunConfig
from gossip_tpu.ops.pallas_round import (
    BITS, LANES, fused_multirumor_pull_round, mr_rows, word_pack,
    word_unpack)
from gossip_tpu.parallel.sharded_fused import (
    assert_prng_invariant, coverage_planes, init_plane_state,
    make_plane_mesh, make_sharded_fused_round, plane_count,
    simulate_until_sharded_fused)

ON_TPU = jax.default_backend() == "tpu"
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the virtual multi-device mesh")


def _bits(rng, rows, fanout=1):
    return (rng.integers(0, 2**32, (fanout, 8, LANES), dtype=np.uint32),
            rng.integers(0, 2**32, (fanout, rows, LANES), dtype=np.uint32))


def test_plane_count_and_init_padding():
    mesh = make_plane_mesh(4)
    assert plane_count(1, 4) == 4            # padded up to the mesh
    assert plane_count(33, 4) == 4
    assert plane_count(129, 4) == 8
    n, rumors = 500, 40                      # plane 1 has 8 real rumors
    planes = init_plane_state(n, rumors, mesh)
    assert planes.shape[0] == 4
    got = np.asarray(word_unpack(planes[1], n, BITS))
    # real rumor columns: exactly one origin each; padding columns all-True
    assert got[:, :8].sum() == 8
    assert got[:, 8:].all()
    # whole padding planes are all-ones for real nodes
    assert np.asarray(word_unpack(planes[2], n, BITS)).all()
    assert float(coverage_planes(planes, n)) == pytest.approx(1.0 / n)


def test_sharded_round_matches_single_device_per_plane():
    n, rumors, n_dev = 128 * 16, 256, 4      # 8 planes over 4 devices
    mesh = make_plane_mesh(n_dev)
    rows = mr_rows(n)
    rng = np.random.default_rng(17)
    planes = init_plane_state(n, rumors, mesh)
    # seed some extra infection so the round moves real data
    seen = rng.random((n, BITS)) < 0.1
    planes = planes.at[3].set(planes[3] | word_pack(jnp.asarray(seen)))
    bits = _bits(rng, rows)
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU,
                                    inject_bits=bits)
    out = np.asarray(step(planes, 0, 0))
    for p in range(planes.shape[0]):
        # materialize the shard slice so the single-device reference call
        # is not itself partitioned over the mesh
        plane_p = jnp.asarray(np.asarray(planes[p]))
        want = fused_multirumor_pull_round(
            plane_p, 0, 0, n, 1, interpret=not ON_TPU, inject_bits=bits)
        np.testing.assert_array_equal(out[p], np.asarray(want),
                                      err_msg=f"plane {p}")


def test_whole_digest_rides_one_partner_across_planes():
    """Nodes holding ALL 256 rumors must transfer all-or-nothing: the
    partner draw is shared across every plane."""
    n, rumors, n_dev = 128 * 16, 256, 4
    mesh = make_plane_mesh(n_dev)
    rows = mr_rows(n)
    rng = np.random.default_rng(23)
    holders = rng.random(n) < 0.1
    seen = jnp.repeat(jnp.asarray(holders)[:, None], BITS, axis=1)
    one = word_pack(seen)
    planes = jax.device_put(
        jnp.stack([one] * plane_count(rumors, n_dev)),
        jax.sharding.NamedSharding(mesh,
                                   jax.sharding.PartitionSpec("planes",
                                                              None, None)))
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU,
                                    inject_bits=_bits(rng, rows))
    out = np.asarray(step(planes, 0, 0))
    got = np.stack([np.asarray(word_unpack(jnp.asarray(out[p]), n, BITS))
                    for p in range(out.shape[0])])   # [W, n, 32]
    flat = got.transpose(1, 0, 2).reshape(n, -1)     # [n, W*32]
    assert (flat.all(axis=1) | (~flat.any(axis=1))).all()


def test_simulate_until_converges_with_degenerate_prng():
    """CPU interpreter stubs the hw PRNG with zeros: every node pulls the
    same fixed partner each round.  Not an epidemic — but the driver must
    still run the full sharded while_loop and terminate at max_rounds."""
    n, rumors = 128 * 8, 64
    mesh = make_plane_mesh(4)
    rounds, cov, msgs, final = simulate_until_sharded_fused(
        n, rumors, RunConfig(max_rounds=3), mesh, interpret=True)
    assert rounds == 3                       # degenerate PRNG never hits 99%
    assert msgs == 2.0 * n * 3
    assert final.shape[0] == plane_count(rumors, 4)
    assert 0.0 < cov < 0.99


def test_prng_same_stream_invariant_digests():
    """The zero-ICI claim as an executed assertion (VERDICT r2 item 4):
    every device's identically-seeded round digests identically.  On TPU
    (GOSSIP_TPU_TEST_PLATFORM=axon tier) this checks the HARDWARE PRNG
    stream; on the CPU interpreter the stubbed PRNG makes equality
    trivial but the digest/all_gather program is the real one."""
    mesh = make_plane_mesh(4)
    d = np.asarray(assert_prng_invariant(128 * 16, mesh,
                                         interpret=not ON_TPU))
    assert d.shape == (4, 2)
    assert (d == d[0]).all()
    assert int(d[0, 0]) > 0      # non-degenerate: bits actually flowed


def test_sharded_round_fault_masks_match_single_device():
    """Round-4 fault masks on the plane-sharded engine: every plane must
    equal the single-device MR kernel run with the SAME masks and bits
    (the masks are replicated over the node dim, rebuilt in-trace on
    each device)."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import fault_masks_word
    n, rumors, n_dev = 128 * 16, 128, 4      # 4 planes over 4 devices
    mesh = make_plane_mesh(n_dev)
    rows = mr_rows(n)
    rng = np.random.default_rng(23)
    planes = init_plane_state(n, rumors, mesh)
    seen = rng.random((n, BITS)) < 0.1
    planes = planes.at[1].set(planes[1] | word_pack(jnp.asarray(seen)))
    bits = _bits(rng, rows)
    fault = FaultConfig(drop_prob=0.3, node_death_rate=0.2, seed=12)
    alive_words, thresh = fault_masks_word(fault, n, 0)
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU,
                                    inject_bits=bits, fault=fault)
    out = np.asarray(step(planes, 0, 0))
    for p in range(planes.shape[0]):
        plane_p = jnp.asarray(np.asarray(planes[p]))
        want = fused_multirumor_pull_round(
            plane_p, 0, 0, n, 1, interpret=not ON_TPU, inject_bits=bits,
            drop_threshold=thresh, alive_words=alive_words)
        np.testing.assert_array_equal(out[p], np.asarray(want),
                                      err_msg=f"plane {p}")


def test_fused_planes_cov_fn_alive_weighting():
    """The alive-weighted plane coverage: padding rumors stay 1.0 (alive
    nodes hold their all-ones bits), real rumors weight by the alive
    population only."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.models.state import alive_mask
    from gossip_tpu.parallel.sharded_fused import fused_planes_cov_fn
    n, rumors, n_dev = 600, 40, 4            # 2 real planes + 2 padding
    mesh = make_plane_mesh(n_dev)
    rng = np.random.default_rng(4)
    fault = FaultConfig(node_death_rate=0.3, seed=9)
    alive = np.asarray(alive_mask(fault, n, 0))
    seen = rng.random((n, rumors)) < 0.6
    planes = init_plane_state(n, rumors, mesh)
    for p in range(2):
        lo = p * BITS
        real = min(rumors - lo, BITS)
        chunk = np.zeros((n, BITS), bool)
        chunk[:, :real] = seen[:, lo:lo + real]
        chunk[:, real:] = True
        planes = planes.at[p].set(planes[p]
                                  | word_pack(jnp.asarray(chunk)))
    got = float(fused_planes_cov_fn(n, fault)(planes))
    # min over REAL rumors of the alive-weighted fraction (origins of
    # the real rumors are seeded, so union with the init state)
    seen_init = np.zeros_like(seen)
    seen_init[(np.arange(rumors)) % n, np.arange(rumors)] = True
    want = ((seen | seen_init)[alive].mean(axis=0)).min()
    assert got == pytest.approx(want, abs=1e-6)
    # and the unweighted chooser is untouched by a drop-only fault
    drop_only = FaultConfig(drop_prob=0.5, seed=1)
    got2 = float(fused_planes_cov_fn(n, drop_only)(planes))
    assert got2 == pytest.approx(float(coverage_planes(planes, n)),
                                 abs=1e-7)


# fault-variant params are slow-tier since the fused-operand-PR
# rebalance (~3.3 s flight data): the fault-operand binding of the
# memoized loops is now additionally pinned in-gate by
# test_sharded_round_full_schedule_matches_single_device and
# test_fused_churn_sweep_matches_solo_and_validates (which walk the
# same step/mask plumbing under a FULL mixed schedule); the static-
# fault depth twins re-prove under -m slow
@pytest.mark.parametrize(
    "fanout,with_fault",
    [(1, False), (2, False),
     pytest.param(1, True, marks=pytest.mark.slow),
     pytest.param(2, True, marks=pytest.mark.slow)])
def test_device_resident_loop_matches_per_round_driver(fanout, with_fault):
    """The memoized device-resident drivers (curve scan + until loop,
    on-device convergence, cached jitted init, alive mask as operand)
    reproduce the per-round driver EXACTLY: same coverage curve, same
    final planes — CPU, fanout 1 and 2, with and without FaultConfig.
    This is the byte-identity contract behind the dry-run steady-state
    speedup: faster, not different."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.parallel.sharded_fused import (
        fused_planes_cov_fn, simulate_curve_sharded_fused)
    n, rumors, n_dev, rounds = 128 * 8, 96, 4, 3
    mesh = make_plane_mesh(n_dev)
    fault = (FaultConfig(node_death_rate=0.2, drop_prob=0.3, seed=7)
             if with_fault else None)
    run = RunConfig(seed=0, max_rounds=rounds)
    covs, final = simulate_curve_sharded_fused(
        n, rumors, run, mesh, fanout=fanout, interpret=not ON_TPU,
        fault=fault)
    # the per-round driver: step eagerly, coverage recorded per round
    step = make_sharded_fused_round(n, mesh, fanout=fanout,
                                    interpret=not ON_TPU, fault=fault)
    planes = init_plane_state(n, rumors, mesh, 0)
    cov_fn = fused_planes_cov_fn(n, fault)
    for t in range(rounds):
        planes = step(planes, 0, t)
        assert float(covs[t]) == float(cov_fn(planes)), t
    np.testing.assert_array_equal(np.asarray(final), np.asarray(planes))
    # the until twin walks the same trajectory (the degenerate stubbed
    # PRNG never reaches target, so it runs the full budget) and must
    # land on the same planes and report coverage through the same
    # chooser
    rounds_u, cov_u, msgs_u, final_u = simulate_until_sharded_fused(
        n, rumors, run, mesh, fanout=fanout, interpret=not ON_TPU,
        fault=fault)
    assert rounds_u == rounds
    assert msgs_u == 2.0 * fanout * n * rounds
    np.testing.assert_array_equal(np.asarray(final_u), np.asarray(planes))
    assert float(cov_u) == float(cov_fn(planes))


def test_fault_loop_shares_executable_across_death_draws():
    """The fault-curve driver must NOT recompile per fault point: two
    configs differing only in death rate/seed (same drop_prob) hit the
    SAME memoized compiled loop — the alive mask is a runtime operand
    (sharded_fused._cached_curve_scan key contract)."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.parallel.sharded_fused import (
        _cached_curve_scan, drop_threshold_for,
        simulate_curve_sharded_fused)
    n, rumors, n_dev = 128 * 8, 64, 4
    mesh = make_plane_mesh(n_dev)
    run = RunConfig(seed=0, max_rounds=2)
    f1 = FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=3)
    f2 = FaultConfig(node_death_rate=0.3, drop_prob=0.2, seed=11)
    assert drop_threshold_for(f1) == drop_threshold_for(f2)
    covs1, _ = simulate_curve_sharded_fused(n, rumors, run, mesh,
                                            interpret=not ON_TPU, fault=f1)
    info_before = _cached_curve_scan.cache_info()
    covs2, _ = simulate_curve_sharded_fused(n, rumors, run, mesh,
                                            interpret=not ON_TPU, fault=f2)
    info_after = _cached_curve_scan.cache_info()
    assert info_after.misses == info_before.misses   # shared loop builder
    assert info_after.hits == info_before.hits + 1
    # ... and the shared executable still separates the trajectories
    # (different death draws weight coverage differently)
    assert covs1.shape == covs2.shape == (2,)


def test_simulate_curve_sharded_fused_matches_stepwise():
    """The plane-sharded curve scan equals stepping the sharded round by
    hand (stubbed interpreter PRNG), coverage recorded per round."""
    from gossip_tpu.parallel.sharded_fused import (
        fused_planes_cov_fn, simulate_curve_sharded_fused)
    n, rumors, n_dev, rounds = 128 * 16, 128, 4, 3
    mesh = make_plane_mesh(n_dev)
    run = RunConfig(seed=0, max_rounds=rounds)
    covs, final = simulate_curve_sharded_fused(n, rumors, run, mesh,
                                               interpret=not ON_TPU)
    assert covs.shape == (rounds,)
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU)
    planes = init_plane_state(n, rumors, mesh, 0)
    cov_fn = fused_planes_cov_fn(n)
    for t in range(rounds):
        planes = step(planes, 0, t)
        assert float(covs[t]) == float(cov_fn(planes)), t
    np.testing.assert_array_equal(np.asarray(final), np.asarray(planes))


# ---------------------------------------------------------------------
# The fused-operand PR: fault content as runtime KERNEL operands — the
# 20-bit drop threshold as an SMEM scalar indexed from the nemesis
# threshold table, partition windows as per-round side-word cut masks
# (render_cut_words), churn events as per-round alive words.  The
# tests below pin (a) the schedule-to-operand lowering against the XLA
# engines' semantics, (b) the sharded round's full-schedule binding
# against the single-device kernel, (c) the partition stall + heal
# bound on the fused path, and (d) the compile-amortization claim: K
# mixed scenarios through ONE executable, salted re-entry compiling
# ZERO.
# ---------------------------------------------------------------------

def _mixed_fault():
    from gossip_tpu.config import ChurnConfig, FaultConfig
    return FaultConfig(seed=1, drop_prob=0.1, churn=ChurnConfig(
        events=((3, 1, 3), (7, 2, -1)),
        partitions=((1, 3, 600),),
        ramp=(0, 4, 0.05, 0.4)))


def test_fused_sched_tables_match_xla_schedule_semantics():
    """The fused engines' schedule operands (ops/nemesis
    .fused_sched_tables) are the SAME timelines the XLA engines consume
    — one _cut_drop_rows construction — and the threshold lowering is
    value-preserving: a flat drop schedule's per-round thresholds equal
    the static path's drop_threshold_for bit for bit (why the fused
    ckpt-static fingerprints stay green), and the side-mask compare
    reproduces ops/nemesis.same_side exactly."""
    from gossip_tpu.config import ChurnConfig, FaultConfig
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops.pallas_round import (drop_threshold_for,
                                             render_cut_words)
    n = 128 * 8
    fault = _mixed_fault()
    sched = NE.build(fault, n)
    cut_np, thr_np = NE.fused_sched_tables(fault, n)
    np.testing.assert_array_equal(cut_np, np.asarray(sched.cut_tbl))
    want_thr = [int(round(float(p) * (1 << 20)))
                for p in np.asarray(sched.drop_tbl, np.float64)]
    np.testing.assert_array_equal(thr_np, want_thr)
    # flat schedule: every row IS the static threshold
    flat = FaultConfig(seed=1, drop_prob=0.1,
                       churn=ChurnConfig(events=((3, 1, 3),)))
    _, thr_flat = NE.fused_sched_tables(flat, n)
    assert (thr_flat == drop_threshold_for(flat)).all()
    # the side-word mask reproduces same_side for every (cut, pair)
    for cut in (-1, 0, 600, n):
        words = np.asarray(render_cut_words(cut, n)).reshape(-1)
        side = words[:n] != 0
        for a, b in ((0, 1), (0, 599), (599, 600), (600, n - 1),
                     (0, n - 1)):
            assert (side[a] == side[b]) == bool(
                NE.same_side(cut, jnp.int32(a), jnp.int32(b))), (cut, a,
                                                                 b)


def test_sharded_round_full_schedule_matches_single_device():
    """The fault-binding wrapper under a MIXED program (event +
    partition window + drop ramp): every plane of the sharded round at
    round r equals the single-device MR kernel run with the explicitly
    assembled operands — alive words at r, the clamped threshold-table
    row, and the rendered cut mask (the operands the compiled loops
    index in-trace)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops.pallas_round import render_cut_words
    n, rumors, n_dev = 128 * 8, 128, 4
    mesh = make_plane_mesh(n_dev)
    rows = mr_rows(n)
    rng = np.random.default_rng(29)
    fault = _mixed_fault()
    planes = init_plane_state(n, rumors, mesh)
    seen = rng.random((n, BITS)) < 0.1
    planes = planes.at[1].set(planes[1] | word_pack(jnp.asarray(seen)))
    bits = _bits(rng, rows)
    step = make_sharded_fused_round(n, mesh, interpret=not ON_TPU,
                                    inject_bits=bits, fault=fault)
    base = NE.fused_base_words(fault, n, 0)
    die_w, rec_w = NE.fused_word_tables(fault, n)
    cut_np, thr_np = NE.fused_sched_tables(fault, n)
    for r in (0, 2, 5):
        out = np.asarray(step(planes, 0, r))
        idx = min(max(r, 0), len(cut_np) - 1)
        aw = NE.fused_alive_words_at(base, die_w, rec_w, r)
        cw = render_cut_words(int(cut_np[idx]), n)
        for p in (0, 1):
            plane_p = jnp.asarray(np.asarray(planes[p]))
            want = fused_multirumor_pull_round(
                plane_p, 0, r, n, 1, interpret=not ON_TPU,
                inject_bits=bits, drop_threshold=int(thr_np[idx]),
                alive_words=aw, cut_words=cw)
            np.testing.assert_array_equal(out[p], np.asarray(want),
                                          err_msg=f"round {r} plane {p}")


def test_fused_partition_stall_and_heal():
    """Partition semantics on the fused kernel, with REAL injected
    randomness: an open cut isolating the origin side stalls the far
    side at zero for the whole window (cross-cut pulls destroyed both
    directions — lost, not deferred), and after the window closes the
    epidemic crosses and completes — the same stall + heal contract
    the XLA engines pin in test_nemesis."""
    from gossip_tpu.config import ChurnConfig, FaultConfig
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops.pallas_round import render_cut_words, word_unpack
    n, rumors, heal = 1024, 4, 5
    rows = mr_rows(n)
    cut = n // 2
    fault = FaultConfig(seed=0, churn=ChurnConfig(
        partitions=((0, heal, cut),)))
    cut_np, _ = NE.fused_sched_tables(fault, n)
    rng = np.random.default_rng(31)
    seen0 = np.zeros((n, rumors), bool)
    seen0[:4, :] = True                     # origins below the cut
    table = word_pack(jnp.asarray(seen0))
    fanout = 2
    for r in range(16):
        idx = min(r, len(cut_np) - 1)
        cw = render_cut_words(int(cut_np[idx]), n)
        table = fused_multirumor_pull_round(
            table, 0, r, n, fanout, interpret=not ON_TPU,
            inject_bits=_bits(rng, rows, fanout), cut_words=cw)
        got = np.asarray(word_unpack(table, n, rumors))
        if r < heal - 1:
            assert not got[cut:].any(), (
                f"round {r}: infection crossed an OPEN partition")
    assert got.all(), "epidemic did not complete after the heal"


def test_fused_churn_sweep_matches_solo_and_validates():
    """parallel/sweep.fused_churn_sweep_curves: per-scenario curves are
    BITWISE the solo fused curve driver's (the sweep is executable
    reuse over the same driver — pinned against drift), and the
    validation matrix rejects schedule-free faults and mixed static
    structure loudly."""
    from gossip_tpu.config import ChurnConfig, FaultConfig
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.parallel.sharded_fused import (
        simulate_curve_sharded_fused)
    from gossip_tpu.parallel.sweep import fused_churn_sweep_curves
    n, rumors, n_dev = 128 * 8, 64, 4
    mesh = make_plane_mesh(n_dev)
    run = RunConfig(seed=0, max_rounds=3)
    faults = NE.mixed_scenarios(4, n, drop_prob=0.05, seed=2)
    res = fused_churn_sweep_curves(n, rumors, run, faults, mesh,
                                   interpret=not ON_TPU)
    assert res.curves.shape == (4, 3)
    for i, f in enumerate(faults):
        covs, _ = simulate_curve_sharded_fused(
            n, rumors, run, mesh, fault=f, interpret=not ON_TPU)
        np.testing.assert_array_equal(res.curves[i], np.asarray(covs))
    assert (res.msgs[:, -1] == 2.0 * n * 3).all()
    with pytest.raises(ValueError, match="churn schedule"):
        fused_churn_sweep_curves(
            n, rumors, run, faults + [FaultConfig(drop_prob=0.5)],
            mesh, interpret=not ON_TPU)
    with pytest.raises(ValueError, match="STATIC fault structure"):
        fused_churn_sweep_curves(
            n, rumors, run,
            faults + [FaultConfig(node_death_rate=0.2, seed=9,
                                  churn=ChurnConfig(
                                      events=((3, 1, 2),)))],
            mesh, interpret=not ON_TPU)


def test_fused_k_scenarios_compile_once(assert_compiles):
    """THE fused amortization acceptance (the tentpole's headline): K=8
    mixed nemesis scenarios — events, partition windows, drop ramps —
    through the plane-sharded fused engine compile EXACTLY once.  The
    memoized curve scan keys WITHOUT the fault config (alive words,
    cut table, threshold table all operands), so scenarios 2..8 are
    pure executable reuses, and a SALTED re-entry (new content, same
    shapes — ops/nemesis.mixed_scenarios' contract) through the sweep
    driver compiles ZERO."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.parallel import sharded_fused as SF
    from gossip_tpu.parallel.sweep import fused_churn_sweep_curves
    n, rumors, n_dev = 128 * 8, 64, 4
    mesh = make_plane_mesh(n_dev)
    run = RunConfig(seed=0, max_rounds=2)
    SF._cached_curve_scan.cache_clear()
    SF._cached_churn_masks.cache_clear()
    faults = NE.mixed_scenarios(8, n, salt=0, drop_prob=0.05, seed=2)
    covs0, _ = SF.simulate_curve_sharded_fused(
        n, rumors, run, mesh, fault=faults[0],
        interpret=not ON_TPU)                  # the only compile
    assert covs0.shape == (2,)
    with assert_compiles(0):
        for f in faults[1:]:
            covs, _ = SF.simulate_curve_sharded_fused(
                n, rumors, run, mesh, fault=f, interpret=not ON_TPU)
            assert covs.shape == (2,)
    # salted re-entry through the sweep driver: same shapes, new
    # schedule content — zero compiles end to end
    with assert_compiles(0):
        res = fused_churn_sweep_curves(
            n, rumors, run,
            NE.mixed_scenarios(8, n, salt=3, drop_prob=0.05, seed=2),
            mesh, interpret=not ON_TPU)
        assert res.curves.shape == (8, 2)


def test_committed_fused_sweep_record():
    """The committed fused amortization artifact
    (artifacts/ledger_fused_sweep_r17.jsonl, tools/fused_sweep_capture
    .py): provenance-carrying; the K>=8-scenario plane-sharded fused
    warm path beat K solo (fresh-compile) reruns by >= 3x — the
    pre-operand cost model, where the drop threshold was a kernel
    compile-time static — and a salted scenario family re-entered the
    executable without a fresh compile leg."""
    import os
    from gossip_tpu.utils import telemetry
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts",
        "ledger_fused_sweep_r17.jsonl")
    evs = telemetry.load_ledger(path, run="last")
    assert evs[0]["ev"] == "provenance"
    assert len(evs[0]["git_commit"]) == 40
    rec = [e for e in evs if e.get("ev") == "fused_sweep_record"][-1]
    assert rec["k"] >= 8 and rec["driver"] == "fused_planes"
    assert rec["accept_3x"] is True
    assert rec["solo_total_ms"] >= 3 * rec["warm_total_ms"]
    assert rec["speedup"] >= 3
    # the salted re-entry (fresh content, same shapes) cost steady
    # walls, not another compile leg
    assert 0 < rec["salted_reentry_ms"] < rec["solo_total_ms"] / 3
    scen = [e for e in evs if e.get("ev") == "fused_sweep_scenario"]
    assert len(scen) == rec["k"]
    # the family mixes all three schedule classes on the FUSED engine
    assert any(s["scenario"]["partitions"] for s in scen)
    assert any(s["scenario"]["ramp"] for s in scen)
    assert any(s["scenario"]["events"] for s in scen)
