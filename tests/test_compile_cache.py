"""Compile-once runtime contract (utils/compile_cache).

Three layers of proof:

  1. **Store mechanics** — a miss writes a serialized executable, the
     next identical lookup hits it, corrupt entries degrade to misses,
     and GOSSIP_COMPILE_CACHE="" disables cleanly.
  2. **Warm-vs-cold bitwise equality, per driver** — every sharded
     driver whose ``timing=`` path goes through the
     ``utils/trace.aot_timed`` chokepoint (sharded / sharded_sparse /
     sharded_fused / the 2-D pod sweep) must produce IDENTICAL outputs
     whether its executable was compiled cold, compiled into the store
     (miss), or deserialized from it (hit) — an executable round-trip
     that changed results would silently corrupt every warm process.
  3. **Cross-process** — process A populates the store, process B must
     hit it and reproduce A's trajectory bitwise (the dry-run contract
     test additionally proves the same for the persistent XLA cache
     layer on whole processes).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.topology import generators as G
from gossip_tpu.utils import compile_cache, telemetry
from gossip_tpu.utils.trace import maybe_aot_timed

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def no_persistent_cache():
    """Suspend the session-scoped XLA persistent cache (conftest) for
    tests asserting the AOT store's miss/hit choreography: with it
    active the "cold" compile can be served warm by the OTHER layer —
    and a persistent-cache-loaded executable cannot enter the store at
    all (the round-trip verify in compile_cache._try_store)."""
    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


@pytest.fixture
def own_cache(tmp_path, monkeypatch, no_persistent_cache):
    """A fresh store dir, made the ambient one (overriding the
    session-scoped conftest dir so hit/miss assertions see only this
    test's traffic)."""
    d = str(tmp_path / "cc")
    monkeypatch.setenv(compile_cache.ENV_VAR, d)
    return d


def test_store_miss_then_hit_bitwise(own_cache):
    f = jax.jit(lambda x: jnp.cumsum(jnp.sin(x) * 3.0))
    x = jnp.arange(64, dtype=jnp.float32)
    c1, s1 = compile_cache.load_or_compile(f, x)
    assert s1 == "miss"
    assert compile_cache.entry_count(own_cache) == 1
    c2, s2 = compile_cache.load_or_compile(f, x)
    assert s2 == "hit"
    np.testing.assert_array_equal(np.asarray(c1(x)), np.asarray(c2(x)))


def test_store_disabled_by_empty_env(tmp_path, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_VAR, "")
    f = jax.jit(lambda x: x * 2)
    _, status = compile_cache.load_or_compile(f, jnp.arange(4))
    assert status == "disabled"
    assert compile_cache.entry_count(str(tmp_path)) == 0


def test_corrupt_entry_degrades_to_miss(own_cache):
    f = jax.jit(lambda x: x + 1)
    x = jnp.arange(8)
    compiled, s1 = compile_cache.load_or_compile(f, x)
    assert s1 == "miss"
    aot = os.path.join(own_cache, "aot")
    (entry,) = os.listdir(aot)
    with open(os.path.join(aot, entry), "wb") as fh:
        fh.write(b"not a pickled executable")
    c2, s2 = compile_cache.load_or_compile(f, x)
    assert s2 == "miss"            # dropped + recompiled, never raised
    np.testing.assert_array_equal(np.asarray(c2(x)), np.arange(8) + 1)


def test_distinct_programs_get_distinct_entries(own_cache):
    x = jnp.arange(8, dtype=jnp.float32)
    _, s1 = compile_cache.load_or_compile(jax.jit(lambda v: v * 2), x)
    _, s2 = compile_cache.load_or_compile(jax.jit(lambda v: v * 3), x)
    # different closed-over constants -> different HLO -> both miss
    assert (s1, s2) == ("miss", "miss")
    assert compile_cache.entry_count(own_cache) == 2
    # shape is part of the key too
    _, s3 = compile_cache.load_or_compile(
        jax.jit(lambda v: v * 2), jnp.arange(16, dtype=jnp.float32))
    assert s3 == "miss"


def test_compile_span_and_counters_reach_ledger(own_cache, tmp_path):
    p = str(tmp_path / "led.jsonl")
    led = telemetry.Ledger(p)
    prev = telemetry.activate(led)
    try:
        f = jax.jit(lambda x: x - 7)
        timing = {}
        out = maybe_aot_timed(f, timing, jnp.arange(4))
        assert timing["compile_cache"] == "miss"
        timing2 = {}
        maybe_aot_timed(f, timing2, jnp.arange(4))
        assert timing2["compile_cache"] == "hit"
        assert int(out[0]) == -7
    finally:
        telemetry.activate(prev)
        led.close()
    events = telemetry.load_ledger(p)
    spans = [e for e in events if e["ev"] == "span_end"
             and e["name"] == "compile"]
    assert [e["cache"] for e in spans] == ["miss", "hit"]
    assert all("key" in e for e in spans)
    counters = {e["name"]: e["total"] for e in events
                if e["ev"] == "counter"}
    assert counters["compile_cache_miss"] == 1
    assert counters["compile_cache_hit"] == 1
    # the driver_timing event carries the verdict alongside the walls
    dts = [e for e in events if e["ev"] == "driver_timing"]
    assert [e["cache"] for e in dts] == ["miss", "hit"]


# timed_split itself is covered through its one production consumer
# (tests/test_bench_contract.py::test_bench_compile_split_measures_
# store_roundtrip, which asserts the (miss, hit) statuses and walls) —
# a second in-process exercise would pay another process-wide
# jax.clear_caches() de-warming for no extra coverage.

# -- warm-vs-cold bitwise equality, driver by driver -------------------

def _mesh(n_devices=4):
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(n_devices)


def _drive_sharded(timing):
    from gossip_tpu.parallel.sharded import simulate_curve_sharded
    covs, msgs, final = simulate_curve_sharded(
        ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2),
        G.erdos_renyi(64, p=0.2, seed=0), RunConfig(seed=0, max_rounds=4),
        _mesh(), fault=FaultConfig(node_death_rate=0.05, drop_prob=0.1,
                                   seed=1), timing=timing)
    return np.asarray(covs), np.asarray(msgs), np.asarray(final.seen)


def _drive_sparse(timing):
    from gossip_tpu.parallel.sharded_sparse import simulate_curve_sparse
    covs, msgs, final, _meta = simulate_curve_sparse(
        ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=2, rumors=5, period=2),
        128, RunConfig(seed=0, max_rounds=4), _mesh(), timing=timing)
    return np.asarray(covs), np.asarray(msgs), np.asarray(final.seen)


def _drive_fused(timing):
    from gossip_tpu.parallel.sharded_fused import (
        make_plane_mesh, simulate_curve_sharded_fused)
    covs, final = simulate_curve_sharded_fused(
        128, 40, RunConfig(seed=0, max_rounds=3), make_plane_mesh(4),
        interpret=True, timing=timing)
    return np.asarray(covs), np.asarray(final)


def _drive_sweep(timing):
    from gossip_tpu.parallel.multislice import make_hybrid_mesh
    from gossip_tpu.parallel.sweep import (SweepPoint,
                                           config_sweep_curves_2d)
    pts = [SweepPoint(mode=m, fanout=f, drop_prob=0.0, period=1, seed=0)
           for m in (C.PUSH, C.PULL) for f in (1, 2)]
    res = config_sweep_curves_2d(
        pts, G.ring(64, k=4), RunConfig(seed=0, max_rounds=3),
        make_hybrid_mesh(2, 2, axis_names=("sweep", "nodes")),
        timing=timing)
    return np.asarray(res.curves), np.asarray(res.msgs)


DRIVERS = {"sharded": _drive_sharded, "sharded_sparse": _drive_sparse,
           "sharded_fused": _drive_fused, "pod_sweep_2d": _drive_sweep}


# pod_sweep_2d rides the slow tier since the log-PR rebalance (~6 s
# flight data): the warm-vs-cold mechanism is driver-generic (the ONE
# trace.aot_timed chokepoint) and stays pinned in-gate by the
# sharded/sparse/fused params; the pod-sweep SURFACE keeps its in-gate
# smokes via the hybrid_2d_sweep dry-run family and the 2-D pod sweep
# parity test (tests/test_config_sweep.py)
@pytest.mark.parametrize(
    "name", [pytest.param(n, marks=pytest.mark.slow)
             if n == "pod_sweep_2d" else n for n in sorted(DRIVERS)])
def test_driver_warm_vs_cold_bitwise(name, tmp_path, monkeypatch,
                                     no_persistent_cache):
    """Cold (store-miss: a real XLA compile) and warm (store-hit: the
    deserialized executable) executions of the same driver call must
    agree BITWISE on every output — the warm path can change walls,
    never values.  (A disabled-cache leg would be the identical
    compile path as the miss leg minus the store write, so it buys no
    extra coverage for a third driver compile.)"""
    drive = DRIVERS[name]
    monkeypatch.setenv(compile_cache.ENV_VAR, str(tmp_path / "cc"))
    t_miss = {}
    cold = drive(t_miss)
    assert t_miss["compile_cache"] == "miss"
    t_hit = {}
    warm = drive(t_hit)
    assert t_hit["compile_cache"] == "hit"
    for a, b in zip(cold, warm):
        np.testing.assert_array_equal(a, b)


_CHILD = r"""
import json, sys
import numpy as np
import jax
sys.path.insert(0, {repo!r})
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu import config as C
from gossip_tpu.topology import generators as G
from gossip_tpu.parallel.sharded import make_mesh, simulate_curve_sharded
timing = {{}}
covs, msgs, final = simulate_curve_sharded(
    ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2),
    G.erdos_renyi(64, p=0.2, seed=0), RunConfig(seed=0, max_rounds=4),
    make_mesh(4), fault=FaultConfig(node_death_rate=0.05, drop_prob=0.1,
                                    seed=1), timing=timing)
print(json.dumps({{"cache": timing["compile_cache"],
                   "covs": np.asarray(covs).tolist(),
                   "digest": int(np.asarray(final.seen).sum())}}))
"""


# ~11 s (txn-PR rebalance): the cross-process reuse claim is proven
# in-gate every session by the dryrun_pair fixture (cold process
# populates, warm process must be ALL-HIT — asserted on the compile
# verdicts in tests/test_graft_entry.py); this store-level twin
# re-proves under -m slow
@pytest.mark.slow
def test_cross_process_populate_then_hit(tmp_path):
    """Process A populates the AOT store; process B — a fresh
    interpreter, same program — must HIT it and reproduce A's
    trajectory bitwise.  The compile-once claim is exactly this
    cross-process reuse; same-process hits (above) would also be
    served by jax's in-memory caches."""
    env = dict(os.environ)
    for hazard in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORM_NAME",
                   "LIBTPU_INIT_ARGS"):
        env.pop(hazard, None)
    env["PYTHONPATH"] = _REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["GOSSIP_COMPILE_CACHE"] = str(tmp_path / "cc")
    env["GOSSIP_TELEMETRY"] = ""

    def run():
        p = subprocess.run([sys.executable, "-c",
                            _CHILD.format(repo=_REPO)],
                           env=env, capture_output=True, text=True,
                           timeout=600)
        assert p.returncode == 0, p.stderr[-2000:]
        return json.loads(p.stdout.splitlines()[-1])

    a = run()
    b = run()
    assert a["cache"] == "miss"
    assert b["cache"] == "hit"
    assert a["covs"] == b["covs"]
    assert a["digest"] == b["digest"] > 0


# -- sweep cache telemetry (satellite) ---------------------------------

def test_pod_sweep_cache_stats_eviction_predicate():
    from collections import namedtuple

    from gossip_tpu.parallel.sweep import _pod_sweep_cache_stats
    Info = namedtuple("CacheInfo", "hits misses maxsize currsize")
    g, ev = _pod_sweep_cache_stats(Info(5, 3, 16, 3), Info(5, 2, 16, 2))
    assert not ev and g["pod_sweep_scan_cache_hits"] == 5
    # a miss while the memo was full: lru evicted to admit this scan
    _, ev = _pod_sweep_cache_stats(Info(0, 17, 16, 16),
                                   Info(0, 16, 16, 16))
    assert ev
    # over-subscribed HISTORY but this call was a memo hit: no warning
    # (the cumulative-totals predicate would cry wolf forever here)
    _, ev = _pod_sweep_cache_stats(Info(9, 17, 16, 16),
                                   Info(8, 17, 16, 16))
    assert not ev
    # a miss while the memo still had room: growth, not eviction
    _, ev = _pod_sweep_cache_stats(Info(0, 4, 16, 4), Info(0, 3, 16, 3))
    assert not ev


# ~7 s (txn-PR rebalance): the eviction-warning predicate stays
# unit-tested above and the 2-D sweep surface stays in-gate via the
# hybrid_2d_sweep dry-run family; the live gauge emission re-proves
# under -m slow
@pytest.mark.slow
def test_pod_sweep_emits_cache_gauges(tmp_path):
    p = str(tmp_path / "led.jsonl")
    led = telemetry.Ledger(p)
    prev = telemetry.activate(led)
    try:
        _drive_sweep(None)
    finally:
        telemetry.activate(prev)
        led.close()
    gauges = {e["name"]: e["value"]
              for e in telemetry.load_ledger(p) if e["ev"] == "gauge"}
    assert "pod_sweep_scan_cache_hits" in gauges
    assert "pod_sweep_scan_cache_misses" in gauges
    assert gauges["pod_sweep_scan_cache_maxsize"] == 16
    assert gauges["pod_sweep_scan_cache_size"] >= 1
