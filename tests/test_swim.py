"""SWIM property tests (SURVEY.md §7 "SWIM semantics in array form: needs
property tests against the protocol description") + sharded bitwise parity.

Properties checked, per the SWIM paper's guarantees:
  * completeness — every failed subject is eventually confirmed DEAD at
    every alive observer;
  * accuracy without loss — with drop_prob=0 an alive subject is never even
    suspected;
  * refutation — with lossy links false suspicions happen, but incarnation
    refutation outruns the (sufficiently long) suspicion timeout, so no
    false confirmation;
  * dead observers freeze — failed nodes stop updating their views.
"""

import jax
import numpy as np
import pytest

from gossip_tpu.config import FaultConfig, ProtocolConfig
from gossip_tpu.models.swim import (
    ALIVE, DEAD, SUSPECT, SwimState, base_alive, decode_status,
    detection_fraction, init_swim_state, make_swim_round,
    resolve_epoch_rounds, subject_window, suggested_suspect_rounds)
from gossip_tpu.parallel.sharded import make_mesh
from gossip_tpu.parallel.sharded_swim import (
    init_sharded_swim_state, make_sharded_swim_round)
from gossip_tpu.topology import generators as G

PROTO = ProtocolConfig(mode="swim", fanout=2, swim_proxies=2,
                       swim_suspect_rounds=4, swim_subjects=4)


def run(step, st, rounds):
    step = jax.jit(step)
    for _ in range(rounds):
        st = step(st)
    return st


def test_completeness_dead_subjects_confirmed_everywhere():
    n, dead = 128, (1, 3)
    step = make_swim_round(PROTO, n, dead_nodes=dead, fail_round=3)
    st = run(step, init_swim_state(n, PROTO.swim_subjects, seed=0), 40)
    status = np.asarray(decode_status(st.wire))
    alive_obs = np.ones(n, bool)
    alive_obs[list(dead)] = False
    assert (status[alive_obs][:, list(dead)] == DEAD).all()
    assert float(detection_fraction(st, dead)) > 0.97


def test_accuracy_no_loss_no_suspicion():
    n = 96
    step = make_swim_round(PROTO, n)           # nobody dies, no drops
    st = init_swim_state(n, PROTO.swim_subjects, seed=1)
    step_j = jax.jit(step)
    for _ in range(30):
        st = step_j(st)
        status = np.asarray(decode_status(st.wire))
        assert (status == ALIVE).all()         # never even SUSPECT
    assert float(st.msgs) > 0


def test_refutation_prevents_false_confirm_under_loss():
    # Lossy links: false suspicions occur, but with the suspicion timeout
    # from suggested_suspect_rounds (long enough for refutation to make the
    # round trip) no alive subject is ever confirmed dead.  SWIM's accuracy
    # guarantee is probabilistic in exactly this timeout (SWIM paper §4);
    # seed pinned.  This also pins the helper to the place its value matters.
    n = 128
    proto = ProtocolConfig(mode="swim", fanout=2, swim_proxies=2,
                           swim_suspect_rounds=suggested_suspect_rounds(n, 2),
                           swim_subjects=4)
    fault = FaultConfig(drop_prob=0.2, seed=3)
    step = jax.jit(make_swim_round(proto, n, fault=fault))
    st = init_swim_state(n, proto.swim_subjects, seed=2)
    suspected_ever = False
    for _ in range(50):
        st = step(st)
        status = np.asarray(decode_status(st.wire))
        suspected_ever |= (status == SUSPECT).any()
        assert not (status == DEAD).any()      # no false confirmation
    assert suspected_ever                      # the test actually bites


def test_incarnation_grows_under_suspicion_churn():
    n = 64
    proto = ProtocolConfig(mode="swim", fanout=2, swim_proxies=1,
                           swim_suspect_rounds=10, swim_subjects=2)
    fault = FaultConfig(drop_prob=0.3, seed=5)
    st = run(make_swim_round(proto, n, fault=fault),
             init_swim_state(n, proto.swim_subjects, seed=4), 40)
    wire = np.asarray(st.wire)
    assert (wire // 2).max() >= 1              # refutations bumped incarnation


def test_dead_observers_freeze():
    n, dead = 64, (7,)
    step = make_swim_round(PROTO, n, dead_nodes=dead, fail_round=2)
    st_mid = run(step, init_swim_state(n, PROTO.swim_subjects, seed=0), 5)
    st_end = run(step, st_mid, 20)
    np.testing.assert_array_equal(np.asarray(st_mid.wire)[7],
                                  np.asarray(st_end.wire)[7])


@pytest.mark.parametrize("topo_fn", [
    # both params ride the slow tier since the CRDT-PR rebalance
    # (tier-1 wall budget): the sharded-swim parity surface keeps its
    # in-gate smoke via test_sharded_rotating_bitwise_parity (the
    # rotating variant runs the same pmax wire merge), the table path
    # via test_sharded_swim_detects_on_powerlaw, and the churn-path
    # parity via tests/test_nemesis.py's SWIM churn pins
    pytest.param(lambda n: None, marks=pytest.mark.slow),
    pytest.param(lambda n: G.erdos_renyi(n, 0.1, seed=6),
                 marks=pytest.mark.slow)],
                         ids=["complete", "er-table"])
def test_sharded_swim_bitwise_parity(topo_fn):
    n, dead = 96, (0, 2)
    fault = FaultConfig(drop_prob=0.15, seed=8)
    topo = topo_fn(n)
    mesh = make_mesh(8)
    single = run(make_swim_round(PROTO, n, dead, 4, fault, topo),
                 init_swim_state(n, PROTO.swim_subjects, seed=9), 12)
    sharded = run(
        make_sharded_swim_round(PROTO, n, mesh, dead, 4, fault, topo),
        init_sharded_swim_state(n, PROTO, mesh, seed=9), 12)
    np.testing.assert_array_equal(np.asarray(sharded.wire)[:n],
                                  np.asarray(single.wire))
    np.testing.assert_array_equal(np.asarray(sharded.timer)[:n],
                                  np.asarray(single.timer))
    assert float(sharded.msgs) == pytest.approx(float(single.msgs))


@pytest.mark.parametrize("impl,max_rounds", [
    # the whole equivalence class rides the slow tier since the
    # CRDT-PR rebalance (tier-1 wall budget): every in-gate SWIM test
    # already RUNS the default 'sort' lowering, so the gate exercises
    # it constantly — what lives here is the scatter-vs-sort-vs-pack
    # bitwise EQUIVALENCE depth, which -m slow re-proves in full
    pytest.param("sort", None, id="sort", marks=pytest.mark.slow),
    pytest.param("pack", 12, id="pack8",            # 8-bit (2*12+3 < 0xFF)
                 marks=pytest.mark.slow),
    pytest.param("pack", 200, id="pack16",          # 16-bit lanes
                 marks=pytest.mark.slow),
    pytest.param("pack", None, id="pack-fallback",  # bound unknown -> sort
                 marks=pytest.mark.slow),
])
def test_dissemination_relowerings_bitwise_equal_scatter(impl, max_rounds):
    """swim_diss='sort'/'pack' are pure relowerings
    (artifacts/swim_ab_r04.json arbitrated sort as default): the whole
    trajectory — single-device AND sharded — must be bitwise identical
    to the scatter control (max-merge is order-independent; empty
    segments clamp to the same 0 floor; pack's transport code is an
    order isomorphism under its round bound).  All impls pinned
    explicitly so the test outlives default flips."""
    n, dead = 96, (0, 2)
    fault = FaultConfig(drop_prob=0.15, seed=8)
    mk = lambda i: ProtocolConfig(mode="swim", fanout=2, swim_proxies=2,
                                  swim_suspect_rounds=4, swim_subjects=4,
                                  swim_diss=i)
    base = run(make_swim_round(mk("scatter"), n, dead, 4, fault),
               init_swim_state(n, 4, seed=9), 12)
    single = run(make_swim_round(mk(impl), n, dead, 4, fault,
                                 max_rounds=max_rounds),
                 init_swim_state(n, 4, seed=9), 12)
    np.testing.assert_array_equal(np.asarray(single.wire),
                                  np.asarray(base.wire))
    np.testing.assert_array_equal(np.asarray(single.timer),
                                  np.asarray(base.timer))
    mesh = make_mesh(8)
    sharded = run(
        make_sharded_swim_round(mk(impl), n, mesh, dead, 4, fault,
                                max_rounds=max_rounds),
        init_sharded_swim_state(n, mk(impl), mesh, seed=9), 12)
    np.testing.assert_array_equal(np.asarray(sharded.wire)[:n],
                                  np.asarray(base.wire))
    assert float(sharded.msgs) == pytest.approx(float(base.msgs))


def test_disseminate_max_pack_unit():
    """Unit contract of the packed transport: bitwise equal to scatter
    on adversarial inputs (DEAD_WIRE rows to exercise the cap remap,
    sentinel targets to exercise the drop, odd S to exercise lane
    padding, wires at the exact proof bound 2*max_rounds+1), at both
    lane widths; width selection follows models/swim.pack_width."""
    import jax.numpy as jnp
    from gossip_tpu.models.swim import DEAD_WIRE, disseminate_max, pack_width
    assert pack_width(None) == 0
    assert pack_width(12) == 8
    assert pack_width(125) == 8
    assert pack_width(126) == 16
    assert pack_width(32765) == 16
    assert pack_width(32766) == 0          # no lane fits: caller falls back
    rng = np.random.default_rng(3)
    for max_rounds in (60, 500):
        n, fanout, s = 257, 3, 5           # odd S: lane padding in play
        targets = jnp.asarray(rng.integers(0, n + 1, size=(n, fanout)),
                              jnp.int32)   # n = silent-sender sentinel
        w = rng.integers(0, 2 * max_rounds + 2, size=(n, s)).astype(np.int32)
        w[rng.random((n, s)) < 0.1] = int(DEAD_WIRE)
        w = jnp.asarray(w)
        base = disseminate_max(targets, w, n, "scatter")
        out = disseminate_max(targets, w, n, "pack", max_rounds)
        assert out.dtype == jnp.int32
        np.testing.assert_array_equal(np.asarray(out), np.asarray(base))


ROTATE = ProtocolConfig(mode="swim", fanout=2, swim_proxies=2,
                        swim_suspect_rounds=4, swim_subjects=8,
                        swim_rotate=True)


PACKED = ProtocolConfig(mode="swim", fanout=2, swim_proxies=2,
                        swim_suspect_rounds=4, swim_subjects=4,
                        swim_rng="packed")


@pytest.mark.parametrize("topo_fn", [lambda n: None,
                                     lambda n: G.erdos_renyi(n, 0.1, seed=6)],
                         ids=["complete", "er-table"])
def test_packed_rng_sharded_bitwise_parity(topo_fn):
    """swim_rng='packed' draws are keyed by GLOBAL node id, so the
    sharded twin must reproduce the single-device trajectory bitwise —
    the same mesh-invariance contract the 'split' scheme carries."""
    n, dead = 96, (0, 2)
    fault = FaultConfig(drop_prob=0.15, seed=8)
    topo = topo_fn(n)
    mesh = make_mesh(8)
    single = run(make_swim_round(PACKED, n, dead, 4, fault, topo),
                 init_swim_state(n, PACKED.swim_subjects, seed=9), 12)
    sharded = run(
        make_sharded_swim_round(PACKED, n, mesh, dead, 4, fault, topo),
        init_sharded_swim_state(n, PACKED, mesh, seed=9), 12)
    np.testing.assert_array_equal(np.asarray(sharded.wire)[:n],
                                  np.asarray(single.wire))
    np.testing.assert_array_equal(np.asarray(sharded.timer)[:n],
                                  np.asarray(single.timer))
    assert float(sharded.msgs) == pytest.approx(float(single.msgs))


def test_packed_rng_detects_and_stays_accurate():
    """The SWIM properties hold under the packed lowering: dead
    subjects confirmed everywhere (completeness), and with no loss an
    alive subject is never suspected (accuracy)."""
    n, dead = 128, (1, 3)
    step = make_swim_round(PACKED, n, dead_nodes=dead, fail_round=3)
    st = run(step, init_swim_state(n, PACKED.swim_subjects, seed=0), 40)
    status = np.asarray(decode_status(st.wire))
    alive_obs = np.ones(n, bool)
    alive_obs[list(dead)] = False
    assert (status[alive_obs][:, list(dead)] == DEAD).all()
    assert float(detection_fraction(st, dead)) > 0.97
    # accuracy: no deaths, no loss -> never even SUSPECT
    st2 = run(make_swim_round(PACKED, n),
              init_swim_state(n, PACKED.swim_subjects, seed=1), 30)
    assert (np.asarray(decode_status(st2.wire)) == ALIVE).all()


def test_packed_rng_field_marginals():
    """Distributional contract of packed_round_draws: every field is
    uniform on its range (loose chi-square-style bound over many
    rounds), peers exclude self on the complete graph, proxies cover
    [0, n), and degree-0 table rows emit the sentinel."""
    from gossip_tpu.models.swim import packed_round_draws
    import jax.numpy as jnp
    n, s_count, proxies, fanout = 64, 4, 3, 2
    gids = jnp.arange(n, dtype=jnp.int32)
    subj_counts = np.zeros(s_count)
    proxy_counts = np.zeros(n)
    peer_counts = np.zeros(n)
    rounds = 200
    base = jax.random.key(3)
    jitted = jax.jit(packed_round_draws, static_argnums=(2, 3, 4, 5, 6))
    for r in range(rounds):
        rkey = jax.random.fold_in(base, r)
        subj, d_drop, proxy_ids, to_p, p_to_s, targets = jitted(
            rkey, gids, s_count, n, proxies, fanout, 0.0)
        subj_counts += np.bincount(np.asarray(subj), minlength=s_count)
        proxy_counts += np.bincount(
            np.asarray(proxy_ids).ravel(), minlength=n)
        t = np.asarray(targets)
        assert ((t >= 0) & (t < n)).all()
        assert (t != np.arange(n)[:, None]).all()      # self excluded
        peer_counts += np.bincount(t.ravel(), minlength=n)
        assert not np.asarray(d_drop).any()            # drop_prob 0
        assert not np.asarray(to_p).any()
    # uniformity: each bucket within 20% of the expected mean
    for counts in (subj_counts, proxy_counts):
        assert counts.min() > counts.mean() * 0.8
        assert counts.max() < counts.mean() * 1.2
    # peers exclude self, so each node is drawn n-1 times out of n(n-1)
    assert peer_counts.min() > peer_counts.mean() * 0.8
    assert peer_counts.max() < peer_counts.mean() * 1.2
    # degree-0 rows emit the sentinel on the table path
    nbrs = jnp.zeros((n, 4), jnp.int32)
    deg = jnp.zeros((n,), jnp.int32).at[0].set(4)
    _, _, _, _, _, t2 = packed_round_draws(
        jax.random.fold_in(base, 0), gids, s_count, n, proxies, fanout,
        0.0, nbrs=nbrs, deg=deg, sentinel=n)
    t2 = np.asarray(t2)
    assert (t2[1:] == n).all()
    assert (t2[0] == 0).all()


def test_packed_rng_drop_coins():
    """Drop coins materialize with drop_prob > 0 at ~the right rate and
    stay independent of the partner fields (distinct words)."""
    from gossip_tpu.models.swim import packed_round_draws
    import jax.numpy as jnp
    n, proxies, fanout, p = 4096, 3, 2, 0.3
    gids = jnp.arange(n, dtype=jnp.int32)
    rkey = jax.random.fold_in(jax.random.key(5), 1)
    _, d_drop, _, to_p, p_to_s, _ = packed_round_draws(
        rkey, gids, 4, n, proxies, fanout, p)
    for mask in (np.asarray(d_drop), np.asarray(to_p),
                 np.asarray(p_to_s)):
        rate = mask.mean()
        assert 0.25 < rate < 0.35, rate


def test_subject_window_covers_all_nodes():
    # Full-membership property: over one full rotation every node id
    # appears in some epoch's window.
    n, s = 50, 8
    e = resolve_epoch_rounds(ROTATE, n)
    seen = set()
    epochs = -(-n // s) + 1          # ceil(n/s) epochs + wrap slack
    for ep in range(epochs):
        seen |= set(np.asarray(subject_window(ep * e, s, n, True, e)
                               ).tolist())
    assert seen == set(range(n))


# non-zero window positions slow since the txn-PR rebalance (~4 s
# each): one position keeps the any-node detection property in-gate;
# the full position sweep re-proves under -m slow
@pytest.mark.parametrize("dead_gid", [
    0,
    pytest.param(29, marks=pytest.mark.slow),
    pytest.param(57, marks=pytest.mark.slow),
    pytest.param(95, marks=pytest.mark.slow)])
def test_rotating_window_detects_any_node(dead_gid):
    # THE full-membership property (VERDICT round 1): a failure among ANY
    # node — not just 0..S-1 — is detected once its window comes around.
    n = 96
    e = resolve_epoch_rounds(ROTATE, n)
    step = jax.jit(make_swim_round(ROTATE, n, dead_nodes=(dead_gid,),
                                   fail_round=0))
    st = init_swim_state(n, ROTATE.swim_subjects, seed=0)
    alive_obs = base_alive(n, (dead_gid,), None)
    total_epochs = -(-n // ROTATE.swim_subjects) + 1
    best = 0.0
    for r in range(e * total_epochs):
        st = step(st)
        w = subject_window(r, ROTATE.swim_subjects, n, True, e)
        best = max(best, float(detection_fraction(
            st, (dead_gid,), alive_obs, subj_gids=w)))
        if best > 0.97:
            break
    assert best > 0.97


def test_rotating_no_false_confirm_and_window_resets():
    # Nobody dies: across several epochs nothing is ever confirmed DEAD,
    # and each epoch starts from a clean (all-ALIVE@0) view table.
    n = 64
    e = resolve_epoch_rounds(ROTATE, n)
    step = jax.jit(make_swim_round(ROTATE, n))
    st = init_swim_state(n, ROTATE.swim_subjects, seed=1)
    for r in range(3 * e):
        st = step(st)
        assert not (np.asarray(decode_status(st.wire)) == DEAD).any()
        if (r + 1) % e == 0 and r + 2 < 3 * e:
            nxt = step(st)      # first round of the new epoch
            # views reset at the boundary: every wire is ALIVE at
            # incarnation 0 or freshly suspected (wire <= 1)
            assert np.asarray(nxt.wire).max() <= 1


def test_sharded_rotating_bitwise_parity():
    n, dead = 96, (57,)
    mesh = make_mesh(8)
    e = resolve_epoch_rounds(ROTATE, n)
    rounds = 2 * e + 3               # cross two epoch boundaries
    single = run(make_swim_round(ROTATE, n, dead, 0),
                 init_swim_state(n, ROTATE.swim_subjects, seed=9), rounds)
    sharded = run(
        make_sharded_swim_round(ROTATE, n, mesh, dead, 0),
        init_sharded_swim_state(n, ROTATE, mesh, seed=9), rounds)
    np.testing.assert_array_equal(np.asarray(sharded.wire)[:n],
                                  np.asarray(single.wire))
    np.testing.assert_array_equal(np.asarray(sharded.timer)[:n],
                                  np.asarray(single.timer))


def test_swim_subjects_must_fit_membership():
    proto = ProtocolConfig(mode="swim", swim_subjects=16)
    with pytest.raises(ValueError, match="swim_subjects"):
        make_swim_round(proto, 8)
    with pytest.raises(ValueError, match="swim_subjects"):
        make_sharded_swim_round(proto, 8, make_mesh(8))


def test_fixed_window_rejects_out_of_window_dead():
    st = init_swim_state(16, 4, seed=0)
    with pytest.raises(ValueError, match="swim_rotate"):
        detection_fraction(st, (9,))


# ~6 s (txn-PR rebalance): sharded SWIM stays pinned in-gate by the
# rotating bitwise parity and the swim_rotating dry-run family; the
# explicit power-law-topology depth re-proves under -m slow
@pytest.mark.slow
def test_sharded_swim_detects_on_powerlaw():
    # The BASELINE.json SWIM config shape (scaled down): power-law topology
    # for dissemination, mesh-sharded state.
    n = 256
    topo = G.power_law(n, m=3, seed=1)
    mesh = make_mesh(8)
    step = make_sharded_swim_round(PROTO, n, mesh, dead_nodes=(2,),
                                   fail_round=2, topo=topo)
    st = run(step, init_sharded_swim_state(n, PROTO, mesh, seed=3), 40)
    frac = float(detection_fraction(
        SwimState(st.wire[:n], st.timer[:n], st.round, st.base_key, st.msgs),
        (2,)))
    assert frac > 0.95


# depth tier (tier-1 wall budget, PR 7 rebalance): the until driver
# keeps in-gate smokes (backend/CLI runs + detects_on_powerlaw); the
# until-vs-curve round-for-round cross-check runs under -m slow
@pytest.mark.slow
def test_swim_until_driver_matches_curve_rounds():
    """The early-exit while_loop driver stops at exactly the round the
    scan driver's curve first hits the target, single-device and
    sharded, rotating included."""
    from gossip_tpu.config import ProtocolConfig
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.runtime.simulator import (simulate_swim_curve,
                                              simulate_swim_until)

    n, target = 256, 0.99
    proto = ProtocolConfig(mode="swim", fanout=2, swim_subjects=4,
                           swim_proxies=2, swim_suspect_rounds=4)
    fracs, _ = simulate_swim_curve(proto, n, 40, dead_nodes=(1,),
                                   fail_round=2, seed=5)
    hit = [i + 1 for i, f in enumerate(fracs) if f >= target]
    rounds, det, peak, final = simulate_swim_until(proto, n, 40, target,
                                                   dead_nodes=(1,),
                                                   fail_round=2, seed=5)
    assert hit and rounds == hit[0]
    assert det >= target
    assert peak >= det
    assert int(final.round) == rounds
    sh_rounds, sh_det, sh_peak, _ = simulate_swim_until(
        proto, n, 40, target, dead_nodes=(1,), fail_round=2, seed=5,
        mesh=make_mesh(8))
    assert (sh_rounds, sh_det, sh_peak) == (rounds, det, peak)
