"""Planted unattributed-compile violations (conventions family).

``bypass_chokepoint`` MUST flag: a raw ``.lower().compile()`` chain
acquires an executable the cost plane never sees — no ``xla_compile``
event, no cache verdict, no attribution (the shape planner/stream's
memory probe had before it migrated onto the chokepoint).
``bypass_jit_inline`` MUST flag too: jitting and chaining in one
expression is the same bypass.  The negative twins must NOT flag:
``measure_chokepoint`` routes through load_or_compile (the sanctioned
acquisition), ``probe_vmem_unattributed`` carries the naming-escape
(a reviewed raw probe, the ``_drain*`` convention applied here), and
``normalize_label`` proves string ``.lower()`` never false-positives.
"""


def bypass_chokepoint(runner, x):
    # MUST flag: the executable exists, the ledger never heard of it
    compiled = runner.lower(x).compile()
    return compiled.memory_analysis()


def bypass_jit_inline(jax, step, x):
    # MUST flag: same chain, built inline from a fresh jit wrapper
    return jax.jit(step).lower(x).compile()


def measure_chokepoint(compile_cache, runner, x):
    # must NOT flag: the ONE sanctioned acquisition path
    compiled, status = compile_cache.load_or_compile(
        runner, x, label="planted")
    return compile_cache.xla_attribution(compiled)


def probe_vmem_unattributed(runner, x):
    # must NOT flag: the declared escape — the function name carries
    # the reviewed rationale, like _drain* for blocking fetches
    return runner.lower(x).compile()


def normalize_label(label):
    # must NOT flag: str.lower() is not a lowering
    return label.lower().strip()
