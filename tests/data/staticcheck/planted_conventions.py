"""Planted convention violations for tests/test_staticcheck.py
(parsed, never executed).  Each construct MUST flag."""


def emit(ledger):
    # `kind` collides with Ledger.event's positional event name
    ledger.event("probe", kind="health")        # MUST FLAG


def _lonely_factory(fault, check_supported):
    # a capability string no other factory registers (singleton)
    check_supported(fault, engine="typo-engine")    # MUST FLAG
