"""Planted sync-emit-in-request-path violations + negative twin for
tests/test_staticcheck.py (parsed, never executed).  The test roots
this module at ``Router.dispatch`` and ``CleanRouter.dispatch``:
``Router`` MUST flag twice (defaulted emit in the root, sync=True in a
reachable helper), ``CleanRouter`` — the same call shape with literal
``sync=False`` everywhere — must stay silent, and the off-path emit
must never flag (reachability, not module scan)."""


class Router:
    def dispatch(self, telemetry, method):
        telemetry.current().event("shed", method=method)    # MUST FLAG
        return self._attempt(telemetry, method)

    def _attempt(self, telemetry, method):
        # sync present but not the literal False
        telemetry.current().event(                          # MUST FLAG
            "dispatch_attempt", sync=True, method=method)


class CleanRouter:
    def dispatch(self, telemetry, method):
        telemetry.current().event("shed", sync=False, method=method)
        return self._attempt(telemetry, method)

    def _attempt(self, telemetry, method):
        telemetry.current().event(
            "dispatch_attempt", sync=False, method=method)


def off_path_report(telemetry):
    # not reachable from any root: a post-run reporter may fsync
    telemetry.current().event("report_done")        # must NOT flag
