"""Planted lock-discipline violations for tests/test_staticcheck.py
(parsed, never executed).  Each construct MUST flag."""

import threading
import time

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def take_ab():
    with LOCK_A:
        with LOCK_B:          # edge A -> B
            return 1


def take_ba():
    with LOCK_B:
        with LOCK_A:          # edge B -> A: cycle MUST FLAG lock-order
            return 2


class PlantedBatcher:
    """The pre-PR-13 batcher shape: stop flag checked OUTSIDE the
    queue lock, and a sleep held under it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._queue = []

    def submit(self, item):
        if self._stop.is_set():      # MUST FLAG stopflag-outside-lock
            raise RuntimeError("closed")
        with self._lock:
            self._queue.append(item)

    def drain(self):
        with self._lock:
            time.sleep(0.01)         # MUST FLAG blocking-under-lock
            q = list(self._queue)
            self._queue.clear()
        return q

    def emit_locked(self, telemetry):
        # held by convention (*_locked): a default-sync ledger emit
        # fsyncs under the lock — MUST FLAG blocking-under-lock
        telemetry.current().event("batch", size=1)

    def ok_emit(self, telemetry):
        with self._lock:
            # sync=False is the sanctioned in-lock emit: must NOT flag
            telemetry.current().event("batch", sync=False, size=1)
