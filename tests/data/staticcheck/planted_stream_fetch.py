"""Planted blocking-fetch-in-segment-loop violations + the negative
twin (tests/test_staticcheck.py proves both directions — the PR 11
a-checker-that-cannot-fail discipline).  Never imported: AST fodder
for gossip_tpu/analysis/recompile.check_stream_fetch only."""

import numpy as np


def stream_segments_serial(tiles, runner, host):
    """The pre-pipeline shape: the fetch blocks inside the tile loop,
    so every tile pays compute + transfer serially.  Both calls below
    MUST flag."""
    for t in range(tiles):
        out = runner(t)
        out.seen.block_until_ready()          # planted: flags
        host[t] = np.asarray(out.seen)        # planted: flags
    return host


def _drain_pending(host, rec):
    """Negative twin: the sanctioned deferred-fetch helper — blocking
    is its JOB (it runs one tile behind the dispatch).  Nothing in
    here may flag, loop or not."""
    for r in rec:
        r.seen.block_until_ready()            # sanctioned: must NOT flag
        host[r.tile] = np.asarray(r.seen)     # sanctioned: must NOT flag
    return host


def stream_segments_pipelined(tiles, runner, host):
    """Negative twin: the three-stage shape — dispatch, then drain the
    PREVIOUS tile through the _drain* helper.  Must NOT flag."""
    pending = None
    for t in range(tiles):
        rec = runner(t)
        if pending is not None:
            _drain_pending(host, [pending])
        pending = rec
    if pending is not None:
        _drain_pending(host, [pending])
    return host
