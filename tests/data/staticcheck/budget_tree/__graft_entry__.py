"""Planted dry-run-budget violation tree: ``fam_unbudgeted`` has no
rows in tools/dryrun_budgets.json (must flag dryrun-budget-row), and
the budgets file names ``fam_ghost`` which no rec() call measures
(must flag the stale-row direction).  Parsed, never executed."""


def rec(name, key, fn):
    return fn()


def _families():
    rec("fam_budgeted", "first_ms", lambda: 1)
    rec("fam_unbudgeted", "first_ms", lambda: 2)        # MUST FLAG
