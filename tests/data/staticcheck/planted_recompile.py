"""Planted recompile-hazard violations for tests/test_staticcheck.py.

Every construct here MUST flag — a checker that cannot fail is not a
checker (the PR 11 txn-checker rule).  This file is never imported or
executed, only parsed (the analyzer excludes tests/data from every
live-tree scan), so the jax imports are props."""

import functools

import jax
import jax.numpy as jnp


def request_handler(specs):
    """Per-request root (request_* naming): both jnp-over-K builds
    below are one tiny XLA program per distinct len(specs)."""
    seeds = jnp.asarray([s.seed for s in specs])          # MUST FLAG
    tables = jnp.stack([s.table for s in specs])          # MUST FLAG
    return _dispatch(seeds, tables)


def _dispatch(seeds, tables):
    """Reachable from the root through the call graph: the per-call
    jit closure retraces every request (the solo-retrace trap)."""
    fn = jax.jit(lambda x: x + 1)                         # MUST FLAG
    return fn(seeds), tables


@functools.lru_cache(maxsize=8)
def _cached_scenario_loop(fault, n):
    """Executable builder keyed on content-named ``fault`` — one
    compiled program per scenario (the _cached_churn_masks bug)."""
    return jax.jit(lambda x: x * n)                       # param MUST FLAG


@functools.lru_cache(maxsize=8)
def _cached_clean_loop(fault_static, n):
    """The declared-static convention: must NOT flag."""
    return jax.jit(lambda x: x * n)


@functools.lru_cache(maxsize=8)
def _cached_byz_loop(liars, quorum, n):
    """Executable builder keyed on liar-program content — one compiled
    program per adversary scenario (liar content is table-tail DATA,
    never shape — ops/nemesis byz_args)."""
    return jax.jit(lambda x: x * n)       # both byz params MUST FLAG


@functools.lru_cache(maxsize=8)
def _cached_byz_clean_loop(byz_static, n):
    """The declared-static escape on the byz vocabulary: must NOT
    flag."""
    return jax.jit(lambda x: x * n)


@functools.lru_cache(maxsize=8)
def _cached_byz_values(byz, n):
    """Caches eager VALUES (no jit in body) keyed on a byz param: the
    build_byz table-lowering pattern itself — must NOT flag."""
    return tuple(range(n))


def request_nested(specs):
    """A violation inside a nested helper must count ONCE even though
    both the enclosing walk and the nested def's own root cover it
    (the dedup contract)."""
    def helper(items):
        return jnp.stack([i.row for i in items])          # MUST FLAG x1
    return helper(specs)
