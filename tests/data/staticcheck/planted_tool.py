"""Planted artifact-writer-provenance violation (a tools/-shaped
script that writes an artifact without ever referencing
telemetry.provenance()/Ledger).  Parsed, never executed."""

import json
import os

ART = os.path.join("artifacts", "planted_lint_demo.json")


def write():
    with open(ART, "w") as f:                   # MUST FLAG
        json.dump({"ok": True}, f)
