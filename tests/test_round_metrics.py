"""Device-resident round metrics (ops/round_metrics): buffer contract,
chokepoint flush, and the two load-bearing invariants — metrics change
NO trajectory bit, and a while_loop that exits early reports exactly
the rounds it ran."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gossip_tpu import config as C
from gossip_tpu.config import ProtocolConfig, RunConfig
from gossip_tpu.ops import round_metrics as RM
from gossip_tpu.topology import generators as G
from gossip_tpu.utils import telemetry


def test_record_under_jit_and_cursor_clamp():
    m = RM.init(3, 2, "unit")

    @jax.jit
    def f(m):
        for r in range(5):      # two writes past the end: clamped
            m = RM.record(m, newly=r, dup=0, msgs=1, bytes=8,
                          front=jnp.array([0.1, 0.2]))
        return m
    out = f(m)
    assert int(out.cursor) == 5
    # rows 0..2 written in order, the overflow writes land on the last
    # row (never out of bounds)
    assert np.asarray(out.newly).tolist() == [0.0, 1.0, 4.0]


def test_init_validates():
    with pytest.raises(ValueError):
        RM.init(0, 1, "x")
    with pytest.raises(ValueError):
        RM.init(4, 0, "x")


def test_counter_helpers_match_numpy():
    rng = np.random.RandomState(0)
    seen = rng.rand(16, 3) < 0.4
    alive = rng.rand(16) < 0.8
    got = float(RM.count_bool(jnp.asarray(seen), jnp.asarray(alive)))
    assert got == float((seen & alive[:, None]).sum())
    front = np.asarray(RM.front_bool(jnp.asarray(seen),
                                     jnp.asarray(alive), 4))
    for s in range(4):
        rows = slice(4 * s, 4 * s + 4)
        cov = (seen[rows].any(1) & alive[rows]).sum()
        tot = max(alive[rows].sum(), 1)
        assert front[s] == pytest.approx(cov / tot)


def test_gate_on_exchange_rounds_matches_kernel_predicate():
    """The ONE quiescent-round gate every recorder shares: full value
    on exchange rounds (round % period == 0), ``off`` otherwise,
    untouched at period <= 1."""
    g = RM.gate_on_exchange_rounds
    assert float(g(10.0, 1, jnp.int32(1))) == 10.0
    assert float(g(10.0, 3, jnp.int32(0))) == 10.0
    assert float(g(10.0, 3, jnp.int32(3))) == 10.0
    assert float(g(10.0, 3, jnp.int32(1))) == 0.0
    assert float(g(10.0, 3, jnp.int32(2), off=4.0)) == 4.0


def test_payload_factor_covers_every_si_mode():
    assert RM.payload_factor(C.PUSH) == 1.0
    assert RM.payload_factor(C.PULL) == 0.5
    assert RM.payload_factor(C.PUSH_PULL) == pytest.approx(2 / 3)
    assert RM.payload_factor(C.ANTI_ENTROPY) == pytest.approx(2 / 3)
    assert RM.payload_factor(C.FLOOD) == 1.0
    # dup can never go negative, whatever the estimator feeds it
    assert float(RM.dup_estimate(3.0, 10.0)) == 0.0


def test_wanted_requires_env_and_active_ledger(tmp_path, monkeypatch):
    monkeypatch.delenv(RM.ENV_VAR, raising=False)
    assert RM.enabled()                      # default on
    monkeypatch.setenv(RM.ENV_VAR, "0")
    assert not RM.enabled() and not RM.wanted()
    monkeypatch.delenv(RM.ENV_VAR, raising=False)
    # env on but no active ledger: buffers would be dead weight
    assert not RM.wanted()
    led = telemetry.Ledger(str(tmp_path / "l.jsonl"))
    prev = telemetry.activate(led)
    try:
        assert RM.wanted()
    finally:
        telemetry.activate(prev)
        led.close()


@pytest.fixture
def mesh8():
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(8)


def _dense_curve(mesh, max_rounds=6):
    from gossip_tpu.parallel.sharded import simulate_curve_sharded
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    run = RunConfig(seed=0, max_rounds=max_rounds, target_coverage=0.99)
    return proto, simulate_curve_sharded(proto, topo, run, mesh)


def test_metrics_change_no_trajectory_bit_and_flush_once(tmp_path,
                                                         mesh8):
    """THE invariant: the instrumented loop's public outputs are
    bitwise the un-instrumented loop's (metrics consume no RNG and
    mask nothing), and the flush is one ledger event per driver call
    with internally consistent series."""
    proto, (covs0, msgs0, _) = _dense_curve(mesh8)

    led = telemetry.Ledger(str(tmp_path / "led.jsonl"))
    prev = telemetry.activate(led)
    try:
        _, (covs1, msgs1, final) = proto, _dense_curve(mesh8)[1]
    finally:
        telemetry.activate(prev)
        led.close()
    assert np.array_equal(covs0, covs1)
    assert np.array_equal(msgs0, msgs1)

    events = telemetry.load_ledger(led.path)
    rms = [e for e in events if e["ev"] == "round_metrics"]
    assert len(rms) == 1                    # once per driver call
    e = rms[0]
    assert e["driver"] == "simulate_curve_sharded"
    assert e["rounds"] == 6 and e["shards"] == 8
    for series in ("newly", "dup", "msgs", "bytes"):
        assert len(e[series]) == 6
    assert len(e["front"]) == 6 and len(e["front"][0]) == 8
    # conservation: newly sums to the entries the run actually set
    # (n=64 divides the mesh, no fault -> every row alive; the run
    # starts with exactly R origin entries)
    final_entries = int(np.asarray(final.seen).sum())
    assert e["totals"]["newly"] == final_entries - proto.rumors
    # msgs series telescopes to the driver's own cumulative counter
    assert e["totals"]["msgs"] == pytest.approx(float(msgs1[-1]))
    # the coverage front ends where the coverage curve ends
    assert e["front_final"] == [pytest.approx(1.0)] * 8


def test_until_driver_truncates_to_rounds_run(tmp_path, mesh8):
    """A while_loop that converges early reports exactly the rounds it
    executed — the preallocated tail rows stay unreported."""
    from gossip_tpu.parallel.sharded import simulate_until_sharded
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    run = RunConfig(seed=0, max_rounds=50, target_coverage=0.99)
    led = telemetry.Ledger(str(tmp_path / "led.jsonl"))
    prev = telemetry.activate(led)
    try:
        rounds, cov, msgs, _ = simulate_until_sharded(proto, topo, run,
                                                      mesh8)
    finally:
        telemetry.activate(prev)
        led.close()
    assert rounds < 50 and cov >= 0.99
    e = [x for x in telemetry.load_ledger(led.path)
         if x["ev"] == "round_metrics"][0]
    assert e["driver"] == "simulate_until_sharded"
    assert e["rounds"] == rounds
    assert len(e["newly"]) == rounds
    assert e["totals"]["msgs"] == pytest.approx(msgs)


def test_aot_path_emits_metrics_with_fn_name(tmp_path, mesh8):
    """The timing= (AOT chokepoint) path flushes the same stack and
    names the jitted fn — the dry run's fused rows rely on exactly
    this wiring."""
    from gossip_tpu.parallel.sharded import simulate_curve_sharded
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=2)
    run = RunConfig(seed=0, max_rounds=4)
    led = telemetry.Ledger(str(tmp_path / "led.jsonl"))
    prev = telemetry.activate(led)
    timing = {}
    try:
        simulate_curve_sharded(proto, topo, run, mesh8, timing=timing)
    finally:
        telemetry.activate(prev)
        led.close()
    assert "steady_s" in timing             # the AOT split still fills
    events = telemetry.load_ledger(led.path)
    e = [x for x in events if x["ev"] == "round_metrics"][0]
    assert e["fn"] == "scan"
    # pull: 2 messages per request, half carry payload — dup plus
    # newly accounts for every offered entry (estimator arithmetic)
    for dup, newly, msgs in zip(e["dup"], e["newly"], e["msgs"]):
        offered = proto.rumors * RM.payload_factor(C.PULL) * msgs
        assert dup == pytest.approx(max(offered - newly, 0.0), abs=0.1)
