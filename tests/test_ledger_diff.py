"""tools/ledger_diff: the cross-run regression gate — verdict-aware
wall joins, threshold+floor flagging, protocol-total drift detection,
and the tier-1 gate runs: the committed 4-device record vs the live
dryrun_pair, plus an artificially injected 2x wall regression that
MUST be flagged."""

import importlib.util
import json
import os

import pytest

from gossip_tpu.utils import telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "ledger_diff", os.path.join(_REPO, "tools", "ledger_diff.py"))
ledger_diff = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ledger_diff)

R09_4DEV = os.path.join(_REPO, "artifacts",
                        "ledger_dryrun_r09_4dev.jsonl")
R09_8DEV = os.path.join(_REPO, "artifacts", "ledger_dryrun_r09.jsonl")
# the byzantine-nemesis PR's 4-device record: same family set as the
# live dry run (churn_heal, churn_sweep, crdt_counter, serving_batch,
# kafka_log, txn_register, fused_churn_sweep, fleet_failover,
# scale_plan, mesh_serving, request_trace, scale_stream_overlap,
# cost_attribution AND byzantine_conv included), so the tier-1 gate
# compares every family like-for-like; r24 (observability PR) stays
# committed as history but predates the byzantine_conv family
R25_4DEV = os.path.join(_REPO, "artifacts",
                        "ledger_dryrun_r25_4dev.jsonl")


def _write_run(path, families, device_count=4, metrics=None,
               verdict="hit"):
    """A minimal synthetic dry-run ledger run: provenance, runtime,
    family + first_ms compile events, optional round_metrics."""
    with telemetry.Ledger(path) as led:
        led.event("runtime", backend="cpu", device_count=device_count)
        for fam, row in families.items():
            led.event("family", family=fam, **row)
            led.event("compile", family=fam, phase="first_ms",
                      cache=verdict)
        for drv, totals in (metrics or {}).items():
            led.event("round_metrics", driver=drv, fn="scan", rounds=4,
                      shards=device_count, newly=[1.0], dup=[0.0],
                      msgs=[10.0], bytes=[64.0], front=[[1.0]],
                      totals=totals, front_final=[1.0])


BASE = {"dense_pushpull": {"first_ms": 600.0, "steady_ms": 4.0},
        "sparse_antientropy": {"first_ms": 900.0, "steady_ms": 7.0}}
MET = {"simulate_until_sharded_fused":
       {"newly": 254.0, "dup": 1000.0, "msgs": 4096.0, "bytes": 8.0}}


def test_identical_runs_diff_clean(tmp_path, capsys):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_run(a, BASE, metrics=MET)
    _write_run(b, BASE, metrics=MET)
    rc = ledger_diff.main([a, b])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Verdict: clean" in out
    assert "REGRESSED" not in out


def test_injected_2x_wall_regression_is_flagged(tmp_path, capsys):
    """The acceptance case: ONE family's walls doubled against a
    steady pack must trip the gate.  A code regression is
    family-shaped, so the pair's median drift stays 1.0 and the full
    2x survives calibration; the first_ms delta clears the absolute
    floor."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    pack = {f"fam{i}": {"first_ms": 500.0 + 40 * i, "steady_ms": 4.0}
            for i in range(4)}
    pack["dense_pushpull"] = {"first_ms": 600.0, "steady_ms": 4.0}
    _write_run(a, pack, metrics=MET)
    injected = {f: dict(row) for f, row in pack.items()}
    injected["dense_pushpull"] = {
        k: 2 * v for k, v in pack["dense_pushpull"].items()}
    _write_run(b, injected, metrics=MET)
    rc = ledger_diff.main([a, b])
    out = capsys.readouterr().out
    assert rc == 1
    assert "dense_pushpull first_ms regressed" in out
    # the small steady walls (4 -> 8 ms) stay under the 50 ms floor:
    # CPU-noise-sized deltas never gate, whatever their ratio
    assert "steady_ms regressed" not in out


def test_uniform_host_drift_is_calibrated_out(tmp_path, capsys):
    """The flake that motivated calibration: EVERY wall inflated 2x
    uniformly (a dry run at the tail of a loaded CI session) must NOT
    gate — the pair's median drift absorbs it — and the report states
    the drift it divided out."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_run(a, BASE, metrics=MET)
    doubled = {f: {k: 2 * v for k, v in row.items()}
               for f, row in BASE.items()}
    _write_run(b, doubled, metrics=MET)
    rc = ledger_diff.main([a, b])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Verdict: clean" in out
    assert "median drift" in out and "2.00x" in out


def test_verdict_mismatch_skips_first_ms(tmp_path, capsys):
    """Cold-vs-warm must not read as a regression: a verdict mismatch
    reports a join note instead of comparing first_ms."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_run(a, BASE, verdict="hit")
    slow = {f: {"first_ms": 10 * row["first_ms"],
                "steady_ms": row["steady_ms"]}
            for f, row in BASE.items()}
    _write_run(b, slow, verdict="miss")
    rc = ledger_diff.main([a, b])
    out = capsys.readouterr().out
    assert rc == 0
    assert "first_ms not compared" in out


def test_metric_drift_flags_only_at_same_device_count(tmp_path,
                                                      capsys):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    c = str(tmp_path / "c.jsonl")
    drifted = {"simulate_until_sharded_fused":
               {**MET["simulate_until_sharded_fused"], "msgs": 5000.0}}
    _write_run(a, BASE, metrics=MET, device_count=4)
    _write_run(b, BASE, metrics=drifted, device_count=4)
    rc = ledger_diff.main([a, b])
    assert rc == 1
    assert "msgs drifted" in capsys.readouterr().out
    # same drift across DIFFERENT device counts: informational only
    _write_run(c, BASE, metrics=drifted, device_count=8)
    rc = ledger_diff.main([a, c])
    out = capsys.readouterr().out
    assert rc == 0
    assert "device counts differ" in out


def test_over_budget_new_run_is_flagged(tmp_path, capsys):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_run(a, BASE)
    over = dict(BASE)
    over["dense_pushpull"] = {"first_ms": 600.0, "steady_ms": 151.0}
    _write_run(b, over)
    rc = ledger_diff.main([a, b])
    out = capsys.readouterr().out
    assert rc == 1
    assert "over budget" in out


def test_lone_family_regression_cannot_self_calibrate(tmp_path,
                                                      capsys):
    """Leave-one-out drift: a family is judged against its PEERS'
    median, so a regression with no (or few) comparable peers cannot
    absorb its own signal — one family regressing 10x must flag even
    though the pair-wide median ratio IS 10x (and even without a
    budget-table backstop: the family name is off the budget table)."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    solo = {"solo_fam": {"first_ms": 600.0, "steady_ms": 4.0}}
    _write_run(a, solo)
    _write_run(b, {"solo_fam": {"first_ms": 6000.0, "steady_ms": 4.0}})
    rc = ledger_diff.main([a, b])
    out = capsys.readouterr().out
    assert rc == 1
    assert "solo_fam first_ms regressed" in out


def test_unknown_run_id_errors_instead_of_clean(tmp_path):
    """A typo'd --run-new id must ERROR, never diff an empty run and
    exit 0 — this tool is a CI gate."""
    a = str(tmp_path / "a.jsonl")
    _write_run(a, BASE)
    with pytest.raises(SystemExit, match="not in"):
        ledger_diff.main([a, a, "--run-new", "no_such_run"])


def test_repeated_driver_labels_keep_every_invocation(tmp_path,
                                                      capsys):
    """Two dry-run families share one driver label (the fused plain and
    fault-curve families both flush ``simulate_*_sharded_fused``): the
    join keys them by invocation order (``#k``), so a drift in the
    FIRST invocation's totals is flagged, not silently overwritten by
    the second."""
    def write(path, first_msgs):
        with telemetry.Ledger(path) as led:
            led.event("runtime", backend="cpu", device_count=4)
            led.event("family", family="f", steady_ms=4.0)
            led.event("compile", family="f", phase="first_ms",
                      cache="hit")
            for msgs in (first_msgs, 4096.0):
                led.event("round_metrics", driver="shared_drv",
                          fn="scan", rounds=2, shards=4, newly=[1.0],
                          dup=[0.0], msgs=[msgs], bytes=[8.0],
                          front=[[1.0]], totals={"msgs": msgs},
                          front_final=[1.0])
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    write(a, 1000.0)
    write(b, 2000.0)                       # only invocation #0 drifts
    rc = ledger_diff.main([a, b])
    out = capsys.readouterr().out
    assert rc == 1
    assert "shared_drv#0" in out and "shared_drv#1" in out
    assert "round_metrics[shared_drv#0].msgs drifted" in out


# -- the committed-record gates (tier-1 acceptance) -------------------

def test_committed_4dev_record_vs_fresh_dryrun_is_clean(dryrun_pair,
                                                        capsys):
    """THE regression gate: the committed 4-device warm record diffed
    against this session's live warm dry run (same device count, same
    machine class) must come back clean — walls within threshold+floor,
    budgets held, protocol totals compared at equal device count.
    Since the byzantine-nemesis PR the committed record is r25, whose
    family set includes churn_heal, churn_sweep, crdt_counter,
    serving_batch, kafka_log, txn_register, fused_churn_sweep,
    fleet_failover, scale_plan, mesh_serving, request_trace,
    scale_stream_overlap, cost_attribution AND byzantine_conv (the
    defended sharded step under a mixed fail-stop + liar program, with
    a salted steady re-entry), so the adversarial family's walls gate
    like every other family.

    Thresholds are calibrated to this container's measured noise: a
    full-suite run swings individual families' warm FIRST-call walls
    up to ~4x NON-uniformly (a live dense_pushpull leg measured
    2676 ms against a 696 ms committed baseline while the pair median
    drifted only 1.56x — the leave-one-out calibration cannot absorb
    a non-uniform blip), so per-family first_ms wall flags are
    DISABLED here (``--first-floor-ms 10000``): a real de-warm is
    already caught four times over, wall-independently — the
    expect_warm guard latches on the cache verdict inside the fixture,
    the live-pair contract test asserts every warm compile event is a
    hit, the aggregate warm*2 <= cold ratio, and the committed-record
    >= 3x pins.  ``--steady-floor-ms 150``: typical steady is
    2-40 ms; anything past 150 ms IS the budget band, and the diff's
    own absolute budget check — which never flaked — flags it.  The
    first_ms wall mechanism itself stays pinned on the synthetic
    fixtures above and the injected-regression test below."""
    rc = ledger_diff.main([R25_4DEV,
                           dryrun_pair["warm"]["ledger_path"],
                           "--first-floor-ms", "10000",
                           "--steady-floor-ms", "150"])
    out = capsys.readouterr().out
    assert rc == 0, f"ledger_diff flagged a fresh dry run:\n{out}"
    assert "Verdict: clean" in out
    # every family joined — nothing fell out as an only-in-one note
    assert "crdt_counter" in out and "serving_batch" in out
    assert "kafka_log" in out and "txn_register" in out
    assert "fused_churn_sweep" in out and "fleet_failover" in out
    assert "scale_plan" in out and "mesh_serving" in out
    assert "request_trace" in out and "scale_stream_overlap" in out
    assert "cost_attribution" in out and "byzantine_conv" in out
    assert "only in" not in out
    # the metric join actually engaged (same device count, fused
    # drivers instrumented in both)
    assert "simulate_until_sharded_fused" in out


def test_committed_record_with_injected_2x_wall_is_flagged(tmp_path,
                                                           capsys):
    """The committed record with ONE family's walls doubled (a
    faithful in-place edit of its own `family` events) must trip the
    gate — a family-shaped 2x on real data survives the median-drift
    calibration that forgives uniform host load, proving the
    thresholds catch a real regression, not just synthetic
    fixtures."""
    events = telemetry.load_ledger(R25_4DEV)
    runs = [e["run"] for e in events if e.get("ev") == "provenance"]
    warm = runs[-1]
    doubled = str(tmp_path / "doubled.jsonl")
    # churn_sweep carries one of the record's largest warm first-call
    # walls (~733 ms in r25), so its doubled delta clears a 500 ms
    # floor — the injection proves the wall mechanism fires on REAL
    # committed data at a noise-hardened floor (warm-wall jitter is
    # tens of ms; the tier-1 like-for-like gate above goes further and
    # hands first_ms detection to the cache-verdict assertions
    # entirely; this pin keeps the wall path honest for manual/CLI
    # use)
    with open(R25_4DEV) as f, open(doubled, "w") as g:
        for line in f:
            if not line.strip():
                continue
            e = json.loads(line)
            if (e.get("ev") == "family" and e.get("run") == warm
                    and e.get("family") == "churn_sweep"):
                for k in ("first_ms", "steady_ms"):
                    if isinstance(e.get(k), (int, float)):
                        e[k] = 2 * e[k]
            g.write(json.dumps(e) + "\n")
    rc = ledger_diff.main([R25_4DEV, doubled, "--first-floor-ms",
                           "500", "--steady-floor-ms", "150"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "churn_sweep first_ms regressed" in out


def test_churn_sweep_family_gates_like_every_other(tmp_path, capsys):
    """The new churn_sweep dry-run family rides the same gates: a
    family-shaped wall regression against a steady pack is flagged,
    and a steady wall past its tools/dryrun_budgets.json row trips the
    budget check — no special-casing anywhere (the gate is generic by
    family name; this pins that the budget row exists and engages)."""
    pack = {f"fam{i}": {"first_ms": 500.0 + 40 * i, "steady_ms": 4.0}
            for i in range(4)}
    pack["churn_sweep"] = {"first_ms": 900.0, "steady_ms": 40.0}
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_run(a, pack)
    regressed = {f: dict(row) for f, row in pack.items()}
    regressed["churn_sweep"] = {"first_ms": 2700.0, "steady_ms": 350.0}
    _write_run(b, regressed)
    rc = ledger_diff.main([a, b])
    out = capsys.readouterr().out
    assert rc == 1
    assert "churn_sweep first_ms regressed" in out
    assert "churn_sweep steady_ms regressed" in out
    # 350 ms also breaches the committed budget row (300 ms)
    assert "over budget 300" in out


def test_committed_r09_cold_vs_warm_self_diff_is_clean(capsys):
    """Within the committed 8-device record, cold run vs warm run:
    the verdict-aware join refuses the cold-vs-warm first_ms
    comparison (miss vs hit) and the steady walls agree — the
    committed artifact demonstrates the join semantics by itself."""
    rc = ledger_diff.main([R09_8DEV, R09_8DEV, "--run-old", "first",
                           "--run-new", "last"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "first_ms not compared" in out
    assert "Verdict: clean" in out
