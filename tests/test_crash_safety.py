"""Crash-tolerant fault programs (the utils/checkpoint crash contract).

Three layers under test:

* **Corrupt/partial checkpoints** — a truncated or foreign file raises
  ``ValueError`` NAMING the file (never a raw ``zipfile``/``KeyError``
  traceback), the CLI ``--resume`` refuses it with a one-line error,
  and a stale ``path + ".tmp"`` stranded by a kill between the tmp
  write and ``os.replace`` is cleaned on the next save and never read.
* **Resume == straight run under an ACTIVE fault program**, bitwise,
  for every checkpointed driver that came off the nemesis rejection
  list (SI single-device, sharded packed, rumor, SWIM, fused planes) —
  including a resume landing INSIDE an open partition window and
  mid-ramp, and the exact destroyed-message total carried across the
  kill (``extra['dropped']`` -> ``lost_prefix``).
* **No-churn checkpointed trajectories are unchanged**: the
  ``ckpt-static:*`` fingerprints in tests/data/churn_fingerprints_r06
  .json were captured from the PRE-lift tree (PR 6, git 2f4d850);
  the lifted drivers must reproduce them bitwise.

The live SIGKILL harness is tools/crashloop.py (single-kill smoke at
the bottom; the committed 3-kill record is
artifacts/ledger_crashloop_r12.jsonl).
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from gossip_tpu.config import (ChurnConfig, FaultConfig, ProtocolConfig,
                               RunConfig)
from gossip_tpu.topology import generators as G
from gossip_tpu.utils.checkpoint import (load_meta, load_state,
                                         run_with_checkpoints,
                                         save_state)

import _churn_surfaces as CS

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           "PYTHONPATH": _REPO}

# events + partition window + drop ramp: every schedule feature the SI
# engines honor.  The partition window [2, 6) and ramp [1, 4) straddle
# the resume points below BY DESIGN: the kill lands inside an open
# window and mid-ramp.
_FAULT = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
    events=((3, 2, 5), (7, 1, -1)),
    partitions=((2, 6, 32),),
    ramp=(1, 4, 0.0, 0.3)))
_N = 64


def _cli(*argv):
    return subprocess.run([sys.executable, "-m", "gossip_tpu", *argv],
                          capture_output=True, text=True, cwd=_REPO,
                          env=CLI_ENV, timeout=240)


# ---------------------------------------------------------------------
# corrupt / partial checkpoints
# ---------------------------------------------------------------------

def _valid_checkpoint(tmp_path, name="ok.npz"):
    from gossip_tpu.models.state import init_state
    p = str(tmp_path / name)
    proto = ProtocolConfig(mode="pushpull", fanout=1)
    save_state(p, init_state(RunConfig(seed=0), proto, 16),
               extra_meta={"k": 1})
    return p


def test_load_corrupt_names_file(tmp_path):
    # truncated npz: a real checkpoint cut mid-archive
    p = _valid_checkpoint(tmp_path)
    raw = open(p, "rb").read()
    trunc = str(tmp_path / "trunc.npz")
    with open(trunc, "wb") as f:
        f.write(raw[:len(raw) // 3])
    for loader in (load_meta, load_state):
        with pytest.raises(ValueError, match="trunc.npz"):
            loader(trunc)
    # non-npz imposter
    imp = str(tmp_path / "imposter.npz")
    with open(imp, "wb") as f:
        f.write(b"not a zip archive at all")
    with pytest.raises(ValueError, match="imposter.npz"):
        load_meta(imp)
    # a missing file stays FileNotFoundError (absent != corrupt)
    with pytest.raises(FileNotFoundError):
        load_meta(str(tmp_path / "nope.npz"))


def test_load_foreign_npz_and_unknown_class(tmp_path):
    # a VALID npz that is not a gossip_tpu checkpoint: no __meta__
    foreign = str(tmp_path / "foreign.npz")
    np.savez(foreign, a=np.arange(3))
    with pytest.raises(ValueError, match="foreign.npz"):
        load_meta(foreign)
    # unknown state class / missing array entry named by the metadata
    bogus = str(tmp_path / "bogus.npz")
    np.savez(bogus, __meta__=json.dumps(
        {"cls": "NoSuchState", "fields": ["x"], "key_field": None}))
    with pytest.raises(ValueError, match="NoSuchState"):
        load_state(bogus)
    torn = str(tmp_path / "torn.npz")
    np.savez(torn, __meta__=json.dumps(
        {"cls": "SimState", "fields": ["seen"], "key_field": None}))
    with pytest.raises(ValueError, match="torn.npz"):
        load_state(torn)
    # incomplete metadata (keyed state, no key_impl): its OWN diagnosis,
    # never misreported as a truncated array write
    incomp = str(tmp_path / "incomplete.npz")
    np.savez(incomp, __meta__=json.dumps(
        {"cls": "SimState", "fields": ["seen", "base_key"],
         "key_field": "base_key"}), seen=np.zeros((4, 1), bool),
        base_key=np.zeros((2,), np.uint32))
    with pytest.raises(ValueError, match="incomplete"):
        load_state(incomp)


def test_load_mid_archive_corruption_names_file(tmp_path):
    """Corruption that leaves the zip central directory (at EOF)
    intact: np.load opens fine and __meta__ parses, then a MEMBER read
    fails its CRC — still the crash contract's ValueError naming the
    file, never a raw zipfile/zlib traceback."""
    p = _valid_checkpoint(tmp_path, "midrot.npz")
    raw = bytearray(open(p, "rb").read())
    # flip bytes inside the member data region (past the first local
    # headers, well before the central directory at EOF)
    mid = len(raw) // 2
    for i in range(mid, mid + 16):
        raw[i] ^= 0xFF
    with open(p, "wb") as f:
        f.write(raw)
    with pytest.raises(ValueError, match="midrot.npz"):
        load_state(p)
    p = _valid_checkpoint(tmp_path)
    good = load_meta(p)
    # a kill between the tmp write and os.replace strands the sibling
    with open(p + ".tmp", "wb") as f:
        f.write(b"partial garbage from a killed writer")
    # loads never look at it
    assert load_meta(p) == good
    # the next save removes the stranded partial before writing
    from gossip_tpu.models.state import init_state
    save_state(p, init_state(RunConfig(seed=1),
                             ProtocolConfig(mode="pushpull", fanout=1),
                             16), extra_meta={"k": 2})
    assert not os.path.exists(p + ".tmp")
    assert load_meta(p)["extra"] == {"k": 2}


def test_cli_resume_corrupt_checkpoint_clean_error(tmp_path):
    bad = str(tmp_path / "corrupt.npz")
    with open(bad, "wb") as f:
        f.write(b"PK\x03\x04 torn by a filesystem crash")
    r = _cli("run", "--mode", "pushpull", "--n", "64",
             "--max-rounds", "4", "--checkpoint", bad, "--resume")
    assert r.returncode == 2
    assert "error:" in r.stderr and "corrupt.npz" in r.stderr
    assert "Traceback" not in r.stderr


# ---------------------------------------------------------------------
# resume == straight run under an active fault program, bitwise
# ---------------------------------------------------------------------

def _si_leg(tmp_path, name, rounds, resume_state=None, lost_prefix=0.0):
    from gossip_tpu.models.si import make_si_round
    from gossip_tpu.models.state import init_state
    proto = ProtocolConfig(mode="pushpull", fanout=2, rumors=2)
    step, tables = make_si_round(proto, G.complete(_N), _FAULT, 0,
                                 tabled=True)
    state = (resume_state if resume_state is not None
             else init_state(RunConfig(seed=0), proto, _N))
    p = str(tmp_path / name)
    fin = run_with_checkpoints(step, state,
                               rounds - int(state.round), p, every=3,
                               step_args=tables, track_lost=True,
                               lost_prefix=lost_prefix)
    return fin, p


@pytest.mark.parametrize(
    "kill_at",
    [pytest.param(3, id="inside-partition-window-and-mid-ramp"),
     # the boundary variant is depth, not a distinct mechanism — slow
     # tier (tier-1 wall budget, ROADMAP gate)
     pytest.param(6, id="at-window-close", marks=pytest.mark.slow)])
def test_si_resume_under_fault_bitwise(tmp_path, kill_at):
    # kill_at=3 lands INSIDE the open partition window [2, 6) and past
    # the ramp start (mid-ramp); kill_at=6 resumes exactly at the heal
    full, pf = _si_leg(tmp_path, "full.npz", 10)
    half, ph = _si_leg(tmp_path, "half.npz", kill_at)
    lp = load_meta(ph)["extra"]["dropped"]
    res, _ = _si_leg(tmp_path, "half.npz", 10,
                     resume_state=load_state(ph), lost_prefix=lp)
    np.testing.assert_array_equal(np.asarray(full.seen),
                                  np.asarray(res.seen))
    assert float(full.msgs) == float(res.msgs)
    assert int(res.round) == 10
    # the destroyed-message total carries across the kill EXACTLY
    assert (load_meta(pf)["extra"]["dropped"]
            == load_meta(ph)["extra"]["dropped"])
    assert load_meta(pf)["extra"]["round"] == 10


# depth tier: see test_swim_resume_under_churn_bitwise's rationale
@pytest.mark.slow
def test_rumor_resume_under_fault_bitwise(tmp_path):
    from gossip_tpu.models.rumor import checkpointed_rumor
    proto = ProtocolConfig(mode="rumor", fanout=2, rumors=2, rumor_k=3)
    topo = G.complete(_N)

    def leg(name, rounds, resume_state=None, lost_prefix=0.0):
        return checkpointed_rumor(
            proto, topo, RunConfig(seed=0, max_rounds=rounds),
            str(tmp_path / name), every=3, fault=_FAULT,
            resume_state=resume_state, lost_prefix=lost_prefix)

    full, cov_f, _, _ = leg("full.npz", 10)
    leg("half.npz", 4)        # inside the partition window, mid-ramp
    lp = load_meta(str(tmp_path / "half.npz"))["extra"]["dropped"]
    res, cov_r, _, _ = leg("half.npz", 10,
                           resume_state=load_state(
                               str(tmp_path / "half.npz")),
                           lost_prefix=lp)
    for f in ("seen", "hot", "cnt"):
        np.testing.assert_array_equal(np.asarray(getattr(full, f)),
                                      np.asarray(getattr(res, f)))
    assert cov_f == cov_r
    assert (load_meta(str(tmp_path / "full.npz"))["extra"]["dropped"]
            == load_meta(str(tmp_path / "half.npz"))["extra"]["dropped"])


# ~11 s (txn-PR rebalance): the shared churn-resume mechanism —
# absolute round cursor + dropped carry + schedule fingerprint — stays
# pinned in-gate by the SI resume params and the fused-planes resume;
# this packed-sharded twin re-proves under -m slow
@pytest.mark.slow
@pytest.mark.skipif(len(jax.devices()) < 4,
                    reason="needs the virtual multi-device mesh")
def test_packed_sharded_resume_under_fault_bitwise(tmp_path):
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sharded_packed import (
        checkpointed_packed_sharded)
    proto = ProtocolConfig(mode="pull", fanout=1, rumors=3)
    topo = G.erdos_renyi(200, 0.06, seed=4)
    fault = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
        events=((3, 2, 5), (7, 1, -1)), partitions=((2, 6, 100),),
        ramp=(1, 4, 0.0, 0.3)))
    mesh = make_mesh(4)

    def leg(name, rounds, resume_state=None, lost_prefix=0.0):
        return checkpointed_packed_sharded(
            proto, topo, RunConfig(seed=11, max_rounds=rounds), mesh,
            str(tmp_path / name), every=3, fault=fault,
            resume_state=resume_state, lost_prefix=lost_prefix)

    full, cov_f, _ = leg("full.npz", 8)
    leg("half.npz", 4)        # inside the partition window, mid-ramp
    lp = load_meta(str(tmp_path / "half.npz"))["extra"]["dropped"]
    res, cov_r, _ = leg("half.npz", 8,
                        resume_state=load_state(
                            str(tmp_path / "half.npz")),
                        lost_prefix=lp)
    np.testing.assert_array_equal(np.asarray(full.seen),
                                  np.asarray(res.seen))
    assert cov_f == cov_r and float(full.msgs) == float(res.msgs)
    assert (load_meta(str(tmp_path / "full.npz"))["extra"]["dropped"]
            == load_meta(str(tmp_path / "half.npz"))["extra"]["dropped"])


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs the virtual multi-device mesh")
def test_fused_planes_resume_under_churn_events_bitwise(tmp_path):
    from gossip_tpu.parallel.sharded_fused import (
        checkpointed_fused_planes, make_plane_mesh)
    fault = FaultConfig(seed=1, churn=ChurnConfig(
        events=((3, 2, 5), (7, 1, -1))))
    mesh = make_plane_mesh(2)

    def leg(name, rounds, resume_state=None):
        return checkpointed_fused_planes(
            _N, 2, RunConfig(seed=0, max_rounds=rounds), mesh,
            str(tmp_path / name), every=3, interpret=True, fault=fault,
            resume_state=resume_state)

    full, cov_f, _ = leg("full.npz", 8)
    leg("half.npz", 4)
    res, cov_r, _ = leg("half.npz", 8,
                        resume_state=load_state(
                            str(tmp_path / "half.npz")))
    np.testing.assert_array_equal(np.asarray(full.table),
                                  np.asarray(res.table))
    assert cov_f == cov_r

    # partitions and ramps run on this engine since the fused-operand
    # PR (per-round cut masks + the threshold table behind the SMEM
    # scalar) — the checkpointed segments index them by the ABSOLUTE
    # round cursor, so resume under the FULL schedule is bitwise too
    full_fault = FaultConfig(seed=1, drop_prob=0.05, churn=ChurnConfig(
        events=((3, 2, 5),), partitions=((1, 6, 32),),
        ramp=(0, 4, 0.0, 0.3)))

    def fleg(name, rounds, resume_state=None):
        return checkpointed_fused_planes(
            _N, 2, RunConfig(seed=0, max_rounds=rounds), mesh,
            str(tmp_path / name), every=3, interpret=True,
            fault=full_fault, resume_state=resume_state)

    ffull, fcov, _ = fleg("pfull.npz", 8)
    fleg("phalf.npz", 4)
    fres, fcov_r, _ = fleg("phalf.npz", 8,
                           resume_state=load_state(
                               str(tmp_path / "phalf.npz")))
    np.testing.assert_array_equal(np.asarray(ffull.table),
                                  np.asarray(fres.table))
    assert fcov == fcov_r


# depth tier (tier-1 wall budget, serving-PR rebalance): the churn-
# resume mechanism (absolute state.round schedule indexing + the lost
# carry through run_with_checkpoints) is shared by every surface and
# stays pinned in-gate by the SI, packed-sharded, and fused-planes
# resumes + the crashloop smoke; the SWIM and rumor per-surface depth
# re-proves under -m slow
@pytest.mark.slow
def test_swim_resume_under_churn_bitwise(tmp_path):
    from gossip_tpu.runtime.simulator import checkpointed_swim
    # events (a permanent crash to detect + a recovering node) + ramp;
    # partitions are rejected by the SWIM factory (membership overlay)
    fault = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
        events=((5, 2, -1), (3, 4, 6)), ramp=(1, 4, 0.0, 0.2)))
    proto = ProtocolConfig(mode="swim", fanout=2, swim_subjects=8,
                           swim_proxies=3, swim_suspect_rounds=6)

    def leg(name, rounds, resume_state=None):
        return checkpointed_swim(
            proto, _N, RunConfig(seed=0, max_rounds=rounds),
            str(tmp_path / name), every=5, dead_nodes=(), fail_round=0,
            fault=fault, resume_state=resume_state)

    full, det_f, _ = leg("full.npz", 12)
    leg("half.npz", 6)        # mid-ramp, while node 3 is churn-down
    res, det_r, _ = leg("half.npz", 12,
                        resume_state=load_state(
                            str(tmp_path / "half.npz")))
    np.testing.assert_array_equal(np.asarray(full.wire),
                                  np.asarray(res.wire))
    np.testing.assert_array_equal(np.asarray(full.timer),
                                  np.asarray(res.timer))
    assert det_f == det_r == 1.0  # the scheduled crash is detected


def test_base_round_mismatch_refused():
    # a driver that rebuilt its state with a re-zeroed round counter
    # would silently restart the fault program from round 0 — refused
    from gossip_tpu.models.si import make_si_round
    from gossip_tpu.models.state import init_state
    proto = ProtocolConfig(mode="pushpull", fanout=1)
    step, tables = make_si_round(proto, G.complete(16), None, 0,
                                 tabled=True)
    st = init_state(RunConfig(seed=0), proto, 16)
    with pytest.raises(ValueError, match="base_round"):
        run_with_checkpoints(step, st, 2, "/dev/null.npz",
                             base_round=7, step_args=tables)


def test_schedule_fingerprint_semantics():
    from gossip_tpu.ops import nemesis as NE
    assert NE.schedule_fingerprint(None, _N) is None
    assert NE.schedule_fingerprint(
        FaultConfig(drop_prob=0.1, seed=0), _N) is None
    fp = NE.schedule_fingerprint(_FAULT, _N)
    assert isinstance(fp, str) and len(fp) == 64
    # deterministic; sensitive to the program AND the denominator
    assert fp == NE.schedule_fingerprint(_FAULT, _N)
    other = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
        events=((4, 2, 5), (7, 1, -1)),
        partitions=((2, 6, 32),), ramp=(1, 4, 0.0, 0.3)))
    assert fp != NE.schedule_fingerprint(other, _N)
    assert fp != NE.schedule_fingerprint(_FAULT, _N * 2)


# ---------------------------------------------------------------------
# CLI: the fault-program fingerprint refusal matrix
# ---------------------------------------------------------------------

_CHURN_FLAGS = ("--churn-event", "3:2:5", "--churn-event", "7:1",
                "--partition", "2:6:32", "--drop-ramp", "1:4:0.0:0.3")


@pytest.mark.slow
def test_cli_resume_fingerprint_refusals(tmp_path):
    """A checkpoint written WITHOUT the fault-program fingerprint (a
    pre-crash-safety build) refuses a churn resume; dropping the churn
    flags on resume refuses too (config fingerprint); and the happy
    path — same program — resumes to the bitwise straight-run state
    with the exact dropped total in the report."""
    ck = str(tmp_path / "c.npz")
    r = _cli("run", "--mode", "pushpull", "--n", "64", "--fanout", "2",
             "--max-rounds", "4", "--checkpoint", ck,
             "--checkpoint-every", "3", "--seed", "1", *_CHURN_FLAGS)
    assert r.returncode == 0, r.stderr
    # strip the fingerprint the way a pre-crash-safety build would
    # have: same arrays, same config fingerprint, no fault_program key
    with np.load(ck, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    assert meta["extra"].pop("fault_program")
    np.savez(ck, __meta__=json.dumps(meta), **arrays)
    r = _cli("run", "--mode", "pushpull", "--n", "64", "--fanout", "2",
             "--max-rounds", "8", "--checkpoint", ck, "--resume",
             "--checkpoint-every", "3", "--seed", "1", *_CHURN_FLAGS)
    assert r.returncode == 2
    assert "no fault-program fingerprint" in r.stderr
    # dropping the churn flags is a config mismatch (refused before the
    # schedule-specific guards)
    r = _cli("run", "--mode", "pushpull", "--n", "64", "--fanout", "2",
             "--max-rounds", "8", "--checkpoint", ck, "--resume",
             "--checkpoint-every", "3", "--seed", "1")
    assert r.returncode == 2 and "config mismatch" in r.stderr

    # happy path: rewrite the run from scratch, kill at 4, resume; the
    # final report matches an uninterrupted run exactly (incl. dropped)
    full_ck = str(tmp_path / "f.npz")
    rf = _cli("run", "--mode", "pushpull", "--n", "64", "--fanout", "2",
              "--max-rounds", "8", "--checkpoint", full_ck,
              "--checkpoint-every", "3", "--seed", "1", *_CHURN_FLAGS)
    os.remove(ck)
    _cli("run", "--mode", "pushpull", "--n", "64", "--fanout", "2",
         "--max-rounds", "4", "--checkpoint", ck,
         "--checkpoint-every", "3", "--seed", "1", *_CHURN_FLAGS)
    rr = _cli("run", "--mode", "pushpull", "--n", "64", "--fanout", "2",
              "--max-rounds", "8", "--checkpoint", ck, "--resume",
              "--checkpoint-every", "3", "--seed", "1", *_CHURN_FLAGS)
    assert rr.returncode == 0, rr.stderr
    full, res = json.loads(rf.stdout), json.loads(rr.stdout)
    for key in ("coverage", "msgs", "dropped", "fault_program",
                "rounds"):
        assert full[key] == res[key], key
    with np.load(full_ck) as a, np.load(ck) as b:
        np.testing.assert_array_equal(a["seen"], b["seen"])


# ---------------------------------------------------------------------
# no-churn checkpointed trajectories: provably unchanged
# ---------------------------------------------------------------------

def _pinned():
    with open(CS.DATA) as f:
        return json.load(f)["digests"]


@pytest.mark.parametrize("name", ["ckpt_si", "ckpt_fused"])
def test_checkpointed_static_fingerprints_fast(name):
    """In-gate subset: the single-device SI surface smokes the
    re-plumbed run_with_checkpoints against its pre-lift digest, and
    the fused-planes surface guards the STATIC fused trajectory
    (drop_prob=0.05 — the drop threshold rides the SMEM scalar operand
    since the fused-operand PR, and this digest proves the promotion
    is value-preserving bit for bit).  The full five-surface matrix
    runs under -m slow below."""
    runner = CS.CHECKPOINTED_SURFACES[name]
    assert runner(CS._static_fault()) == _pinned()[f"ckpt-static:{name}"]


@pytest.mark.slow
@pytest.mark.parametrize("name", ["ckpt_packed", "ckpt_rumor",
                                  "ckpt_swim", "ckpt_fused"])
def test_checkpointed_static_fingerprints_full(name):
    runner = CS.CHECKPOINTED_SURFACES[name]
    assert runner(CS._static_fault()) == _pinned()[f"ckpt-static:{name}"]


# ---------------------------------------------------------------------
# the live SIGKILL harness (single-kill smoke; committed 3-kill record
# is artifacts/ledger_crashloop_r12.jsonl)
# ---------------------------------------------------------------------

# ~18 s (txn-PR tier-1 rebalance, flight data in
# artifacts/ledger_tests.jsonl): the crash-safety surface stays
# in-gate via the committed 3-kill record pin below plus the SI and
# fused-planes churn resumes; the live SIGKILL loop re-proves under
# -m slow
@pytest.mark.slow
def test_crashloop_single_kill_smoke(tmp_path):
    out = str(tmp_path / "ledger_crashloop_smoke.jsonl")
    # n=4096 + a 2 ms poll: each 4-round segment walls ~15 ms on this
    # CPU tier, so the poller reliably observes an INTERMEDIATE durable
    # cursor and the kill lands mid-run (a tiny n publishes its final
    # checkpoint between polls and the tool refuses the vacuous kill)
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "crashloop.py"),
         "--n", "4096", "--max-rounds", "12", "--every", "4",
         "--kills", "1", "--poll-ms", "2",
         "--workdir", str(tmp_path / "wk"), "--out", out],
        capture_output=True, text=True, cwd=_REPO, env=CLI_ENV,
        timeout=420)
    assert r.returncode == 0, r.stderr + r.stdout
    verdict = json.loads(r.stdout)
    assert verdict["ok"] and verdict["kills"] == 1
    assert verdict["coverage"] == 1.0
    # the ledger parses per the flight-recorder contract and carries
    # provenance + one kill event with the durable round cursor
    from gossip_tpu.utils.telemetry import load_ledger
    rows = load_ledger(out)
    kinds = [row.get("ev") for row in rows]
    assert kinds[0] == "provenance"
    assert "kill" in kinds and "verdict" in kinds
    kill = next(row for row in rows if row.get("ev") == "kill")
    assert kill["run_id"]
    # the kill interrupted REAL work: at least one durable segment
    # existed, and the final checkpoint did not (the tool refuses to
    # count a kill that postdates the last durable round)
    assert 4 <= kill["durable_round"] < 12


def test_committed_crashloop_record_is_green():
    """The standing proof: >= 3 SIGKILL/resume cycles, bitwise-equal
    final state, convergence to 1.0 on the eventual-alive set, and a
    kill INSIDE the scheduled partition window — all asserted on the
    committed artifact, so the record can never rot silently."""
    from gossip_tpu.utils.telemetry import load_ledger
    rows = load_ledger(os.path.join(_REPO, "artifacts",
                                    "ledger_crashloop_r12.jsonl"))
    assert rows[0].get("ev") == "provenance"
    cfg = next(r for r in rows if r.get("ev") == "config")
    kills = [r for r in rows if r.get("ev") == "kill"]
    verdict = next(r for r in rows if r.get("ev") == "verdict")
    assert len(kills) >= 3 and verdict["kills"] >= 3
    assert verdict["ok"] and verdict["bitwise_equal"]
    assert verdict["coverage"] == 1.0 and verdict["dropped"] > 0
    # every kill is attributable, durable-round-stamped, and landed
    # BEFORE the final checkpoint (it interrupted real work)
    for k in kills:
        assert k["run_id"]
        assert 0 <= k["durable_round"] < cfg["max_rounds"]
    # at least one kill landed inside the scheduled partition window
    part = cfg["churn"][cfg["churn"].index("--partition") + 1]
    start, end, _cut = (int(x) for x in part.split(":"))
    assert any(start <= k["durable_round"] < end for k in kills), (
        "no kill landed inside the partition window "
        f"[{start}, {end}): {[k['durable_round'] for k in kills]}")
