"""The XLA cost & memory attribution plane (observability PR).

Four layers of proof:

  1. **Self-attribution at the chokepoint** — every
     ``utils/compile_cache.load_or_compile`` acquisition emits one
     ``xla_compile`` event carrying the driver label, the store key,
     the cache verdict, the compile wall, and EVERY attribution field
     (``compile_cache.ATTRIBUTION_FIELDS``) — populated on this CPU
     backend, explicit nulls elsewhere (record-never-gate).
  2. **Degrade path** — an executable without ``cost_analysis`` /
     ``memory_analysis`` attributes as all-None, the event still
     carries the keys, and the report renders ``n/a`` — never a crash,
     never a fabricated zero.  The sidecar's Metrics reply has NO
     ``last_compile`` key before the first chokepoint compile
     (absent-not-wrong).
  3. **The drift gate** — ``planner/budget.crosscheck_peak`` goes
     green on measured ≤ predicted, RED on an inflated measurement (an
     under-predicting closed form must fail, per the acceptance
     criterion), and null on a backend without memory analysis — and
     every verdict lands as one ``budget_xcheck`` event.
  4. **The committed record** — ``artifacts/ledger_cost_r24.jsonl``
     (+ ``.smoke``) pins the capture green: provenance first line,
     every gate true, every compile attributed.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import pytest

from gossip_tpu.planner import budget as PB
from gossip_tpu.utils import compile_cache, telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def own_ledger(tmp_path):
    p = str(tmp_path / "led.jsonl")
    led = telemetry.Ledger(p)
    prev = telemetry.activate(led)
    yield led
    telemetry.activate(prev)
    led.close()


# -- 1. self-attribution at the chokepoint -----------------------------

def test_chokepoint_emits_attributed_xla_compile(own_ledger, tmp_path,
                                                 monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_VAR, str(tmp_path / "cc"))
    f = jax.jit(lambda x: jnp.sin(x).sum())
    x = jnp.arange(128.0)
    _, s1 = compile_cache.load_or_compile(f, x, label="probe_engine")
    _, s2 = compile_cache.load_or_compile(jax.jit(lambda x: jnp.sin(x)
                                                  .sum()), x,
                                          label="probe_engine")
    assert (s1, s2) == ("miss", "hit")
    events = telemetry.load_ledger(own_ledger.path)
    compiles = [e for e in events if e["ev"] == "xla_compile"]
    assert [e["cache"] for e in compiles] == ["miss", "hit"]
    for e in compiles:
        assert e["label"] == "probe_engine"
        assert e["key"] and e["compile_ms"] > 0
        # every attribution field PRESENT — and on this CPU backend,
        # populated (cost_analysis + memory_analysis both work here)
        for field in compile_cache.ATTRIBUTION_FIELDS:
            assert field in e, field
            assert e[field] is not None, field
        assert e["peak_bytes"] == (e["argument_bytes"]
                                   + e["output_bytes"]
                                   + e["temp_bytes"])
    # the live surface kept the most recent record
    last = compile_cache.last_compile()
    assert last is not None and last["cache"] == "hit"
    assert last["label"] == "probe_engine"


def test_default_label_when_caller_passes_none(own_ledger, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_VAR, "")
    f = jax.jit(lambda x: x + 1)
    _, status = compile_cache.load_or_compile(f, jnp.arange(4))
    assert status == "disabled"
    [e] = [e for e in telemetry.load_ledger(own_ledger.path)
           if e["ev"] == "xla_compile"]
    assert e["label"]          # never an unlabeled event
    assert e["key"] is None    # no store, no fingerprint — explicit


# -- 2. degrade path: nulls, n/a, absent-not-wrong ---------------------

class _Opaque:
    """An executable with neither analysis (older jax lines, interpret
    stubs)."""


class _Raising:
    def cost_analysis(self):
        raise RuntimeError("unimplemented on this backend")

    def memory_analysis(self):
        raise RuntimeError("unimplemented on this backend")


@pytest.mark.parametrize("stub", [_Opaque(), _Raising()])
def test_attribution_degrades_to_explicit_nulls(stub):
    out = compile_cache.xla_attribution(stub)
    assert set(out) == set(compile_cache.ATTRIBUTION_FIELDS)
    assert all(v is None for v in out.values())


def test_report_renders_null_attribution_as_na():
    cost_report = _load_tool("cost_report")
    events = [{"ev": "xla_compile", "label": "tpu_only", "fn": "step",
               "key": "k", "cache": "miss", "compile_ms": 12.0,
               **{f: None for f in compile_cache.ATTRIBUTION_FIELDS}}]
    lines = cost_report.render_cost_section(events)
    row = next(ln for ln in lines if "tpu_only" in ln)
    assert "n/a" in row and " 0" not in row.replace("12.0", "")
    # a ledger with no attribution events renders NO section at all
    assert cost_report.render_cost_section([{"ev": "family"}]) == []


def test_telemetry_report_embeds_cost_section():
    telemetry_report = _load_tool("telemetry_report")
    events = [{"ev": "xla_compile", "ts": 0.0, "label": "dense",
               "fn": "step", "key": "k", "cache": "miss",
               "compile_ms": 3.0, "flops": 100.0,
               "bytes_accessed": 4096.0, "argument_bytes": 1024,
               "output_bytes": 1024, "temp_bytes": 0,
               "peak_bytes": 2048}]
    text = telemetry_report.render_markdown(events)
    assert "## Executable costs" in text and "dense" in text


def test_bytes_per_node_round_from_cost_case():
    cost_report = _load_tool("cost_report")
    events = [
        {"ev": "cost_case", "label": "dense", "n": 32, "rounds": 4},
        {"ev": "xla_compile", "label": "dense", "fn": "step",
         "key": "k", "cache": "miss", "compile_ms": 3.0,
         "flops": 1.0, "bytes_accessed": 128 * 32 * 4.0,
         "argument_bytes": 1, "output_bytes": 1, "temp_bytes": 0,
         "peak_bytes": 2},
    ]
    [row] = cost_report.join_costs(events)["rows"]
    assert row["bytes_per_node_round"] == 128.0


def test_sidecar_metrics_last_compile_absent_not_wrong(monkeypatch):
    from gossip_tpu.rpc import sidecar
    monkeypatch.setattr(compile_cache, "_LAST_COMPILE", None)
    reply = json.loads(sidecar._metrics(b"", None))
    assert reply["ok"] and "last_compile" not in reply
    monkeypatch.setattr(
        compile_cache, "_LAST_COMPILE",
        {"label": "dense", "fn": "step", "key": "k", "cache": "hit",
         "compile_ms": 1.5, "peak_bytes": 4096})
    reply = json.loads(sidecar._metrics(b"", None))
    assert reply["last_compile"] == {"label": "dense", "cache": "hit",
                                     "compile_ms": 1.5,
                                     "peak_bytes": 4096}


# -- 3. the drift gate -------------------------------------------------

def test_crosscheck_green_red_and_null(own_ledger):
    green = PB.crosscheck_peak(200, 150, engine="packed", n=64, tiles=4)
    assert green["ok"] is True and green["headroom_frac"] == 0.25
    # an inflated measurement (equivalently: a deflated closed form)
    # MUST go red — the acceptance criterion's failure mode
    red = PB.crosscheck_peak(100, 200)
    assert red["ok"] is False and red["headroom_frac"] == -1.0
    null = PB.crosscheck_peak(100, None)
    assert null["ok"] is None and null["measured_bytes"] is None
    events = [e for e in telemetry.load_ledger(own_ledger.path)
              if e["ev"] == "budget_xcheck"]
    assert [e["ok"] for e in events] == [True, False, None]
    assert events[0]["n"] == 64 and events[0]["tiles"] == 4
    assert all(e["source"] == "xla_memory_analysis" for e in events)


def test_report_marks_exceeded_xcheck():
    cost_report = _load_tool("cost_report")
    events = [{"ev": "budget_xcheck", "engine": "packed", "n": 64,
               "tiles": 4, "predicted_bytes": 100,
               "measured_bytes": 200, "ok": False,
               "headroom_frac": -1.0, "source": "xla_memory_analysis",
               "plan_fingerprint": None}]
    text = "\n".join(cost_report.render_cost_section(events))
    assert "**EXCEEDED**" in text


def test_stream_dispatch_emits_xcheck(own_ledger, monkeypatch):
    """The generalized gate in situ: a real (tiny) streamed dispatch
    with measure_memory=True routes its measuring compile through the
    chokepoint (label ``scale_stream``) and emits ONE budget_xcheck
    whose measured side equals the result's measured_loop_bytes."""
    from gossip_tpu.planner.stream import run_at_scale
    monkeypatch.setenv(compile_cache.ENV_VAR, "")
    dev = PB.forced_device_for_tiles(512, rumors=128, fanout=2,
                                     max_rounds=4, fault=None,
                                     tiles_at_least=2,
                                     host_ram_bytes=1 << 30)
    plan = PB.plan_scale(512, rumors=128, device=dev, fanout=2,
                         max_rounds=4, segment_every=3)
    res = run_at_scale(plan, measure_memory=True)
    events = telemetry.load_ledger(own_ledger.path)
    [xc] = [e for e in events if e["ev"] == "budget_xcheck"]
    assert xc["engine"] == plan.engine and xc["n"] == plan.n
    assert xc["measured_bytes"] == res.measured_loop_bytes
    assert xc["predicted_bytes"] == plan.predicted_peak_device_bytes
    assert xc["ok"] is True     # the live closed form must hold
    compiles = [e for e in events if e["ev"] == "xla_compile"]
    assert "scale_stream" in {e["label"] for e in compiles}


# -- 4. the committed record -------------------------------------------

@pytest.mark.parametrize("name", ["ledger_cost_r24.jsonl",
                                  "ledger_cost_r24.smoke.jsonl"])
def test_committed_cost_record_green(name):
    path = os.path.join(_REPO, "artifacts", name)
    events = telemetry.load_ledger(path, run="last")
    assert events[0]["ev"] == "provenance"
    [rec] = [e for e in events if e["ev"] == "cost_record"]
    for gate in ("ok", "engines_attributed", "all_events_attributed",
                 "attribution_fields_present", "warm_hit",
                 "tiles_ge_4", "xcheck_green"):
        assert rec[gate] is True, gate
    compiles = [e for e in events if e["ev"] == "xla_compile"]
    assert {e["label"] for e in compiles} >= {
        "dense", "packed", "sparse", "fused", "crdt", "log", "txn",
        "scale_stream"}
    for e in compiles:
        assert e["cache"] in ("hit", "miss", "disabled")
        for field in compile_cache.ATTRIBUTION_FIELDS:
            assert field in e, field
    [xc] = [e for e in events if e["ev"] == "budget_xcheck"][-1:]
    assert xc["ok"] is True
    # zero fsyncs from the attribution plane itself: the capture's
    # fsync count must come only from provenance/counters, and every
    # xla_compile/budget_xcheck/cost_case event is flush-only — pinned
    # structurally by test_telemetry's sync=False contract; here we
    # pin that the record renders (the report tool's contract)
    cost_report = _load_tool("cost_report")
    text = "\n".join(cost_report.render_cost_section(events))
    assert "## Executable costs" in text and "scale_stream" in text
