"""The AST invariant analyzer's tier-1 gate (gossip_tpu/analysis).

Three contracts, in the PR 11 txn-checker discipline:

  1. **Every checker family can fail**: each planted-violation fixture
     under tests/data/staticcheck/ MUST flag — a checker that cannot
     fail is not a checker.  The synthetic lock-order cycle and the
     synthetic jnp-over-K hazard are both demonstrably caught here.
  2. **The live tree runs clean**: ``run_tree()`` on this repo exits
     with zero unsuppressed findings, and every suppression carries a
     non-empty rationale.  The committed findings ledger
     (artifacts/ledger_staticcheck_r19.jsonl) is pinned so the clean
     verdict cannot rot.
  3. **The baseline only shrinks**: the entry count is pinned at
     MAX_BASELINE_ENTRIES — raising it requires editing THIS constant
     in review, with a reason; a stale or rationale-free entry is
     itself a finding (fixture-proven).

All pure-stdlib AST work: no jax, no compile cost — the whole file is
cheap tier-1 wall.
"""

import json
import os
import subprocess
import sys

from gossip_tpu.analysis import conventions, core, locks, recompile, runner

REPO = core.REPO
FIX = os.path.join(REPO, "tests", "data", "staticcheck")

# The baseline-only-shrinks pin: lower freely when suppressions burn
# down; raising it is a reviewed decision that needs a reason here.
# Current entry: sharded_fused._cached_alive_words (static-fault jit
# closure is deliberate — the PR 9 pinned-draw rationale, on file in
# tools/staticcheck_baseline.json).
MAX_BASELINE_ENTRIES = 1


def _fixture_modules(*names):
    return core.load_modules(FIX, names)


def _rules(findings):
    return {f.rule for f in findings}


# -- 1. planted fixtures: every family must be able to fail -----------

def test_recompile_fixture_flags():
    mods = _fixture_modules("planted_recompile.py")
    found = recompile.check(mods, mods)
    rules = _rules(found)
    assert "jnp-over-k" in rules, found
    assert "jit-in-request-path" in rules, found
    assert "content-in-memo-key" in rules, found
    # byz-table-in-memo-key: the planted liar-keyed builder flags on
    # BOTH byz params; the *_static escape and the eager-values memo
    # (build_byz's own lowering pattern) stay silent
    byz = [f for f in found if f.rule == "byz-table-in-memo-key"]
    assert {f.symbol for f in byz} == {"_cached_byz_loop"}, found
    assert len(byz) == 2, found           # 'liars' AND 'quorum'
    assert not any(f.symbol == "_cached_byz_clean_loop"
                   for f in found), found
    assert not any(f.symbol == "_cached_byz_values"
                   for f in found), found
    # the jnp-over-K hazard flags all three planted builds (asarray +
    # stack + the nested helper's stack, each exactly once)
    assert sum(f.rule == "jnp-over-k" for f in found) == 3, found
    # the declared-static convention must NOT flag
    assert not any(f.symbol == "_cached_clean_loop" for f in found), \
        found
    # suppression keys are content-addressed (symbol, not line)
    jit = next(f for f in found if f.rule == "jit-in-request-path")
    assert jit.symbol == "_dispatch"
    # a violation in a NESTED helper counts once, not once per
    # covering walk (the enclosing function and the nested def's own
    # root both visit it)
    nested = [f for f in found if f.symbol == "request_nested.helper"]
    assert len(nested) == 1, found


def test_stream_fetch_fixture_flags_and_negative_twin():
    """blocking-fetch-in-segment-loop: the planted serial segment loop
    flags BOTH blocking shapes (block_until_ready + np.asarray); the
    ``_drain*`` deferred-fetch helper and the pipelined loop that
    routes through it must NOT flag — the sanctioned-site escape is
    load-bearing (planner/stream's own loop uses it)."""
    mods = _fixture_modules("planted_stream_fetch.py")
    found = recompile.check_stream_fetch(mods)
    assert _rules(found) == {"blocking-fetch-in-segment-loop"}, found
    serial = [f for f in found
              if f.symbol == "stream_segments_serial"]
    assert len(serial) == 2, found      # the wait AND the fetch
    assert not any(f.symbol.startswith("_drain_pending")
                   for f in found), found
    assert not any(f.symbol == "stream_segments_pipelined"
                   for f in found), found


def test_lock_fixture_flags():
    mods = _fixture_modules("planted_locks.py")
    found = locks.check(mods)
    rules = _rules(found)
    assert "lock-order" in rules, found          # the synthetic cycle
    assert "stopflag-outside-lock" in rules, found   # the PR 13 shape
    assert "blocking-under-lock" in rules, found
    blocking = [f for f in found if f.rule == "blocking-under-lock"]
    # the sleep under the lock AND the default-sync emit in *_locked;
    # the sync=False emit must NOT flag
    assert any("time.sleep" in f.message for f in blocking), blocking
    assert any(f.symbol == "PlantedBatcher.emit_locked"
               for f in blocking), blocking
    assert not any(f.symbol == "PlantedBatcher.ok_emit"
                   for f in found), found


def test_conventions_fixture_flags():
    mods = _fixture_modules("planted_conventions.py")
    found = (conventions.check_event_kind(mods)
             + conventions.check_capability_strings(mods))
    rules = _rules(found)
    assert "ledger-event-kind" in rules, found
    assert "capability-singleton" in rules, found
    tool_mods = _fixture_modules("planted_tool.py")
    tool_found = conventions.check_artifact_provenance(tool_mods)
    assert _rules(tool_found) == {"artifact-writer-provenance"}, \
        tool_found


def test_unattributed_compile_fixture_flags_and_negative_twins():
    """unattributed-compile: both planted ``.lower().compile()``
    chains flag; the chokepoint-routed twin, the ``*_unattributed``
    naming-escape, and a plain string ``.lower()`` stay silent; the
    chokepoint module itself is exempt by path."""
    mods = _fixture_modules("planted_unattributed.py")
    found = conventions.check_unattributed_compile(mods)
    assert _rules(found) == {"unattributed-compile"}, found
    assert {f.symbol for f in found} == {"bypass_chokepoint",
                                         "bypass_jit_inline"}, found
    assert not any("measure_chokepoint" in f.symbol
                   for f in found), found
    assert not any("unattributed" in f.symbol for f in found), found
    assert not any("normalize_label" in f.symbol for f in found), found
    # path exemption: the same tree keyed as the chokepoint module
    exempt = {conventions.UNATTRIBUTED_EXEMPT[0]:
              mods["planted_unattributed.py"]}
    assert conventions.check_unattributed_compile(exempt) == []


def test_sync_emit_fixture_flags_and_negative_twin():
    """sync-emit-in-request-path: the planted Router flags BOTH shapes
    (defaulted emit in the root, sync=True in a reachable helper); the
    negative twin CleanRouter — identical call graph with literal
    sync=False — stays silent; the off-path emit never flags
    (reachability from the roots, not a module-wide scan)."""
    mods = _fixture_modules("planted_sync.py")
    roots = {"planted_sync.py": ("Router.dispatch",
                                 "CleanRouter.dispatch")}
    found = conventions.check_sync_emit(mods, roots=roots)
    assert _rules(found) == {"sync-emit-in-request-path"}, found
    assert len(found) == 2, found
    assert {f.symbol for f in found} == {"Router.dispatch",
                                         "Router._attempt"}, found
    assert not any("CleanRouter" in f.symbol for f in found), found
    assert not any("off_path" in f.symbol for f in found), found


def test_sync_emit_live_roots_resolve():
    """The REQUEST_PATH_ROOTS table must name real qualnames: a rename
    of Router.dispatch (or a batcher scope) that orphans its root
    would silently disarm the rule.  Every configured root must
    resolve to exactly one function in its module."""
    mods = core.load_modules(REPO,
                             sorted(conventions.REQUEST_PATH_ROOTS))
    for rel, qualnames in conventions.REQUEST_PATH_ROOTS.items():
        mod = mods[rel]
        import ast as _ast
        fns = [n for n in _ast.walk(mod.tree)
               if isinstance(n, (_ast.FunctionDef,
                                 _ast.AsyncFunctionDef))]
        for qn in qualnames:
            hits = [fn for fn in fns if mod.qualname(fn) == qn]
            assert len(hits) == 1, (rel, qn, len(hits))


def test_budget_fixture_flags_both_directions():
    found = conventions.check_dryrun_budgets(
        root=os.path.join(FIX, "budget_tree"))
    msgs = [f.message for f in found]
    # unbudgeted family: one finding per table
    assert sum("fam_unbudgeted" in m for m in msgs) == 2, msgs
    # stale budget row naming no live family
    assert sum("fam_ghost" in m for m in msgs) == 2, msgs
    assert all(f.rule == "dryrun-budget-row" for f in found)


def test_baseline_malformed_json_is_a_finding_not_a_crash():
    """A hand-edit's trailing comma must surface as a
    malformed-baseline finding (exit 1 with a named reason) — never a
    JSONDecodeError traceback through every dry run."""
    entries, problems = core.load_baseline(
        os.path.join(FIX, "planted_baseline_malformed.json"))
    assert entries == []
    assert _rules(problems) == {"malformed-baseline"}, problems
    assert "does not parse" in problems[0].message


def test_baseline_fixture_flags_rationale_and_stale():
    entries, problems = core.load_baseline(
        os.path.join(FIX, "planted_baseline.json"))
    # entry 0 (empty rationale) is a finding, not a valid suppression
    assert _rules(problems) == {"missing-rationale"}, problems
    # entry 1 parses but matches nothing -> stale-suppression
    assert len(entries) == 1
    live, suppressed, stale = core.apply_baseline([], entries)
    assert _rules(stale) == {"stale-suppression"}, stale
    assert not live and not suppressed


# -- 2. the live tree runs clean --------------------------------------

def test_live_tree_runs_clean():
    report = runner.run_tree()
    assert report.clean, "staticcheck findings on the live tree:\n" \
        + "\n".join(f.render() for f in report.findings)
    # the scan actually covered the tree (a scope regression that
    # silently skipped everything would also read "clean")
    assert report.files_scanned > 80, report.files_scanned
    # every suppressed finding is rationale-backed by construction
    # (load_baseline rejects empty rationales); the suppressed set
    # matches the committed baseline 1:1 — no silent suppressions
    assert len(report.suppressed) == report.baseline_entries


def test_live_tree_lock_graph_has_no_edges_yet():
    """The rpc modules currently take no nested locks: the acquisition
    graph must be empty.  If this fails, a nested acquisition was
    added — extend the order contract in docs/STATIC_ANALYSIS.md and
    update this pin deliberately."""
    mods = core.load_modules(REPO, locks.SCOPE)
    all_edges = {}
    for rel in sorted(mods):
        mod = mods[rel]
        walk = locks._LockWalk(mod, locks._collect_classes(mod),
                               locks._module_locks(mod)).run()
        all_edges.update(walk.edges)
    assert all_edges == {}, all_edges


# -- 3. the baseline only shrinks -------------------------------------

def test_baseline_shrink_only_pin():
    entries, problems = core.load_baseline(
        os.path.join(REPO, core.BASELINE_PATH))
    assert not problems, [p.render() for p in problems]
    assert len(entries) <= MAX_BASELINE_ENTRIES, (
        f"{len(entries)} baseline entries > pinned "
        f"{MAX_BASELINE_ENTRIES} — the suppression baseline only "
        "shrinks; a new entry needs a reviewed bump of "
        "MAX_BASELINE_ENTRIES in tests/test_staticcheck.py with a "
        "reason, plus an inline rationale in the baseline itself")
    for e in entries:
        assert str(e["rationale"]).strip(), e


# -- committed-artifact pin (the clean verdict cannot rot) ------------

def _load_committed(name):
    from gossip_tpu.utils import telemetry
    path = os.path.join(REPO, "artifacts", name)
    return telemetry.load_ledger(path, strict=True)


def test_committed_staticcheck_ledger_pin():
    for name in ("ledger_staticcheck_r19.jsonl",
                 "ledger_staticcheck_r19.smoke.jsonl"):
        events = _load_committed(name)
        prov = [e for e in events if e.get("ev") == "provenance"]
        assert prov and all(k in prov[0] for k in
                            ("run_id", "git_commit", "captured")), name
        verdict = [e for e in events if e.get("ev") == "staticcheck"]
        assert len(verdict) == 1, name
        v = verdict[0]
        assert v["verdict"] == "clean", v
        assert v["findings"] == 0, v
        assert v["files_scanned"] > 80, v
        # per-checker counts for all four families
        checkers = {e["checker"]: e for e in events
                    if e.get("ev") == "checker"}
        assert set(checkers) == set(runner.FAMILIES), checkers
        assert all(c["findings"] == 0 for c in checkers.values()), \
            checkers
        # the one accepted suppression is visible in the record
        assert v["suppressed"] == v["baseline_entries"] == 1, v


# -- shared provenance-stamping helper --------------------------------

def test_artifact_ledger_helper_rewrite_and_append(tmp_path):
    """telemetry.artifact_ledger is the ONE stamping choreography the
    conftest duration ledger and the staticcheck writer share:
    rewrite=True truncates (a committed artifact is one run's
    evidence), rewrite=False appends (the explicit-env aggregation
    convention); both stamp provenance first."""
    from gossip_tpu.utils import telemetry
    path = str(tmp_path / "led.jsonl")
    with telemetry.artifact_ledger(path) as led:
        led.event("x", v=1)
    with telemetry.artifact_ledger(path) as led:
        led.event("x", v=2)
    events = telemetry.load_ledger(path, strict=True)
    assert sum(e["ev"] == "provenance" for e in events) == 1
    assert [e["v"] for e in events if e["ev"] == "x"] == [2]
    with telemetry.artifact_ledger(path, rewrite=False) as led:
        led.event("x", v=3)
    events = telemetry.load_ledger(path, strict=True)
    assert sum(e["ev"] == "provenance" for e in events) == 2
    assert [e["v"] for e in events if e["ev"] == "x"] == [2, 3]


# -- CLI exposure ------------------------------------------------------

def test_cli_staticcheck_clean_and_dirty():
    """``gossip_tpu staticcheck`` end-to-end: exit 0 + clean JSON on
    the live tree; exit 1 on a planted-violation root (the synthetic
    budget tree) — the tier-1 proof that a violation anywhere in
    scope fails the real gate, not just the library call."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.run(
        [sys.executable, "-m", "gossip_tpu", "staticcheck", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    doc = json.loads(p.stdout.strip().splitlines()[-1])
    assert doc["verdict"] == "clean"
    dirty = subprocess.run(
        [sys.executable, "-m", "gossip_tpu", "staticcheck", "--json",
         "--root", os.path.join(FIX, "budget_tree")],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    doc = json.loads(dirty.stdout.strip().splitlines()[-1])
    assert doc["verdict"] == "dirty"
    assert doc["findings"] >= 4          # both tables, both directions
