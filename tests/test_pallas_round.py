"""Tests for the fused Pallas pull-round kernel (ops/pallas_round.py).

CPU strategy: the Mosaic interpreter stubs the hardware PRNG with zeros
(test_pallas.py round-1 finding), so kernel MATH is tested by injecting
known random bits (``inject_bits``) and checking against an independent
numpy model of the documented sampling scheme.  Statistical properties of
the hardware PRNG path (curve shape, determinism, seed sensitivity) are
TPU-only tests.

Reference semantics being modelled: the batched pull form of the
reference's broadcast relay (/root/reference/main.go:72-88) — every node
asks a uniformly random partner for its digest each round.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu.ops.pallas_round import (
    BITS, LANES, FusedState, compiled_until_fused,
    compiled_until_fused_multirumor, coverage_node_packed, coverage_words,
    fused_multirumor_pull_round, fused_pull_round, init_fused_state,
    init_multirumor_state, mr_rows, n_rows, node_pack, node_unpack,
    word_pack, word_unpack)

ON_TPU = jax.default_backend() == "tpu"


def numpy_reference_round(table, sbits, rbits, n, fanout, sharing=1):
    """Independent model of the kernel's documented sampling scheme
    (``sharing=2``: a plane pair splits one draw's disjoint 12-bit
    fields — the round-5 PRNG-harvest variant)."""
    rows = table.shape[0]
    s = (sbits[0, :].astype(np.uint64) % rows).astype(np.int64)   # [128]
    # rot[i, j] = table[(i - s_j) mod rows, j]
    i = np.arange(rows)[:, None]
    rot = table[(i - s[None, :]) % rows, np.arange(LANES)[None, :]]
    acc = table.copy()
    for k in range(0, BITS, sharing):
        for f in range(fanout):
            rb = rbits[(k // sharing) * fanout + f]
            for j in range(sharing):
                m = (rb >> (12 * j)) & (LANES - 1)
                c = (rb >> (12 * j + 7)) & (BITS - 1)
                partner = np.take_along_axis(rot, m.astype(np.int64),
                                             axis=1)
                bit = (partner >> c) & 1
                acc = acc | (bit.astype(np.uint32) << np.uint32(k + j))
    # phantom masking
    flat = acc.reshape(-1)
    n_valid_words = -(-n // BITS)
    tail = n % BITS
    out = flat.copy()
    out[n_valid_words:] = 0
    if tail:
        out[n_valid_words - 1] &= np.uint32((1 << tail) - 1)
    return out.reshape(rows, LANES)


def _random_bits(rng, rows, fanout, sharing=1):
    """Injected-bit buffers at the kernel's contract shapes — the ONE
    place the (sbits, rbits) layout lives (``sharing`` divides the rbits
    draw count: a plane pair shares one word)."""
    sbits = rng.integers(0, 2**32, size=(8, LANES), dtype=np.uint32)
    rbits = rng.integers(0, 2**32,
                         size=(fanout * BITS // sharing, rows, LANES),
                         dtype=np.uint32)
    return sbits, rbits


@pytest.mark.parametrize("n,fanout,sharing",
                         [(4096 * 8, 1, 1), (4096 * 8 - 37, 1, 1),
                          (4096 * 16, 2, 1),
                          (4096 * 8, 1, 2), (4096 * 8 - 37, 2, 2)])
def test_kernel_math_matches_numpy_model(n, fanout, sharing):
    rng = np.random.default_rng(42 + n + fanout + sharing)
    rows = n_rows(n)
    infected = rng.random(n) < 0.03
    table = np.asarray(node_pack(jnp.asarray(infected)))
    sbits, rbits = _random_bits(rng, rows, fanout, sharing)
    got = fused_pull_round(jnp.asarray(table), 0, 0, n, fanout,
                           interpret=not ON_TPU,
                           inject_bits=(sbits, rbits),
                           plane_sharing=sharing)
    want = numpy_reference_round(table, sbits, rbits, n, fanout, sharing)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_plane_sharing_validation():
    t = init_fused_state(4096 * 8).table
    with pytest.raises(ValueError, match="plane_sharing"):
        fused_pull_round(t, 0, 0, 4096 * 8, 1, interpret=not ON_TPU,
                         plane_sharing=3)
    with pytest.raises(ValueError, match="drop coin"):
        fused_pull_round(t, 0, 0, 4096 * 8, 1, interpret=not ON_TPU,
                         drop_threshold=1000, plane_sharing=2)
    # still loud with the threshold as a runtime operand: a partition
    # side mask overlaps the pair split the same way the drop coin does
    from gossip_tpu.ops.pallas_round import render_cut_bits
    with pytest.raises(ValueError, match="drop coin"):
        fused_pull_round(t, 0, 0, 4096 * 8, 1, interpret=not ON_TPU,
                         cut_words=render_cut_bits(64, 4096 * 8),
                         plane_sharing=2)
    # a TRACED threshold cannot be proven zero at trace time — rejected
    # outright (a silently correlated drop stream would be worse)
    with pytest.raises(ValueError, match="traced"):
        fused_pull_round(t, 0, 0, 4096 * 8, 1, interpret=not ON_TPU,
                         drop_threshold=jnp.int32(104858),
                         plane_sharing=2)


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    for n in (50, 4096 * 8, 4096 * 8 + 1, 60000):
        inf = rng.random(n) < 0.3
        tab = node_pack(jnp.asarray(inf))
        back = np.asarray(node_unpack(tab, n))
        np.testing.assert_array_equal(back, inf)
        cov = float(coverage_node_packed(tab, n))
        assert abs(cov - inf.mean()) < 1e-6


def test_pull_is_monotone_and_phantoms_stay_zero():
    n = 4096 * 8 - 123
    rng = np.random.default_rng(1)
    rows = n_rows(n)
    inf = rng.random(n) < 0.1
    table = node_pack(jnp.asarray(inf))
    sbits, rbits = _random_bits(rng, rows, 1)
    out = np.asarray(fused_pull_round(table, 0, 0, n, 1,
                                      interpret=not ON_TPU,
                                      inject_bits=(sbits, rbits)))
    before = np.asarray(node_unpack(table, n))
    after = np.asarray(node_unpack(jnp.asarray(out), n))
    assert (after | before == after).all(), "pull must be monotone"
    n_valid_words = -(-n // BITS)
    flat = out.reshape(-1)
    assert not flat[n_valid_words:].any()
    tail = n % BITS
    if tail:
        assert flat[n_valid_words - 1] < (1 << tail)


def test_injected_uniform_bits_track_mean_field():
    """With good injected bits the coverage recurrence c' = 1-(1-c)^2
    (every node pulls one uniform partner) must hold to a few percent."""
    n = 4096 * 32
    rows = n_rows(n)
    rng = np.random.default_rng(7)
    cov = 0.2
    inf = rng.random(n) < cov
    table = node_pack(jnp.asarray(inf))
    sbits, rbits = _random_bits(rng, rows, 1)
    out = fused_pull_round(table, 0, 0, n, 1, interpret=not ON_TPU,
                           inject_bits=(sbits, rbits))
    got = float(coverage_node_packed(out, n))
    c = inf.mean()
    want = 1 - (1 - c) ** 2
    assert abs(got - want) < 0.02, (got, want)


# ---- multi-rumor (one-word-per-node) kernel -------------------------------

def numpy_mr_round(table, sbits, rbits, n, fanout):
    """Independent model of the multi-rumor kernel's sampling scheme."""
    rows = table.shape[0]
    acc = table.copy()
    for f in range(fanout):
        s = (sbits[f, 0, :].astype(np.uint64) % rows).astype(np.int64)
        i = np.arange(rows)[:, None]
        rot = table[(i - s[None, :]) % rows, np.arange(LANES)[None, :]]
        m = rbits[f] & (LANES - 1)
        acc = acc | np.take_along_axis(rot, m.astype(np.int64), axis=1)
    flat = acc.reshape(-1)
    flat[n:] = 0
    return flat.reshape(rows, LANES)


def _mr_bits(rng, rows, fanout):
    sbits = rng.integers(0, 2**32, size=(fanout, 8, LANES), dtype=np.uint32)
    rbits = rng.integers(0, 2**32, size=(fanout, rows, LANES),
                         dtype=np.uint32)
    return sbits, rbits


@pytest.mark.parametrize("n,r,fanout", [(128 * 16, 8, 1),
                                        (128 * 16 - 29, 32, 1),
                                        (128 * 24, 3, 2)])
def test_mr_kernel_math_matches_numpy_model(n, r, fanout):
    rng = np.random.default_rng(5 + n + r)
    rows = mr_rows(n)
    seen = rng.random((n, r)) < 0.05
    table = np.asarray(word_pack(jnp.asarray(seen)))
    sbits, rbits = _mr_bits(rng, rows, fanout)
    got = fused_multirumor_pull_round(jnp.asarray(table), 0, 0, n, fanout,
                                      interpret=not ON_TPU,
                                      inject_bits=(sbits, rbits))
    want = numpy_mr_round(table, sbits, rbits, n, fanout)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_mr_pack_roundtrip_and_coverage():
    rng = np.random.default_rng(3)
    for n, r in ((200, 5), (128 * 16 + 1, 32), (5000, 1)):
        seen = rng.random((n, r)) < 0.3
        tab = word_pack(jnp.asarray(seen))
        np.testing.assert_array_equal(np.asarray(word_unpack(tab, n, r)),
                                      seen)
        cov = float(coverage_words(tab, n, r))
        assert abs(cov - seen.mean(axis=0).min()) < 1e-6
    with pytest.raises(ValueError, match="rumors"):
        word_pack(jnp.zeros((64, 33), bool))


def test_mr_all_rumors_share_one_partner_per_draw():
    """A pull moves the partner's WHOLE word: wherever rumor 0 was newly
    received, every rumor the partner held must arrive with it."""
    n, r = 128 * 16, 7
    rng = np.random.default_rng(9)
    rows = mr_rows(n)
    # partner candidates hold either ALL rumors or none
    holders = rng.random(n) < 0.1
    seen = np.repeat(holders[:, None], r, axis=1)
    table = word_pack(jnp.asarray(seen))
    sbits, rbits = _mr_bits(rng, rows, 1)
    out = np.asarray(fused_multirumor_pull_round(
        table, 0, 0, n, 1, interpret=not ON_TPU,
        inject_bits=(sbits, rbits)))
    got = np.asarray(word_unpack(jnp.asarray(out), n, r))
    # every node's row is all-True or all-False: digests moved atomically
    assert (got.all(axis=1) | (~got.any(axis=1))).all()


def test_mr_injected_bits_track_mean_field():
    n, r = 128 * 64, 8
    rows = mr_rows(n)
    rng = np.random.default_rng(11)
    seen = rng.random((n, r)) < 0.2
    table = word_pack(jnp.asarray(seen))
    sbits, rbits = _mr_bits(rng, rows, 1)
    out = fused_multirumor_pull_round(table, 0, 0, n, 1,
                                      interpret=not ON_TPU,
                                      inject_bits=(sbits, rbits))
    got = float(coverage_words(out, n, r))
    c = 0.2
    want = 1 - (1 - c) ** 2
    assert abs(got - want) < 0.03, (got, want)


@pytest.mark.skipif(not ON_TPU, reason="hw PRNG path needs a real TPU "
                    "(interpreter stubs prng_random_bits with zeros)")
class TestHardwarePRNGMultirumor:
    def test_deterministic_and_stream_distinct(self):
        n, r = 128 * 64, 8
        st = init_multirumor_state(n, r)
        a = fused_multirumor_pull_round(st.table, 3, 5, n)
        b = fused_multirumor_pull_round(init_multirumor_state(n, r).table,
                                        3, 5, n)
        assert jnp.array_equal(a, b)
        c = fused_multirumor_pull_round(init_multirumor_state(n, r).table,
                                        3, 6, n)
        assert not jnp.array_equal(a, c)

    def test_mr_curve_matches_mean_field(self):
        n, r = 1 << 18, 8
        loop, init = compiled_until_fused_multirumor(n, r, seed=0,
                                                     max_rounds=64)
        final = loop(init)
        got = int(final.round)
        c, want = 1.0 / n, 0
        while c < 0.99:
            c = 1 - (1 - c) ** 2
            want += 1
        # min-over-rumors lags single-rumor coverage by a round or two
        assert want - 1 <= got <= want + 4, (got, want)
        assert float(coverage_words(final.table, n, r)) >= 0.99


@pytest.mark.skipif(not ON_TPU, reason="hw PRNG path needs a real TPU "
                    "(interpreter stubs prng_random_bits with zeros)")
class TestHardwarePRNG:
    def test_deterministic_same_seed_and_round(self):
        n = 4096 * 16
        st = init_fused_state(n)
        a = fused_pull_round(st.table, 3, 5, n)
        b = fused_pull_round(init_fused_state(n).table, 3, 5, n)
        assert jnp.array_equal(a, b)

    def test_round_and_seed_vary_the_draw(self):
        n = 4096 * 16
        rng = np.random.default_rng(2)
        inf = jnp.asarray(rng.random(n) < 0.2)
        tab = node_pack(inf)
        a = fused_pull_round(tab, 3, 5, n)
        b = fused_pull_round(node_pack(inf), 3, 6, n)
        c = fused_pull_round(node_pack(inf), 4, 5, n)
        assert not jnp.array_equal(a, b)
        assert not jnp.array_equal(a, c)

    def test_curve_matches_mean_field_trajectory(self):
        """rounds-to-99% at N=2^18 must match the mean-field recurrence
        (c' = 1-(1-c)^2 from c0=1/N) within +/-3 rounds, like the threefry
        pull path does."""
        n = 1 << 18
        loop, init = compiled_until_fused(n, seed=0, max_rounds=64)
        final = loop(init)
        got = int(final.round)
        c, want = 1.0 / n, 0
        while c < 0.99:
            c = 1 - (1 - c) ** 2
            want += 1
        assert abs(got - want) <= 3, (got, want)
        assert float(coverage_node_packed(final.table, n)) >= 0.99

    def test_fanout_two_converges_faster(self):
        n = 1 << 18
        l1, i1 = compiled_until_fused(n, seed=1, fanout=1, max_rounds=64)
        l2, i2 = compiled_until_fused(n, seed=1, fanout=2, max_rounds=64)
        r1 = int(l1(i1).round)
        r2 = int(l2(i2).round)
        assert r2 < r1


@pytest.mark.parametrize("n", [128 * 16, 128 * 24 - 37])
def test_mr_staged_big_path_bitwise_matches_value_kernel(n):
    """The staged big-table path (XLA rotation + grid-blocked gather —
    the route for tables past the VMEM envelope, e.g. 10M x 32 rumors)
    computes the SAME function as the value kernel: bitwise-equal on
    identical injected bits, including phantom masking at ragged n."""
    from gossip_tpu.ops.pallas_round import _fused_mr_round_big, _mr_wants_big
    rng = np.random.default_rng(11 + n)
    rows = mr_rows(n)
    seen = rng.random((n, 32)) < 0.03
    table = jnp.asarray(np.asarray(word_pack(jnp.asarray(seen))))
    sbits, rbits = _mr_bits(rng, rows, 1)
    want = fused_multirumor_pull_round(table, 0, 0, n, 1,
                                       interpret=not ON_TPU,
                                       inject_bits=(sbits, rbits))
    got = _fused_mr_round_big(table, 0, 0, n, not ON_TPU, (sbits, rbits))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # fanout 2 (round 5): multi-pass accumulation must still compute the
    # value kernel's function bitwise on identical injected bits
    sbits2, rbits2 = _mr_bits(rng, rows, 2)
    want2 = fused_multirumor_pull_round(table, 0, 0, n, 2,
                                        interpret=not ON_TPU,
                                        inject_bits=(sbits2, rbits2))
    got2 = _fused_mr_round_big(table, 0, 0, n, not ON_TPU,
                               (sbits2, rbits2), fanout=2)
    np.testing.assert_array_equal(np.asarray(got2), np.asarray(want2))
    # routing: any over-VMEM table picks the big path regardless of
    # fanout (round 5); small tables stay on the value kernel
    assert _mr_wants_big(mr_rows(10_000_000) * LANES * 4, 1)
    assert _mr_wants_big(mr_rows(10_000_000) * LANES * 4, 2)
    assert not _mr_wants_big(mr_rows(1_000_000) * LANES * 4, 1)


def test_mr_staged_big_path_multiblock_grid(monkeypatch):
    """Exercise the staged path's block-indexed code — the node_id block
    offset, the per-block rbits BlockSpec index map, and a RAGGED final
    block (rows not a multiple of the block) — by shrinking the block so
    the grid has several steps, as it does at the 10M flagship
    (78128 rows / 1024-row blocks)."""
    import gossip_tpu.ops.pallas_round as PR
    monkeypatch.setattr(PR, "_MR_GATHER_BLOCK", 16)
    rng = np.random.default_rng(23)
    rows = 40                               # 2 full blocks + ragged 8
    n = rows * LANES - 13
    seen = rng.random((n, 32)) < 0.03
    table = jnp.asarray(np.asarray(word_pack(jnp.asarray(seen))))
    sbits, rbits = _mr_bits(rng, rows, 1)
    want = fused_multirumor_pull_round(table, 0, 0, n, 1,
                                       interpret=not ON_TPU,
                                       inject_bits=(sbits, rbits))
    got = PR._fused_mr_round_big(table, 0, 0, n, not ON_TPU, (sbits, rbits))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.skipif(not ON_TPU, reason="hw PRNG path needs a real TPU "
                    "(interpreter stubs prng_random_bits with zeros)")
class TestHardwarePRNGStagedBigPath:
    """Statistical checks of the STAGED big-table path's hw-PRNG scheme —
    the per-block seed fold is new code with its own randomness shape
    (one stream per grid block instead of one (rows,128) draw)."""

    def test_block_streams_are_distinct(self):
        """All-rows-identical table: the rotation is a no-op and each
        output block is a pure function of its own block's lane draws —
        if the per-block seed fold degenerated (same stream per block),
        block outputs would repeat with the grid period."""
        from gossip_tpu.ops.pallas_round import (_MR_GATHER_BLOCK,
                                                 _fused_mr_round_big)
        rows = 4 * _MR_GATHER_BLOCK              # 4 exact grid blocks
        n = rows * LANES
        rng = np.random.default_rng(0)
        row = rng.integers(0, 2**32, size=(1, LANES), dtype=np.uint32)
        table = jnp.asarray(np.repeat(row, rows, axis=0))
        out = np.asarray(_fused_mr_round_big(table, 0, 1, n, False, None))
        blocks = out.reshape(4, _MR_GATHER_BLOCK, LANES)
        assert not np.array_equal(blocks[0], blocks[1])
        assert not np.array_equal(blocks[1], blocks[2])
        assert not np.array_equal(blocks[2], blocks[3])
        # determinism on the same (seed, round)
        out2 = np.asarray(_fused_mr_round_big(table, 0, 1, n, False, None))
        np.testing.assert_array_equal(out, out2)
        # distinct stream on the next round
        out3 = np.asarray(_fused_mr_round_big(table, 0, 2, n, False, None))
        assert not np.array_equal(out, out3)

    def test_big_path_growth_at_flagship_scale(self):
        """12 rounds at N=10M x 32 rumors through the real routing
        (fused_multirumor_pull_round picks the staged path): per-rumor
        populations must grow ~2x/round once past branching noise."""
        from gossip_tpu.ops.pallas_round import (_mr_wants_big,
                                                 fused_table_bytes)
        n = 10_000_000
        assert _mr_wants_big(fused_table_bytes(n, 32), 1)   # routing sanity
        st = init_multirumor_state(n, 32)
        out = st.table
        for r in range(1, 13):
            out = fused_multirumor_pull_round(out, jnp.int32(0),
                                              jnp.int32(r), n, 1)
        flat = np.asarray(out).reshape(-1)[:n]
        counts = np.array([int(((flat >> k) & np.uint32(1)).sum())
                           for k in range(32)])
        # mean over 32 independent rumors after 12 doublings from 1:
        # E ~ 2^12; branching variance is tamed by averaging the rumors
        assert 2**10 <= counts.mean() <= 2**14
        assert (counts > 0).all()


# ---------------------------------------------------------------------------
# Fault masks (round 4): static alive bitmap + 20-bit drop threshold in the
# single-rumor fused kernel.  Same CPU strategy as above — injected bits,
# independent numpy model, exact equality.

def numpy_fault_round(table, sbits, rbits, n, fanout, drop_threshold,
                      alive_table):
    """numpy_reference_round + the documented fault-mask semantics:
    dead nodes cleared from the rotation SOURCE (serve nothing) and from
    plane contributions (acquire nothing); a pull whose draw's bits
    12..31 fall below drop_threshold is dropped."""
    rows = table.shape[0]
    s = (sbits[0, :].astype(np.uint64) % rows).astype(np.int64)
    i = np.arange(rows)[:, None]
    src = table & alive_table if alive_table is not None else table
    rot = src[(i - s[None, :]) % rows, np.arange(LANES)[None, :]]
    acc = table.copy()
    for k in range(BITS):
        for f in range(fanout):
            rb = rbits[k * fanout + f]
            m = rb & (LANES - 1)
            c = (rb >> 7) & (BITS - 1)
            partner = np.take_along_axis(rot, m.astype(np.int64), axis=1)
            bit = ((partner >> c) & 1).astype(np.uint32)
            if drop_threshold:
                bit = np.where((rb >> 12) >= drop_threshold, bit,
                               np.uint32(0))
            if alive_table is not None:
                bit = bit & ((alive_table >> np.uint32(k)) & 1)
            acc = acc | (bit << np.uint32(k))
    flat = acc.reshape(-1)
    n_valid_words = -(-n // BITS)
    tail = n % BITS
    out = flat.copy()
    out[n_valid_words:] = 0
    if tail:
        out[n_valid_words - 1] &= np.uint32((1 << tail) - 1)
    return out.reshape(rows, LANES)


@pytest.mark.parametrize("drop_p,death", [(0.3, 0.0), (0.0, 0.25),
                                          (0.2, 0.2)])
def test_kernel_fault_masks_match_numpy_model(drop_p, death):
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import fault_masks_node_packed
    n, fanout = 4096 * 8 - 37, 1
    rng = np.random.default_rng(97)
    rows = n_rows(n)
    infected = rng.random(n) < 0.05
    table = np.asarray(node_pack(jnp.asarray(infected)))
    fault = FaultConfig(drop_prob=drop_p, node_death_rate=death, seed=3)
    alive_tab, thresh = fault_masks_node_packed(fault, n, origin=0)
    alive_np = None if alive_tab is None else np.asarray(alive_tab)
    assert (thresh > 0) == (drop_p > 0)
    assert (alive_np is not None) == (death > 0)
    sbits, rbits = _random_bits(rng, rows, fanout)
    got = fused_pull_round(jnp.asarray(table), 0, 0, n, fanout,
                           interpret=not ON_TPU,
                           inject_bits=(sbits, rbits),
                           drop_threshold=thresh,
                           alive_table=alive_tab)
    want = numpy_fault_round(table, sbits, rbits, n, fanout, thresh,
                             alive_np)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_fault_free_path_unchanged_by_fault_args():
    """drop_threshold=0 + alive_table=None must be EXACTLY the round-2
    kernel: the flagship bench lowering cannot shift under the fault
    feature."""
    n, fanout = 4096 * 8, 1
    rng = np.random.default_rng(5)
    rows = n_rows(n)
    table = np.asarray(node_pack(jnp.asarray(rng.random(n) < 0.05)))
    sbits, rbits = _random_bits(rng, rows, fanout)
    a = fused_pull_round(jnp.asarray(table), 0, 0, n, fanout,
                         interpret=not ON_TPU, inject_bits=(sbits, rbits))
    b = fused_pull_round(jnp.asarray(table), 0, 0, n, fanout,
                         interpret=not ON_TPU, inject_bits=(sbits, rbits),
                         drop_threshold=0, alive_table=None)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_compiled_until_fused_fault_semantics():
    """Driver-level contract on the CPU interpreter.  The stubbed PRNG
    draws zeros -> no rotation, and every word (row i, lane j, plane k)
    pulls bit 0 of word (i, 0): the only initially-infected such source
    is the origin (node 0), so the epidemic's deterministic fixed point
    is "every ALIVE node of row 0" — enough structure to pin the mask
    semantics exactly.  A drop_threshold of 2^20 (drop everything)
    freezes the epidemic entirely."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import NODES_PER_ROW
    n = 4096 * 8
    fault = FaultConfig(node_death_rate=0.3, seed=11)
    loop, init = compiled_until_fused(n, seed=0, max_rounds=3,
                                      interpret=True, fault=fault)
    final = loop(init)
    from gossip_tpu.models.state import alive_mask
    alive = np.asarray(alive_mask(fault, n, 0))
    inf = np.asarray(node_unpack(final.table, n))
    assert not np.any(inf & ~alive), "a dead node acquired infection"
    want = alive & (np.arange(n) < NODES_PER_ROW)   # row 0, alive only
    np.testing.assert_array_equal(inf, want)
    assert int(final.round) == 3                    # fixed point < target

    # drop everything: nothing ever spreads
    frozen = FaultConfig(drop_prob=1.0, seed=1)
    loop2, init2 = compiled_until_fused(n, seed=0, max_rounds=3,
                                        interpret=True, fault=frozen)
    final2 = loop2(init2)
    assert float(coverage_node_packed(final2.table, n)) * n == 1.0
    assert int(final2.round) == 3


@pytest.mark.skipif(not ON_TPU, reason="hw PRNG path needs a real TPU "
                                       "(interpreter stubs random bits)")
class TestHardwarePRNGFaultMasks:
    def test_dead_stay_dark_and_drop_slows_convergence(self):
        """Fault masks under the REAL hardware PRNG: dead nodes never
        acquire infection over a full epidemic, the alive-weighted
        epidemic still completes, and a heavy drop rate costs extra
        rounds vs the fault-free run (statistical, wide margin)."""
        from gossip_tpu.config import FaultConfig
        from gossip_tpu.models.state import alive_mask
        from gossip_tpu.ops.pallas_round import (
            coverage_node_packed_alive, fault_masks_node_packed)
        n = 1 << 18
        fault = FaultConfig(node_death_rate=0.2, seed=7)
        loop, init = compiled_until_fused(n, seed=3, max_rounds=64,
                                          fault=fault)
        final = loop(init)
        alive = np.asarray(alive_mask(fault, n, 0))
        inf = np.asarray(node_unpack(final.table, n))
        assert not np.any(inf & ~alive)
        alive_tab, _ = fault_masks_node_packed(fault, n, 0)
        assert float(coverage_node_packed_alive(final.table,
                                                alive_tab)) >= 0.99
        l0, i0 = compiled_until_fused(n, seed=3, max_rounds=64)
        r0 = int(l0(i0).round)
        drop = FaultConfig(drop_prob=0.5, seed=2)
        ld, idr = compiled_until_fused(n, seed=3, max_rounds=64,
                                       fault=drop)
        rd = int(ld(idr).round)
        assert rd > r0, (rd, r0)    # half the pulls dropped: more rounds


def numpy_mr_fault_round(table, sbits, rbits, n, fanout, drop_threshold,
                         alive_words):
    """numpy_mr_round + the word-layout fault-mask semantics."""
    rows = table.shape[0]
    src = table & alive_words if alive_words is not None else table
    acc = table.copy()
    for f in range(fanout):
        s = (sbits[f, 0, :].astype(np.uint64) % rows).astype(np.int64)
        i = np.arange(rows)[:, None]
        rot = src[(i - s[None, :]) % rows, np.arange(LANES)[None, :]]
        rb = rbits[f]
        m = rb & (LANES - 1)
        partner = np.take_along_axis(rot, m.astype(np.int64), axis=1)
        if drop_threshold:
            partner = np.where((rb >> 12) >= drop_threshold, partner,
                               np.uint32(0))
        if alive_words is not None:
            partner = partner & alive_words
        acc = acc | partner
    flat = acc.reshape(-1)
    flat[n:] = 0
    return flat.reshape(rows, LANES)


@pytest.mark.parametrize("drop_p,death,fanout", [(0.4, 0.0, 2),
                                                 (0.0, 0.3, 1),
                                                 (0.25, 0.15, 1)])
def test_mr_kernel_fault_masks_match_numpy_model(drop_p, death, fanout):
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import fault_masks_word
    n, r = 128 * 16 - 29, 8
    rng = np.random.default_rng(31)
    rows = mr_rows(n)
    seen = rng.random((n, r)) < 0.06
    table = np.asarray(word_pack(jnp.asarray(seen)))
    fault = FaultConfig(drop_prob=drop_p, node_death_rate=death, seed=5)
    alive_words, thresh = fault_masks_word(fault, n, origin=0)
    alive_np = None if alive_words is None else np.asarray(alive_words)
    sbits, rbits = _mr_bits(rng, rows, fanout)
    got = fused_multirumor_pull_round(jnp.asarray(table), 0, 0, n, fanout,
                                      interpret=not ON_TPU,
                                      inject_bits=(sbits, rbits),
                                      drop_threshold=thresh,
                                      alive_words=alive_words)
    want = numpy_mr_fault_round(table, sbits, rbits, n, fanout, thresh,
                                alive_np)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_mr_staged_big_path_fault_masks_match_value_kernel():
    """Both MR routes implement the SAME faulted function: bitwise-equal
    on identical injected bits with the alive + drop masks on."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import (_fused_mr_round_big,
                                             fault_masks_word)
    n = 128 * 16 - 29
    rng = np.random.default_rng(13)
    rows = mr_rows(n)
    seen = rng.random((n, 32)) < 0.04
    table = jnp.asarray(np.asarray(word_pack(jnp.asarray(seen))))
    fault = FaultConfig(drop_prob=0.3, node_death_rate=0.2, seed=9)
    alive_words, thresh = fault_masks_word(fault, n, origin=0)
    sbits, rbits = _mr_bits(rng, rows, 1)
    want = fused_multirumor_pull_round(table, 0, 0, n, 1,
                                       interpret=not ON_TPU,
                                       inject_bits=(sbits, rbits),
                                       drop_threshold=thresh,
                                       alive_words=alive_words)
    got = _fused_mr_round_big(table, 0, 0, n, not ON_TPU, (sbits, rbits),
                              drop_threshold=thresh,
                              alive_words=alive_words)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_mr_fault_free_path_unchanged_by_fault_args():
    n, r = 128 * 16, 8
    rng = np.random.default_rng(8)
    rows = mr_rows(n)
    table = jnp.asarray(np.asarray(word_pack(
        jnp.asarray(rng.random((n, r)) < 0.05))))
    sbits, rbits = _mr_bits(rng, rows, 1)
    a = fused_multirumor_pull_round(table, 0, 0, n, 1,
                                    interpret=not ON_TPU,
                                    inject_bits=(sbits, rbits))
    b = fused_multirumor_pull_round(table, 0, 0, n, 1,
                                    interpret=not ON_TPU,
                                    inject_bits=(sbits, rbits),
                                    drop_threshold=0, alive_words=None)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_coverage_words_alive_weighting():
    """Alive-weighted MR coverage: dead nodes leave the denominator and
    their rumor bits stop counting."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import (coverage_words_alive,
                                             fault_masks_word)
    from gossip_tpu.models.state import alive_mask
    n, r = 500, 4
    rng = np.random.default_rng(2)
    seen = rng.random((n, r)) < 0.5
    fault = FaultConfig(node_death_rate=0.3, seed=6)
    alive = np.asarray(alive_mask(fault, n, 0))
    alive_words, _ = fault_masks_word(fault, n, 0)
    got = float(coverage_words_alive(word_pack(jnp.asarray(seen)),
                                     alive_words, r))
    want = (seen[alive].mean(axis=0)).min()
    assert got == pytest.approx(want, abs=1e-6)


@pytest.mark.skipif(not ON_TPU, reason="hw PRNG path needs a real TPU "
                                       "(interpreter stubs random bits)")
class TestHardwarePRNGFaultMasksMultirumor:
    def test_mr_dead_stay_dark_under_hw_prng(self):
        """Per-rumor contract: a rumor whose origin survives the death
        draw floods the alive population; a rumor whose origin is dead
        never spreads (rumor.py's documented SI property) — and no dead
        node ever holds any rumor.  Only the loop's max_rounds drives
        the run (the min-over-rumors cond can't reach target when any
        origin is dead, which the alive draw here includes on
        purpose)."""
        from gossip_tpu.config import FaultConfig
        from gossip_tpu.models.state import alive_mask
        from gossip_tpu.ops.pallas_round import (
            compiled_until_fused_multirumor, word_unpack)
        n, r = 1 << 16, 8
        fault = FaultConfig(node_death_rate=0.2, drop_prob=0.1, seed=4)
        loop, init = compiled_until_fused_multirumor(n, r, seed=5,
                                                     max_rounds=48,
                                                     fault=fault)
        final = loop(init)
        alive = np.asarray(alive_mask(fault, n, 0))
        seen = np.asarray(word_unpack(final.table, n, r))
        # dead nodes ACQUIRE nothing, but their own state stays put
        # (kernel contract: acc starts from the table) — so a dead
        # ORIGIN keeps exactly its own seeded bit; every other dead
        # node holds nothing
        dead_ids = np.arange(n)[~alive]
        expect_dark = np.zeros((len(dead_ids), r), bool)
        is_origin = dead_ids < r
        expect_dark[is_origin, dead_ids[is_origin]] = True
        np.testing.assert_array_equal(seen[~alive], expect_dark)
        per_rumor = seen[alive].mean(axis=0)
        for rr in range(r):
            if alive[rr]:              # origin of rumor rr is node rr
                assert per_rumor[rr] >= 0.99, (rr, per_rumor[rr])
            else:
                assert per_rumor[rr] == 0.0, (rr, per_rumor[rr])


# ---------------------------------------------------------------------------
# Reference-vs-Mosaic interpret equivalence.  ``interpret=True`` routes the
# fused entry points through the pure-JAX reference lowering (fast XLA — the
# driver/dry-run path); ``interpret="mosaic"`` forces the real Mosaic
# interpreter.  These tests pin them bitwise-equal on injected bits, so the
# kernel BODIES stay executed in CI and the reference can never drift.
# (Injected bits only: the 0.4.x Mosaic interpreter has no CPU lowering for
# the TPU PRNG primitives — gossip_tpu/compat.py module doc.)

@pytest.mark.parametrize("fanout,sharing,drop_p,death",
                         [(1, 1, 0.0, 0.0),
                          # fault case rides the slow tier (tier-1 wall
                          # budget); the fault masks stay gated via
                          # test_kernel_fault_masks_match_numpy_model
                          pytest.param(2, 1, 0.3, 0.2,
                                       marks=pytest.mark.slow),
                          (1, 2, 0.0, 0.0)])
def test_reference_interpret_matches_mosaic_single_rumor(fanout, sharing,
                                                         drop_p, death):
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import fault_masks_node_packed
    n = 4096 * 8 - 37
    rng = np.random.default_rng(71 + fanout + sharing)
    rows = n_rows(n)
    table = jnp.asarray(np.asarray(node_pack(
        jnp.asarray(rng.random(n) < 0.05))))
    alive_tab, thresh = (None, 0)
    if drop_p or death:
        fault = FaultConfig(drop_prob=drop_p, node_death_rate=death, seed=3)
        alive_tab, thresh = fault_masks_node_packed(fault, n, 0)
    bits = _random_bits(rng, rows, fanout, sharing)
    kw = dict(inject_bits=bits, drop_threshold=thresh,
              alive_table=alive_tab, plane_sharing=sharing)
    ref = fused_pull_round(table, 0, 0, n, fanout, interpret=True, **kw)
    mos = fused_pull_round(table, 0, 0, n, fanout, interpret="mosaic", **kw)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(mos))


@pytest.mark.parametrize("fanout,drop_p,death", [(1, 0.0, 0.0),
                                                 (2, 0.25, 0.15)])
def test_reference_interpret_matches_mosaic_multirumor(fanout, drop_p,
                                                       death):
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import fault_masks_word
    n = 128 * 16 - 29
    rng = np.random.default_rng(83 + fanout)
    rows = mr_rows(n)
    table = jnp.asarray(np.asarray(word_pack(
        jnp.asarray(rng.random((n, 16)) < 0.05))))
    alive_words, thresh = (None, 0)
    if drop_p or death:
        fault = FaultConfig(drop_prob=drop_p, node_death_rate=death, seed=5)
        alive_words, thresh = fault_masks_word(fault, n, 0)
    bits = _mr_bits(rng, rows, fanout)
    kw = dict(inject_bits=bits, drop_threshold=thresh,
              alive_words=alive_words)
    ref = fused_multirumor_pull_round(table, 0, 0, n, fanout,
                                      interpret=True, **kw)
    mos = fused_multirumor_pull_round(table, 0, 0, n, fanout,
                                      interpret="mosaic", **kw)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(mos))


@pytest.mark.parametrize("fanout", [1, 2])
def test_reference_interpret_matches_mosaic_staged_big_path(fanout):
    """Both interpret impls of the STAGED path agree bitwise — and at
    fanout > 1 the mosaic route exercises the no-draw-0-alias donation
    rule (the fanout>1 fix) against the same operands."""
    from gossip_tpu.ops.pallas_round import _fused_mr_round_big
    n = 128 * 16 - 29
    rng = np.random.default_rng(97 + fanout)
    rows = mr_rows(n)
    table = jnp.asarray(np.asarray(word_pack(
        jnp.asarray(rng.random((n, 32)) < 0.04))))
    bits = _mr_bits(rng, rows, fanout)
    ref = _fused_mr_round_big(table, 0, 0, n, True, bits, fanout=fanout)
    mos = _fused_mr_round_big(table, 0, 0, n, "mosaic", bits,
                              fanout=fanout)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(mos))
    # the value kernel computes the same function on the same bits
    want = fused_multirumor_pull_round(table, 0, 0, n, fanout,
                                       interpret=True, inject_bits=bits)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(want))


def test_compiled_curve_fused_matches_stepwise():
    """The fixed-length curve scan is the SAME trajectory as stepping
    the kernel by hand (stubbed interpreter PRNG is deterministic),
    with the per-round coverage recorded — single-rumor and MR twins,
    fault masks included."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import (
        compiled_curve_fused, compiled_curve_fused_multirumor,
        fault_masks_node_packed, fused_cov_fn, fused_mr_cov_fn)
    n, rounds = 4096 * 8, 3
    fault = FaultConfig(node_death_rate=0.25, seed=3)
    scan, init = compiled_curve_fused(n, seed=0, max_rounds=rounds,
                                      interpret=True, fault=fault)
    final, covs = scan(init)
    assert covs.shape == (rounds,) and int(final.round) == rounds
    # stepwise twin
    alive_tab, thresh = fault_masks_node_packed(fault, n, 0)
    tab = init_fused_state(n, 0).table
    cov = fused_cov_fn(n, fault, 0)
    for t in range(rounds):
        tab = fused_pull_round(tab, 0, t, n, 1, interpret=True,
                               drop_threshold=thresh, alive_table=alive_tab)
        assert float(covs[t]) == float(cov(tab)), t
    np.testing.assert_array_equal(np.asarray(final.table), np.asarray(tab))

    n_mr, r = 128 * 16, 8
    scan_mr, init_mr = compiled_curve_fused_multirumor(
        n_mr, r, seed=0, max_rounds=rounds, interpret=True)
    final_mr, covs_mr = scan_mr(init_mr)
    assert covs_mr.shape == (rounds,)
    tab = init_multirumor_state(n_mr, r, 0).table
    cov_mr = fused_mr_cov_fn(n_mr, r)
    for t in range(rounds):
        tab = fused_multirumor_pull_round(tab, 0, t, n_mr, 1,
                                          interpret=True)
        assert float(covs_mr[t]) == float(cov_mr(tab)), t
    np.testing.assert_array_equal(np.asarray(final_mr.table),
                                  np.asarray(tab))
