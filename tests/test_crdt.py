"""CRDT gossip subsystem (ops/crdt, models/crdt, parallel/sharded_crdt):
algebraic merge pins (commutativity / associativity / idempotence,
BITWISE), injection lowering + acked-adds ground truth, the
partition-heal value-convergence acceptance, 1-vs-4-device bitwise
parity under full fault programs, the value_conv round-metrics column,
CLI + Maelstrom counter-workload surfaces, the committed artifact
verdict pin, and the no-CRDT regression guard (existing fabric
trajectories bitwise unchanged)."""

import json
import os

import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import (ChurnConfig, CrdtConfig, FaultConfig,
                               ProtocolConfig, RunConfig)
from gossip_tpu.topology import generators as G

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROTO = ProtocolConfig(mode=C.PULL, fanout=2)
# the full mixed fault program every parity/heal surface runs:
# crash/recover, permanent crash, open partition window, drop ramp
_CFAULT = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
    events=((3, 2, 5), (7, 1, -1)), partitions=((0, 6, 16),),
    ramp=(1, 4, 0.0, 0.3)))


# -- config validation -------------------------------------------------

def test_crdt_config_validation():
    CrdtConfig(kind="gcounter", adds=((0, 0, 5), (3, 2, 1)))
    CrdtConfig(kind="pncounter", adds=((0, 0, -5),))
    CrdtConfig(kind="orset", elements=40, set_adds=((0, 0), (39, 2)),
               set_removes=((0, 3),))
    with pytest.raises(ValueError, match="unknown CRDT kind"):
        CrdtConfig(kind="lww")
    with pytest.raises(ValueError, match="positive"):
        CrdtConfig(kind="gcounter", adds=((0, 0, -1),))
    with pytest.raises(ValueError, match="nonzero"):
        CrdtConfig(kind="pncounter", adds=((0, 0, 0),))
    with pytest.raises(ValueError, match="universe"):
        CrdtConfig(kind="gset", elements=8, set_adds=((8, 0),))
    with pytest.raises(ValueError, match="grow-only"):
        CrdtConfig(kind="gset", set_adds=((0, 0),),
                   set_removes=((0, 1),))
    with pytest.raises(ValueError, match="at most once"):
        CrdtConfig(kind="orset", set_adds=((2, 0), (2, 1)))
    with pytest.raises(ValueError, match="counter adds"):
        CrdtConfig(kind="orset", adds=((0, 0, 1),))
    with pytest.raises(ValueError, match="set_adds"):
        CrdtConfig(kind="gcounter", set_adds=((0, 0),))
    with pytest.raises(ValueError, match="horizon cap"):
        CrdtConfig(kind="gcounter", adds=((0, 10 ** 9, 1),))
    # vclock carries no injection program — a scripted one must be a
    # loud error, never a silent no-op
    with pytest.raises(ValueError, match="no injection program"):
        CrdtConfig(kind="vclock", adds=((0, 0, 5),))
    # a remove at-or-before its element's add would silently fork
    # add-wins into remove-wins — rejected (happens-after contract)
    with pytest.raises(ValueError, match="happen-after"):
        CrdtConfig(kind="orset", elements=8, set_adds=((5, 4),),
                   set_removes=((5, 2),))
    with pytest.raises(ValueError, match="happen-after"):
        CrdtConfig(kind="orset", set_removes=((5, 0),))  # default add @0
    # a remove of a never-added element is a harmless no-op: allowed
    CrdtConfig(kind="orset", elements=8, set_adds=((1, 0),),
               set_removes=((5, 0),))
    # horizon: last injection round + 1
    assert CrdtConfig(kind="gcounter", adds=((0, 7, 1),)).horizon() == 8


# -- algebraic pins: the join-semilattice laws, bitwise ----------------

def _random_state(kind, n, elements, rng):
    from gossip_tpu.ops import crdt as CR
    if kind in C.CRDT_SET_KINDS:
        w = 2 * ((elements + 31) // 32)
        return rng.integers(0, 2 ** 32, size=(n, w), dtype=np.uint32)
    s = CR.shard_columns(kind, n)
    return rng.integers(0, 1000, size=(n, s), dtype=np.int32)


def _assert_merge_laws(kind, seeds, n=16, elements=40):
    import jax.numpy as jnp

    from gossip_tpu.ops import crdt as CR
    for seed in seeds:
        rng = np.random.default_rng(seed)
        a, b, c = (jnp.asarray(_random_state(kind, n, elements, rng))
                   for _ in range(3))
        ab = np.asarray(CR.merge(kind, a, b))
        ba = np.asarray(CR.merge(kind, b, a))
        assert (ab == ba).all(), f"{kind}: merge not commutative"
        abc1 = np.asarray(CR.merge(kind, CR.merge(kind, a, b), c))
        abc2 = np.asarray(CR.merge(kind, a, CR.merge(kind, b, c)))
        assert (abc1 == abc2).all(), f"{kind}: merge not associative"
        aa = np.asarray(CR.merge(kind, a, a))
        assert (aa == np.asarray(a)).all(), f"{kind}: not idempotent"
        # merge is an upper bound of both operands (join-semilattice)
        assert (np.asarray(CR.merge(kind, jnp.asarray(ab), a))
                == ab).all(), f"{kind}: merge not an upper bound"


def test_merge_algebra_bitwise_smoke():
    """Commutativity / associativity / idempotence on random states,
    BITWISE, for every kind (the in-gate smoke; depth under -m slow)."""
    for kind in C.CRDT_KINDS:
        _assert_merge_laws(kind, seeds=range(3))


@pytest.mark.slow
def test_merge_algebra_bitwise_depth():
    for kind in C.CRDT_KINDS:
        _assert_merge_laws(kind, seeds=range(50), n=33, elements=97)


def test_vclock_tick_and_merge():
    """The vector-clock kernel: owner-only ticks, merge = elementwise
    max dominates both histories."""
    import jax.numpy as jnp

    from gossip_tpu.ops import crdt as CR
    n = 4
    vc = jnp.zeros((n, n), jnp.int32)
    ids = jnp.arange(n, dtype=jnp.int32)
    alive = jnp.asarray([True, True, False, True])
    vc = CR.vclock_tick(vc, ids, alive, n)
    assert np.asarray(vc).diagonal().tolist() == [1, 1, 0, 1]
    other = jnp.zeros((n, n), jnp.int32).at[:, 2].set(7)
    merged = np.asarray(CR.merge(C.VCLOCK, vc, other))
    assert (merged[:, 2] == 7).all()
    # max dominates both histories: node 2's own entry takes the
    # larger observed clock, everyone else keeps their tick
    assert merged.diagonal().tolist() == [1, 1, 7, 1]


# -- injection lowering + acked-adds ground truth ----------------------

def test_ground_truth_acked_adds_semantics():
    """An injection is applied iff its owner is alive at the injection
    round AND eventually alive — a permanently-dead owner contributes
    nothing, an owner down at the round misses its one-shot add, a
    temporarily-down-later owner's add stays in (it recovers and must
    re-disseminate)."""
    from gossip_tpu.ops import crdt as CR
    n = 8
    cfg = CrdtConfig(kind="gcounter",
                     adds=((0, 0, 10),   # healthy: applied
                           (1, 2, 20),   # owner down [1, 4): missed
                           (2, 0, 30),   # owner dies forever at 3: out
                           (3, 5, 40)))  # owner down [1, 4), adds at 5
    f = FaultConfig(churn=ChurnConfig(events=((1, 1, 4), (2, 3, -1),
                                              (3, 1, 4))))
    truth = np.asarray(CR.ground_truth(cfg, CR.inject_args(cfg, n), f,
                                       n, 0))
    assert truth.tolist() == [10, 0, 0, 40, 0, 0, 0, 0]
    # fault-free: everything applies
    truth0 = np.asarray(CR.ground_truth(cfg, CR.inject_args(cfg, n),
                                        None, n, 0))
    assert truth0.tolist() == [10, 20, 30, 40, 0, 0, 0, 0]
    # the default program's closed form: node j adds 1 + j%7 at round 0
    d = CrdtConfig(kind="gcounter")
    td = np.asarray(CR.ground_truth(d, CR.inject_args(d, n), None, n, 0))
    assert td.tolist() == [1 + j % 7 for j in range(n)]
    # out-of-range scripted ids are a loud error, not a silent no-op
    with pytest.raises(ValueError, match="node ids"):
        CR.inject_args(CrdtConfig(kind="gcounter", adds=((99, 0, 1),)),
                       n)


def test_set_injection_owner_rotation_and_tombstones():
    from gossip_tpu.ops import crdt as CR
    n = 8
    cfg = CrdtConfig(kind="orset", elements=40, set_removes=((5, 3),))
    truth = np.asarray(CR.ground_truth(cfg, CR.inject_args(cfg, n),
                                       None, n, 0))
    members = np.asarray(CR.set_members(truth[None, :]))[0]
    bits = sum(bin(int(x)).count("1") for x in members)
    assert bits == 39                       # 40 added, element 5 removed
    # a permanent death excludes every element that node owns
    f = FaultConfig(churn=ChurnConfig(events=((7, 1, -1),)))
    trc = np.asarray(CR.ground_truth(cfg, CR.inject_args(cfg, n), f,
                                     n, 0))
    mc = np.asarray(CR.set_members(trc[None, :]))[0]
    bits_c = sum(bin(int(x)).count("1") for x in mc)
    # elements 7, 15, 23, 31, 39 owned by node 7 -> 5 adds excluded
    # (element 5's remove still applies: owner node 5 is alive)
    assert bits_c == 40 - 5 - 1


# -- partition-heal value convergence (the acceptance gate) ------------

_HEAL_N = 64
_HEAL_END = 8    # long enough for each side to saturate its own split


def _heal_bound(fanout):
    # ~2 epidemic legs + slack after the window closes (the
    # docs/ROBUSTNESS.md bound the broadcast heal tests use)
    import math
    leg = math.ceil(math.log(_HEAL_N) / math.log(1 + fanout))
    return _HEAL_END + 2 * leg + 4


def test_partition_heal_value_convergence_stall_and_exact_heal():
    """While the window is open, value convergence provably stalls at
    the partition value split — each side's merged value is exactly its
    OWN side's truth sum, nobody holds the global truth — and after
    heal every node reaches the exact integer ground truth within the
    documented bound."""
    from gossip_tpu.models.crdt import simulate_curve_crdt
    from gossip_tpu.ops import crdt as CR
    cut = 48
    cfg = CrdtConfig(kind="gcounter")
    fault = FaultConfig(seed=0, churn=ChurnConfig(
        partitions=((0, _HEAL_END, cut),)))
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    topo = G.complete(_HEAL_N)
    conv, msgs, final, truth_val = simulate_curve_crdt(
        cfg, _PROTO, topo, run, fault)
    # stalled: nobody converges to the GLOBAL truth while the cut is
    # open (both sides hold strictly partial sums)
    assert all(c == 0.0 for c in conv[:_HEAL_END]), list(conv)
    # ... and the stall sits exactly at the partition value SPLIT: by
    # round _HEAL_END every node holds its own side's full sum — run
    # the open-window prefix and check the integer split
    prefix = RunConfig(seed=0, max_rounds=_HEAL_END - 1,
                       target_coverage=1.0)
    _, _, mid, _ = simulate_curve_crdt(cfg, _PROTO, topo, prefix, fault)
    truth = np.asarray(CR.ground_truth(
        cfg, CR.inject_args(cfg, _HEAL_N), fault, _HEAL_N, 0))
    lo_sum, hi_sum = int(truth[:cut].sum()), int(truth[cut:].sum())
    vals = np.asarray(mid.val).sum(axis=1)
    assert vals.max() <= lo_sum + hi_sum
    assert (vals[:cut] <= lo_sum).all() and (vals[cut:] <= hi_sum).all()
    assert vals[:cut].max() == lo_sum       # near side saturated its split
    # healed: EXACT ground truth everywhere within the bound
    hit = np.nonzero(np.asarray(conv) >= 1.0)[0]
    assert len(hit), f"never healed: {list(conv)}"
    assert int(hit[0]) + 1 <= _heal_bound(_PROTO.fanout), list(conv)
    assert (np.asarray(final.val)
            == truth[None, :]).all()        # integer-exact, every node
    assert truth_val == lo_sum + hi_sum


def test_heal_under_full_fault_program_pncounter():
    """The PN-counter reaches exact ground truth on the eventual-alive
    set under the full mixed fault program (event + permanent crash +
    window + ramp) — the integer-exact eventual-consistency invariant.
    (In-gate this covers the one kind the parity tests below do not
    already drive to 1.0 under _CFAULT; the all-kinds sweep runs in
    the slow tier — tier-1 wall budget.)"""
    from gossip_tpu.models.crdt import simulate_curve_crdt
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    conv, _, final, _ = simulate_curve_crdt(
        CrdtConfig(kind="pncounter"), _PROTO, G.complete(32), run,
        _CFAULT)
    assert conv[-1] == 1.0, list(conv)


@pytest.mark.slow
def test_heal_under_full_fault_program_all_kinds():
    from gossip_tpu.models.crdt import simulate_curve_crdt
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    topo = G.complete(32)
    for cfg in (CrdtConfig(kind="gcounter"),
                CrdtConfig(kind="pncounter"),
                CrdtConfig(kind="orset", elements=40,
                           set_removes=((5, 3),)),
                CrdtConfig(kind="gset", elements=40)):
        conv, _, final, _ = simulate_curve_crdt(cfg, _PROTO, topo, run,
                                                _CFAULT)
        assert conv[-1] == 1.0, (cfg.kind, list(conv))


# -- mesh parity: dense + packed sharded fabric, schedules as operands -

def _mesh(k=4):
    from gossip_tpu.parallel.sharded import make_mesh
    return make_mesh(k)


def test_crdt_mesh_parity_bitwise_gcounter():
    """1-device vs 4-device trajectories BITWISE identical under the
    full fault program — the counter payload on the dense sharded
    exchange (int32 shard rows over all_gather)."""
    from gossip_tpu.models.crdt import simulate_curve_crdt
    from gossip_tpu.parallel.sharded_crdt import (
        simulate_curve_crdt_sharded)
    run = RunConfig(seed=0, max_rounds=16, target_coverage=1.0)
    topo = G.complete(32)
    cfg = CrdtConfig(kind="gcounter")
    c1, m1, f1, t1 = simulate_curve_crdt(cfg, _PROTO, topo, run, _CFAULT)
    c4, m4, f4, t4 = simulate_curve_crdt_sharded(cfg, _PROTO, topo, run,
                                                 _mesh(), _CFAULT)
    assert (np.asarray(c1) == np.asarray(c4)).all()
    assert (np.asarray(f1.val) == np.asarray(f4.val)[:32]).all()
    assert float(f1.msgs) == float(f4.msgs)
    assert t1 == t4
    assert c4[-1] == 1.0


def test_crdt_mesh_parity_bitwise_orset_packed():
    """The packed-plane set payload (uint32 words, 32 elements per
    lane — the ops/bitpack layout) on the sharded exchange: bitwise
    1-vs-4-device parity under the full fault program."""
    from gossip_tpu.models.crdt import simulate_curve_crdt
    from gossip_tpu.parallel.sharded_crdt import (
        simulate_curve_crdt_sharded)
    run = RunConfig(seed=0, max_rounds=16, target_coverage=1.0)
    topo = G.complete(32)
    cfg = CrdtConfig(kind="orset", elements=48,
                     set_removes=((5, 3), (11, 8)))
    c1, m1, f1, t1 = simulate_curve_crdt(cfg, _PROTO, topo, run, _CFAULT)
    c4, m4, f4, t4 = simulate_curve_crdt_sharded(cfg, _PROTO, topo, run,
                                                 _mesh(), _CFAULT)
    assert (np.asarray(c1) == np.asarray(c4)).all()
    assert (np.asarray(f1.val) == np.asarray(f4.val)[:32]).all()
    assert t1 == t4
    assert c4[-1] == 1.0


@pytest.mark.slow
def test_crdt_mesh_parity_bitwise_pncounter():
    from gossip_tpu.models.crdt import simulate_curve_crdt
    from gossip_tpu.parallel.sharded_crdt import (
        simulate_curve_crdt_sharded)
    run = RunConfig(seed=0, max_rounds=16, target_coverage=1.0)
    topo = G.complete(32)
    cfg = CrdtConfig(kind="pncounter")
    c1, _, f1, t1 = simulate_curve_crdt(cfg, _PROTO, topo, run, _CFAULT)
    c4, _, f4, t4 = simulate_curve_crdt_sharded(cfg, _PROTO, topo, run,
                                                _mesh(), _CFAULT)
    assert (np.asarray(c1) == np.asarray(c4)).all()
    assert (np.asarray(f1.val) == np.asarray(f4.val)[:32]).all()
    assert t1 == t4


# ~5 s (flight data, the log-PR rebalance): the integer-target until
# cond stays pinned in-gate by the replicated-log twin
# (tests/test_logs.py::test_until_driver_integer_target — the same
# converged-count compare on the sibling payload) and the CLI crdt
# run (no --curve) smokes the single-device until driver; this
# CRDT-side single-vs-sharded depth runs under -m slow
@pytest.mark.slow
def test_until_driver_integer_target():
    """The while_loop driver's cond is an exact integer converged-count
    compare; single and sharded agree on rounds and the final value."""
    from gossip_tpu.models.crdt import simulate_until_crdt
    from gossip_tpu.parallel.sharded_crdt import (
        simulate_until_crdt_sharded)
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    topo = G.complete(32)
    cfg = CrdtConfig(kind="gcounter")
    r1, c1, m1, f1, t1 = simulate_until_crdt(cfg, _PROTO, topo, run,
                                             _CFAULT)
    r4, c4, m4, f4, t4 = simulate_until_crdt_sharded(
        cfg, _PROTO, topo, run, _mesh(), _CFAULT)
    assert (r1, c1, t1) == (r4, c4, t4)
    assert c1 == 1.0 and r1 < 24


def test_crdt_rejections_are_loud():
    from gossip_tpu.models.crdt import (make_crdt_round,
                                        simulate_until_crdt)
    with pytest.raises(ValueError, match="pull exchange only"):
        make_crdt_round(CrdtConfig(kind="gcounter"),
                        ProtocolConfig(mode=C.PUSH), G.complete(8))
    with pytest.raises(ValueError, match="no exchange driver"):
        make_crdt_round(CrdtConfig(kind="vclock"),
                        ProtocolConfig(mode=C.PULL), G.complete(8))
    # an injection the loop can never fire makes ground truth
    # unreachable by construction — drivers reject it loudly instead
    # of quietly reporting converged:false
    with pytest.raises(ValueError, match="can never fire"):
        simulate_until_crdt(
            CrdtConfig(kind="gcounter", adds=((0, 100, 5),)), _PROTO,
            G.complete(8), RunConfig(seed=0, max_rounds=8))


# -- the value_conv round-metrics column -------------------------------

# ~6 s (flight data, the log-PR rebalance): the payload-column
# recorder mechanism (RM.init flag -> record -> emit, zero-impact
# bitwise) is pinned in-gate by the log twin
# (tests/test_logs.py::test_log_conv_round_metrics_emitted_and_
# bitwise_free — the same recorder shape on the sibling column), and
# the value_conv column itself stays asserted in-gate on the
# committed record (test_committed_crdt_artifact_verdict); this live
# CRDT emission depth runs under -m slow
@pytest.mark.slow
def test_value_conv_round_metrics_emitted_and_bitwise_free(tmp_path):
    """With an active run ledger the sharded CRDT drivers flush a
    round_metrics stack carrying the value_conv column (+ the nemesis
    columns under churn); recording must not move the trajectory
    bitwise (the ops/round_metrics zero-impact contract)."""
    from gossip_tpu.parallel.sharded_crdt import (
        simulate_curve_crdt_sharded)
    from gossip_tpu.utils import telemetry
    run = RunConfig(seed=0, max_rounds=12, target_coverage=1.0)
    topo = G.complete(32)
    cfg = CrdtConfig(kind="gcounter")
    # metrics-off reference
    c0, _, f0, _ = simulate_curve_crdt_sharded(cfg, _PROTO, topo, run,
                                               _mesh(), _CFAULT)
    path = str(tmp_path / "crdt_metrics.jsonl")
    led = telemetry.Ledger(path)
    prev = telemetry.activate(led)
    try:
        c1, _, f1, _ = simulate_curve_crdt_sharded(
            cfg, _PROTO, topo, run, _mesh(), _CFAULT)
    finally:
        telemetry.activate(prev)
        led.close()
    assert (np.asarray(c0) == np.asarray(c1)).all()
    assert (np.asarray(f0.val) == np.asarray(f1.val)).all()
    evs = telemetry.load_ledger(path)
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert rms
    e = rms[-1]
    assert e["driver"] == "simulate_curve_crdt_sharded"
    assert len(e["value_conv"]) == e["rounds"] == 12
    assert e["totals"]["value_conv_final"] == pytest.approx(
        float(c1[-1]), abs=1e-4)
    # nemesis columns ride the same stack under the fault program
    assert e["totals"]["dropped"] > 0
    assert any(p > 0 for p in e["cut_pairs"])


# -- CLI ---------------------------------------------------------------

def test_cli_crdt_run_and_error_paths(capsys, monkeypatch):
    from gossip_tpu import cli

    # in-process cli.main: --no-compile-cache writes
    # GOSSIP_COMPILE_CACHE="" into THIS process's env — monkeypatch
    # re-pins the session cache dir for the tests that follow
    monkeypatch.setenv("GOSSIP_COMPILE_CACHE",
                       os.environ.get("GOSSIP_COMPILE_CACHE", ""))
    rc = cli.main(["crdt", "--type", "gcounter", "--n", "32",
                   "--max-rounds", "24", "--partition", "0:4:16",
                   "--churn-event", "3:2:5", "--drop-ramp",
                   "1:3:0.0:0.2", "--no-compile-cache"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["mode"] == "crdt" and out["type"] == "gcounter"
    assert out["converged"] is True and out["value_conv"] == 1.0
    assert out["truth_value"] > 0 and out["fault_program"] is True
    # scripted adds + curve
    rc = cli.main(["crdt", "--type", "pncounter", "--n", "16",
                   "--add", "0:0:9", "--add", "1:1:-4", "--curve",
                   "--max-rounds", "12", "--no-compile-cache"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["truth_value"] == 5
    assert out["curve"][-1] == 1.0
    # validation surfaces as a clean CLI error, never a traceback
    rc = cli.main(["crdt", "--type", "gcounter", "--add", "0:0:-1",
                   "--no-compile-cache"])
    assert rc == 2
    assert "positive" in capsys.readouterr().err


# -- Maelstrom counter workload (the Gossip Glomers invariant) ---------

def test_workload_startup_failure_stops_spawned_nodes():
    """A topology failure inside the shared _start_workload scaffolding
    must kill the already-spawned node processes, not strand them
    stdin-blocked (the callers' try/finally only guards after it
    returns)."""
    import asyncio

    from gossip_tpu.runtime import maelstrom_harness as MH

    async def main():
        seen = {}
        orig = MH.MaelstromHarness.set_topology

        async def boom(self, topo):
            seen["h"] = self
            raise RuntimeError("no topology_ok")

        MH.MaelstromHarness.set_topology = boom
        try:
            with pytest.raises(RuntimeError, match="no topology_ok"):
                await MH._start_workload(2, ops=4, rate=50.0,
                                         latency=0.001,
                                         topology="line",
                                         partition_mid=False, argv=None)
        finally:
            MH.MaelstromHarness.set_topology = orig
        h = seen["h"]
        assert h.procs
        for nid, proc in h.procs.items():
            assert proc.returncode is not None, (
                f"node {nid} leaked after startup failure")

    asyncio.run(main())


def test_counter_workload_invariant_through_partition():
    """run_counter_workload: every node's final read equals the sum of
    acked adds — EXACT integer equality — with a harness-injected
    partition cutting a mid-cluster link mid-run (the fault-tolerance
    variant of Gossip Glomers challenge #4)."""
    import asyncio

    from gossip_tpu.runtime.maelstrom_harness import run_counter_workload
    stats = asyncio.run(run_counter_workload(
        4, ops=8, rate=25.0, latency=0.001, partition_mid=True, seed=3))
    assert stats["invariant_ok"] is True
    assert stats["partitioned"] is True
    assert stats["final_values"] == [stats["expected"]] * 4
    # per-workload stats surface (the shared accounting): adds are
    # client ops, msgs_per_op counts them
    assert stats["ops"] == 8 and stats["broadcast_ops"] == 0
    assert stats["msgs_per_op"] > 0
    assert stats["op_latency_ms"]["p99"] >= stats["op_latency_ms"]["p50"]


# -- committed artifact + provenance gate ------------------------------

def test_committed_crdt_artifact_verdict():
    """The committed CRDT convergence record
    (artifacts/ledger_crdt_r13.jsonl, tools/crdt_capture.py):
    provenance-carrying; G-Counter, PN-Counter AND OR-Set each reached
    value_conv == 1.0 under the mixed fault program with bitwise
    1-vs-4-device parity; the drivers' round_metrics events carry the
    value_conv column — re-asserted here so the verdict can never
    rot."""
    from gossip_tpu.utils import telemetry
    path = os.path.join(_REPO, "artifacts", "ledger_crdt_r13.jsonl")
    evs = telemetry.load_ledger(path, run="last")
    assert evs[0]["ev"] == "provenance"
    assert len(evs[0]["git_commit"]) == 40
    fp = [e for e in evs if e.get("ev") == "crdt_fault_program"][-1]
    assert fp["partitions"] and fp["ramp"] and len(fp["events"]) == 2
    scen = {e["crdt"]: e for e in evs
            if e.get("ev") == "crdt_scenario"}
    assert set(scen) == {"gcounter", "pncounter", "orset"}
    for name, e in scen.items():
        assert e["value_conv_final"] == 1.0, name
        assert e["mesh_parity_bitwise"] is True, name
        assert e["ok"] is True, name
        # convergence STALLED while the committed window was open
        assert all(c < 1.0
                   for c in e["value_conv_curve"][:6]), name
    assert [e for e in evs if e.get("ev") == "crdt_verdict"][-1]["ok"] \
        is True
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert rms and all("value_conv" in e for e in rms)
    assert all(e["totals"]["value_conv_final"] == 1.0 for e in rms)


def test_validate_artifacts_requires_provenance_on_crdt(tmp_path):
    """``*crdt*`` artifacts can never be grandfathered in without
    provenance (the nemesis/crashloop rule, extended)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "validate_artifacts",
        os.path.join(_REPO, "tools", "validate_artifacts.py"))
    va = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(va)
    bad = tmp_path / "crdt_convergence_rXX.jsonl"
    bad.write_text(json.dumps({"ev": "crdt_scenario"}) + "\n")
    problems = va.validate_file(str(bad))
    assert problems and any("attributable" in p for p in problems)
    badj = tmp_path / "ledger_crdt_sweep.json"
    badj.write_text(json.dumps({"value_conv": 1.0}))
    assert va.validate_file(str(badj))


# -- no-CRDT regression guard ------------------------------------------

def _assert_fingerprints(names):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import _churn_surfaces as CS
    finally:
        sys.path.pop(0)
    with open(CS.DATA) as f:
        golden = json.load(f)["digests"]
    for name in names:
        runner, fault_of = CS.SURFACES[name]
        assert runner(fault_of()) == golden[f"churn:{name}"], (
            f"churn:{name} moved under the CRDT PR")
        assert runner(CS._static_fault()) == golden[f"static:{name}"], (
            f"static:{name} moved under the CRDT PR")


# depth tier since the fleet-PR rebalance (tier-1 wall budget, ~8 s):
# the packed-sharded trajectory stays pinned in-gate by the per-mode
# packed sharded-vs-unpacked parity params (test_packed) and the
# nemesis dense digest (test_nemesis's in-gate subset), and the CRDT
# payload parities (gcounter dense + orset packed mesh parity, both
# in-gate) would surface any fabric move through the payload
# trajectories; this guard's golden-digest re-proof runs with the
# full matrix under -m slow
@pytest.mark.slow
def test_no_crdt_fabric_fingerprints_unchanged():
    """The CRDT subsystem rides the fabric without moving it: the
    packed-sharded broadcast trajectory — churn AND static — is
    BITWISE the golden digest captured before this PR
    (tests/data/churn_fingerprints_r06.json).  Packed sharded is the
    pick because the CRDT payload shares ITS exchange shape
    (all_gather of word rows); dense_sharded is already re-verified
    in-gate by test_nemesis, and the rumor/SWIM surfaces run in the
    slow twin below + test_nemesis's full matrix."""
    _assert_fingerprints(["packed_sharded"])


@pytest.mark.slow
def test_no_crdt_fabric_fingerprints_unchanged_depth():
    _assert_fingerprints(["rumor_single", "packed_single"])
