"""Tests for the batched config sweep (parallel/sweep.config_sweep_curves)."""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.parallel.sharded import make_mesh
from gossip_tpu.parallel.sweep import (SweepPoint, config_sweep_curves,
                                       config_sweep_curves_2d)
from gossip_tpu.runtime.simulator import simulate_curve
from gossip_tpu.topology import generators as G


def _grid_points():
    """8 distinct configs: modes x fanouts x drop, plus seeds."""
    return [
        SweepPoint(mode=C.PUSH, fanout=1, seed=0),
        SweepPoint(mode=C.PUSH, fanout=2, seed=1),
        SweepPoint(mode=C.PULL, fanout=1, seed=2),
        SweepPoint(mode=C.PULL, fanout=2, drop_prob=0.3, seed=3),
        SweepPoint(mode=C.PUSH_PULL, fanout=1, seed=4),
        SweepPoint(mode=C.PUSH_PULL, fanout=2, drop_prob=0.5, seed=5),
        SweepPoint(mode=C.ANTI_ENTROPY, fanout=1, period=3, seed=6),
        SweepPoint(mode=C.ANTI_ENTROPY, fanout=2, period=2, seed=7),
    ]


# depth tier (tier-1 wall budget, PR 7 rebalance): sweep-surface smoke
# coverage stays via test_eight_configs_one_program_all_converge and
# test_2d_pod_sweep_matches_1d_batch[complete]; the seed-axis value-
# invariance twin runs under -m slow
@pytest.mark.slow
def test_sweep_axis_sharding_is_value_invariant():
    # the north-star DP axis: configs sharded over a 1-D device mesh give
    # the exact trajectories of the unsharded batch
    topo = G.complete(512)
    run = RunConfig(seed=0, max_rounds=24)
    pts = _grid_points()
    solo = config_sweep_curves(pts, topo, run)
    mesh = make_mesh(8, axis_name="sweep")
    sh = config_sweep_curves(pts, topo, run, mesh=mesh)
    np.testing.assert_array_equal(sh.curves, solo.curves)
    np.testing.assert_array_equal(sh.msgs, solo.msgs)
    with pytest.raises(ValueError, match="divide"):
        config_sweep_curves(pts[:3], topo, run, mesh=mesh)


# both params slow since the txn-PR rebalance (~11 s each): the 2-D
# configs-x-nodes shard_map program runs in-gate twice per session as
# the hybrid_2d_sweep dry-run family (cold + warm, budget-gated); the
# 1-D-batch bitwise equivalence depth re-proves under -m slow
@pytest.mark.parametrize("family", [
    pytest.param("complete", marks=pytest.mark.slow),
    pytest.param("er", marks=pytest.mark.slow)])
def test_2d_pod_sweep_matches_1d_batch(family):
    # full 2-D mesh: configs x node shards in ONE shard_map program —
    # trajectories identical to the single-device batch
    topo = (G.complete(512) if family == "complete"
            else G.erdos_renyi(512, 0.05, seed=2))
    run = RunConfig(seed=0, max_rounds=24, target_coverage=0.99)
    pts = _grid_points()
    solo = config_sweep_curves(pts, topo, run, rumors=2)
    mesh2d = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                  ("sweep", "nodes"))
    pod = config_sweep_curves_2d(pts, topo, run, mesh2d, rumors=2)
    np.testing.assert_allclose(pod.curves, solo.curves, atol=1e-6)
    np.testing.assert_array_equal(pod.msgs, solo.msgs)
    np.testing.assert_array_equal(pod.rounds_to_target,
                                  solo.rounds_to_target)


# ~5.4 s (flight data, the fused-operand-PR rebalance): the 8-config
# convergence OUTCOMES are depth — the one-program property and the
# per-point trajectory semantics stay in-gate via
# test_bitwise_parity_with_solo_round, test_pure_grid_elides_other_half
# and the compile-cache sweep pins; the full 8-config convergence grid
# re-proves under -m slow
@pytest.mark.slow
def test_eight_configs_one_program_all_converge():
    topo = G.complete(2048)
    run = RunConfig(seed=0, max_rounds=64, target_coverage=0.99)
    res = config_sweep_curves(_grid_points(), topo, run)
    assert res.curves.shape == (8, 64)
    summaries = res.summaries()
    assert len(summaries) == 8
    for s in summaries:
        assert s["converged"], s
    # distinct configs, distinct outcomes: pushpull(f2) beats push(f1)
    rt = res.rounds_to_target
    assert rt[5] < rt[0]        # pushpull f2 (even lossy) < push f1
    assert rt[6] > rt[2]        # periodic anti-entropy slower than pull


# ~11 s (flight data, the log-PR rebalance): composition invariance
# keeps TWO in-gate anchors — the per-point solo-parity params below
# (batch row == make_si_round bitwise, the stronger per-trajectory
# claim) and the serving PR's live RPC coalesce test (replies vs K=1
# driver dispatches on the request megabatch, the generalization of
# this sweep); the batch-of-8-vs-batch-of-1 slice depth runs under
# -m slow
@pytest.mark.slow
def test_batch_composition_invariance():
    """A point's trajectory must not depend on what else is in the batch
    (same k_max): batch-of-8 slice == batch-of-1."""
    topo = G.complete(512)
    run = RunConfig(seed=0, max_rounds=24)
    pts = _grid_points()
    full = config_sweep_curves(pts, topo, run, k_max=2)
    for i in (0, 3, 6):
        solo = config_sweep_curves([pts[i]], topo, run, k_max=2)
        np.testing.assert_array_equal(full.curves[i], solo.curves[0])
        np.testing.assert_array_equal(full.msgs[i], solo.msgs[0])


@pytest.mark.parametrize("mode,fanout,drop", [
    # fault-free params slow since the txn-PR rebalance (~4 s each):
    # the drop-bearing pull param keeps the sweep-vs-solo bitwise
    # surface in-gate; the fault-free modes re-prove under -m slow
    pytest.param(C.PUSH, 2, 0.0, marks=pytest.mark.slow),
    (C.PULL, 2, 0.25),
    pytest.param(C.PUSH_PULL, 2, 0.0, marks=pytest.mark.slow),
])
def test_bitwise_parity_with_solo_round(mode, fanout, drop):
    """A point whose fanout == k_max reproduces make_si_round's trajectory
    bitwise (same RNG keys, same draw shapes)."""
    n = 512
    topo = G.complete(n)
    run = RunConfig(seed=9, max_rounds=20, target_coverage=0.999)
    pt = SweepPoint(mode=mode, fanout=fanout, drop_prob=drop, seed=9)
    res = config_sweep_curves([pt], topo, run, k_max=fanout)
    proto = ProtocolConfig(mode=mode, fanout=fanout)
    fault = FaultConfig(drop_prob=drop, seed=9) if drop else None
    solo = simulate_curve(proto, topo, run, fault)
    np.testing.assert_array_equal(res.curves[0],
                                  np.asarray(solo.coverage, np.float32))
    np.testing.assert_allclose(res.msgs[0][-1], solo.msgs[-1], rtol=0)


# depth tier since the fleet-PR rebalance (tier-1 wall budget, ~8 s):
# the explicit-table sweep lowering stays pinned in-gate by the CLI
# grid one-program run (test_backend_cli_rpc) and the 2-D pod-sweep
# dry-run family's ring table every session; the 4-seed erdos-renyi
# convergence here is depth, re-proved under -m slow
@pytest.mark.slow
def test_explicit_table_topology():
    topo = G.erdos_renyi(1024, p=0.02, seed=1)
    run = RunConfig(seed=0, max_rounds=64)
    pts = [SweepPoint(mode=C.PUSH_PULL, fanout=2, seed=s) for s in range(4)]
    res = config_sweep_curves(pts, topo, run)
    assert all(s["converged"] for s in res.summaries())


# depth tier since the fleet-PR rebalance (tier-1 wall budget, ~8 s):
# the shared-death-mask mechanism is pinned in-gate by the stronger
# checks — the drop-bearing solo-parity param above (bitwise) and the
# cross-mesh fault-mask determinism pin (test_sharding's fault
# params); the monotone rounds-to-target claim here is depth,
# re-proved under -m slow
@pytest.mark.slow
def test_death_mask_shared_drop_per_config():
    topo = G.complete(512)
    run = RunConfig(seed=0, max_rounds=64)
    fault = FaultConfig(node_death_rate=0.2, seed=4)
    pts = [SweepPoint(mode=C.PUSH_PULL, fanout=1, drop_prob=d, seed=1)
           for d in (0.0, 0.6)]
    res = config_sweep_curves(pts, topo, run, fault=fault)
    rt = res.rounds_to_target
    assert rt[0] > 0 and rt[1] > 0 and rt[0] < rt[1]
    with pytest.raises(ValueError, match="drop_prob"):
        config_sweep_curves(pts, topo, run,
                            fault=FaultConfig(drop_prob=0.1))


def test_point_validation():
    with pytest.raises(ValueError, match="flood"):
        SweepPoint(mode=C.FLOOD)
    with pytest.raises(ValueError, match="anti-entropy"):
        SweepPoint(mode=C.PUSH, period=2)
    with pytest.raises(ValueError, match="k_max"):
        config_sweep_curves([SweepPoint(fanout=4)], G.complete(64),
                            RunConfig(max_rounds=4), k_max=2)


# ---------------------------------------------------------------------
# Topology axis (VERDICT r2 item 6): families x modes x fanouts in ONE
# XLA program.


def _families(n=512):
    return [G.erdos_renyi(n, 14.0 / n, seed=3),
            G.watts_strogatz(n, 6, 0.2, seed=3),
            G.power_law(n, 3, seed=3)]


@pytest.mark.slow
def test_topology_axis_matches_solo_bitwise():
    """Every (family, mode, fanout) cell of the batched families grid
    must equal the solo single-topology batch BITWISE."""
    fams = _families()
    run = RunConfig(seed=0, max_rounds=24)
    pts = [SweepPoint(mode=m, fanout=f, seed=2, topo_idx=t)
           for t in range(len(fams))
           for m in (C.PUSH, C.PULL, C.PUSH_PULL)
           for f in (1, 2)]
    full = config_sweep_curves(pts, fams, run, k_max=2)
    assert full.curves.shape[0] == 18
    for i, pt in enumerate(pts):
        solo = config_sweep_curves(
            [SweepPoint(mode=pt.mode, fanout=pt.fanout, seed=pt.seed)],
            fams[pt.topo_idx], run, k_max=2)
        np.testing.assert_array_equal(full.curves[i], solo.curves[0])
        np.testing.assert_array_equal(full.msgs[i], solo.msgs[0])


# depth tier (tier-1 wall budget, serving-PR rebalance): sweep-axis
# mesh sharding is ONE mechanism (_shard_ensemble placement, value-
# invariant by contract) whose complete-graph twin already runs under
# -m slow (test_sweep_axis_sharding_is_value_invariant); the in-gate
# surface keeps test_2d_pod_sweep_matches_1d_batch (a real sweep-axis
# mesh) and the hybrid_2d_sweep dry-run family
@pytest.mark.slow
def test_topology_axis_shards_over_sweep_mesh():
    fams = _families()[:2]
    run = RunConfig(seed=0, max_rounds=16)
    pts = [SweepPoint(mode=m, fanout=1, seed=1, topo_idx=t)
           for t in range(2) for m in (C.PUSH, C.PULL, C.PUSH_PULL,
                                       C.PUSH)]
    solo = config_sweep_curves(pts, fams, run)
    mesh = make_mesh(8, axis_name="sweep")
    sh = config_sweep_curves(pts, fams, run, mesh=mesh)
    np.testing.assert_array_equal(sh.curves, solo.curves)
    np.testing.assert_array_equal(sh.msgs, solo.msgs)


def test_topology_axis_validation():
    fams = _families(256)
    run = RunConfig(max_rounds=4)
    with pytest.raises(ValueError, match="topo_idx"):
        SweepPoint(topo_idx=-1)
    with pytest.raises(ValueError, match="past"):
        config_sweep_curves([SweepPoint(topo_idx=3)], fams, run)
    # mixed-n is 1-D-batchable since round 4; the 2-D pod sweep still
    # shards one node dimension and refuses it loudly
    with pytest.raises(ValueError, match="mixed-n"):
        from jax.sharding import Mesh as _Mesh
        import jax as _j
        m2 = _Mesh(np.asarray(_j.devices()[:4]).reshape(2, 2),
                   ("sweep", "nodes"))
        config_sweep_curves_2d(
            [SweepPoint(), SweepPoint(topo_idx=1)],
            [fams[0], G.erdos_renyi(128, 0.1, seed=0)], run, m2)
    with pytest.raises(ValueError, match="implicit|explicit"):
        config_sweep_curves([SweepPoint()], [fams[0], G.complete(256)],
                            run)
    with pytest.raises(ValueError, match="past"):
        from jax.sharding import Mesh
        import jax as _jax
        mesh2d = Mesh(np.asarray(_jax.devices()[:8]).reshape(2, 4),
                      ("sweep", "nodes"))
        config_sweep_curves_2d([SweepPoint(topo_idx=1), SweepPoint()],
                               fams[0], run, mesh2d)


@pytest.mark.slow
def test_2d_pod_sweep_with_topology_axis_matches_1d():
    """Families × modes on the full 2-D (configs × node-shards) mesh:
    trajectories identical to the 1-D families batch."""
    from jax.sharding import Mesh
    import jax as _jax
    fams = _families(256)[:2]
    run = RunConfig(seed=0, max_rounds=16)
    pts = [SweepPoint(mode=m, fanout=1, seed=1, topo_idx=t)
           for t in range(2) for m in (C.PUSH, C.PULL)]
    solo = config_sweep_curves(pts, fams, run)
    mesh2d = Mesh(np.asarray(_jax.devices()[:8]).reshape(2, 4),
                  ("sweep", "nodes"))
    pod = config_sweep_curves_2d(pts, fams, run, mesh2d)
    np.testing.assert_allclose(pod.curves, solo.curves, atol=1e-6)
    np.testing.assert_array_equal(pod.msgs, solo.msgs)


# ---------------------------------------------------------------------
# Mode-partitioned execution (VERDICT r2 item 7).


# slow tier (tier-1 wall budget): partitioned-vs-batch stays gated
# via test_batch_composition_invariance
@pytest.mark.slow
def test_partitioned_matches_single_batch_bitwise():
    """Bucketed execution returns the exact trajectories of the one-batch
    run, in the caller's point order (shared k_max, disjoint RNG tags)."""
    from gossip_tpu.parallel.sweep import config_sweep_curves_partitioned
    topo = G.complete(512)
    run = RunConfig(seed=0, max_rounds=24)
    pts = _grid_points()          # push / pull / pushpull / AE mix
    full = config_sweep_curves(pts, topo, run, k_max=2)
    part = config_sweep_curves_partitioned(pts, topo, run, k_max=2)
    np.testing.assert_array_equal(part.curves, full.curves)
    np.testing.assert_array_equal(part.msgs, full.msgs)
    np.testing.assert_array_equal(part.rounds_to_target,
                                  full.rounds_to_target)


def test_pure_grid_elides_other_half():
    """A pure-push (resp. pure-pull) batch must never BUILD the other
    half — asserted on the traced program, not the wall clock (on CPU at
    CI scale compile time swamps the per-round win, and this repo's
    policy is no wall-clock asserts — test_utils.py; the per-round
    savings follow from the op counts, and on the 2-D pod sweep the
    elided pull half is a whole all_gather of ICI traffic per round).

    On the implicit complete graph the op signatures are unambiguous:
    the push half is the ONLY source of scatter ops (push_counts'
    .at[].add) and the pull half the ONLY source of gather ops
    (pull_merge's digest row gather)."""
    import jax
    from gossip_tpu.parallel.sweep import _sweep_round_delta
    import jax.numpy as jnp

    n, k_max = 256, 2
    topo = G.complete(n)

    def body(need_push, need_pull):
        def f(seen, key):
            gids = jnp.arange(n, dtype=jnp.int32)
            alive = jnp.ones((n,), jnp.bool_)
            delta, msgs = _sweep_round_delta(
                key, jnp.int32(0), gids, seen, alive, topo, k_max,
                None, None, jnp.bool_(True), jnp.bool_(True),
                jnp.bool_(False), jnp.int32(1), jnp.float32(0.0),
                jnp.int32(1), have_ae=False, scatter_n=n,
                count_reduce=lambda c: c, gather=lambda v: v,
                need_push=need_push, need_pull=need_pull)
            return delta, msgs
        return str(jax.make_jaxpr(f)(jnp.zeros((n, 1), jnp.bool_),
                                     jax.random.key(0)))

    both = body(True, True)
    assert "scatter" in both and "gather" in both
    pure_pull = body(False, True)
    assert "scatter" not in pure_pull          # push half never built
    assert "gather" in pure_pull
    pure_push = body(True, False)
    assert "gather" not in pure_push           # pull half never built
    assert "scatter" in pure_push

    # and the elision is what a pure grid actually gets: trajectories
    # unchanged vs forcing both halves (disjoint RNG tags)
    run = RunConfig(seed=0, max_rounds=16)
    pts = [SweepPoint(mode=C.PUSH, fanout=f, seed=s)
           for f in (1, 2) for s in range(2)]
    lean = config_sweep_curves(pts, topo, run, k_max=2)
    fat = config_sweep_curves(pts, topo, run, k_max=2, _force_both=True)
    np.testing.assert_array_equal(lean.curves, fat.curves)
    np.testing.assert_array_equal(lean.msgs, fat.msgs)


# ---------------------------------------------------------------------
# The n axis (VERDICT r3 item 6): families x SIZES in one program.


def _sizes_stack():
    """Same family at three sizes + a different family at a fourth —
    the ragged stack pads everything to n_max=640 with phantom rows."""
    return [G.erdos_renyi(200, 14.0 / 200, seed=3),
            G.erdos_renyi(384, 14.0 / 384, seed=3),
            G.erdos_renyi(640, 14.0 / 640, seed=3),
            G.ring(333, 4)]


@pytest.mark.slow
def test_n_axis_matches_solo_bitwise():
    """Every (size, mode, fanout) cell of a mixed-n batch equals the solo
    single-topology batch at that n BITWISE — phantom rows are inert."""
    topos = _sizes_stack()
    run = RunConfig(seed=0, max_rounds=20)
    pts = [SweepPoint(mode=m, fanout=f, seed=2, topo_idx=t)
           for t in range(len(topos))
           for m in (C.PUSH, C.PULL, C.PUSH_PULL)
           for f in (1, 2)]
    full = config_sweep_curves(pts, topos, run, k_max=2)
    assert full.curves.shape[0] == 24
    for i, pt in enumerate(pts):
        solo = config_sweep_curves(
            [SweepPoint(mode=pt.mode, fanout=pt.fanout, seed=pt.seed)],
            topos[pt.topo_idx], run, k_max=2)
        np.testing.assert_array_equal(full.curves[i], solo.curves[0])
        np.testing.assert_array_equal(full.msgs[i], solo.msgs[0])


@pytest.mark.slow
def test_n_axis_antientropy_and_drop_match_solo():
    # slow tier (tier-1 wall rebalance, traced-operand PR): depth
    # variant of the phantom-n contract — the in-gate surface keeps
    # test_eight_configs_one_program_all_converge, the 2-D pod-sweep
    # parity, and the sharding-invariance pins
    # the AE reverse delta and per-point loss survive phantom padding
    topos = [G.ring(256, 4), G.ring(512, 4)]
    run = RunConfig(seed=0, max_rounds=24)
    pts = [SweepPoint(mode=C.ANTI_ENTROPY, fanout=1, period=2, seed=5,
                      topo_idx=t, drop_prob=d)
           for t in (0, 1) for d in (0.0, 0.3)]
    full = config_sweep_curves(pts, topos, run, k_max=1)
    for i, pt in enumerate(pts):
        solo = config_sweep_curves(
            [SweepPoint(mode=pt.mode, fanout=1, period=2, seed=5,
                        drop_prob=pt.drop_prob)],
            topos[pt.topo_idx], run, k_max=1)
        np.testing.assert_array_equal(full.curves[i], solo.curves[0])
        np.testing.assert_array_equal(full.msgs[i], solo.msgs[0])


# depth tier (tier-1 wall budget, PR 7 rebalance): same rationale as
# the seed-axis invariance twin above
@pytest.mark.slow
def test_n_axis_shards_over_sweep_mesh():
    topos = _sizes_stack()[:2]
    run = RunConfig(seed=0, max_rounds=16)
    pts = [SweepPoint(mode=m, fanout=1, seed=1, topo_idx=t)
           for t in (0, 1) for m in (C.PUSH, C.PULL, C.PUSH_PULL, C.PUSH)]
    solo = config_sweep_curves(pts, topos, run)
    sh = config_sweep_curves(pts, topos, run,
                             mesh=make_mesh(8, axis_name="sweep"))
    np.testing.assert_array_equal(sh.curves, solo.curves)
    np.testing.assert_array_equal(sh.msgs, solo.msgs)


def test_n_axis_validation():
    topos = [G.ring(256, 4), G.ring(512, 4)]
    run = RunConfig(max_rounds=4)
    with pytest.raises(ValueError, match="FaultConfig"):
        config_sweep_curves([SweepPoint(), SweepPoint(topo_idx=1)],
                            topos, run,
                            fault=FaultConfig(node_death_rate=0.1))
    with pytest.raises(ValueError, match="smallest n"):
        config_sweep_curves(
            [SweepPoint(), SweepPoint(topo_idx=1)], topos,
            RunConfig(max_rounds=4, origin=255), rumors=2)


# slow tier (tier-1 wall budget): the rumor axis stays gated via
# test_mixed_rumor_batch_composes_with_mixed_n
@pytest.mark.slow
def test_mixed_rumor_batch_matches_solo_bitwise():
    """The rumor axis (round 4): points with DIFFERENT rumor counts batch
    into one program by padding R to the max with all-false phantom
    columns.  Each point's curve AND msgs must equal the solo batch of
    just that point at its own rumor count — bitwise, since phantom
    columns never scatter, never gather, and never flip sender_active."""
    n = 384
    topo = G.complete(n)
    run = RunConfig(seed=5, max_rounds=16, target_coverage=0.999)
    pts = [SweepPoint(mode=C.PUSH, fanout=1, seed=3, rumors=1),
           SweepPoint(mode=C.PULL, fanout=2, seed=4, rumors=3),
           SweepPoint(mode=C.PUSH_PULL, fanout=1, seed=5, rumors=2),
           SweepPoint(mode=C.ANTI_ENTROPY, fanout=1, period=2, seed=6,
                      rumors=4)]
    batch = config_sweep_curves(pts, topo, run, k_max=2)
    for i, pt in enumerate(pts):
        solo = config_sweep_curves([pt], topo, run, k_max=2,
                                   rumors=pt.rumors)
        np.testing.assert_array_equal(batch.curves[i], solo.curves[0],
                                      err_msg=f"point {i}")
        np.testing.assert_array_equal(batch.msgs[i], solo.msgs[0],
                                      err_msg=f"point {i} msgs")
    # summaries carry the per-point rumor count
    assert [s["point"]["rumors"] for s in batch.summaries()] == [1, 3, 2, 4]
    # sharding the config axis never changes values (the rum_pts operand
    # rides the same row sharding as the other per-point scalars)
    meshed = config_sweep_curves(pts, topo, run, k_max=2,
                                 mesh=make_mesh(4, axis_name="sweep"))
    np.testing.assert_array_equal(meshed.curves, batch.curves)
    np.testing.assert_array_equal(meshed.msgs, batch.msgs)


@pytest.mark.slow
def test_mixed_rumor_batch_composes_with_mixed_n():
    """Both phantom axes at once: a (sizes x rumor-counts) grid in one
    program, each cell bitwise equal to its solo run.  Slow tier
    (tier-1 wall rebalance, traced-operand PR): the single-axis pins
    for both phantom mechanisms stay in-gate."""
    topos = [G.ring(96, k=4), G.ring(160, k=4)]
    run = RunConfig(seed=2, max_rounds=24, target_coverage=0.999)
    pts = [SweepPoint(mode=C.PUSH, fanout=1, seed=1, topo_idx=t, rumors=r)
           for t in (0, 1) for r in (1, 3)]
    batch = config_sweep_curves(pts, topos, run, k_max=1)
    for i, pt in enumerate(pts):
        solo = config_sweep_curves([pt], topos, run, k_max=1,
                                   rumors=pt.rumors)
        np.testing.assert_array_equal(batch.curves[i], solo.curves[0],
                                      err_msg=f"cell {i}")
        np.testing.assert_array_equal(batch.msgs[i], solo.msgs[0],
                                      err_msg=f"cell {i} msgs")


def test_2d_pod_sweep_rejects_mixed_rumors():
    from gossip_tpu.parallel.multislice import make_hybrid_mesh
    mesh2d = make_hybrid_mesh(2, 4, axis_names=("sweep", "nodes"))
    pts = [SweepPoint(mode=C.PUSH, seed=s, rumors=r)
           for s, r in ((0, 1), (1, 2))]
    with pytest.raises(ValueError, match="ONE rumor axis"):
        config_sweep_curves_2d(pts, G.complete(128),
                               RunConfig(max_rounds=4), mesh2d)


@pytest.mark.slow
def test_mixed_n_complete_batch_matches_solo_bitwise():
    """The last structural axis (round 4): mixed-n IMPLICIT batches.
    Complete graphs have no table to stack; each point's uniform draw is
    bounded by its own n as a traced operand, and randint's draw depends
    only on the bound's VALUE — so every cell of a sizes batch equals
    its solo run bitwise, msgs included."""
    topos = [G.complete(96), G.complete(160), G.complete(257)]
    run = RunConfig(seed=7, max_rounds=14, target_coverage=0.999)
    pts = [SweepPoint(mode=m, fanout=1, seed=4 + t, topo_idx=t)
           for t in range(3) for m in (C.PUSH, C.PULL)]
    batch = config_sweep_curves(pts, topos, run, k_max=1)
    for i, pt in enumerate(pts):
        solo = config_sweep_curves([pt], topos, run, k_max=1)
        np.testing.assert_array_equal(batch.curves[i], solo.curves[0],
                                      err_msg=f"cell {i}")
        np.testing.assert_array_equal(batch.msgs[i], solo.msgs[0],
                                      err_msg=f"cell {i} msgs")
    # ... and equals the plain single-topology batch at that n — the
    # TRUE static-bound program — for a PUSH and a PULL cell, curves
    # AND msgs (the traced-bound lowering must match the constant-bound
    # lowering on both halves and on the accounting)
    for i, pt in ((0, pts[0]), (3, pts[3])):
        assert (pt.mode, pt.topo_idx) in ((C.PUSH, 0), (C.PULL, 1))
        one = config_sweep_curves(
            [dataclasses.replace(pt, topo_idx=0)],
            topos[pt.topo_idx], run, k_max=1)
        np.testing.assert_array_equal(batch.curves[i], one.curves[0],
                                      err_msg=f"static cell {i}")
        np.testing.assert_array_equal(batch.msgs[i], one.msgs[0],
                                      err_msg=f"static cell {i} msgs")


@pytest.mark.slow
def test_mixed_n_complete_composes_with_mixed_rumors():
    topos = [G.complete(96), G.complete(200)]
    run = RunConfig(seed=3, max_rounds=12, target_coverage=0.999)
    pts = [SweepPoint(mode=C.PUSH_PULL, fanout=1, seed=9, topo_idx=t,
                      rumors=r)
           for t in (0, 1) for r in (1, 3)]
    batch = config_sweep_curves(pts, topos, run, k_max=1)
    for i, pt in enumerate(pts):
        solo = config_sweep_curves([pt], topos, run, k_max=1,
                                   rumors=pt.rumors)
        np.testing.assert_array_equal(batch.curves[i], solo.curves[0],
                                      err_msg=f"cell {i}")


def test_implicit_explicit_topology_mix_rejected():
    with pytest.raises(ValueError, match="mixes implicit"):
        config_sweep_curves(
            [SweepPoint(seed=0), SweepPoint(seed=1, topo_idx=1)],
            [G.complete(64), G.ring(64, k=2)], RunConfig(max_rounds=4))
