"""Halo-exchange (ppermute) rounds == single-device kernels, bitwise.

The O(band) communication pattern must never change results — only
traffic.  Cases cover flood, pull, push, and push-pull on every
band-limited family, with drops and deaths, plus the constraint errors."""

import jax
import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.si import make_si_round
from gossip_tpu.models.state import init_state
from gossip_tpu.parallel.halo import band_of, make_halo_round
from gossip_tpu.parallel.sharded import (init_sharded_state, make_mesh)
from gossip_tpu.topology import generators as G


def test_band_of():
    assert band_of(G.ring(64, 4)) == 2
    assert band_of(G.ring(64, 6)) == 3
    assert band_of(G.grid2d(8, 8)) == 8
    ws = G.watts_strogatz(64, 4, beta=0.0, seed=0)   # unrewired lattice
    assert band_of(ws) == 2
    with pytest.raises(ValueError, match="undefined"):
        band_of(G.complete(16))


CASES = [
    ("flood-ring", ProtocolConfig(mode=C.FLOOD), lambda: G.ring(128, 4),
     None),
    ("flood-grid", ProtocolConfig(mode=C.FLOOD), lambda: G.grid2d(8, 16),
     None),
    ("flood-drop-death", ProtocolConfig(mode=C.FLOOD),
     lambda: G.ring(128, 6),
     FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=3)),
    ("pull-ws-lattice", ProtocolConfig(mode=C.PULL, fanout=2, rumors=3),
     lambda: G.watts_strogatz(128, 6, beta=0.0, seed=1), None),
    ("pull-drop", ProtocolConfig(mode=C.PULL, fanout=1),
     lambda: G.ring(128, 4), FaultConfig(drop_prob=0.3, seed=5)),
    ("push-ring", ProtocolConfig(mode=C.PUSH, fanout=2),
     lambda: G.ring(128, 6), None),
    ("push-drop-death", ProtocolConfig(mode=C.PUSH, fanout=1),
     lambda: G.grid2d(8, 16),
     FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=4)),
    ("pushpull-ws", ProtocolConfig(mode=C.PUSH_PULL, fanout=1, rumors=2),
     lambda: G.watts_strogatz(128, 6, beta=0.0, seed=2), None),
]


# the slowest cases ride the slow tier (tier-1 wall budget); the
# remaining five keep every mode smoked in the gate.  pull-drop joined
# the slow set in the log-PR rebalance (~6 s flight data): the pull
# surface stays in-gate via pull-ws-lattice and the drop-coin masking
# via flood-drop-death
_SLOW = {"pushpull-ws", "push-drop-death", "pull-drop"}


@pytest.mark.parametrize("name,proto,topo_fn,fault",
                         [pytest.param(*c, marks=pytest.mark.slow)
                          if c[0] in _SLOW else c for c in CASES],
                         ids=[c[0] for c in CASES])
def test_halo_bitwise_equals_single_device(name, proto, topo_fn, fault):
    topo = topo_fn()
    run = RunConfig(seed=7)
    mesh = make_mesh(8)
    sstep = jax.jit(make_si_round(proto, topo, fault, run.origin))
    sst = init_state(run, proto, topo.n)
    hstep = jax.jit(make_halo_round(proto, topo, mesh, fault, run.origin))
    hst = init_sharded_state(run, proto, topo, mesh)   # n % 8 == 0: no pad
    for _ in range(10):
        sst = sstep(sst)
        hst = hstep(hst)
    np.testing.assert_array_equal(np.asarray(hst.seen), np.asarray(sst.seen))
    assert float(hst.msgs) == pytest.approx(float(sst.msgs))


def test_halo_wraparound_correct():
    # rumor starting at node 0 must cross the 0/n seam through the mesh
    # ring in both directions
    topo = G.ring(64, 2)
    proto = ProtocolConfig(mode=C.FLOOD)
    mesh = make_mesh(8)
    step = jax.jit(make_halo_round(proto, topo, mesh))
    st = init_sharded_state(RunConfig(origin=0), proto, topo, mesh)
    for _ in range(3):
        st = step(st)
    seen = np.asarray(st.seen)[:, 0]
    expect = np.zeros(64, bool)
    for d in range(-3, 4):
        expect[d % 64] = True
    np.testing.assert_array_equal(seen, expect)


def test_halo_constraint_errors():
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="needs an explicit"):
        make_halo_round(ProtocolConfig(mode=C.FLOOD), G.complete(64), mesh)
    with pytest.raises(ValueError, match="flood/pull"):
        make_halo_round(ProtocolConfig(mode=C.ANTI_ENTROPY),
                        G.ring(64, 2), mesh)
    with pytest.raises(ValueError, match="mesh size"):
        make_halo_round(ProtocolConfig(mode=C.FLOOD), G.ring(100, 2), mesh)
    with pytest.raises(ValueError, match="band"):
        # ER edges span the whole id space: band >> rows/shard
        make_halo_round(ProtocolConfig(mode=C.FLOOD),
                        G.erdos_renyi(128, 0.1, seed=1), mesh)
