"""Native C++ mini-Maelstrom router (native/router.cpp) — the L-1
harness twin, driven against the REAL protocol-node processes."""

import shutil

import pytest

from gossip_tpu.runtime.native_router import (build_router,
                                              run_native_workload)

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ compiler")


@needs_gxx
def test_native_router_broadcast_workload():
    stats = run_native_workload(4, ops=8, rate=100.0, latency=0.001,
                                seed=2)
    assert stats["engine"] == "native-router"
    assert stats["invariant_ok"] is True
    assert stats["broadcast_ops"] == 8
    assert stats["msgs_per_op"] > 0
    assert stats["op_latency_ms"]["p99"] >= stats["op_latency_ms"]["p50"] > 0


@needs_gxx
def test_native_router_partition_heals():
    stats = run_native_workload(4, ops=10, rate=25.0, latency=0.001,
                                partition_mid=True, seed=3)
    assert stats["invariant_ok"] is True
    assert stats["partitioned"] is True


# ~8 s (flight data, the log-PR rebalance): the native router keeps
# its in-gate line-topology workload + partition-heal tests above, and
# the grid TOPOLOGY surface stays pinned by the python-router grid
# test (tests/test_maelstrom.py); the native-x-grid cross product runs
# under -m slow
@pytest.mark.slow
@needs_gxx
def test_native_router_grid_topology():
    stats = run_native_workload(6, ops=6, rate=50.0, latency=0.001,
                                topology="grid", seed=1)
    assert stats["invariant_ok"] is True
    # grid degree > line degree -> flood traffic per op must be higher
    line = run_native_workload(6, ops=6, rate=50.0, latency=0.001,
                               topology="line", seed=1)
    assert stats["msgs_per_op"] > line["msgs_per_op"]


@needs_gxx
def test_native_and_python_harness_agree_on_the_contract():
    """Same workload shape through both engines: both must satisfy the
    invariant and report the same stats schema (values differ — the op
    target streams are engine-local RNG)."""
    import asyncio

    from gossip_tpu.runtime.maelstrom_harness import run_broadcast_workload
    nat = run_native_workload(3, ops=6, rate=100.0, latency=0.001, seed=0)
    py = asyncio.run(run_broadcast_workload(3, ops=6, rate=100.0,
                                            latency=0.001, seed=0))
    for k in ("broadcast_ops", "msgs_per_op", "op_latency_ms",
              "invariant_ok", "values", "partitioned"):
        assert k in nat and k in py
    assert nat["invariant_ok"] and py["invariant_ok"]
    assert build_router() is not None
