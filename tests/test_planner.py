"""Scale planner: plan algebra + streamed bit-plane tiling contracts.

The budget model (gossip_tpu/planner/budget) is pure host arithmetic,
so its pins are free; the streaming pins (gossip_tpu/planner/stream)
share ONE plan shape across tests so the tile-loop executable is
compiled once per session (the module-level step cache + jit shape
cache — exactly the reuse the subsystem exists to certify).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import ChurnConfig, FaultConfig
from gossip_tpu.planner import budget as PB
from gossip_tpu.planner import stream as PS

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MIXED = FaultConfig(drop_prob=0.05, seed=2, churn=ChurnConfig(
    events=((3, 1, 4), (9, 2, -1)),       # crash/recover + permanent
    partitions=((1, 4, 256),),            # open window
    ramp=(0, 3, 0.0, 0.15)))              # drop ramp


def _forced_plan(n=512, rumors=128, tiles=2, max_rounds=6, seed=0,
                 fault=MIXED, devices=1):
    """A plan whose artificial HBM budget forces exactly the requested
    tile count — via the ONE shared construction
    (budget.forced_device_for_tiles); every streaming test shares the
    default shape so the tile-loop executable compiles once per
    session."""
    dev = PB.forced_device_for_tiles(
        n, rumors=rumors, fanout=2, max_rounds=max_rounds,
        fault=fault, tiles_at_least=tiles, devices=devices,
        host_ram_bytes=1 << 30)
    return PB.plan_scale(n, rumors=rumors, device=dev, fanout=2,
                         max_rounds=max_rounds, fault=fault,
                         segment_every=3, seed=seed)


# -------------------------------------------------------------- algebra


def test_jax_free_twins_cannot_drift():
    """budget.py never imports jax, so its word-count and canonical-
    horizon forms are duplicated — this pin is what makes the
    duplication safe."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops.bitpack import n_words
    for r in (1, 31, 32, 33, 64, 255, 256, 1000):
        assert PB.n_words(r) == n_words(r)
    for ch in (ChurnConfig(events=((0, 1, 2),)),
               ChurnConfig(partitions=((0, 40, 8),)),
               ChurnConfig(ramp=(0, 100, 0.0, 0.5)),
               MIXED.churn):
        f = FaultConfig(churn=ch)
        assert PB.sched_t_pad(f) == NE.canonical_horizon(ch), ch
    assert PB.sched_t_pad(None) == NE.SCHED_T_MIN
    # and the module really is jax-free (the wedged-tunnel-box
    # contract, the analysis/ rationale)
    import ast
    src = os.path.join(_REPO, "gossip_tpu", "planner", "budget.py")
    tree = ast.parse(open(src).read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            assert not any(a.name.split(".")[0] == "jax"
                           for a in node.names)
        if isinstance(node, ast.ImportFrom):
            assert (node.module or "").split(".")[0] != "jax"


@pytest.mark.parametrize("engine", PB.ENGINES)
def test_budget_monotone_in_n(engine):
    """Per-device peak bytes are nondecreasing in N at fixed tile
    width — the property that makes 'largest feasible N' well-defined
    and feasibility monotone (a smaller N always fits a budget a
    bigger one fit)."""
    last = 0
    for n in (1000, 4096, 10**5, 10**6, 10**7, 10**8):
        p = sum(PB.engine_components(
            engine, n=n, rumors=64, fanout=2, tile_words=1, devices=4,
            fault=MIXED, max_rounds=64).values())
        assert p >= last, (engine, n)
        last = p


def test_bucket_stability_and_determinism():
    """Growing N under a FIXED budget never widens the tile bucket
    (pow2 buckets shrink monotonically), and planning is a pure
    function of its inputs."""
    # 64 MB: fits n=10**6 at the 1-word tile even with the pipeline's
    # fetch_buffer term in the peak (planner/budget engine_components)
    dev = PB.DeviceSpec(chips=1, hbm_bytes_per_chip=64 * 1024**2,
                        host_ram_bytes=1 << 34)
    last_bucket = None
    for n in (10**4, 10**5, 3 * 10**5, 10**6):
        plan = PB.plan_scale(n, rumors=256, device=dev, fanout=1,
                             max_rounds=32)
        assert (plan.bucket_words & (plan.bucket_words - 1)) == 0
        assert plan.tiles * plan.bucket_words >= plan.total_words
        if last_bucket is not None:
            assert plan.bucket_words <= last_bucket, n
        last_bucket = plan.bucket_words
        again = PB.plan_scale(n, rumors=256, device=dev, fanout=1,
                              max_rounds=32)
        assert again.to_dict() == plan.to_dict()


def test_infeasible_refusals_name_the_binding_constraint():
    # HBM wall: even the 1-word tile cannot fit — constraint named in
    # the message AND machine-readable on the exception
    with pytest.raises(PB.InfeasiblePlanError) as ei:
        PB.plan_scale(10**8, rumors=64,
                      device=PB.DeviceSpec(chips=1,
                                           hbm_bytes_per_chip=10**6,
                                           host_ram_bytes=1 << 40),
                      fanout=2, max_rounds=64)
    assert ei.value.binding in dict(
        PB.engine_components("packed", n=10**8, rumors=64, fanout=2,
                             tile_words=1, devices=1, fault=None,
                             max_rounds=64))
    assert ei.value.binding in str(ei.value)
    assert "1-word tile" in str(ei.value)
    # host-RAM wall: streaming cannot help a host that cannot hold the
    # packed state
    with pytest.raises(PB.InfeasiblePlanError) as ei:
        PB.plan_scale(10**8, rumors=1024,
                      device=PB.DeviceSpec(chips=256,
                                           hbm_bytes_per_chip=1 << 34,
                                           host_ram_bytes=10**9))
    assert ei.value.binding == "host_state"
    assert "host RAM" in str(ei.value)
    # int32 node-id space
    with pytest.raises(PB.InfeasiblePlanError) as ei:
        PB.plan_scale(2**31, device=PB.DeviceSpec())
    assert ei.value.binding == "node_id_dtype"
    # non-tileable mode refused at PLAN time
    with pytest.raises(ValueError, match="reverse delta"):
        PB.plan_scale(1000, mode=C.ANTI_ENTROPY)
    with pytest.raises(ValueError, match="unknown engine"):
        PB.plan_scale(1000, engine="warp")


def test_plan_json_round_trip_and_validation():
    plan = _forced_plan()
    doc = json.loads(plan.to_json())
    again = PB.plan_from_dict(doc)
    assert again.to_dict() == plan.to_dict()
    assert again.fault == plan.fault      # churn tuples survive JSON
    # structural validation names the offending field
    bad = json.loads(plan.to_json())
    bad["tiling"]["bucket_words"] = 3
    with pytest.raises(ValueError, match="power of two"):
        PB.validate_plan(bad)
    bad = json.loads(plan.to_json())
    del bad["segments"]
    with pytest.raises(ValueError, match="segments"):
        PB.validate_plan(bad)
    bad = json.loads(plan.to_json())
    bad["version"] = 99
    with pytest.raises(ValueError, match="version"):
        PB.validate_plan(bad)
    # a hand-edited tiling that no longer matches the model is refused
    bad = json.loads(plan.to_json())
    bad["tiling"]["tiles"] = plan.tiles * 2
    bad["tiling"]["bucket_words"] = plan.bucket_words
    with pytest.raises(ValueError, match="tiling"):
        PB.plan_from_dict(bad)
    # a wrong-TYPED section refuses the same one-line way (never a
    # TypeError/AttributeError traceback)
    for sec in ("target", "tiling", "segments", "budget", "device"):
        bad = json.loads(plan.to_json())
        bad[sec] = 7
        with pytest.raises(ValueError, match=sec):
            PB.validate_plan(bad)
    # a truncated budget/foreign device section is a one-line
    # ValueError naming the section, never a KeyError/TypeError
    # traceback (the CLI refusal contract)
    bad = json.loads(plan.to_json())
    del bad["budget"]["reserve_frac"]
    with pytest.raises(ValueError, match="reserve_frac"):
        PB.plan_from_dict(bad)
    bad = json.loads(plan.to_json())
    bad["device"]["warp_drives"] = 1
    with pytest.raises(ValueError, match="device"):
        PB.plan_from_dict(bad)
    # fingerprints: content-sensitive, order-insensitive
    fp = PB.plan_fingerprint(doc)
    assert fp == PB.plan_fingerprint(json.loads(plan.to_json()))
    other = _forced_plan(seed=1)
    assert fp != PB.plan_fingerprint(other.to_dict())


def test_forced_device_verifies_the_tile_count():
    """forced_device_for_tiles must DELIVER >= the requested tiles (it
    plans against its own budget and shrinks the candidate width), and
    refuse loudly when fixed-size components make the request
    unforceable — never silently under-deliver."""
    for tiles in (2, 4):
        dev = PB.forced_device_for_tiles(
            512, rumors=128, fanout=2, max_rounds=6, fault=MIXED,
            tiles_at_least=tiles)
        plan = PB.plan_scale(512, rumors=128, device=dev, fanout=2,
                             max_rounds=6, fault=MIXED)
        assert plan.tiles >= tiles
    # degenerate shape: n so tiny the alignment/sched floors dominate
    # every tile width — a loud refusal, not a 1-tile "forced" plan
    with pytest.raises(ValueError, match="cannot force"):
        PB.forced_device_for_tiles(4, rumors=256, fanout=1,
                                   max_rounds=4, fault=None,
                                   tiles_at_least=4)
    # more tiles than word planes is word-granularly impossible
    with pytest.raises(ValueError, match="word"):
        PB.forced_device_for_tiles(512, rumors=32, fanout=1,
                                   max_rounds=4, fault=None,
                                   tiles_at_least=2)


def test_host_init_packed_matches_jax_init():
    from gossip_tpu.config import ProtocolConfig, RunConfig
    from gossip_tpu.models.si_packed import init_packed_state
    for n, r, o in ((64, 40, 3), (17, 5, 0), (128, 64, 7)):
        st = init_packed_state(RunConfig(seed=0, origin=o),
                               ProtocolConfig(mode=C.PULL, fanout=1,
                                              rumors=r), n)
        assert np.array_equal(np.asarray(st.seen),
                              PS.host_init_packed(n, r, o)), (n, r, o)


# ------------------------------------------------------------ streaming


def test_streamed_bitwise_under_mixed_fault_program():
    """THE tentpole gate: the T-tile streamed trajectory — final
    state, msgs, and the exact ``dropped`` total — is BITWISE the
    untiled in-memory run, under the full mixed program (event +
    permanent crash + open partition window + drop ramp)."""
    plan = _forced_plan()
    assert plan.tiles == 2
    res = PS.run_at_scale(plan, check_bitwise=True)
    assert res.bitwise_equal is True
    assert res.dropped > 0          # the program actually destroyed
    assert res.rounds == plan.max_rounds


def test_pipelined_four_tiles_bitwise_vs_no_overlap_and_untiled(
        tmp_path):
    """The pipeline gate: a forced >=4-tile run with the three-stage
    fetch overlap is BITWISE the serial --no-overlap leg AND the
    untiled reference (state, msgs, exact dropped) under the mixed
    fault program; its tile_stream ledger events carry every tile's
    four walls and the run reports a sane overlap_efficiency."""
    from gossip_tpu.utils import telemetry
    plan = _forced_plan(tiles=4)
    assert plan.tiles >= 4
    path = str(tmp_path / "tile_stream.jsonl")
    led = telemetry.Ledger(path)
    prev = telemetry.activate(led)
    try:
        piped = PS.run_at_scale(plan, check_bitwise=True,
                                keep_state=True)
    finally:
        telemetry.activate(prev)
        led.close()
    assert piped.overlap and piped.bitwise_equal is True
    assert 0.0 <= piped.overlap_efficiency <= 1.0
    serial = PS.run_at_scale(plan, overlap=False, keep_state=True)
    assert not serial.overlap
    assert np.array_equal(piped.final_state, serial.final_state)
    assert (piped.msgs, piped.dropped) == (serial.msgs, serial.dropped)
    evs = [e for e in telemetry.load_ledger(path)
           if e.get("ev") == "tile_stream"]
    # one event per tile per segment, each with the four pipeline walls
    assert len(evs) == plan.tiles * plan.segment_count, evs
    for e in evs:
        for k in ("put_ms", "dispatch_ms", "wait_ms", "copy_ms"):
            assert e[k] >= 0.0, e
    assert {e["tile"] for e in evs} == set(range(plan.tiles))
    run_ev = [e for e in telemetry.load_ledger(path)
              if e.get("ev") == "scale_run"][-1]
    assert run_ev["overlap"] is True
    assert 0.0 <= run_ev["overlap_efficiency"] <= 1.0


def test_two_slice_hybrid_bitwise_vs_single_slice():
    """The multislice gate: a dcn_slices=2 plan EXECUTES (the refusal
    is lifted) on the simulated hybrid mesh — conftest forces 8 CPU
    devices — and its trajectory is bitwise the single-slice run's:
    tiles fan out round-robin with zero cross-slice bytes, so the
    slice count is invisible to the result."""
    plan1 = _forced_plan(tiles=4)
    dev2 = PB.DeviceSpec(
        chips=2, slices=2,
        hbm_bytes_per_chip=plan1.device.hbm_bytes_per_chip,
        host_ram_bytes=plan1.device.host_ram_bytes)
    plan2 = PB.plan_scale(plan1.n, rumors=plan1.rumors, device=dev2,
                          fanout=plan1.fanout,
                          max_rounds=plan1.max_rounds,
                          fault=plan1.fault,
                          segment_every=plan1.segment_every)
    assert plan2.mesh_kind == "hybrid" and plan2.dcn_slices == 2
    assert plan2.tiles == plan1.tiles >= 4
    r1 = PS.run_at_scale(plan1, keep_state=True)
    r2 = PS.run_at_scale(plan2, check_bitwise=True, keep_state=True)
    assert r2.dcn_slices == 2
    assert r2.bitwise_equal is True     # vs its own untiled reference
    assert np.array_equal(r1.final_state, r2.final_state)
    assert (r1.msgs, r1.dropped) == (r2.msgs, r2.dropped)


def test_two_slice_mid_pipeline_resume_bitwise(tmp_path):
    """Crash safety through the fan-out: halt a 2-slice pipelined run
    after one published segment, resume, land bitwise on the
    uninterrupted run — all slices drain into the ONE host cursor
    before the publish, so the resume contract is slice-count
    independent."""
    plan1 = _forced_plan(tiles=4)
    dev2 = PB.DeviceSpec(
        chips=2, slices=2,
        hbm_bytes_per_chip=plan1.device.hbm_bytes_per_chip,
        host_ram_bytes=plan1.device.host_ram_bytes)
    plan = PB.plan_scale(plan1.n, rumors=plan1.rumors, device=dev2,
                         fanout=plan1.fanout,
                         max_rounds=plan1.max_rounds,
                         fault=plan1.fault,
                         segment_every=plan1.segment_every)
    straight = PS.run_at_scale(plan, keep_state=True)
    ck = str(tmp_path / "slice_ck.npz")
    r1 = PS.run_at_scale(plan, checkpoint_path=ck,
                         halt_after_segments=1)
    assert r1.halted
    r2 = PS.run_at_scale(plan, checkpoint_path=ck, resume=True,
                         keep_state=True)
    assert r2.resumed and r2.rounds == plan.max_rounds
    assert np.array_equal(r2.final_state, straight.final_state)
    assert r2.msgs == straight.msgs
    assert r2.dropped == straight.dropped


def test_tiles_compile_once_per_bucket_and_salted_reentry_zero(
        assert_compiles):
    """K tiles share ONE executable per pow2 shape bucket, and a
    SALTED plan (new schedule content + seed, same shapes) re-enters
    with ZERO compiles — tile content and schedules are operands,
    never memo keys."""
    PS.run_at_scale(_forced_plan(seed=3))     # bucket executable built
    salted = FaultConfig(drop_prob=0.05, seed=2, churn=ChurnConfig(
        events=((7, 1, 4), (15, 2, -1)),
        partitions=((1, 4, 100),),
        ramp=(0, 3, 0.0, 0.1)))
    with assert_compiles(0):
        res = PS.run_at_scale(_forced_plan(seed=4, fault=salted))
    assert res.tiles == 2


def test_streamed_resume_bitwise_and_fingerprint_refusals(tmp_path):
    """Crash safety through the streamed driver: halt after the first
    published segment, resume, land bitwise on the uninterrupted run;
    a checkpoint from a DIFFERENT plan (or fault program) is refused
    loudly."""
    plan = _forced_plan()
    straight = PS.run_at_scale(plan, keep_state=True)
    ck = str(tmp_path / "scale_ck.npz")
    r1 = PS.run_at_scale(plan, checkpoint_path=ck,
                         halt_after_segments=1)
    assert r1.halted and r1.rounds == plan.segment_every
    r2 = PS.run_at_scale(plan, checkpoint_path=ck, resume=True,
                         keep_state=True)
    assert r2.resumed and r2.rounds == plan.max_rounds
    assert np.array_equal(r2.final_state, straight.final_state)
    assert r2.msgs == straight.msgs
    assert r2.dropped == straight.dropped
    # a different plan's checkpoint is refused by fingerprint
    with pytest.raises(ValueError, match="different scale plan"):
        PS.run_at_scale(_forced_plan(seed=9), checkpoint_path=ck,
                        resume=True)
    # the fault-program backstop: same plan fingerprint stamped, but a
    # checkpoint whose fault_program entry disagrees (a foreign or
    # pre-planner checkpoint) must not be continued
    import jax
    import jax.numpy as jnp
    from gossip_tpu.models.state import SimState
    from gossip_tpu.utils.checkpoint import save_state
    save_state(ck, SimState(seen=straight.final_state,
                            round=jnp.int32(3),
                            base_key=jax.random.key(0),
                            msgs=jnp.float32(0.0)),
               extra_meta={"round": 3,
                           "scale_plan": PB.plan_fingerprint(
                               plan.to_dict()),
                           "fault_program": "not-the-real-digest"})
    with pytest.raises(ValueError, match="fault program"):
        PS.run_at_scale(plan, checkpoint_path=ck, resume=True)


def test_stream_refusals_are_loud():
    plan = _forced_plan()
    broken = dataclasses.replace(plan, engine="dense")
    with pytest.raises(ValueError, match="packed engine only"):
        PS.run_at_scale(broken)
    # dcn_slices > 1 EXECUTES now (the multislice fan-out), but a plan
    # wanting more slices than the platform reports still refuses
    # loudly (multislice._hybrid_device_grid), never silently shrinks
    overdrawn = dataclasses.replace(plan, dcn_slices=999)
    with pytest.raises(ValueError, match="devices"):
        PS.run_at_scale(overdrawn)
    # a caller-supplied mesh whose grid disagrees with the plan's
    # slicing refuses too — a silently re-gridded run would make the
    # per-slice accounting unattributable
    two_slice = dataclasses.replace(plan, dcn_slices=2)
    from gossip_tpu.parallel.sharded import make_mesh
    with pytest.raises(ValueError, match="hybrid"):
        PS.run_at_scale(two_slice, mesh=make_mesh(1, axis_name="nodes"))
    with pytest.raises(ValueError, match="checkpoint_path"):
        PS.run_at_scale(plan, resume=True)


@pytest.mark.slow
def test_streamed_bitwise_on_node_mesh():
    """The sharded leg: streamed-vs-untiled bitwise on a 4-device node
    mesh.  Slow-tier depth: the dry-run ``scale_plan`` family runs
    this exact mesh program (with the bitwise assert inside) in every
    tier-1 session via the dryrun_pair fixture."""
    from gossip_tpu.parallel.sharded import make_mesh
    plan = _forced_plan(n=1024, devices=4)
    res = PS.run_at_scale(plan, check_bitwise=True,
                          mesh=make_mesh(4, axis_name="nodes"))
    assert res.bitwise_equal is True
    assert res.tiles == 2


def test_memory_prediction_bounds_measurement():
    """The budget model's honesty gate: the tile loop's AOT memory
    analysis (args + outputs + temps) lands INSIDE the predicted peak.
    (Tightness on real HBM is the hw_refresh scale_plan step's job —
    CPU XLA fuses temps, so only the bound direction is portable.)"""
    plan = _forced_plan(seed=5)
    res = PS.run_at_scale(plan, measure_memory=True)
    assert res.measured_loop_bytes is not None
    assert res.measured_loop_bytes <= res.predicted_peak_device_bytes


# --------------------------------------------------- committed evidence


def test_committed_scale_record_verdict():
    """The committed artifacts/ledger_scale_r23.jsonl cannot rot:
    provenance-stamped, N = 2^20 forced to >= 4 streamed tiles through
    the three-stage pipeline, final state bitwise the untiled run AND
    the --no-overlap serial run, a sane overlap_efficiency, the
    simulated 2-slice hybrid leg executing bitwise (the dcn_slices
    refusal is lifted), coverage 1.0 on the eventual-alive set,
    measured allocation inside the predicted peak, resume bitwise."""
    from gossip_tpu.utils import telemetry
    path = os.path.join(_REPO, "artifacts", "ledger_scale_r23.jsonl")
    events = telemetry.load_ledger(path, run="last")
    assert events[0]["ev"] == "provenance"
    assert len(events[0]["git_commit"]) == 40
    rec = [e for e in events if e["ev"] == "scale_record"][-1]
    assert rec["ok"] is True
    assert rec["n"] == 2**20
    assert rec["tiles"] >= 4
    assert rec["bitwise_equal"] is True
    assert rec["no_overlap_bitwise"] is True
    assert 0.0 <= rec["overlap_efficiency"] <= 1.0
    assert rec["two_slice_bitwise"] is True
    assert rec["two_slice_dcn_slices"] == 2
    assert rec["coverage"] == 1.0
    assert rec["resume_bitwise"] is True
    assert rec["measured_loop_bytes"] <= \
        rec["predicted_peak_device_bytes"]
    assert rec["dropped"] > 0        # the mixed program really ran
    # per-tile pipeline walls landed in the same run (sync=False
    # emission from inside the timed segment loop)
    ts = [e for e in events if e["ev"] == "tile_stream"]
    assert len(ts) >= rec["tiles"]
    assert all(k in ts[0]
               for k in ("put_ms", "dispatch_ms", "wait_ms",
                         "copy_ms"))
    # the smoke rehearsal parses with the same shape (hw_refresh
    # convention)
    smoke = telemetry.load_ledger(
        os.path.join(_REPO, "artifacts",
                     "ledger_scale_r23.smoke.jsonl"), run="last")
    srec = [e for e in smoke if e["ev"] == "scale_record"][-1]
    assert srec["ok"] is True and srec["smoke"] is True


# ------------------------------------------------------------------ CLI


def test_cli_plan_validate_and_infeasible(tmp_path, capsys):
    from gossip_tpu import cli
    out = str(tmp_path / "plan.json")
    rc = cli.main(["plan", "--n", "4096", "--rumors", "256", "--chips",
                   "1", "--hbm-gb", "0.001", "--host-ram-gb", "1",
                   "--max-rounds", "6", "--segment-every", "3",
                   "--drop", "0.05",
                   "--scenario", "event=1:1:3;partition=1:3:32;"
                                 "ramp=0:2:0.0:0.2",
                   "--out", out])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip())
    assert line["tiles"] >= 2 and line["plan_written"] == out
    rc = cli.main(["plan", "--validate", out])
    assert rc == 0
    assert json.loads(capsys.readouterr().out.strip())["plan_valid"]
    # infeasible: exit 2, one line, constraint named
    rc = cli.main(["plan", "--n", str(10**8), "--chips", "1",
                   "--hbm-gb", "0.001"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "binding constraint" in captured.err
    assert captured.out == ""
    # a corrupted plan file is refused with the field named
    doc = json.load(open(out))
    doc["tiling"]["tiles"] = doc["tiling"]["tiles"] * 2
    bad = str(tmp_path / "bad.json")
    json.dump(doc, open(bad, "w"))
    rc = cli.main(["plan", "--validate", bad])
    assert rc == 2
    assert "tiling" in capsys.readouterr().err


def test_cli_scale_run_executes_plan(tmp_path, capsys):
    """scale-run end to end on the shared small shape: bitwise gate on,
    checkpoint published, then run --plan resumes it (the two CLI
    surfaces share _run_plan_file)."""
    from gossip_tpu import cli
    plan = _forced_plan()
    pf = str(tmp_path / "plan.json")
    with open(pf, "w") as f:
        f.write(plan.to_json())
    ck = str(tmp_path / "ck.npz")
    rc = cli.main(["scale-run", "--plan", pf, "--checkpoint", ck,
                   "--check-bitwise"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["bitwise_equal"] is True and out["tiles"] == 2
    assert os.path.exists(ck)
    rc = cli.main(["run", "--plan", pf, "--checkpoint", ck, "--resume"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["resumed"] is True
    # no-silent-drop: flags the plan path would discard are refused —
    # both the output-shape flags and any run-shape flag changed from
    # its parser default (the guard reads the LIVE parser defaults)
    rc = cli.main(["run", "--plan", pf, "--curve"])
    assert rc == 2
    assert "drop --ensemble" in capsys.readouterr().err
    rc = cli.main(["run", "--plan", pf, "--n", "9999", "--drop", "0.5"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "--n" in err and "--drop" in err
    # the guarded set is derived from the parser, so engine-specific
    # flags (swim, rumor, topology) are covered without enumeration
    rc = cli.main(["run", "--plan", pf, "--swim-subjects", "16"])
    assert rc == 2
    assert "--swim-subjects" in capsys.readouterr().err
